"""TPU BLS verification benchmark — prints ONE JSON line for the driver.

Measures END-TO-END batched signature-set verification: message bytes ->
bool, including hash-to-curve (run ON DEVICE: batched SSWU + isogeny +
cofactor clearing, ops/bls12_381/h2c.py) and the random-linear-
combination pairing check (scalar muls + Miller loops + shared final
exp).  The reference's equivalent path is blst's native h2c + batched
pairing on CPU workers (chain/bls/multithread/index.ts:39).

Headline metric: signature sets verified per second per chip, with p99
batch latency.  vs_baseline compares against the reference's CPU
batch-verify throughput derived from its recorded engineering constant:
~45 ms per ~100-signature block of batched blst verification
(packages/beacon-node/src/chain/blocks/verifyBlocksSignatures.ts:41-43)
=> ~2,200 sigs/s single-threaded.

Robustness: XLA compile time for the pairing program is unbounded on a
cold cache, and the driver runs this under an external timeout.  The
parent process therefore stages child runs (large batch first, smaller
fallbacks) each under its own wall-clock cap, and ALWAYS prints exactly
one JSON line from the best stage that finished.  A warm persistent
compilation cache (.jax_cache) makes the flagship stage take seconds.

Correctness is asserted in-run (valid batch accepts, corrupted rejects)
before any timing is recorded.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

os.environ.setdefault("LODESTAR_TPU_PRESET", "mainnet")

BASELINE_SIGS_PER_SEC = 2200.0  # reference CPU batched blst (see docstring)
_START = time.monotonic()


def run_config(batch: int, iters: int, cap_s: float | None = None) -> dict:
    """Measure one batch size; returns the result dict (child mode).

    END-TO-END timing: each iteration starts from raw message bytes —
    host expand_message_xmd + field reduction + limb packing, then the
    device kernel that hashes to curve (SSWU+isogeny+cofactor) AND
    batch-verifies, to a single bool.  Nothing is precomputed into the
    timed loop except the signatures themselves (which a node receives,
    not computes)."""
    import jax
    import jax.numpy as jnp

    from lodestar_tpu.aot import cache as aot_cache

    aot_cache.configure()
    # spy on the persistent-cache read path: compile_s alone cannot
    # distinguish a warm load from a fast cold compile, and the whole
    # point of the AOT warm workflow is that this line says "hit"
    aot_cache.install_cache_spy()

    from lodestar_tpu.crypto.bls import api
    from lodestar_tpu.ops.bls12_381 import curve as cv, h2c, verify as dv

    # --- build a valid batch of B signature sets (host oracle signs) ----
    B = batch
    sets = []
    for i in range(B):
        sk = api.SecretKey.from_bytes((i + 1).to_bytes(32, "big"))
        msg = i.to_bytes(32, "little")
        sets.append(api.SignatureSet(sk.to_public_key(), msg, sk.sign(msg)))
    messages = [s.message for s in sets]
    pk_aff, pk_inf, sig_aff, sig_inf, active = dv._encode_pk_sig(sets, B)
    rand = [(2 * i + 3) | 1 for i in range(B)]
    bits = cv.scalars_to_bits(rand, 64)

    fn = dv._jit_hashed

    def end_to_end(sig):
        u0, u1 = h2c.encode_field_draws(messages, B)
        out = fn(pk_aff, pk_inf, u0, u1, sig, sig_inf, bits, active)
        out.block_until_ready()
        return out

    # --- correctness gates before timing --------------------------------
    keys_before = set(aot_cache.observed_keys())
    t0 = time.time()
    ok = bool(end_to_end(sig_aff))
    compile_s = time.time() - t0
    stats = aot_cache.cache_stats()
    # classify THE flagship program, not global traffic: a hit on some
    # trivial sub-program must not mask a cold flagship compile
    flagship = {
        kind
        for key, kind in aot_cache.observed_keys().items()
        if key not in keys_before
        and key.startswith("jit_verify_signature_sets_hashed-")
    }
    # a cold compile leaves "put" as the key's last event (miss -> put)
    cache_state = "hit" if "hit" in flagship else (
        "miss" if flagship & {"miss", "put"} else "off"
    )
    print(
        f"bench: B={B} first run {compile_s:.1f}s, persistent cache "
        f"{cache_state} ({stats})",
        file=sys.stderr,
        flush=True,
    )
    assert ok, "valid batch rejected"
    bad_sig = jax.tree.map(lambda t: jnp.roll(t, 1, axis=0), sig_aff)
    assert not bool(end_to_end(bad_sig)), "corrupted batch accepted"

    # --- timed runs (message bytes -> bool) -----------------------------
    # Deadline-aware: a warm-cache stage on a slow backend (XLA:CPU runs
    # the 4096 batch in minutes, not milliseconds) must bank a real
    # number from however many iterations fit its wall cap instead of
    # dying at iteration 17/20 with nothing.  Even ONE iteration banks
    # (the `iters` field reports how many the mean covers — a
    # high-variance real number beats the 0.0 fallback); the cap is the
    # stage's whole budget, counted from process start
    # (compile/correctness time included).
    deadline = None if cap_s is None else _START + 0.85 * cap_s
    times = []
    host_times = []
    for _ in range(iters):
        if deadline is not None and times and time.monotonic() > deadline:
            break
        t0 = time.perf_counter()
        u0, u1 = h2c.encode_field_draws(messages, B)
        t1 = time.perf_counter()
        out = fn(pk_aff, pk_inf, u0, u1, sig_aff, sig_inf, bits, active)
        out.block_until_ready()
        times.append(time.perf_counter() - t0)
        host_times.append(t1 - t0)
    times.sort()
    mean_s = sum(times) / len(times)
    p99_s = times[min(len(times) - 1, int(0.99 * len(times)))]
    sigs_per_sec = B / mean_s

    # Honesty stamp (ISSUE 7): if ANY verification in this process ran
    # on a degraded tier (per-set fallback, host oracle) or the device
    # breaker tripped, the stage JSON says so — a future driver round
    # that silently ran on the host fallback must not bank a number
    # that looks like device throughput.  Armed fault injections are
    # stamped for the same reason (a chaos-harness run is not a bench).
    from lodestar_tpu.chain.bls import breaker as _breaker
    from lodestar_tpu.testing import faults as _faults

    degradation = _breaker.process_degradation()
    return {
        "metric": "bls_e2e_verify_sigs_per_sec_per_chip",
        "value": round(sigs_per_sec, 1),
        "unit": "sigs/s",
        "vs_baseline": round(sigs_per_sec / BASELINE_SIGS_PER_SEC, 3),
        "batch_size": B,
        "iters": len(times),
        "mean_batch_latency_ms": round(mean_s * 1e3, 2),
        "p99_batch_latency_ms": round(p99_s * 1e3, 2),
        "host_hash_ms": round(sum(host_times) / len(host_times) * 1e3, 2),
        "compile_s": round(compile_s, 1),
        "persistent_cache": cache_state,
        "degradation_tier": degradation["worst_tier"],
        "breaker_state": degradation["breaker_state"],
        "fault_injection": _faults.active(),
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
    }


def _child_main(batch: int, iters: int, cap_s: float | None = None) -> None:
    print(json.dumps(run_config(batch, iters, cap_s)), flush=True)


_live_child = {"proc": None}


def _run_stage(batch: int, iters: int, timeout_s: float) -> dict | None:
    """Run one config in a subprocess under its own wall-clock cap.

    The child env is made DETERMINISTIC w.r.t. the persistent-cache key:
    XLA_FLAGS is pinned to the empty default so a cache warmed by a
    builder shell with stray flags and the driver's bare `python bench.py`
    compute identical keys (a round-4 failure mode: every driver stage
    recompiled cold despite a warm .jax_cache)."""
    cmd = [
        sys.executable,
        os.path.abspath(__file__),
        "--child",
        str(batch),
        str(iters),
        str(timeout_s),
    ]
    env = dict(os.environ)
    from lodestar_tpu.aot import cache as aot_cache

    aot_cache.pin_cache_key_env(env)
    proc = subprocess.Popen(
        cmd,
        stdout=subprocess.PIPE,
        stderr=sys.stderr,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        env=env,
    )
    _live_child["proc"] = proc
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        print(f"bench: stage B={batch} exceeded {timeout_s:.0f}s",
              file=sys.stderr, flush=True)
        return None
    finally:
        _live_child["proc"] = None
    if proc.returncode != 0:
        print(f"bench: stage B={batch} failed rc={proc.returncode}",
              file=sys.stderr, flush=True)
        return None
    for line in out.decode().splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _warm_first(stages: tuple) -> tuple:
    """Order stages warm-program-first per the AOT warm manifest: a cold
    flagship must not burn the whole budget ahead of a warm fallback
    stage (rounds 3-5 banked 0.0 sigs/s exactly that way).  Warming is
    resumable and priority-ordered, so mid-warm hosts routinely have the
    fallback program banked while the flagship is still compiling.

    The probe is read-only and registry-free: stage programs are always
    ``hashed/b<batch>``, so a key shim avoids importing the kernel
    modules into the parent (children own the real dispatch)."""
    if len(stages) < 2:
        return stages
    try:
        from lodestar_tpu.aot import cache as aot_cache, warm

        cache_dir = aot_cache.repo_cache_dir()
        manifest = warm.load_manifest(cache_dir)
        if not manifest.get("entries"):
            return stages
        envk = warm.environment_key()  # imports jax; cheap vs a cold stage
        states = {
            b: warm.program_state(
                type("P", (), {"key": f"hashed/b{b}"})(),
                manifest,
                cache_dir,
                envk,
            )
            for b in stages
        }
        ordered = tuple(
            sorted(stages, key=lambda b: 0 if states[b] == "warm" else 1)
        )
        if ordered != stages:
            print(
                f"bench: reordered stages to {list(ordered)} "
                f"(warm manifest: {states})",
                file=sys.stderr,
                flush=True,
            )
        return ordered
    except Exception as e:  # a broken probe must never cost the bench
        print(
            f"bench: warm-manifest probe failed ({type(e).__name__}: {e}) "
            "— keeping default stage order",
            file=sys.stderr,
            flush=True,
        )
        return stages


# Same metric name as the real stages: three rounds of fallback JSON
# under a DIFFERENT name (bls_batch_verify_...) made the trajectory
# incomparable across rounds.
_FALLBACK = {
    "metric": "bls_e2e_verify_sigs_per_sec_per_chip",
    "value": 0.0,
    "unit": "sigs/s",
    "vs_baseline": 0.0,
    "error": "no stage finished within budget (cold XLA compile; "
    "run `python -m lodestar_tpu.aot warm` first)",
}


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        cap_s = float(sys.argv[4]) if len(sys.argv) > 4 else None
        _child_main(int(sys.argv[2]), int(sys.argv[3]), cap_s)
        return

    # The driver kills this process at an UNKNOWN external timeout (via
    # SIGTERM from `timeout`).  Print the best banked result the moment the
    # signal lands so a partial run still reports real numbers, and also
    # re-print after each completed stage (the driver parses the LAST JSON
    # line).
    import signal

    state = {"best": None, "printed": None}

    def _emit(result) -> None:
        if result is not None and result != state["printed"]:
            print(json.dumps(result), flush=True)
            state["printed"] = result

    def _on_term(signum, frame):
        child = _live_child.get("proc")
        if child is not None:
            try:
                child.kill()
            except Exception:
                pass
        _emit(state["best"] or _FALLBACK)
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    # The driver's external timeout is unknown.  Round-4 post-mortem: the
    # old 4-stage ladder (8/1024/2048/4096, 420 s caps) burned the whole
    # budget on four COLD compiles that share no cache entries — a killed
    # stage banks nothing, and every subprocess re-pays TPU-client init
    # (which alone can take minutes through a cold tunnel).  One real
    # number beats four timeouts, so: the FLAGSHIP batch goes first with
    # nearly the whole budget (cold compile is batch-size independent);
    # one smaller fallback stage gets whatever remains.
    budget = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    iters = int(os.environ.get("BENCH_ITERS", "20"))
    deadline = time.time() + budget
    # Measured r4 (v5e, device h2c+verify, message bytes -> bool):
    # 1024 -> 1632/s, 2048 -> 1890/s, 4096 -> 2604/s = 1.18x baseline.
    batch_max = int(os.environ.get("BENCH_BATCH_MAX", "4096"))
    fallback = min(1024, batch_max)
    stages = _warm_first(tuple(dict.fromkeys((batch_max, fallback))))
    for i, batch in enumerate(stages):
        remaining = deadline - time.time()
        if remaining < 60:
            break
        if i == 0 and len(stages) > 1:
            # flagship: everything except a reserve for the fallback stage
            cap = max(remaining - 480.0, remaining * 0.5)
        else:
            cap = remaining
        result = _run_stage(batch, iters, cap)
        if result is not None:
            print(
                f"bench: stage B={batch} finished "
                f"(compile_s={result.get('compile_s')}, persistent cache "
                f"{result.get('persistent_cache', 'unknown')})",
                file=sys.stderr,
                flush=True,
            )
        if result is not None and (
            state["best"] is None
            or result.get("value", 0) > state["best"].get("value", 0)
        ):
            state["best"] = result
            _emit(result)
        if state["best"] is not None:
            break  # banked: don't spend driver time on smaller batches
    _emit(state["best"] or _FALLBACK)


if __name__ == "__main__":
    main()
