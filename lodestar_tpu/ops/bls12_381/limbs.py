"""Limb representation constants and host-side conversions.

Why radix 2**13 with uint32 limbs: the TPU VPU has no 64-bit integer
multiplier, so the classic 64/32-bit bignum radices are out.  With 13-bit
limbs, a full CIOS Montgomery-multiplication column never exceeds
``2*NLIMBS*(2^13-1)^2 + carry < 2^32`` (see fp.py for the exact bound), so the
whole multiplier runs in native uint32 ops with carries materialised only once
per scan step.  381-bit Fp needs ceil(381/13) = 30 limbs; R = 2^390.

The reference client gets this math from the C ``blst`` library
(packages/beacon-node/src/chain/bls/maybeBatch.ts:17); here it is a JAX
program so it can be vmapped/sharded across a TPU mesh.
"""
from __future__ import annotations

import numpy as np

from lodestar_tpu.crypto.bls.fields import P

LIMB_BITS = 13
NLIMBS = 30
MASK = (1 << LIMB_BITS) - 1
R_EXP = LIMB_BITS * NLIMBS  # 390
R = 1 << R_EXP
assert R > P * 2, "R must exceed 2p for Montgomery bounds"

# -p^{-1} mod 2^LIMB_BITS — the per-limb Montgomery n' constant (CIOS).
N0INV = (-pow(P, -1, 1 << LIMB_BITS)) & MASK
# -p^{-1} mod R — the full-width Montgomery constant for the parallel
# (product-scanning-free) reduction in fp.mont_mul.
NPRIME = (-pow(P, -1, R)) % R
# R^2 mod p — multiply by this (Montgomery) to convert into Montgomery form.
R2 = (R * R) % P
# R mod p — the Montgomery representation of 1.
R1 = R % P


def int_to_limbs(x: int) -> np.ndarray:
    """Host: python int in [0, 2^390) -> uint32[NLIMBS] little-endian limbs."""
    if not 0 <= x < R:
        raise ValueError("value out of limb range")
    out = np.empty(NLIMBS, dtype=np.uint32)
    for i in range(NLIMBS):
        out[i] = x & MASK
        x >>= LIMB_BITS
    return out


def limbs_to_int(limbs) -> int:
    """Host: limb array (any int dtype, canonical or not) -> python int."""
    arr = np.asarray(limbs, dtype=np.uint64)
    x = 0
    for i in range(NLIMBS - 1, -1, -1):
        x = (x << LIMB_BITS) + int(arr[i])
    return x


P_LIMBS = int_to_limbs(P)
R2_LIMBS = int_to_limbs(R2)
ONE_MONT = int_to_limbs(R1)  # 1 in Montgomery form
NPRIME_LIMBS = int_to_limbs(NPRIME)
ZERO = np.zeros(NLIMBS, dtype=np.uint32)


def to_mont_int(x: int) -> int:
    return (x * R) % P


def from_mont_int(x: int) -> int:
    return (x * pow(R, -1, P)) % P
