"""Multi-chip sharded BLS verification — the manual-collectives
formulation, promoted out of ``__graft_entry__`` into a production
module the lodelint v5 shardcheck rules can see (ISSUE 19; ROADMAP
item 3's architecture step).

The SURVEY's §2.5/§7 ICI mapping: the signature-set batch axis is
sharded over the mesh's ``sp`` axis (data parallelism over signature
sets), each device computes its local r_i·sig_i partial sum and its
local Miller-loop product, the partials ride the ICI via ``all_gather``,
and one shared final exponentiation finishes the pairing check.  The
GSPMD formulation (annotate shardings, let XLA insert the collectives)
lives in ``__graft_entry__.dryrun_multichip``; THIS module is the
explicit-axes twin kept for real-hardware bringup, where reading the
collectives off the program text matters more than compile time.

Geometry contract (checked statically by lodelint's ``shard-divisibility``
and dynamically by ``tests/test_mesh_smoke.py``): every bucket in
``SHARDED_BUCKETS`` divides evenly over every ``SUPPORTED_MESH_SIZES``
entry, and every per-device quotient is itself a registered AOT rung, so
a mesh dispatch never truncates, pads, or cold-compiles an unwarmed
program shape.

@mesh: sp
"""
from __future__ import annotations

from functools import lru_cache, partial

# the single mesh axis every collective in this module names: data
# parallelism over signature sets (SURVEY §2.5 row 1)
SHARD_AXIS = "sp"

# mesh geometries the node supports (v4e-8 slice and its halvings);
# lodelint's shard-divisibility reads this table live
SUPPORTED_MESH_SIZES = (2, 4, 8)

# dispatch widths the sharded programs accept: each divides every
# supported mesh size AND shards to a per-device width that is itself a
# registered AOT rung (128/8=16 ... 2048/2=1024 are all in
# buckets.BUCKETS), so `aot warm` coverage extends to the shards
SHARDED_BUCKETS = (128, 512, 1024, 2048)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """Version-portable ``shard_map``: new jax exposes ``jax.shard_map``
    with a ``check_vma`` kwarg; 0.4.x only has
    ``jax.experimental.shard_map.shard_map`` with the same check named
    ``check_rep``.  One adapter so the production formulation (and the
    lint contract on it) is written once against the new spelling."""
    import jax

    new = getattr(jax, "shard_map", None)
    if new is not None:
        return new(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    from jax.experimental.shard_map import shard_map as _old

    return _old(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )


def build_sharded_verify(mesh):
    """Manual-collectives batched signature-set verification over
    ``mesh``: local scalar muls + Miller products per shard, all_gather
    + GT-product reduction over "sp", one replicated final
    exponentiation.  Arg order matches ``__graft_entry__``'s dryrun:
    ``(pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, active,
    bits)``.

    @mesh: sp
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from lodestar_tpu.ops.bls12_381 import curve as cv, pairing as pr, tower as tw
    from lodestar_tpu.ops.bls12_381 import verify as dv

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS),) * 8,
        out_specs=P(),
        # Replication is by construction (every device all_gathers the
        # same partials and reduces them identically) but 0.4.x
        # check_rep / 0.6.x check_vma cannot infer it: psum outputs
        # infer as replicated, all_gather outputs do NOT, and there is
        # no cross-device *product* collective for the GT reduction, so
        # the gather-then-reduce shape is forced and the check must be
        # off.  tests/test_mesh_smoke.py carries the invariant
        # dynamically (bit-equality vs the unsharded program) and
        # tests/test_sharded_verify.py pins that enabling the check
        # raises.
        check_vma=False,  # lodelint: disable=replicated-escape — all_gather+reduce replication is correct by construction but not inferrable (no product collective); bit-equality tested in test_mesh_smoke.py
    )
    def sharded_verify(pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, active, bits):
        pk_jac = cv.from_affine(cv.F1, pk_aff, pk_inf | ~active)
        sig_jac = cv.from_affine(cv.F2, sig_aff, sig_inf | ~active)
        rpk = cv.scalar_mul_bits(cv.F1, pk_jac, bits)
        rsig = cv.scalar_mul_bits(cv.F2, sig_jac, bits)
        local_sig_sum = dv.jac_reduce_add(cv.F2, rsig)

        rpk_aff, rpk_inf = dv.batch_to_affine(cv.F1, rpk)
        mask = active & ~rpk_inf & ~msg_inf
        local_f = dv.multi_miller_product(msg_aff, rpk_aff, mask)

        sums = jax.lax.all_gather(local_sig_sum, "sp")
        fs = jax.lax.all_gather(local_f, "sp")
        sig_sum = dv.jac_reduce_add(cv.F2, sums)
        f_msgs = dv.f12_reduce_mul(fs)

        ss_aff, ss_inf = cv.to_affine(cv.F2, sig_sum, tw.f2_inv)
        f_sig = pr.miller_loop(ss_aff, (dv._NEG_G1_X, dv._NEG_G1_Y))
        f_sig = tw.f12_select(ss_inf, tw.f12_one(shape=()), f_sig)
        f = tw.f12_mul(f_msgs, f_sig)
        return tw.f12_is_one(pr.final_exponentiation(f))

    return sharded_verify


def build_reduced_step(mesh, check_vma=False):
    """Reduced sharded step over ``mesh``: the production curve kernels
    (mixed Jacobian arithmetic, branch-free double-and-add scalar mul)
    with the cross-shard Jacobian reduction made explicit — the pairing
    (Miller + final exp) is omitted so a cold compile fits a test
    budget.  Returns the affine sum ``((x, y), is_inf)`` so bit-equality
    against the unsharded execution compares canonical coordinates.

    ``check_vma`` is exposed so tests can pin WHY the default is off:
    on jax 0.4.x, enabling it raises at trace time because all_gather
    outputs are never inferred replicated (see build_sharded_verify).

    @mesh: sp
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from lodestar_tpu.ops.bls12_381 import curve as cv, fp
    from lodestar_tpu.ops.bls12_381 import verify as dv

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS),) * 4,
        out_specs=P(),
        check_vma=check_vma,  # lodelint: disable=replicated-escape — defaults False: Jacobian sums need gather-then-reduce (no point-add collective), which 0.4.x check_rep cannot infer replicated; test_mesh_smoke.py pins bit-equality, test_sharded_verify.py pins the raise
    )
    def reduced_step(pk_aff, pk_inf, bits, active):
        pk_jac = cv.from_affine(cv.F1, pk_aff, pk_inf | ~active)
        rpk = cv.scalar_mul_bits(cv.F1, pk_jac, bits)
        local = dv.jac_reduce_add(cv.F1, rpk)
        parts = jax.lax.all_gather(local, "sp")
        total = dv.jac_reduce_add(cv.F1, parts)
        return cv.to_affine(cv.F1, total, fp.inv)

    return reduced_step


def default_mesh(mesh_size: int):
    """The canonical ``(sp,)`` mesh over the first ``mesh_size`` local
    devices (the registry's enumeration gate guarantees enough exist)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()[:mesh_size]
    if len(devices) < mesh_size:
        raise ValueError(
            f"sharded program needs {mesh_size} devices, have {len(devices)}"
        )
    return Mesh(devices, (SHARD_AXIS,))


@lru_cache(maxsize=None)
def jitted_for_mesh(mesh):
    """THE memoized jitted sharded-verify program for a concrete mesh
    (``Mesh`` is hashable) — one wrapper per geometry per process, so
    every call site shares one trace cache and the persistent-cache
    filename (``jit_sharded_verify-``) is stable."""
    import jax

    return jax.jit(build_sharded_verify(mesh))


@lru_cache(maxsize=None)
def jitted_sharded(mesh_size: int):
    """``jitted_for_mesh`` over the canonical ``mesh_size``-device mesh
    — the registry's ``Program.fn()`` for ``sharded/b*@m{mesh_size}``
    entries, so warm/--check cover the sharded geometries."""
    return jitted_for_mesh(default_mesh(mesh_size))
