"""Device (TPU) hash-to-curve for G2 — batched SSWU + isogeny + cofactor.

Everything after RFC 9380's expand_message_xmd is field arithmetic, and
at batch width it belongs on the accelerator next to the pairing (the
reference gets the whole pipeline natively inside blst; VERDICT r3
ranked the host-bound hash path its #3 gap).  The host supplies the
``hash_to_field`` outputs u_ij (two Fp2 draws per message — SHA-256 via
native C + one bigint reduction each); the device maps them to G2:

    sswu (branchless, both-candidate sqrt) -> 3-isogeny -> Q0 + Q1
    -> Budroni-Pintore cofactor clearing (psi-based)

Design notes for the TPU shape of each stage:
  * All branching in the RFC algorithms (sqrt success, tv1 == 0, sign
    fix) becomes compute-both + ``select`` — constant-shape SPMD.
  * The two candidate square roots (gx1, gx2) and the two maps per
    message are STACKED into the batch axis, so each fixed-exponent pow
    compiles ONCE and runs at width 4B instead of four instances.
  * Inversions use Fermat pows (batched, 96 scan steps) rather than the
    Montgomery prefix trick (2B sequential scan steps): on TPU the wide
    parallel pow beats the long sequential scan for any real batch.
  * Cofactor clearing needs three [|x|]-multiplications; the two
    independent ones ([|x|]P, [|x|]psi(P)) run stacked as ONE 2B-wide
    ``scalar_mul_bits`` scan, the dependent [|x|][x]P as a second at
    width B (shape-shared with batch verification's r_i*sig_i scan).

Differential-tested against the Python oracle in
tests/test_device_h2c.py (the oracle itself is pinned to the RFC 9380
vectors in tests/test_bls_oracle.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lodestar_tpu.crypto.bls import hash_to_curve as _oh2c
from lodestar_tpu.crypto.bls.curve import PSI_CX, PSI_CY
from lodestar_tpu.crypto.bls.fields import ABS_X, P
from . import curve as cv, fp, tower as tw
from .opcache import cached as _cached

# ---------------------------------------------------------------------------
# constants (encoded from the oracle's tables at import time)
# ---------------------------------------------------------------------------

_SSWU_A = tw.encode_fp2(_oh2c.SSWU_A)
_SSWU_B = tw.encode_fp2(_oh2c.SSWU_B)
_SSWU_Z = tw.encode_fp2(_oh2c.SSWU_Z)
_NEG_B_DIV_A = tw.encode_fp2(
    _oh2c.f2_mul(_oh2c.f2_neg(_oh2c.SSWU_B), _oh2c.f2_inv(_oh2c.SSWU_A))
)
_B_DIV_ZA = tw.encode_fp2(
    _oh2c.f2_mul(
        _oh2c.SSWU_B, _oh2c.f2_inv(_oh2c.f2_mul(_oh2c.SSWU_Z, _oh2c.SSWU_A))
    )
)
_XNUM = [tw.encode_fp2(c) for c in _oh2c.XNUM]
_XDEN = [tw.encode_fp2(c) for c in _oh2c.XDEN]
_YNUM = [tw.encode_fp2(c) for c in _oh2c.YNUM]
_YDEN = [tw.encode_fp2(c) for c in _oh2c.YDEN]
_PSI_CX = tw.encode_fp2(PSI_CX)
_PSI_CY = tw.encode_fp2(PSI_CY)

_ABS_X_BITS = np.array(
    [int(b) for b in bin(ABS_X)[2:]], dtype=np.uint32
)  # MSB-first, 64 bits


def _bc2(c, shape):
    """Broadcast an encoded Fp2 constant over leading batch axes."""
    return jax.tree.map(lambda t: jnp.broadcast_to(t, (*shape, t.shape[-1])), c)


# ---------------------------------------------------------------------------
# batched fixed-exponent Fp2 pow + branchless sqrt
# ---------------------------------------------------------------------------


def f2_pow_fixed(a, e: int):
    """a^e over Fp2, 4-bit fixed window (mirrors fp.mont_pow_fixed)."""
    shape = a[0].shape[:-1]
    one = tw.f2_one(shape=shape)
    if e == 0:
        return one
    ndigits = (e.bit_length() + 3) // 4
    digits = np.array(
        [(e >> (4 * (ndigits - 1 - i))) & 0xF for i in range(ndigits)],
        dtype=np.int32,
    )
    pows = [one, a, tw.f2_sqr(a)]
    for _ in range(13):
        pows.append(tw.f2_mul(pows[-1], a))
    table = jax.tree.map(lambda *xs: jnp.stack(xs), *pows)  # (16, ...)

    def body(acc, d):
        for _ in range(4):
            acc = tw.f2_sqr(acc)
        sel = jax.tree.map(lambda t: t[d], table)
        return tw.f2_mul(acc, sel), None

    acc, _ = jax.lax.scan(body, one, jnp.asarray(digits))
    return acc


f2_pow_fixed = _cached(f2_pow_fixed, static_argnums=(1,))


def f2_inv_pow(a):
    """Batched Fp2 inversion via one Fermat pow on the norm (inv(0)=0).

    On TPU this replaces the sequential Montgomery-trick prefix scan:
    96 wide scan steps instead of 2B dependent multiplies."""
    t = fp.mont_mul(
        jnp.stack([a[0], a[1]]), jnp.stack([a[0], a[1]])
    )
    norm = fp.add(t[0], t[1])
    ninv = fp.mont_pow_fixed(norm, P - 2)
    u = fp.mont_mul(jnp.stack([a[0], a[1]]), jnp.stack([ninv, ninv]))
    return (u[0], fp.neg(u[1]))


f2_inv_pow = _cached(f2_inv_pow)


def f2_sqrt_both(a):
    """Branchless Adj-Rodriguez sqrt (p = 3 mod 4): returns (root, ok).

    Computes both algorithm branches and selects; `ok` is False where
    `a` is a non-residue (root is then garbage-but-canonical)."""
    shape = a[0].shape[:-1]
    a1 = f2_pow_fixed(a, (P - 3) // 4)
    x0 = tw.f2_mul(a1, a)
    alpha = tw.f2_mul(a1, x0)
    minus_one = tw.f2_neg(tw.f2_one(shape=shape))
    is_m1 = tw.f2_eq(alpha, minus_one)
    cand_u = (fp.neg(x0[1]), x0[0])  # u * x0
    one_alpha = tw.f2_add(tw.f2_one(shape=shape), alpha)
    b = f2_pow_fixed(one_alpha, (P - 1) // 2)
    cand_b = tw.f2_mul(b, x0)
    x = tw.f2_select(is_m1, cand_u, cand_b)
    ok = tw.f2_eq(tw.f2_sqr(x), a)
    return x, ok


def _f2_sgn0(a):
    """RFC 9380 sgn0 on device (parity of the canonical integer)."""
    p0 = fp.from_mont(a[0])
    p1 = fp.from_mont(a[1])
    sign_0 = p0[..., 0] & 1
    zero_0 = fp.is_zero(p0)
    sign_1 = p1[..., 0] & 1
    return (sign_0 | (zero_0 & sign_1)).astype(jnp.uint32)


# ---------------------------------------------------------------------------
# SSWU + isogeny (batched over a flat axis)
# ---------------------------------------------------------------------------


def map_to_curve_g2(u):
    """Batched simplified-SWU + 3-isogeny: Fp2 batch -> affine E' batch.

    Mirrors oracle map_to_curve_g2; every branch is compute-both+select.
    """
    shape = u[0].shape[:-1]
    zt2 = tw.f2_mul(_bc2(_SSWU_Z, shape), tw.f2_sqr(u))
    tv1 = tw.f2_add(tw.f2_sqr(zt2), zt2)
    tv1_zero = tw.f2_is_zero(tv1)
    # safe inverse: where tv1 == 0 the select below discards the value
    inv_tv1 = f2_inv_pow(tv1)
    one_plus = tw.f2_add(tw.f2_one(shape=shape), inv_tv1)
    x1_gen = tw.f2_mul(_bc2(_NEG_B_DIV_A, shape), one_plus)
    x1 = tw.f2_select(tv1_zero, _bc2(_B_DIV_ZA, shape), x1_gen)

    def g_of(x):
        xx = tw.f2_add(tw.f2_sqr(x), _bc2(_SSWU_A, shape))
        return tw.f2_add(tw.f2_mul(xx, x), _bc2(_SSWU_B, shape))

    x2 = tw.f2_mul(zt2, x1)
    gx1 = g_of(x1)
    gx2 = g_of(x2)

    # ONE stacked sqrt instance over [gx1; gx2]
    g_both = jax.tree.map(lambda a, b: jnp.concatenate([a, b]), gx1, gx2)
    y_both, ok_both = f2_sqrt_both(g_both)
    n = shape[0]
    y1 = jax.tree.map(lambda t: t[:n], y_both)
    y2 = jax.tree.map(lambda t: t[n:], y_both)
    ok1 = ok_both[:n]

    x = tw.f2_select(ok1, x1, x2)
    y = tw.f2_select(ok1, y1, y2)
    # sign fix: sgn0(u) == sgn0(y)
    flip = _f2_sgn0(u) != _f2_sgn0(y)
    y = tw.f2_select(flip, tw.f2_neg(y), y)

    # 3-isogeny, one stacked inversion for both denominators
    xn = _horner(_XNUM, x, shape)
    xd = _horner(_XDEN, x, shape)
    yn = _horner(_YNUM, x, shape)
    yd = _horner(_YDEN, x, shape)
    d_both = jax.tree.map(lambda a, b: jnp.concatenate([a, b]), xd, yd)
    i_both = f2_inv_pow(d_both)
    xdi = jax.tree.map(lambda t: t[:n], i_both)
    ydi = jax.tree.map(lambda t: t[n:], i_both)
    xo = tw.f2_mul(xn, xdi)
    yo = tw.f2_mul(tw.f2_mul(y, yn), ydi)
    return (xo, yo)


def _horner(coeffs, x, shape):
    acc = _bc2(coeffs[-1], shape)
    for c in reversed(coeffs[:-1]):
        acc = tw.f2_add(tw.f2_mul(acc, x), _bc2(c, shape))
    return acc


# ---------------------------------------------------------------------------
# psi endomorphism + cofactor clearing (Jacobian, batched)
# ---------------------------------------------------------------------------


def _psi(pt):
    """(X, Y, Z) -> (cx*conj(X), cy*conj(Y), conj(Z)) — inversion-free
    projective form of the oracle's affine psi."""
    X, Y, Z = pt
    shape = X[0].shape[:-1]
    return (
        tw.f2_mul(_bc2(_PSI_CX, shape), tw.f2_conj(X)),
        tw.f2_mul(_bc2(_PSI_CY, shape), tw.f2_conj(Y)),
        tw.f2_conj(Z),
    )


def _mul_abs_x(pt):
    """[|x|]P via the shared scalar_mul_bits instance (static bits)."""
    B = pt[0][0].shape[0]
    bits = jnp.broadcast_to(jnp.asarray(_ABS_X_BITS), (B, 64))
    return cv.scalar_mul_bits(cv.F2, pt, bits)


def clear_cofactor(pt):
    """Budroni-Pintore: [x^2-x-1]P + [x-1]psi(P) + psi^2([2]P), x < 0.

    Two of the three [|x|]-multiplications ([|x|]P and [|x|]psi(P)) are
    independent, so they run STACKED as one 2B-wide scan; only [|x|][x]P
    is sequential.  Two scalar-mul scans total instead of three."""
    F = cv.F2
    psip = _psi(pt)
    n = pt[0][0].shape[0]
    both = jax.tree.map(lambda a, b: jnp.concatenate([a, b]), pt, psip)
    m = _mul_abs_x(both)
    t = jax.tree.map(lambda v: v[:n], m)        # [|x|]P
    xpsi_abs = jax.tree.map(lambda v: v[n:], m)  # [|x|]psi(P)
    x_p = cv.jac_neg(F, t)          # [x]P
    u = _mul_abs_x(x_p)
    x2_p = cv.jac_neg(F, u)         # [x^2]P
    part1 = cv.jac_add(F, cv.jac_add(F, x2_p, cv.jac_neg(F, x_p)),
                       cv.jac_neg(F, pt))
    # [x-1]psi(P) = -([|x|]psi(P) + psi(P))
    part2 = cv.jac_neg(F, cv.jac_add(F, xpsi_abs, psip))
    part3 = _psi(_psi(cv.jac_double(F, pt)))
    return cv.jac_add(F, cv.jac_add(F, part1, part2), part3)


# ---------------------------------------------------------------------------
# full hash_to_g2 from field draws
# ---------------------------------------------------------------------------


def hash_to_g2_from_fields(u0, u1):
    """(B,)-batched field draws -> (B,) Jacobian G2 points in the subgroup.

    u0/u1: Fp2 limb tuples in PLAIN (non-Montgomery) canonical form —
    hash_to_field output encoded by ``encode_field_draws``; conversion to
    Montgomery form is the kernel's first (batched) multiply, keeping the
    host encode pure byte-shuffling.  The two SSWU+isogeny maps run
    STACKED as one 2B-wide batch; cofactor clearing runs once on the
    summed point.
    """
    u_both = jax.tree.map(lambda a, b: jnp.concatenate([a, b]), u0, u1)
    u_both = (fp.to_mont(u_both[0]), fp.to_mont(u_both[1]))
    aff = map_to_curve_g2(u_both)
    n = u0[0].shape[0]
    q0 = cv.from_affine(cv.F2, jax.tree.map(lambda t: t[:n], aff))
    q1 = cv.from_affine(cv.F2, jax.tree.map(lambda t: t[n:], aff))
    return clear_cofactor(cv.jac_add(cv.F2, q0, q1))


# ---------------------------------------------------------------------------
# host-side field-draw encoding (expand_message via native C SHA-256)
# ---------------------------------------------------------------------------

_LIMB_WEIGHTS = (1 << np.arange(13, dtype=np.uint32)).astype(np.uint32)


def _ints_to_limbs_np(vals) -> np.ndarray:
    """Vectorized python-int batch -> (N, 30) plain radix-2^13 limbs.

    48-byte big-endian per value -> unpack to LSB-first bits -> regroup
    into 13-bit limbs.  Pure numpy; ~1 us/value vs ~40 us for the
    per-element int_to_limbs loop (the host side of the hashed verify
    path must stay negligible next to the device kernel)."""
    raw = np.frombuffer(
        b"".join(v.to_bytes(48, "little") for v in vals), dtype=np.uint8
    ).reshape(len(vals), 48)
    bits = np.unpackbits(raw, axis=1, bitorder="little")  # (N, 384)
    bits = np.pad(bits, ((0, 0), (0, 390 - 384)))
    limbs = bits.reshape(len(vals), 30, 13).astype(np.uint32) @ _LIMB_WEIGHTS
    return limbs.astype(np.uint32)


def encode_field_draws(messages, size: int):
    """Host: messages -> (u0, u1) PLAIN limb tensors, padded to ``size``
    (padding lanes draw u = 0, masked out downstream)."""
    from lodestar_tpu.crypto.bls.hash_to_curve import hash_to_field_fp2

    draws = [hash_to_field_fp2(m, 2) for m in messages]
    while len(draws) < size:
        draws.append([(0, 0), (0, 0)])
    enc = lambda vals: jnp.asarray(_ints_to_limbs_np(vals))
    u0 = (enc([d[0][0] for d in draws]), enc([d[0][1] for d in draws]))
    u1 = (enc([d[1][0] for d in draws]), enc([d[1][1] for d in draws]))
    return u0, u1
