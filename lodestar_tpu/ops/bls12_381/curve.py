"""G1/G2 group ops on limb tensors: branchless Jacobian arithmetic + scans.

Points are tuples ``(X, Y, Z)`` of field elements (Fp limb tensors for G1,
Fp2 tuples for G2), Jacobian coordinates, ``Z == 0`` meaning infinity.
All control flow is data-independent: the add formula computes both the
add and double paths and selects — the XLA-friendly version of the oracle's
branching (lodestar_tpu/crypto/bls/curve.py `_CurveOps`), mirroring the
role of blst's group ops in the reference client's pubkey aggregation
(packages/beacon-node/src/chain/bls/utils.ts:5).

Scalar multiplication scans over a *runtime* bit tensor — the 64-bit
random-linear-combination coefficients of batch verification arrive as data
(chain/bls/maybeBatch.ts:17), not as compile-time constants.
"""
from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from . import fp, tower as tw


class FieldOps(NamedTuple):
    """Field-op vtable so G1 (Fp) and G2 (Fp2) share one set of formulas."""

    add: callable
    sub: callable
    mul: callable
    sqr: callable
    neg: callable
    is_zero: callable
    select: callable
    zeros_like: callable
    one_like: callable


F1 = FieldOps(
    add=fp.add,
    sub=fp.sub,
    mul=fp.mont_mul,
    sqr=fp.mont_sqr,
    neg=fp.neg,
    is_zero=fp.is_zero,
    select=fp.select,
    zeros_like=lambda a: jnp.zeros_like(a),
    one_like=lambda a: jnp.broadcast_to(fp.one_mont(), a.shape),
)

F2 = FieldOps(
    add=tw.f2_add,
    sub=tw.f2_sub,
    mul=tw.f2_mul,
    sqr=tw.f2_sqr,
    neg=tw.f2_neg,
    is_zero=tw.f2_is_zero,
    select=tw.f2_select,
    zeros_like=lambda a: (jnp.zeros_like(a[0]), jnp.zeros_like(a[1])),
    one_like=lambda a: (jnp.broadcast_to(fp.one_mont(), a[0].shape), jnp.zeros_like(a[1])),
)


def is_inf(F: FieldOps, pt):
    return F.is_zero(pt[2])


def inf_like(F: FieldOps, pt):
    return (F.one_like(pt[0]), F.one_like(pt[1]), F.zeros_like(pt[2]))


def pt_select(F: FieldOps, cond, a, b):
    return tuple(F.select(cond, x, y) for x, y in zip(a, b))


def jac_double(F: FieldOps, pt):
    """EFD dbl-2009-l (a=0); infinity/2-torsion handled by select."""
    X1, Y1, Z1 = pt
    A = F.sqr(X1)
    B = F.sqr(Y1)
    C = F.sqr(B)
    D = F.sub(F.sqr(F.add(X1, B)), F.add(A, C))
    D = F.add(D, D)
    E = F.add(F.add(A, A), A)
    Fq = F.sqr(E)
    X3 = F.sub(Fq, F.add(D, D))
    C8 = F.add(C, C)
    C8 = F.add(C8, C8)
    C8 = F.add(C8, C8)
    Y3 = F.sub(F.mul(E, F.sub(D, X3)), C8)
    Z3 = F.mul(F.add(Y1, Y1), Z1)
    out = (X3, Y3, Z3)
    bad = F.is_zero(Z1) | F.is_zero(Y1)
    return pt_select(F, bad, inf_like(F, pt), out)


def jac_add(F: FieldOps, p1, p2):
    """Complete Jacobian addition: handles inf, equal and opposite inputs.

    Computes the generic add and the doubling path and selects — constant
    shape, no data-dependent branching (EFD add-2007-bl + dbl fallback).
    """
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = F.sqr(Z1)
    Z2Z2 = F.sqr(Z2)
    U1 = F.mul(X1, Z2Z2)
    U2 = F.mul(X2, Z1Z1)
    S1 = F.mul(F.mul(Y1, Z2), Z2Z2)
    S2 = F.mul(F.mul(Y2, Z1), Z1Z1)
    H = F.sub(U2, U1)
    Rr = F.sub(S2, S1)
    HH = F.sqr(H)
    HHH = F.mul(H, HH)
    V = F.mul(U1, HH)
    X3 = F.sub(F.sub(F.sqr(Rr), HHH), F.add(V, V))
    Y3 = F.sub(F.mul(Rr, F.sub(V, X3)), F.mul(S1, HHH))
    Z3 = F.mul(F.mul(Z1, Z2), H)
    added = (X3, Y3, Z3)

    same_x = F.is_zero(H)
    same_y = F.is_zero(Rr)
    doubled = jac_double(F, p1)

    out = pt_select(F, same_x & same_y, doubled, added)          # P + P
    out = pt_select(F, same_x & ~same_y, inf_like(F, p1), out)   # P + (-P)
    out = pt_select(F, is_inf(F, p1), p2, out)
    out = pt_select(F, is_inf(F, p2), p1, out)
    return out


def jac_neg(F: FieldOps, pt):
    return (pt[0], F.neg(pt[1]), pt[2])


def from_affine(F: FieldOps, aff, inf_mask=None):
    """(x, y) -> (x, y, 1); where inf_mask is set, the point at infinity."""
    x, y = aff
    one = F.one_like(x)
    pt = (x, y, one)
    if inf_mask is not None:
        pt = pt_select(F, inf_mask, (one, one, F.zeros_like(x)), pt)
    return pt


def scalar_mul_bits(F: FieldOps, pt, bits):
    """[k]P with k given as an MSB-first bit tensor of shape (..., NBITS).

    Scans over the bit axis; the batch lives in the leading axes of both
    ``pt`` and ``bits``.
    """
    nbits = bits.shape[-1]
    bits_s = jnp.moveaxis(bits, -1, 0)  # (NBITS, ...batch)

    def body(acc, bit):
        acc = jac_double(F, acc)
        acc_plus = jac_add(F, acc, pt)
        acc = pt_select(F, bit != 0, acc_plus, acc)
        return acc, None

    acc0 = inf_like(F, pt)
    acc, _ = jax.lax.scan(body, acc0, bits_s)
    return acc


def to_affine(F: FieldOps, pt, f_inv):
    """Jacobian -> affine; infinity yields (0, 0) plus a mask.

    ``f_inv`` is the field inversion (fp.inv or tower.f2_inv); inv(0) = 0 so
    infinity stays finite garbage that callers mask out.
    """
    X, Y, Z = pt
    zinv = f_inv(Z)
    zinv2 = F.sqr(zinv)
    x = F.mul(X, zinv2)
    y = F.mul(Y, F.mul(zinv, zinv2))
    return (x, y), is_inf(F, pt)


# point-batch reduction lives in verify.py (jac_reduce_add — any batch size)

# trace-once caching (opcache.py): group ops are the widest re-traced
# bodies outside the field layer — a jac_add site binds ~16 field products.
# F / f_inv are static (hashable vtables / function objects).
from .opcache import cached as _cached

jac_double = _cached(jac_double, static_argnums=(0,))
jac_add = _cached(jac_add, static_argnums=(0,))
scalar_mul_bits = _cached(scalar_mul_bits, static_argnums=(0,))
to_affine = _cached(to_affine, static_argnums=(0, 2))
from_affine = _cached(from_affine, static_argnums=(0,))


# ---------------------------------------------------------------------------
# host-side encoding helpers (oracle points -> limb tensors)
# ---------------------------------------------------------------------------


def encode_g1_affine(points):
    """List of oracle AffineG1 (None = inf) -> ((B,NL),(B,NL)) + inf mask."""
    xs, ys, inf = [], [], []
    for pt in points:
        if pt is None:
            xs.append(0)
            ys.append(0)
            inf.append(True)
        else:
            xs.append(pt[0])
            ys.append(pt[1])
            inf.append(False)
    ex = np.stack([fp.encode_int(v) for v in xs])
    ey = np.stack([fp.encode_int(v) for v in ys])
    return (jnp.asarray(ex), jnp.asarray(ey)), jnp.asarray(np.array(inf))


def encode_g2_affine(points):
    """List of oracle AffineG2 -> Fp2-pair limb tensors + inf mask."""
    x0, x1, y0, y1, inf = [], [], [], [], []
    for pt in points:
        if pt is None:
            x0.append(0), x1.append(0), y0.append(0), y1.append(0)
            inf.append(True)
        else:
            (a0, a1), (b0, b1) = pt
            x0.append(a0), x1.append(a1), y0.append(b0), y1.append(b1)
            inf.append(False)
    e = lambda vs: jnp.asarray(np.stack([fp.encode_int(v) for v in vs]))
    return ((e(x0), e(x1)), (e(y0), e(y1))), jnp.asarray(np.array(inf))


def scalars_to_bits(scalars, nbits=64) -> jnp.ndarray:
    """Host: list of python ints -> (B, nbits) MSB-first uint32 bit tensor."""
    out = np.zeros((len(scalars), nbits), dtype=np.uint32)
    for i, s in enumerate(scalars):
        for j in range(nbits):
            out[i, nbits - 1 - j] = (s >> j) & 1
    return jnp.asarray(out)
