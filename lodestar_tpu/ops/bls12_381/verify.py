"""Batched BLS signature-set verification on device — the TPU hot loop.

This is the device half of the reference's batch verification
(packages/beacon-node/src/chain/bls/maybeBatch.ts:17 `verifyMultipleSignatures`
and multithread/worker.ts:32 `verifyManySignatureSets`): given B signature
sets (pubkey in G1, message point in G2, signature in G2) and B random
64-bit coefficients r_i, check

    prod_i e(r_i * pk_i, H(m_i)) * e(-G1gen, sum_i r_i * sig_i) == 1

with ONE shared final exponentiation over the product of B+1 Miller loops.
Also provides the per-set fallback kernel (each set its own 2-pairing check,
vmapped) that replaces the reference's serial retry-each-individually path
(worker.ts:76-98) with a single constant-shape program.

Batch entries can be padding: a `mask` marks active sets; padded/infinity
entries contribute the identity to every reduction.  This is how dynamic
batch sizes meet XLA's static-shape requirement (buckets 16/32/64/128,
mirroring multithread/index.ts:39's 128-sets-per-job policy).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lodestar_tpu.crypto.bls import curve as _oc
from . import curve as cv, fp, pairing as pr, tower as tw

# ---------------------------------------------------------------------------
# device constants: -G1 generator (affine, Montgomery limbs)
# ---------------------------------------------------------------------------

_NEG_G1 = _oc.g1.to_affine(_oc.g1.neg_pt(_oc.G1_GEN_JAC))
_NEG_G1_X = jnp.asarray(fp.encode_int(_NEG_G1[0]))
_NEG_G1_Y = jnp.asarray(fp.encode_int(_NEG_G1[1]))


# ---------------------------------------------------------------------------
# reductions over the batch axis
# ---------------------------------------------------------------------------


def _next_pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


def _rolled_reduce(tree, combine, identity1):
    """Reduce axis 0 of ``tree`` with ``combine`` via a rolled tree scan.

    Pads the batch to a power of two with ``identity1`` (a 1-element
    batch of the combine identity), then runs ONE ``combine`` instance
    inside a log2(B)-step ``lax.scan``: at step s each lane i combines
    lanes i and i+B/2^(s+1) (data-dependent ``jnp.roll``), so lane 0
    holds the full reduction at the end.  Lanes past the live prefix
    carry garbage-but-canonical field elements that never feed the
    result.  An earlier Python-loop halving emitted O(log B) distinct
    combine instances and dominated program build + compile time at
    large B.

    Runtime tradeoff (deliberate): every step combines across the FULL
    width, so total lane-combines are B*log2(B) vs the halving tree's
    ~B.  Below the 512-lane pallas block the extra lanes are padding
    anyway, and above it the reduction is a small term next to the
    64-iteration Miller/scalar-mul scans — compile time was the binding
    constraint (BENCH r1-r3 never finished a cold stage).
    """
    n = jax.tree.leaves(tree)[0].shape[0]
    assert n >= 1, "empty reduction"
    m = _next_pow2(n)
    if m == 1:
        return jax.tree.map(lambda t: t[0], tree)
    if m != n:
        pad = jax.tree.map(
            lambda t: jnp.broadcast_to(t, (m - n, *t.shape[1:])), identity1
        )
        tree = jax.tree.map(lambda t, p: jnp.concatenate([t, p]), tree, pad)
    halves = jnp.asarray([m >> (s + 1) for s in range(m.bit_length() - 1)],
                         dtype=jnp.int32)

    def body(acc, half):
        shifted = jax.tree.map(lambda t: jnp.roll(t, -half, axis=0), acc)
        return combine(acc, shifted), None

    tree, _ = jax.lax.scan(body, tree, halves)
    return jax.tree.map(lambda t: t[0], tree)


def f12_reduce_mul(f, mask=None):
    """Product of a batch of Fp12 values along axis 0, any batch size >= 1.

    Where ``mask`` is False the element is replaced by one.  One
    ``f12_mul`` instance total (see ``_rolled_reduce``)."""
    if mask is not None:
        ones = tw.f12_one(shape=jax.tree.leaves(f)[0].shape[:-1])
        f = tw.f12_select(mask, f, ones)
    return _rolled_reduce(f, tw.f12_mul, tw.f12_one(shape=(1,)))


def jac_reduce_add(F, pts):
    """Sum a batch of Jacobian points along axis 0, any batch size >= 1.

    One ``jac_add`` instance total; padding identity is the point at
    infinity (see ``_rolled_reduce``)."""
    inf1 = jax.tree.map(lambda t: t[:1], cv.inf_like(F, pts))
    return _rolled_reduce(
        pts, lambda a, b: cv.jac_add(F, a, b), inf1
    )


# ---------------------------------------------------------------------------
# batched affine conversion (Montgomery-trick batch inversion)
# ---------------------------------------------------------------------------


def _batch_inv(F, xs):
    """Inverses of a batch of field elements along axis 0 with ONE fp.inv.

    Zero elements yield zero (they are masked to one before the prefix pass
    so they don't zero the running product)."""
    zero_mask = F.is_zero(xs)
    safe = F.select(zero_mask, F.one_like(xs), xs)

    # forward prefix products: pre[i] = x0 * ... * x_{i-1}
    def fwd(acc, x):
        return F.mul(acc, x), acc

    init = _first_one(F, safe)
    total, pre = jax.lax.scan(fwd, init, safe)
    total_inv = _field_inv(F, total)

    # backward pass: inv_i = pre[i] * suffix_inv[i]
    def bwd(acc, xp):
        x, p = xp
        inv_i = F.mul(acc, p)
        return F.mul(acc, x), inv_i

    _, invs = jax.lax.scan(bwd, total_inv, (safe, pre), reverse=True)
    return F.select(zero_mask, _zeros_like_batch(F, invs), invs)


def _first_one(F, xs):
    return F.one_like(jax.tree.map(lambda t: t[0], xs))


def _zeros_like_batch(F, xs):
    return jax.tree.map(lambda t: jnp.zeros_like(t), xs)


def _field_inv(F, x):
    if F is cv.F1:
        return fp.inv(x)
    return tw.f2_inv(x)


def batch_to_affine(F, pts):
    """Jacobian batch -> affine batch + infinity mask.

    Inversion is a batched Fermat pow (inv(0) = 0 keeps infinity lanes
    finite garbage behind the mask).  The Montgomery prefix trick
    (_batch_inv) trades one inversion for 2B *sequential* multiplies —
    a good CPU trade, but on TPU the 96-step data-parallel pow wins for
    any real batch width."""
    X, Y, Z = pts
    if F is cv.F1:
        zinv = fp.inv(Z)
    else:
        from .h2c import f2_inv_pow

        zinv = f2_inv_pow(Z)
    zinv2 = F.sqr(zinv)
    x = F.mul(X, zinv2)
    y = F.mul(Y, F.mul(zinv, zinv2))
    return (x, y), cv.is_inf(F, pts)


# ---------------------------------------------------------------------------
# masked multi-Miller product
# ---------------------------------------------------------------------------


def multi_miller_product(q_aff, p_aff, mask):
    """prod over batch of f_{|x|,Q_i}(P_i), masked entries contribute one.

    PRECONDITION: `mask` must be False for every pair with an infinity
    input — the Miller loop produces garbage limbs there and this function
    only applies the mask it is given (callers pairing_check /
    verify_signature_sets construct the mask from the *_inf flags)."""
    f = pr.miller_loop(q_aff, p_aff)
    return f12_reduce_mul(f, mask)


def pairing_check(p_aff, p_inf, q_aff, q_inf, extra_mask=None):
    """prod_i e(P_i, Q_i) == 1 over a batch, with a shared final exp.

    Pairs where either side is infinity contribute e = 1 (the oracle's
    convention, crypto/bls/pairing.py::multi_miller_loop)."""
    mask = ~(p_inf | q_inf)
    if extra_mask is not None:
        mask = mask & extra_mask
    f = multi_miller_product(q_aff, p_aff, mask)
    return tw.f12_is_one(pr.final_exponentiation(f))


# ---------------------------------------------------------------------------
# the batched signature-set verification kernel
# ---------------------------------------------------------------------------


def verify_signature_sets(
    pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, rand_bits, active
):
    """Random-linear-combination batch verification; returns a scalar bool.

    pk_aff:  ((B,NL),(B,NL)) affine G1 pubkeys (Montgomery limbs)
    msg_aff: Fp2-pair tuples, affine G2 message points H(m_i)
    sig_aff: Fp2-pair tuples, affine G2 signatures
    *_inf:   (B,) bool infinity masks for each of the above
    rand_bits: (B, 64) MSB-first uint32 random coefficients (odd, nonzero)
    active:  (B,) bool — False entries are padding and fully ignored

    Semantics match the oracle `verify_multiple_signature_sets`
    (crypto/bls/api.py) for sets with finite pubkey+signature; sets with an
    infinity pubkey or signature must be rejected host-side before building
    the batch (the reference does the same checks in JS before calling blst).

    See also verify_signature_sets_hashed, which additionally runs the
    message hash-to-curve on device from raw field draws.
    """
    # r_i * pk_i  (G1)  and  r_i * sig_i  (G2), padded entries -> infinity
    pk_jac = cv.from_affine(cv.F1, pk_aff, pk_inf | ~active)
    sig_jac = cv.from_affine(cv.F2, sig_aff, sig_inf | ~active)
    rpk = cv.scalar_mul_bits(cv.F1, pk_jac, rand_bits)
    rsig = cv.scalar_mul_bits(cv.F2, sig_jac, rand_bits)
    sig_sum = jac_reduce_add(cv.F2, rsig)

    rpk_aff, rpk_inf = batch_to_affine(cv.F1, rpk)
    (ss_aff, ss_inf) = _single_to_affine_g2(sig_sum)

    # ONE (B+1)-batch Miller product: the B message pairs plus the
    # signature leg e(-G1, sum r_i sig_i) appended as entry B — a single
    # scan instance instead of two separately-compiled loops.
    def _append(batch, single):
        return jax.tree.map(
            lambda b, s: jnp.concatenate([b, s[None]]), batch, single
        )

    q_all = _append(msg_aff, ss_aff)
    neg_g1 = (_NEG_G1_X, _NEG_G1_Y)
    p_all = _append(rpk_aff, neg_g1)
    mask = jnp.concatenate(
        [active & ~rpk_inf & ~msg_inf, (~ss_inf)[None]]
    )
    f = multi_miller_product(q_all, p_all, mask)
    return tw.f12_is_one(pr.final_exponentiation(f))


def _single_to_affine_g2(pt):
    """Unbatched Jacobian G2 -> affine + inf flag."""
    (x, y), inf = cv.to_affine(cv.F2, pt, tw.f2_inv)
    return (x, y), inf


def verify_signature_sets_hashed(
    pk_aff, pk_inf, u0, u1, sig_aff, sig_inf, rand_bits, active
):
    """Full message-bytes-to-bool verification kernel: the message points
    are produced ON DEVICE from raw hash_to_field draws (u0, u1 — Fp2
    limb tuples per set) via batched SSWU + isogeny + cofactor clearing
    (ops/bls12_381/h2c.py), then fed to the same random-linear-
    combination check as verify_signature_sets.

    This removes the host hash-to-curve from the hot path entirely — the
    reference's blst does h2c in native code per message on CPU
    (VERDICT r3 weak #3 measured the rebuilt host path at ~65 ms/msg);
    here it is ~100 extra wide scan steps amortized over the batch.
    Padding lanes (active=False) carry u = 0 and are masked out.
    """
    from . import h2c as _h2c

    msg_jac = _h2c.hash_to_g2_from_fields(u0, u1)
    msg_aff, msg_inf = batch_to_affine(cv.F2, msg_jac)
    return verify_signature_sets(
        pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, rand_bits, active
    )


def fast_aggregate_verify(pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, active):
    """fastAggregateVerify (BASELINE config 2: 1 msg x N pubkeys — the
    sync-committee shape; reference bls.test.ts aggregatePubkeys +
    fastAggregateVerify): aggregate the N pubkeys on device with a
    log-depth Jacobian tree reduction, then one 2-pair pairing check
    e(agg_pk, H(m)) * e(-G1, sig) == 1.

    pk_aff/pk_inf: (B, ...) affine G1 pubkeys + infinity mask
    msg_aff/msg_inf, sig_aff/sig_inf: UNBATCHED G2 message point and
    signature (leading axis absent)
    active: (B,) bool — padding mask for the pubkey batch
    """
    from . import fp

    pk_jac = cv.from_affine(cv.F1, pk_aff, pk_inf | ~active)
    agg = jac_reduce_add(cv.F1, pk_jac)
    (apk_x, apk_y), apk_inf = cv.to_affine(cv.F1, agg, fp.inv)

    q_pair = jax.tree.map(
        lambda m, s: jnp.stack([m, s]), msg_aff, sig_aff
    )
    p_pair = (
        jnp.stack([apk_x, _NEG_G1_X]),
        jnp.stack([apk_y, _NEG_G1_Y]),
    )
    mask = jnp.stack([~apk_inf & ~msg_inf, ~sig_inf])
    f = multi_miller_product(q_pair, p_pair, mask)
    # an all-infinity aggregate or infinite signature must reject, not
    # trivially accept through an empty product
    ok = tw.f12_is_one(pr.final_exponentiation(f))
    return ok & ~apk_inf & ~sig_inf


def verify_each(pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, active):
    """Per-set verification: e(pk_i, H_i) * e(-G1, sig_i) == 1, vmapped.

    Returns a (B,) bool vector — the constant-shape replacement for the
    reference worker's retry-each-individually loop (worker.ts:76-98).
    Padded (inactive) entries report False.
    """
    negx = jnp.broadcast_to(_NEG_G1_X, pk_aff[0].shape)
    negy = jnp.broadcast_to(_NEG_G1_Y, pk_aff[1].shape)

    # one 2B-batch Miller instance: [e(pk_i, H_i) legs; e(-G1, sig_i) legs]
    cat = lambda a, b: jax.tree.map(
        lambda x, y: jnp.concatenate([x, y]), a, b
    )
    f_all = pr.miller_loop(cat(msg_aff, sig_aff), cat(pk_aff, (negx, negy)))
    f_msg = jax.tree.map(lambda t: t[: t.shape[0] // 2], f_all)  # (B,) Fp12
    f_sig = jax.tree.map(lambda t: t[t.shape[0] // 2 :], f_all)  # (B,) Fp12

    B = pk_aff[0].shape[0]
    ones = tw.f12_one(shape=(B,))
    bad = pk_inf | msg_inf | sig_inf
    f = tw.f12_mul(
        tw.f12_select(pk_inf | msg_inf, ones, f_msg),
        tw.f12_select(sig_inf, ones, f_sig),
    )
    ok = tw.f12_is_one(pr.final_exponentiation(f))
    return ok & ~bad & active


# ---------------------------------------------------------------------------
# host-side wrappers: oracle objects -> device tensors, jit cache per bucket
# ---------------------------------------------------------------------------

from .buckets import BUCKETS as _BUCKETS, bucket_size  # noqa: F401,E402

# The jit wrappers live in the AOT registry (lodestar_tpu/aot/registry.py)
# — the single source of truth for every program the warm tool must
# compile.  The module attributes below are THE registry objects, kept
# under their historical names for call sites (bench.py, tests).
from lodestar_tpu.aot import registry as _aot_registry  # noqa: E402

_aot_registry.register_kernels(
    batch=verify_signature_sets,
    hashed=verify_signature_sets_hashed,
    each=verify_each,
    fast_agg=fast_aggregate_verify,
)

_jit_batch = _aot_registry.jitted("batch")
_jit_hashed = _aot_registry.jitted("hashed")
_jit_each = _aot_registry.jitted("each")


def _encode_pk_sig(sets, size: int):
    """Oracle SignatureSets -> padded pubkey/signature tensors + mask."""
    pks, sigs, act = [], [], []
    for s in sets:
        pks.append(s.public_key.point)
        sigs.append(s.signature.point)
        act.append(True)
    while len(pks) < size:
        pks.append(None)
        sigs.append(None)
        act.append(False)
    pk_aff, pk_inf = cv.encode_g1_affine(pks)
    sig_aff, sig_inf = cv.encode_g2_affine(sigs)
    return pk_aff, pk_inf, sig_aff, sig_inf, jnp.asarray(np.array(act))


def _encode_sets(sets, size: int):
    """Oracle SignatureSets -> padded device tensors (host-side).

    Messages are hashed to G2 on host via the native C fast path
    (hash_to_g2_affine; pure-Python fallback); the device consumes
    affine message points.  The TPU production path skips this host
    hashing entirely — see verify_signature_sets_hashed."""
    from lodestar_tpu.crypto.bls import hash_to_curve as h2c

    pk_aff, pk_inf, sig_aff, sig_inf, act = _encode_pk_sig(sets, size)
    msgs = [h2c.hash_to_g2_affine(s.message) for s in sets]
    msgs += [None] * (size - len(msgs))
    msg_aff, msg_inf = cv.encode_g2_affine(msgs)
    return pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, act


def use_device_h2c() -> bool:
    """Device-side hash-to-curve: default on TPU backends, opt-in/out via
    LODESTAR_TPU_DEVICE_H2C=1/0 (CPU default keeps the smaller program:
    tests and the virtual-mesh dryrun compile the unhashed kernel)."""
    import os as _os

    override = _os.environ.get("LODESTAR_TPU_DEVICE_H2C")
    if override is not None:
        return override == "1"
    return fp._target_platform() == "tpu"


class EncodedJob:
    """Host-encoded device job: padded tensors + dispatch metadata.

    Produced by ``encode_job`` (host CPU work only: expand_message_xmd,
    field-draw reduction, limb packing), consumed by ``execute_batch``
    (device dispatch + sync).  The split lets the pool encode job N+1
    on its host executor while job N holds the device — see
    chain/bls/device_pool.py.
    """

    __slots__ = ("kind", "n", "bucket", "args")

    def __init__(self, kind: str, n: int, bucket: int, args):
        self.kind = kind  # "hashed" | "batch" | "reject"
        self.n = n
        self.bucket = bucket
        self.args = args


def encode_job(sets, rand=None, bucket=None) -> EncodedJob:
    """Host encode stage: oracle SignatureSets -> device-ready tensors.

    Performs the host-side rejection checks (empty input, infinity
    pubkey/signature) up front — a rejected job carries kind="reject"
    and execute_batch returns False without touching the device.
    ``bucket`` overrides the padded width (the pool passes its
    quantized dispatch bucket so job shapes stay inside the AOT warm
    registry); it must be >= len(sets)."""
    import os as _os

    if not sets:
        return EncodedJob("reject", 0, 0, None)
    for s in sets:
        if s.public_key.point is None or s.signature.point is None:
            return EncodedJob("reject", len(sets), 0, None)
    size = bucket if bucket is not None else bucket_size(len(sets))
    assert size >= len(sets), f"bucket {size} < {len(sets)} sets"
    if rand is None:
        rand = [int.from_bytes(_os.urandom(8), "big") | 1 for _ in sets]
    rand = list(rand) + [1] * (size - len(rand))
    bits = cv.scalars_to_bits(rand, 64)
    if use_device_h2c():
        from . import h2c as _h2c

        pk_aff, pk_inf, sig_aff, sig_inf, active = _encode_pk_sig(sets, size)
        u0, u1 = _h2c.encode_field_draws([s.message for s in sets], size)
        return EncodedJob(
            "hashed",
            len(sets),
            size,
            (pk_aff, pk_inf, u0, u1, sig_aff, sig_inf, bits, active),
        )
    pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, active = _encode_sets(
        sets, size
    )
    return EncodedJob(
        "batch",
        len(sets),
        size,
        (pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, bits, active),
    )


def execute_batch(job: EncodedJob) -> bool:
    """Device execute stage for an encoded job: dispatch + sync."""
    if job.kind == "reject":
        return False
    fn = _jit_hashed if job.kind == "hashed" else _jit_batch
    return bool(  # lodelint: disable=host-sync — API boundary: callers need a python bool
        fn(*job.args)
    )


def verify_signature_sets_device(sets, rand=None) -> bool:
    """Host entry: batch-verify oracle SignatureSets on the device.

    Mirrors oracle api.verify_multiple_signature_sets: False on empty input,
    False if any pubkey/signature is infinity or the signature fails the
    subgroup check (checked host-side on deserialization).  On TPU the
    messages are hashed to curve ON DEVICE (verify_signature_sets_hashed);
    the host only runs expand_message_xmd + field reduction.  This is
    encode_job + execute_batch in one call; the pool runs the two stages
    pipelined instead."""
    return execute_batch(encode_job(sets, rand=rand))


_jit_fast_agg = _aot_registry.jitted("fast_agg")


def fast_aggregate_verify_device(public_keys, message: bytes, signature) -> bool:
    """Host entry: fastAggregateVerify (1 msg, N aggregated pubkeys) on
    device — oracle api.fast_aggregate_verify semantics."""
    from lodestar_tpu.crypto.bls import hash_to_curve as h2c

    if not public_keys:
        return False
    pts = [pk.point for pk in public_keys]
    if any(p is None for p in pts) or signature.point is None:
        return False
    size = bucket_size(len(pts))
    pts = pts + [None] * (size - len(pts))
    active = np.zeros(size, dtype=bool)
    active[: len(public_keys)] = True
    pk_aff, pk_inf = cv.encode_g1_affine(pts)
    msg_pt = h2c.hash_to_g2_affine(message)
    msg_aff, msg_inf = cv.encode_g2_affine([msg_pt])
    sig_aff, sig_inf = cv.encode_g2_affine([signature.point])
    squeeze = lambda t: jax.tree.map(lambda x: x[0], t)
    return bool(  # lodelint: disable=host-sync — API boundary: callers need a python bool
        _jit_fast_agg(
            pk_aff,
            pk_inf,
            squeeze(msg_aff),
            msg_inf[0],
            squeeze(sig_aff),
            sig_inf[0],
            jnp.asarray(active),
        )
    )


def verify_each_device(sets, bucket=None):
    """Host entry: per-set verification, returns list[bool].  ``bucket``
    overrides the padded width (the pool passes the same quantized
    bucket as the failed batch job, so the fallback stays inside the
    warm registry's program set)."""
    if not sets:
        return []
    size = bucket if bucket is not None else bucket_size(len(sets))
    assert size >= len(sets), f"bucket {size} < {len(sets)} sets"
    pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, act = _encode_sets(sets, size)
    out = _jit_each(pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, act)
    # API boundary: the per-set host bools leave the device here
    return [bool(x) for x in np.asarray(out)[: len(sets)]]  # lodelint: disable=host-sync
