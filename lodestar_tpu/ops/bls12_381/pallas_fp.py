"""Pallas-fused Montgomery multiplication for the Fp limb engine.

Why: the XLA expression form of ``fp.mont_mul`` lowers to ~20 separate
HBM-roundtripping ops per product (convolution gathers, carry passes,
Kogge-Stone steps).  A pairing chains thousands of products, so the
program is HBM-bandwidth bound.  This kernel computes the whole product +
Montgomery reduction + canonicalization in ONE ``pallas_call`` with every
intermediate in VMEM/registers.  Measured on TPU v5e at the stacked-f12
working width (N=27,648 elements, 32-deep dependency chain): 281 us per
product vs 1050 us for the XLA path — 3.7x.

Layout: the kernel runs **limbs-on-sublanes / elements-on-lanes**
((NLIMBS, N) blocks) so the convolution's limb shifts and the
Kogge-Stone carry steps are sublane moves (nearly free) and all 128
lanes carry real elements.  The public ``mont_mul`` keeps fp.py's
``(..., NLIMBS)`` convention and transposes at the boundary — measured
free: XLA fuses/cancels the transposes between chained products.

Algorithm and overflow bounds are exactly fp.mont_mul's (see its
docstring audit):

    U  = a * b                 (schoolbook convolution, 59 limbs)
    mu = (U mod R) * N' mod R  (low-half convolution, R = 2^390)
    T  = (U + mu * p) / R      (exact; in [0, 2p) -> cond-subtract p)

Dispatch: fp.mont_mul routes here on TPU backends unless
LODESTAR_TPU_PALLAS=0.  CPU tests exercise the kernel through the Pallas
interpreter (tests/test_pallas_fp.py); production CPU stays on the XLA
path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .limbs import LIMB_BITS, MASK, NLIMBS, NPRIME_LIMBS, P_LIMBS

_u32 = jnp.uint32
_WIDE = 2 * NLIMBS - 1            # 59 limbs in a raw product
_BLOCK = 512                      # element lanes per grid step

_NPRIME_NP = np.asarray(NPRIME_LIMBS, dtype=np.uint32)
_P_NP = np.asarray(P_LIMBS, dtype=np.uint32)


# --- sublane-axis ports of fp.py's branch-free carry machinery --------------


def _shl_rows(x, d: int):
    """shifted[i] = x[i-d] along the limb (sublane) axis, zero-filled."""
    if d == 0:
        return x
    return jnp.pad(x[: x.shape[0] - d], ((d, 0), (0, 0)))


def _propagate(g, pr):
    """Kogge-Stone carry/borrow prefix (fp._propagate, sublane version).
    Returns (carry_in rows, carry_out row)."""
    n = g.shape[0]
    G, P = g, pr
    d = 1
    while d < n:
        G = G | (P & _shl_rows(G, d))
        P = P & _shl_rows(P, d)
        d <<= 1
    # static slice: negative indexing lowers to dynamic_slice, which the
    # Mosaic TPU lowering does not implement
    top = jax.lax.slice_in_dim(G, n - 1, n, axis=0)
    return _shl_rows(G, 1), top


def _resolve_single_carries(t):
    """Exact canonicalization; precondition limbs <= 2^14 - 2."""
    g = (t >> LIMB_BITS).astype(_u32)
    pr = (t == MASK).astype(_u32)
    carry_in, _ = _propagate(g, pr)
    return (t + carry_in) & MASK


def _carry_widen(x, width: int):
    """One carry pass producing `width` limb rows (no truncation)."""
    lo = x & MASK
    hi = x >> LIMB_BITS
    n = x.shape[0]
    lo = jnp.pad(lo, ((0, width - n), (0, 0)))
    hi = jnp.pad(hi[: width - 1], ((1, 0), (0, 0)))
    return lo + hi


def _carry_trunc(x):
    lo = x & MASK
    hi = x >> LIMB_BITS
    return lo + jnp.pad(hi[:-1], ((1, 0), (0, 0)))


# --- value-level field ops inside the kernel (limbs-first layout) -----------


def _cond_sub_p(res, p_col):
    """fp._cond_sub_p: canonicalize [0, 2p) -> [0, p)."""
    g = (res < p_col).astype(_u32)
    pr = (res == p_col).astype(_u32)
    borrow_in, borrow_out = _propagate(g, pr)
    dsub = (res + _u32(1 << LIMB_BITS) - p_col - borrow_in) & MASK
    return jnp.where(borrow_out != 0, res, dsub)


def _add_mod(a, b, p_col):
    """fp.add: canonical modular addition."""
    return _cond_sub_p(_resolve_single_carries(a + b), p_col)


def _sub_mod(a, b, p_col):
    """fp.sub: canonical modular subtraction."""
    g = (a < b).astype(_u32)
    pr = (a == b).astype(_u32)
    borrow_in, borrow_out = _propagate(g, pr)
    d = (a + _u32(1 << LIMB_BITS) - b - borrow_in) & MASK
    dp = _resolve_single_carries(d + jnp.broadcast_to(p_col, d.shape))
    return jnp.where(borrow_out != 0, dp, d)


def _mont_core(a, b, np_col, p_col):
    """Full Montgomery product on (30, N) values; canonical output.
    Same algorithm + overflow bounds as fp.mont_mul."""
    n_lanes = a.shape[1]
    # U = a conv b (59 rows): u[i:i+30] += a[i] * b
    u = jnp.zeros((_WIDE, n_lanes), _u32)
    for i in range(NLIMBS):
        u = u + jnp.pad(a[i : i + 1, :] * b, ((i, _WIDE - NLIMBS - i), (0, 0)))
    # two widening passes: limbs <= MASK + ~64, width 61
    u = _carry_widen(_carry_widen(u, _WIDE + 1), _WIDE + 2)

    # mu = (U mod R) * N' mod R (truncated conv, 30 rows)
    u_low = u[:NLIMBS]
    mu = jnp.zeros((NLIMBS, n_lanes), _u32)
    for i in range(NLIMBS):
        mu = mu + jnp.pad(
            u_low[i : i + 1, :] * np_col[: NLIMBS - i], ((i, 0), (0, 0))
        )
    mu = _carry_trunc(_carry_trunc(mu))

    # T = U + mu * p (conv adds rows i..i+29 <= 59; width stays 61)
    t = u
    for i in range(NLIMBS):
        t = t + jnp.pad(
            mu[i : i + 1, :] * p_col, ((i, _WIDE + 2 - NLIMBS - i), (0, 0))
        )
    # limbs < 2^31 + small: two passes then exact resolve (width 63)
    t = _carry_widen(_carry_widen(t, _WIDE + 3), _WIDE + 4)
    t = _resolve_single_carries(t)
    res = t[NLIMBS : 2 * NLIMBS]                       # T / R in [0, 2p)
    return _cond_sub_p(res, p_col)


# --- kernels ----------------------------------------------------------------


def _mont_mul_kernel(a_ref, b_ref, np_ref, p_ref, o_ref):
    o_ref[...] = _mont_core(
        a_ref[...], b_ref[...], np_ref[...], p_ref[...]
    )


def _f2_mul_kernel(a0_ref, a1_ref, b0_ref, b1_ref, np_ref, p_ref, c0_ref, c1_ref):
    """Fused Fp2 Karatsuba multiply (tower.f2_mul: 3 products + the
    pre-adds and post-subs, zero intermediate HBM traffic)."""
    a0, a1 = a0_ref[...], a1_ref[...]
    b0, b1 = b0_ref[...], b1_ref[...]
    np_col, p_col = np_ref[...], p_ref[...]
    lo_a = _add_mod(a0, a1, p_col)
    lo_b = _add_mod(b0, b1, p_col)
    t0 = _mont_core(a0, b0, np_col, p_col)
    t1 = _mont_core(a1, b1, np_col, p_col)
    t2 = _mont_core(lo_a, lo_b, np_col, p_col)
    c0_ref[...] = _sub_mod(t0, t1, p_col)
    c1_ref[...] = _sub_mod(_sub_mod(t2, t0, p_col), t1, p_col)


def _f2_sqr_kernel(a0_ref, a1_ref, np_ref, p_ref, c0_ref, c1_ref):
    """Fused Fp2 square: (a0+a1)(a0-a1), 2*a0*a1."""
    a0, a1 = a0_ref[...], a1_ref[...]
    np_col, p_col = np_ref[...], p_ref[...]
    s = _add_mod(a0, a1, p_col)
    d = _sub_mod(a0, a1, p_col)
    t0 = _mont_core(s, d, np_col, p_col)
    t1 = _mont_core(a0, a1, np_col, p_col)
    c0_ref[...] = t0
    c1_ref[...] = _add_mod(t1, t1, p_col)


def _mont_mul_limbs_first(a2T, b2T, *, interpret: bool):
    from jax.experimental import pallas as pl

    n = a2T.shape[1]
    return pl.pallas_call(
        _mont_mul_kernel,
        out_shape=jax.ShapeDtypeStruct((NLIMBS, n), _u32),
        grid=(n // _BLOCK,),
        in_specs=[
            pl.BlockSpec((NLIMBS, _BLOCK), lambda i: (0, i)),
            pl.BlockSpec((NLIMBS, _BLOCK), lambda i: (0, i)),
            pl.BlockSpec((NLIMBS, 1), lambda i: (0, 0)),
            pl.BlockSpec((NLIMBS, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((NLIMBS, _BLOCK), lambda i: (0, i)),
        interpret=interpret,
    )(a2T, b2T, jnp.asarray(_NPRIME_NP)[:, None], jnp.asarray(_P_NP)[:, None])


def mont_mul(a: jnp.ndarray, b: jnp.ndarray, *, interpret: bool = False) -> jnp.ndarray:
    """Drop-in fused replacement for fp.mont_mul (canonical in/out,
    ``(..., NLIMBS)`` convention; boundary transposes are fused away by
    XLA).  `interpret=True` runs the Pallas interpreter (CPU tests).

    @bounds: a [0, 2^13-1], b [0, 2^13-1], interpret host -> [0, 2^13-1]
    """
    a, b = jnp.broadcast_arrays(a, b)
    aT, lead, n = _prep(a)
    bT, _, _ = _prep(b)
    return _unprep(_mont_mul_limbs_first(aT, bT, interpret=interpret), lead, n)


# --- f2-level fused entry points (consumed by tower.f2_mul/f2_sqr) ----------


def _prep(x):
    """(..., 30) -> padded (30, n) transposed view + restore info."""
    lead = x.shape[:-1]
    x2 = x.reshape((-1, NLIMBS))
    n = x2.shape[0]
    pad = (-n) % _BLOCK
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2.T, lead, n


def _unprep(outT, lead, n):
    out = outT.T
    if out.shape[0] != n:
        out = out[:n]
    return out.reshape((*lead, NLIMBS))


def _consts():
    return jnp.asarray(_NPRIME_NP)[:, None], jnp.asarray(_P_NP)[:, None]


def _limb_specs(n_data: int):
    from jax.experimental import pallas as pl

    data = [pl.BlockSpec((NLIMBS, _BLOCK), lambda i: (0, i)) for _ in range(n_data)]
    consts = [pl.BlockSpec((NLIMBS, 1), lambda i: (0, 0)) for _ in range(2)]
    return data + consts


def f2_mul(a, b, *, interpret: bool = False):
    """Fused tower.f2_mul: ((..,30),(..,30)) x 2 -> 2-tuple.

    @bounds: a [0, 2^13-1], b [0, 2^13-1], interpret host -> [0, 2^13-1]
    """
    from jax.experimental import pallas as pl

    a0, a1, b0, b1 = jnp.broadcast_arrays(a[0], a[1], b[0], b[1])
    a0T, lead, n = _prep(a0)
    a1T, _, _ = _prep(a1)
    b0T, _, _ = _prep(b0)
    b1T, _, _ = _prep(b1)
    npc, pc = _consts()
    width = a0T.shape[1]
    shape = jax.ShapeDtypeStruct((NLIMBS, width), _u32)
    out_spec = pl.BlockSpec((NLIMBS, _BLOCK), lambda i: (0, i))
    c0T, c1T = pl.pallas_call(
        _f2_mul_kernel,
        out_shape=(shape, shape),
        grid=(width // _BLOCK,),
        in_specs=_limb_specs(4),
        out_specs=(out_spec, out_spec),
        interpret=interpret,
    )(a0T, a1T, b0T, b1T, npc, pc)
    return _unprep(c0T, lead, n), _unprep(c1T, lead, n)


def f2_sqr(a, *, interpret: bool = False):
    """Fused tower.f2_sqr.

    @bounds: a [0, 2^13-1], interpret host -> [0, 2^13-1]
    """
    from jax.experimental import pallas as pl

    a0, a1 = jnp.broadcast_arrays(a[0], a[1])
    a0T, lead, n = _prep(a0)
    a1T, _, _ = _prep(a1)
    npc, pc = _consts()
    width = a0T.shape[1]
    shape = jax.ShapeDtypeStruct((NLIMBS, width), _u32)
    out_spec = pl.BlockSpec((NLIMBS, _BLOCK), lambda i: (0, i))
    c0T, c1T = pl.pallas_call(
        _f2_sqr_kernel,
        out_shape=(shape, shape),
        grid=(width // _BLOCK,),
        in_specs=_limb_specs(2),
        out_specs=(out_spec, out_spec),
        interpret=interpret,
    )(a0T, a1T, npc, pc)
    return _unprep(c0T, lead, n), _unprep(c1T, lead, n)
