"""BLS12-381 on TPU: limb-vector field arithmetic, towers, curves, pairing.

Layout: an Fp element is a uint32 tensor whose trailing axis holds
``NLIMBS`` radix-``2**LIMB_BITS`` limbs in Montgomery form.  All operations
broadcast over arbitrary leading batch axes, so "vmap" over a signature batch
is just array layout — the natural TPU mapping of the reference's
data-parallel BLS worker pool (chain/bls/multithread/index.ts:98).
"""
