"""Fp2/Fp6/Fp12 tower arithmetic over limb tensors (JAX pytrees).

Elements are nested tuples of Fp limb tensors, mirroring the oracle's layout
(lodestar_tpu/crypto/bls/fields.py):
  Fp2  = (c0, c1)          u^2 = -1
  Fp6  = (a0, a1, a2)      v^3 = xi = 1 + u
  Fp12 = (b0, b1)          w^2 = v

SIMD structure: every tower multiplication gathers the *independent* Fp
products of its Karatsuba layer into ONE stacked ``fp.mont_mul`` call
(an f12_mul is 54 Fp products but only 3 mont_mul instances in the HLO:
one per tower level).  This keeps compiled program size O(formula depth)
instead of O(product count) — both an XLA-compile-time requirement and the
right shape for the TPU VPU, which wants wide element-wise ops.

Frobenius coefficients (gamma1[i] = xi^(i(p-1)/6)) are computed at import
time with the oracle's exact integer arithmetic and embedded as Montgomery
limb constants — no transcription risk.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lodestar_tpu.crypto.bls import fields as _orc
from . import fp
from .limbs import int_to_limbs, to_mont_int

# ---------------------------------------------------------------------------
# host encode/decode helpers (tests, constants)
# ---------------------------------------------------------------------------


def _const(x: int) -> jnp.ndarray:
    """Python int mod p -> device Montgomery limb constant."""
    return jnp.asarray(int_to_limbs(to_mont_int(x % _orc.P)))


def encode_fp2(a) -> tuple:
    return (_const(a[0]), _const(a[1]))


def encode_fp6(a) -> tuple:
    return tuple(encode_fp2(c) for c in a)


def encode_fp12(a) -> tuple:
    return tuple(encode_fp6(c) for c in a)


def _dec(x) -> int:
    return fp.decode(np.asarray(x))


def decode_fp2(a):
    return (_dec(a[0]), _dec(a[1]))


def decode_fp6(a):
    return tuple(decode_fp2(c) for c in a)


def decode_fp12(a):
    return tuple(decode_fp6(c) for c in a)


# ---------------------------------------------------------------------------
# stacking helpers: N same-shaped pytrees -> one pytree with leading axis N
# ---------------------------------------------------------------------------


def _stack(items):
    return jax.tree.map(lambda *xs: jnp.stack(xs), *items)


def _unstack(tree, n):
    return [jax.tree.map(lambda t: t[i], tree) for i in range(n)]


def outlined(fn):
    """On the CPU backend, wrap ``fn`` in a length-1 lax.scan.

    XLA:CPU's compile time is superlinear in flat graph size; a full pairing
    inlines to ~10^5 elementwise ops and takes hours to compile.  A scan body
    is compiled as its own subcomputation, so outlining each tower op keeps
    every flat region small.  On TPU (where the compiler handles large fused
    graphs well, and fusion is where the performance is) the wrapper is a
    no-op.
    """
    import functools

    @functools.wraps(fn)
    def wrapper(*args):
        # platform via fp._target_platform: under the axon plugin
        # jax.default_backend() misreports "tpu" in CPU-pinned processes
        if fp._target_platform() != "cpu":
            return fn(*args)
        xs = jax.tree.map(lambda t: t[None], args)
        _, out = jax.lax.scan(lambda c, x: (c, fn(*x)), jnp.uint32(0), xs)
        return jax.tree.map(lambda t: t[0], out)

    return wrapper


# ---------------------------------------------------------------------------
# Fp2
# ---------------------------------------------------------------------------


def f2_zeros(shape=()):
    return (fp.zeros(shape), fp.zeros(shape))


def f2_one(shape=()):
    return (fp.one_mont(shape), fp.zeros(shape))


def f2_add(a, b):
    return (fp.add(a[0], b[0]), fp.add(a[1], b[1]))


def f2_sub(a, b):
    return (fp.sub(a[0], b[0]), fp.sub(a[1], b[1]))


def f2_neg(a):
    return (fp.neg(a[0]), fp.neg(a[1]))


def f2_dbl(a):
    return f2_add(a, a)


def f2_mul(a, b):
    """Karatsuba: fused pallas kernel on TPU, one 3-way stacked mont_mul
    on the XLA path."""
    if fp._use_pallas():
        from . import pallas_fp

        return pallas_fp.f2_mul(a, b)
    lo = (fp.add(a[0], a[1]), fp.add(b[0], b[1]))
    A = jnp.stack([a[0], a[1], lo[0]])
    B = jnp.stack([b[0], b[1], lo[1]])
    T = fp.mont_mul(A, B)
    t0, t1, t2 = T[0], T[1], T[2]
    return (fp.sub(t0, t1), fp.sub(fp.sub(t2, t0), t1))


def f2_sqr(a):
    if fp._use_pallas():
        from . import pallas_fp

        return pallas_fp.f2_sqr(a)
    A = jnp.stack([fp.add(a[0], a[1]), a[0]])
    B = jnp.stack([fp.sub(a[0], a[1]), a[1]])
    T = fp.mont_mul(A, B)
    return (T[0], fp.add(T[1], T[1]))


def f2_conj(a):
    return (a[0], fp.neg(a[1]))


def f2_mul_fp(a, k):
    """Fp2 * Fp: one stacked mont_mul."""
    T = fp.mont_mul(jnp.stack([a[0], a[1]]), jnp.stack([k, k]))
    return (T[0], T[1])


def f2_mul_by_xi(a):
    # xi = 1 + u
    return (fp.sub(a[0], a[1]), fp.add(a[0], a[1]))


def f2_inv(a):
    T = fp.mont_mul(jnp.stack([a[0], a[1]]), jnp.stack([a[0], a[1]]))
    norm = fp.add(T[0], T[1])
    ninv = fp.inv(norm)
    U = fp.mont_mul(jnp.stack([a[0], a[1]]), jnp.stack([ninv, ninv]))
    return (U[0], fp.neg(U[1]))


def f2_is_zero(a):
    return fp.is_zero(a[0]) & fp.is_zero(a[1])


def f2_eq(a, b):
    return fp.eq(a[0], b[0]) & fp.eq(a[1], b[1])


def f2_select(cond, a, b):
    return (fp.select(cond, a[0], b[0]), fp.select(cond, a[1], b[1]))


# ---------------------------------------------------------------------------
# Fp6
# ---------------------------------------------------------------------------


def f6_zeros(shape=()):
    return (f2_zeros(shape), f2_zeros(shape), f2_zeros(shape))


def f6_one(shape=()):
    return (f2_one(shape), f2_zeros(shape), f2_zeros(shape))


def f6_add(a, b):
    return tuple(f2_add(x, y) for x, y in zip(a, b))


def f6_sub(a, b):
    return tuple(f2_sub(x, y) for x, y in zip(a, b))


def f6_neg(a):
    return tuple(f2_neg(x) for x in a)


def f6_mul(a, b):
    """Toom-style: 6 independent Fp2 products in one stacked f2_mul."""
    a0, a1, a2 = a
    b0, b1, b2 = b
    # pre-adds, batched: [(a1+a2), (a0+a1), (a0+a2)] and same for b
    pa = _stack([a1, a0, a0])
    pa2 = _stack([a2, a1, a2])
    pb = _stack([b1, b0, b0])
    pb2 = _stack([b2, b1, b2])
    sa = f2_add(pa, pa2)
    sb = f2_add(pb, pb2)
    s = _unstack(sa, 3)
    t = _unstack(sb, 3)
    # products: t0=a0b0, t1=a1b1, t2=a2b2, m12=(a1+a2)(b1+b2),
    #           m01=(a0+a1)(b0+b1), m02=(a0+a2)(b0+b2)
    P = f2_mul(_stack([a0, a1, a2, s[0], s[1], s[2]]),
               _stack([b0, b1, b2, t[0], t[1], t[2]]))
    t0, t1, t2, m12, m01, m02 = _unstack(P, 6)
    c0 = f2_add(t0, f2_mul_by_xi(f2_sub(f2_sub(m12, t1), t2)))
    c1 = f2_add(f2_sub(f2_sub(m01, t0), t1), f2_mul_by_xi(t2))
    c2 = f2_add(f2_sub(f2_sub(m02, t0), t2), t1)
    return (c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_mul_by_v(a):
    return (f2_mul_by_xi(a[2]), a[0], a[1])


def f6_inv(a):
    a0, a1, a2 = a
    # layer 1: squares and cross products in one stacked f2_mul
    P = f2_mul(_stack([a0, a2, a1, a1, a0, a0]),
               _stack([a0, a2, a1, a2, a1, a2]))
    s0, s2, s1, a12, a01, a02 = _unstack(P, 6)
    c0 = f2_sub(s0, f2_mul_by_xi(a12))
    c1 = f2_sub(f2_mul_by_xi(s2), a01)
    c2 = f2_sub(s1, a02)
    # layer 2: t = a0 c0 + xi(a1 c2 + a2 c1)
    Q = f2_mul(_stack([a0, a1, a2]), _stack([c0, c2, c1]))
    q0, q1, q2 = _unstack(Q, 3)
    t = f2_add(q0, f2_mul_by_xi(f2_add(q1, q2)))
    tinv = f2_inv(t)
    R = f2_mul(_stack([c0, c1, c2]),
               _stack([tinv, tinv, tinv]))
    r0, r1, r2 = _unstack(R, 3)
    return (r0, r1, r2)


def f6_is_zero(a):
    return f2_is_zero(a[0]) & f2_is_zero(a[1]) & f2_is_zero(a[2])


def f6_select(cond, a, b):
    return tuple(f2_select(cond, x, y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# Fp12
# ---------------------------------------------------------------------------


def f12_zeros(shape=()):
    return (f6_zeros(shape), f6_zeros(shape))


def f12_one(shape=()):
    return (f6_one(shape), f6_zeros(shape))


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_sub(a, b):
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def f12_mul(a, b):
    """Karatsuba over Fp6: 3 independent f6 products, one stacked call."""
    a0, a1 = a
    b0, b1 = b
    P = f6_mul(_stack([a0, a1, f6_add(a0, a1)]),
               _stack([b0, b1, f6_add(b0, b1)]))
    t0, t1, t01 = _unstack(P, 3)
    c0 = f6_add(t0, f6_mul_by_v(t1))
    c1 = f6_sub(f6_sub(t01, t0), t1)
    return (c0, c1)


def f12_sqr(a):
    a0, a1 = a
    P = f6_mul(_stack([a0, f6_add(a0, a1)]),
               _stack([a1, f6_add(a0, f6_mul_by_v(a1))]))
    t, c0 = _unstack(P, 2)
    c0 = f6_sub(f6_sub(c0, t), f6_mul_by_v(t))
    c1 = f6_add(t, t)
    return (c0, c1)


def f12_conj(a):
    return (a[0], f6_neg(a[1]))


def f12_inv(a):
    a0, a1 = a
    P = f6_mul(_stack([a0, a1]), _stack([a0, a1]))
    s0, s1 = _unstack(P, 2)
    t = f6_sub(s0, f6_mul_by_v(s1))
    tinv = f6_inv(t)
    Q = f6_mul(_stack([a0, a1]), _stack([tinv, tinv]))
    q0, q1 = _unstack(Q, 2)
    return (q0, f6_neg(q1))


def f12_is_one(a):
    c00 = a[0][0]
    eq_one = fp.eq(c00[0], jnp.broadcast_to(fp.one_mont(), c00[0].shape)) & fp.is_zero(c00[1])
    return eq_one & f2_is_zero(a[0][1]) & f2_is_zero(a[0][2]) & f6_is_zero(a[1])


def f12_select(cond, a, b):
    return (f6_select(cond, a[0], b[0]), f6_select(cond, a[1], b[1]))


# ---------------------------------------------------------------------------
# Frobenius (coefficients computed from the oracle at import time)
# ---------------------------------------------------------------------------

_GAMMA1_CONST = [encode_fp2(g) for g in _orc.GAMMA1]


def _to_wcoeffs(a):
    (a0, a1, a2), (b0, b1, b2) = a
    return [a0, b0, a1, b1, a2, b2]


def _from_wcoeffs(c):
    return ((c[0], c[2], c[4]), (c[1], c[3], c[5]))


def f12_frobenius(a, power: int = 1):
    out = a
    for _ in range(power % 12):
        coeffs = _to_wcoeffs(out)
        gammas = [jax.tree.map(lambda t: jnp.broadcast_to(t, coeffs[0][0].shape), g)
                  for g in _GAMMA1_CONST]
        conj = [f2_conj(c) for c in coeffs]
        P = f2_mul(_stack(conj), _stack(gammas))
        coeffs = _unstack(P, 6)
        out = _from_wcoeffs(coeffs)
    return out


# ---------------------------------------------------------------------------
# Program-build containment, two layers:
#   * `outlined` (CPU only): length-1 scan bodies keep XLA:CPU's
#     superlinear fusion/simplification passes fed with small flat
#     regions (the inlined full pairing takes 30+ min / tens of GB
#     there).  On TPU the wrapper no-ops.
#   * `opcache.cached` (all platforms): each op's jaxpr is traced ONCE
#     per argument shape and replayed at every further call site —
#     without it, every site re-traces the pallas kernel / scan body
#     (~0.75 s per f2_mul site on the 1-CPU bench host; build time, not
#     XLA optimization, dominated the cold-compile blowups of rounds
#     1-3).  See opcache.py for measurements.
# ---------------------------------------------------------------------------

from .opcache import cached as _cached

f2_mul = _cached(outlined(f2_mul))
f2_sqr = _cached(outlined(f2_sqr))
f2_inv = _cached(outlined(f2_inv))
f6_mul = _cached(outlined(f6_mul))
f6_sqr = _cached(outlined(f6_sqr))
f6_inv = _cached(outlined(f6_inv))
f12_mul = _cached(outlined(f12_mul))
f12_sqr = _cached(outlined(f12_sqr))
f12_inv = _cached(outlined(f12_inv))
f12_frobenius = _cached(f12_frobenius, static_argnums=(1,))
f12_select = _cached(f12_select)
f12_conj = _cached(f12_conj)
