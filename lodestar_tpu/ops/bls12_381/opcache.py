"""Trace-once op cache — the program-build-time fix for the BLS engine.

Measured on the 1-CPU bench host (tools/probe_dedupe.py): every
``pl.pallas_call`` re-traces its kernel body at EVERY call site (~0.5 s
trace + ~0.25 s lowering per ``f2_mul`` site), while XLA itself dedupes
identical kernels fine (256 chained sites: 136 s trace + 61 s lower vs
22 s compile).  The same re-trace tax applies to the CPU path's
``lax.scan`` bodies (CIOS loop, ``tower.outlined`` wrappers).  Program
BUILD time — not XLA optimization — was the dominant term in the cold
420 s+ bench stages.

``cached(fn)`` traces ``fn`` once per (argument shapes/dtypes, static
args, platform) into a ClosedJaxpr and replays it with ``eval_jaxpr`` at
every subsequent call site: one primitive bind per inner primitive, no
kernel/scan re-tracing, and — because all sites now share one jaxpr
object — the param-identity-keyed lowering caches hit as well.

Replay inlines the jaxpr into the caller's trace, so jit/vmap/shard_map
semantics are exactly those of calling ``fn`` directly.  The cache key
includes the fp platform dispatch state (LODESTAR_TPU_FP_PLATFORM /
PALLAS toggles) because those select different traced code paths.
"""
from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

try:  # jax >= 0.5 keeps eval_jaxpr in _src.core; fall back to jax.core
    from jax._src.core import eval_jaxpr as _eval_jaxpr
except ImportError:  # pragma: no cover
    from jax.core import eval_jaxpr as _eval_jaxpr

_CACHE: dict = {}


def _cache_generation() -> str:
    from lodestar_tpu.aot.cache import cache_generation

    return cache_generation()


def _env_key():
    import os

    from . import fp

    return (
        fp._target_platform(),
        fp._use_pallas(),
        os.environ.get("LODESTAR_TPU_CPU_PARALLEL_FP"),
        # cache-generation salt: a generation bump must invalidate
        # EVERY cached program artifact in the process, traced jaxprs
        # included, so no replay straddles the old and new persistent-
        # cache generations.  The shared helper normalizes the value
        # exactly like the persistent-cache dir does.
        _cache_generation(),
    )


def _leaf_aval(leaf) -> tuple | None:
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return None
    return (tuple(shape), str(dtype))


def cached(fn, static_argnums: tuple = ()):
    """Wrap an op so its jaxpr is traced once per shape and replayed."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if kwargs:  # keyword calls bypass the cache (key would be ambiguous)
            return fn(*args, **kwargs)
        statics = tuple(args[i] for i in static_argnums if i < len(args))
        dyn = tuple(a for i, a in enumerate(args) if i not in static_argnums)
        leaves, treedef = jax.tree.flatten(dyn)
        avals = []
        for leaf in leaves:
            av = _leaf_aval(leaf)
            if av is None:  # non-array leaf (None, python scalar): bypass
                return fn(*args)
            avals.append(av)
        try:
            key = (fn, statics, treedef, tuple(avals), _env_key())
            hash(key)
        except TypeError:
            return fn(*args)
        entry = _CACHE.get(key)
        if entry is None:
            structs = [
                jax.ShapeDtypeStruct(s, d) for (s, d) in avals
            ]

            def flat_fn(*flat):
                dyn_t = iter(jax.tree.unflatten(treedef, flat))
                full = [
                    a if i in static_argnums else next(dyn_t)
                    for i, a in enumerate(args)
                ]
                return fn(*full)

            closed, out_shape = jax.make_jaxpr(flat_fn, return_shape=True)(
                *structs
            )
            _, out_tree = jax.tree.flatten(out_shape)
            entry = (closed, out_tree)
            _CACHE[key] = entry
        closed, out_tree = entry
        out = _eval_jaxpr(closed.jaxpr, closed.consts, *leaves)
        return jax.tree.unflatten(out_tree, out)

    wrapper.__wrapped_uncached__ = fn
    return wrapper


def clear() -> None:
    _CACHE.clear()
