"""Compile-bucket geometry for the BLS kernels — pure integer math.

Every jitted BLS program has a static batch width, so dynamic batch
sizes are met by padding up to a small set of compile buckets.  This
module is the ONE place that set is defined; the kernel wrappers
(ops/bls12_381/verify.py), the device pool's latency governor
(chain/bls/device_pool.py) and the AOT warm registry
(lodestar_tpu/aot/registry.py) all derive their widths from it, so the
governor can never mint a program shape the warm tool does not know
about.

Deliberately jax-free: the device pool imports it for width policy in
service tests that never touch a kernel.
"""
from __future__ import annotations

from typing import Optional, Tuple

# Direct-call buckets (verify_signature_sets_device etc.): geometric up
# to 512, then 512-granular — the Pallas kernels keep per-batch latency
# nearly flat up to ~512 sets, so large buckets pay off.
BUCKETS: Tuple[int, ...] = (4, 8, 16, 32, 64, 128, 256, 512)
_STEP = 512

# The device pool quantizes every job to one of THESE widths (not the
# full direct-call ladder): the kernel's latency is floor-dominated, so
# padding a 3-set job to 128 costs almost nothing on device while
# collapsing the set of programs the warm tool must compile from eleven
# buckets to four — trickle (128), governed steady state (512), the
# mid drain rung (1024) and the overload drain (2048).
POOL_BUCKETS: Tuple[int, ...] = (128, 512, 1024, 2048)


def bucket_size(n: int) -> int:
    """Smallest compile bucket holding n sets (512-granular beyond 512)."""
    for b in BUCKETS:
        if n <= b:
            return b
    return ((n + _STEP - 1) // _STEP) * _STEP


def pool_bucket(n: int, cap: Optional[int] = None) -> int:
    """The pool's dispatch bucket for an n-set job: the smallest
    POOL_BUCKETS width holding it (respecting an explicit pool cap —
    tests build 8-set pools, which fall back to the direct ladder).
    When no rung or ladder bucket fits under the cap (a non-rung cap
    like 600 with n near it), the cap itself is the width: the job must
    still be held, and padding past an explicit cap is never allowed."""
    for b in POOL_BUCKETS:
        if n <= b and (cap is None or b <= cap):
            return b
    b = bucket_size(n)
    if cap is not None and b > cap >= n:
        return cap
    return b


def align_down(n: int) -> int:
    """Largest bucket-boundary width <= n (floor; never below the
    smallest bucket).  The latency governor aligns its width caps with
    this so a cap like 882 dispatches 512-bucket jobs instead of
    minting an unwarmed 1024-bucket program at runtime."""
    if n >= _STEP:
        return (n // _STEP) * _STEP
    best = BUCKETS[0]
    for b in BUCKETS:
        if b <= n:
            best = b
    return best
