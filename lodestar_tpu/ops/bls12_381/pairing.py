"""Optimal ate pairing on limb tensors — the TPU signature-verification core.

The Miller loop is a single ``lax.scan`` over the 64-bit BLS parameter with
the projective sparse-line formulas validated CPU-side in
lodestar_tpu/crypto/bls/pairing_proj.py (see its docstring for the
derivation).  The final exponentiation uses the x-adic hard-part chain
validated in lodestar_tpu/crypto/bls/pairing.py::hard_part_x_chain.

Batching: all inputs carry leading batch axes; a batch of Miller loops is
one compiled program (the TPU analogue of the reference's per-worker batch
verification, packages/beacon-node/src/chain/bls/multithread/worker.ts:32).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lodestar_tpu.crypto.bls.fields import ABS_X
from . import fp, tower as tw

# MSB-first bits of |x| after the leading bit (the loop starts at T = Q).
_X_BITS = np.array([int(b) for b in bin(ABS_X)[3:]], dtype=np.uint32)


def _line_sparse(c0, d1, d2, shape_ref):
    z = jnp.zeros_like(shape_ref[0])
    zero2 = (z, jnp.zeros_like(z))
    return ((c0, zero2, zero2), (zero2, d1, d2))


def _f2_mul_small(a, k: int):
    """a * k for tiny static k via additions (k in 2..9)."""
    acc = a
    for _ in range(k - 1):
        acc = tw.f2_add(acc, a)
    return acc


def _f2_mul_fp_limb(a, xp):
    """Fp2 * Fp (xp is an Fp limb tensor)."""
    return (fp.mont_mul(a[0], xp), fp.mont_mul(a[1], xp))


def _dbl_step(t, xp, yp):
    """Projective doubling step; returns (sparse line at P, 2T).

    Formulas: pairing_proj.py::_dbl_step (validated vs the affine oracle).
    """
    X, Y, Z = t
    xx = tw.f2_sqr(X)
    yy = tw.f2_sqr(Y)
    x3 = tw.f2_mul(xx, X)
    yyz = tw.f2_mul(yy, Z)
    yz = tw.f2_mul(Y, Z)
    # line: c0 = -2 xi Y Z^2 yP ; d1 = 2Y^2Z - 3X^3 ; d2 = 3 X^2 Z xP
    c0 = tw.f2_neg(tw.f2_mul_by_xi(_f2_mul_fp_limb(tw.f2_dbl(tw.f2_mul(yz, Z)), yp)))
    d1 = tw.f2_sub(tw.f2_dbl(yyz), _f2_mul_small(x3, 3))
    d2 = _f2_mul_fp_limb(_f2_mul_small(tw.f2_mul(xx, Z), 3), xp)
    # update
    x3_9 = _f2_mul_small(x3, 9)
    yyz_8 = _f2_mul_small(yyz, 8)
    Xn = tw.f2_mul(tw.f2_dbl(tw.f2_mul(tw.f2_mul(X, Y), Z)), tw.f2_sub(x3_9, yyz_8))
    Yn = tw.f2_sub(
        tw.f2_mul(x3_9, tw.f2_sub(_f2_mul_small(yyz, 4), _f2_mul_small(x3, 3))),
        _f2_mul_small(tw.f2_sqr(yyz), 8),
    )
    Zn = _f2_mul_small(tw.f2_mul(tw.f2_mul(yy, Y), tw.f2_mul(tw.f2_sqr(Z), Z)), 8)
    return _line_sparse(c0, d1, d2, c0), (Xn, Yn, Zn)


def _add_step(t, q, xp, yp):
    """Projective mixed-addition step; returns (sparse line at P, T+Q)."""
    X, Y, Z = t
    x2, y2 = q
    theta = tw.f2_sub(tw.f2_mul(y2, Z), Y)
    lam = tw.f2_sub(tw.f2_mul(x2, Z), X)
    c0 = tw.f2_neg(tw.f2_mul_by_xi(_f2_mul_fp_limb(lam, yp)))
    d1 = tw.f2_sub(tw.f2_mul(lam, y2), tw.f2_mul(theta, x2))
    d2 = _f2_mul_fp_limb(theta, xp)
    ll = tw.f2_sqr(lam)
    lll = tw.f2_mul(ll, lam)
    llx = tw.f2_mul(ll, X)
    n = tw.f2_sub(tw.f2_sub(tw.f2_mul(tw.f2_sqr(theta), Z), tw.f2_dbl(llx)), lll)
    Xn = tw.f2_mul(lam, n)
    Yn = tw.f2_sub(tw.f2_mul(theta, tw.f2_sub(llx, n)), tw.f2_mul(lll, Y))
    Zn = tw.f2_mul(lll, Z)
    return _line_sparse(c0, d1, d2, c0), (Xn, Yn, Zn)


def miller_loop(q_aff, p_aff):
    """f_{|x|,Q}(P) conjugated for x < 0.

    q_aff: affine G2 ((x0,x1),(y0,y1)) Fp2 limb tuples, batched.
    p_aff: affine G1 (x, y) Fp limb tensors, batched.

    PRECONDITION: inputs must be finite affine points.  Infinity inputs
    produce garbage limbs; verify.py masks such entries out of the product
    (multi_miller_product / pairing_check) before they reach a reduction.
    """
    xq, yq = q_aff
    xp, yp = p_aff
    one2 = (jnp.broadcast_to(fp.one_mont(), xq[0].shape), jnp.zeros_like(xq[0]))
    t0 = (xq, yq, one2)
    f0 = tw.f12_one(shape=xp.shape[:-1])
    bits = jnp.asarray(_X_BITS)

    def body(carry, bit):
        f, t = carry
        line, t = _dbl_step(t, xp, yp)
        f = tw.f12_mul(tw.f12_sqr(f), line)

        def with_add(ft):
            f, t = ft
            line, t2 = _add_step(t, (xq, yq), xp, yp)
            return (tw.f12_mul(f, line), t2)

        f, t = jax.lax.cond(bit != 0, with_add, lambda ft: ft, (f, t))
        return (f, t), None

    (f, _), _ = jax.lax.scan(body, (f0, t0), bits)
    return tw.f12_conj(f)


# ---------------------------------------------------------------------------
# final exponentiation (x-adic chain, mirrors oracle hard_part_x_chain)
# ---------------------------------------------------------------------------


def _cyclotomic_pow_abs_x(a):
    """a^|x| by square-and-multiply over the static 64-bit parameter."""
    bits = jnp.asarray(np.array([int(b) for b in bin(ABS_X)[2:]], dtype=np.uint32))
    one = tw.f12_one(shape=jax.tree.leaves(a)[0].shape[:-1])

    def body(acc, bit):
        acc = tw.f12_sqr(acc)
        acc = jax.lax.cond(bit != 0, lambda x: tw.f12_mul(x, a), lambda x: x, acc)
        return acc, None

    acc, _ = jax.lax.scan(body, one, bits)
    return acc


def _pow_neg_x(a):
    return tw.f12_conj(_cyclotomic_pow_abs_x(a))


def final_exponentiation(f):
    """f^((p^6-1)(p^2+1) * 3(p^4-p^2+1)/r) — same chain as the oracle.

    The hard part needs FIVE x-adic exponentiations.  Naively each becomes
    its own 64-iteration scan and XLA compiles five copies of the (large)
    square-and-multiply body — measured ~418 s of the TPU compile budget.
    Instead ONE outer scan runs the pow with a per-step epilogue selected
    by ``lax.switch``: the pow body compiles once (~5x compile saving),
    the tiny epilogues are the only duplicated code.

      step0: t0 = conj(x^|x| * x)         x = m
      step1: t1 = conj(x^|x| * x)         x = t0
      step2: a  = conj(x^|x|) * frob(x,1) x = t1
      step3: b  = conj(x^|x|)             x = a
      step4: t4 = conj(x^|x|) * frob(prev,2) * conj(prev)   prev = a
    """
    # easy part
    f1 = tw.f12_mul(tw.f12_conj(f), tw.f12_inv(f))
    m = tw.f12_mul(tw.f12_frobenius(f1, 2), f1)

    # Per-step epilogues computed UNCONDITIONALLY and selected by the step
    # counter: an earlier lax.switch version compiled each of the 5
    # branches as its own optimized subcomputation (~2x of the pairing's
    # XLA time).  Since conj distributes over mul (conj(p*x) = pc*conj(x)),
    # every epilogue is pc * y1 * y2 with y1/y2 chosen by selects — TWO
    # f12_mul in the body instead of four:
    #   k 0,1: y1 = conj(x)       y2 = 1      (t = conj(p * x))
    #   k 2:   y1 = frob(x, 1)    y2 = 1
    #   k 3:   y1 = 1             y2 = 1      (t = conj(p))
    #   k 4:   y1 = frob(prev,2)  y2 = conj(prev)
    def body(carry, k):
        x, prev = carry
        p = _cyclotomic_pow_abs_x(x)
        pc = tw.f12_conj(p)
        one = tw.f12_one(shape=jax.tree.leaves(x)[0].shape[:-1])
        y1 = tw.f12_select(k == 4, tw.f12_frobenius(prev, 2), one)
        y1 = tw.f12_select(k == 2, tw.f12_frobenius(x, 1), y1)
        y1 = tw.f12_select(k <= 1, tw.f12_conj(x), y1)
        y2 = tw.f12_select(k == 4, tw.f12_conj(prev), one)
        out = tw.f12_mul(tw.f12_mul(pc, y1), y2)
        return (out, x), None

    (t4, _), _ = jax.lax.scan(body, (m, m), jnp.arange(5))
    return tw.f12_mul(t4, tw.f12_mul(tw.f12_sqr(m), m))


def pairing(p_aff, q_aff):
    """e(P, Q) for finite affine inputs (batched)."""
    return final_exponentiation(miller_loop(q_aff, p_aff))
