"""Fp arithmetic over limb tensors — the innermost TPU kernel layer.

Every value is a uint32 tensor ``(..., NLIMBS)`` of radix-2^13 limbs in
Montgomery form, canonical (each limb < 2^13, value < p).  Ops broadcast over
leading axes, so a batch of field elements is just a leading dimension — the
TPU-native analogue of the reference's per-core BLS worker data parallelism
(packages/beacon-node/src/chain/bls/multithread/index.ts:98).

Design note on carry handling: carry/borrow propagation is NOT a sequential
scan here.  A pairing is ~10^5 field ops; giving each one a ``lax.scan``
produces thousands of XLA while-subcomputations and intractable compile
times.  Instead, carries resolve with a branch-free BROADCAST-COMPARE
formulation: carry_in[i] = OR_{j<i} (generate[j] AND limbs j+1..i-1 all
propagate), where the "all propagate" predicate is a prefix-count equality
computed with ONE tiny static matmul (cumulative sum by lower-triangular
matrix).  This yields ~10 elementwise HLO ops on a (..., N, N) tile per
carry resolution — no concatenate/pad chains, which XLA:CPU's fusion and
algebraic-simplifier passes handle pathologically slowly (measured ~1 s of
compile time PER shift-by-concat op, vs milliseconds for dots/elementwise),
and no log-depth shift networks.  On TPU the (30, 30) tile is VPU-friendly.

Limb shifts (multiply/divide by the radix) are likewise static matmuls
(x @ SHIFT) instead of concatenates, for the same compile-time reason.

Overflow soundness is machine-checked, not hand-audited: the
``limb-bounds`` lodelint rule (tools/lint/rules_bounds.py) abstract-
interprets this module and proves, per assignment, that no uint32
expression can reach 2^32 and no implicit dtype promotion sneaks in.
Entry points carry machine-readable ``@bounds:`` contracts in their
docstrings — grammar and suppression semantics in docs/LINT.md
("lodelint v4").  The headline CIOS bound the rule re-derives on every
run — a column receives at most ``2*NLIMBS*(2^13-1)^2 + carry < 2^32``
— is also recomputed from the actual LIMB_BITS/NLIMBS constants by
tests/test_limb_bounds_audit.py, so a radix change cannot leave a
stale audit behind.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .limbs import (
    LIMB_BITS,
    MASK,
    NLIMBS,
    N0INV,
    NPRIME_LIMBS,
    ONE_MONT,
    P_LIMBS,
    R2_LIMBS,
)

_u32 = jnp.uint32

# Unroll the 30-step CIOS loop into straight-line code (no while loop).
# Measured on TPU v5e (B=256 miller loop): scanned CIOS compiles ~40%
# faster AND runs ~15% faster than unrolled (450ms vs 532ms) — the scan
# body is compiled once and the TPU pipeline keeps it fed; unrolling only
# bloats the HLO.  Default False.
CIOS_UNROLL = False

# Device-constant views of host numpy constants (closed over inside jit).
_P = jnp.asarray(P_LIMBS, dtype=_u32)
_R2 = jnp.asarray(R2_LIMBS, dtype=_u32)
_ONE_M = jnp.asarray(ONE_MONT, dtype=_u32)

# Static limb-axis shift matrices (see module docstring): shifts as dots.
_SHIFT_UP_M = jnp.asarray(np.eye(NLIMBS, k=1, dtype=np.uint32))    # x @ M -> limb k = x[k-1]
_SHIFT_DOWN_M = jnp.asarray(np.eye(NLIMBS, k=-1, dtype=np.uint32))  # x @ M -> limb k = x[k+1]
_E0 = jnp.asarray(np.eye(NLIMBS, dtype=np.uint32)[0])


def zeros(shape=()) -> jnp.ndarray:
    return jnp.zeros((*shape, NLIMBS), dtype=_u32)


def one_mont(shape=()) -> jnp.ndarray:
    return jnp.broadcast_to(_ONE_M, (*shape, NLIMBS))


# ---------------------------------------------------------------------------
# carry / borrow primitives (log-depth, no scans)
# ---------------------------------------------------------------------------


def _dot(x, m):
    """x @ m along the limb axis (static 0/1 uint32 matrix).

    Written as broadcast-multiply + reduce-sum rather than dot_general:
    XLA:CPU codegens integer matmuls slowly (no Eigen path), while the
    elementwise form compiles in milliseconds and fuses; on TPU the
    (..., N, N) tile is trivially vectorized."""
    return (x[..., :, None] * m).sum(axis=-2)


def _shift_up(x):
    """Limb k of result = limb k-1 of x (i.e. multiply by 2^13), zero-fill."""
    return _dot(x, _SHIFT_UP_M)


def _carry_pass(x):
    """One parallel carry pass: limbs shrink toward canonical."""
    return (x & MASK) + _shift_up(x >> LIMB_BITS)


_SHIFT_CACHE: dict = {}


def _shift_lo(x, d: int):
    """shifted[i] = x[i-d], zero-filled below (toward less significant).

    TPU: pad/slice (free sublane/lane moves).  CPU: a cached static
    shift-matrix dot — XLA:CPU's fusion/simplification passes take ~1s of
    compile time PER pad-of-slice op, and the Kogge-Stone carry resolves
    emit several per field op; dots compile in milliseconds there."""
    if _target_platform() == "cpu":
        n = x.shape[-1]
        key = (n, d)
        m = _SHIFT_CACHE.get(key)
        if m is None:
            # cache the NUMPY matrix (a jnp constant created inside one
            # trace must not be reused across traces — tracer leak)
            m = np.eye(n, k=d, dtype=np.uint32)
            _SHIFT_CACHE[key] = m
        return _dot(x, m)
    pad = [(0, 0)] * (x.ndim - 1) + [(d, 0)]
    return jnp.pad(x[..., : x.shape[-1] - d], pad)


def _propagate(g, pr):
    """Branch-free single-bit carry/borrow propagation (any limb width).

    g[j]:  limb j generates (uint32 0/1).   pr[j]: limb j propagates.
    g and pr must be disjoint (a generating limb cannot also propagate).
    Returns (carry_in per limb, total carry-out), where
      carry_in[i] = OR_{j<i} ( g[j] AND pr[j+1..i-1] all set ).

    Kogge-Stone parallel prefix over the carry operator
      (G, P) combine-with-lower (G', P')  =  (G | (P & G'), P & P')
    log2(n) doubling steps of pure elementwise ops on (..., n) tensors —
    linear work per step, no quadratic (n, n) intermediates (an earlier
    prefix-count formulation built (..., n, n) masks; at a stacked-f12
    batch width that materialized ~100 MB per carry resolve and dominated
    kernel runtime).
    """
    n = g.shape[-1]
    G, P = g, pr
    d = 1
    while d < n:
        G = G | (P & _shift_lo(G, d))
        P = P & _shift_lo(P, d)
        d <<= 1
    carry_in = _shift_lo(G, 1)               # carry INTO limb i = G[i-1]
    total = G[..., -1]
    return carry_in, total


def _resolve_single_carries(t):
    """Exact canonicalization for limbs with single-bit carries (any width).

    Precondition: every limb of t is <= 2^14 - 2, so carry-out per limb is
    0 or 1 even with an incoming carry.  Callers stay within bound: add()
    feeds limbs <= 2*MASK = 2^14 - 2; sub() feeds d + P <= 2*MASK;
    _norm_wide / mont_mul feed limbs <= MASK + ~64 after two carry passes.
    """
    g = (t >> LIMB_BITS).astype(_u32)          # t >= 2^13 -> generates
    pr = (t == MASK).astype(_u32)              # t == mask -> propagates
    carry_in, _ = _propagate(g, pr)
    return (t + carry_in) & MASK


def _norm_wide(u):
    """Canonicalize limbs up to 2^32 (mont_mul output): 2 passes + resolve."""
    u = _carry_pass(u)   # limbs <= mask + 2^19
    u = _carry_pass(u)   # limbs <= mask + 61 < 2^14
    return _resolve_single_carries(u)


def _borrow_sub(a, b):
    """(a - b) mod 2^390 on canonical limbs; returns (limbs, borrow_flag).

    borrow_flag (uint32 0/1 shaped (...,)) is 1 iff a < b.
    """
    g = (a < b).astype(_u32)
    pr = (a == b).astype(_u32)
    borrow_in, borrow_out = _propagate(g, pr)
    limbs = (a + _u32(1 << LIMB_BITS) - b - borrow_in) & MASK
    return limbs, borrow_out


def _cond_sub_p(t):
    """Canonicalize t in [0, 2p) -> [0, p) (canonical limbs in)."""
    d, borrow = _borrow_sub(t, jnp.broadcast_to(_P, t.shape))
    return jnp.where((borrow != 0)[..., None], t, d)


# ---------------------------------------------------------------------------
# ring ops
# ---------------------------------------------------------------------------


def _flat_leading(fn):
    """Collapse all leading axes to ONE before running `fn`, restore after.

    The tower stacks products into leading axes ((3,6,3,B,NLIMBS) for an
    f12 mul), and the carry machinery adds two more (..., N, N) — XLA's
    layout/fusion passes are superlinear in tensor rank, and rank-7
    intermediates were measured to dominate compile time.  Every fp entry
    point therefore runs rank<=3 internally."""
    import functools

    @functools.wraps(fn)
    def wrapped(a, b):
        a, b = jnp.broadcast_arrays(a, b)
        lead = a.shape[:-1]
        if len(lead) > 1:
            a2 = a.reshape((-1, a.shape[-1]))
            b2 = b.reshape((-1, b.shape[-1]))
            return fn(a2, b2).reshape((*lead, -1))
        return fn(a, b)

    return wrapped


@_flat_leading
def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a + b mod p on canonical limbs.

    @bounds: a [0, 2^13-1], b [0, 2^13-1] -> [0, 2^13-1]
    """
    return _cond_sub_p(_resolve_single_carries(a + b))


@_flat_leading
def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """a - b mod p on canonical limbs.

    @bounds: a [0, 2^13-1], b [0, 2^13-1] -> [0, 2^13-1]
    """
    d, borrow = _borrow_sub(a, b)
    # Where a < b the limbs represent a-b+2^390; adding p and dropping the
    # top carry (exactly 2^390) yields a-b+p in [0, p).
    dp = _resolve_single_carries(d + _P)
    return jnp.where((borrow != 0)[..., None], dp, d)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    """-a mod p.

    @bounds: a [0, 2^13-1] -> [0, 2^13-1]
    """
    return sub(jnp.zeros_like(a), a)


def dbl(a: jnp.ndarray) -> jnp.ndarray:
    """2a mod p.

    @bounds: a [0, 2^13-1] -> [0, 2^13-1]
    """
    return add(a, a)


def _cios_step(u, a_i, b):
    u = u + a_i[..., None] * b
    m = (u[..., 0] * _u32(N0INV)) & MASK
    u = u + m[..., None] * _P
    carry = u[..., 0] >> LIMB_BITS
    # shift down one limb (drop the now-zero column 0) and add the carry
    # into the new limb 0 — as a dot, not a concatenate (see module note)
    return _dot(u, _SHIFT_DOWN_M) + carry[..., None] * _E0


def mont_mul_cios(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product via the serial CIOS scan (kept as the reference
    implementation / fallback; the default mont_mul is the parallel
    full-product reduction below).

    @bounds: a [0, 2^13-1], b [0, 2^13-1] -> [0, 2^13-1]
    """
    a, b = jnp.broadcast_arrays(a, b)
    if CIOS_UNROLL:
        u = jnp.zeros_like(b)
        for i in range(NLIMBS):
            u = _cios_step(u, a[..., i], b)
    else:
        a_s = jnp.moveaxis(a, -1, 0)
        u, _ = jax.lax.scan(lambda u, ai: (_cios_step(u, ai, b), None),
                            jnp.zeros_like(b), a_s)
    return _cond_sub_p(_norm_wide(u))


# ---------------------------------------------------------------------------
# parallel Montgomery multiplication (no serial limb scan)
#
# The CIOS scan above serializes 30 dependent steps per product; a pairing
# is ~10^5 products, so the whole program becomes a chain of ~10^7 tiny VPU
# ops and runs latency-bound (~1 us/op on v5e) regardless of batch size.
# Here the product and the Montgomery reduction are each ONE wide data-
# parallel expression over (..., 2N)-limb tensors:
#
#     U  = a * b                      (schoolbook convolution, 59 limbs)
#     mu = (U mod R) * N' mod R       (low-half convolution, R = 2^390)
#     T  = U + mu * p                 (T = 0 mod R by construction)
#     out = T / R  in [0, 2p) -> cond_sub
#
# Convolutions are gather+multiply+reduce (no data-dependent control flow);
# carries resolve with the branch-free passes/_propagate machinery.  Every
# intermediate fits uint32: products are <= 8223 * 8191 * 30 < 2^31 and the
# sum U + mu*p <= 2^31 + 8223 (see the per-step bounds in comments).
# ---------------------------------------------------------------------------

_NPRIME = jnp.asarray(NPRIME_LIMBS, dtype=_u32)
_WIDE = 2 * NLIMBS - 1  # 59 limbs in a raw product


def _conv_idx(out_width: int) -> np.ndarray:
    """IDX[i, k] = k - i where valid else NLIMBS (a zero slot): gathers
    shifted copies of a zero-padded multiplicand so that
    sum_i a[i] * b_pad[IDX[i, k]] = (a conv b)[k]."""
    idx = np.full((NLIMBS, out_width), NLIMBS, dtype=np.int32)
    for i in range(NLIMBS):
        for k in range(out_width):
            j = k - i
            if 0 <= j < NLIMBS:
                idx[i, k] = j
    return idx


_IDX_FULL = jnp.asarray(_conv_idx(_WIDE))     # (30, 59)
_IDX_LOW = jnp.asarray(_conv_idx(NLIMBS))     # (30, 30): product mod R

def _conv(a, b, idx):
    """Limb convolution sum_{i+j=k} a_i*b_j via one gather + one reduce.

    Output limb k <= 30 * max(a) * max(b); callers keep that < 2^32.
    """
    b_pad = jnp.concatenate([b, jnp.zeros_like(b[..., :1])], axis=-1)
    shifts = b_pad[..., idx]                  # (..., 30, out_width)
    return (a[..., :, None] * shifts).sum(axis=-2)


def _carry_widen(x, grow: int = 1):
    """One carry pass that widens by `grow` limbs (no truncation)."""
    lo = x & MASK
    hi = x >> LIMB_BITS
    pad_tail = [(0, 0)] * (x.ndim - 1) + [(0, grow)]
    pad_head = [(0, 0)] * (x.ndim - 1) + [(1, grow - 1)]
    return jnp.pad(lo, pad_tail) + jnp.pad(hi, pad_head)


def _carry_trunc(x):
    """One carry pass at fixed width (drops the top carry: mod 2^(13*n))."""
    lo = x & MASK
    hi = x >> LIMB_BITS
    return lo + jnp.pad(hi[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])


# Pallas-fused mont_mul on TPU backends (3.7x the XLA expression form —
# see pallas_fp.py); LODESTAR_TPU_PALLAS=0 opts out.  Decided at trace
# time: CPU (tests, virtual mesh) keeps the XLA paths below.
#
# Platform detection caveat: under the axon TPU plugin,
# jax.default_backend() reports "tpu" even in processes whose
# computations target host (CPU) devices (virtual-mesh dryrun, forced-CPU
# tests) — so those entry points must set LODESTAR_TPU_FP_PLATFORM=cpu
# explicitly (tests/conftest.py, __graft_entry__.dryrun_multichip do).
import os as _os

PALLAS = _os.environ.get("LODESTAR_TPU_PALLAS", "1") != "0"


def _target_platform() -> str:
    override = _os.environ.get("LODESTAR_TPU_FP_PLATFORM")
    if override:
        return override
    try:
        return jax.default_backend()
    # no initializable backend IS the probe's "cpu" answer
    except Exception:  # lodelint: disable=silent-except
        return "cpu"


def _use_pallas() -> bool:
    return PALLAS and _target_platform() == "tpu"


@_flat_leading
def mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a*b*R^{-1} mod p, canonical output.

    @bounds: a [0, 2^13-1], b [0, 2^13-1] -> [0, 2^13-1]

    Backend dispatch (trace-time):
      * tpu  -> Pallas fused kernel (pallas_fp.py; bandwidth-optimal)
      * else -> serial CIOS scan (mont_mul_cios): XLA:CPU compiles the
        small scan body in seconds, while the parallel pad/concat form
        below takes *hours* in its fusion/simplification passes (the
        dryrun's sharded program never finished compiling)
      * the parallel XLA form stays available as mont_mul_parallel for
        ablation and as the reference the Pallas kernel is tested against
    """
    if _use_pallas():
        from . import pallas_fp

        return pallas_fp.mont_mul(a, b)
    if _target_platform() != "tpu":
        # CPU: CIOS scan by default; LODESTAR_TPU_CPU_PARALLEL_FP=1 selects
        # the scan-free conv form (fewer, flatter XLA:CPU computations —
        # compile-time experiment knob, safe either way: both forms are
        # differential-tested)
        if _os.environ.get("LODESTAR_TPU_CPU_PARALLEL_FP") == "1":
            return mont_mul_parallel(a, b)
        return mont_mul_cios(a, b)
    return mont_mul_parallel(a, b)


@_flat_leading
def mont_mul_parallel(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """The parallel (no serial limb scan) XLA expression form.

    @bounds: a [0, 2^13-1], b [0, 2^13-1] -> [0, 2^13-1]
    """
    # U = a*b: 59 limbs <= 30*8191^2 < 2^31
    u = _conv(a, b, _IDX_FULL)
    # two widening passes: limbs <= 8191 + 31 (=: B1), width 61
    u = _carry_widen(_carry_widen(u))
    # mu = (U mod R) * N' mod R: low conv of U's low half; since dropping
    # limbs >= 30 only removes multiples of R, the result is exact mod R.
    # products <= B1 * 8191 * 30 < 2^31
    mu = _conv(u[..., :NLIMBS], jnp.broadcast_to(_NPRIME, a.shape), _IDX_LOW)
    mu = _carry_trunc(_carry_trunc(mu))       # limbs <= B1, exact mod R
    # T = U + mu*p: mu*p limbs <= B1 * 8191 * 30 < 2^31 - B1
    mp = _conv(mu, jnp.broadcast_to(_P, a.shape), _IDX_FULL)
    pad = [(0, 0)] * (u.ndim - 1) + [(0, 2)]
    t = u + jnp.pad(mp, pad)                  # width 61
    # exact canonicalization: T = 0 mod R, so limbs 0..29 cancel to zero
    # and T/R is literally limbs 30..59 of the canonical form
    t = _carry_widen(_carry_widen(t))         # width 63, limbs <= 2^14 - 2
    t = _resolve_single_carries(t)
    res = t[..., NLIMBS : 2 * NLIMBS]         # T/R < 2p (Montgomery bound)
    return _cond_sub_p(res)


def mont_sqr(a: jnp.ndarray) -> jnp.ndarray:
    """a^2 in Montgomery form.

    @bounds: a [0, 2^13-1] -> [0, 2^13-1]
    """
    return mont_mul(a, a)


def to_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Plain limbs (value < p) -> Montgomery form.

    @bounds: a [0, 2^13-1] -> [0, 2^13-1]
    """
    return mont_mul(a, jnp.broadcast_to(_R2, a.shape))


def from_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery form -> plain canonical limbs.

    @bounds: a [0, 2^13-1] -> [0, 2^13-1]
    """
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(a, one)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical limbs -> bool (...,)."""
    return jnp.all(a == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """where(cond, a, b) with cond shaped (...,) against (..., NLIMBS)."""
    return jnp.where(cond[..., None], a, b)


# ---------------------------------------------------------------------------
# fixed-exponent powers (exponent is a compile-time python int)
# ---------------------------------------------------------------------------


def _exp_bits(e: int) -> np.ndarray:
    """MSB-first bit array of a positive python int."""
    return np.array([int(c) for c in bin(e)[2:]], dtype=np.uint32)


def mont_pow_fixed(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e in Montgomery form (a Montgomery in, result Montgomery out).

    Fixed-window (4-bit) exponentiation: precompute a^0..a^15 once, then
    scan the exponent's nibbles MSB-first with 4 squarings + ONE
    table-lookup multiply per window.  Halves the multiply count of the
    bitwise square-and-multiply ladder (the Fermat inversions a^(p-2) are
    ~15% of the whole verification program's op count).

    @bounds: a [0, 2^13-1], e host -> [0, 2^13-1]
    """
    if e == 0:
        return jnp.broadcast_to(_ONE_M, a.shape)
    if e < 16:
        # tiny exponents: plain ladder
        acc = jnp.broadcast_to(_ONE_M, a.shape)
        for bit in _exp_bits(e):
            acc = mont_mul(acc, acc)
            if bit:
                acc = mont_mul(acc, a)
        return acc

    # nibble digits, MSB-first, padded to whole windows
    ndigits = (e.bit_length() + 3) // 4
    digits = np.array(
        [(e >> (4 * (ndigits - 1 - i))) & 0xF for i in range(ndigits)],
        dtype=np.int32,
    )

    # table a^0 .. a^15 stacked on a new leading axis (one-time 14 muls)
    pows = [jnp.broadcast_to(_ONE_M, a.shape), a]
    sq = mont_mul(a, a)
    pows.append(sq)
    for _ in range(13):
        pows.append(mont_mul(pows[-1], a))
    table = jnp.stack(pows)  # (16, ..., NLIMBS)

    def body(acc, d):
        for _ in range(4):
            acc = mont_mul(acc, acc)
        acc = mont_mul(acc, table[d])
        return acc, None

    acc = jnp.broadcast_to(_ONE_M, a.shape)
    acc, _ = jax.lax.scan(body, acc, jnp.asarray(digits))
    return acc


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Multiplicative inverse via Fermat (a^(p-2)); a in Montgomery form.

    inv(0) returns 0 (callers guard; matches constant-shape control flow).

    @bounds: a [0, 2^13-1] -> [0, 2^13-1]
    """
    from lodestar_tpu.crypto.bls.fields import P

    return mont_pow_fixed(a, P - 2)


# ---------------------------------------------------------------------------
# trace-once caching (see opcache.py): every public mutating op's jaxpr is
# built once per argument shape and replayed — call sites stop paying the
# pallas-kernel / scan-body re-trace tax that dominated cold program-build
# time on 1-CPU hosts.
# ---------------------------------------------------------------------------

from .opcache import cached as _cached

add = _cached(add)
sub = _cached(sub)
mont_mul = _cached(mont_mul)
mont_mul_cios = _cached(mont_mul_cios)
mont_mul_parallel = _cached(mont_mul_parallel)
mont_pow_fixed = _cached(mont_pow_fixed, static_argnums=(1,))


# host<->device element helpers -------------------------------------------------


def encode_int(x: int) -> np.ndarray:
    """Host: python int mod p -> canonical Montgomery limbs (numpy)."""
    from lodestar_tpu.crypto.bls.fields import P
    from .limbs import int_to_limbs, to_mont_int

    return int_to_limbs(to_mont_int(x % P))


def decode(limbs) -> int:
    """Host: Montgomery limb array -> python int in [0, p)."""
    from .limbs import from_mont_int, limbs_to_int

    return from_mont_int(limbs_to_int(np.asarray(limbs)))
