"""Fp arithmetic over limb tensors — the innermost TPU kernel layer.

Every value is a uint32 tensor ``(..., NLIMBS)`` of radix-2^13 limbs in
Montgomery form, canonical (each limb < 2^13, value < p).  Ops broadcast over
leading axes, so a batch of field elements is just a leading dimension — the
TPU-native analogue of the reference's per-core BLS worker data parallelism
(packages/beacon-node/src/chain/bls/multithread/index.ts:98).

Design note on carry handling: carry/borrow propagation is NOT a sequential
scan here.  A pairing is ~10^5 field ops; giving each one a ``lax.scan``
produces thousands of XLA while-subcomputations and intractable compile
times.  Instead, carries resolve with a branch-free BROADCAST-COMPARE
formulation: carry_in[i] = OR_{j<i} (generate[j] AND limbs j+1..i-1 all
propagate), where the "all propagate" predicate is a prefix-count equality
computed with ONE tiny static matmul (cumulative sum by lower-triangular
matrix).  This yields ~10 elementwise HLO ops on a (..., N, N) tile per
carry resolution — no concatenate/pad chains, which XLA:CPU's fusion and
algebraic-simplifier passes handle pathologically slowly (measured ~1 s of
compile time PER shift-by-concat op, vs milliseconds for dots/elementwise),
and no log-depth shift networks.  On TPU the (30, 30) tile is VPU-friendly.

Limb shifts (multiply/divide by the radix) are likewise static matmuls
(x @ SHIFT) instead of concatenates, for the same compile-time reason.

Overflow audit for mont_mul (uint32, b = 2^13-1 = 8191):
  * product a_i*b_j <= 8191^2 = 67,092,481 < 2^27
  * a column receives at most NLIMBS products from a*b and NLIMBS from m*p:
    2*30*8191^2 = 4,025,548,860, plus one shift carry < 2^20
    -> max 4,026,597,309 < 2^32 - 1.   No wraparound.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .limbs import LIMB_BITS, MASK, NLIMBS, N0INV, ONE_MONT, P_LIMBS, R2_LIMBS

_u32 = jnp.uint32

# Unroll the 30-step CIOS loop into straight-line code (no while loop).
# Measured on TPU v5e (B=256 miller loop): scanned CIOS compiles ~40%
# faster AND runs ~15% faster than unrolled (450ms vs 532ms) — the scan
# body is compiled once and the TPU pipeline keeps it fed; unrolling only
# bloats the HLO.  Default False.
CIOS_UNROLL = False

# Device-constant views of host numpy constants (closed over inside jit).
_P = jnp.asarray(P_LIMBS, dtype=_u32)
_R2 = jnp.asarray(R2_LIMBS, dtype=_u32)
_ONE_M = jnp.asarray(ONE_MONT, dtype=_u32)

# Static limb-axis matrices (see module docstring): shifts and prefix-sums
# as dots, pairwise masks for broadcast-compare carry resolution.
_SHIFT_UP_M = jnp.asarray(np.eye(NLIMBS, k=1, dtype=np.uint32))    # x @ M -> limb k = x[k-1]
_SHIFT_DOWN_M = jnp.asarray(np.eye(NLIMBS, k=-1, dtype=np.uint32))  # x @ M -> limb k = x[k+1]
_CUMSUM_INCL_M = jnp.asarray(np.triu(np.ones((NLIMBS, NLIMBS), dtype=np.uint32)))  # x @ M -> prefix sums
# pairwise_lt[j, i] = 1 iff j < i  (j = source limb, i = destination limb)
_PAIR_LT = jnp.asarray(np.tril(np.ones((NLIMBS, NLIMBS), dtype=np.uint32), k=-1).T)
_E0 = jnp.asarray(np.eye(NLIMBS, dtype=np.uint32)[0])


def zeros(shape=()) -> jnp.ndarray:
    return jnp.zeros((*shape, NLIMBS), dtype=_u32)


def one_mont(shape=()) -> jnp.ndarray:
    return jnp.broadcast_to(_ONE_M, (*shape, NLIMBS))


# ---------------------------------------------------------------------------
# carry / borrow primitives (log-depth, no scans)
# ---------------------------------------------------------------------------


def _dot(x, m):
    """x @ m along the limb axis (static 0/1 uint32 matrix).

    Written as broadcast-multiply + reduce-sum rather than dot_general:
    XLA:CPU codegens integer matmuls slowly (no Eigen path), while the
    elementwise form compiles in milliseconds and fuses; on TPU the
    (..., N, N) tile is trivially vectorized."""
    return (x[..., :, None] * m).sum(axis=-2)


def _shift_up(x):
    """Limb k of result = limb k-1 of x (i.e. multiply by 2^13), zero-fill."""
    return _dot(x, _SHIFT_UP_M)


def _carry_pass(x):
    """One parallel carry pass: limbs shrink toward canonical."""
    return (x & MASK) + _shift_up(x >> LIMB_BITS)


def _propagate(g, pr):
    """Branch-free single-bit carry/borrow propagation.

    g[j]:  limb j generates (uint32 0/1).   pr[j]: limb j propagates.
    g and pr must be disjoint (a generating limb cannot also propagate).
    Returns (carry_in per limb, total carry-out), where
      carry_in[i] = OR_{j<i} ( g[j] AND pr[j+1..i-1] all set )
    computed via prefix-counts of non-propagating limbs: the span j+1..i-1
    is all-propagate iff Z[i-1] == Z[j] with Z = inclusive cumsum of ~pr.
    """
    np_ = pr ^ _u32(1)
    Z = _dot(np_, _CUMSUM_INCL_M)            # Z[k] = #non-propagating in 0..k
    Zi1 = _shift_up(Z)                       # Z[i-1], 0 for i = 0
    # A[..., j, i] = g[j] & (Z[i-1] == Z[j]) & (j < i)
    eq = (Zi1[..., None, :] == Z[..., :, None]).astype(_u32)
    A = g[..., :, None] * eq * _PAIR_LT
    carry_in = A.max(axis=-2)
    # carry out of the top limb: g[j] with pr[j+1..N-1] all set
    total = (g * (Z[..., -1:] == Z).astype(_u32)).max(axis=-1)
    return carry_in, total


def _resolve_single_carries(t):
    """Exact canonicalization for limbs with single-bit carries.

    Precondition: every limb of t is <= 2^14 - 2, so carry-out per limb is
    0 or 1 even with an incoming carry.  Callers stay within bound: add()
    feeds limbs <= 2*MASK = 2^14 - 2; sub() feeds d + P <= 2*MASK;
    _norm_wide feeds limbs <= MASK + 61 after its two carry passes.
    """
    g = (t >> LIMB_BITS).astype(_u32)          # t >= 2^13 -> generates
    pr = (t == MASK).astype(_u32)              # t == mask -> propagates
    carry_in, _ = _propagate(g, pr)
    return (t + carry_in) & MASK


def _norm_wide(u):
    """Canonicalize limbs up to 2^32 (mont_mul output): 2 passes + resolve."""
    u = _carry_pass(u)   # limbs <= mask + 2^19
    u = _carry_pass(u)   # limbs <= mask + 61 < 2^14
    return _resolve_single_carries(u)


def _borrow_sub(a, b):
    """(a - b) mod 2^390 on canonical limbs; returns (limbs, borrow_flag).

    borrow_flag (uint32 0/1 shaped (...,)) is 1 iff a < b.
    """
    g = (a < b).astype(_u32)
    pr = (a == b).astype(_u32)
    borrow_in, borrow_out = _propagate(g, pr)
    limbs = (a + _u32(1 << LIMB_BITS) - b - borrow_in) & MASK
    return limbs, borrow_out


def _cond_sub_p(t):
    """Canonicalize t in [0, 2p) -> [0, p) (canonical limbs in)."""
    d, borrow = _borrow_sub(t, jnp.broadcast_to(_P, t.shape))
    return jnp.where((borrow != 0)[..., None], t, d)


# ---------------------------------------------------------------------------
# ring ops
# ---------------------------------------------------------------------------


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cond_sub_p(_resolve_single_carries(a + b))


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a, b = jnp.broadcast_arrays(a, b)
    d, borrow = _borrow_sub(a, b)
    # Where a < b the limbs represent a-b+2^390; adding p and dropping the
    # top carry (exactly 2^390) yields a-b+p in [0, p).
    dp = _resolve_single_carries(d + _P)
    return jnp.where((borrow != 0)[..., None], dp, d)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


def dbl(a: jnp.ndarray) -> jnp.ndarray:
    return add(a, a)


def _cios_step(u, a_i, b):
    u = u + a_i[..., None] * b
    m = (u[..., 0] * _u32(N0INV)) & MASK
    u = u + m[..., None] * _P
    carry = u[..., 0] >> LIMB_BITS
    # shift down one limb (drop the now-zero column 0) and add the carry
    # into the new limb 0 — as a dot, not a concatenate (see module note)
    return _dot(u, _SHIFT_DOWN_M) + carry[..., None] * _E0


def mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a*b*R^{-1} mod p, canonical output (CIOS)."""
    a, b = jnp.broadcast_arrays(a, b)
    if CIOS_UNROLL:
        u = jnp.zeros_like(b)
        for i in range(NLIMBS):
            u = _cios_step(u, a[..., i], b)
    else:
        a_s = jnp.moveaxis(a, -1, 0)
        u, _ = jax.lax.scan(lambda u, ai: (_cios_step(u, ai, b), None),
                            jnp.zeros_like(b), a_s)
    return _cond_sub_p(_norm_wide(u))


def mont_sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mont_mul(a, a)


def to_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Plain limbs (value < p) -> Montgomery form."""
    return mont_mul(a, jnp.broadcast_to(_R2, a.shape))


def from_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery form -> plain canonical limbs."""
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(a, one)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical limbs -> bool (...,)."""
    return jnp.all(a == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """where(cond, a, b) with cond shaped (...,) against (..., NLIMBS)."""
    return jnp.where(cond[..., None], a, b)


# ---------------------------------------------------------------------------
# fixed-exponent powers (exponent is a compile-time python int)
# ---------------------------------------------------------------------------


def _exp_bits(e: int) -> np.ndarray:
    """MSB-first bit array of a positive python int."""
    return np.array([int(c) for c in bin(e)[2:]], dtype=np.uint32)


def mont_pow_fixed(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e in Montgomery form (a Montgomery in, result Montgomery out).

    One lax.scan over the exponent bits; always-multiply-then-select keeps
    the body branch-free.
    """
    if e == 0:
        return jnp.broadcast_to(_ONE_M, a.shape)
    bits = jnp.asarray(_exp_bits(e))

    def body(acc, bit):
        acc = mont_mul(acc, acc)
        acc = select(bit != 0, mont_mul(acc, a), acc)
        return acc, None

    acc = jnp.broadcast_to(_ONE_M, a.shape)
    acc, _ = jax.lax.scan(body, acc, bits)
    return acc


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Multiplicative inverse via Fermat (a^(p-2)); a in Montgomery form.

    inv(0) returns 0 (callers guard; matches constant-shape control flow).
    """
    from lodestar_tpu.crypto.bls.fields import P

    return mont_pow_fixed(a, P - 2)


# host<->device element helpers -------------------------------------------------


def encode_int(x: int) -> np.ndarray:
    """Host: python int mod p -> canonical Montgomery limbs (numpy)."""
    from lodestar_tpu.crypto.bls.fields import P
    from .limbs import int_to_limbs, to_mont_int

    return int_to_limbs(to_mont_int(x % P))


def decode(limbs) -> int:
    """Host: Montgomery limb array -> python int in [0, p)."""
    from .limbs import from_mont_int, limbs_to_int

    return from_mont_int(limbs_to_int(np.asarray(limbs)))
