"""Fp arithmetic over limb tensors — the innermost TPU kernel layer.

Every value is a uint32 tensor ``(..., NLIMBS)`` of radix-2^13 limbs in
Montgomery form, canonical (each limb < 2^13, value < p).  Ops broadcast over
leading axes, so a batch of field elements is just a leading dimension — the
TPU-native analogue of the reference's per-core BLS worker data parallelism
(packages/beacon-node/src/chain/bls/multithread/index.ts:98).

Sequential structure (carry chains, CIOS) is expressed as ``lax.scan`` over
the limb axis so XLA traces a single step regardless of batch size.

Overflow audit for mont_mul (uint32, b = 2^13-1 = 8191):
  * product a_i*b_j <= 8191^2 = 67,092,481 < 2^27
  * a column receives at most NLIMBS products from a*b and NLIMBS from m*p:
    2*30*8191^2 = 4,025,548,860, plus one shift carry < 2^20
    -> max 4,026,597,309 < 2^32 - 1.   No wraparound.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .limbs import LIMB_BITS, MASK, NLIMBS, N0INV, ONE_MONT, P_LIMBS, R2_LIMBS

_u32 = jnp.uint32

# Device-constant views of host numpy constants (closed over inside jit).
_P = jnp.asarray(P_LIMBS, dtype=_u32)
_R2 = jnp.asarray(R2_LIMBS, dtype=_u32)
_ONE_M = jnp.asarray(ONE_MONT, dtype=_u32)


def zeros(shape=()) -> jnp.ndarray:
    return jnp.zeros((*shape, NLIMBS), dtype=_u32)


def one_mont(shape=()) -> jnp.ndarray:
    return jnp.broadcast_to(_ONE_M, (*shape, NLIMBS))


# ---------------------------------------------------------------------------
# carry / borrow primitives
# ---------------------------------------------------------------------------


def _carry_once(x: jnp.ndarray) -> jnp.ndarray:
    """One parallel carry pass; exact iff each limb < 2^14 and value < 2^390.

    For limbs <= 2*MASK (a single addition of canonical values) the result is
    fully canonical: (2*MASK & MASK) = MASK-1, +carry(<=1) <= MASK.
    """
    low = x & MASK
    carry = x >> LIMB_BITS
    shifted = jnp.concatenate(
        [jnp.zeros_like(carry[..., :1]), carry[..., :-1]], axis=-1
    )
    return low + shifted


def _carry_scan(x: jnp.ndarray) -> jnp.ndarray:
    """Full normalization for limbs up to 2^32: sequential carry scan.

    Drops the final carry (caller guarantees value < 2^390).
    """
    xs = jnp.moveaxis(x, -1, 0)

    def body(carry, xi):
        cur = xi + carry
        return cur >> LIMB_BITS, cur & MASK

    _, ys = jax.lax.scan(body, jnp.zeros_like(xs[0]), xs)
    return jnp.moveaxis(ys, 0, -1)


def _borrow_sub(a: jnp.ndarray, b: jnp.ndarray):
    """(a - b) mod 2^390 with canonical inputs; returns (limbs, borrow_flag).

    borrow_flag (uint32 0/1) is 1 iff a < b.
    """
    a_s = jnp.moveaxis(a, -1, 0)
    b_s = jnp.moveaxis(jnp.broadcast_to(b, a.shape), -1, 0)

    def body(borrow, ab):
        ai, bi = ab
        t = ai + _u32(1 << LIMB_BITS) - bi - borrow
        return _u32(1) - (t >> LIMB_BITS), t & MASK

    borrow, ys = jax.lax.scan(body, jnp.zeros_like(a_s[0]), (a_s, b_s))
    return jnp.moveaxis(ys, 0, -1), borrow


def _cond_sub_p(t: jnp.ndarray) -> jnp.ndarray:
    """Canonicalize t in [0, 2p) -> [0, p)."""
    d, borrow = _borrow_sub(t, _P)
    return jnp.where((borrow != 0)[..., None], t, d)


# ---------------------------------------------------------------------------
# ring ops
# ---------------------------------------------------------------------------


def add(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return _cond_sub_p(_carry_once(a + b))


def sub(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    a, b = jnp.broadcast_arrays(a, b)
    d, borrow = _borrow_sub(a, b)
    # If a < b the limbs represent a-b+2^390; adding p and dropping the top
    # carry (which is exactly 2^390 here) yields a-b+p in [0, p).
    dp = _carry_once(d + _P)
    return jnp.where((borrow != 0)[..., None], dp, d)


def neg(a: jnp.ndarray) -> jnp.ndarray:
    return sub(jnp.zeros_like(a), a)


def dbl(a: jnp.ndarray) -> jnp.ndarray:
    return add(a, a)


def mont_mul(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Montgomery product a*b*R^{-1} mod p, canonical output.

    CIOS over a's limbs as a lax.scan: one traced step regardless of batch.
    """
    a, b = jnp.broadcast_arrays(a, b)
    a_s = jnp.moveaxis(a, -1, 0)  # (NLIMBS, ...batch)

    def body(u, a_i):
        u = u + a_i[..., None] * b
        m = (u[..., 0] * _u32(N0INV)) & MASK
        u = u + m[..., None] * _P
        carry = u[..., 0] >> LIMB_BITS
        head = (u[..., 1] + carry)[..., None]
        u = jnp.concatenate([head, u[..., 2:], jnp.zeros_like(u[..., :1])], axis=-1)
        return u, None

    u, _ = jax.lax.scan(body, jnp.zeros_like(b), a_s)
    return _cond_sub_p(_carry_scan(u))


def mont_sqr(a: jnp.ndarray) -> jnp.ndarray:
    return mont_mul(a, a)


def to_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Plain limbs (value < p) -> Montgomery form."""
    return mont_mul(a, _R2)


def from_mont(a: jnp.ndarray) -> jnp.ndarray:
    """Montgomery form -> plain canonical limbs."""
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(a, one)


def is_zero(a: jnp.ndarray) -> jnp.ndarray:
    """Canonical limbs -> bool (...,)."""
    return jnp.all(a == 0, axis=-1)


def eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def select(cond: jnp.ndarray, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """where(cond, a, b) with cond shaped (...,) against (..., NLIMBS)."""
    return jnp.where(cond[..., None], a, b)


# ---------------------------------------------------------------------------
# fixed-exponent powers (exponent is a compile-time python int)
# ---------------------------------------------------------------------------


def _exp_bits(e: int) -> np.ndarray:
    """MSB-first bit array of a positive python int."""
    bits = bin(e)[2:]
    return np.frombuffer(bits.encode(), dtype=np.uint8).astype(np.uint32) - ord("0")


def mont_pow_fixed(a: jnp.ndarray, e: int) -> jnp.ndarray:
    """a^e in Montgomery form (a Montgomery in, result Montgomery out)."""
    if e == 0:
        return jnp.broadcast_to(_ONE_M, a.shape)
    bits = jnp.asarray(_exp_bits(e))

    def body(acc, bit):
        acc = mont_mul(acc, acc)
        acc = select(bit != 0, mont_mul(acc, a), acc)
        return acc, None

    acc = jnp.broadcast_to(_ONE_M, a.shape)
    acc, _ = jax.lax.scan(body, acc, bits)
    return acc


def inv(a: jnp.ndarray) -> jnp.ndarray:
    """Multiplicative inverse via Fermat (a^(p-2)); a in Montgomery form.

    inv(0) returns 0 (callers guard; matches constant-shape control flow).
    """
    from lodestar_tpu.crypto.bls.fields import P

    return mont_pow_fixed(a, P - 2)


# host<->device element helpers -------------------------------------------------


def encode_int(x: int) -> np.ndarray:
    """Host: python int mod p -> canonical Montgomery limbs (numpy)."""
    from lodestar_tpu.crypto.bls.fields import P
    from .limbs import int_to_limbs, to_mont_int

    return int_to_limbs(to_mont_int(x % P))


def decode(limbs) -> int:
    """Host: Montgomery limb array -> python int in [0, p)."""
    from .limbs import from_mont_int, limbs_to_int

    return from_mont_int(limbs_to_int(np.asarray(limbs)))
