"""TPU-native compute kernels (JAX/XLA/Pallas) for the lodestar-tpu framework.

This package is the device-side counterpart of the pure-Python oracle in
``lodestar_tpu.crypto``: the hot math (BLS12-381 pairings for signature
verification — the role blst plays in the reference client, consumed at
packages/beacon-node/src/chain/bls/maybeBatch.ts:17) runs here as batched,
jit-compiled JAX programs designed for the TPU's VPU/MXU and ICI collectives.
"""
