"""Ops/debug tooling (reference: packages/flare — cli.ts +
cmds/selfSlash{Attester,Proposer}.ts).

Crafts provably-slashable messages for OWNED keys (devnet testing of the
slashing pipeline): a double-vote attester slashing or a double-proposal
proposer slashing, signed with the real domains so the beacon node's
pool validation accepts them.
"""
from __future__ import annotations

from typing import List, Tuple

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
)
from lodestar_tpu.state_transition.util.domain import (
    compute_domain,
    compute_signing_root,
)
from lodestar_tpu.types import ssz


def _fork_version(cfg, epoch: int) -> bytes:
    from lodestar_tpu.config import ForkConfig

    return ForkConfig(cfg).fork_version_at_epoch(epoch)


def make_self_attester_slashing(
    cfg,
    genesis_validators_root: bytes,
    sk: "bls.SecretKey",
    validator_index: int,
    target_epoch: int,
) -> "ssz.phase0.AttesterSlashing":
    """Two attestations, same target epoch, different beacon roots — a
    DOUBLE VOTE (selfSlashAttester.ts)."""
    domain = compute_domain(
        DOMAIN_BEACON_ATTESTER,
        _fork_version(cfg, target_epoch),
        genesis_validators_root,
    )

    def make(att_root: bytes) -> "ssz.phase0.IndexedAttestation":
        data = ssz.phase0.AttestationData(
            slot=target_epoch * _p.SLOTS_PER_EPOCH,
            index=0,
            beacon_block_root=att_root,
            source=ssz.phase0.Checkpoint(epoch=max(0, target_epoch - 1), root=b"\x00" * 32),
            target=ssz.phase0.Checkpoint(epoch=target_epoch, root=att_root),
        )
        root = compute_signing_root(ssz.phase0.AttestationData, data, domain)
        return ssz.phase0.IndexedAttestation(
            attesting_indices=[validator_index],
            data=data,
            signature=sk.sign(root).to_bytes(),
        )

    return ssz.phase0.AttesterSlashing(
        attestation_1=make(b"\x01" * 32), attestation_2=make(b"\x02" * 32)
    )


def make_self_proposer_slashing(
    cfg,
    genesis_validators_root: bytes,
    sk: "bls.SecretKey",
    validator_index: int,
    slot: int,
) -> "ssz.phase0.ProposerSlashing":
    """Two signed headers at the same slot (selfSlashProposer.ts)."""
    epoch = slot // _p.SLOTS_PER_EPOCH
    domain = compute_domain(
        DOMAIN_BEACON_PROPOSER, _fork_version(cfg, epoch), genesis_validators_root
    )

    def make(body_root: bytes) -> "ssz.phase0.SignedBeaconBlockHeader":
        hdr = ssz.phase0.BeaconBlockHeader(
            slot=slot,
            proposer_index=validator_index,
            parent_root=b"\x00" * 32,
            state_root=b"\x00" * 32,
            body_root=body_root,
        )
        root = compute_signing_root(ssz.phase0.BeaconBlockHeader, hdr, domain)
        return ssz.phase0.SignedBeaconBlockHeader(
            message=hdr, signature=sk.sign(root).to_bytes()
        )

    return ssz.phase0.ProposerSlashing(
        signed_header_1=make(b"\x0a" * 32), signed_header_2=make(b"\x0b" * 32)
    )
