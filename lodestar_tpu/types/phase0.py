"""Phase0 SSZ container types (reference: packages/types/src/phase0/sszTypes.ts).

Field order is consensus-critical: it must match the consensus-specs phase0
definitions exactly (validated by the interop genesis-state root KAT in
tests/test_state_kats.py).  Vector lengths come from the active preset, like
the reference's compile-time preset switch.
"""
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DEPOSIT_CONTRACT_TREE_DEPTH,
    JUSTIFICATION_BITS_LENGTH,
)
from lodestar_tpu.ssz.core import (
    Bitlist,
    Bitvector,
    ByteList,
    Bytes4,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    Vector,
    boolean,
    uint64,
)

# aliases mirroring primitiveSsz
Slot = uint64
Epoch = uint64
CommitteeIndex = uint64
ValidatorIndex = uint64
Gwei = uint64
Root = Bytes32
Version = Bytes4
DomainType = Bytes4
ForkDigest = Bytes4
BLSPubkey = Bytes48
BLSSignature = Bytes96
Domain = Bytes32

EpochList = List[uint64, _p.VALIDATOR_REGISTRY_LIMIT]
CommitteeIndices = List[ValidatorIndex, _p.MAX_VALIDATORS_PER_COMMITTEE]
CommitteeBits = Bitlist[_p.MAX_VALIDATORS_PER_COMMITTEE]


class Fork(Container):
    previous_version: Version
    current_version: Version
    epoch: Epoch


class ForkData(Container):
    current_version: Version
    genesis_validators_root: Root


class Checkpoint(Container):
    epoch: Epoch
    root: Root


class Validator(Container):
    # frozen: registry records are immutable values mutated via .replace()
    # so state copies share them and their roots cache per object
    # (the rebuild's analogue of the reference's tree-view structural
    # sharing, state-transition/src/cache/stateCache.ts:30)
    _frozen_ = True
    pubkey: BLSPubkey
    withdrawal_credentials: Bytes32
    effective_balance: Gwei
    slashed: boolean
    activation_eligibility_epoch: Epoch
    activation_epoch: Epoch
    exit_epoch: Epoch
    withdrawable_epoch: Epoch


class AttestationData(Container):
    slot: Slot
    index: CommitteeIndex
    beacon_block_root: Root
    source: Checkpoint
    target: Checkpoint


class IndexedAttestation(Container):
    attesting_indices: CommitteeIndices
    data: AttestationData
    signature: BLSSignature


class PendingAttestation(Container):
    aggregation_bits: CommitteeBits
    data: AttestationData
    inclusion_delay: Slot
    proposer_index: ValidatorIndex


class Eth1Data(Container):
    deposit_root: Root
    deposit_count: uint64
    block_hash: Bytes32


Eth1DataVotes = List[
    Eth1Data, _p.EPOCHS_PER_ETH1_VOTING_PERIOD * _p.SLOTS_PER_EPOCH
]


class HistoricalBatch(Container):
    block_roots: Vector[Root, _p.SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, _p.SLOTS_PER_HISTORICAL_ROOT]


class DepositMessage(Container):
    pubkey: BLSPubkey
    withdrawal_credentials: Bytes32
    amount: Gwei


class DepositData(Container):
    pubkey: BLSPubkey
    withdrawal_credentials: Bytes32
    amount: Gwei
    signature: BLSSignature


# DepositDataRootList: the deposit contract's incremental merkle list
DepositDataRootList = List[Root, 2**DEPOSIT_CONTRACT_TREE_DEPTH]


class DepositEvent(Container):
    deposit_data: DepositData
    block_number: uint64
    index: uint64


class BeaconBlockHeader(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body_root: Root


class SignedBeaconBlockHeader(Container):
    message: BeaconBlockHeader
    signature: BLSSignature


class SigningData(Container):
    object_root: Root
    domain: Domain


class Attestation(Container):
    aggregation_bits: CommitteeBits
    data: AttestationData
    signature: BLSSignature


class AggregateAndProof(Container):
    aggregator_index: ValidatorIndex
    aggregate: Attestation
    selection_proof: BLSSignature


class SignedAggregateAndProof(Container):
    message: AggregateAndProof
    signature: BLSSignature


class AttesterSlashing(Container):
    attestation_1: IndexedAttestation
    attestation_2: IndexedAttestation


class ProposerSlashing(Container):
    signed_header_1: SignedBeaconBlockHeader
    signed_header_2: SignedBeaconBlockHeader


class Deposit(Container):
    proof: Vector[Bytes32, DEPOSIT_CONTRACT_TREE_DEPTH + 1]
    data: DepositData


class VoluntaryExit(Container):
    epoch: Epoch
    validator_index: ValidatorIndex


class SignedVoluntaryExit(Container):
    message: VoluntaryExit
    signature: BLSSignature


class BeaconBlockBody(Container):
    randao_reveal: BLSSignature
    eth1_data: Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[ProposerSlashing, _p.MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[AttesterSlashing, _p.MAX_ATTESTER_SLASHINGS]
    attestations: List[Attestation, _p.MAX_ATTESTATIONS]
    deposits: List[Deposit, _p.MAX_DEPOSITS]
    voluntary_exits: List[SignedVoluntaryExit, _p.MAX_VOLUNTARY_EXITS]


class BeaconBlock(Container):
    slot: Slot
    proposer_index: ValidatorIndex
    parent_root: Root
    state_root: Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: Root
    slot: Slot
    fork: Fork
    latest_block_header: BeaconBlockHeader
    block_roots: Vector[Root, _p.SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[Root, _p.SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[Root, _p.HISTORICAL_ROOTS_LIMIT]
    eth1_data: Eth1Data
    eth1_data_votes: Eth1DataVotes
    eth1_deposit_index: uint64
    validators: List[Validator, _p.VALIDATOR_REGISTRY_LIMIT]
    balances: List[Gwei, _p.VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, _p.EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[Gwei, _p.EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_attestations: List[
        PendingAttestation, _p.MAX_ATTESTATIONS * _p.SLOTS_PER_EPOCH
    ]
    current_epoch_attestations: List[
        PendingAttestation, _p.MAX_ATTESTATIONS * _p.SLOTS_PER_EPOCH
    ]
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: Checkpoint
    current_justified_checkpoint: Checkpoint
    finalized_checkpoint: Checkpoint


# p2p wire types -------------------------------------------------------------


class Status(Container):
    fork_digest: ForkDigest
    finalized_root: Root
    finalized_epoch: Epoch
    head_root: Root
    head_slot: Slot


Goodbye = uint64
Ping = uint64


class Metadata(Container):
    seq_number: uint64
    attnets: Bitvector[64]


class Eth1Block(Container):
    timestamp: uint64
    deposit_root: Root
    deposit_count: uint64
