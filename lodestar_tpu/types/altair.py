"""Altair SSZ container types (reference: packages/types/src/altair/sszTypes.ts)."""
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    FINALIZED_ROOT_DEPTH,
    JUSTIFICATION_BITS_LENGTH,
    NEXT_SYNC_COMMITTEE_DEPTH,
    SYNC_COMMITTEE_SUBNET_COUNT,
)
from lodestar_tpu.ssz.core import (
    Bitlist,
    Bitvector,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    Vector,
    uint8,
    uint64,
)
from . import phase0

SYNC_SUBCOMMITTEE_SIZE = _p.SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT

# per-validator participation flag bytes (uint8), the altair replacement for
# phase0's PendingAttestation lists
EpochParticipation = List[uint8, _p.VALIDATOR_REGISTRY_LIMIT]


class SyncCommittee(Container):
    # frozen: committees are replaced wholesale at period boundaries;
    # freezing makes the per-object root cache sound (pubkeys becomes a
    # tuple at construction)
    _frozen_ = True
    pubkeys: Vector[Bytes48, _p.SYNC_COMMITTEE_SIZE]
    aggregate_pubkey: Bytes48


class SyncAggregate(Container):
    sync_committee_bits: Bitvector[_p.SYNC_COMMITTEE_SIZE]
    sync_committee_signature: Bytes96


class SyncCommitteeMessage(Container):
    slot: phase0.Slot
    beacon_block_root: phase0.Root
    validator_index: phase0.ValidatorIndex
    signature: phase0.BLSSignature


class SyncCommitteeContribution(Container):
    slot: phase0.Slot
    beacon_block_root: phase0.Root
    subcommittee_index: uint64
    aggregation_bits: Bitvector[SYNC_SUBCOMMITTEE_SIZE]
    signature: phase0.BLSSignature


class ContributionAndProof(Container):
    aggregator_index: phase0.ValidatorIndex
    contribution: SyncCommitteeContribution
    selection_proof: phase0.BLSSignature


class SignedContributionAndProof(Container):
    message: ContributionAndProof
    signature: phase0.BLSSignature


class SyncAggregatorSelectionData(Container):
    slot: phase0.Slot
    subcommittee_index: uint64


class BeaconBlockBody(Container):
    randao_reveal: phase0.BLSSignature
    eth1_data: phase0.Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[phase0.ProposerSlashing, _p.MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[phase0.AttesterSlashing, _p.MAX_ATTESTER_SLASHINGS]
    attestations: List[phase0.Attestation, _p.MAX_ATTESTATIONS]
    deposits: List[phase0.Deposit, _p.MAX_DEPOSITS]
    voluntary_exits: List[phase0.SignedVoluntaryExit, _p.MAX_VOLUNTARY_EXITS]
    sync_aggregate: SyncAggregate


class BeaconBlock(Container):
    slot: phase0.Slot
    proposer_index: phase0.ValidatorIndex
    parent_root: phase0.Root
    state_root: phase0.Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: phase0.BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: phase0.Root
    slot: phase0.Slot
    fork: phase0.Fork
    latest_block_header: phase0.BeaconBlockHeader
    block_roots: Vector[phase0.Root, _p.SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[phase0.Root, _p.SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[phase0.Root, _p.HISTORICAL_ROOTS_LIMIT]
    eth1_data: phase0.Eth1Data
    eth1_data_votes: phase0.Eth1DataVotes
    eth1_deposit_index: uint64
    validators: List[phase0.Validator, _p.VALIDATOR_REGISTRY_LIMIT]
    balances: List[phase0.Gwei, _p.VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, _p.EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[phase0.Gwei, _p.EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: EpochParticipation
    current_epoch_participation: EpochParticipation
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: phase0.Checkpoint
    current_justified_checkpoint: phase0.Checkpoint
    finalized_checkpoint: phase0.Checkpoint
    inactivity_scores: List[uint64, _p.VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: SyncCommittee
    next_sync_committee: SyncCommittee


# light client ---------------------------------------------------------------


class LightClientBootstrap(Container):
    header: phase0.BeaconBlockHeader
    current_sync_committee: SyncCommittee
    current_sync_committee_branch: Vector[Bytes32, NEXT_SYNC_COMMITTEE_DEPTH]


class LightClientUpdate(Container):
    attested_header: phase0.BeaconBlockHeader
    next_sync_committee: SyncCommittee
    next_sync_committee_branch: Vector[Bytes32, NEXT_SYNC_COMMITTEE_DEPTH]
    finalized_header: phase0.BeaconBlockHeader
    finality_branch: Vector[Bytes32, FINALIZED_ROOT_DEPTH]
    sync_aggregate: SyncAggregate
    signature_slot: phase0.Slot


class LightClientFinalityUpdate(Container):
    attested_header: phase0.BeaconBlockHeader
    finalized_header: phase0.BeaconBlockHeader
    finality_branch: Vector[Bytes32, FINALIZED_ROOT_DEPTH]
    sync_aggregate: SyncAggregate
    signature_slot: phase0.Slot


class LightClientOptimisticUpdate(Container):
    attested_header: phase0.BeaconBlockHeader
    sync_aggregate: SyncAggregate
    signature_slot: phase0.Slot
