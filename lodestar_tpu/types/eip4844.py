"""EIP-4844 (proto-danksharding) SSZ container types
(reference: packages/types/src/eip4844/sszTypes.ts).

This is the pre-Deneb "blobs sidecar" era the reference targets: blocks
carry blob_kzg_commitments, blobs travel alongside the block in a
BlobsSidecar with one aggregated proof, and gossip carries
SignedBeaconBlockAndBlobsSidecar.
"""
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    BYTES_PER_FIELD_ELEMENT,
    JUSTIFICATION_BITS_LENGTH,
)
from lodestar_tpu.ssz.core import (
    Bitvector,
    ByteList,
    ByteVector,
    Bytes32,
    Bytes48,
    Container,
    List,
    Vector,
    uint64,
    uint256,
)
from . import altair, bellatrix, capella, phase0

KZGCommitment = Bytes48
KZGProof = Bytes48
Blob = ByteVector[BYTES_PER_FIELD_ELEMENT * _p.FIELD_ELEMENTS_PER_BLOB]
Blobs = List[Blob, _p.MAX_BLOBS_PER_BLOCK]
BlobKzgCommitments = List[KZGCommitment, _p.MAX_BLOBS_PER_BLOCK]
VersionedHash = Bytes32


class ExecutionPayload(Container):
    parent_hash: Bytes32
    fee_recipient: bellatrix.ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[_p.BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[_p.MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    excess_data_gas: uint256
    block_hash: Bytes32
    transactions: bellatrix.Transactions
    withdrawals: capella.Withdrawals


class ExecutionPayloadHeader(Container):
    parent_hash: Bytes32
    fee_recipient: bellatrix.ExecutionAddress
    state_root: Bytes32
    receipts_root: Bytes32
    logs_bloom: ByteVector[_p.BYTES_PER_LOGS_BLOOM]
    prev_randao: Bytes32
    block_number: uint64
    gas_limit: uint64
    gas_used: uint64
    timestamp: uint64
    extra_data: ByteList[_p.MAX_EXTRA_DATA_BYTES]
    base_fee_per_gas: uint256
    excess_data_gas: uint256
    block_hash: Bytes32
    transactions_root: phase0.Root
    withdrawals_root: phase0.Root


def payload_to_header(payload: ExecutionPayload) -> ExecutionPayloadHeader:
    return ExecutionPayloadHeader(
        parent_hash=bytes(payload.parent_hash),
        fee_recipient=bytes(payload.fee_recipient),
        state_root=bytes(payload.state_root),
        receipts_root=bytes(payload.receipts_root),
        logs_bloom=bytes(payload.logs_bloom),
        prev_randao=bytes(payload.prev_randao),
        block_number=payload.block_number,
        gas_limit=payload.gas_limit,
        gas_used=payload.gas_used,
        timestamp=payload.timestamp,
        extra_data=bytes(payload.extra_data),
        base_fee_per_gas=payload.base_fee_per_gas,
        excess_data_gas=payload.excess_data_gas,
        block_hash=bytes(payload.block_hash),
        transactions_root=bellatrix.Transactions.hash_tree_root(
            list(payload.transactions)
        ),
        withdrawals_root=capella.Withdrawals.hash_tree_root(list(payload.withdrawals)),
    )


class BeaconBlockBody(Container):
    randao_reveal: phase0.BLSSignature
    eth1_data: phase0.Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[phase0.ProposerSlashing, _p.MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[phase0.AttesterSlashing, _p.MAX_ATTESTER_SLASHINGS]
    attestations: List[phase0.Attestation, _p.MAX_ATTESTATIONS]
    deposits: List[phase0.Deposit, _p.MAX_DEPOSITS]
    voluntary_exits: List[phase0.SignedVoluntaryExit, _p.MAX_VOLUNTARY_EXITS]
    sync_aggregate: altair.SyncAggregate
    execution_payload: ExecutionPayload
    bls_to_execution_changes: List[
        capella.SignedBLSToExecutionChange, _p.MAX_BLS_TO_EXECUTION_CHANGES
    ]
    blob_kzg_commitments: BlobKzgCommitments


class BeaconBlock(Container):
    slot: phase0.Slot
    proposer_index: phase0.ValidatorIndex
    parent_root: phase0.Root
    state_root: phase0.Root
    body: BeaconBlockBody


class SignedBeaconBlock(Container):
    message: BeaconBlock
    signature: phase0.BLSSignature


class BlobsSidecar(Container):
    beacon_block_root: phase0.Root
    beacon_block_slot: phase0.Slot
    blobs: Blobs
    kzg_aggregated_proof: KZGProof


class SignedBeaconBlockAndBlobsSidecar(Container):
    beacon_block: SignedBeaconBlock
    blobs_sidecar: BlobsSidecar


class BlindedBeaconBlockBody(Container):
    randao_reveal: phase0.BLSSignature
    eth1_data: phase0.Eth1Data
    graffiti: Bytes32
    proposer_slashings: List[phase0.ProposerSlashing, _p.MAX_PROPOSER_SLASHINGS]
    attester_slashings: List[phase0.AttesterSlashing, _p.MAX_ATTESTER_SLASHINGS]
    attestations: List[phase0.Attestation, _p.MAX_ATTESTATIONS]
    deposits: List[phase0.Deposit, _p.MAX_DEPOSITS]
    voluntary_exits: List[phase0.SignedVoluntaryExit, _p.MAX_VOLUNTARY_EXITS]
    sync_aggregate: altair.SyncAggregate
    execution_payload_header: ExecutionPayloadHeader
    bls_to_execution_changes: List[
        capella.SignedBLSToExecutionChange, _p.MAX_BLS_TO_EXECUTION_CHANGES
    ]
    blob_kzg_commitments: BlobKzgCommitments


class BlindedBeaconBlock(Container):
    slot: phase0.Slot
    proposer_index: phase0.ValidatorIndex
    parent_root: phase0.Root
    state_root: phase0.Root
    body: BlindedBeaconBlockBody


class SignedBlindedBeaconBlock(Container):
    message: BlindedBeaconBlock
    signature: phase0.BLSSignature


class BuilderBid(Container):
    header: ExecutionPayloadHeader
    value: uint256
    pubkey: phase0.BLSPubkey


class SignedBuilderBid(Container):
    message: BuilderBid
    signature: phase0.BLSSignature


class BeaconState(Container):
    genesis_time: uint64
    genesis_validators_root: phase0.Root
    slot: phase0.Slot
    fork: phase0.Fork
    latest_block_header: phase0.BeaconBlockHeader
    block_roots: Vector[phase0.Root, _p.SLOTS_PER_HISTORICAL_ROOT]
    state_roots: Vector[phase0.Root, _p.SLOTS_PER_HISTORICAL_ROOT]
    historical_roots: List[phase0.Root, _p.HISTORICAL_ROOTS_LIMIT]
    eth1_data: phase0.Eth1Data
    eth1_data_votes: phase0.Eth1DataVotes
    eth1_deposit_index: uint64
    validators: List[phase0.Validator, _p.VALIDATOR_REGISTRY_LIMIT]
    balances: List[phase0.Gwei, _p.VALIDATOR_REGISTRY_LIMIT]
    randao_mixes: Vector[Bytes32, _p.EPOCHS_PER_HISTORICAL_VECTOR]
    slashings: Vector[phase0.Gwei, _p.EPOCHS_PER_SLASHINGS_VECTOR]
    previous_epoch_participation: altair.EpochParticipation
    current_epoch_participation: altair.EpochParticipation
    justification_bits: Bitvector[JUSTIFICATION_BITS_LENGTH]
    previous_justified_checkpoint: phase0.Checkpoint
    current_justified_checkpoint: phase0.Checkpoint
    finalized_checkpoint: phase0.Checkpoint
    inactivity_scores: List[uint64, _p.VALIDATOR_REGISTRY_LIMIT]
    current_sync_committee: altair.SyncCommittee
    next_sync_committee: altair.SyncCommittee
    latest_execution_payload_header: ExecutionPayloadHeader
    next_withdrawal_index: capella.WithdrawalIndex
    next_withdrawal_validator_index: phase0.ValidatorIndex
    historical_summaries: List[capella.HistoricalSummary, _p.HISTORICAL_ROOTS_LIMIT]
