"""SSZ types per fork — the rebuild's `@lodestar/types`.

`ssz.phase0` / `ssz.altair` namespaces mirror packages/types/src/sszTypes.ts.
"""
from . import altair, phase0


class _Namespace:
    def __init__(self, mod):
        self._mod = mod

    def __getattr__(self, name):
        return getattr(self._mod, name)


class _Ssz:
    phase0 = _Namespace(phase0)
    altair = _Namespace(altair)


ssz = _Ssz()
