"""SSZ types per fork — the rebuild's `@lodestar/types`.

`ssz.phase0` … `ssz.eip4844` namespaces mirror packages/types/src/sszTypes.ts.
"""
from . import altair, bellatrix, capella, eip4844, phase0


class _Namespace:
    def __init__(self, mod):
        self._mod = mod

    def __getattr__(self, name):
        return getattr(self._mod, name)


class _Ssz:
    phase0 = _Namespace(phase0)
    altair = _Namespace(altair)
    bellatrix = _Namespace(bellatrix)
    capella = _Namespace(capella)
    eip4844 = _Namespace(eip4844)


ssz = _Ssz()


# fork registry ---------------------------------------------------------------

from lodestar_tpu.params import ForkName  # noqa: E402

_STATE_TYPES = {
    ForkName.phase0: phase0.BeaconState,
    ForkName.altair: altair.BeaconState,
    ForkName.bellatrix: bellatrix.BeaconState,
    ForkName.capella: capella.BeaconState,
    ForkName.eip4844: eip4844.BeaconState,
}
_BLOCK_TYPES = {
    ForkName.phase0: phase0.BeaconBlock,
    ForkName.altair: altair.BeaconBlock,
    ForkName.bellatrix: bellatrix.BeaconBlock,
    ForkName.capella: capella.BeaconBlock,
    ForkName.eip4844: eip4844.BeaconBlock,
}
_SIGNED_BLOCK_TYPES = {
    ForkName.phase0: phase0.SignedBeaconBlock,
    ForkName.altair: altair.SignedBeaconBlock,
    ForkName.bellatrix: bellatrix.SignedBeaconBlock,
    ForkName.capella: capella.SignedBeaconBlock,
    ForkName.eip4844: eip4844.SignedBeaconBlock,
}
_BODY_TYPES = {
    ForkName.phase0: phase0.BeaconBlockBody,
    ForkName.altair: altair.BeaconBlockBody,
    ForkName.bellatrix: bellatrix.BeaconBlockBody,
    ForkName.capella: capella.BeaconBlockBody,
    ForkName.eip4844: eip4844.BeaconBlockBody,
}
# blinded (builder-flow) variants, bellatrix+ (reference allForksBlinded)
_BLINDED_TYPES = {
    ForkName.bellatrix: (
        bellatrix.BlindedBeaconBlock,
        bellatrix.SignedBlindedBeaconBlock,
        bellatrix.BlindedBeaconBlockBody,
    ),
    ForkName.capella: (
        capella.BlindedBeaconBlock,
        capella.SignedBlindedBeaconBlock,
        capella.BlindedBeaconBlockBody,
    ),
    ForkName.eip4844: (
        eip4844.BlindedBeaconBlock,
        eip4844.SignedBlindedBeaconBlock,
        eip4844.BlindedBeaconBlockBody,
    ),
}


def blinded_types_for(fork: ForkName):
    """(BlindedBeaconBlock, SignedBlindedBeaconBlock, BlindedBeaconBlockBody)."""
    return _BLINDED_TYPES[fork]


# era-schema variants: fixture/devnet-era containers (e.g. pre-
# historical_summaries capella) registered so the fork dispatch treats
# them as their fork for processing purposes
_STATE_VARIANTS: dict = {}


def register_state_variant(fork: ForkName, state_type) -> None:
    _STATE_VARIANTS.setdefault(fork, []).append(state_type)


def fork_of_state(state) -> ForkName:
    """Which fork a BeaconState instance belongs to (by container type —
    the reference dispatches on allForks types the same way)."""
    for fork, t in _STATE_TYPES.items():
        if isinstance(state, t):
            return fork
    for fork, variants in _STATE_VARIANTS.items():
        if any(isinstance(state, t) for t in variants):
            return fork
    raise TypeError(f"unknown state type {type(state)!r}")


def fork_of_block(block) -> ForkName:
    for fork, t in _BLOCK_TYPES.items():
        if isinstance(block, t):
            return fork
    for fork, t in _SIGNED_BLOCK_TYPES.items():
        if isinstance(block, t):
            return fork
    for fork, (bt, st, _) in _BLINDED_TYPES.items():
        if isinstance(block, (bt, st)):
            return fork
    raise TypeError(f"unknown block type {type(block)!r}")


def types_for(fork: ForkName):
    """(BeaconState, BeaconBlock, SignedBeaconBlock, BeaconBlockBody)."""
    return (
        _STATE_TYPES[fork],
        _BLOCK_TYPES[fork],
        _SIGNED_BLOCK_TYPES[fork],
        _BODY_TYPES[fork],
    )


class SignedBlockSlotCodec:
    """Wire codec for SignedBeaconBlock that resolves the fork from the
    block's SLOT (the reference's config.getForkTypes(slot) pattern):
    SignedBeaconBlock serializes as [4-byte message offset | 96-byte
    signature | message...], so the message's leading slot uint64 always
    sits at bytes 100..108 regardless of fork.

    Must be `configure(cfg)`-ed with the chain config before post-phase0
    blocks can be decoded; unconfigured it decodes everything as phase0."""

    def __init__(self):
        self._fork_epochs = None  # [(epoch, ForkName)] ascending

    def configure(self, cfg) -> None:
        self._fork_epochs = [
            (0, ForkName.phase0),
            (cfg.ALTAIR_FORK_EPOCH, ForkName.altair),
            (cfg.BELLATRIX_FORK_EPOCH, ForkName.bellatrix),
            (cfg.CAPELLA_FORK_EPOCH, ForkName.capella),
            (cfg.EIP4844_FORK_EPOCH, ForkName.eip4844),
        ]

    def fork_at_slot(self, slot: int) -> ForkName:
        from lodestar_tpu.params import ACTIVE_PRESET as _p

        if self._fork_epochs is None:
            return ForkName.phase0
        epoch = slot // _p.SLOTS_PER_EPOCH
        out = ForkName.phase0
        for fork_epoch, name in self._fork_epochs:
            if fork_epoch <= epoch:
                out = name
        return out

    def serialize(self, sb) -> bytes:
        return type(sb).serialize(sb)

    def deserialize(self, data: bytes):
        if len(data) < 108:
            raise ValueError("signed block too short")
        slot = int.from_bytes(data[100:108], "little")
        return _SIGNED_BLOCK_TYPES[self.fork_at_slot(slot)].deserialize(data)


# process-wide instance shared by reqresp protocol tables and gossip topic
# registrations (configured by Network.__init__ from the chain config)
signed_block_wire_codec = SignedBlockSlotCodec()
