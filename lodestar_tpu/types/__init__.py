"""SSZ types per fork — the rebuild's `@lodestar/types`.

`ssz.phase0` / `ssz.altair` namespaces mirror packages/types/src/sszTypes.ts.
"""
from . import altair, phase0


class _Namespace:
    def __init__(self, mod):
        self._mod = mod

    def __getattr__(self, name):
        return getattr(self._mod, name)


class _Ssz:
    phase0 = _Namespace(phase0)
    altair = _Namespace(altair)


ssz = _Ssz()


# fork registry ---------------------------------------------------------------

from lodestar_tpu.params import ForkName  # noqa: E402

_STATE_TYPES = {
    ForkName.phase0: phase0.BeaconState,
    ForkName.altair: altair.BeaconState,
}
_BLOCK_TYPES = {
    ForkName.phase0: phase0.BeaconBlock,
    ForkName.altair: altair.BeaconBlock,
}
_SIGNED_BLOCK_TYPES = {
    ForkName.phase0: phase0.SignedBeaconBlock,
    ForkName.altair: altair.SignedBeaconBlock,
}
_BODY_TYPES = {
    ForkName.phase0: phase0.BeaconBlockBody,
    ForkName.altair: altair.BeaconBlockBody,
}


def fork_of_state(state) -> ForkName:
    """Which fork a BeaconState instance belongs to (by container type —
    the reference dispatches on allForks types the same way)."""
    for fork, t in _STATE_TYPES.items():
        if isinstance(state, t):
            return fork
    raise TypeError(f"unknown state type {type(state)!r}")


def fork_of_block(block) -> ForkName:
    for fork, t in _BLOCK_TYPES.items():
        if isinstance(block, t):
            return fork
    for fork, t in _SIGNED_BLOCK_TYPES.items():
        if isinstance(block, t):
            return fork
    raise TypeError(f"unknown block type {type(block)!r}")


def types_for(fork: ForkName):
    """(BeaconState, BeaconBlock, SignedBeaconBlock, BeaconBlockBody)."""
    return (
        _STATE_TYPES[fork],
        _BLOCK_TYPES[fork],
        _SIGNED_BLOCK_TYPES[fork],
        _BODY_TYPES[fork],
    )


class SignedBlockSlotCodec:
    """Wire codec for SignedBeaconBlock that resolves the fork from the
    block's SLOT (the reference's config.getForkTypes(slot) pattern):
    SignedBeaconBlock serializes as [4-byte message offset | 96-byte
    signature | message...], so the message's leading slot uint64 always
    sits at bytes 100..108 regardless of fork.

    Must be `configure(cfg)`-ed with the chain config before altair blocks
    can be decoded; unconfigured it decodes everything as phase0."""

    def __init__(self):
        self._altair_epoch = None

    def configure(self, cfg) -> None:
        self._altair_epoch = cfg.ALTAIR_FORK_EPOCH

    def fork_at_slot(self, slot: int) -> ForkName:
        from lodestar_tpu.params import ACTIVE_PRESET as _p

        if (
            self._altair_epoch is not None
            and slot // _p.SLOTS_PER_EPOCH >= self._altair_epoch
        ):
            return ForkName.altair
        return ForkName.phase0

    def serialize(self, sb) -> bytes:
        return type(sb).serialize(sb)

    def deserialize(self, data: bytes):
        if len(data) < 108:
            raise ValueError("signed block too short")
        slot = int.from_bytes(data[100:108], "little")
        return _SIGNED_BLOCK_TYPES[self.fork_at_slot(slot)].deserialize(data)


# process-wide instance shared by reqresp protocol tables and gossip topic
# registrations (configured by Network.__init__ from the chain config)
signed_block_wire_codec = SignedBlockSlotCodec()
