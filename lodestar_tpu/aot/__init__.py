"""AOT compile-lifecycle subsystem: compile once, run forever.

The BLS pairing programs cost minutes-to-hours of XLA compile on a cold
cache (BENCH r3-r5 banked 0.0 sigs/s purely on cold compiles), while a
warm persistent cache loads them in seconds.  This package makes that
lifecycle a first-class subsystem instead of four divergent copies of
``jax_compilation_cache_dir`` setup:

- ``aot.cache``     — the ONE ``configure()`` every entry point uses
                      (node startup, bench, tests, __graft_entry__,
                      diagnose_cache), plus a persistent-cache spy for
                      hit/miss/compile-time observability.
- ``aot.registry``  — the single source of truth for every jit program
                      the node can dispatch: explicit (kernel, bucket)
                      entries with concrete avals.
- ``aot.warm``      — resumable, per-program warmer + freshness
                      manifest; ``python -m lodestar_tpu.aot warm
                      [--check]``.

See docs/AOT.md for the workflow.
"""
from __future__ import annotations

# Submodules are imported lazily by callers (``from lodestar_tpu.aot
# import cache``): this package must stay importable without jax so the
# bench parent / CLI can reference it before any backend init.
