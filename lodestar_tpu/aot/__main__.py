"""CLI: ``python -m lodestar_tpu.aot warm [--check|--heal]`` — compile
the registered BLS programs into the persistent cache (resumable),
verify they are all present/fresh/uncorrupted, or quarantine-and-
recompile poisoned entries (docs/AOT.md troubleshooting).

Also reachable as ``lodestar-tpu aot warm|check`` (cli/main.py).
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m lodestar_tpu.aot",
        description="AOT compile-cache tooling for the BLS kernels",
    )
    sub = ap.add_subparsers(dest="command")
    w = sub.add_parser(
        "warm",
        help="lower+compile every registered program into the persistent "
        "cache (resumable: finished programs are banked per-program)",
    )
    w.add_argument(
        "--check",
        action="store_true",
        help="verify only: exit 0 iff every registered program is warm "
        "and the manifest is fresh (no compiles)",
    )
    w.add_argument(
        "--list",
        action="store_true",
        help="print the registered programs + their warm state and exit",
    )
    w.add_argument(
        "--scope",
        choices=["core", "full"],
        default="core",
        help="core: what bench + the governed pool dispatch (default); "
        "full: every direct-call bucket as well",
    )
    w.add_argument(
        "--heal",
        action="store_true",
        help="load-round-trip every registered program: quarantine "
        "corrupt/undeserializable cache entries (bytes preserved under "
        ".jax_cache/quarantine/) and recompile them; healthy entries "
        "are untouched (see docs/AOT.md troubleshooting)",
    )
    w.add_argument(
        "--budget-s",
        type=float,
        default=None,
        help="stop starting new compiles after this many seconds "
        "(finished programs stay banked)",
    )
    w.add_argument("--cache-dir", default=None, help="override .jax_cache path")
    w.add_argument(
        "--no-export",
        action="store_true",
        help="skip the best-effort jax.export serialization",
    )
    w.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)
    if args.command != "warm":
        ap.print_help()
        return 1
    if args.heal and (args.check or args.list):
        # --check/--list are read-only; silently ignoring --heal would
        # leave an operator believing the poisoned entry was fixed
        ap.error("--heal cannot be combined with --check/--list "
                 "(run --heal first, then --check)")

    # The persistent-cache key includes compile options: pin the env the
    # same way bench.py pins its child stages, BEFORE jax initializes,
    # so warm and bench compute identical keys.
    from lodestar_tpu.aot import cache as aot_cache

    aot_cache.pin_cache_key_env()

    from lodestar_tpu.aot import registry, warm

    programs = registry.registered_programs(scope=args.scope)

    if args.check or args.list:
        ok, rows = warm.check_programs(programs, cache_dir=args.cache_dir)
        if args.json:
            print(json.dumps({"ok": ok, "programs": dict(rows)}, indent=2))
        else:
            for key, state in rows:
                print(f"{state:>8}  {key}")
            print(
                f"aot check: {sum(1 for _, s in rows if s == 'warm')}"
                f"/{len(rows)} programs warm",
                file=sys.stderr,
            )
        return 0 if ok else 1

    # single-warmer lock: two concurrent warms would double-compile the
    # same 40-minute program
    import fcntl

    cache_dir = args.cache_dir or aot_cache.repo_cache_dir()
    import os

    os.makedirs(cache_dir, exist_ok=True)
    lock_fh = open(os.path.join(cache_dir, ".aot.lock"), "w")
    try:
        fcntl.flock(lock_fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        print(
            "aot warm: another warm run holds the lock — exiting "
            "(its finished programs will be banked)",
            file=sys.stderr,
        )
        return 3
    try:
        if args.heal:
            report = warm.heal_programs(
                programs,
                cache_dir=args.cache_dir,
                budget_s=args.budget_s,
                do_export=not args.no_export,
                log=lambda m: print(m, file=sys.stderr, flush=True),
            )
        else:
            report = warm.warm_programs(
                programs,
                cache_dir=args.cache_dir,
                budget_s=args.budget_s,
                do_export=not args.no_export,
                log=lambda m: print(m, file=sys.stderr, flush=True),
            )
    finally:
        lock_fh.close()
    if args.json:
        print(json.dumps(report, indent=2))
    elif args.heal:
        print(
            f"aot heal: {len(report['healthy'])} healthy, "
            f"{len(report['healed'])} healed, "
            f"{len(report['stale_rewarmed'])} re-warmed, "
            f"{len(report['quarantined'])} file(s) quarantined, "
            f"{len(report['deferred'])} deferred"
        )
    else:
        print(
            f"aot warm: {len(report['compiled'])} compiled, "
            f"{len(report['skipped'])} already warm, "
            f"{len(report['deferred'])} deferred"
        )
    return 0 if not report["deferred"] else 2


if __name__ == "__main__":
    sys.exit(main())
