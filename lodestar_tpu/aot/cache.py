"""Persistent compilation-cache config + observability spy.

``configure()`` is the single cache-setup path for every entry point.
Four divergent copies of this logic (bench.py, tests/conftest.py,
__graft_entry__.py, tools/diagnose_cache.py) previously disagreed on
defaults while the production node path never enabled the cache at all
— so first verification on a node paid the full multi-minute compile
every process start.

``install_cache_spy()`` wraps jax's internal persistent-cache get/put
(jax._src.compilation_cache.get_executable_and_time /
put_executable_and_time — both called through module-attribute lookup,
so wrapping the attributes is effective) to count hits/misses and
observe real compile times.  The warm tool uses the captured keys to
learn each program's cache filename; chain/bls/metrics.py feeds the
events into the Prometheus family.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Callable, Dict, List, Optional

_log = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_CACHE_DIR = os.path.join(_REPO_ROOT, ".jax_cache")

# Matches what bench.py historically used: only multi-second compiles
# are worth a cache entry; tests override to 0.0 for tiny programs.
DEFAULT_MIN_COMPILE_SECS = 1.0


def cache_generation() -> str:
    """The operator-bumpable cache-generation salt (ROADMAP item 3).

    jax never rewrites a cache key whose entry exists-but-fails-to-load,
    so a poisoned entry under the OLD key survives recompiles forever.
    The spy's quarantine path (below) heals that in-process; the salt is
    the out-of-band hammer: bump ``LODESTAR_TPU_CACHE_GENERATION`` once
    and every program re-warms into a fresh ``gen-<salt>`` subdirectory
    while the old entries stay untouched on disk (never delete
    ``.jax_cache``).  The salt is also mixed into the trace-replay cache
    key (ops/bls12_381/opcache._env_key) so nothing in the process
    straddles generations."""
    return os.environ.get("LODESTAR_TPU_CACHE_GENERATION", "").strip()


def repo_cache_dir() -> str:
    """The effective persistent-cache dir (override: LODESTAR_TPU_JAX_CACHE;
    salted into a ``gen-<salt>`` subdir when LODESTAR_TPU_CACHE_GENERATION
    is set — see cache_generation)."""
    base = os.environ.get("LODESTAR_TPU_JAX_CACHE", DEFAULT_CACHE_DIR)
    gen = cache_generation()
    return os.path.join(base, f"gen-{gen}") if gen else base


def configure(
    cache_dir: Optional[str] = None,
    *,
    min_compile_time_secs: float = DEFAULT_MIN_COMPILE_SECS,
) -> str:
    """Point jax at the persistent compilation cache.  Idempotent; safe
    before or after backend init (changing the directory mid-process
    resets jax's internal cache handle, which otherwise keeps serving
    the OLD directory).  Returns the cache dir in effect."""
    import jax

    if os.environ.get("XLA_FLAGS"):
        # compile options are part of the persistent-cache KEY: a
        # process running under XLA_FLAGS computes different keys than
        # the warm tool (which pins its env via pin_cache_key_env), so
        # warmed entries are invisible and first dispatch compiles
        # cold.  Warn — don't silently strip: XLA_FLAGS can be a
        # deliberate operator choice (e.g. the multichip dryrun's
        # host_platform_device_count).
        _log.warning(
            "XLA_FLAGS is set: persistent compilation-cache keys will "
            "not match `python -m lodestar_tpu.aot warm` (which clears "
            "it) — warmed programs may recompile cold"
        )
    cache_dir = cache_dir or repo_cache_dir()
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_time_secs
    )
    if prev is not None and prev != cache_dir:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    return cache_dir


def pin_cache_key_env(environ: Optional[Dict[str, str]] = None) -> None:
    """Make the persistent-cache KEY deterministic across invokers by
    clearing XLA_FLAGS (compile options are part of the key: a cache
    warmed under a builder shell's stray flags is invisible to the
    driver's bare ``python bench.py`` — the round-4 failure mode).
    Call BEFORE the first jax backend use.  Mutates ``environ``
    (default: os.environ)."""
    env = environ if environ is not None else os.environ
    env.pop("XLA_FLAGS", None)


# ---------------------------------------------------------------------------
# persistent-cache spy
# ---------------------------------------------------------------------------

_spy_lock = threading.Lock()
_SPY: Dict[str, object] = {"installed": False}
_CALLBACKS: List[Callable[[str, str, float], None]] = []
_STATS = {"hits": 0, "misses": 0, "puts": 0, "load_errors": 0}
_KEYS: Dict[str, str] = {}  # cache_key -> last event kind


def install_cache_spy(
    callback: Optional[Callable[[str, str, float], None]] = None,
) -> None:
    """Wrap the persistent-cache read/write path.  ``callback`` (if
    given) receives (kind, cache_key, seconds) with kind in
    {"hit", "miss", "put"}; seconds is the stored/observed compile time
    (0.0 on miss).  Idempotent: the wrappers install once per process,
    callbacks accumulate."""
    with _spy_lock:
        if callback is not None:
            _CALLBACKS.append(callback)
        if _SPY["installed"]:
            return
        from jax._src import compilation_cache as cc

        orig_get = cc.get_executable_and_time
        orig_put = cc.put_executable_and_time

        def spy_get(cache_key, *args, **kwargs):
            from lodestar_tpu.testing import faults

            try:
                try:
                    faults.fire("aot.cache.get", cache_key=cache_key)
                    executable, compile_time = orig_get(
                        cache_key, *args, **kwargs
                    )
                except Exception as first_err:
                    # retry ONCE before declaring the entry poisoned: a
                    # transient I/O hiccup (flaky disk/NFS read) must
                    # not evict a healthy entry and force a multi-
                    # minute recompile — genuine deserialization
                    # failures are deterministic and fail again
                    _log.debug(
                        "persistent-cache load of %s failed once "
                        "(%s: %s); retrying before quarantine",
                        cache_key, type(first_err).__name__, first_err,
                    )
                    faults.fire("aot.cache.get", cache_key=cache_key)
                    executable, compile_time = orig_get(
                        cache_key, *args, **kwargs
                    )
            except Exception as e:
                # Self-heal (tentpole b): the entry EXISTS but cannot
                # deserialize — the one known production fault (a
                # poisoned 111 MB pairing entry kept full-pairing
                # multichip red for five rounds, because jax never
                # rewrites a failed-load key).  Quarantine the corrupt
                # bytes aside and report a MISS: jax recompiles and the
                # following put writes a fresh entry under the same key.
                try:
                    quarantined = quarantine_entry(
                        _current_cache_dir(), cache_key
                    )
                except OSError as qe:
                    # a read-only/permission-locked cache dir: the
                    # quarantine is best-effort — still degrade to a
                    # miss so the compile proceeds (the poisoned file
                    # stays, but this process gets its executable)
                    _log.warning(
                        "could not quarantine poisoned entry %s (%s: "
                        "%s)", cache_key, type(qe).__name__, qe,
                    )
                    quarantined = None
                _log.warning(
                    "persistent-cache entry %s failed to load (%s: %s); "
                    "quarantined to %s — recompiling",
                    cache_key,
                    type(e).__name__,
                    e,
                    quarantined or "<no file found>",
                )
                _emit("load_error", cache_key, 0.0)
                return None, None
            if executable is not None:
                _emit("hit", cache_key, float(compile_time or 0))
            else:
                _emit("miss", cache_key, 0.0)
            return executable, compile_time

        def spy_put(cache_key, *args, **kwargs):
            from lodestar_tpu.testing import faults

            faults.fire("aot.cache.put", cache_key=cache_key)
            # signature: (cache_key, module_name, executable, backend,
            # compile_time:int seconds)
            seconds = 0.0
            if args:
                try:
                    seconds = float(args[-1])
                except (TypeError, ValueError):
                    seconds = 0.0
            # is this put the rewrite half of a self-heal?  (load_error
            # was this key's last event before the recompile)
            healed = _KEYS.get(cache_key) == "load_error"
            _emit("put", cache_key, seconds)
            result = orig_put(cache_key, *args, **kwargs)
            if healed:
                # re-stamp the warm manifest's entry hash: the healed
                # bytes need not match the warm-time fingerprint, and a
                # stale hash would make the next `warm --check` call
                # this freshly-healed entry "corrupt"
                try:
                    from lodestar_tpu.aot import warm as _warm

                    _warm.refresh_entry_hash(_current_cache_dir(), cache_key)
                except Exception as e:
                    _log.debug(
                        "manifest hash refresh after self-heal failed: "
                        "%s: %s", type(e).__name__, e,
                    )
            return result

        cc.get_executable_and_time = spy_get
        cc.put_executable_and_time = spy_put
        _SPY["installed"] = True


def remove_cache_spy_callback(
    callback: Callable[[str, str, float], None],
) -> None:
    """Unregister a callback added by ``install_cache_spy``.  The spy
    wrappers stay installed (they are process-global and idempotent),
    but the callback — and whatever it strongly references, e.g. a
    closed verifier pool — is released."""
    # Reviewed exception: the lock guards a bare list.remove —
    # microseconds, never held across I/O or a compile — and the async
    # caller (DeviceBlsVerifier.close) runs it once at teardown.
    with _spy_lock:  # lodelint: disable=transitive-blocking
        try:
            _CALLBACKS.remove(callback)
        except ValueError:
            pass


_STAT_KEY = {
    "hit": "hits",
    "miss": "misses",
    "put": "puts",
    "load_error": "load_errors",
}


def _emit(kind: str, cache_key: str, seconds: float) -> None:
    stat = _STAT_KEY.get(kind, kind)
    _STATS[stat] = _STATS.get(stat, 0) + 1
    _KEYS[cache_key] = kind
    for cb in list(_CALLBACKS):
        try:
            cb(kind, cache_key, seconds)
        except Exception as e:
            # a metrics sink must never break a compile — but a broken
            # sink must not be invisible either
            _log.debug(
                "cache-spy callback failed: %s: %s", type(e).__name__, e
            )


def cache_stats() -> Dict[str, int]:
    """Snapshot of persistent-cache traffic since the spy installed."""
    return dict(_STATS)


def observed_keys() -> Dict[str, str]:
    """cache_key -> last event kind ("hit"/"miss"/"put")."""
    return dict(_KEYS)


def reset_stats() -> None:
    for k in list(_STATS):
        _STATS[k] = 0
    _KEYS.clear()


def entry_exists(cache_dir: str, cache_key: str) -> bool:
    """True if a persistent-cache entry for ``cache_key`` is on disk
    (jax's LRU file cache stores ``<key>-cache``; the plain layout
    stores ``<key>``)."""
    return os.path.isfile(os.path.join(cache_dir, cache_key + "-cache")) or (
        os.path.isfile(os.path.join(cache_dir, cache_key))
    )


def entry_paths(cache_dir: str, cache_key: str) -> List[str]:
    """On-disk file(s) holding ``cache_key``'s entry (either layout)."""
    out = []
    for suffix in ("-cache", ""):
        p = os.path.join(cache_dir, cache_key + suffix)
        if os.path.isfile(p):
            out.append(p)
    return out


# ---------------------------------------------------------------------------
# corrupt-entry quarantine (self-healing cache — tentpole b)
# ---------------------------------------------------------------------------

QUARANTINE_DIR = "quarantine"


def quarantine_dir(cache_dir: str) -> str:
    return os.path.join(cache_dir, QUARANTINE_DIR)


def quarantine_entry(cache_dir: str, cache_key: str) -> Optional[str]:
    """Move a corrupt entry's file(s) into ``<cache>/quarantine/``,
    preserving the bytes for post-mortem — NEVER delete, and never
    touch any other entry.  Returns the first quarantined path (None if
    no file was on disk).  Name collisions from repeated poisonings get
    a numeric suffix instead of overwriting earlier evidence."""
    moved: Optional[str] = None
    qdir = quarantine_dir(cache_dir)
    for src in entry_paths(cache_dir, cache_key):
        os.makedirs(qdir, exist_ok=True)
        dst = os.path.join(qdir, os.path.basename(src))
        n = 1
        while os.path.exists(dst):
            dst = os.path.join(qdir, f"{os.path.basename(src)}.{n}")
            n += 1
        os.replace(src, dst)
        moved = moved or dst
    return moved


def quarantined_files(cache_dir: str) -> List[str]:
    qdir = quarantine_dir(cache_dir)
    if not os.path.isdir(qdir):
        return []
    return sorted(
        os.path.join(qdir, f) for f in os.listdir(qdir)
        if os.path.isfile(os.path.join(qdir, f))
    )


def _current_cache_dir() -> str:
    """The dir jax is ACTUALLY using right now (falls back to the
    configured repo dir when jax has none set)."""
    try:
        import jax

        d = jax.config.jax_compilation_cache_dir
        if d:
            return d
    except ImportError:  # no jax in this process: the configured default
        pass
    return repo_cache_dir()
