"""Persistent compilation-cache config + observability spy.

``configure()`` is the single cache-setup path for every entry point.
Four divergent copies of this logic (bench.py, tests/conftest.py,
__graft_entry__.py, tools/diagnose_cache.py) previously disagreed on
defaults while the production node path never enabled the cache at all
— so first verification on a node paid the full multi-minute compile
every process start.

``install_cache_spy()`` wraps jax's internal persistent-cache get/put
(jax._src.compilation_cache.get_executable_and_time /
put_executable_and_time — both called through module-attribute lookup,
so wrapping the attributes is effective) to count hits/misses and
observe real compile times.  The warm tool uses the captured keys to
learn each program's cache filename; chain/bls/metrics.py feeds the
events into the Prometheus family.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Dict, List, Optional

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
DEFAULT_CACHE_DIR = os.path.join(_REPO_ROOT, ".jax_cache")

# Matches what bench.py historically used: only multi-second compiles
# are worth a cache entry; tests override to 0.0 for tiny programs.
DEFAULT_MIN_COMPILE_SECS = 1.0


def repo_cache_dir() -> str:
    """The repo-local persistent cache (override: LODESTAR_TPU_JAX_CACHE)."""
    return os.environ.get("LODESTAR_TPU_JAX_CACHE", DEFAULT_CACHE_DIR)


def configure(
    cache_dir: Optional[str] = None,
    *,
    min_compile_time_secs: float = DEFAULT_MIN_COMPILE_SECS,
) -> str:
    """Point jax at the persistent compilation cache.  Idempotent; safe
    before or after backend init (changing the directory mid-process
    resets jax's internal cache handle, which otherwise keeps serving
    the OLD directory).  Returns the cache dir in effect."""
    import jax

    if os.environ.get("XLA_FLAGS"):
        # compile options are part of the persistent-cache KEY: a
        # process running under XLA_FLAGS computes different keys than
        # the warm tool (which pins its env via pin_cache_key_env), so
        # warmed entries are invisible and first dispatch compiles
        # cold.  Warn — don't silently strip: XLA_FLAGS can be a
        # deliberate operator choice (e.g. the multichip dryrun's
        # host_platform_device_count).
        import logging

        logging.getLogger(__name__).warning(
            "XLA_FLAGS is set: persistent compilation-cache keys will "
            "not match `python -m lodestar_tpu.aot warm` (which clears "
            "it) — warmed programs may recompile cold"
        )
    cache_dir = cache_dir or repo_cache_dir()
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", min_compile_time_secs
    )
    if prev is not None and prev != cache_dir:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    return cache_dir


def pin_cache_key_env(environ: Optional[Dict[str, str]] = None) -> None:
    """Make the persistent-cache KEY deterministic across invokers by
    clearing XLA_FLAGS (compile options are part of the key: a cache
    warmed under a builder shell's stray flags is invisible to the
    driver's bare ``python bench.py`` — the round-4 failure mode).
    Call BEFORE the first jax backend use.  Mutates ``environ``
    (default: os.environ)."""
    env = environ if environ is not None else os.environ
    env.pop("XLA_FLAGS", None)


# ---------------------------------------------------------------------------
# persistent-cache spy
# ---------------------------------------------------------------------------

_spy_lock = threading.Lock()
_SPY: Dict[str, object] = {"installed": False}
_CALLBACKS: List[Callable[[str, str, float], None]] = []
_STATS = {"hits": 0, "misses": 0, "puts": 0}
_KEYS: Dict[str, str] = {}  # cache_key -> last event kind


def install_cache_spy(
    callback: Optional[Callable[[str, str, float], None]] = None,
) -> None:
    """Wrap the persistent-cache read/write path.  ``callback`` (if
    given) receives (kind, cache_key, seconds) with kind in
    {"hit", "miss", "put"}; seconds is the stored/observed compile time
    (0.0 on miss).  Idempotent: the wrappers install once per process,
    callbacks accumulate."""
    with _spy_lock:
        if callback is not None:
            _CALLBACKS.append(callback)
        if _SPY["installed"]:
            return
        from jax._src import compilation_cache as cc

        orig_get = cc.get_executable_and_time
        orig_put = cc.put_executable_and_time

        def spy_get(cache_key, *args, **kwargs):
            executable, compile_time = orig_get(cache_key, *args, **kwargs)
            if executable is not None:
                _emit("hit", cache_key, float(compile_time or 0))
            else:
                _emit("miss", cache_key, 0.0)
            return executable, compile_time

        def spy_put(cache_key, *args, **kwargs):
            # signature: (cache_key, module_name, executable, backend,
            # compile_time:int seconds)
            seconds = 0.0
            if args:
                try:
                    seconds = float(args[-1])
                except (TypeError, ValueError):
                    seconds = 0.0
            _emit("put", cache_key, seconds)
            return orig_put(cache_key, *args, **kwargs)

        cc.get_executable_and_time = spy_get
        cc.put_executable_and_time = spy_put
        _SPY["installed"] = True


def remove_cache_spy_callback(
    callback: Callable[[str, str, float], None],
) -> None:
    """Unregister a callback added by ``install_cache_spy``.  The spy
    wrappers stay installed (they are process-global and idempotent),
    but the callback — and whatever it strongly references, e.g. a
    closed verifier pool — is released."""
    # Reviewed exception: the lock guards a bare list.remove —
    # microseconds, never held across I/O or a compile — and the async
    # caller (DeviceBlsVerifier.close) runs it once at teardown.
    with _spy_lock:  # lodelint: disable=transitive-blocking
        try:
            _CALLBACKS.remove(callback)
        except ValueError:
            pass


_STAT_KEY = {"hit": "hits", "miss": "misses", "put": "puts"}


def _emit(kind: str, cache_key: str, seconds: float) -> None:
    stat = _STAT_KEY.get(kind, kind)
    _STATS[stat] = _STATS.get(stat, 0) + 1
    _KEYS[cache_key] = kind
    for cb in list(_CALLBACKS):
        try:
            cb(kind, cache_key, seconds)
        except Exception:
            pass  # a metrics sink must never break a compile


def cache_stats() -> Dict[str, int]:
    """Snapshot of persistent-cache traffic since the spy installed."""
    return dict(_STATS)


def observed_keys() -> Dict[str, str]:
    """cache_key -> last event kind ("hit"/"miss"/"put")."""
    return dict(_KEYS)


def reset_stats() -> None:
    for k in list(_STATS):
        _STATS[k] = 0
    _KEYS.clear()


def entry_exists(cache_dir: str, cache_key: str) -> bool:
    """True if a persistent-cache entry for ``cache_key`` is on disk
    (jax's LRU file cache stores ``<key>-cache``; the plain layout
    stores ``<key>``)."""
    return os.path.isfile(os.path.join(cache_dir, cache_key + "-cache")) or (
        os.path.isfile(os.path.join(cache_dir, cache_key))
    )
