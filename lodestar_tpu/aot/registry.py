"""AOT program registry — every jit program the node can dispatch.

The BLS verify kernels used to be jitted by ad-hoc module-level
closures in ops/bls12_381/verify.py; nothing enumerated which
(kernel, bucket) shapes a node would actually run, so the warm tooling
had to guess and the latency governor could mint program shapes nobody
ever compiled.  This registry is now the single source of truth:

- ``jitted(kernel)`` hands out THE memoized ``jax.jit`` wrapper per
  kernel (verify.py's ``_jit_*`` attributes are these objects, and the
  lodelint ``unregistered-jit`` rule keeps any other module-scope
  ``jax.jit`` out of ``lodestar_tpu/``);
- ``registered_programs()`` enumerates the concrete (kernel, bucket)
  entries — with example avals — that ``python -m lodestar_tpu.aot
  warm`` compiles into the persistent cache.

Scopes: the default ``core`` scope is the set a production node + the
bench actually dispatch (bench stages, the pool's quantized widths, the
sync-committee fast-aggregate bucket) — deliberately small because one
cold compile costs ~15-40 min on a 2-core host.  ``full`` adds every
direct-call bucket for belt-and-braces coverage.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from lodestar_tpu.ops.bls12_381 import buckets as bk

RAND_BITS = 64  # production random-coefficient width (bits)

_KERNELS: Dict[str, Callable] = {}


def register_kernels(**kernels: Callable) -> None:
    """Called by ops/bls12_381/verify.py at import with its kernel
    functions (batch/hashed/each/fast_agg)."""
    _KERNELS.update(kernels)


def ensure_kernels() -> Dict[str, Callable]:
    if not _KERNELS:
        # verify.py registers its kernels at import time
        import lodestar_tpu.ops.bls12_381.verify  # noqa: F401
    return _KERNELS


_JITTED: Dict[str, object] = {}


def jitted(kernel: str):
    """THE jit wrapper for a kernel — one object per process, so every
    call site shares one trace cache and the persistent-cache filename
    is stable (``jit_<fn name>-<key>``).

    Memoized with an explicit dict, NOT lru_cache: resolving the kernel
    table can import ops/bls12_381/verify.py, whose module body calls
    jitted() reentrantly — under lru_cache the outer frame would mint a
    SECOND wrapper and overwrite the reentrant one, silently splitting
    the trace cache by import order.  Resolving kernels BEFORE the
    memo check makes the reentrant wrapper the one everybody gets."""
    fns = ensure_kernels()
    if kernel in _JITTED:
        return _JITTED[kernel]
    if kernel not in fns:
        raise KeyError(
            f"unknown kernel {kernel!r} (registered: {sorted(fns)})"
        )
    import jax

    # Reviewed exception: this IS the memoized factory jit-in-func
    # points everyone at — the dict above guarantees one wrapper per
    # kernel per process (lru_cache would double-mint on the reentrant
    # verify.py import; see docstring).
    wrapper = _JITTED[kernel] = jax.jit(  # lodelint: disable=jit-in-func
        fns[kernel]
    )
    return wrapper


@dataclass(frozen=True)
class Program:
    """One compilable program: a kernel at a concrete batch bucket,
    optionally sharded over a ``mesh_size``-device (sp,) mesh
    (``mesh_size=0`` means the ordinary single-device program)."""

    kernel: str  # "batch" | "hashed" | "each" | "fast_agg" | "sharded"
    bucket: int
    priority: int = 100  # warm order: lower first
    note: str = ""
    mesh_size: int = 0  # 0 = unsharded; else devices on the (sp,) mesh

    @property
    def key(self) -> str:
        base = f"{self.kernel}/b{self.bucket}"
        return f"{base}@m{self.mesh_size}" if self.mesh_size else base

    def fn(self):
        if self.mesh_size:
            from lodestar_tpu.ops.bls12_381 import sharded

            return sharded.jitted_sharded(self.mesh_size)
        return jitted(self.kernel)

    def fn_name(self) -> str:
        """Underlying function name — the persistent-cache filename
        prefix is ``jit_<fn_name>-``."""
        if self.mesh_size:
            return "sharded_verify"
        return ensure_kernels()[self.kernel].__name__

    def example_args(self) -> tuple:
        """Concrete zero/padding inputs with the exact avals the host
        wrappers produce at this bucket (values never matter for the
        cache key — only shapes/dtypes do)."""
        return _example_args(self.kernel, self.bucket)


def _example_args(kernel: str, B: int) -> tuple:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from lodestar_tpu.ops.bls12_381 import curve as cv

    pk_aff, pk_inf = cv.encode_g1_affine([None] * B)
    sig_aff, sig_inf = cv.encode_g2_affine([None] * B)
    active = jnp.asarray(np.zeros(B, dtype=bool))
    bits = cv.scalars_to_bits([1] * B, RAND_BITS)
    if kernel == "hashed":
        from lodestar_tpu.ops.bls12_381 import h2c

        u0, u1 = h2c.encode_field_draws([], B)
        return (pk_aff, pk_inf, u0, u1, sig_aff, sig_inf, bits, active)
    msg_aff, msg_inf = cv.encode_g2_affine([None] * B)
    if kernel == "batch":
        return (pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, bits, active)
    if kernel == "sharded":
        # ops/bls12_381/sharded.py arg order (active before bits,
        # matching __graft_entry__'s dryrun signature)
        return (pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, active, bits)
    if kernel == "each":
        return (pk_aff, pk_inf, msg_aff, msg_inf, sig_aff, sig_inf, active)
    if kernel == "fast_agg":
        squeeze = lambda t: jax.tree.map(lambda x: x[0], t)
        return (
            pk_aff,
            pk_inf,
            squeeze(msg_aff),
            msg_inf[0],
            squeeze(sig_aff),
            sig_inf[0],
            active,
        )
    raise KeyError(f"unknown kernel {kernel!r}")


# ---------------------------------------------------------------------------
# the registered set
# ---------------------------------------------------------------------------


def _device_h2c() -> Optional[bool]:
    from lodestar_tpu.ops.bls12_381 import verify as dv

    return dv.use_device_h2c()


def bench_buckets() -> List[int]:
    """The widths bench.py stages dispatch (flagship + fallback)."""
    batch_max = int(os.environ.get("BENCH_BATCH_MAX", "4096"))
    return list(dict.fromkeys((min(1024, batch_max), batch_max)))


def registered_programs(
    scope: str = "core", device_h2c: Optional[bool] = None
) -> List[Program]:
    """The programs ``warm`` compiles and ``warm --check`` requires.

    Priority order matters operationally: warming is resumable but each
    cold program costs tens of minutes on the 2-core host, so the bench
    fallback stage comes first — the first completed warm invocation is
    enough for bench to bank a real number.
    """
    if scope not in ("core", "full"):
        raise ValueError(f"unknown scope {scope!r} (core|full)")
    if device_h2c is None:
        device_h2c = _device_h2c()
    from lodestar_tpu.chain.bls import device_pool as dp
    from lodestar_tpu.params import SYNC_COMMITTEE_SIZE

    progs: List[Program] = []
    # 1. bench stages (bench uses the device-h2c kernel explicitly:
    #    end-to-end message-bytes -> bool is the headline metric)
    for i, b in enumerate(bench_buckets()):
        progs.append(
            Program("hashed", b, priority=i, note="bench stage")
        )
    # 2. the pool's quantized dispatch widths for the node's verify
    #    kernel (h2c mode decides which kernel that is).  EVERY rung up
    #    to the overload drain width is reachable (partial packs
    #    quantize to the smallest rung that holds them), so every rung
    #    is registered.  The per-set fallback kernel ("each") is FULL
    #    scope only: it dispatches exclusively after a failed batch — a
    #    misbehaving-peer event, not the steady path — and each core
    #    program costs tens of minutes of warm time on a 2-core host
    #    (docs/AOT.md discusses the tradeoff).
    vk = "hashed" if device_h2c else "batch"
    drain = bk.align_down(dp.MAX_SIGNATURE_SETS_PER_JOB)
    pool_widths = sorted(b for b in bk.POOL_BUCKETS if b <= drain)
    for b in pool_widths:
        progs.append(Program(vk, b, priority=10, note="pool dispatch"))
    # 3. sync-committee fast aggregate (fastAggregateVerify path)
    progs.append(
        Program(
            "fast_agg",
            bk.bucket_size(SYNC_COMMITTEE_SIZE),
            priority=30,
            note="sync committee",
        )
    )
    if scope == "full":
        for b in pool_widths:
            progs.append(Program("each", b, priority=40, note="pool fallback"))
        widths = set(bk.BUCKETS) | set(bk.POOL_BUCKETS)
        widths |= set(
            range(bk.BUCKETS[-1], dp.MAX_SIGNATURE_SETS_PER_JOB + 1, 512)
        )
        for b in sorted(widths):
            for k in (vk, "each"):
                progs.append(Program(k, b, priority=50, note="full sweep"))
        for b in bk.BUCKETS:
            progs.append(Program("fast_agg", b, priority=60, note="full sweep"))
        # mesh-parameterized sharded verify (ops/bls12_381/sharded.py):
        # one entry per (bucket, mesh geometry) this host can actually
        # build — warming a sharded program on a host with too few
        # devices would abort the whole warm run, so the gate is on
        # live device count.  Full scope only: a cold sharded pairing
        # compile costs hours on XLA:CPU (docs/AOT.md).
        from lodestar_tpu.ops.bls12_381 import sharded as sh

        import jax

        n_dev = len(jax.devices())
        for m in sh.SUPPORTED_MESH_SIZES:
            if m > n_dev:
                continue
            for b in sh.SHARDED_BUCKETS:
                progs.append(
                    Program(
                        "sharded", b, priority=70, note="sharded verify",
                        mesh_size=m,
                    )
                )
    # dedupe by key, keeping the highest-priority (lowest number) entry
    seen: Dict[str, Program] = {}
    for p in sorted(progs, key=lambda p: p.priority):
        seen.setdefault(p.key, p)
    return sorted(seen.values(), key=lambda p: (p.priority, p.bucket))


def registered_keys(scope: str = "core", device_h2c: Optional[bool] = None) -> List[str]:
    return [p.key for p in registered_programs(scope, device_h2c)]
