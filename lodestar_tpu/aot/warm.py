"""Resumable AOT warmer + freshness manifest.

``warm_programs()`` lowers + compiles each registered program into the
persistent cache and records it in ``<cache>/warm_manifest.json``, one
entry per program, SAVED AFTER EVERY PROGRAM: on the 2-core driver host
a single pairing compile costs ~15-40 minutes, so a warm run killed by
an external timeout must bank every finished program — the next
invocation skips them (manifest fresh + cache entry on disk) and picks
up where it left off.

Manifest freshness is keyed by (backend, jax version, source
fingerprint): the fingerprint hashes the kernel-relevant sources
(ops/bls12_381, crypto/bls, aot), so editing a kernel invalidates
exactly the manifest — never the cache files themselves.  Nothing here
ever deletes ``.jax_cache`` entries; stale entries are merely
recompiled under their new keys.

Where the running jax supports ``jax.export``, each warmed program is
additionally serialized to ``<cache>/export/<kernel>_b<bucket>.bin``
(portable StableHLO, usable for cross-process AOT loading); failures
are recorded, not fatal.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import cache as aot_cache

MANIFEST_NAME = "warm_manifest.json"
SCHEMA = 2

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
# sources whose edits can change a compiled kernel (or what gets warmed)
SOURCE_DIRS = (
    "lodestar_tpu/ops/bls12_381",
    "lodestar_tpu/crypto/bls",
    "lodestar_tpu/aot",
)


def source_fingerprint() -> str:
    """sha256 over the kernel-relevant source tree (path + content)."""
    h = hashlib.sha256()
    for d in SOURCE_DIRS:
        root = os.path.join(_REPO_ROOT, d)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(x for x in dirnames if x != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), _REPO_ROOT)
                h.update(rel.encode())
                with open(os.path.join(dirpath, fn), "rb") as fh:
                    h.update(hashlib.sha256(fh.read()).digest())
    return h.hexdigest()


def environment_key() -> Dict[str, str]:
    import jax

    return {
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "source": source_fingerprint(),
    }


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def manifest_path(cache_dir: Optional[str] = None) -> str:
    return os.path.join(cache_dir or aot_cache.repo_cache_dir(), MANIFEST_NAME)


def load_manifest(cache_dir: Optional[str] = None) -> Dict:
    path = manifest_path(cache_dir)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError):
        data = {}
    if data.get("schema") != SCHEMA:
        data = {"schema": SCHEMA, "entries": {}}
    data.setdefault("entries", {})
    return data


def save_manifest(manifest: Dict, cache_dir: Optional[str] = None) -> None:
    """Atomic write (tmp + rename): a killed warm run must never leave
    a half-written manifest that voids earlier banked programs."""
    path = manifest_path(cache_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def program_state(
    prog, manifest: Dict, cache_dir: str, envk: Dict[str, str]
) -> str:
    """"warm" | "stale" | "missing" for one registered program."""
    entry = manifest.get("entries", {}).get(prog.key)
    if entry is None:
        return "missing"
    for k in ("backend", "jax", "source"):
        if entry.get(k) != envk[k]:
            return "stale"
    keys = entry.get("cache_keys") or []
    # entries warmed before the spy captured a key are trusted on
    # manifest freshness alone; captured keys are verified on disk
    if keys and not all(aot_cache.entry_exists(cache_dir, k) for k in keys):
        return "missing"
    return "warm"


# ---------------------------------------------------------------------------
# warming
# ---------------------------------------------------------------------------


def _try_export(prog, cache_dir: str) -> Tuple[Optional[str], Optional[str]]:
    """Serialize via jax.export where supported; (path, error)."""
    try:
        from jax import export as jexport
    except ImportError:  # old jax: no export API
        return None, "jax.export unavailable"
    try:
        exported = jexport.export(prog.fn())(*prog.example_args())
        blob = exported.serialize()
        out_dir = os.path.join(cache_dir, "export")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"{prog.kernel}_b{prog.bucket}.bin")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, path)
        return path, None
    except Exception as e:  # serialization is best-effort by design
        return None, f"{type(e).__name__}: {e}"


def warm_program(prog, cache_dir: str, do_export: bool = True) -> Dict:
    """Lower + compile ONE program (hitting the persistent cache when
    the entry already exists) and return its manifest entry."""
    aot_cache.install_cache_spy()
    before = set(aot_cache.observed_keys())
    t0 = time.monotonic()
    lowered = prog.fn().lower(*prog.example_args())
    lower_s = time.monotonic() - t0
    t1 = time.monotonic()
    lowered.compile()
    compile_s = time.monotonic() - t1
    prefix = f"jit_{prog.fn_name()}-"
    events = {
        k: kind
        for k, kind in aot_cache.observed_keys().items()
        if k not in before and k.startswith(prefix)
    }
    hit = any(kind == "hit" for kind in events.values())
    entry = {
        "kernel": prog.kernel,
        "bucket": prog.bucket,
        "cache_keys": sorted(events),
        "cache_hit": hit,
        "lower_s": round(lower_s, 3),
        "compile_s": round(compile_s, 3),
        "warmed_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if do_export:
        path, err = _try_export(prog, cache_dir)
        if path:
            entry["export"] = os.path.basename(path)
        if err:
            entry["export_error"] = err
    return entry


def warm_programs(
    programs: Sequence,
    cache_dir: Optional[str] = None,
    *,
    budget_s: Optional[float] = None,
    min_compile_time_secs: float = aot_cache.DEFAULT_MIN_COMPILE_SECS,
    do_export: bool = True,
    log=print,
) -> Dict:
    """Warm every program not already fresh, in priority order, saving
    the manifest after EACH program.  ``budget_s`` stops before
    STARTING a program that no longer fits (a started compile runs to
    completion — killing it would bank nothing); the FIRST pending
    program always starts, so even an undersized budget makes forward
    progress across repeated invocations."""
    cache_dir = aot_cache.configure(
        cache_dir, min_compile_time_secs=min_compile_time_secs
    )
    envk = environment_key()
    manifest = load_manifest(cache_dir)
    t0 = time.monotonic()
    report = {"compiled": [], "skipped": [], "deferred": [], "cache_dir": cache_dir}
    for prog in programs:
        state = program_state(prog, manifest, cache_dir, envk)
        if state == "warm":
            report["skipped"].append(prog.key)
            log(f"aot warm: {prog.key} already warm — skipped")
            continue
        if (
            budget_s is not None
            and report["compiled"]
            and time.monotonic() - t0 > budget_s
        ):
            report["deferred"].append(prog.key)
            continue
        log(f"aot warm: compiling {prog.key} ({state}) ...")
        entry = warm_program(prog, cache_dir, do_export=do_export)
        entry.update(envk)
        manifest["entries"][prog.key] = entry
        save_manifest(manifest, cache_dir)  # bank immediately
        report["compiled"].append(prog.key)
        log(
            f"aot warm: {prog.key} done in {entry['compile_s']:.1f}s compile "
            f"(+{entry['lower_s']:.1f}s lower, persistent-cache "
            f"{'HIT' if entry['cache_hit'] else 'miss'})"
        )
    if report["deferred"]:
        log(
            "aot warm: budget exhausted — deferred "
            + ", ".join(report["deferred"])
            + " (re-run to continue; finished programs are banked)"
        )
    return report


def check_programs(
    programs: Sequence, cache_dir: Optional[str] = None
) -> Tuple[bool, List[Tuple[str, str]]]:
    """(all_warm, [(program key, state)]).  Read-only: no compiles, no
    lowering — manifest freshness + on-disk cache entries only."""
    cache_dir = cache_dir or aot_cache.repo_cache_dir()
    envk = environment_key()
    manifest = load_manifest(cache_dir)
    rows = [
        (p.key, program_state(p, manifest, cache_dir, envk)) for p in programs
    ]
    return all(state == "warm" for _, state in rows), rows
