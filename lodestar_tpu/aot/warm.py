"""Resumable AOT warmer + freshness manifest.

``warm_programs()`` lowers + compiles each registered program into the
persistent cache and records it in ``<cache>/warm_manifest.json``, one
entry per program, SAVED AFTER EVERY PROGRAM: on the 2-core driver host
a single pairing compile costs ~15-40 minutes, so a warm run killed by
an external timeout must bank every finished program — the next
invocation skips them (manifest fresh + cache entry on disk) and picks
up where it left off.

Manifest freshness is keyed by (backend, jax version, source
fingerprint): the fingerprint hashes the kernel-relevant sources
(ops/bls12_381, crypto/bls, aot), so editing a kernel invalidates
exactly the manifest — never the cache files themselves.  Nothing here
ever deletes ``.jax_cache`` entries; stale entries are merely
recompiled under their new keys.

Where the running jax supports ``jax.export``, each warmed program is
additionally serialized to ``<cache>/export/<kernel>_b<bucket>.bin``
(portable StableHLO, usable for cross-process AOT loading); failures
are recorded, not fatal.
"""
from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

from . import cache as aot_cache

MANIFEST_NAME = "warm_manifest.json"
SCHEMA = 2

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
# sources whose edits can change a compiled kernel (or what gets warmed)
SOURCE_DIRS = (
    "lodestar_tpu/ops/bls12_381",
    "lodestar_tpu/crypto/bls",
    "lodestar_tpu/aot",
)


def source_fingerprint() -> str:
    """sha256 over the kernel-relevant source tree (path + content)."""
    h = hashlib.sha256()
    for d in SOURCE_DIRS:
        root = os.path.join(_REPO_ROOT, d)
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(x for x in dirnames if x != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), _REPO_ROOT)
                h.update(rel.encode())
                with open(os.path.join(dirpath, fn), "rb") as fh:
                    h.update(hashlib.sha256(fh.read()).digest())
    return h.hexdigest()


def environment_key() -> Dict[str, str]:
    import jax

    return {
        "backend": jax.default_backend(),
        "jax": jax.__version__,
        "source": source_fingerprint(),
    }


# ---------------------------------------------------------------------------
# manifest
# ---------------------------------------------------------------------------


def manifest_path(cache_dir: Optional[str] = None) -> str:
    return os.path.join(cache_dir or aot_cache.repo_cache_dir(), MANIFEST_NAME)


def load_manifest(cache_dir: Optional[str] = None) -> Dict:
    path = manifest_path(cache_dir)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError):
        data = {}
    if data.get("schema") != SCHEMA:
        data = {"schema": SCHEMA, "entries": {}}
    data.setdefault("entries", {})
    return data


def save_manifest(manifest: Dict, cache_dir: Optional[str] = None) -> None:
    """Atomic write (tmp + rename): a killed warm run must never leave
    a half-written manifest that voids earlier banked programs."""
    path = manifest_path(cache_dir)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(manifest, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def mismatched_entry_keys(entry: Dict, cache_dir: str) -> List[str]:
    """Cache keys of ``entry`` whose on-disk bytes no longer match the
    sha256 recorded at warm time (reads + hashes each entry file)."""
    out = []
    hashes = entry.get("entry_sha256") or {}
    for k in entry.get("cache_keys") or []:
        recorded = hashes.get(k)
        if not recorded:
            continue  # warmed before hashes were recorded: trusted
        paths = aot_cache.entry_paths(cache_dir, k)
        if paths and _file_sha256(paths[0]) != recorded:
            out.append(k)
    return out


def program_state(
    prog, manifest: Dict, cache_dir: str, envk: Dict[str, str],
    check_hashes: bool = True,
) -> str:
    """"warm" | "stale" | "missing" | "corrupt" for one registered
    program.  "corrupt" means the entry file EXISTS but its bytes no
    longer match the sha256 recorded at warm time — the
    poisoned-cache-entry class ``--check`` previously could not see
    (an entry that exists but cannot deserialize looked "warm").

    ``check_hashes=False`` skips the content hashing and reports such
    entries as "warm": existence/freshness checks are stat-cheap, but
    hashing reads every entry file (hundreds of MB for the pairing
    programs) — callers that only need a freshness gauge (the pool's
    startup probe) must not pay that on a 2-core host."""
    entry = manifest.get("entries", {}).get(prog.key)
    if entry is None:
        return "missing"
    for k in ("backend", "jax", "source"):
        if entry.get(k) != envk[k]:
            return "stale"
    keys = entry.get("cache_keys") or []
    # entries warmed before the spy captured a key are trusted on
    # manifest freshness alone; captured keys are verified on disk
    if keys and not all(aot_cache.entry_exists(cache_dir, k) for k in keys):
        return "missing"
    if check_hashes and mismatched_entry_keys(entry, cache_dir):
        return "corrupt"
    return "warm"


# ---------------------------------------------------------------------------
# warming
# ---------------------------------------------------------------------------


def _try_export(prog, cache_dir: str) -> Tuple[Optional[str], Optional[str]]:
    """Serialize via jax.export where supported; (path, error)."""
    try:
        from jax import export as jexport
    except ImportError:  # old jax: no export API
        return None, "jax.export unavailable"
    try:
        exported = jexport.export(prog.fn())(*prog.example_args())
        blob = exported.serialize()
        out_dir = os.path.join(cache_dir, "export")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"{prog.kernel}_b{prog.bucket}.bin")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, path)
        return path, None
    except Exception as e:  # serialization is best-effort by design
        return None, f"{type(e).__name__}: {e}"


def warm_program(prog, cache_dir: str, do_export: bool = True) -> Dict:
    """Lower + compile ONE program (hitting the persistent cache when
    the entry already exists) and return its manifest entry."""
    prefix = f"jit_{prog.fn_name()}-"
    # scoped event capture: a per-call callback (not a global observed-
    # keys delta, which is empty when the same program was already
    # touched earlier in this process — e.g. warm followed by heal)
    events: Dict[str, str] = {}

    def _capture(kind: str, key: str, seconds: float) -> None:
        if key.startswith(prefix):
            events[key] = kind

    aot_cache.install_cache_spy(_capture)
    try:
        t0 = time.monotonic()
        lowered = prog.fn().lower(*prog.example_args())
        lower_s = time.monotonic() - t0
        t1 = time.monotonic()
        lowered.compile()
        compile_s = time.monotonic() - t1
    finally:
        aot_cache.remove_cache_spy_callback(_capture)
    hit = any(kind == "hit" for kind in events.values())
    # content fingerprint of each entry file: ``--check`` compares these
    # so an entry that later rots on disk reports "corrupt", not "warm"
    entry_sha = {}
    for k in events:
        paths = aot_cache.entry_paths(cache_dir, k)
        if paths:
            entry_sha[k] = _file_sha256(paths[0])
    entry = {
        "kernel": prog.kernel,
        "bucket": prog.bucket,
        "cache_keys": sorted(events),
        "cache_hit": hit,
        "entry_sha256": entry_sha,
        "lower_s": round(lower_s, 3),
        "compile_s": round(compile_s, 3),
        "warmed_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    if do_export:
        path, err = _try_export(prog, cache_dir)
        if path:
            entry["export"] = os.path.basename(path)
        if err:
            entry["export_error"] = err
    return entry


def warm_programs(
    programs: Sequence,
    cache_dir: Optional[str] = None,
    *,
    budget_s: Optional[float] = None,
    min_compile_time_secs: float = aot_cache.DEFAULT_MIN_COMPILE_SECS,
    do_export: bool = True,
    log=print,
) -> Dict:
    """Warm every program not already fresh, in priority order, saving
    the manifest after EACH program.  ``budget_s`` stops before
    STARTING a program that no longer fits (a started compile runs to
    completion — killing it would bank nothing); the FIRST pending
    program always starts, so even an undersized budget makes forward
    progress across repeated invocations."""
    cache_dir = aot_cache.configure(
        cache_dir, min_compile_time_secs=min_compile_time_secs
    )
    envk = environment_key()
    manifest = load_manifest(cache_dir)
    t0 = time.monotonic()
    report = {"compiled": [], "skipped": [], "deferred": [], "cache_dir": cache_dir}
    for prog in programs:
        state = program_state(prog, manifest, cache_dir, envk)
        if state == "warm":
            report["skipped"].append(prog.key)
            log(f"aot warm: {prog.key} already warm — skipped")
            continue
        if (
            budget_s is not None
            and report["compiled"]
            and time.monotonic() - t0 > budget_s
        ):
            report["deferred"].append(prog.key)
            continue
        log(f"aot warm: compiling {prog.key} ({state}) ...")
        entry = warm_program(prog, cache_dir, do_export=do_export)
        entry.update(envk)
        manifest["entries"][prog.key] = entry
        save_manifest(manifest, cache_dir)  # bank immediately
        report["compiled"].append(prog.key)
        log(
            f"aot warm: {prog.key} done in {entry['compile_s']:.1f}s compile "
            f"(+{entry['lower_s']:.1f}s lower, persistent-cache "
            f"{'HIT' if entry['cache_hit'] else 'miss'})"
        )
    if report["deferred"]:
        log(
            "aot warm: budget exhausted — deferred "
            + ", ".join(report["deferred"])
            + " (re-run to continue; finished programs are banked)"
        )
    return report


def check_programs(
    programs: Sequence,
    cache_dir: Optional[str] = None,
    *,
    check_hashes: bool = True,
) -> Tuple[bool, List[Tuple[str, str]]]:
    """(all_warm, [(program key, state)]).  Read-only: no compiles, no
    lowering — manifest freshness + on-disk cache entries (existence
    and, unless ``check_hashes=False``, content hash)."""
    cache_dir = cache_dir or aot_cache.repo_cache_dir()
    envk = environment_key()
    manifest = load_manifest(cache_dir)
    rows = [
        (p.key, program_state(p, manifest, cache_dir, envk, check_hashes))
        for p in programs
    ]
    return all(state == "warm" for _, state in rows), rows


def refresh_entry_hash(cache_dir: str, cache_key: str) -> bool:
    """Re-stamp the manifest's ``entry_sha256`` for every program whose
    entry was just rewritten under ``cache_key``.

    Called by the cache spy after an in-process self-heal (load failure
    → quarantine → recompile → put): the fresh bytes are NOT guaranteed
    to match the hash recorded at warm time, and without this re-stamp
    the next ``warm --check`` would cry "corrupt" over a healthy entry
    — and ``--heal`` would re-pay the multi-minute compile for nothing.
    Returns True if any manifest entry was updated.

    Takes the warm tool's ``.aot.lock`` (non-blocking): a concurrent
    resumable warm run banks manifest entries program-by-program, and a
    lockless read-modify-write here could overwrite an entry it just
    banked (voiding a 40 min-2 h compile).  If the lock is busy, skip —
    the re-stamp is best-effort and ``warm --heal`` repairs a stale
    hash later anyway."""
    import fcntl

    paths = aot_cache.entry_paths(cache_dir, cache_key)
    if not paths:
        return False
    try:
        lock_fh = open(os.path.join(cache_dir, ".aot.lock"), "w")
    except OSError:
        return False
    try:
        try:
            fcntl.flock(lock_fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            return False  # a warm run owns the manifest right now
        manifest = load_manifest(cache_dir)
        new_hash = _file_sha256(paths[0])
        changed = False
        for entry in manifest.get("entries", {}).values():
            hashes = entry.get("entry_sha256")
            if hashes and cache_key in hashes and hashes[cache_key] != new_hash:
                hashes[cache_key] = new_hash
                changed = True
        if changed:
            save_manifest(manifest, cache_dir)
        return changed
    finally:
        lock_fh.close()


# ---------------------------------------------------------------------------
# healing (``warm --heal``)
# ---------------------------------------------------------------------------


def heal_programs(
    programs: Sequence,
    cache_dir: Optional[str] = None,
    *,
    budget_s: Optional[float] = None,
    min_compile_time_secs: float = aot_cache.DEFAULT_MIN_COMPILE_SECS,
    do_export: bool = True,
    log=print,
) -> Dict:
    """Load-round-trip every registered program; quarantine entries
    that are corrupt on disk or fail deserialization; recompile what
    was quarantined or missing.  Healthy entries are NOT rewritten (the
    round-trip is a persistent-cache HIT, which never touches the
    file).

    Two corruption detectors compose here:

    * the manifest's ``entry_sha256`` catches byte rot / truncation
      against the fingerprint recorded at warm time (also what makes
      ``--check`` honest), and
    * the spy's load-error path catches entries whose bytes LOOK intact
      but still fail jax deserialization — those are quarantined by the
      spy mid-compile and rewritten by the put that follows.

    ``budget_s`` mirrors warm_programs: stop before STARTING a
    round-trip that no longer fits (the first program always runs, the
    manifest banks after each, and deferred programs are listed so a
    re-invocation continues).

    Report keys: ``healthy`` (round-tripped clean), ``healed``
    (quarantined + recompiled), ``stale_rewarmed`` (manifest stale or
    entry missing — recompiled), ``quarantined`` (files moved aside),
    ``deferred`` (budget ran out first).
    """
    cache_dir = aot_cache.configure(
        cache_dir, min_compile_time_secs=min_compile_time_secs
    )
    aot_cache.install_cache_spy()
    envk = environment_key()
    manifest = load_manifest(cache_dir)
    t0 = time.monotonic()
    report = {
        "healthy": [],
        "healed": [],
        "stale_rewarmed": [],
        "quarantined": [],
        "deferred": [],
        "cache_dir": cache_dir,
    }
    started = 0
    for prog in programs:
        if (
            budget_s is not None
            and started
            and time.monotonic() - t0 > budget_s
        ):
            report["deferred"].append(prog.key)
            continue
        started += 1
        # one hash pass, not two: classify WITHOUT hashing, then hash
        # each file exactly once to find what needs quarantining
        state = program_state(
            prog, manifest, cache_dir, envk, check_hashes=False
        )
        entry = manifest.get("entries", {}).get(prog.key) or {}
        if state == "warm":
            bad_keys = mismatched_entry_keys(entry, cache_dir)
            if bad_keys:
                state = "corrupt"
                # quarantine BEFORE the round-trip so jax can't load
                # the bad bytes; recompile then rewrites a fresh entry
                for k in bad_keys:
                    moved = aot_cache.quarantine_entry(cache_dir, k)
                    if moved:
                        report["quarantined"].append(moved)
                        log(f"aot heal: quarantined corrupt entry {k} -> {moved}")
        errors_before = aot_cache.cache_stats().get("load_errors", 0)
        q_before = set(aot_cache.quarantined_files(cache_dir))
        log(f"aot heal: round-tripping {prog.key} ({state}) ...")
        new_entry = warm_program(prog, cache_dir, do_export=do_export)
        new_entry.update(envk)
        manifest["entries"][prog.key] = new_entry
        save_manifest(manifest, cache_dir)  # bank immediately
        load_errors = aot_cache.cache_stats().get("load_errors", 0) - errors_before
        # the spy quarantines undeserializable bytes mid-round-trip;
        # report whatever newly landed in the quarantine dir
        report["quarantined"].extend(
            sorted(set(aot_cache.quarantined_files(cache_dir)) - q_before)
        )
        if state == "corrupt" or load_errors:
            report["healed"].append(prog.key)
            log(f"aot heal: {prog.key} healed (recompiled)")
        elif state == "warm" and new_entry.get("cache_hit"):
            report["healthy"].append(prog.key)
        else:
            report["stale_rewarmed"].append(prog.key)
            log(f"aot heal: {prog.key} was {state} — re-warmed")
    if report["deferred"]:
        log(
            "aot heal: budget exhausted — deferred "
            + ", ".join(report["deferred"])
            + " (re-run to continue)"
        )
    return report
