"""BLS sidecar server: tenancy, fairness, cross-tenant coalescing.

One ``BlsPoolServer`` fronts one inner ``BlsVerifier`` — the device
pool (``DeviceBlsVerifier``) where an accelerator exists, the host
oracle otherwise — and serves N tenant nodes.  The multi-tenant
intelligence lives HERE, not in the inner pool:

* **admission** — per-tenant GCRA (``reqresp/rate_limiter.py``) with
  request weight = signature-set count, so one tenant's flood is shed
  at the door while light tenants keep their full quota, plus a
  pool-wide pending-sets bound (backpressure) so an admitted backlog
  can never grow without limit;
* **coalescing** — admitted requests buffer for a short window and
  dispatch as ONE batch across tenants.  Width quantization stays the
  inner pool's job (``buckets.pool_bucket`` — the coalescer can only
  ever produce widths the AOT warm registry knows because the only
  dispatch path is ``DeviceBlsVerifier.verify_signature_sets``); the
  coalescer's contribution is filling rungs no single tenant's offered
  load can fill.  A ``False`` batch verdict re-verifies per REQUEST so
  one tenant's invalid set cannot poison another tenant's verdict;
* **degradation stamping** — every response carries
  ``degradation_tier``/``breaker_state`` read from the inner pool's
  circuit breaker, so a tenant can tell device verdicts from host
  fallbacks (the PR 7 contract, extended across the wire).

Fault checkpoints (docs/FAULTS.md): ``blspool.rpc.respond`` at request
ingress (Delay stalls the response, any other FaultError makes the
binding surface a transport-level error — the shape of a crashing
server) and ``blspool.batch.coalesce`` at batch formation (a fault
fails the batch servably: every waiter gets an error RESPONSE and the
client-side ladder takes over).
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from lodestar_tpu.chain.bls import breaker as brk
from lodestar_tpu.chain.bls.device_pool import MAX_SIGNATURE_SETS_PER_JOB
from lodestar_tpu.chain.bls.interface import VerifyOptions
from lodestar_tpu.chain.bls.single_thread import SingleThreadBlsVerifier
from lodestar_tpu.crypto.bls.api import SignatureSet
from lodestar_tpu.network.reqresp.rate_limiter import RateLimiterGCRA
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import gather_settled, get_logger
from . import codec
from .metrics import BlsPoolSidecarMetrics

PROTOCOL_ID = "/lodestar_tpu/blspool/verify/1"

# Per-tenant admission: sets per window.  A single tenant at the
# steady-state gossip firehose offers ~500 sets/s; the default leaves
# each tenant that much headroom while a flood (weight > the whole
# burst window) is rejected outright — without ever mutating the
# tenant's TAT, so a shed flood cannot poison its OWN future quota
# (pinned by tests/test_blspool.py::TestGcraWeightSemantics).
DEFAULT_TENANT_QUOTA = (2048, 2_000)

# Coalescing window: long enough to collect concurrent tenants' bursts
# into one rung, short next to the inner pool's own 100 ms batching
# window (the two windows pipeline, they do not add for steady flow).
COALESCE_WAIT_MS = 10


@dataclass
class _PendingRequest:
    tenant: str
    sets: List[SignatureSet]
    future: "asyncio.Future[dict]"  # resolves to response-body kwargs


class BlsPoolServer:
    """Transport-agnostic sidecar core: both bindings (fabric reqresp,
    HTTP) feed ``handle_payload`` and return its bytes verbatim."""

    def __init__(
        self,
        verifier=None,
        *,
        metrics: Optional[BlsPoolSidecarMetrics] = None,
        tenant_quota: Tuple[int, int] = DEFAULT_TENANT_QUOTA,
        weights: Optional[Dict[str, float]] = None,
        coalesce_wait_ms: float = COALESCE_WAIT_MS,
        max_sets_per_batch: int = MAX_SIGNATURE_SETS_PER_JOB,
        max_pending_sets: Optional[int] = None,
        now=time.monotonic,
    ):
        self._verifier = verifier if verifier is not None else SingleThreadBlsVerifier()
        # per-tenant quota weighting (ROADMAP item 4): a tenant with
        # weight w advances its TAT by 1/w emission intervals per
        # admitted set, so it sustains w× the base quota under
        # contention — and an over-weight burst still sheds without TAT
        # mutation (tests/test_blspool.py::TestTenantWeighting).
        self._limiter = RateLimiterGCRA(
            tenant_quota[0], tenant_quota[1], now=now, shares=weights
        )
        self._metrics = metrics
        self._coalesce_wait_s = coalesce_wait_ms / 1000
        self._max_sets_per_batch = max_sets_per_batch
        # backpressure bound: two full batches of admitted-but-unserved
        # sets is overload — shedding is cheaper than unbounded latency
        self._max_pending_sets = (
            max_pending_sets if max_pending_sets is not None
            else 2 * max_sets_per_batch
        )
        self._pending: List[_PendingRequest] = []
        self._pending_sets = 0
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._closed = False
        self._tasks: Set[asyncio.Task] = set()
        self._log = get_logger("blspool")
        # per-batch (width, distinct-tenant count) — the swarm proof
        # asserts on this (and the width histogram mirrors it)
        self.batch_log: List[Tuple[int, int]] = []
        self.shed_log: List[str] = []  # tenant per shed, in order

    # -- bindings ------------------------------------------------------

    def attach(self, fabric) -> None:
        """Serve over a MeshFabric (loopback for swarms, TCP+noise for
        deployment — the transport conformance suite covers both)."""
        fabric.handle(PROTOCOL_ID, self._handle_rpc)

    async def _handle_rpc(self, from_peer: str, proto: str, data: bytes) -> bytes:
        return await self.handle_payload(from_peer, data)

    # -- request path --------------------------------------------------

    async def handle_payload(self, default_tenant: str, data: bytes) -> bytes:
        """One request's bytes in, one response's bytes out.  Raises
        only for an injected ``blspool.rpc.respond`` fault (the binding
        turns that into its transport-level error shape)."""
        try:
            tenant, sets, _batchable = codec.decode_request(data)
        except codec.CodecError as e:
            return codec.encode_response(
                ok=False, error=f"{codec.ERR_BAD_REQUEST}: {e}"
            )
        tenant = tenant or default_tenant
        try:
            faults.fire("blspool.rpc.respond", tenant=tenant, sets=len(sets))
        except faults.Delay as d:
            await asyncio.sleep(d.seconds)
        if self._metrics:
            self._metrics.requests_total.labels(tenant=tenant).inc()
            if sets:
                self._metrics.sets_total.labels(tenant=tenant).inc(len(sets))
        if self._closed:
            return codec.encode_response(ok=False, error=codec.ERR_SERVER_CLOSED)
        if not sets:
            # the BlsVerifier contract: empty input is a False verdict
            tier, state = self._stamp()
            return codec.encode_response(
                ok=True, valid=False, degradation_tier=tier, breaker_state=state
            )

        # admission: GCRA fairness (weight = set count) then backpressure
        if not self._limiter.allows(tenant, weight=len(sets)):
            return self._shed(tenant, codec.ERR_RATE_LIMITED)
        if self._pending_sets + len(sets) > self._max_pending_sets:
            return self._shed(tenant, codec.ERR_OVERLOADED)

        req = _PendingRequest(
            tenant=tenant,
            sets=sets,
            future=asyncio.get_running_loop().create_future(),
        )
        self._pending.append(req)
        self._pending_sets += len(sets)
        if self._metrics:
            self._metrics.pending_sets.set(self._pending_sets)
        if self._pending_sets >= self._max_sets_per_batch:
            self._schedule_flush(0)
        elif self._flush_handle is None:
            self._schedule_flush(self._coalesce_wait_s)
        body = await req.future
        return codec.encode_response(**body)

    def _shed(self, tenant: str, error: str) -> bytes:
        self.shed_log.append(tenant)
        if self._metrics:
            self._metrics.shed_total.labels(tenant=tenant).inc()
        return codec.encode_response(ok=False, error=error)

    # -- coalescing ----------------------------------------------------

    def _schedule_flush(self, delay: float) -> None:
        loop = asyncio.get_running_loop()
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self._flush_handle = loop.call_later(delay, self._flush)

    def _flush(self) -> None:
        """Work-conserving: take the whole backlog (up to the batch
        cap) as ONE cross-tenant batch; anything left re-arms."""
        self._flush_handle = None
        if self._closed or not self._pending:
            return
        batch: List[_PendingRequest] = []
        count = 0
        while self._pending:
            req = self._pending[0]
            if batch and count + len(req.sets) > self._max_sets_per_batch:
                break
            batch.append(self._pending.pop(0))
            count += len(req.sets)
        self._pending_sets -= count
        if self._metrics:
            self._metrics.pending_sets.set(self._pending_sets)
        task = asyncio.ensure_future(self._run_batch(batch))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        if self._pending:
            self._schedule_flush(self._coalesce_wait_s)

    async def _run_batch(self, batch: List[_PendingRequest]) -> None:
        all_sets: List[SignatureSet] = []
        tenants: Set[str] = set()
        for req in batch:
            all_sets.extend(req.sets)
            tenants.add(req.tenant)
        width, n_tenants = len(all_sets), len(tenants)
        try:
            faults.fire("blspool.batch.coalesce", width=width, tenants=n_tenants)
        except faults.Delay as d:
            await asyncio.sleep(d.seconds)
        except faults.FaultError as e:
            # chaos: the batch fails SERVABLY — error responses, never
            # stranded waiters (the client ladder retries or degrades)
            self._fail_batch(batch, f"{codec.ERR_VERIFY_FAILED}: {e}")
            return
        self.batch_log.append((width, n_tenants))
        if self._metrics:
            self._metrics.batches_total.inc()
            self._metrics.batch_width.observe(width)
            self._metrics.batch_tenants.observe(n_tenants)
        try:
            verdict = await self._verifier.verify_signature_sets(
                all_sets, VerifyOptions(batchable=True)
            )
            per_req: List[bool]
            if verdict:
                per_req = [True] * len(batch)
            else:
                # per-REQUEST split: tenant isolation for verdicts too —
                # re-verification rides the inner pool's own batch path
                per_req = await gather_settled(
                    *(
                        self._verifier.verify_signature_sets(
                            req.sets, VerifyOptions(batchable=True)
                        )
                        for req in batch
                    )
                )
        except asyncio.CancelledError:
            self._fail_batch(batch, codec.ERR_SERVER_CLOSED)
            raise
        except Exception as e:
            self._log.warn(
                f"inner verifier failed a coalesced batch "
                f"(width={width}): {type(e).__name__}: {e}"
            )
            self._fail_batch(batch, f"{codec.ERR_VERIFY_FAILED}: {type(e).__name__}")
            return
        tier, state = self._stamp()
        if self._metrics:
            self._metrics.responses_total.labels(tier=tier).inc(len(batch))
        for req, ok in zip(batch, per_req):
            if not req.future.done():
                req.future.set_result(
                    dict(
                        ok=True,
                        valid=bool(ok),
                        degradation_tier=tier,
                        breaker_state=state,
                        coalesced_width=width,
                        coalesced_tenants=n_tenants,
                    )
                )

    def _fail_batch(self, batch: List[_PendingRequest], error: str) -> None:
        for req in batch:
            if not req.future.done():
                req.future.set_result(dict(ok=False, error=error))

    # -- degradation stamp ---------------------------------------------

    def _stamp(self) -> Tuple[str, str]:
        """(degradation_tier, breaker_state) for a response.  Read from
        the inner pool's breaker: ``device`` only while the breaker is
        closed (verdicts ride the device), ``host`` otherwise — and
        ALWAYS ``host`` for a breaker-less oracle, so a sidecar without
        a device can never masquerade as device throughput."""
        breaker = getattr(self._verifier, "_breaker", None)
        if breaker is None:
            return brk.TIER_HOST, brk.CLOSED
        state = breaker.state
        tier = brk.TIER_DEVICE if state == brk.CLOSED else brk.TIER_HOST
        return tier, state

    # -- lifecycle -----------------------------------------------------

    def prune(self, older_than_ms: float = 60_000) -> None:
        """Drop idle tenants' TAT state (the reqresp heartbeat idiom)."""
        self._limiter.prune(older_than_ms)

    async def close(self) -> None:
        """Cancel-and-settle: pending requests get error RESPONSES (the
        client degrades locally), in-flight batch tasks are awaited, and
        the inner verifier is shut down."""
        self._closed = True
        if self._flush_handle is not None:
            self._flush_handle.cancel()
            self._flush_handle = None
        for req in self._pending:
            if not req.future.done():
                req.future.set_result(
                    dict(ok=False, error=codec.ERR_SERVER_CLOSED)
                )
        self._pending.clear()
        self._pending_sets = 0
        tasks = [t for t in self._tasks if not t.done()]
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        await self._verifier.close()
