"""Second-process sidecar entry::

    python -m lodestar_tpu.blspool serve --port 0 --verifier auto

prints ``{"url": ..., "port": ...}`` on stdout once listening (the
tests/test_cli_node.py announce idiom), then serves until killed.
``--verifier auto`` resolves exactly like the beacon CLI: the device
pool when an accelerator backend is live, the host oracle otherwise.
"""
from __future__ import annotations

import argparse
import json


def build_inner_verifier(choice: str):
    from lodestar_tpu.cli.main import resolve_verifier_choice

    if resolve_verifier_choice(choice) == "device":
        from lodestar_tpu.chain.bls import DeviceBlsVerifier
        from lodestar_tpu.chain.bls.metrics import BlsPoolMetrics

        return DeviceBlsVerifier(metrics=BlsPoolMetrics.get())
    from lodestar_tpu.chain.bls import SingleThreadBlsVerifier

    return SingleThreadBlsVerifier()


def main(argv=None) -> int:
    import asyncio

    from .http import BlsPoolHttpServer
    from .metrics import BlsPoolSidecarMetrics
    from .server import DEFAULT_TENANT_QUOTA, BlsPoolServer

    parser = argparse.ArgumentParser(prog="python -m lodestar_tpu.blspool")
    sub = parser.add_subparsers(dest="command")
    serve = sub.add_parser("serve", help="serve the BLS pool over HTTP")
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--port", type=int, default=0)
    serve.add_argument(
        "--verifier", choices=["auto", "oracle", "device"], default="auto"
    )
    serve.add_argument(
        "--tenant-quota", type=int, default=DEFAULT_TENANT_QUOTA[0],
        help="per-tenant admitted signature sets per quota window",
    )
    serve.add_argument(
        "--tenant-quota-ms", type=int, default=DEFAULT_TENANT_QUOTA[1],
        help="GCRA quota window in milliseconds",
    )
    args = parser.parse_args(argv)
    if args.command != "serve":
        parser.print_help()
        return 2

    server = BlsPoolServer(
        build_inner_verifier(args.verifier),
        metrics=BlsPoolSidecarMetrics.get(),
        tenant_quota=(args.tenant_quota, args.tenant_quota_ms),
    )
    http = BlsPoolHttpServer(server)

    async def run():
        url = await http.start(args.host, args.port)
        print(json.dumps({"url": url, "port": http.port}), flush=True)
        try:
            while True:
                await asyncio.sleep(3600)
        finally:
            await http.close()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
