"""Multi-tenant BLS verification sidecar (docs/BLSPOOL.md).

The paper's literal north star — "a JAX sidecar that runs batched
pairings on TPU" — served to N beacon nodes behind the IBlsVerifier
boundary (chain/bls/interface.py).  One process owns the device pool;
every tenant node plugs a ``RemoteBlsVerifier`` into its ``BeaconChain``
unchanged and the server coalesces cross-tenant traffic into the same
AOT bucket rungs no single node's offered load can fill.

* ``server.BlsPoolServer``  — tenancy, GCRA fairness, cross-tenant
  coalescing, degradation stamping; binds to a MeshFabric protocol or
  the HTTP endpoint in ``http.py``.
* ``client.RemoteBlsVerifier`` — the BlsVerifier implementation a
  tenant runs; degrades to the local host oracle when the sidecar is
  unreachable (never throws).
* ``codec``  — the JSON wire schema shared by both bindings.
* ``python -m lodestar_tpu.blspool serve`` — the second-process entry
  (``__main__.py``), announced-port idiom of testing/mock_el_server.py.
"""
from .client import TIER_LOCAL_HOST, FabricPoolTransport, RemoteBlsVerifier
from .codec import CodecError
from .metrics import BlsPoolSidecarMetrics
from .server import PROTOCOL_ID, BlsPoolServer

__all__ = [
    "BlsPoolServer",
    "BlsPoolSidecarMetrics",
    "CodecError",
    "FabricPoolTransport",
    "PROTOCOL_ID",
    "RemoteBlsVerifier",
    "TIER_LOCAL_HOST",
]
