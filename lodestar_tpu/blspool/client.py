"""RemoteBlsVerifier: a tenant's end of the BLS sidecar.

Implements the ``BlsVerifier`` Protocol (chain/bls/interface.py) so a
``BeaconChain`` plugs in unchanged.  The degradation contract is the
PR 7 ladder extended one hop outward: sidecar unreachable (or
shedding, or erroring) → bounded retry → the LOCAL host oracle — a
waiter always gets a boolean verdict, never a transport exception.
Every hop is stamped: remote verdicts carry the server's
``degradation_tier``/``breaker_state``; local fallbacks stamp
``TIER_LOCAL_HOST``, so a tenant quietly living off its own CPU cannot
masquerade as device throughput (``last_stamp`` + the
``client_local_fallbacks_total`` counter).

Fault checkpoint (docs/FAULTS.md): ``blspool.rpc.request`` fires per
send attempt — a Drop loses that attempt (retry, then degrade), a
Delay stalls it.
"""
from __future__ import annotations

import asyncio
from typing import Optional, Sequence

from lodestar_tpu.chain.bls.interface import VerifyOptions
from lodestar_tpu.chain.bls.single_thread import SingleThreadBlsVerifier
from lodestar_tpu.crypto.bls.api import SignatureSet, verify_signature_set
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger
from . import codec
from .metrics import BlsPoolSidecarMetrics

# the tier stamped on verdicts served by the client's own fallback
# oracle — distinct from the server-side "host" tier so the two
# degradations (sidecar on host fallback vs sidecar unreachable) are
# tellable apart in metrics
TIER_LOCAL_HOST = "local_host"

DEFAULT_ATTEMPTS = 2


class FabricPoolTransport:
    """Sidecar transport over a MeshFabric link (loopback in swarms,
    TCP+noise in deployment — whatever the fabric is bound to)."""

    def __init__(self, fabric, server_peer_id: str):
        self._fabric = fabric
        self._server = server_peer_id

    async def request(self, data: bytes) -> bytes:
        from .server import PROTOCOL_ID

        return await self._fabric.request(self._server, PROTOCOL_ID, data)

    async def close(self) -> None:
        return None


class RemoteBlsVerifier:
    """BlsVerifier served by a BlsPoolServer, with local degradation."""

    def __init__(
        self,
        transport,
        *,
        tenant: str = "default",
        metrics: Optional[BlsPoolSidecarMetrics] = None,
        attempts: int = DEFAULT_ATTEMPTS,
        fallback=None,
    ):
        self._transport = transport
        self._tenant = tenant
        self._metrics = metrics
        self._attempts = max(1, attempts)
        self._fallback = fallback if fallback is not None else SingleThreadBlsVerifier()
        self._log = get_logger("blspool-client")
        # the most recent verdict's provenance, for tests and bench
        # stamping: {"degradation_tier": ..., "breaker_state": ...}
        self.last_stamp: dict = {}
        self.local_fallbacks = 0

    async def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: VerifyOptions = VerifyOptions()
    ) -> bool:
        if not sets:
            return False
        if opts.verify_on_main_thread:
            # the caller explicitly wants a local synchronous verdict —
            # never a wire round-trip
            return all(verify_signature_set(s) for s in sets)
        payload = codec.encode_request(self._tenant, sets, batchable=opts.batchable)
        for attempt in range(self._attempts):
            try:
                faults.fire(
                    "blspool.rpc.request", tenant=self._tenant, attempt=attempt
                )
            except faults.Delay as d:
                await asyncio.sleep(d.seconds)
            except faults.FaultError:
                # the request frame was lost in flight: next attempt
                continue
            try:
                raw = await self._transport.request(payload)
                resp = codec.decode_response(raw)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # transport/codec failure — a dead sidecar, a timeout, a
                # garbled response; all retryable, all degradable
                self._log.debug(
                    f"sidecar attempt {attempt + 1}/{self._attempts} "
                    f"failed: {type(e).__name__}: {e}"
                )
                continue
            if resp.get("ok"):
                tier = resp.get("degradation_tier") or "unknown"
                self.last_stamp = {
                    "degradation_tier": tier,
                    "breaker_state": resp.get("breaker_state"),
                    "coalesced_width": resp.get("coalesced_width"),
                    "coalesced_tenants": resp.get("coalesced_tenants"),
                }
                if self._metrics:
                    self._metrics.client_remote_verdicts_total.labels(
                        tier=tier
                    ).inc()
                return bool(resp.get("valid"))
            # a served REJECTION (shed, overload, server error): retry
            # once in case the window clears, then degrade locally
            self._log.debug(
                f"sidecar rejected request: {resp.get('error', 'unknown')}"
            )
        # bounded retries exhausted: the local host oracle answers.
        # Device/transport trouble never throws past this point — only a
        # genuine local verification bug could.
        self.local_fallbacks += 1
        self.last_stamp = {
            "degradation_tier": TIER_LOCAL_HOST,
            "breaker_state": None,
        }
        if self._metrics:
            self._metrics.client_local_fallbacks_total.inc()
        return await self._fallback.verify_signature_sets(sets, opts)

    async def close(self) -> None:
        await self._transport.close()
        await self._fallback.close()
