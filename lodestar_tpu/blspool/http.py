"""HTTP+JSON binding for the BLS sidecar (the second-process path).

Mirrors ``testing/mock_el_server.py``: one aiohttp endpoint, ephemeral
port, announced on stdout by ``__main__.py``.  The payload bytes are
EXACTLY the fabric binding's (``codec.py``) — the HTTP layer adds only
framing, so a tenant can switch bindings without touching the schema.

POST /verify   — request body in, response body out (always HTTP 200
                 for a served response, including sheds: the verdict
                 lives in the JSON ``ok``/``error`` fields; a raw HTTP
                 5xx means the server itself failed, which the client
                 treats as a transport fault)
GET  /healthz  — liveness probe for process supervisors/tests
"""
from __future__ import annotations

from typing import Optional

from .server import BlsPoolServer


class BlsPoolHttpServer:
    def __init__(self, server: BlsPoolServer):
        self.server = server
        self._runner = None
        self.url: Optional[str] = None
        self.port: Optional[int] = None

    def build_app(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_post("/verify", self._verify)
        app.router.add_get("/healthz", self._healthz)
        return app

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        from aiohttp import web

        self._runner = web.AppRunner(self.build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self.url = f"http://{host}:{self.port}"
        return self.url

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        await self.server.close()

    async def _verify(self, request):
        from aiohttp import web

        data = await request.read()
        # transport-level tenant identity: the peer address (the JSON
        # body's explicit tenant field, when present, wins — see
        # docs/BLSPOOL.md on the cooperative tenancy model).  An armed
        # blspool.rpc.respond fault escapes here → aiohttp answers a
        # bare HTTP 500, the crashing-server shape the client's ladder
        # must absorb.
        tenant = request.remote or "http"
        body = await self.server.handle_payload(tenant, data)
        return web.Response(body=body, content_type="application/json")

    async def _healthz(self, request):
        from aiohttp import web

        return web.json_response({"ok": True})


class HttpPoolTransport:
    """Client-side transport for RemoteBlsVerifier over the HTTP
    binding (``lodestar-tpu beacon --bls-pool-url``)."""

    def __init__(self, url: str, request_timeout: float = 10.0):
        self._url = url.rstrip("/")
        self._timeout = request_timeout
        self._session = None

    async def request(self, data: bytes) -> bytes:
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self._timeout)
            )
        async with self._session.post(
            self._url + "/verify",
            data=data,
            headers={"Content-Type": "application/json"},
        ) as resp:
            if resp.status != 200:
                raise ConnectionError(f"sidecar HTTP {resp.status}")
            return await resp.read()

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
        self._session = None
