"""BLS sidecar wire codec — one JSON schema, two bindings.

The fabric reqresp binding (server.BlsPoolServer.attach) and the HTTP
binding (http.BlsPoolHttpServer) carry EXACTLY these bytes; the schema
is documented in docs/BLSPOOL.md.  Curve points travel in their
compressed byte encodings (48B G1 pubkey / 96B G2 signature, hex), so
decoding a request performs the same subgroup/point validation every
other ingress path performs — a malformed point is a CodecError, never
a crash deeper in the pool.

Deliberately jax-free and asyncio-free: pure bytes -> values.
"""
from __future__ import annotations

import json
from typing import List, Optional, Sequence, Tuple

from lodestar_tpu.crypto.bls.api import PublicKey, Signature, SignatureSet

SCHEMA_VERSION = 1

# response error codes (docs/BLSPOOL.md): the client retries/degrades on
# any of them, but dashboards and tests distinguish the causes
ERR_RATE_LIMITED = "rate_limited"
ERR_OVERLOADED = "overloaded"
ERR_BAD_REQUEST = "bad_request"
ERR_VERIFY_FAILED = "verify_error"
ERR_SERVER_CLOSED = "server_closed"


class CodecError(ValueError):
    """Malformed sidecar request/response payload."""


def _hex(data: bytes) -> str:
    return "0x" + data.hex()


def _unhex(value, what: str) -> bytes:
    if not isinstance(value, str):
        raise CodecError(f"{what}: expected hex string")
    try:
        return bytes.fromhex(value.removeprefix("0x"))
    except ValueError:
        raise CodecError(f"{what}: not hex") from None


def encode_request(
    tenant: str, sets: Sequence[SignatureSet], batchable: bool = True
) -> bytes:
    body = {
        "v": SCHEMA_VERSION,
        "tenant": tenant,
        "batchable": bool(batchable),
        "sets": [
            {
                "pubkey": _hex(s.public_key.to_bytes()),
                "message": _hex(s.message),
                "signature": _hex(s.signature.to_bytes()),
            }
            for s in sets
        ],
    }
    return json.dumps(body, separators=(",", ":")).encode()


def decode_request(data: bytes) -> Tuple[Optional[str], List[SignatureSet], bool]:
    """-> (tenant or None, sets, batchable).  Raises CodecError on any
    malformation, including invalid curve points."""
    try:
        body = json.loads(data.decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise CodecError(f"request is not JSON: {e}") from None
    if not isinstance(body, dict) or body.get("v") != SCHEMA_VERSION:
        raise CodecError("unknown request schema version")
    tenant = body.get("tenant")
    if tenant is not None and not isinstance(tenant, str):
        raise CodecError("tenant: expected string")
    raw_sets = body.get("sets")
    if not isinstance(raw_sets, list):
        raise CodecError("sets: expected list")
    sets: List[SignatureSet] = []
    for i, raw in enumerate(raw_sets):
        if not isinstance(raw, dict):
            raise CodecError(f"sets[{i}]: expected object")
        try:
            pk = PublicKey.from_bytes(_unhex(raw.get("pubkey"), f"sets[{i}].pubkey"))
            sig = Signature.from_bytes(
                _unhex(raw.get("signature"), f"sets[{i}].signature")
            )
        except CodecError:
            raise
        except ValueError as e:
            raise CodecError(f"sets[{i}]: invalid point encoding: {e}") from None
        msg = _unhex(raw.get("message"), f"sets[{i}].message")
        sets.append(SignatureSet(public_key=pk, message=msg, signature=sig))
    return tenant, sets, bool(body.get("batchable", True))


def encode_response(
    *,
    ok: bool,
    valid: bool = False,
    error: Optional[str] = None,
    degradation_tier: Optional[str] = None,
    breaker_state: Optional[str] = None,
    coalesced_width: int = 0,
    coalesced_tenants: int = 0,
) -> bytes:
    body = {
        "v": SCHEMA_VERSION,
        "ok": bool(ok),
        "valid": bool(valid),
        "degradation_tier": degradation_tier,
        "breaker_state": breaker_state,
        "coalesced_width": int(coalesced_width),
        "coalesced_tenants": int(coalesced_tenants),
    }
    if error is not None:
        body["error"] = error
    return json.dumps(body, separators=(",", ":")).encode()


def decode_response(data: bytes) -> dict:
    try:
        body = json.loads(data.decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise CodecError(f"response is not JSON: {e}") from None
    if not isinstance(body, dict) or body.get("v") != SCHEMA_VERSION:
        raise CodecError("unknown response schema version")
    if not isinstance(body.get("ok"), bool):
        raise CodecError("response missing ok verdict")
    return body
