"""Prometheus metrics for the BLS sidecar (ROADMAP 9b slice).

Per-tenant accounting is the point: the sidecar's economics rest on
cross-tenant coalescing, and its fairness promise rests on per-tenant
GCRA shedding — both must be visible on a dashboard
(dashboards/lodestar_tpu_blspool.json), not inferred from logs.  The
``lodestar_tpu_blspool`` namespace is distinct from the in-process
pool's ``lodestar_tpu_bls_pool`` family: one sidecar process serves
many nodes, so its series would double-count if they shared a family.
"""
from __future__ import annotations

from prometheus_client import Counter, Gauge, Histogram, REGISTRY


class BlsPoolSidecarMetrics:
    _instance = None

    def __init__(self, registry=REGISTRY):
        ns = "lodestar_tpu_blspool"
        self.requests_total = Counter(
            f"{ns}_requests_total",
            "Verification requests received, by tenant",
            labelnames=("tenant",),
            registry=registry,
        )
        self.sets_total = Counter(
            f"{ns}_sets_total",
            "Signature sets offered, by tenant (admitted or shed)",
            labelnames=("tenant",),
            registry=registry,
        )
        self.shed_total = Counter(
            f"{ns}_shed_total",
            "Requests shed by per-tenant GCRA admission or pool "
            "backpressure, by tenant",
            labelnames=("tenant",),
            registry=registry,
        )
        self.batches_total = Counter(
            f"{ns}_batches_total",
            "Cross-tenant coalesced batches dispatched to the inner pool",
            registry=registry,
        )
        self.batch_width = Histogram(
            f"{ns}_batch_width",
            "Coalesced batch width (signature sets per dispatched batch)",
            buckets=(1, 4, 16, 64, 128, 256, 512, 1024, 2048),
            registry=registry,
        )
        self.batch_tenants = Histogram(
            f"{ns}_batch_tenants",
            "Distinct tenants per coalesced batch",
            buckets=(1, 2, 4, 8, 16, 32),
            registry=registry,
        )
        self.responses_total = Counter(
            f"{ns}_responses_total",
            "Served verdicts by degradation tier (device vs host "
            "fallback — a tenant-visible stamp, docs/BLSPOOL.md)",
            labelnames=("tier",),
            registry=registry,
        )
        self.pending_sets = Gauge(
            f"{ns}_pending_sets",
            "Signature sets admitted and awaiting a coalesced batch",
            registry=registry,
        )
        self.client_local_fallbacks_total = Counter(
            f"{ns}_client_local_fallbacks_total",
            "Client-side degradations to the local host oracle "
            "(sidecar unreachable, shedding, or erroring)",
            registry=registry,
        )
        self.client_remote_verdicts_total = Counter(
            f"{ns}_client_remote_verdicts_total",
            "Verdicts this tenant received from the sidecar, by the "
            "tier the server stamped",
            labelnames=("tier",),
            registry=registry,
        )

    @classmethod
    def get(cls) -> "BlsPoolSidecarMetrics":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance
