"""Named network bundles — config + genesis anchors as data modules.

Reference role: packages/cli/src/networks/{mainnet,sepolia,goerli}.ts,
which bundle each network's chain config, genesis metadata and bootnode
lists behind the `--network` flag.  Here each bundle carries:

  * chain_config      — the network's ChainConfig (fork schedule, TTD,
                        deposit contract), values from the public
                        consensus-specs config files
  * genesis_validators_root / genesis_time — the deployed chain's
                        anchors (needed to compute fork digests and to
                        validate checkpoint states without genesis)
  * checkpoint_sync_urls — public weak-subjectivity providers
  * bootnodes         — wire-format ENRs for this client (hex SSZ,
                        network/discovery.py records).  DOCUMENTED
                        DEVIATION: the rebuild's discovery speaks its
                        own signed-record format, not discv5-wire, so
                        the canonical EF bootnode `enr:` strings (shipped
                        in the reference's networks/*.ts) cannot be
                        dialed and are not embedded; operators seed
                        peers via --bootnode-enr or these lists once
                        records exist for a deployment.

NOTE: sepolia/goerli/mainnet run the mainnet *preset*; select it with
LODESTAR_TPU_PRESET=mainnet (the CLI enforces this at resolution).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from lodestar_tpu.config import ChainConfig, FAR_FUTURE_EPOCH


@dataclass(frozen=True)
class NetworkBundle:
    name: str
    chain_config: ChainConfig
    genesis_validators_root: bytes
    genesis_time: int
    checkpoint_sync_urls: tuple = ()
    bootnodes: tuple = ()  # wire-format ENR hex strings (this client)


mainnet = NetworkBundle(
    name="mainnet",
    # defaults are the mainnet config; the deployed chain has since
    # activated capella (Shapella, epoch 194048) — the bundle tracks the
    # REAL network where the pinned reference default predates it
    chain_config=ChainConfig(CAPELLA_FORK_EPOCH=194048),
    genesis_validators_root=bytes.fromhex(
        "4b363db94e286120d76eb905340fdd4e54bfe9f06bf33ff6cf5ad27f511bfe95"
    ),
    genesis_time=1606824023,
    checkpoint_sync_urls=(
        "https://beaconstate.info",
        "https://mainnet-checkpoint-sync.attestant.io",
    ),
)

sepolia = NetworkBundle(
    name="sepolia",
    chain_config=ChainConfig(
        PRESET_BASE="mainnet",
        CONFIG_NAME="sepolia",
        TERMINAL_TOTAL_DIFFICULTY=17_000_000_000_000_000,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=1300,
        MIN_GENESIS_TIME=1655647200,
        GENESIS_FORK_VERSION=bytes.fromhex("90000069"),
        GENESIS_DELAY=86400,
        ALTAIR_FORK_VERSION=bytes.fromhex("90000070"),
        ALTAIR_FORK_EPOCH=50,
        BELLATRIX_FORK_VERSION=bytes.fromhex("90000071"),
        BELLATRIX_FORK_EPOCH=100,
        CAPELLA_FORK_VERSION=bytes.fromhex("90000072"),
        CAPELLA_FORK_EPOCH=56832,
        DEPOSIT_CHAIN_ID=11155111,
        DEPOSIT_NETWORK_ID=11155111,
        DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex(
            "7f02c3e3c98b133055b8b348b2ac625669ed295d"
        ),
    ),
    genesis_validators_root=bytes.fromhex(
        "d8ea171f3c94aea21ebc42a1ed61052acf3f9209c00e4efbaaddac09ed9b8078"
    ),
    genesis_time=1655733600,
    checkpoint_sync_urls=("https://sepolia.beaconstate.info",),
)

goerli = NetworkBundle(
    name="goerli",
    chain_config=ChainConfig(
        PRESET_BASE="mainnet",
        CONFIG_NAME="goerli",
        TERMINAL_TOTAL_DIFFICULTY=10_790_000,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16384,
        MIN_GENESIS_TIME=1614588812,
        GENESIS_FORK_VERSION=bytes.fromhex("00001020"),
        GENESIS_DELAY=1919188,
        ALTAIR_FORK_VERSION=bytes.fromhex("01001020"),
        ALTAIR_FORK_EPOCH=36660,
        BELLATRIX_FORK_VERSION=bytes.fromhex("02001020"),
        BELLATRIX_FORK_EPOCH=112260,
        CAPELLA_FORK_VERSION=bytes.fromhex("03001020"),
        CAPELLA_FORK_EPOCH=162304,
        DEPOSIT_CHAIN_ID=5,
        DEPOSIT_NETWORK_ID=5,
        DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex(
            "ff50ed3d0ec03ac01d4c79aad74928bff48a7b2b"
        ),
    ),
    genesis_validators_root=bytes.fromhex(
        "043db0d9a83813551ee2f33450d23797757d430911a9320530ad8a0eabc43efb"
    ),
    genesis_time=1616508000,
    checkpoint_sync_urls=("https://goerli.beaconstate.info",),
)

NETWORKS: Dict[str, NetworkBundle] = {
    b.name: b for b in (mainnet, sepolia, goerli)
}


def get_network(name: str) -> NetworkBundle:
    try:
        return NETWORKS[name]
    except KeyError:
        raise ValueError(
            f"unknown network {name!r} (have: {', '.join(sorted(NETWORKS))})"
        ) from None
