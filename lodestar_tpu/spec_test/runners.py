"""Per-suite spec-test runners (reference: beacon-node/test/spec/presets/
{operations,epoch_processing,sanity,ssz_static}.ts + test/spec/bls/bls.ts).

Each runner adapts one official suite layout onto the state transition /
crypto stack and returns the computed post bytes for the harness's
byte-equality check.
"""
from __future__ import annotations

from typing import Dict

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.state_transition import CachedBeaconState, process_slots
from lodestar_tpu.types import fork_of_state, ssz, types_for
from . import SpecTestCase


def _state_type_of(case: SpecTestCase, fork):
    return types_for(fork)[0]


def make_operations_runner(cfg, fork, operation_stem: str, op_type, apply_fn):
    """Suite: operations/<op> — pre + operation -> post (or failure).

    apply_fn(cfg, cached_state, operation) mutates the cached state."""
    state_t = types_for(fork)[0]

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", state_t)
        op = case.ssz(operation_stem, op_type)
        cached = CachedBeaconState(cfg, pre)
        apply_fn(cfg, cached, op)
        return state_t.serialize(cached.state)

    return runner


def make_epoch_processing_runner(cfg, fork, process_fn):
    """Suite: epoch_processing/<sub> — pre -> post via one epoch step."""
    state_t = types_for(fork)[0]

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", state_t)
        cached = CachedBeaconState(cfg, pre)
        process_fn(cfg, cached)
        return state_t.serialize(cached.state)

    return runner


def make_sanity_slots_runner(cfg, fork):
    """Suite: sanity/slots — pre + slots.yaml -> post."""
    state_t = types_for(fork)[0]

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", state_t)
        n = int(case.yaml("slots"))
        cached = CachedBeaconState(cfg, pre)
        process_slots(cached, cached.state.slot + n)
        return type(cached.state).serialize(cached.state)

    return runner


def make_sanity_blocks_runner(cfg, fork):
    """Suite: sanity/blocks — pre + blocks_0..N -> post (or failure)."""
    from lodestar_tpu.state_transition import state_transition

    state_t, _, signed_t, _ = types_for(fork)

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", state_t)
        meta = case.meta()
        n_blocks = int(meta.get("blocks_count", 1))
        cached = CachedBeaconState(cfg, pre)
        for i in range(n_blocks):
            block = case.ssz(f"blocks_{i}", signed_t)
            cached = state_transition(
                cached, block,
                verify_state_root=True, verify_proposer=True,
                verify_signatures=True,
            )
        return type(cached.state).serialize(cached.state)

    return runner


def make_ssz_static_runner(ssz_type):
    """Suite: ssz_static/<Type> — serialized.ssz_snappy + roots.yaml."""

    def runner(case: SpecTestCase):
        data = case.raw("serialized")
        value = ssz_type.deserialize(data)
        roots = case.yaml("roots")
        got_root = "0x" + ssz_type.hash_tree_root(value).hex()
        if got_root != roots["root"]:
            raise AssertionError(f"root {got_root} != {roots['root']}")
        if ssz_type.serialize(value) != data:
            raise AssertionError("serialization round-trip mismatch")
        return None

    return runner


# ---------------------------------------------------------------------------
# BLS suite (test/spec/bls/bls.ts:8 mapping)
# ---------------------------------------------------------------------------


def _hex_bytes(s: str) -> bytes:
    return bytes.fromhex(s.replace("0x", ""))


def bls_runner(case: SpecTestCase):
    """Official bls test layout: data.yaml with {input, output}."""
    data = case.yaml("data")
    inp, out = data["input"], data["output"]
    kind = case.meta().get("handler") or _infer_bls_handler(inp)
    if kind == "sign":
        sk = bls.SecretKey.from_bytes(_hex_bytes(inp["privkey"]))
        got = sk.sign(_hex_bytes(inp["message"])).to_bytes()
        assert out is not None and got == _hex_bytes(out), "sign mismatch"
    elif kind == "verify":
        try:
            ok = bls.verify(
                bls.PublicKey.from_bytes(_hex_bytes(inp["pubkey"])),
                _hex_bytes(inp["message"]),
                bls.Signature.from_bytes(_hex_bytes(inp["signature"])),
            )
        except ValueError:
            ok = False
        assert ok == bool(out), f"verify: got {ok} want {out}"
    elif kind == "aggregate":
        try:
            sigs = [bls.Signature.from_bytes(_hex_bytes(s)) for s in inp]
            got = bls.aggregate_signatures(sigs).to_bytes()
        except ValueError:
            assert out is None, "aggregate should have succeeded"
            return None
        assert out is not None and got == _hex_bytes(out), "aggregate mismatch"
    elif kind == "eth_fast_aggregate_verify":
        try:
            ok = bls.eth_fast_aggregate_verify(
                [bls.PublicKey.from_bytes(_hex_bytes(p)) for p in inp["pubkeys"]],
                _hex_bytes(inp["message"]),
                bls.Signature.from_bytes(_hex_bytes(inp["signature"])),
            )
        except ValueError:
            ok = False
        assert ok == bool(out), f"eth_fast_aggregate_verify: got {ok} want {out}"
    elif kind == "fast_aggregate_verify":
        try:
            ok = bls.fast_aggregate_verify(
                [bls.PublicKey.from_bytes(_hex_bytes(p)) for p in inp["pubkeys"]],
                _hex_bytes(inp["message"]),
                bls.Signature.from_bytes(_hex_bytes(inp["signature"])),
            )
        except ValueError:
            ok = False
        assert ok == bool(out), f"fast_aggregate_verify: got {ok} want {out}"
    elif kind == "aggregate_verify":
        try:
            ok = bls.aggregate_verify(
                [bls.PublicKey.from_bytes(_hex_bytes(p)) for p in inp["pubkeys"]],
                [_hex_bytes(m) for m in inp["messages"]],
                bls.Signature.from_bytes(_hex_bytes(inp["signature"])),
            )
        except ValueError:
            ok = False
        assert ok == bool(out), f"aggregate_verify: got {ok} want {out}"
    else:
        raise AssertionError(f"unknown bls handler {kind!r}")
    return None


def _infer_bls_handler(inp) -> str:
    if isinstance(inp, list):
        return "aggregate"
    if "privkey" in inp:
        return "sign"
    if "pubkeys" in inp and "messages" in inp:
        return "aggregate_verify"
    if "pubkeys" in inp:
        return "fast_aggregate_verify"
    return "verify"
