"""Per-suite spec-test runners (reference: beacon-node/test/spec/presets/
{operations,epoch_processing,sanity,ssz_static}.ts + test/spec/bls/bls.ts).

Each runner adapts one official suite layout onto the state transition /
crypto stack and returns the computed post bytes for the harness's
byte-equality check.
"""
from __future__ import annotations

from typing import Dict

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.state_transition import CachedBeaconState, process_slots
from lodestar_tpu.types import fork_of_state, ssz, types_for
from . import SpecTestCase


def _state_type_of(case: SpecTestCase, fork):
    return types_for(fork)[0]


def make_operations_runner(cfg, fork, operation_stem: str, op_type, apply_fn):
    """Suite: operations/<op> — pre + operation -> post (or failure).

    apply_fn(cfg, cached_state, operation) mutates the cached state;
    handlers that need sibling files (execution.yaml engine verdicts)
    declare a `case` keyword and receive the SpecTestCase."""
    import inspect

    state_t = types_for(fork)[0]
    takes_case = "case" in inspect.signature(apply_fn).parameters

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", state_t)
        op = case.ssz(operation_stem, op_type)
        cached = CachedBeaconState(cfg, pre)
        if takes_case:
            apply_fn(cfg, cached, op, case=case)
        else:
            apply_fn(cfg, cached, op)
        return state_t.serialize(cached.state)

    return runner


def make_epoch_processing_runner(cfg, fork, process_fn):
    """Suite: epoch_processing/<sub> — pre -> post via one epoch step."""
    state_t = types_for(fork)[0]

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", state_t)
        cached = CachedBeaconState(cfg, pre)
        process_fn(cfg, cached)
        return state_t.serialize(cached.state)

    return runner


def make_sanity_slots_runner(cfg, fork):
    """Suite: sanity/slots — pre + slots.yaml -> post."""
    state_t = types_for(fork)[0]

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", state_t)
        n = int(case.yaml("slots"))
        cached = CachedBeaconState(cfg, pre)
        process_slots(cached, cached.state.slot + n)
        return type(cached.state).serialize(cached.state)

    return runner


def make_sanity_blocks_runner(cfg, fork):
    """Suite: sanity/blocks — pre + blocks_0..N -> post (or failure)."""
    from lodestar_tpu.state_transition import state_transition

    state_t, _, signed_t, _ = types_for(fork)

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", state_t)
        meta = case.meta()
        n_blocks = int(meta.get("blocks_count", 1))
        cached = CachedBeaconState(cfg, pre)
        for i in range(n_blocks):
            block = case.ssz(f"blocks_{i}", signed_t)
            cached = state_transition(
                cached, block,
                verify_state_root=True, verify_proposer=True,
                verify_signatures=True,
            )
        return type(cached.state).serialize(cached.state)

    return runner


def make_ssz_static_runner(ssz_type):
    """Suite: ssz_static/<Type> — serialized.ssz_snappy + roots.yaml."""

    def runner(case: SpecTestCase):
        data = case.raw("serialized")
        value = ssz_type.deserialize(data)
        roots = case.yaml("roots")
        got_root = "0x" + ssz_type.hash_tree_root(value).hex()
        if got_root != roots["root"]:
            raise AssertionError(f"root {got_root} != {roots['root']}")
        if ssz_type.serialize(value) != data:
            raise AssertionError("serialization round-trip mismatch")
        return None

    return runner


def make_finality_runner(cfg, fork):
    """Suite: finality/finality — identical layout to sanity/blocks
    (pre + blocks_i -> post), the cases just push the chain through
    justification/finalization transitions (test/spec/presets/finality.ts)."""
    return make_sanity_blocks_runner(cfg, fork)


def make_fork_upgrade_runner(cfg, pre_fork, upgrade_fn):
    """Suite: fork/fork — pre (old-fork state) -> post (upgraded state)
    (test/spec/presets/fork.ts)."""
    pre_t = types_for(pre_fork)[0]

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", pre_t)
        post = upgrade_fn(cfg, pre, CachedBeaconState(cfg, pre).epoch_ctx)
        return type(post).serialize(post)

    return runner


def make_rewards_runner(cfg, fork):
    """Suite: rewards/* (altair+ layout): pre -> per-component Deltas
    files {source,target,head}_deltas + inactivity_penalty_deltas
    (test/spec/presets/rewards.ts).  The component table comes from
    fixtures.rewards_components — the same table generation uses."""
    from lodestar_tpu.state_transition.epoch import altair as ea
    from .fixtures import rewards_components

    state_t = types_for(fork)[0]
    deltas_t = _deltas_type()

    def runner(case: SpecTestCase):
        pre = case.ssz("pre", state_t)
        cached = CachedBeaconState(cfg, pre)
        proc = ea.before_process_epoch(cfg, cached.state, cached.epoch_ctx)
        components = rewards_components(cfg, cached.state, proc)
        checked = 0
        for stem, (rewards, penalties) in components.items():
            if not case.has(stem):
                continue
            got = deltas_t.serialize(
                deltas_t(
                    rewards=[int(x) for x in rewards],
                    penalties=[int(x) for x in penalties],
                )
            )
            if got != case.raw(stem):
                raise AssertionError(f"{stem} mismatch")
            checked += 1
        if checked == 0:
            raise AssertionError("no known delta component files in case")
        return None

    return runner


_DELTAS_T = None


def _deltas_type():
    """Deltas{rewards, penalties} (built via the metaclass directly —
    this module's `from __future__ import annotations` would turn class-
    body annotations into strings, which ContainerMeta rejects)."""
    global _DELTAS_T
    if _DELTAS_T is None:
        from lodestar_tpu.params import ACTIVE_PRESET as _p
        from lodestar_tpu.ssz import core as sszc

        lst = sszc.List[sszc.uint64, _p.VALIDATOR_REGISTRY_LIMIT]
        _DELTAS_T = sszc.ContainerMeta(
            "Deltas",
            (sszc.Container,),
            {"__annotations__": {"rewards": lst, "penalties": lst}},
        )
    return _DELTAS_T


def make_fork_choice_runner(cfg, fork):
    """Suite: fork_choice/* — anchor_state + anchor_block + steps.yaml
    driving ticks/blocks/attestations with interleaved head/checkpoint
    checks (test/spec/presets/fork_choice.ts).  Steps run through a full
    BeaconChain (clock + block pipeline + fork choice), i.e. the same
    integrated path gossip and sync use; block signatures are assumed
    pre-validated like the reference's fork-choice harness."""
    import asyncio

    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.chain.clock import LocalClock
    from lodestar_tpu.db import BeaconDb

    state_t, block_t, signed_t, _ = types_for(fork)
    att_t = ssz.phase0.Attestation

    class _TrustAll:
        async def verify_signature_sets(self, sets, opts=None):
            return True

    def runner(case: SpecTestCase):
        anchor_state = case.ssz("anchor_state", state_t)
        case.ssz("anchor_block", block_t)  # layout presence check

        class _FT:
            t = float(anchor_state.genesis_time)

            def __call__(self):
                return self.t

        ft = _FT()
        chain = BeaconChain(
            cfg,
            BeaconDb(),
            anchor_state,
            verifier=_TrustAll(),
            clock=LocalClock(
                anchor_state.genesis_time, cfg.SECONDS_PER_SLOT, now=ft
            ),
        )

        async def drive():
            for step in case.yaml("steps"):
                if "tick" in step:
                    ft.t = anchor_state.genesis_time + int(step["tick"])
                    chain.fork_choice.update_time(chain.clock.current_slot)
                elif "block" in step:
                    signed = case.ssz(step["block"], signed_t)
                    try:
                        await chain.process_block(signed)
                    except ValueError:
                        if step.get("valid", True):
                            raise
                        continue
                    if not step.get("valid", True):
                        raise AssertionError(
                            f"{step['block']}: invalid block imported"
                        )
                elif "attestation" in step:
                    att = case.ssz(step["attestation"], att_t)
                    # committee from the ATTESTED block's imported state
                    # (head shuffling is wrong/absent for side-fork or
                    # older-epoch attestations); head is the fallback for
                    # attestations to blocks this harness never imported
                    st = chain.state_cache.get(
                        bytes(att.data.beacon_block_root)
                    ) or chain.get_head_state()
                    committee = st.epoch_ctx.get_committee(
                        att.data.slot, att.data.index
                    )
                    indices = [
                        committee[i]
                        for i, bit in enumerate(att.aggregation_bits)
                        if bit
                    ]
                    chain.fork_choice.on_attestation(
                        indices,
                        "0x" + bytes(att.data.beacon_block_root).hex(),
                        att.data.target.epoch,
                    )
                elif "checks" in step:
                    checks = step["checks"]
                    head = chain.fork_choice.update_head()
                    if "head" in checks:
                        want = checks["head"]
                        if int(want["slot"]) != head.slot:
                            raise AssertionError(
                                f"head slot {head.slot} != {want['slot']}"
                            )
                        if want.get("root") and want["root"] != head.block_root:
                            raise AssertionError(
                                f"head root {head.block_root} != {want['root']}"
                            )
                    if "justified_checkpoint" in checks:
                        want = checks["justified_checkpoint"]
                        got = chain.fork_choice.store.justified
                        if int(want["epoch"]) != got.epoch:
                            raise AssertionError(
                                f"justified epoch {got.epoch} != {want['epoch']}"
                            )
                    if "finalized_checkpoint" in checks:
                        want = checks["finalized_checkpoint"]
                        got = chain.fork_choice.store.finalized
                        if int(want["epoch"]) != got.epoch:
                            raise AssertionError(
                                f"finalized epoch {got.epoch} != {want['epoch']}"
                            )

        asyncio.run(drive())
        return None

    return runner


# ---------------------------------------------------------------------------
# BLS suite (test/spec/bls/bls.ts:8 mapping)
# ---------------------------------------------------------------------------


def _hex_bytes(s: str) -> bytes:
    return bytes.fromhex(s.replace("0x", ""))


def bls_runner(case: SpecTestCase):
    """Official bls test layout: data.yaml with {input, output}."""
    data = case.yaml("data")
    inp, out = data["input"], data["output"]
    kind = case.meta().get("handler") or _infer_bls_handler(inp)
    if kind == "sign":
        sk = bls.SecretKey.from_bytes(_hex_bytes(inp["privkey"]))
        got = sk.sign(_hex_bytes(inp["message"])).to_bytes()
        assert out is not None and got == _hex_bytes(out), "sign mismatch"
    elif kind == "verify":
        try:
            ok = bls.verify(
                bls.PublicKey.from_bytes(_hex_bytes(inp["pubkey"])),
                _hex_bytes(inp["message"]),
                bls.Signature.from_bytes(_hex_bytes(inp["signature"])),
            )
        except ValueError:
            ok = False
        assert ok == bool(out), f"verify: got {ok} want {out}"
    elif kind == "aggregate":
        try:
            sigs = [bls.Signature.from_bytes(_hex_bytes(s)) for s in inp]
            got = bls.aggregate_signatures(sigs).to_bytes()
        except ValueError:
            assert out is None, "aggregate should have succeeded"
            return None
        assert out is not None and got == _hex_bytes(out), "aggregate mismatch"
    elif kind == "eth_fast_aggregate_verify":
        try:
            ok = bls.eth_fast_aggregate_verify(
                [bls.PublicKey.from_bytes(_hex_bytes(p)) for p in inp["pubkeys"]],
                _hex_bytes(inp["message"]),
                bls.Signature.from_bytes(_hex_bytes(inp["signature"])),
            )
        except ValueError:
            ok = False
        assert ok == bool(out), f"eth_fast_aggregate_verify: got {ok} want {out}"
    elif kind == "fast_aggregate_verify":
        try:
            ok = bls.fast_aggregate_verify(
                [bls.PublicKey.from_bytes(_hex_bytes(p)) for p in inp["pubkeys"]],
                _hex_bytes(inp["message"]),
                bls.Signature.from_bytes(_hex_bytes(inp["signature"])),
            )
        except ValueError:
            ok = False
        assert ok == bool(out), f"fast_aggregate_verify: got {ok} want {out}"
    elif kind == "aggregate_verify":
        try:
            ok = bls.aggregate_verify(
                [bls.PublicKey.from_bytes(_hex_bytes(p)) for p in inp["pubkeys"]],
                [_hex_bytes(m) for m in inp["messages"]],
                bls.Signature.from_bytes(_hex_bytes(inp["signature"])),
            )
        except ValueError:
            ok = False
        assert ok == bool(out), f"aggregate_verify: got {ok} want {out}"
    else:
        raise AssertionError(f"unknown bls handler {kind!r}")
    return None


def _infer_bls_handler(inp) -> str:
    if isinstance(inp, list):
        return "aggregate"
    if "privkey" in inp:
        return "sign"
    if "pubkeys" in inp and "messages" in inp:
        return "aggregate_verify"
    if "pubkeys" in inp:
        return "fast_aggregate_verify"
    return "verify"
