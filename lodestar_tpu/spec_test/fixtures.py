"""Conformance fixture generation: official-layout suites for every
operation × fork, plus sanity / finality / fork-upgrade / rewards /
fork_choice.

Role: the reference DOWNLOADS ethereum/consensus-spec-tests
(test/spec/specTestVersioning.ts:17-32) — impossible offline, so these
generators write dev-chain transitions in the exact official directory
layout and the same runners consume them.  Self-generated vectors are a
REGRESSION oracle, not an independent one (the two external fixtures in
tests/fixtures/external/ plus the blst/RFC KATs are the independent
evidence); pointing LODESTAR_TPU_SPEC_TESTS at a real
consensus-spec-tests checkout runs the identical harness against the
official vectors (tests/test_official_vectors.py).

Layout written per suite (single.ts consumption contract):

    <root>/<fork>/<runner>/<handler>/pyspec_tests/<case>/
        pre.ssz_snappy, post.ssz_snappy (absent => must fail), ...
"""
from __future__ import annotations

import os
from dataclasses import replace
from typing import Dict, List

from lodestar_tpu.chain.dev import DevChain
from lodestar_tpu.config import minimal_chain_config
from lodestar_tpu.params import ACTIVE_PRESET as _p, FORK_SEQ, ForkName
from lodestar_tpu.state_transition import CachedBeaconState, process_slots
from lodestar_tpu.types import fork_of_state, ssz, types_for

from . import write_ssz_snappy, write_yaml

E = _p.SLOTS_PER_EPOCH


def config_for(fork: ForkName):
    """Minimal-preset chain config with every fork up to `fork` at epoch 0."""
    kw = {}
    order = [
        (ForkName.altair, "ALTAIR_FORK_EPOCH"),
        (ForkName.bellatrix, "BELLATRIX_FORK_EPOCH"),
        (ForkName.capella, "CAPELLA_FORK_EPOCH"),
        (ForkName.eip4844, "EIP4844_FORK_EPOCH"),
    ]
    for f, attr in order:
        if FORK_SEQ[f] <= FORK_SEQ[fork]:
            kw[attr] = 0
    if FORK_SEQ[fork] >= FORK_SEQ[ForkName.bellatrix]:
        kw["TERMINAL_TOTAL_DIFFICULTY"] = 0
    return replace(minimal_chain_config, **kw)


def _case_dir(root: str, fork: ForkName, runner: str, handler: str, case: str) -> str:
    return os.path.join(root, fork.value, runner, handler, "pyspec_tests", case)


def _dev(fork: ForkName, slots: int) -> DevChain:
    dc = DevChain(config_for(fork), 8, genesis_time=0)
    dc.run_until(slots, verify_signatures=False)
    return dc


def _write_pre_post(case_dir, state_t, pre, post) -> None:
    write_ssz_snappy(case_dir, "pre", state_t, pre)
    if post is not None:
        write_ssz_snappy(case_dir, "post", state_t, post)


def _apply(cfg, pre, fn) -> object:
    """Run fn against a clone; return post state (or raise)."""
    cached = CachedBeaconState(cfg, pre)
    work = cached.clone()
    fn(work)
    return work.state


# ---------------------------------------------------------------------------
# operations × forks
# ---------------------------------------------------------------------------


def _resolve_processor(fork: ForkName, name: str):
    """The fork's processor for `name`, falling back down the fork ladder
    — later forks reuse phase0's slashing/exit/header processors (which
    internally fork-switch where the spec modifies behavior) without
    re-exporting them as module attributes."""
    from lodestar_tpu.state_transition.block import altair as b_altair, phase0 as b0
    from lodestar_tpu.state_transition.state_transition import _PROCESSORS

    chain = [_PROCESSORS[fork][0]]
    if FORK_SEQ[fork] >= FORK_SEQ[ForkName.altair]:
        chain.append(b_altair)
    chain.append(b0)
    for mod in chain:
        fn = getattr(mod, name, None)
        if fn is not None:
            return fn
    raise AttributeError(f"no processor {name} for {fork.value}")


def operation_specs(fork: ForkName) -> Dict[str, tuple]:
    """handler -> (op_stem, op_type, apply_fn(cfg, cached, op)).

    ONE source of truth shared by the generator (below) and the
    conformance runner (tests/test_spec_conformance.py builds
    make_operations_runner from these), mirroring the reference's
    operations.ts handler table."""
    from lodestar_tpu.state_transition.block.process_deposit import (
        process_deposit as _process_deposit,
    )

    specs: Dict[str, tuple] = {}

    def _adv(w, slot):
        if w.state.slot < slot:
            process_slots(w, slot)

    p_att = _resolve_processor(fork, "process_attestation")
    p_hdr = _resolve_processor(fork, "process_block_header")
    p_ps = _resolve_processor(fork, "process_proposer_slashing")
    p_as = _resolve_processor(fork, "process_attester_slashing")
    p_exit = _resolve_processor(fork, "process_voluntary_exit")

    specs["attestation"] = (
        "attestation",
        ssz.phase0.Attestation,
        lambda cfg, w, op: p_att(cfg, w.state, w.epoch_ctx, op, True),
    )
    specs["block_header"] = (
        "block",
        types_for(fork)[1],
        lambda cfg, w, op: (
            _adv(w, op.slot),
            p_hdr(cfg, w.state, w.epoch_ctx, op),
        ),
    )
    specs["proposer_slashing"] = (
        "proposer_slashing",
        ssz.phase0.ProposerSlashing,
        lambda cfg, w, op: p_ps(cfg, w.state, w.epoch_ctx, op, True),
    )
    specs["attester_slashing"] = (
        "attester_slashing",
        ssz.phase0.AttesterSlashing,
        lambda cfg, w, op: p_as(cfg, w.state, w.epoch_ctx, op, True),
    )
    specs["voluntary_exit"] = (
        "voluntary_exit",
        ssz.phase0.SignedVoluntaryExit,
        lambda cfg, w, op: p_exit(cfg, w.state, w.epoch_ctx, op, True),
    )
    specs["deposit"] = (
        "deposit",
        ssz.phase0.Deposit,
        lambda cfg, w, op: _process_deposit(fork, cfg, w.state, op),
    )
    if FORK_SEQ[fork] >= FORK_SEQ[ForkName.altair]:
        p_sync = _resolve_processor(fork, "process_sync_aggregate")

        def apply_sync_aggregate(cfg, w, op):
            # synthesize a block at the state's slot carrying the
            # aggregate — the signature set derives the signed root from
            # the STATE (block root at slot-1), the block only supplies
            # slot + aggregate
            block_t = types_for(fork)[1]
            blk = block_t.default()
            blk.slot = int(w.state.slot)
            blk.body.sync_aggregate = op
            p_sync(cfg, w.state, w.epoch_ctx, blk, True)

        specs["sync_aggregate"] = (
            "sync_aggregate", ssz.altair.SyncAggregate, apply_sync_aggregate
        )
    if FORK_SEQ[fork] >= FORK_SEQ[ForkName.bellatrix]:
        from lodestar_tpu.state_transition.block import bellatrix as bm

        payload_t = getattr(ssz, fork.value).ExecutionPayload

        def apply_execution_payload(cfg, w, op, case=None):
            # official cases carry execution.yaml {execution_valid: bool}
            # for engine-rejected payloads (test/spec: operations/
            # execution_payload); model the engine verdict with a stub
            engine = None
            if case is not None and case.has("execution"):
                valid = bool(case.yaml("execution").get("execution_valid", True))

                class _Engine:
                    def notify_new_payload_sync(self, payload, _v=valid):
                        return _v

                engine = _Engine()
            body = types_for(fork)[3].default()
            body.execution_payload = op
            bm.process_execution_payload(cfg, w.state, body, engine)

        specs["execution_payload"] = (
            "execution_payload", payload_t, apply_execution_payload
        )
    if FORK_SEQ[fork] >= FORK_SEQ[ForkName.capella]:
        from lodestar_tpu.state_transition.block import capella as bc

        specs["withdrawals"] = (
            "execution_payload",
            getattr(ssz, fork.value).ExecutionPayload,
            lambda cfg, w, op: bc.process_withdrawals(cfg, w.state, op),
        )
        specs["bls_to_execution_change"] = (
            "address_change",
            ssz.capella.SignedBLSToExecutionChange,
            lambda cfg, w, op: bc.process_bls_to_execution_change(
                cfg, w.state, op, True
            ),
        )
    return specs


def gen_operations(root: str, fork: ForkName) -> List[str]:
    """Write operations/<handler> suites for every operation the fork has.

    Valid cases come from live dev-chain objects; each handler also gets
    at least one invalid case (post absent => the runner must raise).
    Apply semantics come from operation_specs() — the SAME table the
    conformance runner consumes, so generation and verification cannot
    drift apart."""
    from lodestar_tpu import flare
    from lodestar_tpu.state_transition.util.interop import interop_secret_keys

    cfg = config_for(fork)
    state_t, block_t, signed_t, _ = types_for(fork)
    specs = operation_specs(fork)
    dc = _dev(fork, 2 * E + 2)
    sks = dc.sks
    gvr = bytes(dc.head.state.genesis_validators_root)
    written = []

    def emit(handler: str, case: str, pre, op):
        stem, op_t, apply_fn = specs[handler]
        case_dir = _case_dir(root, fork, "operations", handler, case)
        write_ssz_snappy(case_dir, stem, op_t, op)
        try:
            post = _apply(cfg, pre, lambda w: apply_fn(cfg, w, op))
        except ValueError:
            # the STF contract: invalid operations raise ValueError — any
            # OTHER exception is a harness bug and must crash generation,
            # not become an expected-failure fixture
            post = None
        # the case NAME is the generator's intent; a valid_* case that
        # failed to apply (or an invalid_* that applied) is a generator
        # bug that would otherwise ship as silently-wrong coverage
        if case.startswith("valid") and post is None:
            raise AssertionError(
                f"{fork.value}/operations/{handler}/{case}: intended-valid "
                "case failed to apply"
            )
        if case.startswith("invalid") and post is not None:
            raise AssertionError(
                f"{fork.value}/operations/{handler}/{case}: intended-invalid "
                "case applied cleanly"
            )
        _write_pre_post(case_dir, state_t, pre, post)
        written.append(f"operations/{handler}/{case}")

    head = dc.head.state

    # -- attestation ----------------------------------------------------
    # pre advanced one slot past the attested slot so the inclusion
    # delay (MIN_ATTESTATION_INCLUSION_DELAY) is satisfied
    att = dc.attest(int(head.slot))[0]
    att_pre = dc.head.clone()
    process_slots(att_pre, int(head.slot) + 1)
    emit("attestation", "valid_head_att", att_pre.state, att)
    bad_att = ssz.phase0.Attestation(
        aggregation_bits=list(att.aggregation_bits),
        data=att.data.replace(target=att.data.target.replace(epoch=99)),
        signature=bytes(att.signature),
    )
    emit("attestation", "invalid_target_epoch", att_pre.state, bad_att)

    # -- block_header ----------------------------------------------------
    blk = dc.produce_block(int(head.slot) + 1)
    emit("block_header", "valid_next_block", head, blk.message)
    emit(
        "block_header", "invalid_proposer", head,
        blk.message.replace(proposer_index=7 - blk.message.proposer_index),
    )

    # -- proposer/attester slashing --------------------------------------
    ps = flare.make_self_proposer_slashing(cfg, gvr, sks[2], 2, int(head.slot))
    emit("proposer_slashing", "valid_double_proposal", head, ps)
    emit(
        "proposer_slashing", "invalid_same_header", head,
        ssz.phase0.ProposerSlashing(
            signed_header_1=ps.signed_header_1, signed_header_2=ps.signed_header_1
        ),
    )
    asl = flare.make_self_attester_slashing(
        cfg, gvr, sks[3], 3, int(head.slot) // E
    )
    emit("attester_slashing", "valid_double_vote", head, asl)
    emit(
        "attester_slashing", "invalid_same_attestation", head,
        ssz.phase0.AttesterSlashing(
            attestation_1=asl.attestation_1, attestation_2=asl.attestation_1
        ),
    )

    # -- voluntary_exit ---------------------------------------------------
    from lodestar_tpu.config import ForkConfig
    from lodestar_tpu.validator.validator_store import ValidatorStore

    period = cfg.SHARD_COMMITTEE_PERIOD
    deep = dc.head.clone()
    process_slots(deep, (period + 3) * E)
    store = ValidatorStore(interop_secret_keys(8), ForkConfig(cfg), gvr)
    exit_ = store.sign_voluntary_exit(store.pubkeys[5], 5, period + 2)
    emit("voluntary_exit", "valid_exit", deep.state, exit_)
    emit("voluntary_exit", "invalid_too_early", head, exit_)

    # -- deposit ----------------------------------------------------------
    _gen_deposit_cases(root, fork, cfg, emit)

    # -- sync_aggregate (altair+) ----------------------------------------
    if "sync_aggregate" in specs:
        nxt = dc.produce_block(int(head.slot) + 1)
        adv = dc.head.clone()
        process_slots(adv, int(head.slot) + 1)
        agg = nxt.message.body.sync_aggregate
        emit("sync_aggregate", "valid_from_block", adv.state, agg)
        flipped = list(agg.sync_committee_bits)
        if any(flipped):
            flipped[next(i for i, b in enumerate(flipped) if b)] = False
            emit(
                "sync_aggregate", "invalid_bit_flip", adv.state,
                ssz.altair.SyncAggregate(
                    sync_committee_bits=flipped,
                    sync_committee_signature=bytes(agg.sync_committee_signature),
                ),
            )

    # -- execution_payload (bellatrix+) ----------------------------------
    if "execution_payload" in specs:
        from lodestar_tpu.execution.engine import build_dev_payload

        adv = dc.head.clone()
        process_slots(adv, int(head.slot) + 1)
        payload = build_dev_payload(cfg, adv.state)
        emit("execution_payload", "valid_dev_payload", adv.state, payload)
        bad_payload = payload.copy()
        bad_payload.parent_hash = b"\x13" * 32
        emit("execution_payload", "invalid_parent_hash", adv.state, bad_payload)

    # -- withdrawals + bls_to_execution_change (capella+) -----------------
    if "withdrawals" in specs:
        from lodestar_tpu.state_transition.block import capella as bc
        from lodestar_tpu.execution.engine import build_dev_payload as _bdp

        wstate = head.copy()
        wstate.validators[2] = wstate.validators[2].replace(
            withdrawal_credentials=b"\x01" + b"\x00" * 11 + b"\xaa" * 20
        )
        wstate.balances[2] = _p.MAX_EFFECTIVE_BALANCE + 12345
        expected = bc.get_expected_withdrawals(wstate)
        wp = _bdp(cfg, wstate)
        wp.withdrawals = list(expected)
        emit("withdrawals", "valid_partial_withdrawal", wstate, wp)
        bad_wp = wp.copy()
        if expected:
            bad_wp.withdrawals = [
                expected[0].replace(amount=expected[0].amount + 1)
            ] + list(expected[1:])
        emit("withdrawals", "invalid_amount", wstate, bad_wp)

    if "bls_to_execution_change" in specs:
        from lodestar_tpu.params import DOMAIN_BLS_TO_EXECUTION_CHANGE
        from lodestar_tpu.state_transition.util.domain import (
            compute_domain,
            compute_signing_root,
        )

        idx = 5
        change = ssz.capella.BLSToExecutionChange(
            validator_index=idx,
            from_bls_pubkey=sks[idx].to_public_key().to_bytes(),
            to_execution_address=b"\xdd" * 20,
        )
        domain = compute_domain(
            DOMAIN_BLS_TO_EXECUTION_CHANGE, cfg.GENESIS_FORK_VERSION, gvr
        )
        sig = sks[idx].sign(
            compute_signing_root(ssz.capella.BLSToExecutionChange, change, domain)
        )
        signed = ssz.capella.SignedBLSToExecutionChange(
            message=change, signature=sig.to_bytes()
        )
        emit("bls_to_execution_change", "valid_change", head, signed)
        bad = ssz.capella.SignedBLSToExecutionChange(
            message=change.replace(to_execution_address=b"\xee" * 20),
            signature=sig.to_bytes(),
        )
        emit("bls_to_execution_change", "invalid_signature", head, bad)

    return written


def _gen_deposit_cases(root, fork, cfg, emit):
    """Deposit cases: build a 9-leaf interop deposit tree, initialize a
    state from the first 8 deposits with eth1_data committing to all 9,
    then the 9th deposit (valid proof) applies cleanly; a corrupted
    proof must fail."""
    from lodestar_tpu.state_transition.util import genesis as g

    deposits = g.interop_deposits(cfg, 9)
    pre8 = g.initialize_beacon_state_from_eth1(cfg, b"B" * 32, 2**40, deposits[:8])
    # commit the eth1 data to the FULL 9-leaf tree so deposit 8 proves
    full = g.initialize_beacon_state_from_eth1(cfg, b"B" * 32, 2**40, deposits)
    pre8.eth1_data = ssz.phase0.Eth1Data(
        deposit_root=bytes(full.eth1_data.deposit_root),
        deposit_count=9,
        block_hash=bytes(full.eth1_data.block_hash),
    )
    # fork-match the pre state (deposit processing is fork-generic)
    pre = _upgrade_to(cfg, pre8, fork)
    dep = deposits[8]
    emit("deposit", "valid_new_validator", pre, dep)
    bad_proof = list(dep.proof)
    bad_proof[0] = b"\x77" * 32
    emit(
        "deposit", "invalid_proof", pre,
        ssz.phase0.Deposit(proof=bad_proof, data=dep.data),
    )


def upgrade_ladder():
    """fork -> its upgrade function, in canonical order — the single copy
    shared by _upgrade_to, gen_fork_upgrade, and the conformance tests."""
    from lodestar_tpu.state_transition import upgrade as upg

    return {
        ForkName.altair: upg.upgrade_to_altair,
        ForkName.bellatrix: upg.upgrade_to_bellatrix,
        ForkName.capella: upg.upgrade_to_capella,
        ForkName.eip4844: upg.upgrade_to_eip4844,
    }


def _upgrade_to(cfg, phase0_state, fork: ForkName):
    """Chain the upgrade functions from phase0 up to `fork`."""
    state = phase0_state
    for f, fn in upgrade_ladder().items():
        if FORK_SEQ[f] <= FORK_SEQ[fork]:
            state = fn(cfg, state, CachedBeaconState(cfg, state).epoch_ctx)
    return state


def rewards_components(cfg, state, proc):
    """stem -> (rewards, penalties) — the single component table shared
    by gen_rewards and make_rewards_runner (drift-proof by construction)."""
    from lodestar_tpu.params import (
        TIMELY_HEAD_FLAG_INDEX,
        TIMELY_SOURCE_FLAG_INDEX,
        TIMELY_TARGET_FLAG_INDEX,
    )
    from lodestar_tpu.state_transition.epoch import altair as ea

    return {
        "source_deltas": ea.get_flag_index_deltas(
            cfg, state, proc, TIMELY_SOURCE_FLAG_INDEX
        ),
        "target_deltas": ea.get_flag_index_deltas(
            cfg, state, proc, TIMELY_TARGET_FLAG_INDEX
        ),
        "head_deltas": ea.get_flag_index_deltas(
            cfg, state, proc, TIMELY_HEAD_FLAG_INDEX
        ),
        "inactivity_penalty_deltas": ea.get_inactivity_penalty_deltas(
            cfg, state, proc
        ),
    }


# ---------------------------------------------------------------------------
# sanity / finality / fork / rewards / fork_choice
# ---------------------------------------------------------------------------


def gen_sanity(root: str, fork: ForkName) -> None:
    cfg = config_for(fork)
    state_t, _, signed_t, _ = types_for(fork)
    dc = _dev(fork, E + 1)
    pre = dc.head.state

    case_dir = _case_dir(root, fork, "sanity", "slots", "advance_epoch")
    w = CachedBeaconState(cfg, pre).clone()
    _write_pre_post(case_dir, state_t, pre, None)
    write_yaml(case_dir, "slots", E)
    process_slots(w, w.state.slot + E)
    write_ssz_snappy(case_dir, "post", state_t, w.state)

    blk = dc.produce_block(int(pre.slot) + 1)
    case_dir = _case_dir(root, fork, "sanity", "blocks", "one_block")
    from lodestar_tpu.state_transition import state_transition

    post = state_transition(
        CachedBeaconState(cfg, pre), blk,
        verify_state_root=True, verify_proposer=True, verify_signatures=True,
    )
    write_ssz_snappy(case_dir, "pre", state_t, pre)
    write_yaml(case_dir, "meta", {"blocks_count": 1})
    write_ssz_snappy(case_dir, "blocks_0", signed_t, blk)
    write_ssz_snappy(case_dir, "post", state_t, post.state)


def gen_finality(root: str, fork: ForkName) -> None:
    """finality/finality: 3+ epochs of blocks finalizing an epoch."""
    cfg = config_for(fork)
    state_t, _, signed_t, _ = types_for(fork)
    dc = DevChain(cfg, 8, genesis_time=0)
    pre = dc.head.state.copy()
    blocks = []
    for slot in range(1, 4 * E + 1):
        if slot > 1:
            dc.attest(slot - 1)
        blk = dc.produce_block(slot)
        dc.import_block(blk, verify_signatures=False)
        blocks.append(blk)
    assert dc.head.state.finalized_checkpoint.epoch > 0, "no finality reached"
    case_dir = _case_dir(root, fork, "finality", "finality", "finalize_epochs")
    write_ssz_snappy(case_dir, "pre", state_t, pre)
    write_yaml(case_dir, "meta", {"blocks_count": len(blocks)})
    for i, blk in enumerate(blocks):
        write_ssz_snappy(case_dir, f"blocks_{i}", signed_t, blk)
    write_ssz_snappy(case_dir, "post", state_t, dc.head.state)


def gen_fork_upgrade(root: str, post_fork: ForkName) -> None:
    """fork/fork: a pre-fork state and its upgraded form."""
    forks = list(upgrade_ladder())
    pre_fork = (
        ForkName.phase0
        if post_fork is forks[0]
        else forks[forks.index(post_fork) - 1]
    )
    fn = upgrade_ladder()[post_fork]
    cfg = config_for(pre_fork)
    dc = _dev(pre_fork, E + 1)
    pre = dc.head.state
    post = fn(cfg, pre.copy(), CachedBeaconState(cfg, pre.copy()).epoch_ctx)
    case_dir = _case_dir(root, post_fork, "fork", "fork", "upgrade")
    write_ssz_snappy(case_dir, "pre", types_for(pre_fork)[0], pre)
    write_ssz_snappy(case_dir, "post", types_for(post_fork)[0], post)
    write_yaml(case_dir, "meta", {"fork": post_fork.value})


def gen_rewards(root: str, fork: ForkName) -> None:
    """rewards/basic: per-component Deltas at an epoch boundary (the
    component table is shared with make_rewards_runner)."""
    from lodestar_tpu.state_transition.epoch import altair as ea
    from .runners import _deltas_type

    cfg = config_for(fork)
    state_t = types_for(fork)[0]
    dc = _dev(fork, 2 * E)
    pre = dc.head.state
    cached = CachedBeaconState(cfg, pre)
    proc = ea.before_process_epoch(cfg, cached.state, cached.epoch_ctx)
    deltas_t = _deltas_type()
    case_dir = _case_dir(root, fork, "rewards", "basic", "epoch_boundary")
    write_ssz_snappy(case_dir, "pre", state_t, pre)
    for stem, (r, p) in rewards_components(cfg, cached.state, proc).items():
        write_ssz_snappy(
            case_dir, stem, deltas_t,
            deltas_t(rewards=[int(x) for x in r], penalties=[int(x) for x in p]),
        )


def gen_fork_choice(root: str, fork: ForkName) -> None:
    """fork_choice/on_block: ticks + blocks + head/checkpoint checks from
    a dev-chain run (official steps.yaml layout)."""
    cfg = config_for(fork)
    state_t, block_t, signed_t, _ = types_for(fork)
    dc = DevChain(cfg, 8, genesis_time=0)
    anchor_state = dc.head.state.copy()
    anchor_block = block_t.default()
    # anchor block mirrors the genesis latest_block_header with state root
    anchor_block = anchor_block.replace(
        slot=anchor_state.slot,
        state_root=type(anchor_state).hash_tree_root(anchor_state),
    )
    steps: List[dict] = []
    blocks: Dict[str, object] = {}
    n = 3 * E + 1
    for slot in range(1, n + 1):
        if slot > 1:
            dc.attest(slot - 1)
        blk = dc.produce_block(slot)
        dc.import_block(blk, verify_signatures=False)
        steps.append({"tick": slot * cfg.SECONDS_PER_SLOT})
        name = f"block_{slot - 1}"
        blocks[name] = blk
        steps.append({"block": name})
    steps.append(
        {
            "checks": {
                "head": {
                    "slot": int(dc.head.state.slot),
                    "root": "0x" + dc._head_root().hex(),
                },
                "justified_checkpoint": {
                    "epoch": int(dc.head.state.current_justified_checkpoint.epoch)
                },
                "finalized_checkpoint": {
                    "epoch": int(dc.head.state.finalized_checkpoint.epoch)
                },
            }
        }
    )
    case_dir = _case_dir(root, fork, "fork_choice", "on_block", "chain_3_epochs")
    write_ssz_snappy(case_dir, "anchor_state", state_t, anchor_state)
    write_ssz_snappy(case_dir, "anchor_block", block_t, anchor_block)
    for name, blk in blocks.items():
        write_ssz_snappy(case_dir, name, signed_t, blk)
    write_yaml(case_dir, "steps", steps)


ALL_FORKS = [
    ForkName.phase0,
    ForkName.altair,
    ForkName.bellatrix,
    ForkName.capella,
    ForkName.eip4844,
]


def generate_all(root: str, forks=None) -> None:
    for fork in forks or ALL_FORKS:
        gen_operations(root, fork)
        gen_sanity(root, fork)
        if fork is not ForkName.phase0:
            gen_fork_upgrade(root, fork)
        if FORK_SEQ[fork] >= FORK_SEQ[ForkName.altair]:
            gen_rewards(root, fork)
    # the heavier multi-epoch suites on the two ends of the fork ladder
    for fork in (ForkName.phase0, (forks or ALL_FORKS)[-1]):
        gen_finality(root, fork)
        gen_fork_choice(root, fork)
