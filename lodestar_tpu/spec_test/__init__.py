"""Spec-conformance fixture harness (reference:
packages/spec-test-util/src/single.ts describeDirectorySpecTest +
beacon-node/test/spec/ runners).

Consumes the official ethereum/consensus-spec-tests directory layout:

    <suite root>/<case name>/
        meta.yaml                  (optional)
        <input>.ssz_snappy         (snappy-block-compressed SSZ)
        <input>.yaml               (YAML scalar/object inputs)
        post.ssz_snappy            (absent => the operation must FAIL)

A SpecTestCase lazily decodes files on access; run_directory_spec_test
walks every case dir, calls the suite runner, and enforces the
valid/invalid contract exactly like the reference harness: when the
expected `post` is absent the runner must raise, when present the
computed result must equal it bit-for-bit.

The same mechanism runs against locally generated fixtures (fixtures.py
writes dev-chain transitions in the official layout) because this
environment cannot download the published vectors; dropping the real
release tarball at the same root works unchanged.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import yaml

from lodestar_tpu.utils.snappy import compress as snappy_compress
from lodestar_tpu.utils.snappy import decompress as snappy_decompress


class SpecTestError(AssertionError):
    pass


@dataclass
class SpecTestCase:
    """One fixture directory; file contents decoded on demand."""

    name: str
    path: str
    input_types: Dict[str, object]  # file stem -> ssz type descriptor

    def files(self) -> List[str]:
        return sorted(os.listdir(self.path))

    def has(self, stem: str) -> bool:
        return os.path.exists(
            os.path.join(self.path, f"{stem}.ssz_snappy")
        ) or os.path.exists(os.path.join(self.path, f"{stem}.yaml"))

    def ssz(self, stem: str, ssz_type=None):
        """Decode `<stem>.ssz_snappy` with the declared (or given) type."""
        t = ssz_type or self.input_types.get(stem)
        if t is None:
            raise SpecTestError(f"{self.name}: no ssz type declared for {stem!r}")
        fn = os.path.join(self.path, f"{stem}.ssz_snappy")
        with open(fn, "rb") as f:
            return t.deserialize(snappy_decompress(f.read()))

    def raw(self, stem: str) -> bytes:
        with open(os.path.join(self.path, f"{stem}.ssz_snappy"), "rb") as f:
            return snappy_decompress(f.read())

    def yaml(self, stem: str):
        with open(os.path.join(self.path, f"{stem}.yaml")) as f:
            return yaml.safe_load(f)

    def meta(self) -> dict:
        if os.path.exists(os.path.join(self.path, "meta.yaml")):
            return self.yaml("meta")
        return {}


@dataclass
class SpecTestResult:
    suite: str
    passed: List[str] = field(default_factory=list)
    failed: List[str] = field(default_factory=list)
    errors: Dict[str, str] = field(default_factory=dict)

    def assert_ok(self) -> None:
        if self.failed:
            details = "; ".join(
                f"{n}: {self.errors.get(n, '?')}" for n in self.failed[:5]
            )
            raise SpecTestError(
                f"{self.suite}: {len(self.failed)}/{len(self.passed) + len(self.failed)}"
                f" cases failed ({details})"
            )
        if not self.passed:
            raise SpecTestError(f"{self.suite}: no cases found (silently skipped?)")


def run_directory_spec_test(
    root: str,
    runner: Callable[[SpecTestCase], Optional[bytes]],
    input_types: Optional[Dict[str, object]] = None,
    suite: Optional[str] = None,
    uses_post: bool = True,
) -> SpecTestResult:
    """Run every case directory under `root` through `runner`.

    Contract (single.ts:93 semantics):
    - runner returns the computed POST SSZ bytes (or None for pure checks);
    - a case with no post.ssz_snappy expects the runner to RAISE;
    - a case with post.ssz_snappy expects byte equality with the result.

    Suites whose validity is intrinsic to the runner (ssz_static, bls —
    no post files in the official layout) pass uses_post=False: every
    case must simply not raise.
    """
    result = SpecTestResult(suite=suite or os.path.basename(root))
    if not os.path.isdir(root):
        raise SpecTestError(f"spec test root missing: {root}")
    for name in sorted(os.listdir(root)):
        case_dir = os.path.join(root, name)
        if not os.path.isdir(case_dir):
            continue
        case = SpecTestCase(name=name, path=case_dir, input_types=input_types or {})
        expect_valid = case.has("post") if uses_post else True
        try:
            got = runner(case)
        except Exception as e:  # noqa: BLE001 — invalid cases raise anything
            if expect_valid:
                result.failed.append(name)
                result.errors[name] = f"raised {type(e).__name__}: {e}"
            else:
                result.passed.append(name)
            continue
        if not expect_valid:
            result.failed.append(name)
            result.errors[name] = "expected failure but runner succeeded"
            continue
        if got is not None:
            want = case.raw("post")
            if bytes(got) != want:
                result.failed.append(name)
                result.errors[name] = "post-state mismatch"
                continue
        result.passed.append(name)
    return result


# ---------------------------------------------------------------------------
# fixture writing (the generator half; downloadTests.ts replacement)
# ---------------------------------------------------------------------------


def write_ssz_snappy(case_dir: str, stem: str, ssz_type, value) -> None:
    os.makedirs(case_dir, exist_ok=True)
    with open(os.path.join(case_dir, f"{stem}.ssz_snappy"), "wb") as f:
        f.write(snappy_compress(ssz_type.serialize(value)))


def write_yaml(case_dir: str, stem: str, obj) -> None:
    os.makedirs(case_dir, exist_ok=True)
    with open(os.path.join(case_dir, f"{stem}.yaml"), "w") as f:
        yaml.safe_dump(obj, f)
