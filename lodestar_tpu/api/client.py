"""Beacon API HTTP client (reference: packages/api getClient fetch client)
— the seam the validator client uses to talk to the beacon node.
"""
from __future__ import annotations

from typing import List

from lodestar_tpu.execution.http_session import ReusedClientSession
from lodestar_tpu.ssz.json import from_json, to_json
from lodestar_tpu.types import ssz


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status


class ApiClient(ReusedClientSession):
    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")

    async def _get(self, path: str, **params):
        ses = await self._ses()
        async with ses.get(self.base_url + path, params=params or None) as resp:
            body = await resp.json()
            if resp.status >= 400:
                raise ApiError(resp.status, body.get("message", ""))
            return body

    async def _post(self, path: str, payload):
        ses = await self._ses()
        async with ses.post(self.base_url + path, json=payload) as resp:
            if resp.status >= 400:
                try:
                    body = await resp.json()
                    msg = body.get("message", "")
                except Exception as e:
                    # unparseable error body: surface the raw text (and
                    # the parse failure) through the ApiError instead
                    msg = (
                        await resp.text()
                        or f"<unparseable error body: {type(e).__name__}>"
                    )
                raise ApiError(resp.status, msg)
            return await resp.json() if resp.content_type == "application/json" else {}

    # beacon -----------------------------------------------------------

    async def get_json(self, path: str) -> dict:
        """Generic GET returning the route's `data` payload."""
        return (await self._get(path))["data"]

    async def get_state_ssz(self, state_id: str = "finalized"):
        """Download a full BeaconState (debug/getStateV2 SSZ route) — the
        client side of weak-subjectivity checkpoint sync."""
        from lodestar_tpu.db.beacon import _STATE_MF

        ses = await self._ses()
        async with ses.get(
            self.base_url + f"/eth/v2/debug/beacon/states/{state_id}"
        ) as resp:
            if resp.status >= 400:
                raise ApiError(resp.status, await resp.text())
            return _STATE_MF.deserialize(await resp.read())

    async def get_genesis(self) -> dict:
        return (await self._get("/eth/v1/beacon/genesis"))["data"]

    async def get_validators(self, state_id: str = "head") -> List[dict]:
        return (await self._get(f"/eth/v1/beacon/states/{state_id}/validators"))["data"]

    async def get_block_root(self, block_id: str = "head") -> bytes:
        data = (await self._get(f"/eth/v1/beacon/blocks/{block_id}/root"))["data"]
        return bytes.fromhex(data["root"][2:])

    async def publish_block(self, signed_block) -> None:
        await self._post(
            "/eth/v1/beacon/blocks", to_json(type(signed_block), signed_block)
        )

    async def submit_pool_attestations(self, atts) -> None:
        await self._post(
            "/eth/v1/beacon/pool/attestations",
            [to_json(ssz.phase0.Attestation, a) for a in atts],
        )

    # node -------------------------------------------------------------

    async def get_syncing(self) -> dict:
        return (await self._get("/eth/v1/node/syncing"))["data"]

    async def get_version(self) -> str:
        return (await self._get("/eth/v1/node/version"))["data"]["version"]

    # validator --------------------------------------------------------

    async def get_proposer_duties(self, epoch: int) -> List[dict]:
        return (await self._get(f"/eth/v1/validator/duties/proposer/{epoch}"))["data"]

    async def get_attester_duties(self, epoch: int, indices: List[int]) -> List[dict]:
        body = await self._post(
            f"/eth/v1/validator/duties/attester/{epoch}", [str(i) for i in indices]
        )
        return body["data"]

    async def produce_block(self, slot: int, randao_reveal: bytes, graffiti: str = ""):
        body = await self._get(
            f"/eth/v2/validator/blocks/{slot}",
            randao_reveal="0x" + randao_reveal.hex(),
            graffiti=graffiti,
        )
        # fork-aware decode via the response's version field (the
        # reference's getForkTypes(version) pattern) — an altair+ block
        # decoded as phase0 would silently drop sync_aggregate
        from lodestar_tpu.params import ForkName
        from lodestar_tpu.types import types_for

        fork = ForkName(body.get("version", "phase0"))
        return from_json(types_for(fork)[1], body["data"])

    async def produce_attestation_data(self, slot: int, committee_index: int):
        body = await self._get(
            "/eth/v1/validator/attestation_data",
            slot=str(slot),
            committee_index=str(committee_index),
        )
        return from_json(ssz.phase0.AttestationData, body["data"])

    async def get_aggregate(self, slot: int, data_root: bytes):
        body = await self._get(
            "/eth/v1/validator/aggregate_attestation",
            slot=str(slot),
            attestation_data_root="0x" + data_root.hex(),
        )
        return from_json(ssz.phase0.Attestation, body["data"])

    async def submit_aggregate_and_proofs(self, signed_aggs) -> None:
        await self._post(
            "/eth/v1/validator/aggregate_and_proofs",
            [to_json(ssz.phase0.SignedAggregateAndProof, s) for s in signed_aggs],
        )

    async def prepare_beacon_proposer(self, entries: List[dict]) -> None:
        """POST prepare_beacon_proposer: [{validator_index, fee_recipient}]."""
        payload = [
            {
                "validator_index": str(e["validator_index"]),
                "fee_recipient": "0x" + bytes(e["fee_recipient"]).hex(),
            }
            for e in entries
        ]
        await self._post("/eth/v1/validator/prepare_beacon_proposer", payload)

    # blinded / builder flow (routes/validator.ts:168,248) ----------------

    async def produce_blinded_block(
        self, slot: int, randao_reveal: bytes, graffiti: str = ""
    ):
        body = await self._get(
            f"/eth/v1/validator/blinded_blocks/{slot}",
            randao_reveal="0x" + randao_reveal.hex(),
            graffiti=graffiti,
        )
        from lodestar_tpu.params import ForkName
        from lodestar_tpu.types import blinded_types_for

        fork = ForkName(body.get("version", "bellatrix"))
        return from_json(blinded_types_for(fork)[0], body["data"])

    async def publish_blinded_block(self, signed_blinded) -> None:
        await self._post(
            "/eth/v1/beacon/blinded_blocks",
            to_json(type(signed_blinded), signed_blinded),
        )

    # sync-committee validator flow (routes/validator.ts:245-249) --------

    async def get_sync_duties(self, epoch: int, indices: List[int]) -> List[dict]:
        return (
            await self._post(
                f"/eth/v1/validator/duties/sync/{epoch}", [str(i) for i in indices]
            )
        )["data"]

    async def submit_pool_sync_committee_messages(self, messages) -> None:
        await self._post(
            "/eth/v1/beacon/pool/sync_committees",
            [to_json(ssz.altair.SyncCommitteeMessage, m) for m in messages],
        )

    async def produce_sync_committee_contribution(
        self, slot: int, subcommittee_index: int, beacon_block_root: bytes
    ):
        data = (
            await self._get(
                "/eth/v1/validator/sync_committee_contribution",
                slot=str(slot),
                subcommittee_index=str(subcommittee_index),
                beacon_block_root="0x" + beacon_block_root.hex(),
            )
        )["data"]
        return from_json(ssz.altair.SyncCommitteeContribution, data)

    async def submit_contribution_and_proofs(self, signed) -> None:
        await self._post(
            "/eth/v1/validator/contribution_and_proofs",
            [to_json(ssz.altair.SignedContributionAndProof, s) for s in signed],
        )

    async def prepare_sync_committee_subnets(self, subs: List[dict]) -> None:
        payload = [
            {
                "validator_index": str(s["validator_index"]),
                "sync_committee_indices": [
                    str(i) for i in s["sync_committee_indices"]
                ],
                "until_epoch": str(s.get("until_epoch", 0)),
            }
            for s in subs
        ]
        await self._post("/eth/v1/validator/sync_committee_subscriptions", payload)

    async def prepare_beacon_committee_subnet(self, subs: List[dict]) -> None:
        """POST beacon_committee_subscriptions (attestationDuties.ts
        subnet announcement; items carry is_aggregator).  Numerics are
        string-encoded uint64s per the beacon-API schema."""
        payload = [
            {
                "validator_index": str(s["validator_index"]),
                "committee_index": str(s["committee_index"]),
                "committees_at_slot": str(s["committees_at_slot"]),
                "slot": str(s["slot"]),
                "is_aggregator": bool(s["is_aggregator"]),
            }
            for s in subs
        ]
        await self._post("/eth/v1/validator/beacon_committee_subscriptions", payload)

    async def get_liveness(self, epoch: int, indices):
        """POST /eth/v1/validator/liveness/{epoch} (doppelganger source)."""
        return (await self._post(f"/eth/v1/validator/liveness/{epoch}",
                                 [str(i) for i in indices]))["data"]

    async def submit_attester_slashing(self, slashing) -> None:
        await self._post(
            "/eth/v1/beacon/pool/attester_slashings",
            to_json(ssz.phase0.AttesterSlashing, slashing),
        )

    async def submit_proposer_slashing(self, slashing) -> None:
        await self._post(
            "/eth/v1/beacon/pool/proposer_slashings",
            to_json(ssz.phase0.ProposerSlashing, slashing),
        )

    async def submit_voluntary_exit(self, signed_exit) -> None:
        await self._post(
            "/eth/v1/beacon/pool/voluntary_exits",
            to_json(ssz.phase0.SignedVoluntaryExit, signed_exit),
        )
