"""Beacon REST API server (reference: packages/api route definitions +
packages/beacon-node/src/api/{impl,rest} — fastify there, aiohttp here).

Implements the Eth Beacon API surface the validator client and tooling
consume: beacon (genesis/states/headers/blocks/pools), node, config,
validator duties + production, debug, events (SSE), plus the lodestar
namespace.
"""
from __future__ import annotations

import asyncio
import json
import time
from typing import Optional

from aiohttp import web

from lodestar_tpu.params import ACTIVE_PRESET as _p, ACTIVE_PRESET_NAME
from lodestar_tpu.ssz.json import from_json, to_json
from lodestar_tpu.state_transition.util.misc import (
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    get_block_root_at_slot,
)
from lodestar_tpu.types import ssz
from lodestar_tpu.chain.chain import ChainEvent

VERSION = "lodestar-tpu/0.2.0"


def _ok(data, **extra) -> web.Response:
    return web.json_response({"data": data, **extra})


def _err(code: int, message: str) -> web.Response:
    return web.json_response({"code": code, "message": message}, status=code)


class BeaconRestApiServer:
    """chain+db+network -> HTTP (BeaconRestApiServer role)."""

    def __init__(
        self, chain, db, network=None, sync=None, light_client_server=None,
        builder=None,
    ):
        self.light_client_server = light_client_server
        from lodestar_tpu.types import signed_block_wire_codec

        signed_block_wire_codec.configure(chain.cfg)
        self.chain = chain
        self.db = db
        self.network = network
        self.sync = sync
        self.builder = builder  # MEV builder API (HttpBuilderApi / MockBuilder)
        # prepareBeaconProposer registrations: proposer index -> fee
        # recipient, consumed by local payload production
        # (validator/src/services/prepareBeaconProposer.ts counterpart)
        self.fee_recipients: dict = {}
        self.app = web.Application()
        self._event_queues: list = []
        self._routes()
        self._runner: Optional[web.AppRunner] = None
        chain.on(ChainEvent.block, self._on_block_event)
        chain.on(ChainEvent.head, self._on_head_event)
        chain.on(ChainEvent.finalized, self._on_finalized_event)

    # ------------------------------------------------------------------

    def _routes(self) -> None:
        r = self.app.router
        # beacon
        r.add_get("/eth/v1/beacon/genesis", self.get_genesis)
        r.add_get("/eth/v1/beacon/states/{state_id}/root", self.get_state_root)
        r.add_get("/eth/v1/beacon/states/{state_id}/fork", self.get_state_fork)
        r.add_get(
            "/eth/v1/beacon/states/{state_id}/finality_checkpoints",
            self.get_finality_checkpoints,
        )
        r.add_get("/eth/v1/beacon/states/{state_id}/validators", self.get_validators)
        r.add_get(
            "/eth/v1/beacon/states/{state_id}/validators/{validator_id}",
            self.get_validator,
        )
        r.add_get("/eth/v1/beacon/headers/{block_id}", self.get_header)
        r.add_get("/eth/v2/beacon/blocks/{block_id}", self.get_block)
        r.add_get("/eth/v1/beacon/blocks/{block_id}/root", self.get_block_root)
        r.add_post("/eth/v1/beacon/blocks", self.post_block)
        r.add_post("/eth/v1/beacon/pool/attestations", self.post_pool_attestations)
        r.add_post("/eth/v1/beacon/pool/voluntary_exits", self.post_pool_exit)
        r.add_post(
            "/eth/v1/beacon/pool/bls_to_execution_changes",
            self.post_pool_bls_to_execution_change,
        )
        r.add_post(
            "/eth/v1/beacon/pool/attester_slashings", self.post_pool_attester_slashing
        )
        r.add_post(
            "/eth/v1/beacon/pool/proposer_slashings", self.post_pool_proposer_slashing
        )
        r.add_post("/eth/v1/validator/liveness/{epoch}", self.post_liveness)
        # node
        r.add_get("/eth/v1/node/version", self.get_version)
        r.add_get("/eth/v1/node/health", self.get_health)
        r.add_get("/eth/v1/node/syncing", self.get_syncing)
        r.add_get("/eth/v1/node/identity", self.get_identity)
        r.add_get("/eth/v1/node/peers", self.get_peers)
        # config
        r.add_get("/eth/v1/config/spec", self.get_spec)
        r.add_get("/eth/v1/config/deposit_contract", self.get_deposit_contract)
        # validator
        r.add_get("/eth/v1/validator/duties/proposer/{epoch}", self.get_proposer_duties)
        r.add_post("/eth/v1/validator/duties/attester/{epoch}", self.post_attester_duties)
        r.add_get("/eth/v2/validator/blocks/{slot}", self.produce_block)
        # blinded / builder flow (routes/validator.ts:168, beacon.ts blinded_blocks)
        r.add_get(
            "/eth/v1/validator/blinded_blocks/{slot}", self.produce_blinded_block
        )
        r.add_post("/eth/v1/beacon/blinded_blocks", self.post_blinded_block)
        r.add_get("/eth/v1/validator/attestation_data", self.produce_attestation_data)
        r.add_get("/eth/v1/validator/aggregate_attestation", self.get_aggregate)
        r.add_post("/eth/v1/validator/aggregate_and_proofs", self.post_aggregate_and_proofs)
        r.add_post(
            "/eth/v1/validator/beacon_committee_subscriptions",
            self.post_committee_subscriptions,
        )
        # sync-committee validator flow (beacon/routes/validator.ts:245-249)
        r.add_post("/eth/v1/validator/duties/sync/{epoch}", self.post_sync_duties)
        r.add_post(
            "/eth/v1/validator/prepare_beacon_proposer",
            self.post_prepare_beacon_proposer,
        )
        r.add_get(
            "/eth/v1/validator/sync_committee_contribution",
            self.get_sync_committee_contribution,
        )
        r.add_post(
            "/eth/v1/validator/contribution_and_proofs",
            self.post_contribution_and_proofs,
        )
        r.add_post(
            "/eth/v1/validator/sync_committee_subscriptions",
            self.post_sync_committee_subscriptions,
        )
        r.add_post("/eth/v1/beacon/pool/sync_committees", self.post_pool_sync_committees)
        # light client (beacon/routes/lightclient.ts)
        r.add_get(
            "/eth/v1/beacon/light_client/bootstrap/{block_root}",
            self.get_lc_bootstrap,
        )
        r.add_get("/eth/v1/beacon/light_client/updates", self.get_lc_updates)
        r.add_get(
            "/eth/v1/beacon/light_client/finality_update", self.get_lc_finality_update
        )
        r.add_get(
            "/eth/v1/beacon/light_client/optimistic_update",
            self.get_lc_optimistic_update,
        )
        # proofs (beacon/routes/proof.ts getStateProof role; deviation:
        # single field-path proofs via query param instead of compact
        # multiproof descriptors — the SSZ engine is value-backed)
        r.add_get("/eth/v1/beacon/proof/state/{state_id}", self.get_state_proof)
        # events + debug
        r.add_get("/eth/v1/events", self.get_events)
        r.add_get("/eth/v1/debug/beacon/heads", self.get_debug_heads)
        r.add_get(
            "/eth/v2/debug/beacon/states/{state_id}", self.get_debug_state_ssz
        )

    # ------------------------------------------------------------------
    # state helpers
    # ------------------------------------------------------------------

    def _resolve_state(self, state_id: str):
        if state_id == "head":
            return self.chain.get_head_state()
        if state_id in ("justified", "finalized"):
            # the actual checkpoint state — a checkpoint-sync client
            # anchoring on "finalized" must NOT receive the reorgable tip
            cp = getattr(self.chain.fork_choice.store, state_id)
            return self.chain.get_checkpoint_state(
                cp.epoch, bytes.fromhex(cp.root[2:])
            )
        if state_id.startswith("0x"):
            # by state root: search cache
            for root, cached in self.chain.state_cache._map.items():
                if cached.hash_tree_root().hex() == state_id[2:]:
                    return cached
            return None
        # by slot
        try:
            slot = int(state_id)
        except ValueError:
            return None
        st = self.chain.get_head_state()
        return st if st.state.slot == slot else None

    # ------------------------------------------------------------------
    # beacon handlers
    # ------------------------------------------------------------------

    async def get_genesis(self, request):
        st = self.chain.get_head_state().state
        return _ok(
            {
                "genesis_time": str(st.genesis_time),
                "genesis_validators_root": "0x"
                + bytes(st.genesis_validators_root).hex(),
                "genesis_fork_version": "0x"
                + self.chain.cfg.GENESIS_FORK_VERSION.hex(),
            }
        )

    async def get_state_root(self, request):
        st = self._resolve_state(request.match_info["state_id"])
        if st is None:
            return _err(404, "state not found")
        return _ok(
            {"root": "0x" + st.hash_tree_root().hex()},
            execution_optimistic=self._state_optimistic(st),
        )

    async def get_state_fork(self, request):
        st = self._resolve_state(request.match_info["state_id"])
        if st is None:
            return _err(404, "state not found")
        return _ok(to_json(ssz.phase0.Fork, st.state.fork))

    async def get_finality_checkpoints(self, request):
        st = self._resolve_state(request.match_info["state_id"])
        if st is None:
            return _err(404, "state not found")
        s = st.state
        return _ok(
            {
                "previous_justified": to_json(
                    ssz.phase0.Checkpoint, s.previous_justified_checkpoint
                ),
                "current_justified": to_json(
                    ssz.phase0.Checkpoint, s.current_justified_checkpoint
                ),
                "finalized": to_json(ssz.phase0.Checkpoint, s.finalized_checkpoint),
            },
            execution_optimistic=self._state_optimistic(st),
        )

    def _validator_status(self, v, epoch: int) -> str:
        from lodestar_tpu.params import FAR_FUTURE_EPOCH

        if epoch < v.activation_eligibility_epoch:
            return "pending_initialized"
        if epoch < v.activation_epoch:
            return "pending_queued"
        if epoch < v.exit_epoch:
            return "active_slashed" if v.slashed else "active_ongoing"
        if epoch < v.withdrawable_epoch:
            return "exited_slashed" if v.slashed else "exited_unslashed"
        return "withdrawal_possible"

    async def get_validators(self, request):
        st = self._resolve_state(request.match_info["state_id"])
        if st is None:
            return _err(404, "state not found")
        s = st.state
        epoch = compute_epoch_at_slot(s.slot)
        out = []
        for i, v in enumerate(s.validators):
            out.append(
                {
                    "index": str(i),
                    "balance": str(s.balances[i]),
                    "status": self._validator_status(v, epoch),
                    "validator": to_json(ssz.phase0.Validator, v),
                }
            )
        return _ok(out)

    async def get_validator(self, request):
        st = self._resolve_state(request.match_info["state_id"])
        if st is None:
            return _err(404, "state not found")
        vid = request.match_info["validator_id"]
        s = st.state
        if vid.startswith("0x"):
            pk = bytes.fromhex(vid[2:])
            index = st.epoch_ctx.pubkey2index.get(pk)
        else:
            index = int(vid)
        if index is None or index >= len(s.validators):
            return _err(404, "validator not found")
        v = s.validators[index]
        return _ok(
            {
                "index": str(index),
                "balance": str(s.balances[index]),
                "status": self._validator_status(v, compute_epoch_at_slot(s.slot)),
                "validator": to_json(ssz.phase0.Validator, v),
            }
        )

    def _resolve_block(self, block_id: str):
        if block_id == "head":
            return self.db.block.get(self.chain.head_root)
        if block_id.startswith("0x"):
            return self.db.block.get(bytes.fromhex(block_id[2:]))
        try:
            slot = int(block_id)
        except ValueError:
            return None
        node = self.chain.fork_choice.proto_array.get_ancestor_at_or_before_slot(
            "0x" + self.chain.head_root.hex(), slot
        )
        if node is not None and node.slot == slot:
            return self.db.block.get(bytes.fromhex(node.block_root[2:]))
        return self.db.block_archive.get(slot)

    def _optimistic_flag(self, root: bytes) -> bool:
        return self.chain.is_optimistic_root("0x" + bytes(root).hex())

    def _state_optimistic(self, st) -> bool:
        """execution_optimistic of the RESOLVED state (beacon-API: the
        flag is per requested resource, not per the node's head) — via
        the block root its latest header commits to."""
        hdr = st.state.latest_block_header
        h = ssz.phase0.BeaconBlockHeader(
            slot=hdr.slot, proposer_index=hdr.proposer_index,
            parent_root=bytes(hdr.parent_root),
            state_root=bytes(hdr.state_root),
            body_root=bytes(hdr.body_root),
        )
        if bytes(h.state_root) == b"\x00" * 32:
            h.state_root = st.hash_tree_root()
        return self._optimistic_flag(
            ssz.phase0.BeaconBlockHeader.hash_tree_root(h)
        )

    async def get_block(self, request):
        blk = self._resolve_block(request.match_info["block_id"])
        if blk is None:
            return _err(404, "block not found")
        root = type(blk.message).hash_tree_root(blk.message)
        return _ok(
            to_json(ssz.phase0.SignedBeaconBlock, blk),
            version="phase0",
            execution_optimistic=self._optimistic_flag(root),
        )

    async def get_block_root(self, request):
        blk = self._resolve_block(request.match_info["block_id"])
        if blk is None:
            return _err(404, "block not found")
        root = type(blk.message).hash_tree_root(blk.message)
        return _ok({"root": "0x" + root.hex()})

    async def get_header(self, request):
        blk = self._resolve_block(request.match_info["block_id"])
        if blk is None:
            return _err(404, "block not found")
        m = blk.message
        root = type(m).hash_tree_root(m)
        body_t = type(m)._fields_["body"]
        header = ssz.phase0.BeaconBlockHeader(
            slot=m.slot,
            proposer_index=m.proposer_index,
            parent_root=bytes(m.parent_root),
            state_root=bytes(m.state_root),
            body_root=body_t.hash_tree_root(m.body),
        )
        return _ok(
            {
                "root": "0x" + root.hex(),
                "canonical": True,
                "header": {
                    "message": to_json(ssz.phase0.BeaconBlockHeader, header),
                    "signature": "0x" + bytes(blk.signature).hex(),
                },
            }
        )

    async def post_block(self, request):
        body = await request.json()
        # fork-aware: the JSON's message.slot picks the container type
        from lodestar_tpu.types import signed_block_wire_codec, types_for

        slot = int(body["message"]["slot"])
        fork = signed_block_wire_codec.fork_at_slot(slot)
        signed = from_json(types_for(fork)[2], body)
        try:
            await self.chain.process_block(signed)
        except ValueError as e:
            return _err(400, str(e))
        if self.network is not None:
            await self.network.publish_block(signed)
        return web.json_response({}, status=200)

    async def post_pool_attestations(self, request):
        body = await request.json()
        failures = []
        for i, att_json in enumerate(body):
            att = from_json(ssz.phase0.Attestation, att_json)
            try:
                from lodestar_tpu.chain.validation import validate_gossip_attestation

                indices = await validate_gossip_attestation(self.chain, att)
                self.chain.attestation_pool.add(att)
                self.chain.fork_choice.on_attestation(
                    indices,
                    "0x" + bytes(att.data.beacon_block_root).hex(),
                    att.data.target.epoch,
                )
                if self.network is not None:
                    from lodestar_tpu.chain.validation import (
                        compute_subnet_for_attestation,
                    )

                    cps = self.chain.get_head_state().epoch_ctx.get_committee_count_per_slot(
                        att.data.target.epoch
                    )
                    subnet = compute_subnet_for_attestation(
                        cps, att.data.slot, att.data.index
                    )
                    await self.network.publish_attestation(att, subnet)
            except Exception as e:
                failures.append({"index": i, "message": str(e)})
        if failures:
            return web.json_response(
                {"code": 400, "message": "some failed", "failures": failures},
                status=400,
            )
        return web.json_response({}, status=200)

    async def post_pool_exit(self, request):
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_gossip_voluntary_exit,
        )

        body = await request.json()
        exit_ = from_json(ssz.phase0.SignedVoluntaryExit, body)
        try:
            await validate_gossip_voluntary_exit(self.chain, exit_)
        except GossipValidationError as e:
            return _err(400, str(e))
        self.chain.op_pool.add_voluntary_exit(exit_)
        return web.json_response({}, status=200)

    async def post_pool_bls_to_execution_change(self, request):
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_gossip_bls_to_execution_change,
        )

        body = await request.json()
        items = body if isinstance(body, list) else [body]
        for item in items:
            chg = from_json(ssz.capella.SignedBLSToExecutionChange, item)
            try:
                await validate_gossip_bls_to_execution_change(self.chain, chg)
            except GossipValidationError as e:
                return _err(400, str(e))
            self.chain.op_pool.add_bls_to_execution_change(chg)
        return web.json_response({}, status=200)

    # ------------------------------------------------------------------
    # node / config
    # ------------------------------------------------------------------

    async def get_version(self, request):
        return _ok({"version": VERSION})

    async def get_health(self, request):
        return web.Response(status=200)

    async def get_syncing(self, request):
        head = self.chain.fork_choice.get_head()
        current = self.chain.clock.current_slot
        distance = max(0, current - head.slot)
        return _ok(
            {
                "head_slot": str(head.slot),
                "sync_distance": str(distance),
                "is_syncing": distance > 1,
                # beacon-API: optimistic = head imported without an EL
                # verdict; el_offline = no EL attached, or the last
                # engine call failed at transport level
                "is_optimistic": self.chain.is_optimistic_head(),
                "el_offline": (
                    self.chain.execution_engine is None
                    or self.chain.el_offline
                ),
            }
        )

    async def get_identity(self, request):
        pid = self.network.peer_id if self.network else "unknown"
        return _ok(
            {
                "peer_id": pid,
                "enr": "",
                "p2p_addresses": [],
                "discovery_addresses": [],
                "metadata": {"seq_number": "0", "attnets": "0x" + "00" * 8},
            }
        )

    async def get_peers(self, request):
        peers = []
        if self.network:
            for pid in self.network.peer_manager.connected_peers():
                peers.append(
                    {
                        "peer_id": pid,
                        "state": "connected",
                        "direction": "outbound",
                        "last_seen_p2p_address": "",
                        "enr": "",
                    }
                )
        return _ok(peers, meta={"count": len(peers)})

    async def get_spec(self, request):
        from dataclasses import fields as dc_fields

        out = {}
        for f in dc_fields(type(self.chain.cfg)):
            v = getattr(self.chain.cfg, f.name)
            out[f.name] = "0x" + v.hex() if isinstance(v, bytes) else str(v)
        for name in dir(_p):
            if name.isupper():
                out[name] = str(getattr(_p, name))
        out["PRESET_BASE"] = ACTIVE_PRESET_NAME
        return _ok(out)

    async def get_deposit_contract(self, request):
        return _ok(
            {
                "chain_id": str(self.chain.cfg.DEPOSIT_CHAIN_ID),
                "address": "0x" + self.chain.cfg.DEPOSIT_CONTRACT_ADDRESS.hex(),
            }
        )

    # ------------------------------------------------------------------
    # validator handlers
    # ------------------------------------------------------------------

    def _state_for_epoch(self, epoch: int):
        """Head state advanced (dirty-clone) into `epoch` if it has already
        started on the clock but no block has arrived yet (the reference
        regens the epoch-start state for duties)."""
        from lodestar_tpu.state_transition import process_slots

        st = self.chain.get_head_state()
        if epoch == st.epoch_ctx.epoch:
            return st
        start = compute_start_slot_at_epoch(epoch)
        if st.state.slot < start and epoch <= compute_epoch_at_slot(
            max(self.chain.clock.current_slot, start)
        ):
            advanced = st.clone()
            process_slots(advanced, start)
            return advanced
        return st

    async def get_proposer_duties(self, request):
        epoch = int(request.match_info["epoch"])
        st = self._state_for_epoch(epoch)
        if epoch != st.epoch_ctx.epoch:
            return _err(400, f"epoch {epoch} not current")
        duties = []
        start = compute_start_slot_at_epoch(epoch)
        for i, proposer in enumerate(st.epoch_ctx.proposers):
            pk = bytes(st.state.validators[proposer].pubkey)
            duties.append(
                {
                    "pubkey": "0x" + pk.hex(),
                    "validator_index": str(proposer),
                    "slot": str(start + i),
                }
            )
        return _ok(duties, dependent_root="0x" + self.chain.head_root.hex())

    async def post_attester_duties(self, request):
        epoch = int(request.match_info["epoch"])
        indices = [int(i) for i in await request.json()]
        st = self._state_for_epoch(epoch)
        try:
            shuffling = st.epoch_ctx.get_shuffling(epoch)
        except ValueError:
            return _err(400, f"epoch {epoch} out of range")
        duties = []
        start = compute_start_slot_at_epoch(epoch)
        for slot in range(start, start + _p.SLOTS_PER_EPOCH):
            for cidx in range(shuffling.committees_per_slot):
                committee = shuffling.committee(slot, cidx)
                for pos, vi in enumerate(committee):
                    if int(vi) in indices:
                        pk = bytes(st.state.validators[int(vi)].pubkey)
                        duties.append(
                            {
                                "pubkey": "0x" + pk.hex(),
                                "validator_index": str(int(vi)),
                                "committee_index": str(cidx),
                                "committee_length": str(len(committee)),
                                "committees_at_slot": str(shuffling.committees_per_slot),
                                "validator_committee_index": str(pos),
                                "slot": str(slot),
                            }
                        )
        return _ok(duties, dependent_root="0x" + self.chain.head_root.hex())

    async def produce_block(self, request):
        slot = int(request.match_info["slot"])
        if self.chain.is_optimistic_head():
            # sync/optimistic.md: an optimistic node MUST NOT produce
            # blocks — the EL has not validated the chain it would
            # build on (503 = beacon-API "unable to respond: syncing")
            return _err(503, "head is optimistic (EL has not validated it)")
        randao_reveal = bytes.fromhex(
            request.query.get("randao_reveal", "0x" + "00" * 96)[2:]
        )
        graffiti = request.query.get("graffiti", "")
        block = await self._produce_block(slot, randao_reveal, graffiti)
        from lodestar_tpu.types import fork_of_block

        fork = fork_of_block(block)
        if self.chain.metrics:
            self.chain.metrics.lodestar.blocks_produced_total.labels(
                flavor="full"
            ).inc()
        return _ok(
            to_json(type(block), block),
            version=fork.value,
            execution_payload_blinded=False,
        )

    async def _produce_block(self, slot, randao_reveal, graffiti=""):
        """produceBlockWrapper + produceBlockBody in miniature."""
        from lodestar_tpu.state_transition import process_slots, state_transition

        _t0 = time.perf_counter()

        head_state = self.chain.get_head_state()
        pre = head_state.clone()
        if pre.state.slot < slot:
            process_slots(pre, slot)
        proposer = pre.epoch_ctx.get_beacon_proposer(slot)
        atts = self.chain.aggregated_attestation_pool.get_attestations_for_block(slot)
        prop_slash, att_slash, exits = self.chain.op_pool.get_slashings_and_exits(
            pre.state
        )
        # eth1 data vote + due deposits (produceBlockBody.ts eth1 section)
        eth1_tracker = getattr(self.chain, "eth1", None)
        eth1_data = pre.state.eth1_data
        deposits = []
        if eth1_tracker is not None:
            eth1_data = eth1_tracker.get_eth1_vote(pre.state)
            # deposits must be counted/proven against the eth1_data the
            # block CARRIES: process_eth1_data may flip state.eth1_data to
            # this vote before process_operations checks deposit counts
            deposits = eth1_tracker.get_deposits(pre.state, eth1_data)
        g = graffiti.encode()[:32].ljust(32, b"\x00") if isinstance(graffiti, str) else graffiti
        from lodestar_tpu.types import fork_of_state, types_for

        fork = fork_of_state(pre.state)
        _, block_t, signed_t, body_t = types_for(fork)
        body = body_t(
            randao_reveal=randao_reveal,
            eth1_data=eth1_data,
            graffiti=g,
            proposer_slashings=prop_slash,
            attester_slashings=att_slash,
            attestations=atts,
            deposits=deposits,
            voluntary_exits=exits,
        )
        if hasattr(body, "sync_aggregate"):
            # assemble from the contribution pool (produceBlockBody.ts
            # syncAggregate from SyncContributionAndProofPool)
            body.sync_aggregate = self.chain.sync_contribution_pool.get_sync_aggregate(
                slot, self.chain.head_root
            )
        if hasattr(body, "bls_to_execution_changes"):
            body.bls_to_execution_changes = (
                self.chain.op_pool.get_bls_to_execution_changes(pre.state)
            )
        if hasattr(body, "execution_payload"):
            from lodestar_tpu.state_transition.block.bellatrix import (
                is_merge_transition_complete,
            )

            if is_merge_transition_complete(pre.state):
                from lodestar_tpu.execution.engine import build_dev_payload

                fee_recipient = self.fee_recipients.get(proposer, b"\x00" * 20)
                payload = None
                if self.chain.execution_engine is not None:
                    payload = await self._produce_engine_payload(
                        pre, slot, fee_recipient
                    )
                if payload is None:
                    # watchdog fallback (or no engine): a complete
                    # locally-built payload, never a half-built block
                    payload = build_dev_payload(
                        self.chain.cfg, pre.state, fee_recipient=fee_recipient
                    )
                body.execution_payload = payload
        hdr = head_state.state.latest_block_header
        parent_hdr = ssz.phase0.BeaconBlockHeader(
            slot=hdr.slot, proposer_index=hdr.proposer_index,
            parent_root=bytes(hdr.parent_root), state_root=bytes(hdr.state_root),
            body_root=bytes(hdr.body_root),
        )
        if bytes(parent_hdr.state_root) == b"\x00" * 32:
            parent_hdr.state_root = head_state.hash_tree_root()
        block = block_t(
            slot=slot,
            proposer_index=proposer,
            parent_root=ssz.phase0.BeaconBlockHeader.hash_tree_root(parent_hdr),
            state_root=b"\x00" * 32,
            body=body,
        )
        trial = signed_t(message=block, signature=b"\x00" * 96)
        post = state_transition(
            self.chain.get_head_state(), trial,
            verify_state_root=False, verify_proposer=False, verify_signatures=False,
        )
        block.state_root = post.hash_tree_root()
        if self.chain.metrics:
            self.chain.metrics.lodestar.produce_block_seconds.observe(
                time.perf_counter() - _t0
            )
        return block

    async def _produce_engine_payload(self, pre, slot, fee_recipient):
        """Engine-backed payload for the proposal: forkchoiceUpdated
        with attributes → getPayload, raced against the proposal
        deadline (one slot interval).  Returns None on any failure —
        the caller falls back to the locally-built payload, so a
        stalling or refusing EL degrades production instead of killing
        it (the watchdog counts the distinct fallback metric)."""
        import asyncio as _asyncio

        from lodestar_tpu.execution.engine import dev_payload_attributes
        from lodestar_tpu.execution.payload_builder import (
            PayloadDeadlineError,
            produce_engine_payload,
        )
        from lodestar_tpu.params import INTERVALS_PER_SLOT

        metrics = self.chain.metrics.lodestar if self.chain.metrics else None
        try:
            # everything from attribute building onward funnels into the
            # fallback: a pre-request failure (serde, attribute shape)
            # must degrade production, not 500 it
            st = pre.state
            clock = self.chain.clock
            cfg = self.chain.cfg
            # budget: until one interval into the slot (the attestation
            # deadline); a late request still gets a small floor so a
            # healthy EL can answer
            deadline_s = max(
                0.25,
                clock.slot_start_time(slot)
                + cfg.SECONDS_PER_SLOT / INTERVALS_PER_SLOT
                - clock._now(),
            )
            attrs = dev_payload_attributes(
                cfg, st, fee_recipient=fee_recipient,
                parent_beacon_block_root=self.chain.head_root,
            )
            fin = self.chain.fork_choice.get_block(
                self.chain.fork_choice.store.finalized.root
            )
            fin_hash = (
                bytes.fromhex(fin.execution_payload_block_hash[2:])
                if fin is not None and fin.execution_payload_block_hash
                else b"\x00" * 32
            )
            head_hash = bytes(st.latest_execution_payload_header.block_hash)
            return await produce_engine_payload(
                self.chain.execution_engine,
                head_block_hash=head_hash,
                safe_block_hash=head_hash,
                finalized_block_hash=fin_hash,
                attrs=attrs,
                deadline_s=deadline_s,
                metrics=metrics,
                log=lambda m: None,
            )
        except _asyncio.CancelledError:
            raise
        except PayloadDeadlineError:
            return None
        except Exception:
            # pre-request failures (serde, attribute shape) also fall
            # back; the fallback payload is complete either way
            if metrics is not None:
                metrics.produce_payload_fallbacks_total.labels(
                    reason="error"
                ).inc()
            return None

    async def produce_blinded_block(self, request):
        """produceBlindedBlock (routes/validator.ts:168): a block whose body
        commits to an ExecutionPayloadHeader.  With a builder configured the
        header is the builder's bid (getHeader); otherwise the locally-built
        payload is blinded — HTR(header) == HTR(payload) by SSZ design, so
        the full-block state_root carries over unchanged."""
        from lodestar_tpu.state_transition import state_transition
        from lodestar_tpu.types import blinded_types_for, fork_of_block, types_for

        slot = int(request.match_info["slot"])
        if self.chain.is_optimistic_head():
            return _err(503, "head is optimistic (EL has not validated it)")
        randao_reveal = bytes.fromhex(
            request.query.get("randao_reveal", "0x" + "00" * 96)[2:]
        )
        graffiti = request.query.get("graffiti", "")
        full = await self._produce_block(slot, randao_reveal, graffiti)
        fork = fork_of_block(full)
        try:
            blinded_block_t, blinded_signed_t, blinded_body_t = blinded_types_for(fork)
        except KeyError:
            return _err(400, f"{fork.value} has no blinded block flow")
        mod = getattr(ssz, fork.value)
        body_kwargs = {}
        for n in blinded_body_t._fields_:
            if n == "execution_payload_header":
                continue
            body_kwargs[n] = getattr(full.body, n)
        header = mod.payload_to_header(full.body.execution_payload)
        state_root = bytes(full.state_root)
        if self.builder is not None:
            st = self.chain.get_head_state()
            parent_hash = bytes(st.state.latest_execution_payload_header.block_hash)
            pubkey = bytes(st.state.validators[full.proposer_index].pubkey)
            try:
                bid = await self.builder.get_header(slot, parent_hash, pubkey)
                header = bid.message.header
            except Exception as e:
                return _err(502, f"builder getHeader failed: {e}")
            if self.chain.metrics:
                self.chain.metrics.lodestar.builder_bids_total.inc()
            # builder payload differs from the local one: re-run the
            # (blinded) STF to get the right post-state root
            trial_body = blinded_body_t(
                execution_payload_header=header, **body_kwargs
            )
            trial = blinded_signed_t(
                message=blinded_block_t(
                    slot=full.slot,
                    proposer_index=full.proposer_index,
                    parent_root=bytes(full.parent_root),
                    state_root=b"\x00" * 32,
                    body=trial_body,
                ),
                signature=b"\x00" * 96,
            )
            post = state_transition(
                self.chain.get_head_state(), trial,
                verify_state_root=False, verify_proposer=False,
                verify_signatures=False,
            )
            state_root = post.hash_tree_root()
        blinded = blinded_block_t(
            slot=full.slot,
            proposer_index=full.proposer_index,
            parent_root=bytes(full.parent_root),
            state_root=state_root,
            body=blinded_body_t(execution_payload_header=header, **body_kwargs),
        )
        if self.chain.metrics:
            self.chain.metrics.lodestar.blocks_produced_total.labels(
                flavor="blinded"
            ).inc()
        return _ok(
            to_json(blinded_block_t, blinded),
            version=fork.value,
            execution_payload_blinded=True,
        )

    async def post_blinded_block(self, request):
        """publishBlindedBlock: unblind via the builder (submitBlindedBlock
        reveals the payload), reassemble the full signed block — same
        signature, since blinded and full blocks share their signing root —
        and import+gossip it (reference publishBlindedBlock)."""
        from lodestar_tpu.types import blinded_types_for, signed_block_wire_codec, types_for

        body = await request.json()
        slot = int(body["message"]["slot"])
        fork = signed_block_wire_codec.fork_at_slot(slot)
        try:
            _, blinded_signed_t, _ = blinded_types_for(fork)
        except KeyError:
            return _err(400, f"{fork.value} has no blinded block flow")
        signed = from_json(blinded_signed_t, body)
        if self.builder is None:
            return _err(400, "no builder configured to unblind")
        try:
            payload = await self.builder.submit_blinded_block(signed)
        except Exception as e:
            return _err(502, f"builder submitBlindedBlock failed: {e}")
        if bytes(payload.block_hash) != bytes(
            signed.message.body.execution_payload_header.block_hash
        ):
            return _err(400, "builder revealed a different payload")
        if self.chain.metrics:
            self.chain.metrics.lodestar.builder_unblinds_total.inc()
        _, block_t, signed_t, body_t = types_for(fork)
        body_kwargs = {
            n: getattr(signed.message.body, n)
            for n in body_t._fields_
            if n != "execution_payload"
        }
        full = signed_t(
            message=block_t(
                slot=signed.message.slot,
                proposer_index=signed.message.proposer_index,
                parent_root=bytes(signed.message.parent_root),
                state_root=bytes(signed.message.state_root),
                body=body_t(execution_payload=payload, **body_kwargs),
            ),
            signature=bytes(signed.signature),
        )
        try:
            await self.chain.process_block(full)
        except ValueError as e:
            return _err(400, str(e))
        if self.network is not None:
            await self.network.publish_block(full)
        return web.json_response({}, status=200)

    async def produce_attestation_data(self, request):
        slot = int(request.query["slot"])
        committee_index = int(request.query["committee_index"])
        st = self.chain.get_head_state()
        s = st.state
        epoch = compute_epoch_at_slot(slot)
        start = compute_start_slot_at_epoch(epoch)
        head_root = self.chain.head_root
        if start >= s.slot:
            target_root = head_root
        else:
            target_root = get_block_root_at_slot(s, start)
        data = ssz.phase0.AttestationData(
            slot=slot,
            index=committee_index,
            beacon_block_root=head_root,
            source=s.current_justified_checkpoint,
            target=ssz.phase0.Checkpoint(epoch=epoch, root=target_root),
        )
        return _ok(to_json(ssz.phase0.AttestationData, data))

    async def get_aggregate(self, request):
        slot = int(request.query["slot"])
        data_root = bytes.fromhex(
            request.query["attestation_data_root"].removeprefix("0x")
        )
        agg = self.chain.attestation_pool.get_aggregate(slot, data_root)
        if agg is None:
            return _err(404, "no matching aggregate")
        return _ok(to_json(ssz.phase0.Attestation, agg))

    async def post_aggregate_and_proofs(self, request):
        body = await request.json()
        for item in body:
            signed = from_json(ssz.phase0.SignedAggregateAndProof, item)
            from lodestar_tpu.chain.validation import (
                validate_gossip_aggregate_and_proof,
            )

            try:
                indices = await validate_gossip_aggregate_and_proof(self.chain, signed)
            except Exception as e:
                return _err(400, str(e))
            agg = signed.message.aggregate
            self.chain.aggregated_attestation_pool.add(agg)
            self.chain.fork_choice.on_attestation(
                indices,
                "0x" + bytes(agg.data.beacon_block_root).hex(),
                agg.data.target.epoch,
            )
            if self.network is not None:
                await self.network.publish_aggregate(signed)
        return web.json_response({}, status=200)

    async def post_committee_subscriptions(self, request):
        """prepareBeaconCommitteeSubnet (api/impl/validator): feed the
        attnets service so duty subnets get meshed ahead of time."""
        body = await request.json()
        svc = getattr(self.network, "attnets_service", None) if self.network else None
        if svc is not None:
            from lodestar_tpu.network.subnets import CommitteeSubscription

            try:
                subs = [
                    CommitteeSubscription(
                        validator_index=int(item["validator_index"]),
                        committees_at_slot=int(item["committees_at_slot"]),
                        slot=int(item["slot"]),
                        committee_index=int(item["committee_index"]),
                        is_aggregator=bool(item.get("is_aggregator", False)),
                    )
                    for item in body
                ]
            except (TypeError, KeyError, ValueError) as e:
                return _err(400, f"bad subscription item: {e!r}")
            svc.add_committee_subscriptions(subs)
        return web.json_response({}, status=200)

    # ------------------------------------------------------------------
    # sync-committee validator flow (the reference's
    # api/src/beacon/routes/validator.ts:245-249 + impl/validator/index.ts
    # getSyncCommitteeDuties / produceSyncCommitteeContribution /
    # publishContributionAndProofs / prepareSyncCommitteeSubnets, and the
    # beacon pool route submitPoolSyncCommitteeSignatures)
    # ------------------------------------------------------------------

    def _sync_committee_for_epoch(self, st, epoch: int):
        """current or next committee by sync-committee period of `epoch`
        relative to the state's period (spec: compute_sync_committee_period)."""
        per = _p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
        state_period = st.state.slot // _p.SLOTS_PER_EPOCH // per
        period = epoch // per
        if period == state_period:
            return st.state.current_sync_committee
        if period == state_period + 1:
            return st.state.next_sync_committee
        return None

    async def post_sync_duties(self, request):
        epoch = int(request.match_info["epoch"])
        indices = [int(i) for i in await request.json()]
        st = self.chain.get_head_state()
        if not hasattr(st.state, "current_sync_committee"):
            return _err(400, "pre-altair state has no sync committees")
        committee = self._sync_committee_for_epoch(st, epoch)
        if committee is None:
            return _err(400, f"epoch {epoch} outside current+next sync periods")
        by_pubkey = {}
        for pos, pk in enumerate(committee.pubkeys):
            by_pubkey.setdefault(bytes(pk), []).append(pos)
        duties = []
        for vi in indices:
            if vi >= len(st.state.validators):
                continue
            pk = bytes(st.state.validators[vi].pubkey)
            positions = by_pubkey.get(pk)
            if positions:
                duties.append(
                    {
                        "pubkey": "0x" + pk.hex(),
                        "validator_index": str(vi),
                        "validator_sync_committee_indices": [
                            str(p) for p in positions
                        ],
                    }
                )
        return _ok(duties, execution_optimistic=False)

    async def post_prepare_beacon_proposer(self, request):
        """prepareBeaconProposer (routes/validator.ts prepareBeaconProposer):
        fee-recipient registrations consumed by local payload production."""
        body = await request.json()
        try:
            for item in body:
                vi = int(item["validator_index"])
                fr = bytes.fromhex(item["fee_recipient"].removeprefix("0x"))
                if len(fr) != 20:
                    return _err(400, "fee_recipient must be 20 bytes")
                self.fee_recipients[vi] = fr
        except (TypeError, KeyError, ValueError) as e:
            return _err(400, f"bad prepare_beacon_proposer item: {e!r}")
        return web.json_response({}, status=200)

    async def post_pool_sync_committees(self, request):
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_sync_committee_message,
        )
        from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_SIZE

        body = await request.json()
        for item in body:
            message = from_json(ssz.altair.SyncCommitteeMessage, item)
            st = self.chain.get_head_state()
            if message.validator_index >= len(st.state.validators):
                return _err(400, "unknown validator index")
            positions = [
                i
                for i, cpk in enumerate(st.state.current_sync_committee.pubkeys)
                if bytes(cpk)
                == bytes(st.state.validators[message.validator_index].pubkey)
            ]
            if not positions:
                return _err(400, "validator not in current sync committee")
            subnets = {p // SYNC_COMMITTEE_SUBNET_SIZE for p in positions}
            for subnet in subnets:
                try:
                    sub_positions = await validate_sync_committee_message(
                        self.chain, message, subnet
                    )
                except GossipValidationError as e:
                    return _err(400, f"invalid sync committee message: {e}")
                for p in sub_positions:
                    self.chain.sync_committee_message_pool.add(subnet, p, message)
                if self.network is not None:
                    await self.network.publish_sync_committee_message(message, subnet)
        return web.json_response({}, status=200)

    async def get_sync_committee_contribution(self, request):
        slot = int(request.query["slot"])
        subcommittee_index = int(request.query["subcommittee_index"])
        root = bytes.fromhex(request.query["beacon_block_root"].removeprefix("0x"))
        contribution = self.chain.sync_committee_message_pool.get_contribution(
            slot, root, subcommittee_index
        )
        if contribution is None:
            return _err(404, "no contribution available")
        return _ok(to_json(ssz.altair.SyncCommitteeContribution, contribution))

    async def post_contribution_and_proofs(self, request):
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_sync_committee_contribution,
        )

        body = await request.json()
        for item in body:
            signed = from_json(ssz.altair.SignedContributionAndProof, item)
            try:
                await validate_sync_committee_contribution(self.chain, signed)
            except GossipValidationError as e:
                return _err(400, f"invalid contribution: {e}")
            self.chain.sync_contribution_pool.add(signed.message.contribution)
            if self.network is not None:
                await self.network.publish_sync_contribution(signed)
        return web.json_response({}, status=200)

    async def post_sync_committee_subscriptions(self, request):
        """prepareSyncCommitteeSubnets: mesh the syncnet subnets for the
        requested validators ahead of their duties."""
        body = await request.json()
        svc = getattr(self.network, "syncnets_service", None) if self.network else None
        if svc is not None:
            st = self.chain.get_head_state()
            positions = []
            for item in body:
                try:
                    vi = int(item["validator_index"])
                    idxs = [int(i) for i in item["sync_committee_indices"]]
                except (TypeError, KeyError, ValueError) as e:
                    return _err(400, f"bad subscription item: {e!r}")
                if vi >= len(st.state.validators):
                    continue
                positions.extend(idxs)
            svc.subscribe_for_positions(positions)
        return web.json_response({}, status=200)

    # ------------------------------------------------------------------
    # events (SSE) + debug
    # ------------------------------------------------------------------

    def _on_block_event(self, signed_block, root):
        self._push_event(
            "block",
            {
                "slot": str(signed_block.message.slot),
                "block": "0x" + root.hex(),
                "execution_optimistic": self._optimistic_flag(root),
            },
        )

    def _on_head_event(self, root):
        head = self.chain.fork_choice.get_head()
        self._push_event(
            "head",
            {
                "slot": str(head.slot),
                "block": "0x" + root.hex(),
                "state": head.state_root,
                "epoch_transition": head.slot % _p.SLOTS_PER_EPOCH == 0,
                "execution_optimistic": self._optimistic_flag(root),
            },
        )

    def _on_finalized_event(self, cp):
        self._push_event(
            "finalized_checkpoint",
            {"epoch": str(cp.epoch), "block": cp.root, "state": cp.root},
        )

    def _push_event(self, topic: str, data: dict) -> None:
        for queue, topics in self._event_queues:
            if topic in topics:
                queue.put_nowait((topic, data))

    async def get_events(self, request):
        topics = request.query.get("topics", "head,block,finalized_checkpoint").split(",")
        queue: asyncio.Queue = asyncio.Queue()
        entry = (queue, topics)
        self._event_queues.append(entry)
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
            }
        )
        await resp.prepare(request)
        try:
            while True:
                topic, data = await queue.get()
                payload = f"event: {topic}\ndata: {json.dumps(data)}\n\n"
                await resp.write(payload.encode())
        except asyncio.CancelledError:
            raise  # server shutdown / client gone; aiohttp expects it
        except ConnectionResetError:
            pass
        finally:
            self._event_queues.remove(entry)
        return resp

    async def get_state_proof(self, request):
        """Merkle proof of a state field path against the state root
        (proof.ts getStateProof; path=dot-separated container fields)."""
        path = request.query.get("path", "")
        if not path:
            return _err(400, "missing ?path=field[.field...]")
        st = self._resolve_state(request.match_info["state_id"])
        if st is None:
            return _err(404, "state not found")
        from lodestar_tpu.ssz.proof import container_field_proof

        state = st.state
        try:
            leaf, branch, depth, index = container_field_proof(
                type(state), state, path.split(".")
            )
        except (KeyError, ValueError, AttributeError) as e:
            return _err(400, f"bad path: {e!r}")
        gindex = (1 << depth) | index
        # derive the apex from the proof itself (a second full-state
        # merkleization here would double a multi-second hash pass on
        # mainnet-scale states)
        import hashlib as _hl

        node, idx = leaf, index
        for sib in branch:
            pair = sib + node if idx & 1 else node + sib
            node = _hl.sha256(pair).digest()
            idx >>= 1
        return _ok(
            {
                "leaf": "0x" + leaf.hex(),
                "branch": ["0x" + b.hex() for b in branch],
                "depth": depth,
                "index": index,
                "gindex": str(gindex),
                "state_root": "0x" + node.hex(),
            }
        )

    async def get_debug_state_ssz(self, request):
        """Full state as fork-tagged SSZ bytes (debug/getStateV2 role) —
        the trusted-node side of weak-subjectivity checkpoint sync
        (fetchWeakSubjectivityState downloads exactly this)."""
        st = self._resolve_state(request.match_info["state_id"])
        if st is None:
            return _err(404, "state not found")
        from lodestar_tpu.db.beacon import _STATE_MF

        return web.Response(
            body=_STATE_MF.serialize(st.state),
            content_type="application/octet-stream",
        )

    async def get_debug_heads(self, request):
        heads = []
        arr = self.chain.fork_choice.proto_array
        children = {n.parent for n in arr.nodes if n.parent is not None}
        from lodestar_tpu.fork_choice import ExecutionStatus

        for i, node in enumerate(arr.nodes):
            if i not in children:
                heads.append(
                    {"root": node.block_root, "slot": str(node.slot),
                     "execution_optimistic": (
                         node.execution_status is ExecutionStatus.Optimistic
                     )}
                )
        return _ok(heads)

    # ------------------------------------------------------------------

    async def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        return site._server.sockets[0].getsockname()[1]

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        # HttpBuilderApi keeps a reused aiohttp session; release it with
        # the server (MockBuilder / no-builder configs have no close)
        builder_close = getattr(self.builder, "close", None)
        if builder_close is not None:
            await builder_close()


    # ------------------------------------------------------------------
    # light client routes (api/impl/lightclient)
    # ------------------------------------------------------------------

    async def get_lc_bootstrap(self, request):
        if self.light_client_server is None:
            return _err(501, "light client server not enabled")
        root = bytes.fromhex(request.match_info["block_root"].replace("0x", ""))
        bs = self.light_client_server.get_bootstrap(root)
        if bs is None:
            return _err(404, "no bootstrap for that root")
        return _ok(to_json(ssz.altair.LightClientBootstrap, bs))

    async def get_lc_updates(self, request):
        if self.light_client_server is None:
            return _err(501, "light client server not enabled")
        start = int(request.query.get("start_period", 0))
        count = min(128, int(request.query.get("count", 1)))
        out = []
        for period in range(start, start + count):
            u = self.light_client_server.get_update(period)
            if u is not None:
                out.append(to_json(ssz.altair.LightClientUpdate, u))
        return _ok(out)

    async def get_lc_finality_update(self, request):
        if self.light_client_server is None:
            return _err(501, "light client server not enabled")
        u = self.light_client_server.latest_finality_update
        if u is None:
            return _err(404, "no finality update yet")
        return _ok(to_json(ssz.altair.LightClientFinalityUpdate, u))

    async def get_lc_optimistic_update(self, request):
        if self.light_client_server is None:
            return _err(501, "light client server not enabled")
        u = self.light_client_server.latest_optimistic_update
        if u is None:
            return _err(404, "no optimistic update yet")
        return _ok(to_json(ssz.altair.LightClientOptimisticUpdate, u))


    # ------------------------------------------------------------------
    # slashing pools + liveness (flare/doppelganger support)
    # ------------------------------------------------------------------

    async def post_pool_attester_slashing(self, request):
        body = await request.json()
        s = from_json(ssz.phase0.AttesterSlashing, body)
        from lodestar_tpu.state_transition.block.phase0 import (
            is_slashable_attestation_data,
            is_valid_indexed_attestation,
        )

        st = self.chain.get_head_state()
        if not is_slashable_attestation_data(s.attestation_1.data, s.attestation_2.data):
            return _err(400, "attestations are not slashable")
        for att in (s.attestation_1, s.attestation_2):
            if not is_valid_indexed_attestation(
                self.chain.cfg, st.state, att, verify_signature=True
            ):
                return _err(400, "invalid indexed attestation")
        self.chain.op_pool.add_attester_slashing(s)
        return _ok(None)

    async def post_pool_proposer_slashing(self, request):
        body = await request.json()
        s = from_json(ssz.phase0.ProposerSlashing, body)
        from lodestar_tpu.state_transition.signature_sets import (
            get_proposer_slashing_signature_sets,
        )
        from lodestar_tpu.crypto.bls import api as _bls

        st = self.chain.get_head_state()
        h1, h2 = s.signed_header_1.message, s.signed_header_2.message
        if h1.slot != h2.slot or h1.proposer_index != h2.proposer_index:
            return _err(400, "headers not slashable")
        if ssz.phase0.BeaconBlockHeader.serialize(h1) == ssz.phase0.BeaconBlockHeader.serialize(h2):
            return _err(400, "identical headers")
        for sig_set in get_proposer_slashing_signature_sets(
            self.chain.cfg, st.state, s
        ):
            if not _bls.verify_signature_set(sig_set):
                return _err(400, "invalid header signature")
        self.chain.op_pool.add_proposer_slashing(s)
        return _ok(None)

    async def post_liveness(self, request):
        """Validator liveness per epoch from the seen-attester cache
        (validator/liveness route, the doppelganger data source)."""
        epoch = int(request.match_info["epoch"])
        indices = [int(i) for i in await request.json()]
        return _ok(
            [
                {
                    "index": str(i),
                    "is_live": self.chain.seen_attesters.is_known(epoch, i)
                    or self.chain.seen_block_proposers.is_known_proposer_in_epoch(
                        epoch, i
                    ),
                }
                for i in indices
            ]
        )
