"""KZG polynomial commitments for EIP-4844 blobs — the rebuild's `c-kzg`
(reference: consumed via packages/beacon-node/src/util/kzg.ts, init at
node/nodejs.ts:146-151; spec: consensus-specs eip4844
polynomial-commitments.md).

Built from scratch on the in-tree BLS12-381 oracle (crypto/bls): blobs are
polynomials in evaluation form over a bit-reversed power-of-two subgroup of
Fr; commitments/proofs are G1 multi-exponentiations against a Lagrange-form
trusted setup; verification is a two-pairing check.

Trusted setup: `dev_setup(n)` derives an INSECURE deterministic setup from
a fixed secret tau — sufficient for dev chains and tests (the secret is
public, so proofs can be forged; never use for mainnet).  A production
setup in c-kzg's JSON format loads via `load_trusted_setup`.  The dev path
computes Lagrange coefficients L_i(tau) directly in Fr (we know tau), so
setup generation is n scalar muls, not a group FFT.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from functools import lru_cache
from typing import List, Optional, Sequence, Tuple

from lodestar_tpu.params import ACTIVE_PRESET as _p, BYTES_PER_FIELD_ELEMENT
from .bls import curve as cv, fields as ff, pairing as pr
from .bls.curve import G1_GEN_JAC, G2_GEN_JAC, g1, g2
from .bls.fields import R

# Fiat-Shamir domain (spec polynomial-commitments.md)
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
PRIMITIVE_ROOT_OF_UNITY = 7

BYTES_PER_BLOB = BYTES_PER_FIELD_ELEMENT * _p.FIELD_ELEMENTS_PER_BLOB


class KzgError(Exception):
    pass


# ---------------------------------------------------------------------------
# Fr helpers
# ---------------------------------------------------------------------------


def bytes_to_bls_field(b: bytes) -> int:
    """Canonical little-endian field element (this spec era's encoding);
    rejects non-canonical values like the spec's bytes_to_bls_field."""
    x = int.from_bytes(b, "little")
    if x >= R:
        raise KzgError("non-canonical field element")
    return x


def bls_field_to_bytes(x: int) -> bytes:
    return (x % R).to_bytes(BYTES_PER_FIELD_ELEMENT, "little")


def hash_to_bls_field(data: bytes) -> int:
    return int.from_bytes(hashlib.sha256(data).digest(), "little") % R


def compute_powers(x: int, n: int) -> List[int]:
    out = []
    acc = 1
    for _ in range(n):
        out.append(acc)
        acc = acc * x % R
    return out


def _bit_reversal_permutation(seq: Sequence) -> List:
    n = len(seq)
    if n & (n - 1):
        raise KzgError("length must be a power of two")
    bits = n.bit_length() - 1
    return [seq[int(format(i, f"0{bits}b")[::-1], 2)] for i in range(n)] if bits else list(seq)


@lru_cache(maxsize=4)
def roots_of_unity_brp(n: int) -> Tuple[int, ...]:
    """Bit-reversal-permuted n-th roots of unity in Fr."""
    if (R - 1) % n:
        raise KzgError(f"no {n}-th roots of unity in Fr")
    omega = pow(PRIMITIVE_ROOT_OF_UNITY, (R - 1) // n, R)
    roots = []
    acc = 1
    for _ in range(n):
        roots.append(acc)
        acc = acc * omega % R
    return tuple(_bit_reversal_permutation(roots))


# ---------------------------------------------------------------------------
# trusted setup
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TrustedSetup:
    g1_lagrange: Tuple  # JacG1 per evaluation point (bit-reversed order)
    g2_tau: object      # [tau]G2 (jacobian)
    n: int


_DEV_SECRET = int.from_bytes(hashlib.sha256(b"lodestar-tpu insecure dev tau").digest(), "big") % R


@lru_cache(maxsize=2)
def dev_setup(n: Optional[int] = None) -> TrustedSetup:
    """INSECURE deterministic setup (tau is public — dev/test only)."""
    n = n or _p.FIELD_ELEMENTS_PER_BLOB
    tau = _DEV_SECRET
    domain = roots_of_unity_brp(n)
    n_inv = pow(n, R - 2, R)
    zn = (pow(tau, n, R) - 1) % R  # tau^n - 1
    points = []
    for w in domain:
        # L_w(tau) = w/n * (tau^n - 1)/(tau - w)
        li = w * n_inv % R * zn % R * pow((tau - w) % R, R - 2, R) % R
        points.append(g1.mul_scalar(G1_GEN_JAC, li))
    return TrustedSetup(
        g1_lagrange=tuple(points), g2_tau=g2.mul_scalar(G2_GEN_JAC, tau), n=n
    )


def load_trusted_setup(obj: dict) -> TrustedSetup:
    """c-kzg-style JSON: {"setup_G1_lagrange": [hex48...],
    "setup_G2": [hex96...]} (only [tau]G2 — index 1 — is needed)."""
    g1_points = tuple(
        g1.from_affine(cv.g1_from_bytes(bytes.fromhex(h.removeprefix("0x"))))
        for h in obj["setup_G1_lagrange"]
    )
    g2_tau = g2.from_affine(
        cv.g2_from_bytes(bytes.fromhex(obj["setup_G2"][1].removeprefix("0x")))
    )
    return TrustedSetup(g1_lagrange=g1_points, g2_tau=g2_tau, n=len(g1_points))


_active_setup: Optional[TrustedSetup] = None


def get_setup() -> TrustedSetup:
    global _active_setup
    if _active_setup is None:
        _active_setup = dev_setup()
    return _active_setup


def set_setup(setup: Optional[TrustedSetup]) -> None:
    global _active_setup
    _active_setup = setup


# ---------------------------------------------------------------------------
# polynomial ops (evaluation form, bit-reversed domain)
# ---------------------------------------------------------------------------


def blob_to_polynomial(blob: bytes) -> List[int]:
    if len(blob) != BYTES_PER_BLOB:
        raise KzgError(f"blob must be {BYTES_PER_BLOB} bytes")
    return [
        bytes_to_bls_field(blob[i : i + BYTES_PER_FIELD_ELEMENT])
        for i in range(0, len(blob), BYTES_PER_FIELD_ELEMENT)
    ]


def polynomial_to_blob(poly: Sequence[int]) -> bytes:
    return b"".join(bls_field_to_bytes(x) for x in poly)


def evaluate_polynomial_in_evaluation_form(poly: Sequence[int], z: int) -> int:
    """Barycentric evaluation at an arbitrary point (spec
    evaluate_polynomial_in_evaluation_form)."""
    n = len(poly)
    domain = roots_of_unity_brp(n)
    if z in domain:
        return poly[domain.index(z)]
    zn_minus_1 = (pow(z, n, R) - 1) % R
    n_inv = pow(n, R - 2, R)
    total = 0
    for f_i, w in zip(poly, domain):
        total = (total + f_i * w % R * pow((z - w) % R, R - 2, R)) % R
    return total * zn_minus_1 % R * n_inv % R


def g1_lincomb(points: Sequence, scalars: Sequence[int]):
    """MSM over jacobian G1 points (naive double-and-add per term; the
    TPU MSM kernel is the future fast path — SURVEY §2.3 c-kzg row)."""
    acc = (g1.one, g1.one, g1.zero)
    for pt, s in zip(points, scalars):
        if s:
            acc = g1.add_pts(acc, g1.mul_scalar(pt, s))
    return acc


# ---------------------------------------------------------------------------
# the eip4844 KZG API (blob_to_kzg_commitment / aggregate proofs)
# ---------------------------------------------------------------------------


def blob_to_kzg_commitment(blob: bytes, setup: Optional[TrustedSetup] = None) -> bytes:
    setup = setup or get_setup()
    poly = blob_to_polynomial(blob)
    pt = g1_lincomb(setup.g1_lagrange, poly)
    return cv.g1_to_bytes(g1.to_affine(pt))


def verify_kzg_proof(
    commitment: bytes, z: int, y: int, proof: bytes,
    setup: Optional[TrustedSetup] = None,
) -> bool:
    """Pairing check e(P - y·G1, G2) == e(proof, tau·G2 - z·G2), i.e. the
    quotient polynomial is consistent at tau."""
    setup = setup or get_setup()
    try:
        c_aff = cv.g1_from_bytes(commitment)
        p_aff = cv.g1_from_bytes(proof)
    # malformed point encodings are an INVALID-proof verdict by spec
    # (verify returns False), not an error to surface
    except Exception:  # lodelint: disable=silent-except
        return False
    c_jac = g1.from_affine(c_aff)
    p_jac = g1.from_affine(p_aff)
    # X - z in G2; commitment - y in G1
    x_minus_z = g2.add_pts(
        setup.g2_tau, g2.neg_pt(g2.mul_scalar(G2_GEN_JAC, z % R))
    )
    c_minus_y = g1.add_pts(c_jac, g1.neg_pt(g1.mul_scalar(G1_GEN_JAC, y % R)))
    cmy_aff = g1.to_affine(c_minus_y)
    xmz_aff = g2.to_affine(x_minus_z)
    p_aff2 = g1.to_affine(p_jac)
    # e(C - yG1, -G2) * e(proof, (tau-z)G2) == 1
    f = ff.f12_mul(
        pr.miller_loop(g2.to_affine(g2.neg_pt(G2_GEN_JAC)), cmy_aff)
        if cmy_aff is not None
        else _f12_one(),
        pr.miller_loop(xmz_aff, p_aff2) if p_aff2 is not None and xmz_aff is not None else _f12_one(),
    )
    return ff.f12_is_one(pr.final_exponentiation(f))


def _f12_one():
    one = (((1, 0), (0, 0), (0, 0)), ((0, 0), (0, 0), (0, 0)))
    return one


def compute_quotient_eval_within_domain(
    z: int, poly: Sequence[int], y: int
) -> int:
    """Quotient value at z when z IS a domain point (spec
    compute_quotient_eval_within_domain)."""
    domain = roots_of_unity_brp(len(poly))
    result = 0
    for f_i, w in zip(poly, domain):
        if w == z:
            continue
        num = (f_i - y) % R * w % R
        den = z * ((z - w) % R) % R
        result = (result + num * pow(den, R - 2, R)) % R
    return result


def compute_kzg_proof_from_poly(
    poly: Sequence[int], z: int, setup: Optional[TrustedSetup] = None
) -> Tuple[bytes, int]:
    """(proof, y) for p(z) = y via the evaluation-form quotient."""
    setup = setup or get_setup()
    domain = roots_of_unity_brp(len(poly))
    y = evaluate_polynomial_in_evaluation_form(poly, z)
    quotient = []
    for f_i, w in zip(poly, domain):
        if w == z:
            quotient.append(compute_quotient_eval_within_domain(z, poly, y))
        else:
            quotient.append((f_i - y) % R * pow((w - z) % R, R - 2, R) % R)
    pt = g1_lincomb(setup.g1_lagrange, quotient)
    return cv.g1_to_bytes(g1.to_affine(pt)), y


def compute_kzg_proof(blob: bytes, z: int, setup: Optional[TrustedSetup] = None) -> Tuple[bytes, int]:
    return compute_kzg_proof_from_poly(blob_to_polynomial(blob), z, setup)


# --- aggregation (this spec era: one aggregated proof per BlobsSidecar) ----


def _g1_identity_bytes() -> bytes:
    return bytes([0xC0]) + b"\x00" * 47


def compute_aggregated_poly_and_commitment(
    blobs: Sequence[bytes], commitments: Sequence[bytes]
) -> Tuple[List[int], bytes, int]:
    """(agg_poly, agg_commitment, evaluation challenge r) via Fiat-Shamir
    over the blobs and commitments (spec
    compute_aggregated_poly_and_commitment)."""
    h = hashlib.sha256()
    h.update(FIAT_SHAMIR_PROTOCOL_DOMAIN)
    h.update(len(blobs).to_bytes(8, "little"))
    h.update(_p.FIELD_ELEMENTS_PER_BLOB.to_bytes(8, "little"))
    for b in blobs:
        h.update(b)
    for c in commitments:
        h.update(bytes(c))
    r = int.from_bytes(h.digest(), "little") % R
    r_powers = compute_powers(r, len(blobs))

    n = _p.FIELD_ELEMENTS_PER_BLOB
    agg_poly = [0] * n
    for rp, blob in zip(r_powers, blobs):
        for i, f in enumerate(blob_to_polynomial(blob)):
            agg_poly[i] = (agg_poly[i] + rp * f) % R

    pts = [g1.from_affine(cv.g1_from_bytes(bytes(c))) for c in commitments]
    agg_pt = g1_lincomb(pts, r_powers)
    agg_aff = g1.to_affine(agg_pt)
    agg_comm = cv.g1_to_bytes(agg_aff) if agg_aff is not None else _g1_identity_bytes()
    return agg_poly, agg_comm, r


def _evaluation_challenge(agg_poly: Sequence[int], agg_comm: bytes) -> int:
    h = hashlib.sha256()
    h.update(FIAT_SHAMIR_PROTOCOL_DOMAIN)
    h.update(polynomial_to_blob(agg_poly))
    h.update(agg_comm)
    return int.from_bytes(h.digest(), "little") % R


def compute_aggregate_kzg_proof(
    blobs: Sequence[bytes], setup: Optional[TrustedSetup] = None
) -> bytes:
    if not blobs:
        return _g1_identity_bytes()
    commitments = [blob_to_kzg_commitment(b, setup) for b in blobs]
    agg_poly, agg_comm, _ = compute_aggregated_poly_and_commitment(blobs, commitments)
    x = _evaluation_challenge(agg_poly, agg_comm)
    proof, _y = compute_kzg_proof_from_poly(agg_poly, x, setup)
    return proof


def verify_aggregate_kzg_proof(
    blobs: Sequence[bytes],
    commitments: Sequence[bytes],
    proof: bytes,
    setup: Optional[TrustedSetup] = None,
) -> bool:
    if len(blobs) != len(commitments):
        return False
    if not blobs:
        return bytes(proof) == _g1_identity_bytes()
    try:
        agg_poly, agg_comm, _ = compute_aggregated_poly_and_commitment(
            blobs, commitments
        )
    except (KzgError, ValueError):
        # malformed blob field elements / commitment bytes
        return False
    x = _evaluation_challenge(agg_poly, agg_comm)
    y = evaluate_polynomial_in_evaluation_form(agg_poly, x)
    return verify_kzg_proof(agg_comm, x, y, proof, setup)
