"""Ethereum BLS signature API (CPU oracle backend).

Mirrors the surface of ``@chainsafe/bls`` that the reference client consumes:
SecretKey/PublicKey/Signature objects, aggregate, verify, fastAggregateVerify,
aggregateVerify, and verifyMultipleSignatures (the random-linear-combination
batch verification of chain/bls/maybeBatch.ts:17).

Scheme: minimal-pubkey-size (pubkeys in G1/48B, signatures in G2/96B), POP
ciphersuite — the Ethereum consensus configuration.
"""
from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from . import curve, pairing
from .curve import (
    AffineG1,
    AffineG2,
    G1_GEN_JAC,
    g1,
    g2,
    g1_from_bytes,
    g1_in_subgroup,
    g1_to_bytes,
    g2_from_bytes,
    g2_in_subgroup,
    g2_to_bytes,
)
from .fields import R
from .hash_to_curve import hash_to_g2_affine

_NEG_G1_GEN = g1.neg_pt(G1_GEN_JAC)
_NEG_G1_GEN_AFF = g1.to_affine(_NEG_G1_GEN)

# Batch-verification random coefficients are 64-bit like the reference's blst
# randomness (sufficient for 2^-64 soundness per set).
RAND_BITS = 64


class BlsError(ValueError):
    pass


@dataclass(frozen=True)
class SecretKey:
    value: int

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != 32:
            raise BlsError("secret key must be 32 bytes")
        v = int.from_bytes(data, "big")
        if not 0 < v < R:
            raise BlsError("secret key out of range")
        return cls(v)

    @classmethod
    def key_gen(cls, ikm: bytes, key_info: bytes = b"") -> "SecretKey":
        """EIP-2333-compatible HKDF keygen (draft-irtf-cfrg-bls-signature KeyGen)."""
        salt = b"BLS-SIG-KEYGEN-SALT-"
        sk = 0
        while sk == 0:
            salt = hashlib.sha256(salt).digest()
            prk = hmac.new(salt, ikm + b"\x00", hashlib.sha256).digest()
            okm = b""
            t = b""
            info = key_info + (48).to_bytes(2, "big")
            i = 1
            while len(okm) < 48:
                t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
                okm += t
                i += 1
            sk = int.from_bytes(okm[:48], "big") % R
        return cls(sk)

    @classmethod
    def generate(cls) -> "SecretKey":
        return cls.key_gen(os.urandom(32))

    def to_bytes(self) -> bytes:
        return self.value.to_bytes(32, "big")

    def to_public_key(self) -> "PublicKey":
        return PublicKey(g1.to_affine(g1.mul_scalar(G1_GEN_JAC, self.value)))

    def sign(self, message: bytes) -> "Signature":
        h = g2.from_affine(hash_to_g2_affine(message))
        return Signature(g2.to_affine(g2.mul_scalar(h, self.value)))


@dataclass(frozen=True)
class PublicKey:
    point: AffineG1  # None == identity (invalid for Ethereum key-validate)

    @classmethod
    def from_bytes(cls, data: bytes, validate: bool = True) -> "PublicKey":
        pt = g1_from_bytes(data)
        if validate:
            if pt is None:
                raise BlsError("infinity pubkey rejected (KeyValidate)")
            if not g1_in_subgroup(g1.from_affine(pt)):
                raise BlsError("pubkey not in G1 subgroup")
        return cls(pt)

    def to_bytes(self, compressed: bool = True) -> bytes:
        return g1_to_bytes(self.point, compressed)

    def __bytes__(self) -> bytes:
        return self.to_bytes()


@dataclass(frozen=True)
class Signature:
    point: AffineG2

    @classmethod
    def from_bytes(cls, data: bytes, validate: bool = True) -> "Signature":
        pt = g2_from_bytes(data)
        if validate and pt is not None and not g2_in_subgroup(g2.from_affine(pt)):
            raise BlsError("signature not in G2 subgroup")
        return cls(pt)

    def to_bytes(self, compressed: bool = True) -> bytes:
        return g2_to_bytes(self.point, compressed)

    def __bytes__(self) -> bytes:
        return self.to_bytes()


def aggregate_public_keys(pks: Sequence[PublicKey]) -> PublicKey:
    """Jacobian-coordinate pubkey aggregation (reference: chain/bls/utils.ts:5)."""
    acc = curve.INF_G1
    for pk in pks:
        acc = g1.add_pts(acc, g1.from_affine(pk.point))
    return PublicKey(g1.to_affine(acc))


def aggregate_signatures(sigs: Sequence[Signature]) -> Signature:
    acc = curve.INF_G2
    for s in sigs:
        acc = g2.add_pts(acc, g2.from_affine(s.point))
    return Signature(g2.to_affine(acc))


def verify(pk: PublicKey, message: bytes, sig: Signature) -> bool:
    """CoreVerify: e(pk, H(m)) * e(-G1, sig) == 1."""
    if pk.point is None or sig.point is None:
        return False
    if not g2_in_subgroup(g2.from_affine(sig.point)):
        return False
    h = hash_to_g2_affine(message)
    return pairing.multi_pairing_is_one(
        [(pk.point, h), (_NEG_G1_GEN_AFF, sig.point)]
    )


def fast_aggregate_verify(pks: Sequence[PublicKey], message: bytes, sig: Signature) -> bool:
    if not pks:
        return False
    agg = aggregate_public_keys(pks)
    if agg.point is None:
        return False
    return verify(agg, message, sig)


def eth_fast_aggregate_verify(pks: Sequence[PublicKey], message: bytes, sig: Signature) -> bool:
    """Ethereum consensus wrapper: accepts (no pubkeys, infinity signature) as
    valid — the empty-sync-aggregate case (consensus-specs eth_fast_aggregate_verify)."""
    if not pks and sig.point is None:
        return True
    return fast_aggregate_verify(pks, message, sig)


def aggregate_verify(pks: Sequence[PublicKey], messages: Sequence[bytes], sig: Signature) -> bool:
    if not pks or len(pks) != len(messages) or sig.point is None:
        return False
    if any(pk.point is None for pk in pks):
        return False
    if not g2_in_subgroup(g2.from_affine(sig.point)):
        return False
    pairs: List[Tuple[AffineG1, AffineG2]] = [
        (pk.point, hash_to_g2_affine(m)) for pk, m in zip(pks, messages)
    ]
    pairs.append((_NEG_G1_GEN_AFF, sig.point))
    return pairing.multi_pairing_is_one(pairs)


@dataclass(frozen=True)
class SignatureSet:
    """One verification task: (pubkey, message, signature) — the same triple
    as the reference's ISignatureSet (state-transition/src/util/signatureSets.ts:10)
    after pubkey aggregation."""

    public_key: PublicKey
    message: bytes
    signature: Signature


def verify_signature_set(s: SignatureSet) -> bool:
    return verify(s.public_key, s.message, s.signature)


def verify_multiple_signature_sets(
    sets: Sequence[SignatureSet], rand: Optional[Sequence[int]] = None
) -> bool:
    """Batch verification with random linear combination (blst's
    verifyMultipleSignatures; reference chain/bls/maybeBatch.ts:17).

    prod_i [ e(pk_i, r_i H(m_i)) * e(-G1, r_i sig_i) ] == 1
    realised as  prod_i e(r_i pk_i, H(m_i)) * e(-G1, sum_i r_i sig_i) == 1
    so the n+1 Miller loops share one final exponentiation.
    """
    if not sets:
        return False
    if rand is None:
        rand = [int.from_bytes(os.urandom(RAND_BITS // 8), "big") | 1 for _ in sets]
    elif len(rand) != len(sets):
        raise BlsError("rand coefficient count must match set count")
    pairs: List[Tuple[AffineG1, AffineG2]] = []
    sig_acc = curve.INF_G2
    for s, r in zip(sets, rand):
        if s.public_key.point is None or s.signature.point is None:
            return False
        if not g2_in_subgroup(g2.from_affine(s.signature.point)):
            return False
        h = hash_to_g2_affine(s.message)
        rpk = g1.to_affine(g1.mul_scalar(g1.from_affine(s.public_key.point), r))
        pairs.append((rpk, h))
        sig_acc = g2.add_pts(sig_acc, g2.mul_scalar(g2.from_affine(s.signature.point), r))
    pairs.append((_NEG_G1_GEN_AFF, g2.to_affine(sig_acc)))
    return pairing.multi_pairing_is_one(pairs)
