"""BLS12-381 for Ethereum consensus — CPU oracle implementation.

The TPU-accelerated engine lives in ``lodestar_tpu.ops``; this package is the
from-scratch pure-Python reference used as its differential-testing oracle and
as the host-side fallback verifier (the role herumi/bls-eth-wasm plays in the
reference client, chain/bls/multithread/index.ts:123-126).
"""
from .api import (
    BlsError,
    PublicKey,
    SecretKey,
    Signature,
    SignatureSet,
    aggregate_public_keys,
    aggregate_signatures,
    aggregate_verify,
    eth_fast_aggregate_verify,
    fast_aggregate_verify,
    verify,
    verify_multiple_signature_sets,
    verify_signature_set,
)

__all__ = [
    "BlsError",
    "PublicKey",
    "SecretKey",
    "Signature",
    "SignatureSet",
    "aggregate_public_keys",
    "aggregate_signatures",
    "aggregate_verify",
    "eth_fast_aggregate_verify",
    "fast_aggregate_verify",
    "verify",
    "verify_multiple_signature_sets",
    "verify_signature_set",
]
