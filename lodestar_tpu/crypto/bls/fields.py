"""BLS12-381 field tower arithmetic — pure-Python CPU oracle.

This is the reference ("oracle") implementation the TPU engine in
``lodestar_tpu.ops`` is differential-tested against.  It replaces the role of
the supranational ``blst`` C library in the reference client
(reference: packages/beacon-node/src/chain/bls/maybeBatch.ts:17, yarn dep
``@chainsafe/blst``), but is written from scratch from the BLS12-381 spec.

Representation (functional, tuple-based — mirrors the JAX engine's layout):
  Fp   : python int in [0, P)
  Fp2  : (c0, c1)            meaning c0 + c1*u,  u^2 = -1
  Fp6  : (a0, a1, a2)        meaning a0 + a1*v + a2*v^2,  v^3 = xi = u + 1
  Fp12 : (b0, b1)            meaning b0 + b1*w,  w^2 = v
"""
from __future__ import annotations

from typing import Tuple

# ---------------------------------------------------------------------------
# Curve constants (standard, widely published BLS12-381 parameters)
# ---------------------------------------------------------------------------

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001  # subgroup order
# BLS parameter x (negative): curve is parameterised by x = -0xd201000000010000
X = -0xD201000000010000
ABS_X = 0xD201000000010000
H_EFF_G1 = 0xD201000000010001  # 1 - x, effective G1 cofactor multiplier (RFC 9380)

Fp2T = Tuple[int, int]
Fp6T = Tuple[Fp2T, Fp2T, Fp2T]
Fp12T = Tuple[Fp6T, Fp6T]

# ---------------------------------------------------------------------------
# Fp
# ---------------------------------------------------------------------------


def fp_add(a: int, b: int) -> int:
    c = a + b
    return c - P if c >= P else c


def fp_sub(a: int, b: int) -> int:
    c = a - b
    return c + P if c < 0 else c


def fp_mul(a: int, b: int) -> int:
    return (a * b) % P


def fp_neg(a: int) -> int:
    return P - a if a else 0


def fp_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("Fp inverse of zero")
    return pow(a, P - 2, P)


def fp_sqrt(a: int) -> int | None:
    """Square root in Fp (P % 4 == 3 so a^((P+1)/4) works). None if no root."""
    c = pow(a, (P + 1) // 4, P)
    return c if c * c % P == a else None


# ---------------------------------------------------------------------------
# Fp2 = Fp[u] / (u^2 + 1)
# ---------------------------------------------------------------------------

F2_ZERO: Fp2T = (0, 0)
F2_ONE: Fp2T = (1, 0)


def f2(c0: int, c1: int) -> Fp2T:
    return (c0 % P, c1 % P)


def f2_add(a: Fp2T, b: Fp2T) -> Fp2T:
    return (fp_add(a[0], b[0]), fp_add(a[1], b[1]))


def f2_sub(a: Fp2T, b: Fp2T) -> Fp2T:
    return (fp_sub(a[0], b[0]), fp_sub(a[1], b[1]))


def f2_neg(a: Fp2T) -> Fp2T:
    return (fp_neg(a[0]), fp_neg(a[1]))


def f2_mul(a: Fp2T, b: Fp2T) -> Fp2T:
    # (a0 + a1 u)(b0 + b1 u) = (a0 b0 - a1 b1) + (a0 b1 + a1 b0) u
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def f2_sqr(a: Fp2T) -> Fp2T:
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    t0 = (a[0] + a[1]) * (a[0] - a[1])
    t1 = 2 * a[0] * a[1]
    return (t0 % P, t1 % P)


def f2_mul_scalar(a: Fp2T, k: int) -> Fp2T:
    return (a[0] * k % P, a[1] * k % P)


def f2_conj(a: Fp2T) -> Fp2T:
    return (a[0], fp_neg(a[1]))


def f2_inv(a: Fp2T) -> Fp2T:
    # (a0 - a1 u) / (a0^2 + a1^2)
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    ninv = fp_inv(norm)
    return (a[0] * ninv % P, (P - a[1]) * ninv % P if a[1] else 0)


def f2_mul_by_xi(a: Fp2T) -> Fp2T:
    # xi = 1 + u:  (a0 + a1 u)(1 + u) = (a0 - a1) + (a0 + a1) u
    return (fp_sub(a[0], a[1]), fp_add(a[0], a[1]))


def f2_pow(a: Fp2T, e: int) -> Fp2T:
    result = F2_ONE
    base = a
    while e:
        if e & 1:
            result = f2_mul(result, base)
        base = f2_sqr(base)
        e >>= 1
    return result


def f2_is_zero(a: Fp2T) -> bool:
    return a[0] == 0 and a[1] == 0


def f2_sqrt(a: Fp2T) -> Fp2T | None:
    """Square root in Fp2 (algorithm for p % 4 == 3: Adj-Rodriguez)."""
    if f2_is_zero(a):
        return F2_ZERO
    # Adj-Rodriguez for p % 4 == 3:
    #   a1 = a^((p-3)/4); x0 = a1*a; alpha = a1*x0 = a^((p-1)/2)
    #   alpha == -1  ->  x = u * x0;  else  x = (1+alpha)^((p-1)/2) * x0
    a1 = f2_pow(a, (P - 3) // 4)
    x0 = f2_mul(a1, a)
    alpha = f2_mul(a1, x0)
    if alpha == (P - 1, 0):
        x = (fp_neg(x0[1]), x0[0])  # u * x0
    else:
        b = f2_pow(f2_add(F2_ONE, alpha), (P - 1) // 2)
        x = f2_mul(b, x0)
    return x if f2_sqr(x) == a else None


def f2_sgn0(a: Fp2T) -> int:
    """RFC 9380 sgn0 for Fp2 (sign of the 'lowest' non-zero component)."""
    sign_0 = a[0] & 1
    zero_0 = 1 if a[0] == 0 else 0
    sign_1 = a[1] & 1
    return sign_0 | (zero_0 & sign_1)


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v] / (v^3 - xi),  xi = u + 1
# ---------------------------------------------------------------------------

F6_ZERO: Fp6T = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE: Fp6T = (F2_ONE, F2_ZERO, F2_ZERO)


def f6_add(a: Fp6T, b: Fp6T) -> Fp6T:
    return (f2_add(a[0], b[0]), f2_add(a[1], b[1]), f2_add(a[2], b[2]))


def f6_sub(a: Fp6T, b: Fp6T) -> Fp6T:
    return (f2_sub(a[0], b[0]), f2_sub(a[1], b[1]), f2_sub(a[2], b[2]))


def f6_neg(a: Fp6T) -> Fp6T:
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def f6_mul(a: Fp6T, b: Fp6T) -> Fp6T:
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = f2_mul(a0, b0)
    t1 = f2_mul(a1, b1)
    t2 = f2_mul(a2, b2)
    # c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
    c0 = f2_add(t0, f2_mul_by_xi(f2_sub(f2_sub(f2_mul(f2_add(a1, a2), f2_add(b1, b2)), t1), t2)))
    # c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
    c1 = f2_add(f2_sub(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)), t0), t1), f2_mul_by_xi(t2))
    # c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    c2 = f2_add(f2_sub(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)), t0), t2), t1)
    return (c0, c1, c2)


def f6_sqr(a: Fp6T) -> Fp6T:
    return f6_mul(a, a)


def f6_mul_by_v(a: Fp6T) -> Fp6T:
    # (a0 + a1 v + a2 v^2) * v = xi*a2 + a0 v + a1 v^2
    return (f2_mul_by_xi(a[2]), a[0], a[1])


def f6_inv(a: Fp6T) -> Fp6T:
    a0, a1, a2 = a
    c0 = f2_sub(f2_sqr(a0), f2_mul_by_xi(f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul_by_xi(f2_sqr(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sqr(a1), f2_mul(a0, a2))
    t = f2_add(f2_mul(a0, c0), f2_mul_by_xi(f2_add(f2_mul(a1, c2), f2_mul(a2, c1))))
    tinv = f2_inv(t)
    return (f2_mul(c0, tinv), f2_mul(c1, tinv), f2_mul(c2, tinv))


def f6_is_zero(a: Fp6T) -> bool:
    return all(f2_is_zero(c) for c in a)


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w] / (w^2 - v)
# ---------------------------------------------------------------------------

F12_ZERO: Fp12T = (F6_ZERO, F6_ZERO)
F12_ONE: Fp12T = (F6_ONE, F6_ZERO)


def f12_add(a: Fp12T, b: Fp12T) -> Fp12T:
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_sub(a: Fp12T, b: Fp12T) -> Fp12T:
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def f12_mul(a: Fp12T, b: Fp12T) -> Fp12T:
    a0, a1 = a
    b0, b1 = b
    t0 = f6_mul(a0, b0)
    t1 = f6_mul(a1, b1)
    c0 = f6_add(t0, f6_mul_by_v(t1))
    c1 = f6_sub(f6_sub(f6_mul(f6_add(a0, a1), f6_add(b0, b1)), t0), t1)
    return (c0, c1)


def f12_sqr(a: Fp12T) -> Fp12T:
    a0, a1 = a
    # (a0 + a1 w)^2 = (a0^2 + v a1^2) + 2 a0 a1 w
    t = f6_mul(a0, a1)
    c0 = f6_mul(f6_add(a0, a1), f6_add(a0, f6_mul_by_v(a1)))
    c0 = f6_sub(f6_sub(c0, t), f6_mul_by_v(t))
    c1 = f6_add(t, t)
    return (c0, c1)


def f12_conj(a: Fp12T) -> Fp12T:
    """Conjugation = Frobenius^6 (negates the w component)."""
    return (a[0], f6_neg(a[1]))


def f12_inv(a: Fp12T) -> Fp12T:
    a0, a1 = a
    t = f6_sub(f6_sqr(a0), f6_mul_by_v(f6_sqr(a1)))
    tinv = f6_inv(t)
    return (f6_mul(a0, tinv), f6_neg(f6_mul(a1, tinv)))


def f12_pow(a: Fp12T, e: int) -> Fp12T:
    if e < 0:
        return f12_pow(f12_inv(a), -e)
    result = F12_ONE
    base = a
    while e:
        if e & 1:
            result = f12_mul(result, base)
        base = f12_sqr(base)
        e >>= 1
    return result


def f12_is_one(a: Fp12T) -> bool:
    return a[0] == F6_ONE and f6_is_zero(a[1])


# ---------------------------------------------------------------------------
# Frobenius endomorphism on Fp12.
#
# Coefficients are *computed* at import time (not hard-coded) to avoid any
# transcription risk: gamma1[i] = xi^(i*(p-1)/6) for i in 0..5.
# frobenius(a)_as_Fp2_coeffs[i] = conj(coeff_i) * gamma1[i] in the w-basis.
# ---------------------------------------------------------------------------

_XI: Fp2T = (1, 1)
GAMMA1 = [f2_pow(_XI, i * (P - 1) // 6) for i in range(6)]


def _f12_to_wcoeffs(a: Fp12T) -> list[Fp2T]:
    """Fp12 as 6 Fp2 coefficients in the basis 1, w, w^2(=v), w^3, w^4, w^5."""
    (a0, a1, a2), (b0, b1, b2) = a
    # a0 + a1 v + a2 v^2 + w(b0 + b1 v + b2 v^2), v = w^2
    return [a0, b0, a1, b1, a2, b2]


def _f12_from_wcoeffs(c: list[Fp2T]) -> Fp12T:
    return ((c[0], c[2], c[4]), (c[1], c[3], c[5]))


def f12_frobenius(a: Fp12T, power: int = 1) -> Fp12T:
    out = a
    for _ in range(power % 12):
        coeffs = _f12_to_wcoeffs(out)
        coeffs = [f2_mul(f2_conj(c), GAMMA1[i]) for i, c in enumerate(coeffs)]
        out = _f12_from_wcoeffs(coeffs)
    return out


# ---------------------------------------------------------------------------
# Cyclotomic operations (for the final exponentiation hard part).
# After the easy part, f lies in the cyclotomic subgroup where
# f^(p^6+1... ) structure allows cheap inversion: f^-1 = conj(f).
# ---------------------------------------------------------------------------


def f12_cyclotomic_sqr(a: Fp12T) -> Fp12T:
    # Granger-Scott compressed squaring could go here; plain squaring is fine
    # for the oracle.
    return f12_sqr(a)


def f12_cyclotomic_pow_x(a: Fp12T) -> Fp12T:
    """a^|x| using square-and-multiply over the (sparse) BLS parameter.

    NOTE: exponent is |x|; callers account for the sign of x via conjugation.
    """
    result = F12_ONE
    base = a
    e = ABS_X
    while e:
        if e & 1:
            result = f12_mul(result, base)
        base = f12_cyclotomic_sqr(base)
        e >>= 1
    return result
