"""BLS12-381 G1/G2 group operations — pure-Python CPU oracle.

Points are represented in Jacobian coordinates as tuples ``(X, Y, Z)`` over
the base field (Fp for G1, Fp2 for G2); ``Z == 0`` is the point at infinity.
Affine points are ``(x, y)`` with an explicit ``None`` for infinity.

This supplies the role that blst's G1/G2 ops play in the reference client
(pubkey aggregation at packages/beacon-node/src/chain/bls/utils.ts:5 — done in
Jacobian coordinates per state-transition/src/cache/pubkeyCache.ts:76).
"""
from __future__ import annotations

from typing import Optional, Tuple

from .fields import (
    P,
    R,
    X,
    Fp2T,
    F2_ONE,
    F2_ZERO,
    f2_add,
    f2_conj,
    f2_inv,
    f2_is_zero,
    f2_mul,
    f2_neg,
    f2_pow,
    f2_sqr,
    f2_sqrt,
    f2_sub,
    fp_add,
    fp_inv,
    fp_mul,
    fp_neg,
    fp_sqrt,
    fp_sub,
)

# Curve: E/Fp:  y^2 = x^3 + 4          (G1)
#        E'/Fp2: y^2 = x^3 + 4(u+1)    (G2, M-twist)
B_G1 = 4
B_G2: Fp2T = (4, 4)

# Standard generators (widely published BLS12-381 constants).
G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

AffineG1 = Optional[Tuple[int, int]]
AffineG2 = Optional[Tuple[Fp2T, Fp2T]]
JacG1 = Tuple[int, int, int]
JacG2 = Tuple[Fp2T, Fp2T, Fp2T]

INF_G1: JacG1 = (1, 1, 0)
INF_G2: JacG2 = (F2_ONE, F2_ONE, F2_ZERO)


# ---------------------------------------------------------------------------
# Generic Jacobian arithmetic, parameterised by the field ops.  We instantiate
# twice (Fp and Fp2) with small closures; the oracle favours one well-tested
# code path over duplicated formulas.
# ---------------------------------------------------------------------------


class _CurveOps:
    def __init__(self, add, sub, mul, sqr, neg, inv, is_zero, zero, one, b):
        self.add, self.sub, self.mul, self.sqr = add, sub, mul, sqr
        self.neg, self.inv, self.is_zero = neg, inv, is_zero
        self.zero, self.one, self.b = zero, one, b

    # -- Jacobian formulas (standard EFD dbl-2009-l / add-2007-bl) --

    def is_inf(self, pt):
        return self.is_zero(pt[2])

    def double(self, pt):
        X1, Y1, Z1 = pt
        if self.is_zero(Z1) or self.is_zero(Y1):
            return (self.one, self.one, self.zero)
        A = self.sqr(X1)
        B = self.sqr(Y1)
        C = self.sqr(B)
        D = self.sub(self.sqr(self.add(X1, B)), self.add(A, C))
        D = self.add(D, D)
        E = self.add(self.add(A, A), A)
        F = self.sqr(E)
        X3 = self.sub(F, self.add(D, D))
        C8 = self.add(C, C)
        C8 = self.add(C8, C8)
        C8 = self.add(C8, C8)
        Y3 = self.sub(self.mul(E, self.sub(D, X3)), C8)
        Z3 = self.mul(self.add(Y1, Y1), Z1)
        return (X3, Y3, Z3)

    def add_pts(self, p1, p2):
        if self.is_inf(p1):
            return p2
        if self.is_inf(p2):
            return p1
        X1, Y1, Z1 = p1
        X2, Y2, Z2 = p2
        Z1Z1 = self.sqr(Z1)
        Z2Z2 = self.sqr(Z2)
        U1 = self.mul(X1, Z2Z2)
        U2 = self.mul(X2, Z1Z1)
        S1 = self.mul(self.mul(Y1, Z2), Z2Z2)
        S2 = self.mul(self.mul(Y2, Z1), Z1Z1)
        if U1 == U2:
            if S1 != S2:
                return (self.one, self.one, self.zero)
            return self.double(p1)
        H = self.sub(U2, U1)
        I = self.sqr(self.add(H, H))
        J = self.mul(H, I)
        rr = self.sub(S2, S1)
        rr = self.add(rr, rr)
        V = self.mul(U1, I)
        X3 = self.sub(self.sub(self.sqr(rr), J), self.add(V, V))
        S1J = self.mul(S1, J)
        Y3 = self.sub(self.mul(rr, self.sub(V, X3)), self.add(S1J, S1J))
        Z3 = self.mul(self.sub(self.sqr(self.add(Z1, Z2)), self.add(Z1Z1, Z2Z2)), H)
        return (X3, Y3, Z3)

    def neg_pt(self, pt):
        return (pt[0], self.neg(pt[1]), pt[2])

    def mul_scalar(self, pt, k: int):
        if k < 0:
            return self.mul_scalar(self.neg_pt(pt), -k)
        result = (self.one, self.one, self.zero)
        addend = pt
        while k:
            if k & 1:
                result = self.add_pts(result, addend)
            addend = self.double(addend)
            k >>= 1
        return result

    def to_affine(self, pt):
        if self.is_inf(pt):
            return None
        zinv = self.inv(pt[2])
        zinv2 = self.sqr(zinv)
        return (self.mul(pt[0], zinv2), self.mul(self.mul(pt[1], zinv), zinv2))

    def from_affine(self, aff):
        if aff is None:
            return (self.one, self.one, self.zero)
        return (aff[0], aff[1], self.one)

    def on_curve(self, aff) -> bool:
        if aff is None:
            return True
        x, y = aff
        return self.sqr(y) == self.add(self.mul(self.sqr(x), x), self.b)

    def eq(self, p1, p2) -> bool:
        inf1, inf2 = self.is_inf(p1), self.is_inf(p2)
        if inf1 or inf2:
            return inf1 == inf2
        # X1 Z2^2 == X2 Z1^2 and Y1 Z2^3 == Y2 Z1^3
        Z1Z1, Z2Z2 = self.sqr(p1[2]), self.sqr(p2[2])
        if self.mul(p1[0], Z2Z2) != self.mul(p2[0], Z1Z1):
            return False
        return self.mul(self.mul(p1[1], p2[2]), Z2Z2) == self.mul(self.mul(p2[1], p1[2]), Z1Z1)


def _fp_is_zero(a: int) -> bool:
    return a == 0


g1 = _CurveOps(fp_add, fp_sub, fp_mul, lambda a: a * a % P, fp_neg, fp_inv, _fp_is_zero, 0, 1, B_G1)
g2 = _CurveOps(f2_add, f2_sub, f2_mul, f2_sqr, f2_neg, f2_inv, f2_is_zero, F2_ZERO, F2_ONE, B_G2)

G1_GEN_JAC: JacG1 = g1.from_affine(G1_GEN)
G2_GEN_JAC: JacG2 = g2.from_affine(G2_GEN)


# ---------------------------------------------------------------------------
# psi endomorphism on E'(Fp2): untwist -> Frobenius -> twist.
# psi(x, y) = (c_x * conj(x), c_y * conj(y)) with constants computed at
# import time:  c_x = 1/xi^((p-1)/3),  c_y = 1/xi^((p-1)/2).
# On G2 psi acts as the Frobenius eigenvalue; used for fast cofactor clearing
# and (testably) satisfies psi(P) == [p mod r] P for P in G2.
# ---------------------------------------------------------------------------

_XI: Fp2T = (1, 1)
PSI_CX = f2_inv(f2_pow(_XI, (P - 1) // 3))
PSI_CY = f2_inv(f2_pow(_XI, (P - 1) // 2))


def psi(pt: JacG2) -> JacG2:
    aff = g2.to_affine(pt)
    if aff is None:
        return INF_G2
    x, y = aff
    return g2.from_affine((f2_mul(PSI_CX, f2_conj(x)), f2_mul(PSI_CY, f2_conj(y))))


def clear_cofactor_g2(pt: JacG2) -> JacG2:
    """Budroni-Pintore efficient cofactor clearing (RFC 9380 appendix G.3):

    h_eff * P = [x^2 - x - 1]P + [x - 1]psi(P) + psi^2([2]P)

    (coefficient choice validated numerically: the result of this combination
    on a random E'(Fp2) point lands in the r-torsion; the sign variants do not)
    """
    x_p = g2.mul_scalar(pt, X)                 # [x]P      (x negative)
    x2_p = g2.mul_scalar(x_p, X)               # [x^2]P
    part1 = g2.add_pts(g2.add_pts(x2_p, g2.neg_pt(x_p)), g2.neg_pt(pt))   # [x^2-x-1]P
    part2 = g2.mul_scalar(psi(pt), X - 1)      # [x-1]psi(P)
    part3 = psi(psi(g2.double(pt)))            # psi^2([2]P)
    return g2.add_pts(g2.add_pts(part1, part2), part3)


def g2_in_subgroup(pt: JacG2) -> bool:
    """Fast subgroup check: psi(P) == [x]P  iff  P in G2 (Bowe's criterion)."""
    if g2.is_inf(pt):
        return True
    if not g2.on_curve(g2.to_affine(pt)):
        return False
    return g2.eq(psi(pt), g2.mul_scalar(pt, X))


def g1_in_subgroup(pt: JacG1) -> bool:
    """G1 subgroup check via the GLV endomorphism sigma(x,y) = (beta*x, y):
    P in G1  iff  sigma^2(P) == [-x^2] ... we use the direct criterion
    [r]P == inf (oracle favours obviousness; the TPU path optimises)."""
    if g1.is_inf(pt):
        return True
    if not g1.on_curve(g1.to_affine(pt)):
        return False
    return g1.is_inf(g1.mul_scalar(pt, R))


# ---------------------------------------------------------------------------
# Serialization — ZCash-style compressed/uncompressed encodings used by the
# Ethereum consensus spec (48B G1 / 96B G2 compressed; flag bits in the top
# three bits of the first byte: compressed, infinity, lexicographically-larger-y).
# ---------------------------------------------------------------------------

_COMP = 0x80
_INF = 0x40
_SORT = 0x20
_HALF_P = (P - 1) // 2


def _fp_to_bytes(a: int) -> bytes:
    return a.to_bytes(48, "big")


def g1_to_bytes(aff: AffineG1, compressed: bool = True) -> bytes:
    if not compressed:
        if aff is None:
            out = bytearray(96)
            out[0] = _INF
            return bytes(out)
        return _fp_to_bytes(aff[0]) + _fp_to_bytes(aff[1])
    if aff is None:
        out = bytearray(48)
        out[0] = _COMP | _INF
        return bytes(out)
    x, y = aff
    flags = _COMP | (_SORT if y > _HALF_P else 0)
    out = bytearray(_fp_to_bytes(x))
    out[0] |= flags
    return bytes(out)


def g1_from_bytes(data: bytes) -> AffineG1:
    """Decode + validate (on curve; subgroup check is separate)."""
    if len(data) == 48:
        flags = data[0]
        if not flags & _COMP:
            raise ValueError("48-byte G1 encoding must have compression bit set")
        if flags & _INF:
            if any(data[1:]) or data[0] != (_COMP | _INF):
                raise ValueError("invalid G1 infinity encoding")
            return None
        x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
        if x >= P:
            raise ValueError("G1 x >= p")
        y = fp_sqrt((x * x % P * x + B_G1) % P)
        if y is None:
            raise ValueError("G1 x not on curve")
        y_big = y > _HALF_P
        if bool(flags & _SORT) != y_big:
            y = fp_neg(y)
        return (x, y)
    elif len(data) == 96:
        if data[0] & (_COMP | _SORT):
            raise ValueError("uncompressed G1 encoding has invalid flag bits")
        if data[0] & _INF:
            if data[0] != _INF or any(data[1:]):
                raise ValueError("invalid G1 infinity encoding")
            return None
        x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
        y = int.from_bytes(data[48:], "big")
        if x >= P or y >= P:
            raise ValueError("G1 coordinate >= p")
        if not g1.on_curve((x, y)):
            raise ValueError("G1 point not on curve")
        return (x, y)
    raise ValueError(f"invalid G1 encoding length {len(data)}")


def g2_to_bytes(aff: AffineG2, compressed: bool = True) -> bytes:
    if not compressed:
        if aff is None:
            out = bytearray(192)
            out[0] = _INF
            return bytes(out)
        (x0, x1), (y0, y1) = aff
        return _fp_to_bytes(x1) + _fp_to_bytes(x0) + _fp_to_bytes(y1) + _fp_to_bytes(y0)
    if aff is None:
        out = bytearray(96)
        out[0] = _COMP | _INF
        return bytes(out)
    (x0, x1), (y0, y1) = aff
    y_big = (y1 > _HALF_P) or (y1 == 0 and y0 > _HALF_P)
    flags = _COMP | (_SORT if y_big else 0)
    out = bytearray(_fp_to_bytes(x1) + _fp_to_bytes(x0))
    out[0] |= flags
    return bytes(out)


def g2_from_bytes(data: bytes) -> AffineG2:
    if len(data) == 96:
        flags = data[0]
        if not flags & _COMP:
            raise ValueError("96-byte G2 encoding must have compression bit set")
        if flags & _INF:
            if any(data[1:]) or data[0] != (_COMP | _INF):
                raise ValueError("invalid G2 infinity encoding")
            return None
        x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
        x0 = int.from_bytes(data[48:96], "big")
        if x0 >= P or x1 >= P:
            raise ValueError("G2 x coordinate >= p")
        x = (x0, x1)
        y = f2_sqrt(f2_add(f2_mul(f2_sqr(x), x), B_G2))
        if y is None:
            raise ValueError("G2 x not on curve")
        y_big = (y[1] > _HALF_P) or (y[1] == 0 and y[0] > _HALF_P)
        if bool(flags & _SORT) != y_big:
            y = f2_neg(y)
        return (x, y)
    elif len(data) == 192:
        if data[0] & (_COMP | _SORT):
            raise ValueError("uncompressed G2 encoding has invalid flag bits")
        if data[0] & _INF:
            if data[0] != _INF or any(data[1:]):
                raise ValueError("invalid G2 infinity encoding")
            return None
        x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
        x0 = int.from_bytes(data[48:96], "big")
        y1 = int.from_bytes(data[96:144], "big")
        y0 = int.from_bytes(data[144:], "big")
        if max(x0, x1, y0, y1) >= P:
            raise ValueError("G2 coordinate >= p")
        aff = ((x0, x1), (y0, y1))
        if not g2.on_curve(aff):
            raise ValueError("G2 point not on curve")
        return aff
    raise ValueError(f"invalid G2 encoding length {len(data)}")
