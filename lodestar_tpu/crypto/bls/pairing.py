"""BLS12-381 optimal ate pairing — pure-Python CPU oracle.

Strategy: obvious-correctness over speed.  G2 points are *untwisted* into
E(Fp12) and the Miller loop runs with affine line functions in Fp12; the
final-exponentiation hard part uses the directly computed integer exponent
(p^4 - p^2 + 1)/r rather than a transcribed addition chain.  The TPU engine
(lodestar_tpu/ops) implements the fast projective/cyclotomic versions and is
differential-tested against this module.

Multi-pairing (shared final exponentiation over a product of Miller loops)
mirrors blst's ``verifyMultipleSignatures`` random-linear-combination batching
used by the reference's BLS pool (chain/bls/maybeBatch.ts:17).
"""
from __future__ import annotations

from typing import Iterable, Sequence, Tuple

from .curve import AffineG1, AffineG2, g1, g2
from .fields import (
    ABS_X,
    f12_cyclotomic_pow_x,
    F6_ONE,
    F6_ZERO,
    F12_ONE,
    P,
    R,
    Fp12T,
    f12_conj,
    f12_frobenius,
    f12_inv,
    f12_is_one,
    f12_mul,
    f12_pow,
    f12_sqr,
    f12_sub,
)

# Fp12 constants for the untwist map: w, w^-2, w^-3  (w^2 = v).
_W: Fp12T = (F6_ZERO, F6_ONE)
_W2 = f12_sqr(_W)
_W3 = f12_mul(_W2, _W)
_W2_INV = f12_inv(_W2)
_W3_INV = f12_inv(_W3)

# Hard part of the final exponentiation, computed (not transcribed).
_HARD_EXP = (P**4 - P**2 + 1) // R

Fp12Point = Tuple[Fp12T, Fp12T]  # affine point over Fp12 (never infinity here)


def _embed_fp(a: int) -> Fp12T:
    return (((a, 0), (0, 0), (0, 0)), F6_ZERO)


def _embed_fp2(a) -> Fp12T:
    return ((a, (0, 0), (0, 0)), F6_ZERO)


def untwist(q: AffineG2) -> Fp12Point:
    """E'(Fp2) -> E(Fp12): (x, y) -> (x * w^-2, y * w^-3)."""
    assert q is not None
    x, y = q
    return (f12_mul(_embed_fp2(x), _W2_INV), f12_mul(_embed_fp2(y), _W3_INV))


def embed_g1(p: AffineG1) -> Fp12Point:
    assert p is not None
    return (_embed_fp(p[0]), _embed_fp(p[1]))


def _line_and_step(r: Fp12Point, q: Fp12Point, at: Fp12Point, doubling: bool):
    """Evaluate the line through r,q (tangent if doubling) at ``at`` and return
    (line_value, r_next)."""
    xr, yr = r
    xq, yq = q
    xt, yt = at
    if doubling:
        # tangent slope m = 3 x^2 / 2 y
        xx = f12_sqr(xr)
        num = f12_mul(_embed_fp(3), xx)
        den = f12_inv(f12_mul(_embed_fp(2), yr))
        m = f12_mul(num, den)
        x2 = xr
    else:
        if xr == xq:
            # vertical line (r == -q): value = xt - xr; result is infinity but
            # this never happens in a subgroup Miller loop with ABS_X < r.
            return f12_sub(xt, xr), None
        m = f12_mul(f12_sub(yq, yr), f12_inv(f12_sub(xq, xr)))
        x2 = xq
    # new point
    xn = f12_sub(f12_sub(f12_sqr(m), xr), x2)
    yn = f12_sub(f12_mul(m, f12_sub(xr, xn)), yr)
    # line value at `at`: m*(xt - xr) - (yt - yr)
    line = f12_sub(f12_mul(m, f12_sub(xt, xr)), f12_sub(yt, yr))
    return line, (xn, yn)


def miller_loop(q: AffineG2, p: AffineG1) -> Fp12T:
    """f_{|x|,Q}(P), conjugated for the negative BLS parameter x."""
    if q is None or p is None:
        return F12_ONE
    q12 = untwist(q)
    p12 = embed_g1(p)
    r = q12
    f = F12_ONE
    for bit in bin(ABS_X)[3:]:  # MSB already consumed by r = q
        line, r = _line_and_step(r, r, p12, doubling=True)
        f = f12_mul(f12_sqr(f), line)
        if bit == "1":
            line, r = _line_and_step(r, q12, p12, doubling=False)
            f = f12_mul(f, line)
    # x < 0  =>  invert, realised as conjugation under the final exponentiation
    return f12_conj(f)


def _pow_neg_x(a: Fp12T) -> Fp12T:
    """a^x for the (negative) BLS parameter x, for cyclotomic-subgroup a.

    a^x = conj(a^|x|) since inversion is conjugation in the cyclotomic
    subgroup.
    """
    return f12_conj(f12_cyclotomic_pow_x(a))


def hard_part_x_chain(m: Fp12T) -> Fp12T:
    """m^(3*(p^4 - p^2 + 1)/r) via the x-adic chain (5 pow-by-x).

    Uses 3*(p^4-p^2+1)/r = (x-1)^2 (x+p)(x^2+p^2-1) + 3 — the standard
    BLS12 hard-part decomposition.  The spurious cube is harmless for
    pairing equality checks because gcd(3, r) = 1; the TPU engine
    (lodestar_tpu/ops) implements the identical chain so the two engines
    agree bit-for-bit.  Validated against the direct integer exponent in
    tests/test_bls_oracle.py.
    """
    # t1 = m^((x-1)^2):  m^(x-1) = conj(m^|x| * m)  (x < 0)
    t0 = f12_conj(f12_mul(f12_cyclotomic_pow_x(m), m))
    t1 = f12_conj(f12_mul(f12_cyclotomic_pow_x(t0), t0))
    # a = t1^(x+p)
    a = f12_mul(_pow_neg_x(t1), f12_frobenius(t1, 1))
    # t4 = a^(x^2+p^2-1) = (a^x)^x * a^(p^2) * conj(a)
    b = _pow_neg_x(a)
    t4 = f12_mul(f12_mul(_pow_neg_x(b), f12_frobenius(a, 2)), f12_conj(a))
    # * m^3
    return f12_mul(t4, f12_mul(f12_sqr(m), m))


def _easy_part(f: Fp12T) -> Fp12T:
    """f^((p^6 - 1)(p^2 + 1)) — shared by both hard-part variants."""
    f1 = f12_mul(f12_conj(f), f12_inv(f))          # f^(p^6 - 1)
    return f12_mul(f12_frobenius(f1, 2), f1)       # ^(p^2 + 1)


def final_exponentiation(f: Fp12T) -> Fp12T:
    # hard part (times 3, see hard_part_x_chain)
    return hard_part_x_chain(_easy_part(f))


def pairing(p: AffineG1, q: AffineG2) -> Fp12T:
    """e(P, Q)^3 for P in G1, Q in G2 (affine, None = infinity).

    NOTE: this returns the standard ate pairing CUBED — final_exponentiation
    uses the x-adic hard part 3*(p^4-p^2+1)/r (see hard_part_x_chain).  All
    is-one / equality / bilinearity checks are unaffected (gcd(3, r) = 1 so
    g -> g^3 is a bijection of the r-torsion GT), and the TPU engine
    implements the identical chain, so the two engines agree bit-for-bit.
    Only cross-implementation GT *serialization* vectors would differ; use
    pairing_standard() for those.
    """
    if p is None or q is None:
        return F12_ONE
    return final_exponentiation(miller_loop(q, p))


def pairing_standard(p: AffineG1, q: AffineG2) -> Fp12T:
    """The standard (un-cubed) optimal ate pairing, for cross-implementation
    GT vectors.  Slow path: direct integer hard-part exponent."""
    if p is None or q is None:
        return F12_ONE
    return f12_pow(_easy_part(miller_loop(q, p)), _HARD_EXP)


def multi_miller_loop(pairs: Sequence[Tuple[AffineG1, AffineG2]]) -> Fp12T:
    acc = F12_ONE
    for p, q in pairs:
        if p is None or q is None:
            continue
        acc = f12_mul(acc, miller_loop(q, p))
    return acc


def multi_pairing_is_one(pairs: Sequence[Tuple[AffineG1, AffineG2]]) -> bool:
    """prod_i e(P_i, Q_i) == 1, with a single shared final exponentiation."""
    return f12_is_one(final_exponentiation(multi_miller_loop(pairs)))
