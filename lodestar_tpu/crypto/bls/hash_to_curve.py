"""RFC 9380 hash-to-curve for BLS12-381 G2 (BLS12381G2_XMD:SHA-256_SSWU_RO_).

Ethereum consensus signs over G2 with the proof-of-possession ciphersuite DST
``BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_`` (the scheme the reference's
``@chainsafe/bls`` backends implement).

Pipeline: expand_message_xmd(SHA-256) -> hash_to_field(Fp2, count=2) ->
simplified SWU on the 3-isogenous curve E'' -> isogeny map to E' ->
clear_cofactor (psi-based Budroni-Pintore, equivalent to h_eff per RFC 9380
appendix G.3).

The isogeny coefficient tables are validated programmatically by
tests/test_bls_oracle.py (SSWU output must land on E'', the isogeny image on
E', and the cleared point in G2).
"""
from __future__ import annotations

import hashlib
from typing import List, Tuple

from .curve import JacG2, clear_cofactor_g2, g2
from .fields import (
    P,
    Fp2T,
    F2_ONE,
    f2_add,
    f2_inv,
    f2_is_zero,
    f2_mul,
    f2_neg,
    f2_sgn0,
    f2_sqr,
    f2_sqrt,
    f2_sub,
)

CIPHERSUITE_DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# --- SSWU parameters for the 3-isogenous curve E'': y^2 = x^3 + A'x + B' ---
SSWU_A: Fp2T = (0, 240)
SSWU_B: Fp2T = (1012, 1012)
SSWU_Z: Fp2T = (P - 2, P - 1)  # -(2 + u)

# --- 3-isogeny map E'' -> E' coefficients (RFC 9380 appendix E.3) ---
_C1 = 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6
_C2 = 0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A
_C3 = 0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E
_C4 = 0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D
_C5 = 0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1

XNUM: List[Fp2T] = [
    (_C1, _C1),
    (0, _C2),
    (_C3, _C4),
    (_C5, 0),
]
XDEN: List[Fp2T] = [
    (0, P - 0x48),        # (p - 72) * u
    (0xC, P - 0xC),       # 12 + (p - 12) u
    F2_ONE,               # monic x^2
]
_C6 = 0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706
_C7 = 0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE
_C8 = 0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C
_C9 = 0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F
_C10 = 0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10

YNUM: List[Fp2T] = [
    (_C6, _C6),
    (0, _C7),
    (_C8, _C9),
    (_C10, 0),
]
YDEN: List[Fp2T] = [
    (P - 0x1B0, P - 0x1B0),   # (p - 432)(1 + u)
    (0, P - 0xD8),            # (p - 216) u
    (0x12, P - 0x12),
    F2_ONE,                   # monic x^3
]


# ---------------------------------------------------------------------------
# expand_message_xmd (SHA-256)
# ---------------------------------------------------------------------------


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    b_in_bytes = 32   # SHA-256 output
    r_in_bytes = 64   # SHA-256 block
    ell = (len_in_bytes + b_in_bytes - 1) // b_in_bytes
    if ell > 255 or len(dst) > 255:
        raise ValueError("expand_message_xmd: invalid length")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(r_in_bytes)
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b = [hashlib.sha256(b0 + b"\x01" + dst_prime).digest()]
    for i in range(2, ell + 1):
        tv = bytes(x ^ y for x, y in zip(b0, b[-1]))
        b.append(hashlib.sha256(tv + bytes([i]) + dst_prime).digest())
    return b"".join(b)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes = CIPHERSUITE_DST) -> List[Fp2T]:
    L = 64
    uniform = expand_message_xmd(msg, dst, count * 2 * L)
    out = []
    for i in range(count):
        e = []
        for j in range(2):
            off = L * (j + i * 2)
            e.append(int.from_bytes(uniform[off : off + L], "big") % P)
        out.append((e[0], e[1]))
    return out


# ---------------------------------------------------------------------------
# Simplified SWU on E'' and isogeny to E'
# ---------------------------------------------------------------------------


def map_to_curve_sswu(t: Fp2T) -> Tuple[Fp2T, Fp2T]:
    """Non-constant-time simplified SWU; returns a point on E''."""
    zt2 = f2_mul(SSWU_Z, f2_sqr(t))          # Z t^2
    tv1 = f2_add(f2_sqr(zt2), zt2)           # Z^2 t^4 + Z t^2
    if f2_is_zero(tv1):
        x1 = f2_mul(SSWU_B, f2_inv(f2_mul(SSWU_Z, SSWU_A)))
    else:
        x1 = f2_mul(
            f2_mul(f2_neg(SSWU_B), f2_inv(SSWU_A)),
            f2_add(F2_ONE, f2_inv(tv1)),
        )
    gx1 = f2_add(f2_mul(f2_add(f2_sqr(x1), SSWU_A), x1), SSWU_B)
    y = f2_sqrt(gx1)
    if y is not None:
        x = x1
    else:
        x2 = f2_mul(zt2, x1)
        gx2 = f2_add(f2_mul(f2_add(f2_sqr(x2), SSWU_A), x2), SSWU_B)
        x, y = x2, f2_sqrt(gx2)
    assert y is not None
    if f2_sgn0(t) != f2_sgn0(y):
        y = f2_neg(y)
    return (x, y)


def _horner(coeffs: List[Fp2T], x: Fp2T) -> Fp2T:
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = f2_add(f2_mul(acc, x), c)
    return acc


def iso_map_g2(x: Fp2T, y: Fp2T) -> Tuple[Fp2T, Fp2T]:
    """3-isogeny E'' -> E'."""
    x_num = _horner(XNUM, x)
    x_den = _horner(XDEN, x)
    y_num = _horner(YNUM, x)
    y_den = _horner(YDEN, x)
    xo = f2_mul(x_num, f2_inv(x_den))
    yo = f2_mul(f2_mul(y, y_num), f2_inv(y_den))
    return (xo, yo)


# ---------------------------------------------------------------------------
# Full hash_to_curve
# ---------------------------------------------------------------------------


def map_to_curve_g2(t: Fp2T) -> Tuple[Fp2T, Fp2T]:
    x, y = map_to_curve_sswu(t)
    return iso_map_g2(x, y)


def hash_to_g2(msg: bytes, dst: bytes = CIPHERSUITE_DST) -> JacG2:
    """hash_to_curve: returns a Jacobian point in the G2 subgroup."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = g2.from_affine(map_to_curve_g2(u0))
    q1 = g2.from_affine(map_to_curve_g2(u1))
    return clear_cofactor_g2(g2.add_pts(q0, q1))


def hash_to_g2_affine(msg: bytes, dst: bytes = CIPHERSUITE_DST):
    """Affine hash_to_curve with the native C fast path when available.

    ~100x the pure-Python pipeline (native/csrc/bls_h2c.c, differential-
    tested in tests/test_native_h2c.py); the production verification path
    (ops/bls12_381/verify._encode_sets) hashes every message through
    here.  Role parity: blst's in-C hash_to_g2 behind @chainsafe/bls."""
    from lodestar_tpu import native

    if native.has_h2c():
        try:
            return native.hash_to_g2_affine(msg, dst)
        except ValueError:
            pass  # e.g. message beyond the C buffer cap: uniform fallback
    return g2.to_affine(hash_to_g2(msg, dst))
