"""Projective (inversion-free) Miller loop — CPU prototype of the TPU kernel.

This module is the validated formula template for the JAX engine
(lodestar_tpu/ops/bls12_381/pairing.py): homogeneous-projective point updates
on the twist E'(Fp2) with sparse line evaluation, no field inversions inside
the loop (the oracle's pairing.py uses affine lines + Fp12 inversions, which
would be prohibitive as a per-step device op).

Derivation (matches the oracle's untwist (x', y') -> (x' w^-2, y' w^-3) for
the M-twist E': y^2 = x^3 + 4(1+u), w^6 = xi = 1+u):

  Tangent at T=(X,Y,Z):   theta = 3X^2, lam = 2YZ
  Chord T,Q2=(x2,y2):     theta = y2 Z - Y, lam = x2 Z - X

  Scaled line value at P=(xP, yP) (scale factors lie in Fp2 and cancel under
  the final exponentiation):
      L = theta*xP * w^5  +  d1 * w^3  -  xi*lam_z*yP
  with (doubling)  d1 = 2Y^2 Z - 3X^3,          lam_z = 2YZ^2
       (addition)  d1 = lam*y2 - theta*x2,      lam_z = lam

  i.e. in the tower layout Fp12 = ((c0,c1,c2),(d0,d1,d2)) the line is the
  sparse element ((c0,0,0),(0,d1,d2)) — "slots 0,3,5" of the w-basis.

Point updates (generic Weierstrass, homogeneous):
  double:  X3 = 2XYZ(9X^3 - 8Y^2 Z)
           Y3 = 9X^3(4Y^2 Z - 3X^3) - 8Y^4 Z^2
           Z3 = 8 Y^3 Z^3
  mixed add (Z2=1):  N  = theta^2 Z - 2 lam^2 X - lam^3
           X3 = lam * N
           Y3 = theta*(lam^2 X - N) - lam^3 Y
           Z3 = lam^3 Z

Validated against the oracle pairing in tests/test_pairing_proj.py; the JAX
engine then ports these formulas verbatim onto limb tensors.
"""
from __future__ import annotations

from typing import Sequence, Tuple

from .curve import AffineG1, AffineG2
from .fields import (
    ABS_X,
    F12_ONE,
    Fp2T,
    Fp12T,
    F2_ZERO,
    f2_add,
    f2_mul,
    f2_mul_by_xi,
    f2_mul_scalar,
    f2_neg,
    f2_sqr,
    f2_sub,
    f12_conj,
    f12_mul,
    f12_sqr,
)
from .pairing import final_exponentiation

ProjG2 = Tuple[Fp2T, Fp2T, Fp2T]  # homogeneous (X, Y, Z), never infinity here


def _line_sparse(c0: Fp2T, d1: Fp2T, d2: Fp2T) -> Fp12T:
    return ((c0, F2_ZERO, F2_ZERO), (F2_ZERO, d1, d2))


def _dbl_step(t: ProjG2, xp: int, yp: int):
    """Double T and return (line(P), 2T)."""
    X, Y, Z = t
    xx = f2_sqr(X)            # X^2
    yy = f2_sqr(Y)            # Y^2
    x3 = f2_mul(xx, X)        # X^3
    yyz = f2_mul(yy, Z)       # Y^2 Z
    # line
    c0 = f2_mul_by_xi(f2_mul_scalar(f2_mul(f2_mul(Y, Z), Z), 2 * yp))  # 2 xi Y Z^2 yP
    c0 = f2_neg(c0)
    d1 = f2_sub(f2_mul_scalar(yyz, 2), f2_mul_scalar(x3, 3))           # 2Y^2Z - 3X^3
    d2 = f2_mul_scalar(f2_mul(xx, Z), 3 * xp)                          # 3 X^2 Z xP
    # update
    x3_9 = f2_mul_scalar(x3, 9)
    yyz_8 = f2_mul_scalar(yyz, 8)
    Xn = f2_mul(f2_mul_scalar(f2_mul(f2_mul(X, Y), Z), 2), f2_sub(x3_9, yyz_8))
    Yn = f2_sub(
        f2_mul(x3_9, f2_sub(f2_mul_scalar(yyz, 4), f2_mul_scalar(x3, 3))),
        f2_mul_scalar(f2_sqr(yyz), 8),
    )
    Zn = f2_mul_scalar(f2_mul(f2_mul(yy, Y), f2_mul(f2_sqr(Z), Z)), 8)
    return _line_sparse(c0, d1, d2), (Xn, Yn, Zn)


def _add_step(t: ProjG2, q: Tuple[Fp2T, Fp2T], xp: int, yp: int):
    """Mixed-add affine q into T and return (line(P), T+Q)."""
    X, Y, Z = t
    x2, y2 = q
    theta = f2_sub(f2_mul(y2, Z), Y)
    lam = f2_sub(f2_mul(x2, Z), X)
    # line
    c0 = f2_neg(f2_mul_by_xi(f2_mul_scalar(lam, yp)))
    d1 = f2_sub(f2_mul(lam, y2), f2_mul(theta, x2))
    d2 = f2_mul_scalar(theta, xp)
    # update
    ll = f2_sqr(lam)          # lam^2
    lll = f2_mul(ll, lam)     # lam^3
    llx = f2_mul(ll, X)
    n = f2_sub(f2_sub(f2_mul(f2_sqr(theta), Z), f2_mul_scalar(llx, 2)), lll)
    Xn = f2_mul(lam, n)
    Yn = f2_sub(f2_mul(theta, f2_sub(llx, n)), f2_mul(lll, Y))
    Zn = f2_mul(lll, Z)
    return _line_sparse(c0, d1, d2), (Xn, Yn, Zn)


def miller_loop_proj(q: AffineG2, p: AffineG1) -> Fp12T:
    """f_{|x|,Q}(P) (conjugated for x < 0) with projective steps.

    Agrees with the oracle miller_loop up to subfield factors — i.e. exactly
    after final exponentiation.
    """
    if q is None or p is None:
        return F12_ONE
    xp, yp = p
    t: ProjG2 = (q[0], q[1], (1, 0))
    f = F12_ONE
    for bit in bin(ABS_X)[3:]:
        line, t = _dbl_step(t, xp, yp)
        f = f12_mul(f12_sqr(f), line)
        if bit == "1":
            line, t = _add_step(t, q, xp, yp)
            f = f12_mul(f, line)
    return f12_conj(f)


def pairing_proj(p: AffineG1, q: AffineG2) -> Fp12T:
    return final_exponentiation(miller_loop_proj(q, p))


def multi_pairing_is_one_proj(pairs: Sequence[Tuple[AffineG1, AffineG2]]) -> bool:
    acc = F12_ONE
    for p, q in pairs:
        if p is None or q is None:
            continue
        acc = f12_mul(acc, miller_loop_proj(q, p))
    fe = final_exponentiation(acc)
    from .fields import f12_is_one

    return f12_is_one(fe)
