"""HTTP JSON-RPC Eth1Provider (reference:
packages/beacon-node/src/eth1/provider/eth1Provider.ts).

Implements the ``Eth1Provider`` protocol the deposit tracker consumes
over real JSON-RPC: ``eth_blockNumber`` / ``eth_getBlockByNumber`` for
the follow head and ``eth_getLogs`` over the deposit contract for
DepositEvent logs, fetched in bounded block-range chunks (the reference
fetches in getLogs batches for the same reason: an unbounded mainnet
range times out or trips provider limits).

DepositEvent log ABI (the deposit contract's single event): five
dynamic ``bytes`` arguments — pubkey(48), withdrawal_credentials(32),
amount(8, little-endian gwei), signature(96), index(8, little-endian) —
encoded as a standard ABI head of five offsets plus length-prefixed,
32-byte-padded tails.  ``decode_deposit_log`` walks that layout
strictly; a malformed log is a corrupt provider, not something to skip.
"""
from __future__ import annotations

from typing import List, Optional

from lodestar_tpu.eth1 import Eth1Block
from lodestar_tpu.execution.http_session import (
    ReusedClientSession,
    json_rpc_result,
    post_json_rpc_once,
    request_with_retry,
)
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger

# keccak256("DepositEvent(bytes,bytes,bytes,bytes,bytes)") — the deposit
# contract's only event topic (same on every network)
DEPOSIT_EVENT_TOPIC = (
    "0x649bbc62d0e31342afea4e5cd82d4049e7e1ee912fc0889aa790803be39038c5"
)
# mainnet deposit contract (config DEPOSIT_CONTRACT_ADDRESS default)
MAINNET_DEPOSIT_CONTRACT = "0x00000000219ab540356cbb839cbe05303d7705fa"


class Eth1HttpError(RuntimeError):
    """Non-2xx HTTP response from the eth1 node (5xx retries)."""

    def __init__(self, method: str, status: int):
        super().__init__(f"{method}: HTTP {status}")
        self.status = status


class Eth1RpcError(RuntimeError):
    """JSON-RPC error response: a deterministic answer, never retried."""

    def __init__(self, method: str, code: int, message: str):
        super().__init__(f"{method}: JSON-RPC error {code}: {message}")
        self.method = method
        self.code = code
        self.message = message


def _abi_encode_bytes_tuple(values) -> bytes:
    """ABI-encode a tuple of dynamic `bytes` values (the DepositEvent
    data layout) — shared with the mock EL server so both sides of the
    seam speak the byte-exact contract encoding."""
    head = b""
    tail = b""
    offset = 32 * len(values)
    for v in values:
        head += offset.to_bytes(32, "big")
        padded = bytes(v) + b"\x00" * ((32 - len(v) % 32) % 32)
        tail += len(v).to_bytes(32, "big") + padded
        offset += 32 + len(padded)
    return head + tail


def _abi_decode_bytes_tuple(data: bytes, n: int) -> List[bytes]:
    if len(data) < 32 * n:
        raise ValueError(f"ABI data too short for {n}-bytes head: {len(data)}")
    out = []
    for i in range(n):
        offset = int.from_bytes(data[32 * i : 32 * (i + 1)], "big")
        if offset + 32 > len(data):
            raise ValueError(f"ABI offset {offset} out of range")
        length = int.from_bytes(data[offset : offset + 32], "big")
        start = offset + 32
        if start + length > len(data):
            raise ValueError(f"ABI tail [{start}:{start+length}] out of range")
        out.append(data[start : start + length])
    return out


def decode_deposit_log(log: dict):
    """One eth_getLogs entry → ssz.phase0.DepositEvent."""
    from lodestar_tpu.types import ssz

    data = bytes.fromhex(log["data"].removeprefix("0x"))
    pubkey, wc, amount, signature, index = _abi_decode_bytes_tuple(data, 5)
    if (len(pubkey), len(wc), len(amount), len(signature), len(index)) != (
        48, 32, 8, 96, 8,
    ):
        raise ValueError(
            "DepositEvent field widths wrong: "
            f"{[len(x) for x in (pubkey, wc, amount, signature, index)]}"
        )
    return ssz.phase0.DepositEvent(
        deposit_data=ssz.phase0.DepositData(
            pubkey=pubkey,
            withdrawal_credentials=wc,
            amount=int.from_bytes(amount, "little"),
            signature=signature,
        ),
        block_number=int(log["blockNumber"], 16),
        index=int.from_bytes(index, "little"),
    )


class HttpEth1Provider(ReusedClientSession):
    """The production Eth1Provider: JSON-RPC over aiohttp with the same
    bounded-retry discipline as the engine client (transport faults and
    5xx retry on these read-only — hence idempotent — methods; JSON-RPC
    errors surface immediately as ``Eth1RpcError``)."""

    def __init__(
        self,
        url: str,
        deposit_contract: str = MAINNET_DEPOSIT_CONTRACT,
        timeout: float = 12.0,
        log_chunk_size: int = 1000,
    ):
        self.url = url
        self.deposit_contract = deposit_contract.lower()
        self.timeout = timeout
        self.log_chunk_size = max(1, int(log_chunk_size))
        self._id = 0
        self._log = get_logger("eth1")

    async def _rpc(self, method: str, params):
        async def send_once():
            faults.fire("eth1.provider.http", method=method)
            return await self._post_once(method, params)

        body = await request_with_retry(
            send_once,
            idempotent=True,
            retryable_status=lambda e: (
                isinstance(e, Eth1HttpError) and e.status >= 500
            ),
            log=lambda m: self._log.warn(f"{method}: {m}"),
        )
        return json_rpc_result(
            body, on_error=lambda code, msg: Eth1RpcError(method, code, msg)
        )

    async def _post_once(self, method: str, params) -> dict:
        """One transport attempt (overridden by transport-free tests);
        status/error-body semantics live in post_json_rpc_once."""
        self._id += 1
        session = await self._ses()
        return await post_json_rpc_once(
            session,
            self.url,
            method=method,
            params=params,
            rpc_id=self._id,
            timeout_s=self.timeout,
            http_error=Eth1HttpError,
        )

    # -- Eth1Provider protocol ------------------------------------------

    async def get_block_number(self) -> int:
        return int(await self._rpc("eth_blockNumber", []), 16)

    async def get_block(self, number: int) -> Optional[Eth1Block]:
        blk = await self._rpc("eth_getBlockByNumber", [hex(int(number)), False])
        if blk is None:
            return None
        return Eth1Block(
            number=int(blk["number"], 16),
            hash=bytes.fromhex(blk["hash"].removeprefix("0x")),
            timestamp=int(blk["timestamp"], 16),
        )

    async def get_deposit_events(self, from_block: int, to_block: int):
        """DepositEvent logs for [from_block, to_block], fetched in
        ``log_chunk_size`` ranges and returned sorted by deposit index
        (the tracker asserts the index sequence is gap-free)."""
        events = []
        start = int(from_block)
        while start <= to_block:
            end = min(start + self.log_chunk_size - 1, int(to_block))
            logs = await self._rpc(
                "eth_getLogs",
                [
                    {
                        "fromBlock": hex(start),
                        "toBlock": hex(end),
                        "address": self.deposit_contract,
                        "topics": [DEPOSIT_EVENT_TOPIC],
                    }
                ],
            )
            events.extend(decode_deposit_log(log) for log in logs)
            start = end + 1
        events.sort(key=lambda ev: ev.index)
        return events
