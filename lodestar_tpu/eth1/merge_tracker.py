"""Merge-transition watcher (reference:
beacon-node/src/eth1/eth1MergeBlockTracker.ts): polls the eth1/execution
provider for the terminal proof-of-work block — the first block whose
total difficulty reaches TERMINAL_TOTAL_DIFFICULTY while its parent's is
still below — and validates candidate merge blocks during block import
(spec `validate_merge_block`, consumed by the bellatrix block path).

State machine mirrors the reference's StatusCode:
  PRE_MERGE -> SEARCHING_FOR_MERGE_BLOCK -> FOUND -> POST_MERGE
POST_MERGE is entered externally once a finalized execution payload
exists (the tracker is then shut down, eth1MergeBlockTracker.ts
`mergeCompleted`).
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass
from enum import Enum
from typing import Dict, Optional, Protocol

from lodestar_tpu.utils import get_logger

_log = get_logger("merge-tracker")


@dataclass(frozen=True)
class PowBlock:
    """eth_getBlockByHash projection (merge fields only)."""

    block_hash: bytes
    parent_hash: bytes
    total_difficulty: int


class PowProvider(Protocol):
    async def get_pow_block(self, block_hash: bytes) -> Optional[PowBlock]: ...
    async def get_pow_head(self) -> Optional[PowBlock]: ...


class MergeStatus(Enum):
    PRE_MERGE = "PRE_MERGE"
    SEARCHING = "SEARCHING_FOR_MERGE_BLOCK"
    FOUND = "FOUND_MERGE_BLOCK"
    POST_MERGE = "POST_MERGE"


class MockPowChain:
    """Scripted PoW chain for tests/sim (difficulty accumulates per
    block); stands in for the EL's eth_getBlockByHash."""

    def __init__(self, difficulty_per_block: int = 10):
        self.blocks: Dict[bytes, PowBlock] = {}
        self._head: Optional[PowBlock] = None
        self.difficulty_per_block = difficulty_per_block

    def mine(self, n: int = 1) -> PowBlock:
        for _ in range(n):
            parent = self._head
            td = (parent.total_difficulty if parent else 0) + self.difficulty_per_block
            num = len(self.blocks)
            blk = PowBlock(
                block_hash=num.to_bytes(8, "big").rjust(32, b"\x0f"),
                parent_hash=parent.block_hash if parent else b"\x00" * 32,
                total_difficulty=td,
            )
            self.blocks[blk.block_hash] = blk
            self._head = blk
        return self._head

    async def get_pow_block(self, block_hash: bytes) -> Optional[PowBlock]:
        return self.blocks.get(block_hash)

    async def get_pow_head(self) -> Optional[PowBlock]:
        return self._head


class Eth1MergeBlockTracker:
    def __init__(self, cfg, provider: PowProvider):
        self.cfg = cfg
        self.provider = provider
        self.status = MergeStatus.PRE_MERGE
        self.merge_block: Optional[PowBlock] = None
        self._task: Optional[asyncio.Task] = None

    # -- polling ---------------------------------------------------------

    async def poll_once(self) -> Optional[PowBlock]:
        """One head poll: advance the state machine, return the terminal
        block if (now) known."""
        if self.status in (MergeStatus.FOUND, MergeStatus.POST_MERGE):
            return self.merge_block
        head = await self.provider.get_pow_head()
        if head is None:
            return None
        ttd = self.cfg.TERMINAL_TOTAL_DIFFICULTY
        # single-owner state machine: poll_once runs only on the node
        # notifier task, so the read->await->write sequences below have
        # exactly one writer (await-in-critical suppressions document that)
        if head.total_difficulty < ttd:
            self.status = MergeStatus.PRE_MERGE  # lodelint: disable=await-in-critical
            return None
        # TTD reached somewhere at or below head: walk parents until the
        # crossing block (bounded by the distance TD can have overshot).
        self.status = MergeStatus.SEARCHING  # lodelint: disable=await-in-critical
        block = head
        while True:
            parent = await self.provider.get_pow_block(block.parent_hash)
            if parent is None or parent.total_difficulty < ttd:
                if parent is None and block.parent_hash != b"\x00" * 32:
                    return None  # ancestor unavailable: keep searching
                self.merge_block = block  # lodelint: disable=await-in-critical
                self.status = MergeStatus.FOUND  # lodelint: disable=await-in-critical
                return block
            block = parent

    def get_terminal_pow_block(self) -> Optional[PowBlock]:
        """FOUND-state accessor (produceBlock asks for this pre-merge)."""
        return self.merge_block

    def merge_completed(self) -> None:
        """Finalized execution payload seen — stop tracking."""
        self.status = MergeStatus.POST_MERGE
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # -- spec validate_merge_block (consumed on block import) ------------

    async def validate_merge_block(self, parent_hash: bytes) -> bool:
        """Spec validate_merge_block: the payload's parent must be a valid
        terminal block (TD >= TTD, parent TD < TTD), or match
        TERMINAL_BLOCK_HASH when that override is configured."""
        if self.cfg.TERMINAL_BLOCK_HASH != b"\x00" * 32:
            return parent_hash == self.cfg.TERMINAL_BLOCK_HASH
        pow_block = await self.provider.get_pow_block(parent_hash)
        if pow_block is None:
            return False
        pow_parent = await self.provider.get_pow_block(pow_block.parent_hash)
        ttd = self.cfg.TERMINAL_TOTAL_DIFFICULTY
        if pow_block.total_difficulty < ttd:
            return False
        if pow_parent is None:
            # genesis-parent terminal block: valid iff TTD met from zero
            return pow_block.parent_hash == b"\x00" * 32
        return pow_parent.total_difficulty < ttd

    # -- background loop -------------------------------------------------

    async def start(self, interval_s: float = 12.0) -> None:
        async def _loop():
            while self.status not in (
                MergeStatus.FOUND,
                MergeStatus.POST_MERGE,
            ):
                try:
                    await self.poll_once()
                except Exception as e:
                    _log.warn(
                        f"eth1 poll failed: {type(e).__name__}: {e}; "
                        f"retrying in {interval_s:.0f}s"
                    )
                await asyncio.sleep(interval_s)

        self._task = asyncio.create_task(_loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass  # our own cancel — the expected outcome
            except Exception as e:
                _log.debug(f"poll task ended with {type(e).__name__}: {e}")
            self._task = None
