"""Eth1 deposit tracking + eth1 data voting (reference:
packages/beacon-node/src/eth1/ — eth1DepositDataTracker.ts,
eth1DataCache.ts, provider/).

The tracker follows an eth1 provider (JSON-RPC in production; the mock
here plays the engine/mock.ts role), ingests DepositEvent logs into the
deposit cache (db.deposit_event + db.deposit_data_root), and serves
block production with:

- the eth1 data VOTE (spec get_eth1_vote: the majority vote within the
  current voting period, else the follow-distance block), and
- the DEPOSITS due for inclusion (proofs against the state's
  eth1_data.deposit_root from the incremental deposit tree).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Protocol, Tuple

from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DEPOSIT_CONTRACT_TREE_DEPTH,
)
from lodestar_tpu.state_transition.util.merkle import (
    list_single_proof,
    list_tree_root,
)
from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot
from lodestar_tpu.types import ssz


@dataclass(frozen=True)
class Eth1Block:
    number: int
    hash: bytes
    timestamp: int


class Eth1Provider(Protocol):
    """The JSON-RPC seam (provider/eth1Provider.ts)."""

    async def get_block_number(self) -> int: ...
    async def get_block(self, number: int) -> Optional[Eth1Block]: ...
    async def get_deposit_events(
        self, from_block: int, to_block: int
    ) -> List["ssz.phase0.DepositEvent"]: ...


class MockEth1Provider:
    """In-memory eth1 chain with scripted deposits (the test/sim EL)."""

    def __init__(self, genesis_timestamp: int = 0, block_time: int = 14):
        self.blocks: List[Eth1Block] = []
        self.deposits_by_block: Dict[int, List["ssz.phase0.DepositEvent"]] = {}
        self.block_time = block_time
        self.genesis_timestamp = genesis_timestamp
        self.add_blocks(1)

    def add_blocks(self, n: int) -> None:
        for _ in range(n):
            num = len(self.blocks)
            self.blocks.append(
                Eth1Block(
                    number=num,
                    hash=num.to_bytes(4, "big").rjust(32, b"\xe1"),
                    timestamp=self.genesis_timestamp + num * self.block_time,
                )
            )

    def add_deposit(self, deposit_data: "ssz.phase0.DepositData") -> None:
        """Include a deposit log in the latest block."""
        num = len(self.blocks) - 1
        index = sum(len(v) for v in self.deposits_by_block.values())
        ev = ssz.phase0.DepositEvent(
            deposit_data=deposit_data, block_number=num, index=index
        )
        self.deposits_by_block.setdefault(num, []).append(ev)

    async def get_block_number(self) -> int:
        return len(self.blocks) - 1

    async def get_block(self, number: int) -> Optional[Eth1Block]:
        if 0 <= number < len(self.blocks):
            return self.blocks[number]
        return None

    async def get_deposit_events(self, from_block: int, to_block: int):
        out = []
        for n in range(from_block, to_block + 1):
            out.extend(self.deposits_by_block.get(n, []))
        return out


class DepositTree:
    """Incremental deposit merkle tree (the deposit contract's tree;
    persistent-merkle-tree role for deposit proofs)."""

    def __init__(self):
        self.roots: List[bytes] = []  # DepositData hash tree roots, by index

    def push(self, deposit_data: "ssz.phase0.DepositData") -> None:
        self.roots.append(ssz.phase0.DepositData.hash_tree_root(deposit_data))

    def count(self) -> int:
        return len(self.roots)

    def root_at(self, count: int) -> bytes:
        return list_tree_root(
            self.roots[:count], DEPOSIT_CONTRACT_TREE_DEPTH, count
        )

    def proof(self, index: int, count: int) -> List[bytes]:
        return list_single_proof(
            self.roots[:count], DEPOSIT_CONTRACT_TREE_DEPTH, index, count
        )


class Eth1DepositDataTracker:
    def __init__(self, provider: Eth1Provider, cfg, db=None):
        self.provider = provider
        self.cfg = cfg
        self.db = db
        self.tree = DepositTree()
        self.deposit_events: List["ssz.phase0.DepositEvent"] = []
        self.block_cache: List[Eth1Block] = []
        self._synced_to = -1

    # -- ingestion ------------------------------------------------------

    async def update(self) -> int:
        """Pull new blocks + deposit logs from the provider; returns the
        number of new deposit events ingested."""
        head = await self.provider.get_block_number()
        if head <= self._synced_to:
            return 0
        events = await self.provider.get_deposit_events(self._synced_to + 1, head)
        ingested = 0
        for ev in events:
            # re-deliveries are NOT a gap: a previous update() that
            # ingested events but failed before advancing _synced_to
            # (e.g. an HTTP get_block fault mid-range) re-fetches the
            # same range on retry — replaying an already-ingested index
            # must be a no-op, or the tracker wedges on its own assert
            if ev.index < self.tree.count():
                continue
            assert ev.index == self.tree.count(), "deposit log gap"
            ingested += 1
            self.tree.push(ev.deposit_data)
            self.deposit_events.append(ev)
            if self.db is not None:
                self.db.deposit_event.put(ev.index, ev)
                self.db.deposit_data_root.put(
                    ev.index,
                    ssz.phase0.DepositData.hash_tree_root(ev.deposit_data),
                )
        # same idempotence on retry: resume AFTER the blocks a
        # partially-failed earlier update already cached — re-fetching
        # them only to discard the responses wastes a round-trip each
        last_cached = self.block_cache[-1].number if self.block_cache else -1
        for n in range(max(self._synced_to + 1, last_cached + 1), head + 1):
            blk = await self.provider.get_block(n)
            if blk is not None and blk.number > last_cached:
                self.block_cache.append(blk)
        # single-owner: the eth1 follow task is the only writer of
        # _synced_to; the read->await->write spans only its own loop
        self._synced_to = head  # lodelint: disable=await-in-critical
        return ingested

    # -- eth1 data voting (spec get_eth1_vote) --------------------------

    def _voting_period_start_time(self, state) -> int:
        period_start_slot = state.slot - state.slot % (
            _p.EPOCHS_PER_ETH1_VOTING_PERIOD * _p.SLOTS_PER_EPOCH
        )
        return state.genesis_time + period_start_slot * self.cfg.SECONDS_PER_SLOT

    def _is_candidate(self, block: Eth1Block, period_start: int) -> bool:
        f = self.cfg.ETH1_FOLLOW_DISTANCE * self.cfg.SECONDS_PER_ETH1_BLOCK
        return (
            block.timestamp + f <= period_start
            and block.timestamp + 2 * f >= period_start
        )

    def _eth1_data_for_block(self, block: Eth1Block) -> "ssz.phase0.Eth1Data":
        count = sum(
            1 for ev in self.deposit_events if ev.block_number <= block.number
        )
        return ssz.phase0.Eth1Data(
            deposit_root=self.tree.root_at(count),
            deposit_count=count,
            block_hash=block.hash,
        )

    def get_eth1_vote(self, state) -> "ssz.phase0.Eth1Data":
        period_start = self._voting_period_start_time(state)
        candidates = [
            b for b in self.block_cache if self._is_candidate(b, period_start)
        ]
        # only blocks whose deposit count has not regressed
        valid = [
            self._eth1_data_for_block(b)
            for b in candidates
        ]
        valid = [d for d in valid if d.deposit_count >= state.eth1_data.deposit_count]
        if not valid:
            return state.eth1_data
        # majority among the state's existing votes, else the latest candidate
        def key(d):
            return (bytes(d.deposit_root), d.deposit_count, bytes(d.block_hash))

        votes: Dict[tuple, int] = {}
        for v in state.eth1_data_votes:
            votes[key(v)] = votes.get(key(v), 0) + 1
        best = max(valid, key=lambda d: (votes.get(key(d), 0), d.deposit_count))
        return best

    # -- deposit inclusion (getDeposits) --------------------------------

    def get_deposits(self, state, eth1_data=None) -> List["ssz.phase0.Deposit"]:
        """Deposits due under `eth1_data` (default: the state's), proven
        against its deposit root."""
        data = eth1_data if eth1_data is not None else state.eth1_data
        start = state.eth1_deposit_index
        count = min(
            _p.MAX_DEPOSITS, data.deposit_count - start
        )
        out = []
        for i in range(start, start + count):
            proof = self.tree.proof(i, data.deposit_count)
            out.append(
                ssz.phase0.Deposit(
                    proof=proof,
                    data=self.deposit_events[i].deposit_data,
                )
            )
        return out
