"""Node-level composition helpers (reference: beacon-node/src/node/):
the periodic status notifier (notifier.ts:29 runNodeNotifier) — the
once-per-slot human-readable log line summarizing sync state, head,
finalized checkpoint, peer count, and the execution/merge status.
"""
from __future__ import annotations

import asyncio
from typing import Optional

from lodestar_tpu.utils import Logger, get_logger

_log = get_logger("node")


def format_status_line(chain, network=None, sync=None) -> str:
    """One notifier line (notifier.ts builds exactly this shape):

      `synced - slot: 123 - head: 0xabcd… (slot 123) - finalized: 3 - peers: 8`
    """
    slot = chain.clock.current_slot
    head_root = chain.head_root
    head_slot = None
    try:
        head_slot = chain.fork_choice.get_block(
            "0x" + head_root.hex()
        ).slot  # proto-array node
    # status-line decoration is best-effort: a head outside the
    # proto-array simply renders without a head slot
    except Exception:  # lodelint: disable=silent-except
        pass
    st = chain.fork_choice.store

    if sync is not None and getattr(sync, "is_syncing", lambda: False)():
        distance = max(0, slot - (head_slot if head_slot is not None else 0))
        state = f"syncing ({distance} slots behind)"
    elif head_slot is not None and slot - head_slot > 3:
        state = f"stalled ({slot - head_slot} slots behind)"
    else:
        state = "synced"

    parts = [
        state,
        f"slot: {slot}",
        f"head: 0x{head_root.hex()[:8]}…"
        + (f" (slot {head_slot})" if head_slot is not None else ""),
        f"justified: {st.justified.epoch}",
        f"finalized: {st.finalized.epoch}",
    ]
    if network is not None:
        try:
            parts.append(f"peers: {len(network.peer_manager.connected_peers())}")
        # status-line decoration is best-effort: a network double
        # without peer accounting renders without the peers field
        except Exception:  # lodelint: disable=silent-except
            pass
    return " - ".join(parts)


async def run_node_notifier(
    chain,
    network=None,
    sync=None,
    logger: Optional[Logger] = None,
    *,
    interval_s: Optional[float] = None,
    stop_after: Optional[int] = None,
) -> None:
    """Log a status line once per slot (aligned to slot boundaries like
    the reference's timeToNextSlot wait). Runs until cancelled, or for
    `stop_after` lines (tests)."""
    log = (logger or Logger("node")).child("notifier")
    seconds_per_slot = float(
        interval_s
        if interval_s is not None
        else getattr(chain.cfg, "SECONDS_PER_SLOT", 12)
    )
    emitted = 0
    try:
        while True:
            log.info(format_status_line(chain, network, sync))
            emitted += 1
            if stop_after is not None and emitted >= stop_after:
                return
            # sleep to just past the next slot boundary, per the chain's
            # own clock (works with injected/fake time sources)
            try:
                into = chain.clock.seconds_into_slot()
                delay = max(0.05, min(seconds_per_slot - into + 0.01, seconds_per_slot))
            except Exception as e:
                # a clock double without seconds_into_slot: fall back
                # to whole-slot cadence, visibly
                _log.debug(
                    f"clock probe failed ({type(e).__name__}: {e}); "
                    f"sleeping a full slot"
                )
                delay = seconds_per_slot
            await asyncio.sleep(delay)
    except asyncio.CancelledError:
        raise  # cancellation is the normal shutdown path; let it propagate
