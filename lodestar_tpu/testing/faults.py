"""Deterministic fault injection for chaos tests (docs/FAULTS.md).

Production code marks its failure seams with a named checkpoint::

    from lodestar_tpu.testing import faults
    ...
    faults.fire("bls.device.execute")   # no-op unless a test armed it

and a test arms the point with a deterministic schedule::

    with faults.inject("bls.device.execute", times=2,
                       error=lambda: XlaRuntimeError("injected")):
        ...  # the first two fire() calls raise, later ones pass

Design constraints:

* **Zero cost when disarmed** — ``fire()`` is one dict check on the BLS
  hot path; the module imports nothing heavy.
* **Deterministic** — schedules are count/script/modulo based, never
  random, so a chaos test's failure sequence is exactly reproducible.
* **Thread-safe** — fire() is called from executor threads (device
  dispatch) and the event loop alike; arming/disarming takes a lock and
  per-plan counters are guarded by it.
* **Scoped** — ``inject`` is a context manager that restores whatever
  plan (usually none) was armed before, so a failing test cannot leak
  an armed fault into the rest of the suite.

The registered injection points are listed in docs/FAULTS.md; grep for
``faults.fire`` to find the seams in code.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence


class FaultError(RuntimeError):
    """Default error raised by an armed injection point."""


class Drop(FaultError):
    """Directive: the seam silently discards the unit of work (a wire
    frame, a gossip message) instead of failing loudly.  Network seams
    interpret it; elsewhere it behaves like any injected error."""


class Delay(FaultError):
    """Directive: the seam sleeps ``seconds`` then proceeds normally —
    slow links, stalling responders.  Only meaningful at async seams
    that declare support (net.transport.write, net.reqresp.respond)."""

    def __init__(self, seconds: float):
        super().__init__(f"injected delay: {seconds}s")
        self.seconds = seconds


class Garble(FaultError):
    """Directive: the seam corrupts the payload bytes then proceeds —
    garbage on the wire that must be absorbed by validation/scoring, not
    crash the pipeline.  ``mutate(raw) -> bytes`` defaults to a bitwise
    complement of the payload (deterministic, never a no-op)."""

    def __init__(self, mutate: Optional[Callable[[bytes], bytes]] = None):
        super().__init__("injected garble")
        self.mutate = mutate or (lambda raw: bytes(b ^ 0xFF for b in raw))


class FaultPlan:
    """One armed point's failure schedule.

    Exactly one of the schedule knobs is normally set:

    * ``times=N``   — the first N fire() calls fail, the rest pass
    * ``script=[True, False, ...]`` — per-call verdicts, pass when the
      script is exhausted
    * ``every=K``   — calls 0, K, 2K, ... fail (deterministic "rate")

    With no knob set every call fails (fail-always).  ``error`` is a
    zero-arg factory so each raise gets a fresh exception instance.

    ``match`` scopes the plan to a subset of a point's traffic: it is
    called with the seam's context kwargs (``match(**ctx) -> bool``) and
    a non-matching call neither fails nor consumes a schedule index —
    this is how a single armed ``net.transport.write`` plan partitions
    specific peer pairs while the rest of the fabric stays healthy.
    ``match`` runs under the harness lock; keep it cheap and pure.
    """

    def __init__(
        self,
        point: str,
        *,
        times: Optional[int] = None,
        script: Optional[Sequence[bool]] = None,
        every: Optional[int] = None,
        error: Optional[Callable[[], BaseException]] = None,
        match: Optional[Callable[..., bool]] = None,
    ):
        knobs = sum(x is not None for x in (times, script, every))
        if knobs > 1:
            raise ValueError("set at most one of times/script/every")
        self.point = point
        self.times = times
        self.script = list(script) if script is not None else None
        self.every = every
        self.error = error or (lambda: FaultError(f"injected fault: {point}"))
        self.match = match
        self.calls = 0  # total fire() checks seen (match-accepted only)
        self.fired = 0  # checks that raised

    def _should_fail(self, idx: int) -> bool:
        if self.times is not None:
            return idx < self.times
        if self.script is not None:
            return idx < len(self.script) and bool(self.script[idx])
        if self.every is not None:
            return self.every > 0 and idx % self.every == 0
        return True


_lock = threading.Lock()
_ARMED: Dict[str, List[FaultPlan]] = {}


def fire(point: str, **ctx) -> None:
    """Production checkpoint: raise if a test armed ``point`` and its
    schedule says this call fails.  ``ctx`` carries seam context (peer
    ids, topics, method names); plans with a ``match`` predicate only
    see the calls it accepts — the innermost *matching* plan wins."""
    if not _ARMED:  # fast path: nothing armed anywhere in the process
        return
    # Reviewed exception: only reachable with a fault armed (tests), and
    # guards dict/counter reads — microseconds, never held across I/O.
    with _lock:  # lodelint: disable=transitive-blocking
        plans = _ARMED.get(point)
        if not plans:
            return
        plan = None
        for p in reversed(plans):  # innermost matching inject() wins
            if p.match is None or p.match(**ctx):
                plan = p
                break
        if plan is None:
            return
        idx = plan.calls
        plan.calls += 1
        fail = plan._should_fail(idx)
        if fail:
            plan.fired += 1
            err = plan.error()
    if fail:
        raise err


def is_armed(point: str) -> bool:
    with _lock:
        return bool(_ARMED.get(point))


def active() -> List[str]:
    """Names of currently armed points (bench stamps these into its
    JSON so a fault-injected run can never pass as a clean number)."""
    with _lock:
        return sorted(p for p, plans in _ARMED.items() if plans)


@contextmanager
def inject(
    point: str,
    *,
    times: Optional[int] = None,
    script: Optional[Sequence[bool]] = None,
    every: Optional[int] = None,
    error: Optional[Callable[[], BaseException]] = None,
    match: Optional[Callable[..., bool]] = None,
):
    """Arm ``point`` for the duration of the block; yields the plan so
    tests can assert on ``plan.calls`` / ``plan.fired``.  Nested
    injections on the same point stack — the innermost plan whose
    ``match`` accepts the call is the one consulted until its block
    exits."""
    plan = FaultPlan(
        point, times=times, script=script, every=every, error=error, match=match
    )
    with _lock:
        _ARMED.setdefault(point, []).append(plan)
    try:
        yield plan
    finally:
        with _lock:
            plans = _ARMED.get(point, [])
            if plan in plans:
                plans.remove(plan)
            if not plans:
                _ARMED.pop(point, None)


def reset() -> None:
    """Disarm everything (test-suite safety net, not production API)."""
    with _lock:
        _ARMED.clear()
