"""Swarm harness: N in-process beacon nodes on the REAL network pipeline
(ISSUE 15 / ROADMAP 6).

Each node is a full `Network` — real gossip mesh + v1.1 scoring, real
reqresp + GCRA rate limiter, real range sync — attached to a
`MeshFabric` over shared-memory loopback links (network/loopback.py).
Nothing in the stack knows it is under test: chaos arrives exclusively
through the deterministic fault checkpoints (`net.transport.*`,
`net.gossip.*`, `net.reqresp.*`, `sync.range.batch_download`) and
through byzantine node behaviors scripted here.

Determinism rules (docs/SWARM.md):

* **scripted clock** — every chain shares one `FakeTime`; slots advance
  by assignment, never by wall time;
* **manual heartbeats** — mesh maintenance (`heartbeat_fabrics`) and
  peer maintenance (`heartbeat_networks`) run when the test says so;
* **no sleeps-as-synchronization** — convergence is awaited with
  `settle(predicate, ...)`, a bounded poll that fails loudly with the
  predicate's name instead of silently passing after a lucky sleep;
* **deterministic fault schedules** — partitions/storms are
  `faults.inject` plans (times/script/every + `match` over peer ids),
  so a failure sequence replays exactly.

Swarm size: `n` defaults to the `LODESTAR_TPU_SWARM_N` env var (default
4 — small, this is a 2-core CI host; scale it up locally to probe
capacity, ROADMAP 6's nodes×validators metric).
"""
from __future__ import annotations

import asyncio
import os
from typing import Callable, Dict, List, Optional, Sequence, Set

from lodestar_tpu.chain.chain import BeaconChain
from lodestar_tpu.chain.clock import LocalClock
from lodestar_tpu.chain.dev import DevChain
from lodestar_tpu.config import minimal_chain_config as default_cfg
from lodestar_tpu.db import BeaconDb
from lodestar_tpu.network.fabric import MeshFabric
from lodestar_tpu.network.loopback import LoopbackNet
from lodestar_tpu.network.network import Network
from lodestar_tpu.state_transition.util.genesis import init_dev_state
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger

_log = get_logger("swarm")

DEFAULT_N = int(os.environ.get("LODESTAR_TPU_SWARM_N", "4"))


class FakeTime:
    """Scripted monotonic clock shared by every node in the swarm."""

    def __init__(self, t0: float = 0.0):
        self.t = t0

    def __call__(self) -> float:
        return self.t


class _TrustAllVerifier:
    """BLS stub: swarm chaos targets the network/sync fabric, not
    signature math (the BLS fault domain has its own chaos suite)."""

    async def verify_signature_sets(self, sets, opts=None):
        return True


class SwarmNode:
    def __init__(self, idx: int, fabric: MeshFabric, chain: BeaconChain, net: Network):
        self.idx = idx
        self.fabric = fabric
        self.chain = chain
        self.net = net
        self.peer_id = fabric.peer_id

    @property
    def head_slot(self) -> int:
        return self.chain.fork_choice.get_head().slot

    @property
    def head_root(self) -> bytes:
        return self.chain.head_root


class Swarm:
    """N nodes + one DevChain block producer over a loopback fabric."""

    def __init__(self, cfg=default_cfg, validators: int = 8):
        self.cfg = cfg
        self.validators = validators
        self.ft = FakeTime(0.0)
        self.dev = DevChain(cfg, validators, genesis_time=0)
        self.tip_slot = 0  # last slot produced on the dev chain
        self.loopback = LoopbackNet()
        self.nodes: List[SwarmNode] = []
        # the interop genesis state is identical for every node and
        # expensive to rebuild (pure-python BLS pubkey derivation);
        # compute once and hand each node a serialized clone
        _, anchor = init_dev_state(cfg, validators, genesis_time=0)
        self._anchor_type = type(anchor)
        self._anchor_bytes = self._anchor_type.serialize(anchor)

    # -- construction ---------------------------------------------------

    def add_node(
        self,
        request_timeout: float = 1.0,
        rate_quota=None,  # None -> reqresp.DEFAULT_RATE_QUOTA
        metrics=None,
    ) -> SwarmNode:
        idx = len(self.nodes)
        fabric = self.loopback.register(
            MeshFabric(f"swarm-{idx:02d}", request_timeout=request_timeout)
        )
        anchor = self._anchor_type.deserialize(self._anchor_bytes)
        chain = BeaconChain(
            self.cfg,
            BeaconDb(),
            anchor,
            verifier=_TrustAllVerifier(),
            clock=LocalClock(0, self.cfg.SECONDS_PER_SLOT, now=self.ft),
            metrics=metrics,
        )
        net = Network(None, chain, chain.db, endpoint=fabric, rate_quota=rate_quota)
        # swarm chaos uses short reqresp timeouts so stalling-responder
        # scripts resolve in test time, not the production 10 s
        net.reqresp.request_timeout = request_timeout
        node = SwarmNode(idx, fabric, chain, net)
        self.nodes.append(node)
        return node

    @classmethod
    async def create(
        cls,
        n: int = DEFAULT_N,
        validators: int = 8,
        subscribe: bool = True,
        request_timeout: float = 1.0,
        rate_quota=None,  # None -> reqresp.DEFAULT_RATE_QUOTA
    ) -> "Swarm":
        """Build a fully-connected, status-handshaked swarm of n nodes."""
        swarm = cls(validators=validators)
        for _ in range(n):
            swarm.add_node(request_timeout=request_timeout, rate_quota=rate_quota)
        await swarm.connect_full()
        if subscribe:
            for node in swarm.nodes:
                node.net.subscribe_core_topics()
            swarm.heartbeat_fabrics()
            await swarm.drain()
        return swarm

    async def connect_full(self) -> None:
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1 :]:
                await self.connect(a, b)

    async def connect(self, a: SwarmNode, b: SwarmNode) -> None:
        """Link + mutual status handshake (what two production nodes do
        after dial)."""
        await self.loopback.connect(a.fabric, b.fabric)
        await a.net.connect(b.peer_id)
        await b.net.connect(a.peer_id)

    def disconnect(self, a: SwarmNode, b: SwarmNode) -> None:
        self.loopback.disconnect(a.peer_id, b.peer_id)
        a.net.peer_manager.on_disconnect(b.peer_id)
        b.net.peer_manager.on_disconnect(a.peer_id)

    async def attach_blspool(
        self,
        verifier=None,
        metrics=None,
        request_timeout: float = 1.0,
        **server_kwargs,
    ):
        """Attach ONE shared BLS sidecar to the swarm (ISSUE 16): a
        dedicated fabric endpoint on the loopback running a
        ``BlsPoolServer`` over ``verifier`` (default: host oracle), plus
        a ``RemoteBlsVerifier`` per node — stored as ``node.bls_client``
        AND installed as the chain's verifier, so block import verifies
        through the pool.  The caller owns the server's shutdown
        (``await swarm.blspool_server.close()`` before ``close()``)."""
        from lodestar_tpu.blspool import (
            BlsPoolServer,
            FabricPoolTransport,
            RemoteBlsVerifier,
        )

        fabric = self.loopback.register(
            MeshFabric("blspool", request_timeout=request_timeout)
        )
        server = BlsPoolServer(verifier, metrics=metrics, **server_kwargs)
        server.attach(fabric)
        for node in self.nodes:
            await self.loopback.connect(node.fabric, fabric)
            client = RemoteBlsVerifier(
                FabricPoolTransport(node.fabric, fabric.peer_id),
                tenant=node.peer_id,
                metrics=metrics,
            )
            node.bls_client = client
            node.chain.bls = client
        self.blspool_server = server
        self.blspool_fabric = fabric
        return server

    def close(self) -> None:
        for node in self.nodes:
            node.net.close()
        self.loopback.close()

    # -- deterministic drivers ------------------------------------------

    def heartbeat_fabrics(self) -> None:
        """One mesh-maintenance round on every fabric (GRAFT/PRUNE +
        IHAVE digests) — the scripted form of the 1 s heartbeat loop."""
        for node in self.nodes:
            node.fabric._heartbeat_once()

    async def heartbeat_networks(self) -> None:
        """One peer-maintenance round on every Network (score
        disconnects/bans, store pruning, rate-limiter prune, metrics)."""
        for node in self.nodes:
            await node.net.heartbeat()

    async def drain(self, rounds: int = 3) -> None:
        """Let in-flight frame pumps and validation queues run. Bounded:
        each round yields the loop a few times."""
        for _ in range(rounds * 5):
            await asyncio.sleep(0)
        await asyncio.sleep(0.01)

    async def settle(
        self,
        predicate: Callable[[], bool],
        timeout_s: float = 10.0,
        what: str = "condition",
        tick: Optional[Callable[[], None]] = None,
    ) -> None:
        """Await ``predicate()`` with a bounded poll — the harness's
        ONLY wait primitive (no bare sleeps in tests).  ``tick`` (e.g.
        heartbeat_fabrics) runs between polls to drive mesh repair."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        while True:
            if predicate():
                return
            if loop.time() >= deadline:
                raise AssertionError(f"swarm did not settle: {what}")
            if tick is not None:
                tick()
            await asyncio.sleep(0.02)

    # -- block production -----------------------------------------------

    async def advance(
        self,
        n_slots: int,
        publisher: Optional[SwarmNode] = None,
        import_into: Optional[Sequence[SwarmNode]] = None,
    ) -> list:
        """Produce ``n_slots`` blocks on the dev chain.  Each block is
        either imported directly into ``import_into`` nodes (pre-gossip
        seeding) or imported+published by ``publisher`` so the swarm
        receives it over the real mesh."""
        blocks = []
        start = self.tip_slot + 1
        # claim the slot range before the first await so two interleaved
        # advance() calls cannot produce the same slots
        self.tip_slot = start + n_slots - 1
        for slot in range(start, start + n_slots):
            self.ft.t = slot * self.cfg.SECONDS_PER_SLOT
            if slot > 1:
                self.dev.attest(slot - 1)
            block = self.dev.produce_block(slot)
            self.dev.import_block(block, verify_signatures=False)
            targets = import_into if import_into is not None else (
                [publisher] if publisher is not None else []
            )
            for node in targets:
                await node.chain.process_block(block)
            if publisher is not None:
                await publisher.net.publish_block(block)
            blocks.append(block)
        return blocks

    # -- chaos scripting ------------------------------------------------

    def partition(self, *groups: Sequence[SwarmNode]):
        """Context manager: while armed, every wire frame CROSSING the
        given groups is dropped (both directions, deterministically) —
        a clean network partition.  Heal by leaving the block."""
        side: Dict[str, int] = {}
        for gi, group in enumerate(groups):
            for node in group:
                side[node.peer_id] = gi

        def crosses(src=None, dst=None, **_ctx) -> bool:
            return (
                src in side and dst in side and side[src] != side[dst]
            )

        return faults.inject(
            "net.transport.write", error=faults.Drop, match=crosses
        )

    def drop_storm(self, every: int = 2):
        """Context manager: drop every ``every``-th frame fabric-wide —
        a lossy-network storm that degrades throughput but must never
        deadlock the pipeline."""
        return faults.inject(
            "net.transport.write", every=every, error=faults.Drop
        )

    def make_byzantine_block_server(self, node: SwarmNode) -> None:
        """Turn ``node`` into a byzantine batch server: its
        beacon_blocks_by_range handler serves structurally valid blocks
        whose state roots are garbage — they decode fine and fail
        processing, the worst case for a syncing peer."""
        from lodestar_tpu.network.reqresp.protocols import BEACON_BLOCKS_BY_RANGE

        async def evil_blocks_by_range(from_peer, req):
            out = []
            for slot in range(req.start_slot, req.start_slot + req.count * req.step, req.step):
                blk = node.net._block_at_slot(slot)
                if blk is not None:
                    bad = type(blk).deserialize(type(blk).serialize(blk))
                    bad.message.state_root = b"\xde" * 32
                    out.append(bad)
            return out

        node.net.reqresp.register_handler(
            BEACON_BLOCKS_BY_RANGE, evil_blocks_by_range
        )

    # -- views ----------------------------------------------------------

    def heads(self) -> List[bytes]:
        return [node.head_root for node in self.nodes]

    def converged(self, nodes: Optional[Sequence[SwarmNode]] = None) -> bool:
        nodes = list(nodes if nodes is not None else self.nodes)
        return len({node.head_root for node in nodes}) == 1

    def mesh_connected_across(
        self, topic: str, group_a: Sequence[SwarmNode], group_b: Sequence[SwarmNode]
    ) -> bool:
        """True if at least one mesh edge crosses the two groups for
        ``topic`` (the partition-heal mesh re-convergence check)."""
        b_ids: Set[str] = {n.peer_id for n in group_b}
        for node in group_a:
            st = node.fabric._topics.get(topic)
            if st and st.mesh & b_ids:
                return True
        return False
