"""Adversarial execution layer: a deterministic per-method script over
the mock EL (ROADMAP item 5b; docs/FAULTS.md style).

``ElScript`` holds per-method directive queues; each engine call
consumes the next directive for its method stem and an exhausted queue
falls through to the honest ``MockExecutionEngine`` behavior — so a
test scripts *exactly* the adversarial phase it wants (three SYNCING
answers, one INVALID mid-chain, a stalled getPayload at the proposal
deadline) and the EL behaves again afterwards.

Directives are plain dicts; recognized keys:

* ``status``            — answer this ExecutePayloadStatus instead of
  validating (``newPayload`` / ``forkchoiceUpdated``); combine with
  ``latest_valid_hash`` (bytes) and ``validation_error`` (str).
* ``delay_s``           — await this long before answering (slow EL;
  getPayload near the deadline).
* ``error``             — raise instead of answering: an exception
  instance or zero-arg factory (connection refused, EL crash).

``ScriptedExecutionEngine`` is consumed two ways:

* directly as a chain's ``execution_engine`` (in-process chaos tests on
  the real import pipeline), or
* behind ``MockElServer(engine=ScriptedExecutionEngine(...))`` so the
  same script plays out over real HTTP against ``HttpExecutionEngine``
  — statuses ride the JSON-RPC loop, delays stall the socket, and
  raised ``RpcError``s become JSON-RPC error bodies.

Transport-level storms (bare HTTP 500s, the retried shape) are scripted
separately through the ``mock_el.engine`` fault seam in
``mock_el_server.py`` — see docs/FAULTS.md.

Everything is deterministic: queues, not probabilities.
"""
from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from lodestar_tpu.execution.engine import (
    ExecutePayloadStatus,
    ForkchoiceUpdateResult,
    MockExecutionEngine,
    PayloadStatus,
)

# method stems a script can target
NEW_PAYLOAD = "new_payload"
FORKCHOICE = "forkchoice"
GET_PAYLOAD = "get_payload"
_STEMS = (NEW_PAYLOAD, FORKCHOICE, GET_PAYLOAD)


class ElScript:
    """Deterministic per-method adversarial directives (FIFO per stem)."""

    def __init__(self, **per_method):
        unknown = set(per_method) - set(_STEMS)
        if unknown:
            raise ValueError(f"unknown method stem(s): {sorted(unknown)}")
        self._queues: Dict[str, List[dict]] = {
            stem: list(per_method.get(stem, ())) for stem in _STEMS
        }
        self.consumed: Dict[str, List[dict]] = {stem: [] for stem in _STEMS}

    def queue(self, stem: str, *directives: dict) -> "ElScript":
        """Append directives for ``stem``; chainable."""
        if stem not in _STEMS:
            raise ValueError(f"unknown method stem {stem!r}")
        self._queues[stem].extend(directives)
        return self

    def next(self, stem: str) -> Optional[dict]:
        q = self._queues[stem]
        if not q:
            return None
        d = q.pop(0)
        self.consumed[stem].append(d)
        return d

    def pending(self, stem: str) -> int:
        return len(self._queues[stem])


def _scripted_status(d: dict) -> PayloadStatus:
    lvh = d.get("latest_valid_hash")
    return PayloadStatus(
        ExecutePayloadStatus(d["status"]),
        bytes(lvh) if lvh is not None else None,
        d.get("validation_error"),
    )


class ScriptedExecutionEngine(MockExecutionEngine):
    """MockExecutionEngine that answers its ``ElScript`` first.

    Honest behavior (accept everything, build payloads) resumes per
    method once its directive queue drains — the "EL recovers" phase of
    a chaos scenario needs no re-wiring.
    """

    def __init__(self, script: Optional[ElScript] = None):
        super().__init__()
        self.script = script or ElScript()

    async def _apply(self, stem: str) -> Optional[dict]:
        d = self.script.next(stem)
        if d is None:
            return None
        delay = d.get("delay_s")
        if delay:
            await asyncio.sleep(delay)
        err = d.get("error")
        if err is not None:
            raise err() if callable(err) else err
        return d

    async def notify_new_payload(
        self, payload, versioned_hashes=None, parent_beacon_block_root=None
    ) -> PayloadStatus:
        d = await self._apply(NEW_PAYLOAD)
        if d is not None and "status" in d:
            self.notified_payloads += 1
            return _scripted_status(d)
        return await super().notify_new_payload(
            payload, versioned_hashes, parent_beacon_block_root
        )

    async def notify_forkchoice_update(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None, fork=None,
    ) -> ForkchoiceUpdateResult:
        d = await self._apply(FORKCHOICE)
        if d is not None and "status" in d:
            # a non-VALID verdict mints no payloadId (the EL cannot
            # build on a head it does not recognize as valid)
            return ForkchoiceUpdateResult(_scripted_status(d), None)
        return await super().notify_forkchoice_update(
            head_block_hash, safe_block_hash, finalized_block_hash,
            payload_attributes, fork,
        )

    async def get_payload(self, payload_id: bytes):
        await self._apply(GET_PAYLOAD)  # delay / error directives
        return await super().get_payload(payload_id)
