"""HTTP-served mock execution layer (reference: the mergemock /
mock-EL role the reference sim tests drive over real JSON-RPC).

Wraps the in-process doubles — ``MockExecutionEngine`` (engine_* API)
and ``MockEth1Provider`` (eth_* deposit/log API) — behind a real aiohttp
JSON-RPC endpoint with Engine-API JWT verification, so e2e tests
exercise the full serialize→HTTP→deserialize loop the production
clients speak, not the in-memory shortcut.

Version strictness is the point: ``engine_newPayloadV1`` parses a
bellatrix body (withdrawals rejected), V2 capella, V3 eip4844 (blob
versioned hashes + parentBeaconBlockRoot params), and
``engine_getPayloadVn`` refuses to serve a payload of a different
fork (-38005 Unsupported fork) — a client selecting the wrong version
for a fork must fail the test, not silently round-trip.

Also runnable as a second process (mirroring tests/test_cli_node.py)::

    python -m lodestar_tpu.testing.mock_el_server \
        --port 0 --jwt-secret-file jwt.hex --deposits 4

prints ``{"url": ..., "port": ...}`` on stdout once listening.
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import List, Optional

from lodestar_tpu.eth1 import MockEth1Provider
from lodestar_tpu.eth1.http_provider import (
    DEPOSIT_EVENT_TOPIC,
    _abi_encode_bytes_tuple,
)
from lodestar_tpu.execution import serde
from lodestar_tpu.execution.engine import (
    SUPPORTED_ENGINE_METHODS,
    MockExecutionEngine,
)
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger

# Engine API auth spec: iat must be within ±60 s of the EL's clock
JWT_MAX_AGE_S = 60

# JSON-RPC / Engine API error codes
INVALID_REQUEST = -32600
METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
UNKNOWN_PAYLOAD = -38001
UNSUPPORTED_FORK = -38005

_FORK_BY_VERSION = serde.FORK_BY_ENGINE_VERSION


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class MockElServer:
    """One aiohttp JSON-RPC endpoint serving both the engine_* and the
    eth_* namespaces from the shared in-process doubles."""

    def __init__(
        self,
        engine: Optional[MockExecutionEngine] = None,
        eth1: Optional[MockEth1Provider] = None,
        jwt_secret: Optional[bytes] = None,
        deposit_contract: Optional[str] = None,
    ):
        from lodestar_tpu.eth1.http_provider import MAINNET_DEPOSIT_CONTRACT

        self.engine = engine if engine is not None else MockExecutionEngine()
        self.eth1 = eth1 if eth1 is not None else MockEth1Provider()
        self.jwt_secret = jwt_secret
        self.deposit_contract = (deposit_contract or MAINNET_DEPOSIT_CONTRACT).lower()
        self.calls: List[str] = []  # method names, in arrival order
        self.auth_failures: List[str] = []  # rejection reasons, for tests
        # last payload served by getPayload / received by newPayload, so
        # e2e tests can assert byte-identity across the HTTP loop
        self.last_served_payload = None
        self.last_received_payload = None
        self.last_new_payload_extra = None  # (versioned_hashes, parent_root)
        self._log = get_logger("mock-el")
        self._runner = None
        self.url: Optional[str] = None
        self.port: Optional[int] = None

    # -- lifecycle ------------------------------------------------------

    def build_app(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_post("/", self._handle)
        return app

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        from aiohttp import web

        self._runner = web.AppRunner(self.build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self.url = f"http://{host}:{self.port}"
        return self.url

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    # -- auth -----------------------------------------------------------

    def _jwt_rejection(self, request) -> Optional[str]:
        """None when the Bearer JWT verifies; else the rejection reason
        (missing / malformed / bad signature / missing or stale iat)."""
        auth = request.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            return "missing token"
        parts = auth[len("Bearer "):].split(".")
        if len(parts) != 3:
            return "malformed token"
        header_b64, claims_b64, sig_b64 = parts
        expected = _b64url(
            hmac.new(
                self.jwt_secret,
                f"{header_b64}.{claims_b64}".encode(),
                hashlib.sha256,
            ).digest()
        )
        if not hmac.compare_digest(sig_b64, expected):
            return "bad signature"
        try:
            claims = json.loads(_b64url_decode(claims_b64))
        except (ValueError, UnicodeDecodeError):
            return "malformed claims"
        iat = claims.get("iat")
        if not isinstance(iat, (int, float)):
            return "missing iat"
        if abs(time.time() - iat) > JWT_MAX_AGE_S:
            return "stale iat"
        return None

    # -- dispatch -------------------------------------------------------

    async def _handle(self, request):
        from aiohttp import web

        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response(
                _error_body(None, INVALID_REQUEST, "body is not JSON"),
            )
        rpc_id = body.get("id")
        method = body.get("method", "")
        params = body.get("params", [])
        self.calls.append(method)
        if method.startswith("engine_"):
            # adversarial seam (docs/FAULTS.md): an armed fault here
            # escapes the handler → aiohttp answers a bare HTTP 500,
            # the retried-transport-error shape of an EL error storm
            faults.fire("mock_el.engine", method=method)
        if method.startswith("engine_") and self.jwt_secret is not None:
            reason = self._jwt_rejection(request)
            if reason is not None:
                self.auth_failures.append(reason)
                return web.json_response(
                    _error_body(rpc_id, INVALID_REQUEST, f"unauthorized: {reason}"),
                    status=401,
                )
        handler = getattr(self, "_rpc_" + method.replace("_", "__"), None)
        if handler is None:
            return web.json_response(
                _error_body(rpc_id, METHOD_NOT_FOUND, f"unknown method {method}")
            )
        try:
            result = await handler(params)
        except RpcError as e:
            return web.json_response(_error_body(rpc_id, e.code, e.message))
        except (serde.EngineSerdeError, KeyError, ValueError, TypeError) as e:
            return web.json_response(
                _error_body(rpc_id, INVALID_PARAMS, f"{type(e).__name__}: {e}")
            )
        return web.json_response({"jsonrpc": "2.0", "id": rpc_id, "result": result})

    # -- engine namespace ----------------------------------------------

    async def _rpc_engine__exchangeCapabilities(self, params):
        # the client's own list, so mock capabilities can never drift
        # from what HttpExecutionEngine actually issues
        return list(SUPPORTED_ENGINE_METHODS)

    async def _new_payload(self, params, version: int):
        fork = _FORK_BY_VERSION[version]
        payload = serde.payload_from_json(fork, params[0])
        self.last_received_payload = payload
        if version >= 3:
            if len(params) < 3:
                raise RpcError(
                    INVALID_PARAMS,
                    "newPayloadV3 takes (payload, versionedHashes, "
                    "parentBeaconBlockRoot)",
                )
            hashes = [serde.parse_data(h, 32) for h in params[1]]
            parent_root = serde.parse_data(params[2], 32)
            self.last_new_payload_extra = (hashes, parent_root)
        # async dispatch so a scripted adversarial engine can stall or
        # answer SYNCING/INVALID through the same HTTP loop
        status = await self.engine.notify_new_payload(payload)
        return _payload_status_json(status)

    async def _rpc_engine__newPayloadV1(self, params):
        return await self._new_payload(params, 1)

    async def _rpc_engine__newPayloadV2(self, params):
        return await self._new_payload(params, 2)

    async def _rpc_engine__newPayloadV3(self, params):
        return await self._new_payload(params, 3)

    async def _forkchoice_updated(self, params, version: int):
        fc = params[0]
        attrs_json = params[1] if len(params) > 1 else None
        head = serde.parse_data(fc["headBlockHash"], 32)
        safe = serde.parse_data(fc["safeBlockHash"], 32)
        finalized = serde.parse_data(fc["finalizedBlockHash"], 32)
        attrs = (
            serde.payload_attributes_from_json(attrs_json, version)
            if attrs_json is not None
            else None
        )
        res = await self.engine.notify_forkchoice_update(head, safe, finalized, attrs)
        pid = res.payload_id
        return {
            "payloadStatus": _payload_status_json(res.status),
            "payloadId": serde.data(pid) if pid is not None else None,
        }

    async def _rpc_engine__forkchoiceUpdatedV1(self, params):
        return await self._forkchoice_updated(params, 1)

    async def _rpc_engine__forkchoiceUpdatedV2(self, params):
        return await self._forkchoice_updated(params, 2)

    async def _rpc_engine__forkchoiceUpdatedV3(self, params):
        return await self._forkchoice_updated(params, 3)

    async def _get_payload(self, params, version: int):
        pid = serde.parse_data(params[0], 8)
        try:
            payload = await self.engine.get_payload(pid)
        except ValueError as e:
            raise RpcError(UNKNOWN_PAYLOAD, str(e)) from None
        built_version = serde.engine_version_for_fork(
            serde.fork_of_payload(payload)
        )
        if built_version != version:
            raise RpcError(
                UNSUPPORTED_FORK,
                f"payload is a V{built_version} structure, asked via V{version}",
            )
        self.last_served_payload = payload
        body = serde.payload_to_json(payload)
        if version == 1:
            return body
        result = {"executionPayload": body, "blockValue": "0x0"}
        if version >= 3:
            result["blobsBundle"] = {"commitments": [], "proofs": [], "blobs": []}
        return result

    async def _rpc_engine__getPayloadV1(self, params):
        return await self._get_payload(params, 1)

    async def _rpc_engine__getPayloadV2(self, params):
        return await self._get_payload(params, 2)

    async def _rpc_engine__getPayloadV3(self, params):
        return await self._get_payload(params, 3)

    # -- eth namespace (deposit tracking) -------------------------------

    async def _rpc_eth__blockNumber(self, params):
        return hex(await self.eth1.get_block_number())

    async def _rpc_eth__getBlockByNumber(self, params):
        tag = params[0]
        if tag == "latest":
            number = await self.eth1.get_block_number()
        else:
            number = int(tag, 16)
        blk = await self.eth1.get_block(number)
        if blk is None:
            return None
        return {
            "number": hex(blk.number),
            "hash": "0x" + bytes(blk.hash).hex(),
            "timestamp": hex(blk.timestamp),
        }

    async def _rpc_eth__getLogs(self, params):
        flt = params[0]
        address = str(flt.get("address", "")).lower()
        if address and address != self.deposit_contract:
            return []
        topics = flt.get("topics") or []
        if topics and topics[0] != DEPOSIT_EVENT_TOPIC:
            return []
        frm = int(flt["fromBlock"], 16)
        to = int(flt["toBlock"], 16)
        logs = []
        for ev in await self.eth1.get_deposit_events(frm, to):
            dd = ev.deposit_data
            data = _abi_encode_bytes_tuple(
                [
                    bytes(dd.pubkey),
                    bytes(dd.withdrawal_credentials),
                    int(dd.amount).to_bytes(8, "little"),
                    bytes(dd.signature),
                    int(ev.index).to_bytes(8, "little"),
                ]
            )
            logs.append(
                {
                    "address": self.deposit_contract,
                    "topics": [DEPOSIT_EVENT_TOPIC],
                    "data": "0x" + data.hex(),
                    "blockNumber": hex(ev.block_number),
                    "logIndex": hex(ev.index),
                    "removed": False,
                }
            )
        return logs


class RpcError(Exception):
    """Handler-raised JSON-RPC error (code + message)."""

    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


def _error_body(rpc_id, code: int, message: str) -> dict:
    return {
        "jsonrpc": "2.0",
        "id": rpc_id,
        "error": {"code": code, "message": message},
    }


def _payload_status_json(status) -> dict:
    lvh = status.latest_valid_hash
    return {
        "status": str(getattr(status.status, "value", status.status)),
        "latestValidHash": serde.data(lvh) if lvh is not None else None,
        "validationError": status.validation_error,
    }


def scripted_deposit_data(index: int):
    """Deterministic DepositData for second-process scripts — structural
    only (no real BLS signature; the tracker never verifies them)."""
    from lodestar_tpu.types import ssz

    return ssz.phase0.DepositData(
        pubkey=bytes([0xD0 + (index % 16)]) * 48,
        withdrawal_credentials=index.to_bytes(4, "big").rjust(32, b"\x00"),
        amount=32_000_000_000,
        signature=bytes([index % 256]) * 96,
    )


def main(argv=None) -> int:
    """Second-process entry: serve until killed, announcing the bound
    port as a JSON line on stdout (tests/test_cli_node.py idiom)."""
    import argparse
    import asyncio

    parser = argparse.ArgumentParser(prog="mock-el-server")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--jwt-secret-file", default=None)
    parser.add_argument(
        "--deposits", type=int, default=0,
        help="script N deterministic deposits into the eth1 chain",
    )
    parser.add_argument(
        "--blocks", type=int, default=8,
        help="extra eth1 blocks appended after the scripted deposits",
    )
    args = parser.parse_args(argv)

    jwt_secret = None
    if args.jwt_secret_file:
        with open(args.jwt_secret_file) as f:
            jwt_secret = bytes.fromhex(f.read().strip().removeprefix("0x"))

    eth1 = MockEth1Provider()
    for i in range(args.deposits):
        eth1.add_deposit(scripted_deposit_data(i))
    eth1.add_blocks(args.blocks)
    server = MockElServer(eth1=eth1, jwt_secret=jwt_secret)

    async def run():
        url = await server.start(args.host, args.port)
        print(json.dumps({"url": url, "port": server.port}), flush=True)
        try:
            while True:
                await asyncio.sleep(3600)
        finally:
            await server.close()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
