"""Test-support code importable from production seams.

Only the fault-injection harness lives here (``testing.faults``): the
production modules call its zero-cost ``fire()`` checkpoints so chaos
tests can arm deterministic failures without monkeypatching internals.
Nothing in this package may import jax or heavy dependencies — a
``fire()`` call sits on the BLS hot path.
"""
