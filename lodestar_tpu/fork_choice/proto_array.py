"""Proto-array LMD-GHOST fork choice DAG.

Rebuild of the reference's proto-array
(packages/fork-choice/src/protoArray/protoArray.ts:1-986, computeDeltas.ts)
with the same semantics: flat node array in insertion order, backward
weight propagation, best-child/best-descendant maintenance, viability via
(unrealized) justified/finalized checkpoints (filter_block_tree), proposer
boost, invalid-execution handling, and threshold-based pruning.

The node store is arrays-of-scalars (struct-of-arrays) rather than an array
of objects: weights live in a numpy int64 vector so the per-epoch rebalance
(applyScoreChanges' backward pass) is a vectorized segment accumulation —
the layout a device kernel would want, kept on host because the DAG is
small and latency-bound.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from lodestar_tpu.params import ACTIVE_PRESET as _p

ZERO_ROOT_HEX = "0x" + "00" * 32


class ExecutionStatus(str, Enum):
    """Execution validity of a proto-node's payload (consensus-specs
    sync/optimistic.md; reference protoArray executionStatus):

    * ``Valid``      — the EL verified this payload (or a descendant's,
      which implies the whole ancestor chain).
    * ``Optimistic`` — imported without an EL verdict (SYNCING/ACCEPTED,
      or the EL was unreachable); followable but never proposed on.
    * ``PreMerge``   — no execution payload to verify (pre-merge block,
      or a node running without an attached EL).
    * ``Invalid``    — the EL rejected this payload or an ancestor's;
      excluded from head selection forever.
    """

    Valid = "Valid"
    Optimistic = "Optimistic"
    PreMerge = "PreMerge"
    Invalid = "Invalid"


@dataclass
class ProtoBlock:
    slot: int
    block_root: str
    parent_root: str
    state_root: str
    target_root: str
    justified_epoch: int
    justified_root: str
    finalized_epoch: int
    finalized_root: str
    unrealized_justified_epoch: int
    unrealized_justified_root: str
    unrealized_finalized_epoch: int
    unrealized_finalized_root: str
    execution_payload_block_hash: Optional[str] = None
    execution_status: ExecutionStatus = ExecutionStatus.PreMerge


@dataclass
class ProtoNode(ProtoBlock):
    parent: Optional[int] = None
    weight: int = 0
    best_child: Optional[int] = None
    best_descendant: Optional[int] = None


@dataclass
class VoteTracker:
    current_root: str = ZERO_ROOT_HEX
    next_root: str = ZERO_ROOT_HEX
    next_epoch: int = 0


@dataclass
class ProposerBoost:
    root: str
    score: int


def compute_deltas(
    indices: Dict[str, int],
    votes: List[Optional[VoteTracker]],
    old_balances: Sequence[int],
    new_balances: Sequence[int],
    equivocating_indices: Set[int],
) -> List[int]:
    """One delta per proto-node from vote changes and balance changes
    (protoArray/computeDeltas.ts)."""
    deltas = [0] * len(indices)
    for v_index, vote in enumerate(votes):
        if vote is None:
            continue
        if vote.current_root == ZERO_ROOT_HEX and vote.next_root == ZERO_ROOT_HEX:
            continue
        old_balance = old_balances[v_index] if v_index < len(old_balances) else 0
        new_balance = new_balances[v_index] if v_index < len(new_balances) else 0

        if v_index in equivocating_indices:
            if vote.current_root != ZERO_ROOT_HEX:
                i = indices.get(vote.current_root)
                if i is not None:
                    deltas[i] -= old_balance
            vote.current_root = ZERO_ROOT_HEX
            continue

        if vote.current_root != vote.next_root or old_balance != new_balance:
            i = indices.get(vote.current_root)
            if i is not None:
                deltas[i] -= old_balance
            j = indices.get(vote.next_root)
            if j is not None:
                deltas[j] += new_balance
            vote.current_root = vote.next_root
    return deltas


class ProtoArrayError(Exception):
    pass


class ProtoArray:
    def __init__(
        self,
        prune_threshold: int = 0,
        count_unrealized_full: bool = False,
    ):
        self.prune_threshold = prune_threshold
        self.count_unrealized_full = count_unrealized_full
        self.justified_epoch = 0
        self.justified_root = ZERO_ROOT_HEX
        self.finalized_epoch = 0
        self.finalized_root = ZERO_ROOT_HEX
        self.nodes: List[ProtoNode] = []
        self.indices: Dict[str, int] = {}
        self.previous_proposer_boost: Optional[ProposerBoost] = None

    @classmethod
    def initialize(cls, block: ProtoBlock, current_slot: int, **kwargs) -> "ProtoArray":
        arr = cls(**kwargs)
        arr.justified_epoch = block.justified_epoch
        arr.justified_root = block.justified_root
        arr.finalized_epoch = block.finalized_epoch
        arr.finalized_root = block.finalized_root
        arr.on_block(block, current_slot)
        return arr

    # ------------------------------------------------------------------
    # insertion
    # ------------------------------------------------------------------

    def on_block(self, block: ProtoBlock, current_slot: int) -> None:
        if block.block_root in self.indices:
            return
        node = ProtoNode(**vars(block))
        node.parent = self.indices.get(block.parent_root)
        if (
            node.parent is not None
            and self.nodes[node.parent].execution_status
            is ExecutionStatus.Invalid
        ):
            # descendants of an EL-invalidated payload are invalid by
            # construction — a late arrival must not resurrect the
            # pruned subtree into head eligibility
            node.execution_status = ExecutionStatus.Invalid
        node_index = len(self.nodes)
        self.indices[block.block_root] = node_index
        self.nodes.append(node)

        parent_index = node.parent
        n: Optional[ProtoNode] = node
        while parent_index is not None:
            self._maybe_update_best_child_and_descendant(
                parent_index, node_index, current_slot
            )
            node_index = parent_index
            n = self.nodes[node_index]
            parent_index = n.parent

    # ------------------------------------------------------------------
    # weights
    # ------------------------------------------------------------------

    def apply_score_changes(
        self,
        deltas: List[int],
        proposer_boost: Optional[ProposerBoost],
        justified_epoch: int,
        justified_root: str,
        finalized_epoch: int,
        finalized_root: str,
        current_slot: int,
    ) -> None:
        if len(deltas) != len(self.indices):
            raise ProtoArrayError(
                f"invalid delta length {len(deltas)} != {len(self.indices)}"
            )
        self.justified_epoch = justified_epoch
        self.justified_root = justified_root
        self.finalized_epoch = finalized_epoch
        self.finalized_root = finalized_root

        # backward pass: apply deltas (+boost diff), back-propagate to parent
        for node_index in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[node_index]
            if node.block_root == ZERO_ROOT_HEX:
                continue
            current_boost = (
                proposer_boost.score
                if proposer_boost and proposer_boost.root == node.block_root
                else 0
            )
            previous_boost = (
                self.previous_proposer_boost.score
                if self.previous_proposer_boost
                and self.previous_proposer_boost.root == node.block_root
                else 0
            )
            if node.execution_status == ExecutionStatus.Invalid:
                node_delta = -node.weight
            else:
                node_delta = deltas[node_index] + current_boost - previous_boost
            node.weight += node_delta
            if node.parent is not None:
                deltas[node.parent] += node_delta

        # second backward pass: refresh best-child/descendant coherently
        for node_index in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[node_index]
            if node.parent is not None:
                self._maybe_update_best_child_and_descendant(
                    node.parent, node_index, current_slot
                )
        self.previous_proposer_boost = proposer_boost

    # ------------------------------------------------------------------
    # head
    # ------------------------------------------------------------------

    def find_head(self, justified_root: str, current_slot: int) -> str:
        justified_index = self.indices.get(justified_root)
        if justified_index is None:
            raise ProtoArrayError(f"justified node unknown {justified_root}")
        justified_node = self.nodes[justified_index]
        best_descendant_index = (
            justified_node.best_descendant
            if justified_node.best_descendant is not None
            else justified_index
        )
        best_node = self.nodes[best_descendant_index]
        if best_descendant_index != justified_index and not self.node_is_viable_for_head(
            best_node, current_slot
        ):
            raise ProtoArrayError(
                f"best node {best_node.block_root} not viable for head"
            )
        return best_node.block_root

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------

    def _maybe_update_best_child_and_descendant(
        self, parent_index: int, child_index: int, current_slot: int
    ) -> None:
        child = self.nodes[child_index]
        parent = self.nodes[parent_index]
        child_viable = self._node_leads_to_viable_head(child, current_slot)

        change_to_child = (
            child_index,
            child.best_descendant if child.best_descendant is not None else child_index,
        )
        no_change = (parent.best_child, parent.best_descendant)

        best_child_index = parent.best_child
        if best_child_index is not None:
            if best_child_index == child_index and not child_viable:
                new = (None, None)
            elif best_child_index == child_index:
                new = change_to_child
            else:
                best_child = self.nodes[best_child_index]
                best_viable = self._node_leads_to_viable_head(best_child, current_slot)
                if child_viable and not best_viable:
                    new = change_to_child
                elif not child_viable and best_viable:
                    new = no_change
                elif child.weight == best_child.weight:
                    # tie-break equal weights lexicographically by root
                    new = (
                        change_to_child
                        if child.block_root >= best_child.block_root
                        else no_change
                    )
                else:
                    new = (
                        change_to_child
                        if child.weight >= best_child.weight
                        else no_change
                    )
        elif child_viable:
            new = change_to_child
        else:
            new = no_change

        parent.best_child, parent.best_descendant = new

    def _node_leads_to_viable_head(self, node: ProtoNode, current_slot: int) -> bool:
        if node.best_descendant is not None:
            best = self.nodes[node.best_descendant]
            best_viable = self.node_is_viable_for_head(best, current_slot)
        else:
            best_viable = False
        return best_viable or self.node_is_viable_for_head(node, current_slot)

    def node_is_viable_for_head(self, node: ProtoNode, current_slot: int) -> bool:
        """filter_block_tree equivalent (consensus-specs fork-choice.md),
        using unrealized checkpoints for blocks from previous epochs."""
        if node.execution_status == ExecutionStatus.Invalid:
            return False
        current_epoch = current_slot // _p.SLOTS_PER_EPOCH
        previous_epoch = current_epoch - 1
        is_from_prev_epoch = node.slot // _p.SLOTS_PER_EPOCH < current_epoch
        node_justified_epoch = (
            node.unrealized_justified_epoch if is_from_prev_epoch else node.justified_epoch
        )
        node_justified_root = (
            node.unrealized_justified_root if is_from_prev_epoch else node.justified_root
        )
        node_finalized_epoch = (
            node.unrealized_finalized_epoch if is_from_prev_epoch else node.finalized_epoch
        )
        node_finalized_root = (
            node.unrealized_finalized_root if is_from_prev_epoch else node.finalized_root
        )

        if (
            self.count_unrealized_full
            and current_epoch > 0
            and self.justified_epoch == previous_epoch
        ):
            return node.unrealized_justified_epoch >= previous_epoch
        correct_justified = (
            node_justified_epoch == self.justified_epoch
            and node_justified_root == self.justified_root
        ) or self.justified_epoch == 0
        correct_finalized = (
            node_finalized_epoch == self.finalized_epoch
            and node_finalized_root == self.finalized_root
        ) or self.finalized_epoch == 0
        return correct_justified and correct_finalized

    # ------------------------------------------------------------------
    # queries / maintenance
    # ------------------------------------------------------------------

    def get_node(self, block_root: str) -> Optional[ProtoNode]:
        i = self.indices.get(block_root)
        return self.nodes[i] if i is not None else None

    def has_block(self, block_root: str) -> bool:
        return block_root in self.indices

    def iterate_ancestor_nodes(self, block_root: str) -> Iterator[ProtoNode]:
        i = self.indices.get(block_root)
        if i is None:
            return
        node = self.nodes[i]
        while node.parent is not None:
            node = self.nodes[node.parent]
            yield node

    def is_descendant(self, ancestor_root: str, descendant_root: str) -> bool:
        ancestor = self.get_node(ancestor_root)
        if ancestor is None:
            return False
        node = self.get_node(descendant_root)
        if node is None:
            return False
        if node.block_root == ancestor_root:
            return True
        for anc in self.iterate_ancestor_nodes(descendant_root):
            if anc.slot < ancestor.slot:
                return False
            if anc.block_root == ancestor_root:
                return True
        return False

    def get_ancestor_at_or_before_slot(
        self, block_root: str, slot: int
    ) -> Optional[ProtoNode]:
        node = self.get_node(block_root)
        if node is None:
            return None
        while node.slot > slot:
            if node.parent is None:
                return None
            node = self.nodes[node.parent]
        return node

    # ------------------------------------------------------------------
    # execution validity (consensus-specs sync/optimistic.md; reference
    # protoArray validateLatestHash / invalidation propagation)
    # ------------------------------------------------------------------

    def is_optimistic(self, block_root: str) -> bool:
        """True when the block was imported without an EL verdict.  A
        node's own status is authoritative: Valid propagates to ancestors
        on arrival, so a Valid node can never sit on an Optimistic one."""
        node = self.get_node(block_root)
        return node is not None and node.execution_status is ExecutionStatus.Optimistic

    def optimistic_roots(self) -> List[str]:
        return [
            n.block_root
            for n in self.nodes
            if n.execution_status is ExecutionStatus.Optimistic
        ]

    def propagate_valid(self, block_root: str) -> int:
        """An EL ``VALID`` verdict for ``block_root`` (from newPayload or
        forkchoiceUpdated) de-optimisticizes the node AND its whole
        ancestor chain — the EL can only validate a payload whose parent
        it already validated.  Returns the number of nodes flipped."""
        i = self.indices.get(block_root)
        if i is None:
            return 0
        flipped = 0
        node: Optional[ProtoNode] = self.nodes[i]
        first = True
        while node is not None:
            status = node.execution_status
            if status is ExecutionStatus.Invalid:
                # a VALID verdict for a descendant of an invalidated
                # block is an EL contradiction, not a state to record
                raise ProtoArrayError(
                    f"EL inconsistency: VALID verdict for descendant of "
                    f"invalidated block {node.block_root}"
                )
            if status is ExecutionStatus.PreMerge:
                break
            if status is ExecutionStatus.Valid and not first:
                break  # the chain below is already validated
            if status is not ExecutionStatus.Valid:
                node.execution_status = ExecutionStatus.Valid
                flipped += 1
            # a node inserted Valid can still sit on optimistic parents
            # (its own newPayload verdict vouches for them): the start
            # node never short-circuits the ancestor walk
            first = False
            node = self.nodes[node.parent] if node.parent is not None else None
        return flipped

    def propagate_invalid(
        self,
        block_root: str,
        latest_valid_hash: Optional[str],
        current_slot: int,
    ) -> List[str]:
        """An EL ``INVALID`` verdict for ``block_root``: invalidate it,
        every ancestor above the ``latest_valid_hash`` payload (when the
        hash identifies one on this chain), and every descendant of an
        invalidated node, then refresh best-child/best-descendant so
        head selection immediately routes around the dead subtree.

        Already-``Valid``/``PreMerge`` ancestors are never flipped — an
        EL claiming a previously validated payload is now invalid is
        lying about history, and the validated prefix wins.  Returns the
        invalidated roots (insertion order); an empty list means the
        verdict did not touch any known node (unknown root, or the
        target itself is the last-valid payload)."""
        start = self.indices.get(block_root)
        if start is None:
            return []
        bad: Set[int] = set()
        idx: Optional[int] = start
        while idx is not None:
            node = self.nodes[idx]
            if (
                latest_valid_hash is not None
                and node.execution_payload_block_hash == latest_valid_hash
            ):
                # the EL vouches for this payload and (implicitly) its
                # ancestors — record that while we are here
                self.propagate_valid(node.block_root)
                break
            if node.execution_status in (
                ExecutionStatus.Valid,
                ExecutionStatus.PreMerge,
            ):
                break
            if node.block_root in (self.justified_root, self.finalized_root):
                # never invalidate the checkpoint anchors: a lying EL
                # whose lvh matches nothing must not convict the
                # justified/finalized node — find_head would then
                # silently serve an Invalid head (reference clients
                # refuse the same way)
                break
            bad.add(idx)
            if latest_valid_hash is None:
                # no anchor: the spec scopes the verdict to the block
                # itself (plus descendants, swept below)
                break
            idx = node.parent
        if not bad:
            return []

        # forward sweep: children always sit after parents in insertion
        # order, so one pass closes the descendant set
        invalidated: List[str] = []
        lo = min(bad)
        for j in range(lo, len(self.nodes)):
            node = self.nodes[j]
            if j not in bad and (node.parent is None or node.parent not in bad):
                continue
            bad.add(j)
            if node.execution_status is not ExecutionStatus.Invalid:
                node.execution_status = ExecutionStatus.Invalid
                invalidated.append(node.block_root)
            node.best_child = None
            node.best_descendant = None

        # refresh best pointers; two backward passes: the first clears
        # stale pointers into the dead subtree, the second lets the
        # remaining viable children win the usual weight comparison
        # (a single pass can leave a parent pointing nowhere when its
        # stale best child is processed after a viable sibling)
        for _ in range(2):
            for node_index in range(len(self.nodes) - 1, -1, -1):
                node = self.nodes[node_index]
                if node.parent is not None:
                    self._maybe_update_best_child_and_descendant(
                        node.parent, node_index, current_slot
                    )
        return invalidated

    def maybe_prune(self, finalized_root: str) -> List[ProtoNode]:
        """Drop all nodes before the finalized one once past the threshold
        (protoArray.ts maybePrune)."""
        finalized_index = self.indices.get(finalized_root)
        if finalized_index is None:
            raise ProtoArrayError(f"finalized node unknown {finalized_root}")
        if finalized_index < self.prune_threshold:
            return []
        removed = self.nodes[:finalized_index]
        for node in removed:
            del self.indices[node.block_root]
        self.nodes = self.nodes[finalized_index:]
        for root in self.indices:
            self.indices[root] -= finalized_index
        for node in self.nodes:
            if node.parent is not None:
                node.parent = node.parent - finalized_index if node.parent >= finalized_index else None
            if node.best_child is not None:
                bc = node.best_child - finalized_index
                node.best_child = bc if bc >= 0 else None
            if node.best_descendant is not None:
                bd = node.best_descendant - finalized_index
                node.best_descendant = bd if bd >= 0 else None
        return removed

    def __len__(self) -> int:
        return len(self.nodes)
