from .proto_array import (  # noqa: F401
    ExecutionStatus,
    ProposerBoost,
    ProtoArray,
    ProtoArrayError,
    ProtoBlock,
    ProtoNode,
    VoteTracker,
    ZERO_ROOT_HEX,
    compute_deltas,
)
from .fork_choice import (  # noqa: F401
    CheckpointHex,
    ForkChoice,
    ForkChoiceError,
    ForkChoiceStore,
    compute_proposer_boost_score,
)
