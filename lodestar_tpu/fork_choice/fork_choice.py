"""LMD-GHOST + Casper FFG fork choice over the proto-array.

Rebuild of packages/fork-choice/src/forkChoice/forkChoice.ts:66 — vote
tracking, checkpoint management (incl. unrealized pull-up), proposer boost,
equivocation handling, and head computation.  Time must be advanced with
``update_time`` every slot like the reference (forkChoice.ts:64).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set

from lodestar_tpu.params import ACTIVE_PRESET as _p, INTERVALS_PER_SLOT
from .proto_array import (
    ProposerBoost,
    ProtoArray,
    ProtoBlock,
    ProtoNode,
    VoteTracker,
    ZERO_ROOT_HEX,
    compute_deltas,
)


@dataclass(frozen=True)
class CheckpointHex:
    epoch: int
    root: str


@dataclass
class ForkChoiceStore:
    """The subset of the spec's Store the fork choice needs
    (forkChoice/store.ts), balances by effective-balance increment."""

    current_slot: int
    justified: CheckpointHex
    justified_balances: Sequence[int]
    finalized: CheckpointHex
    unrealized_justified: CheckpointHex
    unrealized_finalized: CheckpointHex
    equivocating_indices: Set[int] = field(default_factory=set)


class ForkChoiceError(Exception):
    pass


def compute_proposer_boost_score(
    justified_balances: Sequence[int], proposer_score_boost: int
) -> int:
    total = 0
    active = 0
    for b in justified_balances:
        if b > 0:
            active += 1
            total += b
    if active == 0:
        return 0
    avg = total // active
    committee_size = active // _p.SLOTS_PER_EPOCH
    return committee_size * avg * proposer_score_boost // 100


class ForkChoice:
    def __init__(
        self,
        cfg,
        store: ForkChoiceStore,
        proto_array: ProtoArray,
        proposer_boost_enabled: bool = True,
        justified_balances_getter: Optional[
            Callable[[CheckpointHex], Optional[Sequence[int]]]
        ] = None,
    ):
        self.cfg = cfg
        self.store = store
        self.proto_array = proto_array
        # Invoked on EVERY justified-checkpoint change (incl. the on-tick
        # epoch-boundary pull-up, which has no post-state in hand) so LMD
        # weights/proposer boost always use the justified state's balances
        # (reference recomputes via justifiedBalancesGetter on each change).
        self._justified_balances_getter = justified_balances_getter
        self.votes: List[Optional[VoteTracker]] = []
        self.proposer_boost_root: Optional[str] = None
        self.proposer_boost_enabled = proposer_boost_enabled
        self._justified_proposer_boost_score: Optional[int] = None
        # balances the current proto-array weights were computed with —
        # compute_deltas needs (old, new) to rebalance on justified change
        self._applied_balances: Sequence[int] = store.justified_balances
        self.head: Optional[ProtoNode] = None

    # ------------------------------------------------------------------
    # head
    # ------------------------------------------------------------------

    def update_head(self) -> ProtoNode:
        balances = self.store.justified_balances
        deltas = compute_deltas(
            self.proto_array.indices,
            self.votes,
            self._applied_balances,
            balances,
            self.store.equivocating_indices,
        )
        self._applied_balances = balances
        boost = None
        if self.proposer_boost_enabled and self.proposer_boost_root:
            if self._justified_proposer_boost_score is None:
                self._justified_proposer_boost_score = compute_proposer_boost_score(
                    balances, self.cfg.PROPOSER_SCORE_BOOST
                )
            boost = ProposerBoost(
                self.proposer_boost_root, self._justified_proposer_boost_score
            )
        self.proto_array.apply_score_changes(
            deltas,
            boost,
            self.store.justified.epoch,
            self.store.justified.root,
            self.store.finalized.epoch,
            self.store.finalized.root,
            self.store.current_slot,
        )
        head_root = self.proto_array.find_head(
            self.store.justified.root, self.store.current_slot
        )
        node = self.proto_array.get_node(head_root)
        if node is None:
            raise ForkChoiceError(f"missing head node {head_root}")
        self.head = node
        return node

    def get_head(self) -> ProtoNode:
        return self.head if self.head is not None else self.update_head()

    # ------------------------------------------------------------------
    # events
    # ------------------------------------------------------------------

    def on_block(
        self,
        block: ProtoBlock,
        block_delay_sec: float,
        justified_checkpoint: CheckpointHex,
        finalized_checkpoint: CheckpointHex,
        justified_balances: Optional[Sequence[int]] = None,
    ) -> None:
        """Register a (fully verified) block.  Checkpoint updates follow
        forkChoice.ts:389-458: realized from the post-state, unrealized
        pulled up for timely epochs."""
        if not self.proto_array.has_block(block.parent_root):
            raise ForkChoiceError(f"unknown parent {block.parent_root}")

        # proposer boost: first block of the slot arriving timely
        if (
            self.proposer_boost_enabled
            and block.slot == self.store.current_slot
            and block_delay_sec < self.cfg.SECONDS_PER_SLOT / INTERVALS_PER_SLOT
            and self.proposer_boost_root is None
        ):
            self.proposer_boost_root = block.block_root

        self._update_checkpoints(
            justified_checkpoint, finalized_checkpoint, justified_balances
        )

        # Track the highest unrealized checkpoints for the epoch-boundary
        # pull-up (spec on_tick / reference forkChoice.ts:450): they are
        # applied to the realized store either now — iff the block is from
        # a PRIOR epoch — or at the next epoch transition in update_time().
        unrealized_j = CheckpointHex(
            block.unrealized_justified_epoch, block.unrealized_justified_root
        )
        unrealized_f = CheckpointHex(
            block.unrealized_finalized_epoch, block.unrealized_finalized_root
        )
        if unrealized_j.epoch > self.store.unrealized_justified.epoch:
            self.store.unrealized_justified = unrealized_j
        if unrealized_f.epoch > self.store.unrealized_finalized.epoch:
            self.store.unrealized_finalized = unrealized_f
        block_epoch = block.slot // _p.SLOTS_PER_EPOCH
        current_epoch = self.store.current_slot // _p.SLOTS_PER_EPOCH
        if block_epoch < current_epoch:
            self._update_checkpoints(unrealized_j, unrealized_f, justified_balances)

        self.proto_array.on_block(block, self.store.current_slot)
        self.head = None

    def on_attestation(
        self,
        validator_indices: Sequence[int],
        block_root: str,
        target_epoch: int,
    ) -> None:
        """Record LMD votes (forkChoice.ts:505 onAttestation after
        validation; the caller has already validated the attestation)."""
        for v in validator_indices:
            if v in self.store.equivocating_indices:
                continue
            while len(self.votes) <= v:
                self.votes.append(None)
            vote = self.votes[v]
            if vote is None:
                self.votes[v] = VoteTracker(
                    current_root=ZERO_ROOT_HEX,
                    next_root=block_root,
                    next_epoch=target_epoch,
                )
            elif target_epoch > vote.next_epoch:
                vote.next_root = block_root
                vote.next_epoch = target_epoch
        self.head = None

    def on_attester_slashing(self, attester_indices_1, attester_indices_2) -> None:
        inter = set(attester_indices_1) & set(attester_indices_2)
        self.store.equivocating_indices.update(inter)
        self.head = None

    def update_time(self, current_slot: int) -> None:
        """Per-slot tick: reset proposer boost; at epoch boundaries pull
        unrealized checkpoints into the realized store (spec on_tick).

        Large gaps (cold start against an old anchor) fast-forward in one
        step: repeated boundary pull-ups with unchanged unrealized
        checkpoints are idempotent, so crossing N boundaries at once
        applies the same single update."""
        if current_slot - self.store.current_slot > _p.SLOTS_PER_EPOCH:
            boundary = (current_slot // _p.SLOTS_PER_EPOCH) * _p.SLOTS_PER_EPOCH
            self.store.current_slot = max(self.store.current_slot, boundary)
            self.proposer_boost_root = None
            self._update_checkpoints(
                self.store.unrealized_justified, self.store.unrealized_finalized, None
            )
        while self.store.current_slot < current_slot:
            self.store.current_slot += 1
            self.proposer_boost_root = None
            if self.store.current_slot % _p.SLOTS_PER_EPOCH == 0:
                self._update_checkpoints(
                    self.store.unrealized_justified,
                    self.store.unrealized_finalized,
                    None,
                )
        self.head = None

    def on_valid_execution(self, block_root: str) -> int:
        """EL verdict VALID for ``block_root`` (newPayload or
        forkchoiceUpdated): de-optimisticize it and its ancestor chain."""
        flipped = self.proto_array.propagate_valid(block_root)
        if flipped:
            self.head = None
        return flipped

    def on_invalid_execution(
        self, block_root: str, latest_valid_hash: Optional[str] = None
    ) -> List[str]:
        """EL verdict INVALID for ``block_root``: invalidate the subtree
        above ``latest_valid_hash`` (sync/optimistic.md semantics) and
        force the next head computation to route around it."""
        invalidated = self.proto_array.propagate_invalid(
            block_root, latest_valid_hash, self.store.current_slot
        )
        if invalidated:
            self.head = None
        return invalidated

    def is_optimistic(self, block_root: str) -> bool:
        return self.proto_array.is_optimistic(block_root)

    def optimistic_roots(self) -> List[str]:
        return self.proto_array.optimistic_roots()

    def prune(self, finalized_root: str) -> List[ProtoNode]:
        return self.proto_array.maybe_prune(finalized_root)

    # ------------------------------------------------------------------

    def _update_checkpoints(
        self,
        justified: CheckpointHex,
        finalized: CheckpointHex,
        justified_balances: Optional[Sequence[int]],
    ) -> None:
        if justified.epoch > self.store.justified.epoch:
            self.store.justified = justified
            balances = justified_balances
            if balances is None and self._justified_balances_getter is not None:
                balances = self._justified_balances_getter(justified)
            if balances is not None:
                self.store.justified_balances = balances
            # even when balances could not be refreshed (getter miss), the
            # boost score must be recomputed from whatever balances the
            # store holds so the (balances, score) pair stays consistent
            self._justified_proposer_boost_score = None
        if finalized.epoch > self.store.finalized.epoch:
            self.store.finalized = finalized

    # queries ----------------------------------------------------------

    def get_block(self, root: str) -> Optional[ProtoNode]:
        return self.proto_array.get_node(root)

    def has_block(self, root: str) -> bool:
        return self.proto_array.has_block(root)

    def is_descendant(self, ancestor: str, descendant: str) -> bool:
        return self.proto_array.is_descendant(ancestor, descendant)

    def get_ancestor(self, root: str, slot: int) -> Optional[str]:
        node = self.proto_array.get_ancestor_at_or_before_slot(root, slot)
        return node.block_root if node else None
