"""Shared aiohttp session reuse + bounded retry for the long-lived
HTTP clients.

HttpExecutionEngine, HttpBuilderApi, and the beacon ApiClient each talk
to a single upstream over many small requests; creating a ClientSession
per request costs a connector + FD churn on every call (painful on the
2-core host).  This mixin keeps one lazily-created session per
instance, re-creates it if something closed it out from under us while
the client is live, and refuses to resurrect it after an explicit
``close()`` — a late request from a draining task must fail loudly, not
leak a fresh connector.

Ownership: whoever wires the client owns its shutdown.  Engine/builder
instances are injected into BeaconChain / BeaconRestApiServer and those
hosts close them; the validator/lightclient CLI constructs its own
ApiClient and closes it in a ``finally``.  A client instance must not
be shared across owners or reused after its owner shuts down; build a
fresh client instead.
"""
from __future__ import annotations

import asyncio
import random
from typing import Awaitable, Callable, Optional, TypeVar

T = TypeVar("T")

# Bounded: a dead upstream must fail the caller in ~a second, not hang
# a slot's worth of duties behind open-ended retries.
RETRY_ATTEMPTS = 3
RETRY_BASE_DELAY_S = 0.2
RETRY_MAX_DELAY_S = 2.0


def _transient_transport_error(e: BaseException) -> bool:
    """Connection-level faults: the TCP/TLS layer failed outright.
    These are worth retrying on idempotent calls — a flaky EL restart
    must not fail block production on the first hiccup.  TIMEOUTS are
    deliberately NOT retried: each attempt against a hung upstream
    burns the full client timeout (12 s default), so three attempts
    would stretch a slot-deadlined engine call to ~3x the timeout —
    far worse than surfacing the first one.  aiohttp's timeout errors
    (ServerTimeoutError, ConnectionTimeoutError, ...) SUBCLASS
    ClientConnectionError, so the timeout exclusion must be explicit."""
    import aiohttp

    if isinstance(e, (asyncio.TimeoutError, TimeoutError)):
        return False
    return isinstance(e, (aiohttp.ClientConnectionError, ConnectionError))


async def request_with_retry(
    send_once: Callable[[], Awaitable[T]],
    *,
    idempotent: bool = True,
    retryable_status: Optional[Callable[[BaseException], bool]] = None,
    attempts: int = RETRY_ATTEMPTS,
    base_delay_s: float = RETRY_BASE_DELAY_S,
    max_delay_s: float = RETRY_MAX_DELAY_S,
    log: Optional[Callable[[str], None]] = None,
) -> T:
    """Run ``send_once`` with bounded retry, exponential backoff and
    full jitter for transient faults.

    Only **idempotent** calls retry at all: a non-idempotent request
    that failed mid-flight may have been applied upstream, so its first
    error surfaces unretried.  Retried error classes: connection-level
    transport faults (see _transient_transport_error) plus whatever
    ``retryable_status`` accepts (clients pass a predicate matching
    their 5xx error type).  Cancellation-safe: ``CancelledError``
    re-raises immediately — shutdown must never sit out a backoff
    sleep.  The jittered delay (0.5-1.0x of the exponential step)
    keeps a fleet of restarted validators from stampeding a recovering
    EL in lockstep."""
    for attempt in range(attempts):
        try:
            return await send_once()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            transient = _transient_transport_error(e) or (
                retryable_status is not None and retryable_status(e)
            )
            if not (idempotent and transient) or attempt == attempts - 1:
                raise
            delay = min(max_delay_s, base_delay_s * (2**attempt))
            delay *= random.uniform(0.5, 1.0)
            if log is not None:
                log(
                    f"transient HTTP fault ({type(e).__name__}: {e}); "
                    f"retry {attempt + 1}/{attempts - 1} in {delay:.2f}s"
                )
        await asyncio.sleep(delay)
    raise AssertionError("unreachable")  # loop always returns or raises


async def post_json_rpc_once(
    session,
    url: str,
    *,
    method: str,
    params,
    rpc_id: int,
    headers: Optional[dict] = None,
    timeout_s: float,
    http_error,
):
    """One JSON-RPC POST attempt with the error semantics every JSON-RPC
    client in this repo shares (engine + eth1 — one implementation so a
    semantics fix can never land on one seam and drift on the other):

    * HTTP 401 → ``http_error(method, 401)`` — an auth verdict,
      deterministic, never retried;
    * any other 4xx/5xx carrying a JSON-RPC error object (geth answers
      bad params with HTTP 400 + error body, internal errors with 500 +
      error body) → the body is RETURNED — it is a deterministic ANSWER
      whose diagnostic the caller surfaces as its typed RPC error;
    * bodyless non-2xx → ``http_error(method, status)`` (callers retry
      only >= 500 via their ``retryable_status`` predicate);
    * 2xx → parsed JSON body.
    """
    import aiohttp

    async with session.post(
        url,
        json={"jsonrpc": "2.0", "id": rpc_id, "method": method, "params": params},
        headers=headers or {},
        timeout=aiohttp.ClientTimeout(total=timeout_s),
    ) as resp:
        if resp.status == 401:
            raise http_error(method, 401)
        if resp.status >= 400:
            try:
                body = await resp.json()
            except (aiohttp.ContentTypeError, ValueError):
                body = None
            if isinstance(body, dict) and "error" in body:
                return body
            raise http_error(method, resp.status)
        return await resp.json()


def json_rpc_result(body: dict, *, on_error):
    """JSON-RPC response body → result, raising ``on_error(code,
    message)`` (the client's typed RPC-error factory) on an error
    object."""
    if "error" in body:
        err = body["error"] or {}
        raise on_error(int(err.get("code", 0)), str(err.get("message", "")))
    return body["result"]


class ReusedClientSession:
    """Per-instance aiohttp.ClientSession, created on first use and
    reused across requests; ``close()`` releases it (idempotent) and
    makes any later ``_ses()`` raise."""

    _session = None  # set lazily; class defaults keep __init__ optional
    _ses_closed = False

    async def _ses(self):
        import aiohttp

        if self._ses_closed:
            raise RuntimeError(
                f"{type(self).__name__} is closed; no further HTTP requests"
            )
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        self._ses_closed = True
        if self._session is not None and not self._session.closed:
            await self._session.close()
