"""Shared aiohttp session reuse for the long-lived HTTP clients.

HttpExecutionEngine, HttpBuilderApi, and the beacon ApiClient each talk
to a single upstream over many small requests; creating a ClientSession
per request costs a connector + FD churn on every call (painful on the
2-core host).  This mixin keeps one lazily-created session per
instance, re-creates it if something closed it out from under us while
the client is live, and refuses to resurrect it after an explicit
``close()`` — a late request from a draining task must fail loudly, not
leak a fresh connector.

Ownership: whoever wires the client owns its shutdown.  Engine/builder
instances are injected into BeaconChain / BeaconRestApiServer and those
hosts close them; the validator/lightclient CLI constructs its own
ApiClient and closes it in a ``finally``.  A client instance must not
be shared across owners or reused after its owner shuts down; build a
fresh client instead.
"""
from __future__ import annotations


class ReusedClientSession:
    """Per-instance aiohttp.ClientSession, created on first use and
    reused across requests; ``close()`` releases it (idempotent) and
    makes any later ``_ses()`` raise."""

    _session = None  # set lazily; class defaults keep __init__ optional
    _ses_closed = False

    async def _ses(self):
        import aiohttp

        if self._ses_closed:
            raise RuntimeError(
                f"{type(self).__name__} is closed; no further HTTP requests"
            )
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        self._ses_closed = True
        if self._session is not None and not self._session.closed:
            await self._session.close()
