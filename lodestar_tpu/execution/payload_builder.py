"""Engine-backed payload production under a slot-deadline watchdog
(reference: produceBlockBody.ts getExecutionPayload + the
prepareExecutionPayload timeout handling).

A proposal has one slot interval (SECONDS_PER_SLOT / INTERVALS_PER_SLOT)
to ship a block; an EL that stalls on ``engine_getPayload`` near that
deadline must not take the proposal down with it.  The watchdog races
forkchoiceUpdated-with-attributes and getPayload against the deadline
with retry-then-abort semantics:

* a QUICK failure (connection refused, JSON-RPC error) retries while
  budget remains — a flapping EL gets its second chance;
* a TIMEOUT burned the budget — abort immediately, no half-slot second
  attempt against an EL that just proved it is hung;
* every abort raises ``PayloadDeadlineError`` and increments the
  distinct ``produce_payload_fallbacks_total`` metric, so the caller
  falls back to a complete locally-built payload — never a half-built
  block, never a stalled proposal loop.
"""
from __future__ import annotations

import asyncio
from typing import Callable, Optional


class PayloadDeadlineError(RuntimeError):
    """The EL could not deliver a payload before the proposal deadline
    (or refused to build one); the caller must fall back, not wait."""

    def __init__(self, message: str, reason: str = "error"):
        super().__init__(message)
        self.reason = reason  # "deadline" | "error" | "refused"


def _count_fallback(metrics, reason: str) -> None:
    if metrics is not None:
        metrics.produce_payload_fallbacks_total.labels(reason=reason).inc()


async def get_payload_with_watchdog(
    engine,
    payload_id: bytes,
    *,
    deadline_s: float,
    retries: int = 1,
    metrics=None,
    log: Optional[Callable[[str], None]] = None,
):
    """``engine_getPayload`` raced against ``deadline_s`` seconds.

    Quick failures retry (up to ``retries`` extra attempts) while budget
    remains; a timeout aborts outright.  Raises ``PayloadDeadlineError``
    (with the fallback metric already counted) instead of ever returning
    a partial result.
    """
    loop = asyncio.get_running_loop()
    deadline = loop.time() + max(0.0, deadline_s)
    last_err: Optional[BaseException] = None
    reason = "deadline"
    for attempt in range(retries + 1):
        remaining = deadline - loop.time()
        if remaining <= 0:
            break
        try:
            return await asyncio.wait_for(
                engine.get_payload(payload_id), timeout=remaining
            )
        except asyncio.CancelledError:
            raise
        except (asyncio.TimeoutError, TimeoutError) as e:
            # the deadline itself fired: the EL is hung, a second
            # attempt would just stall the proposal past the slot
            last_err = e
            reason = "deadline"
            break
        except Exception as e:
            last_err = e
            reason = "error"
            if log is not None:
                log(
                    f"getPayload attempt {attempt + 1}/{retries + 1} "
                    f"failed: {type(e).__name__}: {e}"
                )
    _count_fallback(metrics, reason)
    raise PayloadDeadlineError(
        f"getPayload missed the proposal deadline ({deadline_s:.2f}s): "
        f"{type(last_err).__name__ if last_err else 'budget exhausted'}: "
        f"{last_err}",
        reason=reason,
    ) from last_err


async def produce_engine_payload(
    engine,
    *,
    head_block_hash: bytes,
    safe_block_hash: bytes,
    finalized_block_hash: bytes,
    attrs: dict,
    deadline_s: float,
    retries: int = 1,
    metrics=None,
    log: Optional[Callable[[str], None]] = None,
):
    """Full engine production flow under one deadline budget:
    forkchoiceUpdated-with-attributes mints the payloadId, getPayload
    fetches the built payload.  Any failure — transport, a non-VALID
    head verdict, a withheld payloadId, a stall — funnels into
    ``PayloadDeadlineError`` so the caller has exactly one fallback
    seam."""
    from lodestar_tpu.execution.engine import ExecutePayloadStatus

    loop = asyncio.get_running_loop()
    deadline = loop.time() + max(0.0, deadline_s)
    try:
        res = await asyncio.wait_for(
            engine.notify_forkchoice_update(
                head_block_hash,
                safe_block_hash,
                finalized_block_hash,
                payload_attributes=attrs,
            ),
            timeout=max(0.01, deadline - loop.time()),
        )
    except asyncio.CancelledError:
        raise
    except (asyncio.TimeoutError, TimeoutError) as e:
        _count_fallback(metrics, "deadline")
        raise PayloadDeadlineError(
            f"forkchoiceUpdated(attributes) missed the proposal deadline: {e}",
            reason="deadline",
        ) from e
    except Exception as e:
        _count_fallback(metrics, "error")
        raise PayloadDeadlineError(
            f"forkchoiceUpdated(attributes) failed: {type(e).__name__}: {e}",
            reason="error",
        ) from e
    if res.status.status is not ExecutePayloadStatus.VALID or res.payload_id is None:
        _count_fallback(metrics, "refused")
        raise PayloadDeadlineError(
            f"EL refused to build: status={res.status.status.value} "
            f"payloadId={'minted' if res.payload_id else 'none'}",
            reason="refused",
        )
    return await get_payload_with_watchdog(
        engine,
        res.payload_id,
        deadline_s=deadline - loop.time(),
        retries=retries,
        metrics=metrics,
        log=log,
    )
