"""ExecutionPayload <-> engine JSON-RPC wire encoding (reference:
packages/beacon-node/src/execution/engine/types.ts
serializeExecutionPayload / parseExecutionPayload).

The Engine API does NOT use the Beacon-API JSON dialect (ssz/json.py):
field names are camelCase, integers are QUANTITY (`0x`-hex, no leading
zeros, `0x0` for zero) and byte strings are DATA (`0x`-hex, fixed
width).  Fork coverage follows the payload's own shape: withdrawals
from capella (V2), `excessDataGas` for eip4844 (V3).

Everything here is pure data transformation shared by both sides of the
HTTP seam: `HttpExecutionEngine` (client) and the mock EL server
(`lodestar_tpu/testing/mock_el_server.py`).  Strictness lives in
``payload_from_json``: a payload for fork F must carry exactly fork F's
fields — a V2 body without withdrawals, or a V1 body with them, is an
encoding bug worth failing loudly on, not papering over.
"""
from __future__ import annotations

from typing import Dict, Optional

from lodestar_tpu.params import ForkName

# engine structure version by fork (engine/http.ts:158-161: forkName →
# newPayload/forkchoiceUpdated/getPayload V1/V2/V3)
ENGINE_VERSION_BY_FORK: Dict[ForkName, int] = {
    ForkName.bellatrix: 1,
    ForkName.capella: 2,
    ForkName.eip4844: 3,
}
FORK_BY_ENGINE_VERSION: Dict[int, ForkName] = {
    v: f for f, v in ENGINE_VERSION_BY_FORK.items()
}


class EngineSerdeError(ValueError):
    """Malformed engine JSON (wrong width, missing/extra fork fields)."""


def engine_version_for_fork(fork: ForkName) -> int:
    try:
        return ENGINE_VERSION_BY_FORK[ForkName(fork)]
    except KeyError:
        raise EngineSerdeError(
            f"fork {fork!r} has no Engine API structure version "
            f"(pre-merge forks never reach an EL)"
        ) from None


def fork_of_payload(payload) -> ForkName:
    """The fork an ExecutionPayload instance belongs to, from its SSZ
    module (lodestar_tpu.types.<fork>.ExecutionPayload)."""
    mod = type(payload).__module__.rsplit(".", 1)[-1]
    try:
        return ForkName(mod)
    except ValueError:
        raise EngineSerdeError(
            f"{type(payload)!r} is not a fork ExecutionPayload"
        ) from None


# -- scalar encodings -------------------------------------------------------


def quantity(value: int) -> str:
    """QUANTITY: 0x-hex, no leading zeros ("0x0" for zero)."""
    return hex(int(value))


def parse_quantity(s) -> int:
    if not isinstance(s, str) or not s.startswith("0x"):
        raise EngineSerdeError(f"QUANTITY must be 0x-hex, got {s!r}")
    return int(s, 16)


def data(value: bytes) -> str:
    """DATA: 0x-hex of the raw bytes."""
    return "0x" + bytes(value).hex()


def parse_data(s, length: Optional[int] = None) -> bytes:
    if not isinstance(s, str) or not s.startswith("0x"):
        raise EngineSerdeError(f"DATA must be 0x-hex, got {s!r}")
    try:
        b = bytes.fromhex(s[2:])
    except ValueError:
        raise EngineSerdeError(f"DATA is not hex: {s!r}") from None
    if length is not None and len(b) != length:
        raise EngineSerdeError(f"DATA expected {length} bytes, got {len(b)}")
    return b


# -- withdrawals (capella, V2+) ---------------------------------------------


def withdrawal_to_json(w) -> dict:
    return {
        "index": quantity(w.index),
        "validatorIndex": quantity(w.validator_index),
        "address": data(w.address),
        "amount": quantity(w.amount),
    }


def withdrawal_from_json(obj: dict):
    from lodestar_tpu.types import ssz

    return ssz.capella.Withdrawal(
        index=parse_quantity(obj["index"]),
        validator_index=parse_quantity(obj["validatorIndex"]),
        address=parse_data(obj["address"], 20),
        amount=parse_quantity(obj["amount"]),
    )


# -- ExecutionPayload -------------------------------------------------------


def payload_to_json(payload) -> dict:
    """SSZ ExecutionPayload (any fork) → engine JSON body; the emitted
    fields follow the payload's own fork shape."""
    obj = {
        "parentHash": data(payload.parent_hash),
        "feeRecipient": data(payload.fee_recipient),
        "stateRoot": data(payload.state_root),
        "receiptsRoot": data(payload.receipts_root),
        "logsBloom": data(payload.logs_bloom),
        "prevRandao": data(payload.prev_randao),
        "blockNumber": quantity(payload.block_number),
        "gasLimit": quantity(payload.gas_limit),
        "gasUsed": quantity(payload.gas_used),
        "timestamp": quantity(payload.timestamp),
        "extraData": data(payload.extra_data),
        "baseFeePerGas": quantity(payload.base_fee_per_gas),
        "blockHash": data(payload.block_hash),
        "transactions": [data(tx) for tx in payload.transactions],
    }
    if hasattr(payload, "withdrawals"):  # capella+
        obj["withdrawals"] = [withdrawal_to_json(w) for w in payload.withdrawals]
    if hasattr(payload, "excess_data_gas"):  # eip4844
        obj["excessDataGas"] = quantity(payload.excess_data_gas)
    return obj


def payload_from_json(fork: ForkName, obj: dict):
    """Engine JSON body → fork's SSZ ExecutionPayload, strict about the
    fork's field set (a mismatched shape means client and EL disagree
    about the fork — the most dangerous failure to swallow)."""
    from lodestar_tpu.types import ssz

    fork = ForkName(fork)
    if not isinstance(obj, dict):
        raise EngineSerdeError(f"payload body must be an object, got {type(obj)}")
    mod = getattr(ssz, fork.value, None)
    cls = getattr(mod, "ExecutionPayload", None)
    if cls is None:
        raise EngineSerdeError(f"fork {fork.value} has no ExecutionPayload")
    try:
        kwargs = dict(
            parent_hash=parse_data(obj["parentHash"], 32),
            fee_recipient=parse_data(obj["feeRecipient"], 20),
            state_root=parse_data(obj["stateRoot"], 32),
            receipts_root=parse_data(obj["receiptsRoot"], 32),
            logs_bloom=parse_data(obj["logsBloom"], 256),
            prev_randao=parse_data(obj["prevRandao"], 32),
            block_number=parse_quantity(obj["blockNumber"]),
            gas_limit=parse_quantity(obj["gasLimit"]),
            gas_used=parse_quantity(obj["gasUsed"]),
            timestamp=parse_quantity(obj["timestamp"]),
            extra_data=parse_data(obj["extraData"]),
            base_fee_per_gas=parse_quantity(obj["baseFeePerGas"]),
            block_hash=parse_data(obj["blockHash"], 32),
            transactions=[parse_data(tx) for tx in obj["transactions"]],
        )
    except KeyError as e:
        raise EngineSerdeError(f"payload missing field {e.args[0]!r}") from None
    has_withdrawals = "withdrawals" in obj
    wants_withdrawals = fork in (ForkName.capella, ForkName.eip4844)
    if has_withdrawals != wants_withdrawals:
        raise EngineSerdeError(
            f"{fork.value} payload and 'withdrawals' field disagree "
            f"(present={has_withdrawals})"
        )
    if wants_withdrawals:
        kwargs["withdrawals"] = [withdrawal_from_json(w) for w in obj["withdrawals"]]
    has_excess = "excessDataGas" in obj
    wants_excess = fork is ForkName.eip4844
    if has_excess != wants_excess:
        raise EngineSerdeError(
            f"{fork.value} payload and 'excessDataGas' field disagree "
            f"(present={has_excess})"
        )
    if wants_excess:
        kwargs["excess_data_gas"] = parse_quantity(obj["excessDataGas"])
    return cls(**kwargs)


# -- PayloadAttributes ------------------------------------------------------


def payload_attributes_to_json(attrs: dict, version: int) -> dict:
    """Internal attributes dict (MockExecutionEngine's format: fork,
    timestamp, prev_randao, suggested_fee_recipient, withdrawals,
    parent_beacon_block_root) → engine PayloadAttributesV{1,2,3}."""
    obj = {
        "timestamp": quantity(attrs["timestamp"]),
        "prevRandao": data(attrs["prev_randao"]),
        "suggestedFeeRecipient": data(
            attrs.get("suggested_fee_recipient", b"\x00" * 20)
        ),
    }
    if version >= 2:
        obj["withdrawals"] = [
            withdrawal_to_json(w) for w in attrs.get("withdrawals", ())
        ]
    elif attrs.get("withdrawals"):
        # silently dropping withdrawals here would make the EL build a
        # bellatrix-shaped payload for a capella slot — the classic
        # "forgot the fork tag" caller bug; fail loudly instead
        raise EngineSerdeError(
            "attributes carry withdrawals but PayloadAttributesV1 cannot "
            "(missing/wrong 'fork' tag?)"
        )
    if version >= 3:
        # required by the spec's PayloadAttributesV3 — a real EL answers
        # -38003 Invalid payload attributes without it, so omission must
        # fail in-repo too
        root = attrs.get("parent_beacon_block_root")
        if root is None:
            raise EngineSerdeError(
                "PayloadAttributesV3 requires parent_beacon_block_root"
            )
        obj["parentBeaconBlockRoot"] = data(root)
    return obj


def payload_attributes_from_json(obj: dict, version: int) -> dict:
    """Engine PayloadAttributesV{1,2,3} → the internal attributes dict
    MockExecutionEngine consumes, fork-tagged by structure version."""
    attrs = {
        "fork": FORK_BY_ENGINE_VERSION[version],
        "timestamp": parse_quantity(obj["timestamp"]),
        "prev_randao": parse_data(obj["prevRandao"], 32),
        "suggested_fee_recipient": parse_data(obj["suggestedFeeRecipient"], 20),
    }
    if version >= 2:
        if "withdrawals" not in obj:
            raise EngineSerdeError(
                f"PayloadAttributesV{version} requires 'withdrawals'"
            )
        attrs["withdrawals"] = [
            withdrawal_from_json(w) for w in obj["withdrawals"]
        ]
    elif "withdrawals" in obj:
        raise EngineSerdeError("PayloadAttributesV1 must not carry withdrawals")
    if version >= 3:
        if "parentBeaconBlockRoot" not in obj:
            raise EngineSerdeError(
                "PayloadAttributesV3 requires parentBeaconBlockRoot"
            )
        attrs["parent_beacon_block_root"] = parse_data(
            obj["parentBeaconBlockRoot"], 32
        )
    return attrs
