"""Execution-layer Engine API (reference:
packages/beacon-node/src/execution/engine/{interface,http,mock}.ts).

ExecutionEngine is the protocol the chain consumes (notifyNewPayload /
notifyForkchoiceUpdate / getPayload); MockExecutionEngine is the in-process
fake EL (engine/mock.ts role) used by dev chains and merge tests;
HttpExecutionEngine speaks engine JSON-RPC over aiohttp (http.ts:155).
"""
from __future__ import annotations

import os
import secrets
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Optional, Protocol

from lodestar_tpu.execution.http_session import (
    ReusedClientSession,
    json_rpc_result,
    post_json_rpc_once,
    request_with_retry,
)
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger


class ExecutePayloadStatus(str, Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"


@dataclass
class PayloadStatus:
    status: ExecutePayloadStatus
    latest_valid_hash: Optional[bytes] = None
    validation_error: Optional[str] = None


@dataclass
class ForkchoiceUpdateResult:
    """engine_forkchoiceUpdated response: the EL's verdict on the head
    we pointed it at (VALID / SYNCING / INVALID-with-latestValidHash)
    plus the payloadId minted when attributes were attached.  The chain
    consumes the status for optimistic-sync bookkeeping — discarding it
    was how PR 9's seam silently ate INVALID heads."""

    status: PayloadStatus
    payload_id: Optional[bytes] = None


class ExecutionEngine(Protocol):
    async def notify_new_payload(
        self, payload, versioned_hashes=None, parent_beacon_block_root=None
    ) -> PayloadStatus: ...
    async def notify_forkchoice_update(
        self, head_block_hash: bytes, safe_block_hash: bytes,
        finalized_block_hash: bytes, payload_attributes=None, fork=None,
    ) -> ForkchoiceUpdateResult: ...
    async def get_payload(self, payload_id: bytes): ...


def _mock_block_hash(parent_hash: bytes, prev_randao: bytes, timestamp: int) -> bytes:
    import hashlib

    return hashlib.sha256(
        b"lodestar-tpu-mock-el"
        + bytes(parent_hash)
        + bytes(prev_randao)
        + int(timestamp).to_bytes(8, "little")
    ).digest()


def build_payload(
    fork,
    parent_hash: bytes,
    timestamp: int,
    prev_randao: bytes,
    fee_recipient: bytes = b"\x00" * 20,
    withdrawals=(),
    block_number: int = 0,
    transactions=(),
):
    """Deterministic mock ExecutionPayload for `fork`, chained by
    block_hash (engine/mock.ts fakeBlockProductionLoop role)."""
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.types import ssz

    mod = getattr(ssz, fork.value)
    payload = mod.ExecutionPayload.default()
    payload.parent_hash = bytes(parent_hash)
    payload.fee_recipient = bytes(fee_recipient)
    payload.prev_randao = bytes(prev_randao)
    payload.block_number = block_number
    payload.gas_limit = 30_000_000
    payload.timestamp = int(timestamp)
    payload.base_fee_per_gas = 7
    payload.transactions = list(transactions)
    if hasattr(payload, "withdrawals"):
        payload.withdrawals = list(withdrawals)
    payload.block_hash = _mock_block_hash(parent_hash, prev_randao, timestamp)
    return payload


def dev_payload_attributes(
    cfg, state, fee_recipient=b"\x00" * 20, parent_beacon_block_root=None
):
    """PayloadAttributes for the next block on ``state`` (already
    advanced to the block's slot).  Shared by the local
    ``build_dev_payload`` shortcut and the engine-backed production path
    (forkchoiceUpdated-with-attributes → getPayload), so both build the
    byte-identical payload and every process_execution_payload
    consistency check (parent_hash / prev_randao / timestamp) holds."""
    from lodestar_tpu.params import ACTIVE_PRESET as _p, ForkName
    from lodestar_tpu.types import fork_of_state

    fork = fork_of_state(state)
    epoch = state.slot // _p.SLOTS_PER_EPOCH
    attrs = {
        "fork": fork,
        "timestamp": state.genesis_time + state.slot * cfg.SECONDS_PER_SLOT,
        "prev_randao": bytes(
            state.randao_mixes[epoch % _p.EPOCHS_PER_HISTORICAL_VECTOR]
        ),
        "suggested_fee_recipient": bytes(fee_recipient),
        "block_number": state.latest_execution_payload_header.block_number + 1,
    }
    if hasattr(state, "next_withdrawal_index"):
        from lodestar_tpu.state_transition.block.capella import (
            get_expected_withdrawals,
        )

        attrs["withdrawals"] = get_expected_withdrawals(state)
    if fork is ForkName.eip4844 and parent_beacon_block_root is not None:
        attrs["parent_beacon_block_root"] = bytes(parent_beacon_block_root)
    return attrs


def build_dev_payload(cfg, state, transactions=(), fee_recipient=b"\x00" * 20):
    """Payload valid for the next block on `state` (already advanced to the
    block's slot): satisfies every process_execution_payload consistency
    check (parent_hash / prev_randao / timestamp)."""
    attrs = dev_payload_attributes(cfg, state, fee_recipient=fee_recipient)
    return build_payload(
        attrs["fork"],
        parent_hash=bytes(state.latest_execution_payload_header.block_hash),
        timestamp=attrs["timestamp"],
        prev_randao=attrs["prev_randao"],
        withdrawals=attrs.get("withdrawals", ()),
        block_number=attrs["block_number"],
        transactions=transactions,
        fee_recipient=attrs["suggested_fee_recipient"],
    )


class MockExecutionEngine:
    """Accept-everything EL double with payload building
    (engine/mock.ts)."""

    def __init__(self):
        self.head: Optional[bytes] = None
        self.finalized: Optional[bytes] = None
        self._payloads: Dict[bytes, object] = {}
        self.notified_payloads = 0

    async def notify_new_payload(
        self, payload, versioned_hashes=None, parent_beacon_block_root=None
    ) -> PayloadStatus:
        return self.notify_new_payload_sync_status(payload)

    def notify_new_payload_sync_status(self, payload) -> PayloadStatus:
        self.notified_payloads += 1
        return PayloadStatus(
            ExecutePayloadStatus.VALID, getattr(payload, "block_hash", None)
        )

    def notify_new_payload_sync(self, payload) -> bool:
        """Synchronous accept/reject used by the STF's optional engine hook
        (process_execution_payload)."""
        return self.notify_new_payload_sync_status(payload).status is (
            ExecutePayloadStatus.VALID
        )

    async def notify_forkchoice_update(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None, fork=None,
    ) -> ForkchoiceUpdateResult:
        self.head = head_block_hash
        self.finalized = finalized_block_hash
        pid = None
        if payload_attributes is not None:
            pid = secrets.token_bytes(8)
            self._payloads[pid] = (head_block_hash, dict(payload_attributes))
        return ForkchoiceUpdateResult(
            PayloadStatus(ExecutePayloadStatus.VALID, bytes(head_block_hash)),
            pid,
        )

    async def get_payload(self, payload_id: bytes):
        """Build the payload promised by a forkchoiceUpdated with
        attributes: {fork, timestamp, prev_randao, suggested_fee_recipient,
        withdrawals?, block_number?}."""
        if payload_id not in self._payloads:
            raise ValueError("unknown payloadId")
        parent_hash, attrs = self._payloads.pop(payload_id)
        return build_payload(
            attrs["fork"],
            parent_hash=parent_hash,
            timestamp=attrs["timestamp"],
            prev_randao=attrs["prev_randao"],
            fee_recipient=attrs.get("suggested_fee_recipient", b"\x00" * 20),
            withdrawals=attrs.get("withdrawals", ()),
            block_number=attrs.get("block_number", 0),
        )


class EngineHttpError(RuntimeError):
    """Non-2xx HTTP response from the EL (before JSON-RPC framing).
    401 means JWT auth failed — deterministic, never retried."""

    def __init__(self, method: str, status: int):
        super().__init__(f"{method}: HTTP {status}")
        self.status = status


class EngineRpcError(RuntimeError):
    """A JSON-RPC *error response* from the EL: a deterministic answer
    carrying the EL's diagnostic (code + message), never retried."""

    def __init__(self, method: str, code: int, message: str):
        super().__init__(f"{method}: JSON-RPC error {code}: {message}")
        self.method = method
        self.code = code
        self.message = message


# engine_* methods this client can issue (engine_exchangeCapabilities
# payload; the exchange method itself is excluded per the Engine API spec)
SUPPORTED_ENGINE_METHODS = tuple(
    f"engine_{stem}V{v}"
    for stem in ("newPayload", "forkchoiceUpdated", "getPayload")
    for v in (1, 2, 3)
)


class HttpExecutionEngine(ReusedClientSession):
    """engine_* JSON-RPC client (http.ts).  Supports the jwt-secret auth
    the Engine API requires and selects the engine structure version by
    fork (http.ts:158-161,321): bellatrix→V1, capella→V2 (withdrawals),
    eip4844→V3 (excessDataGas + blob versioned hashes).

    Transport faults (connection errors, 5xx) retry with bounded
    exponential backoff + jitter: every engine_* method is idempotent —
    re-submitting the same payload / forkchoice state is a no-op on the
    EL — so a flaky EL hiccup must not fail block production outright
    (reference engine/http.ts retries the same way).  JSON-RPC *error
    responses* are answers, not faults: they surface immediately as
    typed ``EngineRpcError``; HTTP 401 (bad/stale JWT) surfaces as
    ``EngineHttpError`` unretried."""

    def __init__(
        self,
        url: str,
        jwt_secret: Optional[bytes] = None,
        timeout: float = 12.0,
        metrics=None,
    ):
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout = timeout
        self.metrics = metrics  # LodestarMetrics or None
        self.capabilities: Optional[list] = None
        self._id = 0
        # payloadId → fork promised by the forkchoiceUpdated that minted
        # it, so get_payload can parse the response without re-asking
        self._payload_forks: Dict[bytes, object] = {}
        self._log = get_logger("engine")

    async def _rpc(self, method: str, params):
        import time as _time

        async def send_once():
            faults.fire("execution.engine.http", method=method)
            return await self._post_once(method, params)

        t0 = _time.perf_counter()
        try:
            body = await request_with_retry(
                send_once,
                idempotent=True,
                retryable_status=lambda e: (
                    isinstance(e, EngineHttpError) and e.status >= 500
                ),
                log=lambda m: self._log.warn(f"{method}: {m}"),
            )
        except Exception as e:
            self._count_error(method, e)
            raise

        def rpc_error(code, message):
            self._count_error(method, None, kind="rpc_error")
            return EngineRpcError(method, code, message)

        result = json_rpc_result(body, on_error=rpc_error)
        if self.metrics is not None:
            self.metrics.engine_rpc_seconds.labels(method=method).observe(
                _time.perf_counter() - t0
            )
        return result

    def _count_error(self, method: str, e, kind: Optional[str] = None) -> None:
        if self.metrics is None:
            return
        if kind is None:
            kind = "http" if isinstance(e, EngineHttpError) else "transport"
        self.metrics.engine_rpc_errors_total.labels(method=method, kind=kind).inc()

    async def _post_once(self, method: str, params) -> dict:
        """One transport attempt (overridden by transport-free tests);
        status/error-body semantics live in post_json_rpc_once."""
        self._id += 1
        headers = {}
        if self.jwt_secret is not None:
            headers["Authorization"] = f"Bearer {self._jwt_token()}"
        session = await self._ses()
        return await post_json_rpc_once(
            session,
            self.url,
            method=method,
            params=params,
            rpc_id=self._id,
            headers=headers,
            timeout_s=self.timeout,
            http_error=EngineHttpError,
        )

    def _jwt_token(self) -> str:
        """HS256 JWT with iat claim (Engine API auth spec)."""
        import base64
        import hashlib
        import hmac
        import json
        import time

        def b64(data: bytes) -> str:
            return base64.urlsafe_b64encode(data).rstrip(b"=").decode()

        header = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        payload = b64(json.dumps({"iat": int(time.time())}).encode())
        msg = f"{header}.{payload}".encode()
        sig = b64(hmac.new(self.jwt_secret, msg, hashlib.sha256).digest())
        return f"{header}.{payload}.{sig}"

    async def exchange_capabilities(self) -> list:
        """engine_exchangeCapabilities probe (connect-time handshake):
        sends our method list, remembers the EL's, and warns about any
        method we may need that the EL does not announce."""
        result = await self._rpc(
            "engine_exchangeCapabilities", [list(SUPPORTED_ENGINE_METHODS)]
        )
        self.capabilities = list(result or [])
        missing = [
            m for m in SUPPORTED_ENGINE_METHODS if m not in self.capabilities
        ]
        if missing:
            self._log.warn(
                f"EL does not announce {len(missing)} engine method(s): "
                + ", ".join(missing)
            )
        return self.capabilities

    async def notify_new_payload(
        self, payload, versioned_hashes=None, parent_beacon_block_root=None
    ) -> PayloadStatus:
        """engine_newPayloadV{1,2,3} selected by the payload's own fork;
        V3 carries blob versioned hashes + parent beacon block root
        (computed by the caller from the block body)."""
        from lodestar_tpu.execution import serde

        fork = serde.fork_of_payload(payload)
        version = serde.engine_version_for_fork(fork)
        params = [serde.payload_to_json(payload)]
        if version >= 3:
            # an empty hash list is a legitimate no-blob block, but the
            # parent root has no sane default — a zero root would make
            # the EL validate against the wrong parent with no
            # client-side hint that the caller forgot it
            if parent_beacon_block_root is None:
                raise serde.EngineSerdeError(
                    "engine_newPayloadV3 requires parent_beacon_block_root"
                )
            params.append([serde.data(h) for h in (versioned_hashes or ())])
            params.append(serde.data(parent_beacon_block_root))
        result = await self._rpc(f"engine_newPayloadV{version}", params)
        return PayloadStatus(
            ExecutePayloadStatus(result["status"]),
            bytes.fromhex(result["latestValidHash"][2:]) if result.get("latestValidHash") else None,
            result.get("validationError"),
        )

    async def notify_forkchoice_update(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None, fork=None,
    ) -> ForkchoiceUpdateResult:
        """engine_forkchoiceUpdatedV{1,2,3} selected by ``fork`` (or the
        fork tagged inside ``payload_attributes``; bellatrix default).
        Returns the EL's payloadStatus verdict (the optimistic-sync
        input) alongside any minted payloadId."""
        from lodestar_tpu.execution import serde
        from lodestar_tpu.params import ForkName

        if fork is None and payload_attributes is not None:
            fork = payload_attributes.get("fork")
        fork = ForkName(fork) if fork is not None else ForkName.bellatrix
        version = serde.engine_version_for_fork(fork)
        fc_state = {
            "headBlockHash": "0x" + head_block_hash.hex(),
            "safeBlockHash": "0x" + safe_block_hash.hex(),
            "finalizedBlockHash": "0x" + finalized_block_hash.hex(),
        }
        attrs_json = (
            serde.payload_attributes_to_json(payload_attributes, version)
            if payload_attributes is not None
            else None
        )
        result = await self._rpc(
            f"engine_forkchoiceUpdatedV{version}", [fc_state, attrs_json]
        )
        status_json = result.get("payloadStatus") or {}
        lvh = status_json.get("latestValidHash")
        status = PayloadStatus(
            ExecutePayloadStatus(status_json.get("status", "SYNCING")),
            bytes.fromhex(lvh[2:]) if lvh else None,
            status_json.get("validationError"),
        )
        pid = result.get("payloadId")
        if not pid:
            return ForkchoiceUpdateResult(status, None)
        pid_bytes = bytes.fromhex(pid[2:])
        self._payload_forks[pid_bytes] = fork
        # bounded: ids minted but never fetched (reorg past the slot,
        # missed proposal window) must not accumulate for a node's
        # lifetime; oldest-first eviction, one live id per slot in
        # practice
        while len(self._payload_forks) > 64:
            self._payload_forks.pop(next(iter(self._payload_forks)))
        return ForkchoiceUpdateResult(status, pid_bytes)

    async def get_payload(self, payload_id: bytes, fork=None):
        """engine_getPayloadV{1,2,3} → the fork's SSZ ExecutionPayload.
        The fork defaults to whatever the forkchoiceUpdated that minted
        this payloadId promised."""
        from lodestar_tpu.execution import serde
        from lodestar_tpu.params import ForkName

        if fork is None:
            fork = self._payload_forks.get(bytes(payload_id), ForkName.bellatrix)
        fork = ForkName(fork)
        version = serde.engine_version_for_fork(fork)
        result = await self._rpc(
            f"engine_getPayloadV{version}", ["0x" + bytes(payload_id).hex()]
        )
        self._payload_forks.pop(bytes(payload_id), None)
        # V1 answers the payload body directly; V2+ wrap it with blockValue
        body = result if version == 1 else result["executionPayload"]
        return serde.payload_from_json(fork, body)
