"""Execution-layer Engine API (reference:
packages/beacon-node/src/execution/engine/{interface,http,mock}.ts).

ExecutionEngine is the protocol the chain consumes (notifyNewPayload /
notifyForkchoiceUpdate / getPayload); MockExecutionEngine is the in-process
fake EL (engine/mock.ts role) used by dev chains and merge tests;
HttpExecutionEngine speaks engine JSON-RPC over aiohttp (http.ts:155).
"""
from __future__ import annotations

import os
import secrets
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Optional, Protocol

from lodestar_tpu.execution.http_session import (
    ReusedClientSession,
    request_with_retry,
)
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger


class ExecutePayloadStatus(str, Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"


@dataclass
class PayloadStatus:
    status: ExecutePayloadStatus
    latest_valid_hash: Optional[bytes] = None
    validation_error: Optional[str] = None


class ExecutionEngine(Protocol):
    async def notify_new_payload(self, payload) -> PayloadStatus: ...
    async def notify_forkchoice_update(
        self, head_block_hash: bytes, safe_block_hash: bytes,
        finalized_block_hash: bytes, payload_attributes=None,
    ) -> Optional[bytes]: ...
    async def get_payload(self, payload_id: bytes): ...


def _mock_block_hash(parent_hash: bytes, prev_randao: bytes, timestamp: int) -> bytes:
    import hashlib

    return hashlib.sha256(
        b"lodestar-tpu-mock-el"
        + bytes(parent_hash)
        + bytes(prev_randao)
        + int(timestamp).to_bytes(8, "little")
    ).digest()


def build_payload(
    fork,
    parent_hash: bytes,
    timestamp: int,
    prev_randao: bytes,
    fee_recipient: bytes = b"\x00" * 20,
    withdrawals=(),
    block_number: int = 0,
    transactions=(),
):
    """Deterministic mock ExecutionPayload for `fork`, chained by
    block_hash (engine/mock.ts fakeBlockProductionLoop role)."""
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.types import ssz

    mod = getattr(ssz, fork.value)
    payload = mod.ExecutionPayload.default()
    payload.parent_hash = bytes(parent_hash)
    payload.fee_recipient = bytes(fee_recipient)
    payload.prev_randao = bytes(prev_randao)
    payload.block_number = block_number
    payload.gas_limit = 30_000_000
    payload.timestamp = int(timestamp)
    payload.base_fee_per_gas = 7
    payload.transactions = list(transactions)
    if hasattr(payload, "withdrawals"):
        payload.withdrawals = list(withdrawals)
    payload.block_hash = _mock_block_hash(parent_hash, prev_randao, timestamp)
    return payload


def build_dev_payload(cfg, state, transactions=(), fee_recipient=b"\x00" * 20):
    """Payload valid for the next block on `state` (already advanced to the
    block's slot): satisfies every process_execution_payload consistency
    check (parent_hash / prev_randao / timestamp)."""
    from lodestar_tpu.params import ACTIVE_PRESET as _p
    from lodestar_tpu.types import fork_of_state

    fork = fork_of_state(state)
    epoch = state.slot // _p.SLOTS_PER_EPOCH
    prev_randao = bytes(
        state.randao_mixes[epoch % _p.EPOCHS_PER_HISTORICAL_VECTOR]
    )
    withdrawals = ()
    if hasattr(state, "next_withdrawal_index"):
        from lodestar_tpu.state_transition.block.capella import (
            get_expected_withdrawals,
        )

        withdrawals = get_expected_withdrawals(state)
    return build_payload(
        fork,
        parent_hash=bytes(state.latest_execution_payload_header.block_hash),
        timestamp=state.genesis_time + state.slot * cfg.SECONDS_PER_SLOT,
        prev_randao=prev_randao,
        withdrawals=withdrawals,
        block_number=state.latest_execution_payload_header.block_number + 1,
        transactions=transactions,
        fee_recipient=fee_recipient,
    )


class MockExecutionEngine:
    """Accept-everything EL double with payload building
    (engine/mock.ts)."""

    def __init__(self):
        self.head: Optional[bytes] = None
        self.finalized: Optional[bytes] = None
        self._payloads: Dict[bytes, object] = {}
        self.notified_payloads = 0

    async def notify_new_payload(self, payload) -> PayloadStatus:
        return self.notify_new_payload_sync_status(payload)

    def notify_new_payload_sync_status(self, payload) -> PayloadStatus:
        self.notified_payloads += 1
        return PayloadStatus(
            ExecutePayloadStatus.VALID, getattr(payload, "block_hash", None)
        )

    def notify_new_payload_sync(self, payload) -> bool:
        """Synchronous accept/reject used by the STF's optional engine hook
        (process_execution_payload)."""
        return self.notify_new_payload_sync_status(payload).status is (
            ExecutePayloadStatus.VALID
        )

    async def notify_forkchoice_update(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> Optional[bytes]:
        self.head = head_block_hash
        self.finalized = finalized_block_hash
        if payload_attributes is not None:
            pid = secrets.token_bytes(8)
            self._payloads[pid] = (head_block_hash, dict(payload_attributes))
            return pid
        return None

    async def get_payload(self, payload_id: bytes):
        """Build the payload promised by a forkchoiceUpdated with
        attributes: {fork, timestamp, prev_randao, suggested_fee_recipient,
        withdrawals?, block_number?}."""
        if payload_id not in self._payloads:
            raise ValueError("unknown payloadId")
        parent_hash, attrs = self._payloads.pop(payload_id)
        return build_payload(
            attrs["fork"],
            parent_hash=parent_hash,
            timestamp=attrs["timestamp"],
            prev_randao=attrs["prev_randao"],
            fee_recipient=attrs.get("suggested_fee_recipient", b"\x00" * 20),
            withdrawals=attrs.get("withdrawals", ()),
            block_number=attrs.get("block_number", 0),
        )


class EngineHttpError(RuntimeError):
    """Non-2xx HTTP response from the EL (before JSON-RPC framing)."""

    def __init__(self, method: str, status: int):
        super().__init__(f"{method}: HTTP {status}")
        self.status = status


class HttpExecutionEngine(ReusedClientSession):
    """engine_* JSON-RPC client (http.ts).  Supports the jwt-secret auth
    the Engine API requires.

    Transport faults (connection errors, 5xx) retry with bounded
    exponential backoff + jitter: every engine_* method is idempotent —
    re-submitting the same payload / forkchoice state is a no-op on the
    EL — so a flaky EL hiccup must not fail block production outright
    (reference engine/http.ts retries the same way).  JSON-RPC *error
    responses* are answers, not faults: they surface immediately."""

    def __init__(self, url: str, jwt_secret: Optional[bytes] = None, timeout: float = 12.0):
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout = timeout
        self._id = 0
        self._log = get_logger("engine")

    async def _rpc(self, method: str, params):
        async def send_once():
            faults.fire("execution.engine.http", method=method)
            return await self._post_once(method, params)

        body = await request_with_retry(
            send_once,
            idempotent=True,
            retryable_status=lambda e: (
                isinstance(e, EngineHttpError) and e.status >= 500
            ),
            log=lambda m: self._log.warn(f"{method}: {m}"),
        )
        if "error" in body:
            raise RuntimeError(f"{method}: {body['error']}")
        return body["result"]

    async def _post_once(self, method: str, params) -> dict:
        """One transport attempt (overridden by transport-free tests)."""
        import aiohttp

        self._id += 1
        headers = {}
        if self.jwt_secret is not None:
            headers["Authorization"] = f"Bearer {self._jwt_token()}"
        session = await self._ses()
        async with session.post(
            self.url,
            json={"jsonrpc": "2.0", "id": self._id, "method": method, "params": params},
            headers=headers,
            timeout=aiohttp.ClientTimeout(total=self.timeout),
        ) as resp:
            if resp.status >= 500:
                # some ELs answer internal errors with HTTP 500 + a
                # JSON-RPC error object: that is a deterministic ANSWER
                # — surface it (the caller raises with its message)
                # instead of retrying it and losing the diagnostic
                try:
                    body = await resp.json()
                except (aiohttp.ContentTypeError, ValueError):
                    body = None
                if isinstance(body, dict) and "error" in body:
                    return body
                raise EngineHttpError(method, resp.status)
            return await resp.json()

    def _jwt_token(self) -> str:
        """HS256 JWT with iat claim (Engine API auth spec)."""
        import base64
        import hashlib
        import hmac
        import json
        import time

        def b64(data: bytes) -> str:
            return base64.urlsafe_b64encode(data).rstrip(b"=").decode()

        header = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        payload = b64(json.dumps({"iat": int(time.time())}).encode())
        msg = f"{header}.{payload}".encode()
        sig = b64(hmac.new(self.jwt_secret, msg, hashlib.sha256).digest())
        return f"{header}.{payload}.{sig}"

    async def notify_new_payload(self, payload) -> PayloadStatus:
        result = await self._rpc("engine_newPayloadV1", [payload])
        return PayloadStatus(
            ExecutePayloadStatus(result["status"]),
            bytes.fromhex(result["latestValidHash"][2:]) if result.get("latestValidHash") else None,
            result.get("validationError"),
        )

    async def notify_forkchoice_update(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> Optional[bytes]:
        fc_state = {
            "headBlockHash": "0x" + head_block_hash.hex(),
            "safeBlockHash": "0x" + safe_block_hash.hex(),
            "finalizedBlockHash": "0x" + finalized_block_hash.hex(),
        }
        result = await self._rpc(
            "engine_forkchoiceUpdatedV1", [fc_state, payload_attributes]
        )
        pid = result.get("payloadId")
        return bytes.fromhex(pid[2:]) if pid else None

    async def get_payload(self, payload_id: bytes):
        return await self._rpc("engine_getPayloadV1", ["0x" + payload_id.hex()])
