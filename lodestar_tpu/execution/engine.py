"""Execution-layer Engine API (reference:
packages/beacon-node/src/execution/engine/{interface,http,mock}.ts).

ExecutionEngine is the protocol the chain consumes (notifyNewPayload /
notifyForkchoiceUpdate / getPayload); MockExecutionEngine is the in-process
fake EL (engine/mock.ts role) used by dev chains and merge tests;
HttpExecutionEngine speaks engine JSON-RPC over aiohttp (http.ts:155).
"""
from __future__ import annotations

import os
import secrets
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, Optional, Protocol


class ExecutePayloadStatus(str, Enum):
    VALID = "VALID"
    INVALID = "INVALID"
    SYNCING = "SYNCING"
    ACCEPTED = "ACCEPTED"


@dataclass
class PayloadStatus:
    status: ExecutePayloadStatus
    latest_valid_hash: Optional[bytes] = None
    validation_error: Optional[str] = None


class ExecutionEngine(Protocol):
    async def notify_new_payload(self, payload) -> PayloadStatus: ...
    async def notify_forkchoice_update(
        self, head_block_hash: bytes, safe_block_hash: bytes,
        finalized_block_hash: bytes, payload_attributes=None,
    ) -> Optional[bytes]: ...
    async def get_payload(self, payload_id: bytes): ...


class MockExecutionEngine:
    """Accept-everything EL double with payload building
    (engine/mock.ts)."""

    def __init__(self):
        self.head: Optional[bytes] = None
        self.finalized: Optional[bytes] = None
        self._payloads: Dict[bytes, object] = {}
        self.notified_payloads = 0

    async def notify_new_payload(self, payload) -> PayloadStatus:
        self.notified_payloads += 1
        return PayloadStatus(ExecutePayloadStatus.VALID, getattr(payload, "block_hash", None))

    async def notify_forkchoice_update(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> Optional[bytes]:
        self.head = head_block_hash
        self.finalized = finalized_block_hash
        if payload_attributes is not None:
            pid = secrets.token_bytes(8)
            self._payloads[pid] = payload_attributes
            return pid
        return None

    async def get_payload(self, payload_id: bytes):
        if payload_id not in self._payloads:
            raise ValueError("unknown payloadId")
        return self._payloads.pop(payload_id)


class HttpExecutionEngine:
    """engine_* JSON-RPC client (http.ts).  Supports the jwt-secret auth
    the Engine API requires."""

    def __init__(self, url: str, jwt_secret: Optional[bytes] = None, timeout: float = 12.0):
        self.url = url
        self.jwt_secret = jwt_secret
        self.timeout = timeout
        self._id = 0

    async def _rpc(self, method: str, params):
        import aiohttp

        self._id += 1
        headers = {}
        if self.jwt_secret is not None:
            headers["Authorization"] = f"Bearer {self._jwt_token()}"
        async with aiohttp.ClientSession() as session:
            async with session.post(
                self.url,
                json={"jsonrpc": "2.0", "id": self._id, "method": method, "params": params},
                headers=headers,
                timeout=aiohttp.ClientTimeout(total=self.timeout),
            ) as resp:
                body = await resp.json()
        if "error" in body:
            raise RuntimeError(f"{method}: {body['error']}")
        return body["result"]

    def _jwt_token(self) -> str:
        """HS256 JWT with iat claim (Engine API auth spec)."""
        import base64
        import hashlib
        import hmac
        import json
        import time

        def b64(data: bytes) -> str:
            return base64.urlsafe_b64encode(data).rstrip(b"=").decode()

        header = b64(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
        payload = b64(json.dumps({"iat": int(time.time())}).encode())
        msg = f"{header}.{payload}".encode()
        sig = b64(hmac.new(self.jwt_secret, msg, hashlib.sha256).digest())
        return f"{header}.{payload}.{sig}"

    async def notify_new_payload(self, payload) -> PayloadStatus:
        result = await self._rpc("engine_newPayloadV1", [payload])
        return PayloadStatus(
            ExecutePayloadStatus(result["status"]),
            bytes.fromhex(result["latestValidHash"][2:]) if result.get("latestValidHash") else None,
            result.get("validationError"),
        )

    async def notify_forkchoice_update(
        self, head_block_hash, safe_block_hash, finalized_block_hash,
        payload_attributes=None,
    ) -> Optional[bytes]:
        fc_state = {
            "headBlockHash": "0x" + head_block_hash.hex(),
            "safeBlockHash": "0x" + safe_block_hash.hex(),
            "finalizedBlockHash": "0x" + finalized_block_hash.hex(),
        }
        result = await self._rpc(
            "engine_forkchoiceUpdatedV1", [fc_state, payload_attributes]
        )
        pid = result.get("payloadId")
        return bytes.fromhex(pid[2:]) if pid else None

    async def get_payload(self, payload_id: bytes):
        return await self._rpc("engine_getPayloadV1", ["0x" + payload_id.hex()])
