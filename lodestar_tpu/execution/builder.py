"""MEV builder API client (reference:
packages/beacon-node/src/execution/builder/http.ts; builder-specs REST).

The blinded-block flow: the validator registers fee recipients, the node
asks the builder for a header bid (getHeader), the proposer signs a
blinded block over the header, and submitBlindedBlock reveals the payload.
MockBuilder is the in-process double for tests/dev (the reference tests
against mock-builder/mergemock the same way).
"""
from __future__ import annotations

import secrets
from typing import Dict, Optional

from lodestar_tpu.execution.http_session import (
    ReusedClientSession,
    request_with_retry,
)
from lodestar_tpu.params import ForkName
from lodestar_tpu.testing import faults
from lodestar_tpu.types import ssz
from lodestar_tpu.utils import get_logger


class BuilderApiError(Exception):
    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


class HttpBuilderApi(ReusedClientSession):
    """builder-specs REST client (http.ts role).

    Idempotent calls (status, getHeader, validator registration — the
    registrations overwrite by pubkey) retry transport faults and 5xx
    with bounded backoff + jitter.  ``submit_blinded_block`` never
    retries: revealing a payload is the point-of-no-return of the
    blinded flow, and a request that died mid-flight may already have
    been accepted by the relay."""

    def __init__(self, base_url: str, timeout: float = 12.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._log = get_logger("builder")

    async def _req(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        idempotent: bool = True,
    ):
        async def send_once():
            faults.fire("execution.builder.http", method=method, path=path)
            return await self._req_once(method, path, body)

        return await request_with_retry(
            send_once,
            idempotent=idempotent,
            retryable_status=lambda e: (
                isinstance(e, BuilderApiError)
                and e.status is not None
                and e.status >= 500
            ),
            log=lambda m: self._log.warn(f"{path}: {m}"),
        )

    async def _req_once(self, method: str, path: str, body: Optional[bytes]):
        """One transport attempt (overridden by transport-free tests)."""
        import aiohttp

        session = await self._ses()
        async with session.request(
            method,
            self.base_url + path,
            data=body,
            headers={"Content-Type": "application/octet-stream"},
            timeout=aiohttp.ClientTimeout(total=self.timeout),
        ) as resp:
            data = await resp.read()
            if resp.status >= 400:
                raise BuilderApiError(f"{path}: HTTP {resp.status}", resp.status)
            return data

    async def check_status(self) -> None:
        await self._req("GET", "/eth/v1/builder/status")

    async def register_validators(self, signed_registrations) -> None:
        t = ssz.bellatrix.SignedValidatorRegistrationV1
        body = b"".join(t.serialize(r) for r in signed_registrations)
        await self._req("POST", "/eth/v1/builder/validators", body)

    async def get_header(self, slot: int, parent_hash: bytes, pubkey: bytes):
        data = await self._req(
            "GET",
            f"/eth/v1/builder/header/{slot}/0x{parent_hash.hex()}/0x{pubkey.hex()}",
        )
        return ssz.bellatrix.SignedBuilderBid.deserialize(data)

    async def submit_blinded_block(self, signed_blinded_block):
        t = type(signed_blinded_block)
        data = await self._req(
            "POST",
            "/eth/v1/builder/blinded_blocks",
            t.serialize(signed_blinded_block),
            idempotent=False,
        )
        return ssz.bellatrix.ExecutionPayload.deserialize(data)


class MockBuilder:
    """In-process builder double: bids with a payload built by the mock EL
    builder and reveals it on submission.

    With a `chain` reference (the dev/test configuration) the bid payload
    is built against the head state advanced to the bid slot, so it passes
    every process_execution_payload consistency check — the same service
    the reference gets from mock-builder/mergemock."""

    def __init__(self, value: int = 1_000_000, chain=None):
        self.value = value
        self.chain = chain
        self.registrations: Dict[bytes, object] = {}
        self._payloads: Dict[bytes, object] = {}

    async def check_status(self) -> None:
        return None

    async def register_validators(self, signed_registrations) -> None:
        for r in signed_registrations:
            self.registrations[bytes(r.message.pubkey)] = r.message

    async def get_header(self, slot: int, parent_hash: bytes, pubkey: bytes):
        from .engine import build_dev_payload, build_payload

        reg = self.registrations.get(bytes(pubkey))
        fee_recipient = bytes(reg.fee_recipient) if reg else b"\x00" * 20
        if self.chain is not None:
            from lodestar_tpu.state_transition import process_slots
            from lodestar_tpu.types import fork_of_state

            st = self.chain.get_head_state().clone()
            if st.state.slot < slot:
                process_slots(st, slot)
            payload = build_dev_payload(
                self.chain.cfg, st.state, fee_recipient=fee_recipient
            )
            fork = fork_of_state(st.state)
        else:
            payload = build_payload(
                ForkName.bellatrix,
                parent_hash=parent_hash,
                timestamp=slot,
                prev_randao=b"\x00" * 32,
                fee_recipient=fee_recipient,
                block_number=slot,
            )
            fork = ForkName.bellatrix
        mod = getattr(ssz, fork.value)
        header = mod.payload_to_header(payload)
        self._payloads[bytes(payload.block_hash)] = payload
        # fork-matched bid container so the header field's declared SSZ
        # type matches its value (serialize/HTR would otherwise use the
        # wrong layout); eip4844 reuses capella's bid shape here
        bid_mod = mod if hasattr(mod, "BuilderBid") else ssz.capella
        bid = bid_mod.BuilderBid(
            header=header, value=self.value, pubkey=b"\xaa" * 48
        )
        return bid_mod.SignedBuilderBid(message=bid, signature=b"\x00" * 96)

    async def submit_blinded_block(self, signed_blinded_block):
        h = bytes(
            signed_blinded_block.message.body.execution_payload_header.block_hash
        )
        payload = self._payloads.get(h)
        if payload is None:
            raise BuilderApiError("unknown blinded block payload")
        return payload
