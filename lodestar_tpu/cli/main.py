"""lodestar-tpu CLI entry point.

Mirrors the reference's command set (packages/cli/src/cmds: beacon,
validator, lightclient, dev).  The `dev` command runs the in-process
single-node dev chain (reference cmds/dev/: interop validators producing
and importing blocks), with BLS verification on the host oracle or the
device verifier.

NOTE: the preset is chosen by the LODESTAR_TPU_PRESET env var at import
time (like the reference's LODESTAR_PRESET compile-time switch); `dev`
defaults to minimal via the wrapper in __main__.
"""
from __future__ import annotations

import argparse
import json
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="lodestar-tpu",
        description="TPU-native Ethereum consensus client",
    )
    sub = parser.add_subparsers(dest="command")
    sub.add_parser("version", help="print version and exit")

    dev = sub.add_parser(
        "dev", help="run a single-node interop dev chain in-process"
    )
    dev.add_argument("--validators", type=int, default=8, help="interop validator count")
    dev.add_argument("--slots", type=int, default=None, help="stop after N slots (default: run forever)")
    dev.add_argument(
        "--verifier",
        choices=["none", "oracle", "device"],
        default="oracle",
        help="BLS verification backend for block import",
    )
    dev.add_argument(
        "--realtime",
        action="store_true",
        help="tick wall-clock slots (SECONDS_PER_SLOT) instead of running flat out",
    )
    dev.add_argument("--genesis-time", type=int, default=None)
    return parser


def run_dev(args) -> int:
    from lodestar_tpu.chain.dev import DevChain
    from lodestar_tpu.config import default_chain_config as cfg
    from lodestar_tpu.params import ACTIVE_PRESET_NAME, SLOTS_PER_EPOCH
    from lodestar_tpu.types import ssz

    genesis_time = args.genesis_time if args.genesis_time is not None else int(time.time())
    print(
        f"dev chain: preset={ACTIVE_PRESET_NAME} validators={args.validators} "
        f"verifier={args.verifier}",
        flush=True,
    )
    chain = DevChain(cfg, args.validators, genesis_time=genesis_time)
    print(
        f"genesis state root {chain.head.hash_tree_root().hex()} "
        f"(slots/epoch={SLOTS_PER_EPOCH})",
        flush=True,
    )

    verifier = None
    verify = args.verifier != "none"
    if args.verifier == "device":
        from lodestar_tpu.chain.bls import DeviceBlsVerifier

        verifier = DeviceBlsVerifier()

    slot = 0
    try:
        while args.slots is None or slot < args.slots:
            slot += 1
            if args.realtime:
                target = genesis_time + slot * cfg.SECONDS_PER_SLOT
                while time.time() < target:
                    time.sleep(min(0.25, max(0.0, target - time.time())))
            t0 = time.time()
            imported = chain.run_slot(slot, verifier, verify_signatures=verify)
            st = chain.head.state
            print(
                json.dumps(
                    {
                        "slot": slot,
                        "root": imported.root.hex()[:16],
                        "attestations": len(
                            imported.block.message.body.attestations
                        ),
                        "justified": st.current_justified_checkpoint.epoch,
                        "finalized": st.finalized_checkpoint.epoch,
                        "verified_sets": chain.verified_set_count,
                        "ms": round((time.time() - t0) * 1e3),
                    }
                ),
                flush=True,
            )
    except KeyboardInterrupt:
        pass
    st = chain.head.state
    print(
        f"stopped at slot {st.slot}: justified={st.current_justified_checkpoint.epoch} "
        f"finalized={st.finalized_checkpoint.epoch} "
        f"verified_sets={chain.verified_set_count}",
        flush=True,
    )
    return 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command == "version":
        from importlib.metadata import PackageNotFoundError, version

        try:
            print(version("lodestar-tpu"))
        except PackageNotFoundError:
            print("0.2.0 (uninstalled tree)")
        return 0
    if args.command == "dev":
        return run_dev(args)
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
