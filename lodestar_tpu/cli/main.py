"""lodestar-tpu CLI entry point.

Mirrors the reference's command set (cli/src/cmds: beacon, validator,
lightclient, dev); commands are registered as subsystems land.
"""
from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="lodestar",
        description="TPU-native Ethereum consensus client",
    )
    sub = parser.add_subparsers(dest="command")
    sub.add_parser("version", help="print version and exit")
    return parser


def main(argv: list[str] | None = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command == "version":
        from importlib.metadata import PackageNotFoundError, version

        try:
            print(version("lodestar-tpu"))
        except PackageNotFoundError:
            print("0.1.0 (uninstalled tree)")
        return 0
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
