"""lodestar-tpu CLI entry point.

Mirrors the reference's command set (packages/cli/src/cmds: beacon,
validator, lightclient, dev).  The `dev` command runs the in-process
single-node dev chain (reference cmds/dev/: interop validators producing
and importing blocks), with BLS verification on the host oracle or the
device verifier.

NOTE: the preset is chosen by the LODESTAR_TPU_PRESET env var at import
time (like the reference's LODESTAR_PRESET compile-time switch); `dev`
defaults to minimal via the wrapper in __main__.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="lodestar-tpu",
        description="TPU-native Ethereum consensus client",
    )
    sub = parser.add_subparsers(dest="command")
    sub.add_parser("version", help="print version and exit")

    dev = sub.add_parser(
        "dev", help="run a single-node interop dev chain in-process"
    )
    dev.add_argument("--validators", type=int, default=8, help="interop validator count")
    dev.add_argument("--slots", type=int, default=None, help="stop after N slots (default: run forever)")
    dev.add_argument(
        "--verifier",
        choices=["auto", "none", "oracle", "device"],
        default="auto",
        help="BLS verification backend for block import (auto = device "
             "when an accelerator is present, oracle otherwise)",
    )
    dev.add_argument(
        "--realtime",
        action="store_true",
        help="tick wall-clock slots (SECONDS_PER_SLOT) instead of running flat out",
    )
    dev.add_argument("--genesis-time", type=int, default=None)

    beacon = sub.add_parser(
        "beacon",
        help="run a beacon node: chain + REST API + metrics (cmds/beacon)",
    )
    beacon.add_argument("--validators", type=int, default=8,
                        help="interop validator count for the genesis state")
    beacon.add_argument("--genesis-time", type=int, default=None)
    beacon.add_argument("--checkpoint-state", type=str, default=None,
                        help="weak-subjectivity start: fork-tagged SSZ BeaconState file "
                             "(initBeaconState.ts checkpoint-sync role)")
    beacon.add_argument("--checkpoint-sync-url", type=str, default=None,
                        help="weak-subjectivity start: fetch the finalized state from "
                             "another node's REST API (fetchWeakSubjectivityState role)")
    beacon.add_argument("--rest-port", type=int, default=9596)
    beacon.add_argument("--metrics-port", type=int, default=8008)
    beacon.add_argument(
        "--verifier", choices=["auto", "oracle", "device"], default="auto"
    )
    beacon.add_argument(
        "--bls-pool-url", type=str, default=None,
        help="BLS sidecar endpoint (python -m lodestar_tpu.blspool "
             "serve); the node verifies through the shared pool via "
             "RemoteBlsVerifier and degrades to its local host oracle "
             "if the sidecar is unreachable — overrides --verifier",
    )
    beacon.add_argument("--slots", type=int, default=None,
                        help="exit after N clock slots (default: run forever)")
    # live execution-layer seam (execution/engine.py + eth1/http_provider.py):
    # without these flags the node keeps its default in-process behavior
    beacon.add_argument("--execution-url", type=str, default=None,
                        help="Engine API JSON-RPC endpoint of an execution "
                             "client (e.g. http://127.0.0.1:8551); enables "
                             "fork-versioned newPayload/forkchoiceUpdated/"
                             "getPayload against a real EL")
    beacon.add_argument("--jwt-secret-file", type=str, default=None,
                        help="hex file holding the 32-byte Engine API JWT "
                             "secret shared with the execution client")
    beacon.add_argument("--eth1-url", type=str, default=None,
                        help="eth1 JSON-RPC endpoint for deposit tracking "
                             "(eth_getLogs over the deposit contract)")
    beacon.add_argument("--deposit-contract", type=str, default=None,
                        help="deposit contract address for --eth1-url log "
                             "filtering (default: the mainnet contract)")
    # wire networking (libp2p TCP+noise+gossipsub role; network/wire.py)
    beacon.add_argument("--listen-host", type=str, default="127.0.0.1",
                        help="bind address for TCP + UDP networking")
    beacon.add_argument("--advertise-ip", type=str, default=None,
                        help="IPv4 advertised in the ENR "
                             "(default: --listen-host)")
    beacon.add_argument("--listen-port", type=int, default=0,
                        help="TCP wire-transport port (0 = ephemeral)")
    beacon.add_argument("--discovery-port", type=int, default=0,
                        help="UDP discovery port (0 = ephemeral)")
    beacon.add_argument("--bootnode-enr", action="append", default=[],
                        help="hex SSZ ENR of a bootnode (repeatable)")
    beacon.add_argument("--target-peers", type=int, default=8)

    val = sub.add_parser(
        "validator",
        help="run a validator client against a beacon REST endpoint",
    )
    val.add_argument("--beacon-url", type=str, default="http://127.0.0.1:9596")
    val.add_argument("--interop-indices", type=str, default="0..7",
                     help="interop key range LO..HI (inclusive)")
    val.add_argument("--slots", type=int, default=None)

    lc = sub.add_parser(
        "lightclient",
        help="follow the chain with the altair light client over REST",
    )
    lc.add_argument("--beacon-url", type=str, default="http://127.0.0.1:9596")
    lc.add_argument("--checkpoint-root", type=str, required=False,
                    help="trusted block root hex (default: the node's finalized root)")
    lc.add_argument("--updates", type=int, default=4,
                    help="stop after N processed updates")

    # validator ops subcommands (cmds/validator/{voluntaryExit,
    # slashingProtection}) — separate top-level verbs for argparse clarity
    vexit = sub.add_parser("validator-exit", help="sign + submit a voluntary exit")
    vexit.add_argument("--beacon-url", type=str, default="http://127.0.0.1:9596")
    vexit.add_argument("--index", type=int, required=True, help="validator index (interop key)")
    vexit.add_argument("--epoch", type=int, default=None, help="exit epoch (default: current)")

    sp_exp = sub.add_parser(
        "slashing-protection-export", help="write the EIP-3076 interchange file"
    )
    sp_exp.add_argument("--db", type=str, required=True, help="slashing protection sqlite db FILE path")
    sp_exp.add_argument("--file", type=str, required=True)
    sp_exp.add_argument("--genesis-validators-root", type=str, required=True)
    sp_exp.add_argument("--pubkeys", type=str, default="", help="comma-separated hex pubkeys")

    sp_imp = sub.add_parser(
        "slashing-protection-import", help="merge an EIP-3076 interchange file"
    )
    sp_imp.add_argument("--db", type=str, required=True)
    sp_imp.add_argument("--file", type=str, required=True)
    sp_imp.add_argument("--genesis-validators-root", type=str, required=True)

    aot = sub.add_parser(
        "aot",
        help="AOT compile-cache tooling: warm/check the persistent BLS "
        "program cache (same as python -m lodestar_tpu.aot)",
    )
    aot.add_argument(
        "aot_args",
        nargs=argparse.REMAINDER,
        help="arguments for the aot tool, e.g. `warm`, `warm --check`",
    )

    flare = sub.add_parser(
        "flare", help="ops/debug tooling: craft self-slashings for OWNED devnet keys"
    )
    flare.add_argument("action", choices=["self-slash-attester", "self-slash-proposer"])
    flare.add_argument("--beacon-url", type=str, default="http://127.0.0.1:9596")
    flare.add_argument("--index", type=int, required=True, help="interop validator index")
    flare.add_argument("--epoch", type=int, default=0)

    # --network / --param on every subcommand (the reference's
    # `--network sepolia` + `--params.ALTAIR_FORK_EPOCH=0` yargs flags,
    # cli/src/options/{globalOptions,paramsOptions}.ts + cli/src/networks/)
    for p in sub.choices.values():
        p.add_argument(
            "--network",
            type=str,
            default=None,
            help="named network bundle (mainnet, sepolia, goerli): chain "
                 "config + genesis anchors from lodestar_tpu.networks",
        )
        p.add_argument(
            "--param",
            action="append",
            default=[],
            metavar="KEY=VALUE",
            help="chain config override, e.g. --param ALTAIR_FORK_EPOCH=0",
        )
    return parser


def resolve_chain_config(args):
    """--network bundle (if any) + any --param overrides."""
    from lodestar_tpu.config import chain_config_from_dict, default_chain_config

    base = default_chain_config
    network = getattr(args, "network", None)
    if network:
        from lodestar_tpu.params import ACTIVE_PRESET_NAME
        from lodestar_tpu.networks import get_network

        try:
            bundle = get_network(network)
        except ValueError as e:
            raise SystemExit(str(e)) from None
        if bundle.chain_config.PRESET_BASE != ACTIVE_PRESET_NAME:
            raise SystemExit(
                f"--network {network} needs the "
                f"{bundle.chain_config.PRESET_BASE} preset "
                f"(set LODESTAR_TPU_PRESET={bundle.chain_config.PRESET_BASE})"
            )
        base = bundle.chain_config
    overrides = {}
    for kv in getattr(args, "param", []) or []:
        if "=" not in kv:
            raise SystemExit(f"--param expects KEY=VALUE, got {kv!r}")
        k, v = kv.split("=", 1)
        overrides[k] = v
    if not overrides:
        return base
    import dataclasses

    known = {f.name for f in dataclasses.fields(type(base))}
    unknown = set(overrides) - known
    if unknown:
        raise SystemExit(f"unknown --param key(s): {', '.join(sorted(unknown))}")
    return chain_config_from_dict(overrides, base=base)


def resolve_verifier_choice(choice: str) -> str:
    """'auto' -> 'device' when an accelerator backend is live, else
    'oracle'.  A TPU-native node defaults to its device path (VERDICT r3
    weak #5: the reverse default made every unflagged run unusable at
    gossip rates); hosts without an accelerator (tests, CI, laptops)
    still get a working node."""
    if choice != "auto":
        return choice
    try:
        import jax

        if jax.default_backend() in ("tpu", "gpu"):
            return "device"
    # an unusable/missing accelerator backend IS the probe's "oracle"
    # answer — nothing to surface
    except Exception:  # lodelint: disable=silent-except
        pass
    return "oracle"


def load_jwt_secret(path: str) -> bytes:
    """Engine API JWT secret file: 32 bytes of hex (geth/nethermind
    jwt.hex format, optional 0x prefix + trailing newline)."""
    with open(path) as f:
        text = f.read().strip()
    try:
        secret = bytes.fromhex(text.removeprefix("0x"))
    except ValueError:
        raise SystemExit(f"--jwt-secret-file {path}: not hex") from None
    if len(secret) != 32:
        raise SystemExit(
            f"--jwt-secret-file {path}: expected 32 bytes, got {len(secret)}"
        )
    return secret


def build_execution_engine(args, metrics=None):
    """--execution-url/--jwt-secret-file → HttpExecutionEngine, or None
    without the flag (the default in-process behavior is unchanged).
    Separated from run_beacon so construction is testable offline."""
    url = getattr(args, "execution_url", None)
    if not url:
        return None
    from lodestar_tpu.execution.engine import HttpExecutionEngine

    secret = None
    if getattr(args, "jwt_secret_file", None):
        secret = load_jwt_secret(args.jwt_secret_file)
    return HttpExecutionEngine(url, jwt_secret=secret, metrics=metrics)


def build_eth1_provider(args):
    """--eth1-url → HttpEth1Provider feeding the deposit tracker, or
    None without the flag."""
    url = getattr(args, "eth1_url", None)
    if not url:
        return None
    from lodestar_tpu.eth1.http_provider import (
        MAINNET_DEPOSIT_CONTRACT,
        HttpEth1Provider,
    )

    contract = getattr(args, "deposit_contract", None) or MAINNET_DEPOSIT_CONTRACT
    return HttpEth1Provider(url, deposit_contract=contract)


def run_dev(args) -> int:
    from lodestar_tpu.chain.dev import DevChain

    cfg = resolve_chain_config(args)
    from lodestar_tpu.params import ACTIVE_PRESET_NAME, SLOTS_PER_EPOCH
    from lodestar_tpu.types import ssz

    genesis_time = args.genesis_time if args.genesis_time is not None else int(time.time())
    args.verifier = resolve_verifier_choice(args.verifier)
    print(
        f"dev chain: preset={ACTIVE_PRESET_NAME} validators={args.validators} "
        f"verifier={args.verifier}",
        flush=True,
    )
    chain = DevChain(cfg, args.validators, genesis_time=genesis_time)
    print(
        f"genesis state root {chain.head.hash_tree_root().hex()} "
        f"(slots/epoch={SLOTS_PER_EPOCH})",
        flush=True,
    )

    verifier = None
    verify = args.verifier != "none"
    if args.verifier == "device":
        from lodestar_tpu.chain.bls import DeviceBlsVerifier

        verifier = DeviceBlsVerifier()

    slot = 0
    try:
        while args.slots is None or slot < args.slots:
            slot += 1
            if args.realtime:
                target = genesis_time + slot * cfg.SECONDS_PER_SLOT
                while time.time() < target:
                    time.sleep(min(0.25, max(0.0, target - time.time())))
            t0 = time.time()
            imported = chain.run_slot(slot, verifier, verify_signatures=verify)
            st = chain.head.state
            print(
                json.dumps(
                    {
                        "slot": slot,
                        "root": imported.root.hex()[:16],
                        "attestations": len(
                            imported.block.message.body.attestations
                        ),
                        "justified": st.current_justified_checkpoint.epoch,
                        "finalized": st.finalized_checkpoint.epoch,
                        "verified_sets": chain.verified_set_count,
                        "ms": round((time.time() - t0) * 1e3),
                    }
                ),
                flush=True,
            )
    except KeyboardInterrupt:
        pass
    st = chain.head.state
    print(
        f"stopped at slot {st.slot}: justified={st.current_justified_checkpoint.epoch} "
        f"finalized={st.finalized_checkpoint.epoch} "
        f"verified_sets={chain.verified_set_count}",
        flush=True,
    )
    return 0


def run_beacon(args) -> int:
    """Beacon node process (cmds/beacon/handler.ts role): chain + REST API
    + metrics + archiver + light-client server, driven by the wall clock.
    Block production/attestation comes from `validator` processes over
    REST."""
    import asyncio

    from lodestar_tpu.api.server import BeaconRestApiServer
    from lodestar_tpu.chain.archiver import Archiver
    from lodestar_tpu.chain.chain import BeaconChain
    from lodestar_tpu.chain.light_client_server import LightClientServer
    cfg = resolve_chain_config(args)
    from lodestar_tpu.db import BeaconDb
    from lodestar_tpu.metrics import Metrics
    from lodestar_tpu.metrics.server import HttpMetricsServer
    from lodestar_tpu.state_transition.util.genesis import init_dev_state

    # named networks supply genesis anchors + default checkpoint
    # providers (cli/src/networks role)
    bundle = None
    if getattr(args, "network", None):
        from lodestar_tpu.networks import get_network

        bundle = get_network(args.network)
        if not getattr(args, "checkpoint_sync_url", None) and not args.checkpoint_state:
            if bundle.checkpoint_sync_urls:
                print(
                    f"note: --network {bundle.name} nodes normally start from "
                    f"a checkpoint provider, e.g. {bundle.checkpoint_sync_urls[0]} "
                    "(pass --checkpoint-sync-url); falling back to a dev genesis",
                    flush=True,
                )

    def _check_bundle_anchor(anchor_state) -> None:
        """A checkpoint state for --network X must belong to network X
        (wrong-network anchors silently build an unusable node)."""
        if bundle is None:
            return
        gvr = bytes(anchor_state.genesis_validators_root)
        # deployed-network anchors must match the recorded root; dev/test
        # fixtures (self-genesis'd states) are identified by config match
        if gvr != bundle.genesis_validators_root and (
            bytes(anchor_state.fork.current_version)[:4]
            not in (
                bundle.chain_config.GENESIS_FORK_VERSION,
                bundle.chain_config.ALTAIR_FORK_VERSION,
                bundle.chain_config.BELLATRIX_FORK_VERSION,
                bundle.chain_config.CAPELLA_FORK_VERSION,
            )
        ):
            raise SystemExit(
                f"checkpoint state is not a {bundle.name} state "
                f"(genesis_validators_root {gvr.hex()} and fork version "
                f"{bytes(anchor_state.fork.current_version).hex()} match "
                "neither the network's root nor its fork schedule)"
            )

    if args.checkpoint_state:
        # weak-subjectivity start (initBeaconState.ts checkpoint sync)
        from lodestar_tpu.db.beacon import _STATE_MF

        anchor = _STATE_MF.deserialize(open(args.checkpoint_state, "rb").read())
        _check_bundle_anchor(anchor)
        print(f"checkpoint sync: anchor slot {anchor.slot}", flush=True)
    elif getattr(args, "checkpoint_sync_url", None):
        # fetch the trusted node's finalized state over REST
        # (networks/index.ts fetchWeakSubjectivityState)
        from lodestar_tpu.api.client import ApiClient

        async def _fetch():
            client = ApiClient(args.checkpoint_sync_url)
            try:
                return await client.get_state_ssz("finalized")
            finally:
                await client.close()

        anchor = asyncio.run(_fetch())
        _check_bundle_anchor(anchor)
        print(
            f"checkpoint sync from {args.checkpoint_sync_url}: "
            f"anchor slot {anchor.slot}",
            flush=True,
        )
    else:
        genesis_time = (
            args.genesis_time if args.genesis_time is not None else int(time.time())
        )
        _, anchor = init_dev_state(cfg, args.validators, genesis_time=genesis_time)

    verifier = None
    if getattr(args, "bls_pool_url", None):
        # shared-pool tenancy (docs/BLSPOOL.md): verification rides the
        # sidecar; the RemoteBlsVerifier's own ladder falls back to the
        # local host oracle if the sidecar goes away
        from lodestar_tpu.blspool import RemoteBlsVerifier
        from lodestar_tpu.blspool.http import HttpPoolTransport

        verifier = RemoteBlsVerifier(
            HttpPoolTransport(args.bls_pool_url),
            tenant=f"beacon-{os.getpid()}",
        )
        print(f"bls verification: sidecar {args.bls_pool_url}", flush=True)
    elif resolve_verifier_choice(args.verifier) == "device":
        from lodestar_tpu.chain.bls import DeviceBlsVerifier

        verifier = DeviceBlsVerifier()

    metrics = Metrics()
    # live execution seam (default None: in-process behavior unchanged);
    # the chain owns the engine's shutdown (chain.close())
    execution_engine = build_execution_engine(args, metrics=metrics.lodestar)
    eth1_provider = build_eth1_provider(args)
    chain = BeaconChain(
        cfg, BeaconDb(), anchor, verifier=verifier, metrics=metrics,
        execution_engine=execution_engine,
    )
    Archiver(chain)
    lc_server = LightClientServer(chain)
    api = BeaconRestApiServer(
        chain, chain.db, light_client_server=lc_server
    )

    async def run():
        from aiohttp import web

        runner = web.AppRunner(api.app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", args.rest_port)
        await site.start()
        msrv = HttpMetricsServer(metrics, port=args.metrics_port)
        await msrv.start()

        # -- wire networking: TCP transport + gossip mesh + UDP discovery
        # (network.ts + peerManager + discv5 roles) ---------------------
        from lodestar_tpu.config import compute_fork_digest
        from lodestar_tpu.network.discovery import (
            ENR,
            DiscoveryService,
            LocalIdentity,
            UdpEndpoint,
        )
        from lodestar_tpu.network.network import Network
        from lodestar_tpu.network.wire import WireTransport
        from lodestar_tpu.crypto.bls.api import SecretKey
        from lodestar_tpu.utils import Logger

        log = Logger("beacon").child("network")
        advertise_ip = args.advertise_ip or args.listen_host
        wire = WireTransport()
        tcp_port = await wire.listen(args.listen_host, args.listen_port)
        network = Network(None, chain, chain.db, endpoint=wire)
        network.subscribe_core_topics()
        api.network = network  # REST submissions now publish to gossip

        udp = UdpEndpoint()
        svc_box = {}

        async def on_dgram(from_addr, data):
            svc = svc_box.get("svc")
            if svc is not None:
                await svc.on_datagram(from_addr, data)

        await udp.open(args.listen_host, args.discovery_port, on_dgram)
        udp_port = udp._transport.get_extra_info("sockname")[1]
        identity = LocalIdentity(
            secret_key=SecretKey.key_gen(os.urandom(32)),
            ip=bytes(int(x) for x in advertise_ip.split(".")),
            udp_port=udp_port,
            tcp_port=tcp_port,
            fork_digest=compute_fork_digest(
                chain.cfg.GENESIS_FORK_VERSION, chain.genesis_validators_root
            ),
        )
        discovery = DiscoveryService(identity, udp.send)
        svc_box["svc"] = discovery
        for enr_hex in args.bootnode_enr:
            discovery.add_bootnode(ENR.deserialize(bytes.fromhex(enr_hex)))
        discovery_task = asyncio.ensure_future(discovery.start())

        # (host, tcp_port) -> peer_id: a discovered ENR we're already
        # connected to must NOT be re-dialed — with the wire transport a
        # fresh dial supersedes the live connection and churns the
        # gossip mesh (r4 review finding)
        dialed: dict = {}

        async def resolve_peer(enr):
            ip = bytes(enr.content.ip)
            host = f"{ip[0]}.{ip[1]}.{ip[2]}.{ip[3]}"
            key = (host, int(enr.content.tcp_port))
            pid = dialed.get(key)
            if pid is not None and pid in wire.conns:
                return pid
            try:
                pid = await wire.dial(*key)
            except (OSError, ConnectionError, asyncio.TimeoutError):
                return None
            dialed[key] = pid
            return pid

        network.attach_discovery(discovery, resolve_peer)

        print(
            f"beacon node up: REST :{args.rest_port} metrics :{args.metrics_port} "
            f"p2p tcp :{tcp_port} udp :{udp_port} "
            f"genesis_time={chain.genesis_time}",
            flush=True,
        )
        print(
            json.dumps({"enr": ENR.serialize(identity.to_enr()).hex()}),
            flush=True,
        )

        async def network_maintenance():
            """Heartbeat: peer top-up from discovery + range-sync when a
            peer's status is ahead of our head (sync/range_sync role)."""
            from lodestar_tpu.sync.range_sync import RangeSync

            while True:
                try:
                    await network.heartbeat(args.target_peers)
                    head_slot = chain.fork_choice.get_head().slot
                    for pid, peer in list(network.peer_manager.peers.items()):
                        status = getattr(peer, "status", None)
                        if status is not None and status.head_slot > head_slot:
                            await RangeSync(network, chain).sync()
                            break
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    log.warn(f"network maintenance error: {e!r}")
                await asyncio.sleep(2.0)

        maintenance_task = asyncio.ensure_future(network_maintenance())

        # -- live execution seam: capability probe + eth1 deposit follow
        engine_probe_task = None
        if execution_engine is not None:
            async def probe_engine():
                """engine_exchangeCapabilities at connect (Engine API
                handshake); a down EL must not kill the node — the
                engine client retries per call anyway."""
                try:
                    caps = await execution_engine.exchange_capabilities()
                    log.info(f"engine capabilities: {len(caps)} methods")
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    log.warn(f"engine capability probe failed: {e!r}")

            engine_probe_task = asyncio.ensure_future(probe_engine())

        eth1_task = None
        if eth1_provider is not None:
            from lodestar_tpu.eth1 import Eth1DepositDataTracker

            eth1_tracker = Eth1DepositDataTracker(eth1_provider, cfg, db=chain.db)

            async def eth1_follow():
                """Deposit tracking loop (eth1DepositDataTracker.ts
                runAutoUpdate role): pull new blocks + DepositEvent logs,
                export sync lag + ingestion counters."""
                poll = max(2.0, float(cfg.SECONDS_PER_ETH1_BLOCK) / 2)

                def set_lag(head: int) -> None:
                    metrics.lodestar.eth1_sync_lag_blocks.set(
                        max(0, head - eth1_tracker._synced_to)
                    )

                while True:
                    try:
                        # measure lag BEFORE ingesting so a failing
                        # update() still leaves the real (growing) lag
                        # on the gauge — a stalled deposit sync must be
                        # visible, not frozen at 0 (test_dashboards pin)
                        head = await eth1_provider.get_block_number()
                        set_lag(head)
                        n = await eth1_tracker.update()
                        if n:
                            metrics.lodestar.eth1_deposit_events_total.inc(n)
                        set_lag(head)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        log.warn(f"eth1 follow error: {e!r}")
                    await asyncio.sleep(poll)

            eth1_task = asyncio.ensure_future(eth1_follow())
            # block production reads votes/deposits via chain.eth1
            # (api/server.py:615 produceBlock path)
            chain.eth1 = eth1_tracker

        # periodic status logline on stderr (node/notifier.ts:29)
        from lodestar_tpu.node import run_node_notifier

        notifier_task = asyncio.ensure_future(run_node_notifier(chain))
        last_slot = -1
        try:
            while True:
                slot = chain.clock.current_slot
                if slot > last_slot:
                    last_slot = slot
                    chain.fork_choice.update_time(max(slot, 0))
                    metrics.beacon.clock_slot.set(slot)
                    if execution_engine is not None:
                        # per-slot forkchoiceUpdated: keeps the EL's head
                        # current and consumes its verdict (VALID
                        # de-optimisticizes, INVALID prunes).  The chain
                        # method never raises for a dead EL, but nothing
                        # an EL sends may kill the clock loop either.
                        try:
                            await chain.notify_forkchoice_to_engine()
                        except asyncio.CancelledError:
                            raise
                        except Exception as e:
                            log.warn(
                                f"forkchoiceUpdated tick failed: {e!r}"
                            )
                    st = chain.fork_choice.store
                    print(
                        json.dumps(
                            {
                                "slot": slot,
                                "head": chain.head_root.hex()[:16],
                                "justified": st.justified.epoch,
                                "finalized": st.finalized.epoch,
                                "peers": len(
                                    network.peer_manager.connected_peers()
                                ),
                            }
                        ),
                        flush=True,
                    )
                    if args.slots is not None and slot >= args.slots:
                        break
                await asyncio.sleep(0.2)
        finally:
            notifier_task.cancel()
            maintenance_task.cancel()
            discovery_task.cancel()
            if engine_probe_task is not None:
                engine_probe_task.cancel()
            if eth1_task is not None:
                eth1_task.cancel()
            if eth1_provider is not None:
                await eth1_provider.close()
            await discovery.stop()
            udp.close()
            network.close()
            await msrv.close()
            await runner.cleanup()
            await chain.close()

    asyncio.run(run())
    return 0


def run_validator(args) -> int:
    """Validator client process (cmds/validator): duties over REST."""
    import asyncio

    from lodestar_tpu.api.client import ApiClient
    from lodestar_tpu.config import ForkConfig

    cfg = resolve_chain_config(args)
    from lodestar_tpu.state_transition.util.interop import interop_secret_keys
    from lodestar_tpu.validator.validator import Validator
    from lodestar_tpu.validator.validator_store import ValidatorStore

    lo, hi = args.interop_indices.split("..")
    count = int(hi) + 1
    sks = interop_secret_keys(count)[int(lo) :]

    async def run():
        # close the REST session and SSE tracker on every exit path (an
        # ApiError mid-slot otherwise leaks both, and the node side then
        # waits out aiohttp's shutdown grace on the dead connections)
        api = ApiClient(args.beacon_url)
        tracker = None
        try:
            genesis0 = await api.get_genesis()
            gvr = bytes.fromhex(genesis0["genesis_validators_root"][2:])
            store = ValidatorStore(sks, ForkConfig(cfg), gvr)
            from lodestar_tpu.validator.chain_header_tracker import (
                ChainHeaderTracker,
            )

            tracker = ChainHeaderTracker(args.beacon_url)
            await tracker.start()
            v = Validator(api, store, header_tracker=tracker)
            await v.initialize()
            print(
                f"validator client: {len(sks)} keys -> {args.beacon_url}",
                flush=True,
            )
            genesis_time = int(genesis0["genesis_time"])
            slot = 0
            while args.slots is None or slot < args.slots:
                slot += 1
                target = genesis_time + slot * cfg.SECONDS_PER_SLOT
                while time.time() < target:
                    await asyncio.sleep(0.1)
                await v.run_slot(slot)
                print(
                    json.dumps(
                        {
                            "slot": slot,
                            "proposed": v.produced_blocks,
                            "attested": v.produced_attestations,
                            "aggregated": v.produced_aggregates,
                            "sync_messages": v.produced_sync_messages,
                            "sync_contributions": v.produced_sync_contributions,
                        }
                    ),
                    flush=True,
                )
        finally:
            try:
                if tracker is not None:
                    await tracker.stop()
            finally:
                await api.close()

    asyncio.run(run())
    return 0


def run_lightclient(args) -> int:
    """Light client follower (cmds/lightclient): bootstrap from a trusted
    root, then track finality/optimistic updates over REST."""
    import asyncio

    from lodestar_tpu.api.client import ApiClient
    cfg = resolve_chain_config(args)
    from lodestar_tpu.light_client import LightClient
    from lodestar_tpu.ssz.json import from_json
    from lodestar_tpu.types import ssz

    async def run():
        api = ApiClient(args.beacon_url)
        try:
            genesis = await api.get_genesis()
            gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
            if args.checkpoint_root:
                root = bytes.fromhex(args.checkpoint_root.replace("0x", ""))
            else:
                cp = await api.get_json(
                    "/eth/v1/beacon/states/head/finality_checkpoints"
                )
                root = bytes.fromhex(cp["finalized"]["root"][2:])
                if root == b"\x00" * 32:
                    hdr = await api.get_json("/eth/v1/beacon/headers/head")
                    root = bytes.fromhex(hdr["root"][2:])
            bs_json = await api.get_json(
                f"/eth/v1/beacon/light_client/bootstrap/0x{root.hex()}"
            )
            bootstrap = from_json(ssz.altair.LightClientBootstrap, bs_json)
            lc = LightClient.initialize_from_checkpoint_root(cfg, gvr, root, bootstrap)
            print(
                f"light client bootstrapped at slot {lc.store.finalized_header.slot}",
                flush=True,
            )
            processed = 0
            seen_sigs = set()
            while processed < args.updates:
                try:
                    fu_json = await api.get_json(
                        "/eth/v1/beacon/light_client/finality_update"
                    )
                    fu = from_json(ssz.altair.LightClientFinalityUpdate, fu_json)
                    key = (fu.signature_slot, fu.attested_header.slot)
                    if key not in seen_sigs:
                        seen_sigs.add(key)
                        lc.process_finality_update(fu)
                        processed += 1
                        print(
                            json.dumps(
                                {
                                    "finalized_slot": lc.store.finalized_header.slot,
                                    "optimistic_slot": lc.store.optimistic_header.slot,
                                }
                            ),
                            flush=True,
                        )
                except Exception as e:  # not yet available — keep polling
                    if "404" not in str(e):
                        raise
                await asyncio.sleep(1.0)
        finally:
            await api.close()

    asyncio.run(run())
    return 0


def run_validator_exit(args) -> int:
    import asyncio

    from lodestar_tpu.api.client import ApiClient
    from lodestar_tpu.config import ForkConfig

    cfg = resolve_chain_config(args)
    from lodestar_tpu.state_transition.util.interop import interop_secret_keys
    from lodestar_tpu.validator.validator_store import ValidatorStore

    async def run():
        api = ApiClient(args.beacon_url)
        try:
            genesis = await api.get_genesis()
            gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
            sk = interop_secret_keys(args.index + 1)[args.index]
            store = ValidatorStore([sk], ForkConfig(cfg), gvr)
            if args.epoch is not None:
                epoch = args.epoch
            else:
                from lodestar_tpu.params import SLOTS_PER_EPOCH

                genesis_time = int(genesis["genesis_time"])
                epoch = max(
                    0, int((time.time() - genesis_time) / cfg.SECONDS_PER_SLOT)
                ) // SLOTS_PER_EPOCH
            signed = store.sign_voluntary_exit(
                sk.to_public_key().to_bytes(), args.index, epoch
            )
            await api.submit_voluntary_exit(signed)
        finally:
            await api.close()
        print(json.dumps({"submitted_exit": args.index, "epoch": epoch}))

    asyncio.run(run())
    return 0


def run_slashing_protection(args, export: bool) -> int:
    from lodestar_tpu.db.controller import SqliteController
    from lodestar_tpu.validator.slashing_protection import SlashingProtection

    gvr = bytes.fromhex(args.genesis_validators_root.replace("0x", ""))
    sp = SlashingProtection(SqliteController(args.db))
    if export:
        pubkeys = [
            bytes.fromhex(p.replace("0x", ""))
            for p in args.pubkeys.split(",")
            if p
        ]
        obj = sp.export_interchange(gvr, pubkeys)
        with open(args.file, "w") as f:
            json.dump(obj, f, indent=2)
        print(f"exported {len(pubkeys)} keys -> {args.file}")
    else:
        with open(args.file) as f:
            sp.import_interchange(json.load(f), gvr)
        print(f"imported interchange from {args.file}")
    return 0


def run_flare(args) -> int:
    import asyncio

    from lodestar_tpu.api.client import ApiClient
    cfg = resolve_chain_config(args)
    from lodestar_tpu.flare import (
        make_self_attester_slashing,
        make_self_proposer_slashing,
    )
    from lodestar_tpu.state_transition.util.interop import interop_secret_keys

    async def run():
        api = ApiClient(args.beacon_url)
        try:
            genesis = await api.get_genesis()
            gvr = bytes.fromhex(genesis["genesis_validators_root"][2:])
            sk = interop_secret_keys(args.index + 1)[args.index]
            if args.action == "self-slash-attester":
                s = make_self_attester_slashing(
                    cfg, gvr, sk, args.index, args.epoch
                )
                await api.submit_attester_slashing(s)
            else:
                from lodestar_tpu.params import SLOTS_PER_EPOCH

                s = make_self_proposer_slashing(
                    cfg, gvr, sk, args.index, args.epoch * SLOTS_PER_EPOCH + 1
                )
                await api.submit_proposer_slashing(s)
        finally:
            await api.close()
        print(json.dumps({"submitted": args.action, "index": args.index}))

    asyncio.run(run())
    return 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command == "version":
        from importlib.metadata import PackageNotFoundError, version

        try:
            print(version("lodestar-tpu"))
        except PackageNotFoundError:
            print("0.2.0 (uninstalled tree)")
        return 0
    if args.command == "dev":
        return run_dev(args)
    if args.command == "beacon":
        return run_beacon(args)
    if args.command == "validator":
        return run_validator(args)
    if args.command == "lightclient":
        return run_lightclient(args)
    if args.command == "validator-exit":
        return run_validator_exit(args)
    if args.command == "slashing-protection-export":
        return run_slashing_protection(args, export=True)
    if args.command == "slashing-protection-import":
        return run_slashing_protection(args, export=False)
    if args.command == "flare":
        return run_flare(args)
    if args.command == "aot":
        from lodestar_tpu.aot.__main__ import main as aot_main

        return aot_main(args.aot_args)
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
