"""Per-validator duty tracking (reference:
packages/beacon-node/src/metrics/validatorMonitor.ts:165
createValidatorMonitor).

Registered (tracked) validators get per-epoch summaries of attestation
performance — seen on gossip, included in blocks, inclusion distance —
and block proposals, surfaced both as Prometheus metrics and as queryable
epoch summaries (the reference logs these per epoch).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from prometheus_client import CollectorRegistry, Counter, Gauge, Histogram


@dataclass
class EpochSummary:
    """One tracked validator's performance within one epoch
    (validatorMonitor.ts EpochSummary)."""

    attestations_seen: int = 0
    attestation_min_delay_sec: Optional[float] = None
    attestation_included: bool = False
    attestation_inclusion_distance: Optional[int] = None
    blocks_proposed: int = 0
    aggregates_seen: int = 0


class ValidatorMonitor:
    def __init__(self, registry: Optional[CollectorRegistry] = None):
        self._tracked: Dict[int, Dict[int, EpochSummary]] = {}
        reg = registry
        self.m_attestation_seen = Counter(
            "validator_monitor_attestation_total",
            "Tracked validators' attestations seen on gossip or in blocks",
            registry=reg,
        )
        self.m_attestation_included = Counter(
            "validator_monitor_attestation_in_block_total",
            "Tracked validators' attestations included on chain",
            registry=reg,
        )
        self.m_inclusion_distance = Histogram(
            "validator_monitor_attestation_inclusion_distance",
            "Slots between attestation and inclusion",
            buckets=(1, 2, 3, 4, 8, 16, 32),
            registry=reg,
        )
        self.m_blocks_proposed = Counter(
            "validator_monitor_beacon_block_total",
            "Tracked validators' proposed blocks imported",
            registry=reg,
        )
        self.m_tracked = Gauge(
            "validator_monitor_validators",
            "Number of tracked validator indices",
            registry=reg,
        )

    # -- registration ---------------------------------------------------

    def register_validator(self, index: int) -> None:
        if index not in self._tracked:
            self._tracked[index] = {}
            self.m_tracked.set(len(self._tracked))

    def tracked(self) -> List[int]:
        return sorted(self._tracked)

    def _summary(self, index: int, epoch: int) -> Optional[EpochSummary]:
        epochs = self._tracked.get(index)
        if epochs is None:
            return None
        if epoch not in epochs:
            epochs[epoch] = EpochSummary()
        return epochs[epoch]

    # -- event hooks (mirroring registerGossipAttestation etc.) ---------

    def on_gossip_attestation(
        self, index: int, target_epoch: int, delay_sec: float
    ) -> None:
        s = self._summary(index, target_epoch)
        if s is None:
            return
        s.attestations_seen += 1
        if s.attestation_min_delay_sec is None or delay_sec < s.attestation_min_delay_sec:
            s.attestation_min_delay_sec = delay_sec
        self.m_attestation_seen.inc()

    def on_attestation_in_block(
        self, index: int, target_epoch: int, inclusion_distance: int
    ) -> None:
        s = self._summary(index, target_epoch)
        if s is None:
            return
        s.attestations_seen += 1
        if not s.attestation_included or (
            s.attestation_inclusion_distance is not None
            and inclusion_distance < s.attestation_inclusion_distance
        ):
            s.attestation_inclusion_distance = inclusion_distance
        s.attestation_included = True
        self.m_attestation_included.inc()
        self.m_inclusion_distance.observe(inclusion_distance)

    def on_block_imported(self, proposer_index: int, epoch: int) -> None:
        s = self._summary(proposer_index, epoch)
        if s is None:
            return
        s.blocks_proposed += 1
        self.m_blocks_proposed.inc()

    # -- queries --------------------------------------------------------

    def epoch_summary(self, index: int, epoch: int) -> Optional[EpochSummary]:
        epochs = self._tracked.get(index)
        if epochs is None:
            return None
        return epochs.get(epoch)

    def prune(self, before_epoch: int) -> None:
        for epochs in self._tracked.values():
            for e in [e for e in epochs if e < before_epoch]:
                del epochs[e]
