"""Metrics subsystem (reference: packages/beacon-node/src/metrics/).

Three metric groups like the reference's createMetrics (metrics.ts:14):
- beacon: spec-standard names (metrics/metrics/beacon.ts)
- lodestar: internal instrumentation (metrics/metrics/lodestar.ts) —
  block pipeline timings, gossip queues, regen, op pools; the BLS pool
  family lives in chain/bls/metrics.py and shares the same registry
- process: Python runtime stats (prom-client collectDefaultMetrics role)

plus the per-validator duty tracker (validator_monitor.py mirroring
createValidatorMonitor, metrics/validatorMonitor.ts:165) and the HTTP
exposition server (server.py, metrics/server/).

Registration contract (mechanically enforced by lodelint's
``metric-label-drift`` rule, docs/LINT.md): every metric name is
constructed at exactly ONE site repo-wide, and every call site passes
exactly the declared label set — a drifted ``.labels(...)`` or a bare
``.inc()`` on a labeled family raises ``ValueError`` at runtime, usually
inside the error handler the metric was meant to make visible.
"""
from __future__ import annotations

from typing import Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    REGISTRY,
    generate_latest,
)

from .validator_monitor import ValidatorMonitor  # noqa: F401


class BeaconMetrics:
    """Spec-standard beacon metrics (metrics/metrics/beacon.ts)."""

    def __init__(self, registry: CollectorRegistry):
        self.head_slot = Gauge(
            "beacon_head_slot", "Slot of the head block", registry=registry
        )
        self.finalized_epoch = Gauge(
            "beacon_finalized_epoch", "Latest finalized epoch", registry=registry
        )
        self.current_justified_epoch = Gauge(
            "beacon_current_justified_epoch",
            "Latest justified epoch",
            registry=registry,
        )
        self.proposed_blocks_total = Counter(
            "beacon_proposed_blocks_total",
            "Blocks imported as head proposals",
            registry=registry,
        )
        self.reorgs_total = Counter(
            "beacon_reorgs_total", "Detected chain reorganizations", registry=registry
        )
        self.peers = Gauge(
            "beacon_peers", "Connected libp2p peers", registry=registry
        )
        self.clock_slot = Gauge(
            "beacon_clock_slot", "Current wall-clock slot", registry=registry
        )


class LodestarMetrics:
    """Internal instrumentation (metrics/metrics/lodestar.ts)."""

    def __init__(self, registry: CollectorRegistry):
        ns = "lodestar_tpu"
        self.block_import_seconds = Histogram(
            f"{ns}_block_import_seconds",
            "Wall time of the full verify+import pipeline per block",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10),
            registry=registry,
        )
        self.stfn_seconds = Histogram(
            f"{ns}_stfn_seconds",
            "State transition wall time per block",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5),
            registry=registry,
        )
        self.block_sig_verify_seconds = Histogram(
            f"{ns}_block_sig_verify_seconds",
            "Signature-set verification wall time per block "
            "(verifyBlocksSignatures.ts:49 latency)",
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5),
            registry=registry,
        )
        self.block_queue_length = Gauge(
            f"{ns}_block_processor_queue_length",
            "Blocks waiting in the BlockProcessor job queue",
            registry=registry,
        )
        self.gossip_queue_length = Gauge(
            f"{ns}_gossip_validation_queue_length",
            "Per-topic gossip validation queue length",
            ["topic"],
            registry=registry,
        )
        self.gossip_queue_dropped = Counter(
            f"{ns}_gossip_validation_queue_dropped_total",
            "Gossip jobs dropped by full queues",
            ["topic"],
            registry=registry,
        )
        self.regen_requests = Counter(
            f"{ns}_regen_requests_total",
            "State regeneration cache misses (replay path)",
            registry=registry,
        )
        self.state_cache_size = Gauge(
            f"{ns}_state_cache_size", "States held by the LRU", registry=registry
        )
        self.op_pool_attestations = Gauge(
            f"{ns}_op_pool_attestation_count",
            "Attestations buffered for aggregation/packing",
            registry=registry,
        )
        # network fault domain (ISSUE 15; panels in
        # dashboards/lodestar_tpu_gossip.json +
        # lodestar_tpu_range_sync.json, pinned both directions by
        # tests/test_dashboards.py)
        self.reqresp_requests_total = Counter(
            f"{ns}_reqresp_requests_total",
            "Client-side reqresp requests sent, by method",
            ["method"],
            registry=registry,
        )
        self.reqresp_request_timeouts_total = Counter(
            f"{ns}_reqresp_request_timeouts_total",
            "Client-side reqresp requests that hit the timeout, by method",
            ["method"],
            registry=registry,
        )
        self.reqresp_request_retries_total = Counter(
            f"{ns}_reqresp_request_retries_total",
            "Requests re-sent to ANOTHER peer after a failure/timeout "
            "(request_any's bounded cross-peer retry), by method",
            ["method"],
            registry=registry,
        )
        self.reqresp_rate_limited_total = Counter(
            f"{ns}_reqresp_rate_limited_total",
            "Server-side requests shed by the GCRA rate limiter, by method",
            ["method"],
            registry=registry,
        )
        self.peer_score = Histogram(
            f"{ns}_peer_score",
            "Connected peers' rpc scores, observed each network heartbeat",
            buckets=(-100, -50, -20, -10, -5, -1, 0, 1, 5, 10),
            registry=registry,
        )
        self.gossip_mesh_peers = Gauge(
            f"{ns}_gossip_mesh_peers",
            "Gossip mesh degree per topic (mesh transports only)",
            ["topic"],
            registry=registry,
        )
        # range sync (sync/range metrics role: batches by terminal status,
        # usable peers, current chain target)
        self.sync_batches_total = Counter(
            f"{ns}_sync_batches_total",
            "Range-sync batches by outcome",
            ["status"],  # downloaded | processed | retried | failed
            registry=registry,
        )
        self.sync_peers = Gauge(
            f"{ns}_sync_peers",
            "Peers whose status can serve the current sync window",
            registry=registry,
        )
        self.sync_target_slot = Gauge(
            f"{ns}_sync_target_slot",
            "Best peer head slot the range sync is driving toward",
            registry=registry,
        )
        # execution / builder (execution engine + builder http.ts roles)
        self.engine_new_payload_total = Counter(
            f"{ns}_engine_new_payload_total",
            "notifyNewPayload calls by engine verdict",
            ["status"],  # valid | invalid
            registry=registry,
        )
        self.builder_bids_total = Counter(
            f"{ns}_builder_bids_total",
            "Builder getHeader bids fetched",
            registry=registry,
        )
        self.builder_unblinds_total = Counter(
            f"{ns}_builder_unblinds_total",
            "Blinded blocks revealed via submitBlindedBlock",
            registry=registry,
        )
        # live execution seam (versioned Engine API + HTTP eth1 provider;
        # panels in dashboards/lodestar_tpu_execution_el.json, pinned by
        # tests/test_dashboards.py)
        self.engine_rpc_seconds = Histogram(
            f"{ns}_engine_rpc_seconds",
            "Engine JSON-RPC round-trip latency by method (the label value "
            "carries the structure version, e.g. engine_newPayloadV2)",
            ["method"],
            buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5),
            registry=registry,
        )
        self.engine_rpc_errors_total = Counter(
            f"{ns}_engine_rpc_errors_total",
            "Engine JSON-RPC failures by method and kind",
            ["method", "kind"],  # rpc_error | http | transport
            registry=registry,
        )
        self.eth1_sync_lag_blocks = Gauge(
            f"{ns}_eth1_sync_lag_blocks",
            "Eth1 follow head minus the deposit tracker's synced block",
            registry=registry,
        )
        self.eth1_deposit_events_total = Counter(
            f"{ns}_eth1_deposit_events_total",
            "DepositEvent logs ingested by the deposit tracker",
            registry=registry,
        )
        # optimistic sync + proposal robustness (ISSUE 12; panels in
        # dashboards/lodestar_tpu_execution_el.json, pinned both
        # directions by tests/test_dashboards.py)
        self.blocks_imported_optimistic_total = Counter(
            f"{ns}_blocks_imported_optimistic_total",
            "Blocks imported without an EL verdict (SYNCING/ACCEPTED or "
            "engine unreachable) — followable, never proposed on",
            registry=registry,
        )
        self.blocks_invalidated_total = Counter(
            f"{ns}_blocks_invalidated_total",
            "Proto-array blocks invalidated by an EL INVALID verdict "
            "(latestValidHash subtree pruning)",
            registry=registry,
        )
        self.el_offline = Gauge(
            f"{ns}_el_offline",
            "1 while the last engine call failed at transport level",
            registry=registry,
        )
        self.produce_payload_fallbacks_total = Counter(
            f"{ns}_produce_payload_fallbacks_total",
            "getPayload watchdog fallbacks to the locally-built payload",
            ["reason"],  # deadline | error | refused
            registry=registry,
        )
        # block production (api/impl produceBlock role)
        self.blocks_produced_total = Counter(
            f"{ns}_blocks_produced_total",
            "Blocks produced over REST by flavor",
            ["flavor"],  # full | blinded
            registry=registry,
        )
        self.produce_block_seconds = Histogram(
            f"{ns}_produce_block_seconds",
            "Wall time of produceBlock (pool packing + trial STF + root)",
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5),
            registry=registry,
        )


class Metrics:
    """Composition root: one registry, all groups (createMetrics)."""

    def __init__(self, registry: Optional[CollectorRegistry] = None):
        # a private registry by default so tests can create many instances
        self.registry = registry or CollectorRegistry()
        self.beacon = BeaconMetrics(self.registry)
        self.lodestar = LodestarMetrics(self.registry)
        self.validator_monitor = ValidatorMonitor(self.registry)

    def expose(self) -> bytes:
        """Prometheus text exposition of the whole registry."""
        return generate_latest(self.registry)


_default: Optional[Metrics] = None


def get_metrics() -> Metrics:
    global _default
    if _default is None:
        _default = Metrics()
    return _default
