"""Prometheus exposition endpoint (reference:
packages/beacon-node/src/metrics/server/ HttpMetricsServer).

Serves GET /metrics in text format from a Metrics registry over aiohttp,
like the node's scrape target in prometheus.yml.
"""
from __future__ import annotations

from aiohttp import web

from . import Metrics


class HttpMetricsServer:
    def __init__(self, metrics: Metrics, host: str = "127.0.0.1", port: int = 8008):
        self.metrics = metrics
        self.host = host
        self.port = port
        self._runner = None
        self.app = web.Application()
        self.app.router.add_get("/metrics", self._handle)

    async def _handle(self, request: web.Request) -> web.Response:
        return web.Response(
            body=self.metrics.expose(),
            content_type="text/plain",
            charset="utf-8",
        )

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
