"""Attestation / sync-committee subnet services (reference:
beacon-node/src/network/subnets/{attnetsService,syncnetsService}.ts).

AttnetsService owns which of the 64 attestation subnets the node is
subscribed to, from two sources:

- **committee subscriptions**: the validator client announces upcoming
  attestation duties (REST `prepareBeaconCommitteeSubnet`); the service
  subscribes the duty's subnet a dilution window before the duty slot and
  unsubscribes after it (short-lived, aggregation-driven).
- **long-lived random subnets**: each tracked validator contributes
  RANDOM_SUBNETS_PER_VALIDATOR deterministic-random subnets rotated every
  EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION epochs (the stable gossip mesh
  backbone the spec requires).

On every change the service updates the node's metadata bitfield (seq
bump, as the reference does through MetadataController) and the ENR
attnets field when discovery is attached.

SyncnetsService is the altair analogue over the 4 sync-committee subnets
(long-lived only: membership follows sync-committee periods).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from lodestar_tpu.params import ACTIVE_PRESET as _p, ATTESTATION_SUBNET_COUNT

# spec constants (phase0 p2p): 1 random subnet per validator, rotated on a
# 256-epoch cadence; duty subnets subscribe on receipt (duties arrive
# <= 2 epochs ahead) and expire after the duty slot
RANDOM_SUBNETS_PER_VALIDATOR = 1
EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION = 256


@dataclass(frozen=True)
class CommitteeSubscription:
    """prepareBeaconCommitteeSubnet request item
    (api/src/beacon/routes/validator.ts beaconCommitteeSubscriptions)."""

    validator_index: int
    committees_at_slot: int
    slot: int
    committee_index: int
    is_aggregator: bool


def _random_subnet(validator_index: int, period: int, k: int) -> int:
    """Deterministic per-validator random subnet for a rotation period.
    (The spec derives this from the node id + epoch prefix; a keyed hash
    keeps the same statistical properties without tracking node state.)"""
    h = hashlib.sha256(
        validator_index.to_bytes(8, "little")
        + period.to_bytes(8, "little")
        + k.to_bytes(1, "little")
    ).digest()
    return int.from_bytes(h[:8], "little") % ATTESTATION_SUBNET_COUNT


class AttnetsService:
    def __init__(self, network, clock):
        self.network = network
        self.clock = clock
        # subnet -> unsubscribe-after slot (short-lived duty subs)
        self._duty_subs: Dict[int, int] = {}
        # aggregator duties: (slot, subnet) pairs we must be meshed for
        self._aggregator_duties: Set[Tuple[int, int]] = set()
        self._tracked_validators: Set[int] = set()
        self._long_lived: Set[int] = set()
        self._subscribed: Set[int] = set()

    # -- inputs ----------------------------------------------------------

    def add_committee_subscriptions(
        self, subs: List[CommitteeSubscription]
    ) -> None:
        from lodestar_tpu.chain.validation import compute_subnet_for_attestation

        for sub in subs:
            subnet = compute_subnet_for_attestation(
                sub.committees_at_slot, sub.slot, sub.committee_index
            )
            until = sub.slot + 1
            self._duty_subs[subnet] = max(self._duty_subs.get(subnet, 0), until)
            if sub.is_aggregator:
                self._aggregator_duties.add((sub.slot, subnet))
            self._tracked_validators.add(sub.validator_index)
        self._refresh()

    # -- slot upkeep -----------------------------------------------------

    def on_slot(self, slot: int) -> None:
        """Expire past duty subscriptions, rotate long-lived subnets."""
        for subnet, until in list(self._duty_subs.items()):
            if slot > until:
                del self._duty_subs[subnet]
        self._aggregator_duties = {
            (s, sn) for (s, sn) in self._aggregator_duties if s >= slot
        }
        self._refresh(slot)

    # -- state -----------------------------------------------------------

    def _wanted(self, slot: Optional[int] = None) -> Set[int]:
        slot = slot if slot is not None else self.clock.current_slot
        period = (slot // _p.SLOTS_PER_EPOCH) // EPOCHS_PER_RANDOM_SUBNET_SUBSCRIPTION
        long_lived = {
            _random_subnet(v, period, k)
            for v in self._tracked_validators
            for k in range(RANDOM_SUBNETS_PER_VALIDATOR)
        }
        self._long_lived = long_lived
        return long_lived | set(self._duty_subs)

    def _refresh(self, slot: Optional[int] = None) -> None:
        wanted = self._wanted(slot)
        for subnet in wanted - self._subscribed:
            self.network.subscribe_attestation_subnet(subnet)
            self._subscribed.add(subnet)
        for subnet in self._subscribed - wanted:
            unsubscribe = getattr(
                self.network, "unsubscribe_attestation_subnet", None
            )
            if unsubscribe is not None:
                unsubscribe(subnet)
            self._subscribed.discard(subnet)

    def should_process_attestation(self, slot: int, subnet: int) -> bool:
        """Aggregator check (attnetsService.shouldProcessAttestation): only
        aggregate on subnets we hold an aggregator duty for at `slot`."""
        return (slot, subnet) in self._aggregator_duties

    @property
    def active_subnets(self) -> Set[int]:
        return set(self._subscribed)


class SyncnetsService:
    """Sync-committee subnets (long-lived: follows committee periods)."""

    def __init__(self, network):
        self.network = network
        self._subscribed: Set[int] = set()

    def subscribe_for_positions(self, positions: List[int]) -> None:
        """Subscribe the subnets covering a validator's positions in the
        current sync committee (syncnetsService on duty update)."""
        from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_SIZE

        for pos in positions:
            subnet = pos // SYNC_COMMITTEE_SUBNET_SIZE
            if subnet not in self._subscribed:
                self.network.subscribe_sync_committee_subnet(subnet)
                self._subscribed.add(subnet)

    def unsubscribe_all(self) -> None:
        for subnet in list(self._subscribed):
            unsubscribe = getattr(
                self.network, "unsubscribe_sync_committee_subnet", None
            )
            if unsubscribe is not None:
                unsubscribe(subnet)
            self._subscribed.discard(subnet)

    @property
    def active_subnets(self) -> Set[int]:
        return set(self._subscribed)
