"""MeshFabric: the pluggable transport seam (ROADMAP 6's refactor
unlock, ISSUE 15).

One gossipsub-v1.1-shaped router — degree-limited per-topic meshes,
GRAFT/PRUNE heartbeat, IHAVE/IWANT recovery, multiplexed reqresp — that
runs over ANY link layer.  Three bindings share this class:

* ``loopback.LoopbackNet``  — in-process shared-memory links (the swarm
  harness fabric, ``testing/swarm.py``);
* ``wire.WireTransport``    — OS sockets + noise AEAD sessions (the
  production TCP stack);
* fault-wrapped variants of either, via the ``net.transport.*``
  checkpoints below — no wrapper class needed, the seams are in the
  shared code path.

The Link contract (duck-typed; see ``loopback.LoopbackLink`` and
``wire._Conn``):

* ``peer_id``                — remote peer id (stable per connection)
* ``async send(plain)``      — deliver one plaintext frame to the peer;
  raising ``ConnectionError``/``OSError`` means the link is dead
* ``close()``                — release resources; idempotent
* ``closed``                 — bool

The fabric owns per-link protocol state (``link.topics``,
``link.pending_reqs``) which it initializes in ``add_link``; the link
layer calls ``await fabric.on_frame(link, plain)`` per received frame
and ``fabric.drop_link(link)`` when the link dies.

Wire format of a plaintext frame (encryption, if any, is the link
layer's business):

    plain   := 1B type || body
    REQ     := 8B req id || 2B proto len || proto || data
    RESP_OK / RESP_ERR := 8B req id || data / utf8 error
    GOSSIP  := 2B topic len || topic || raw message
    SUB/UNSUB/GRAFT/PRUNE := 2B topic len || topic
    IHAVE   := 2B topic len || topic || N * 20B message ids
    IWANT   := 2B topic len || topic || N * 20B message ids

Deterministic fault checkpoints (docs/FAULTS.md): every outbound frame
passes ``net.transport.write`` and every inbound frame
``net.transport.read`` with ``src``/``dst``/``ftype`` context — a
``faults.Drop`` (or any ``FaultError``) discards the frame, a
``faults.Delay`` stalls it; scoping the plan with ``match=`` scripts
partitions and slow links per peer pair without touching healthy
traffic.
"""
from __future__ import annotations

import asyncio
import random
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Awaitable, Dict, List, Optional, Set, Tuple

from .gossip import compute_message_id
from .transport import GossipHandler, RequestHandler
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger

_log = get_logger("fabric")

# frame types
_REQ = 0x01
_RESP_OK = 0x02
_RESP_ERR = 0x03
_GOSSIP = 0x10
_SUB = 0x15
_UNSUB = 0x16
_GRAFT = 0x11
_PRUNE = 0x12
_IHAVE = 0x13
_IWANT = 0x14

# gossipsub-shaped mesh degrees (gossipsub v1.1 defaults)
MESH_D = 6
MESH_D_LOW = 4
MESH_D_HIGH = 10
IHAVE_PEERS = 3
HEARTBEAT_S = 1.0
REQUEST_TIMEOUT_S = 10.0

_MSG_ID_LEN = 20


def _with_topic(topic: str, rest: bytes = b"") -> bytes:
    tb = topic.encode()
    return len(tb).to_bytes(2, "big") + tb + rest


def _read_topic(body: bytes) -> Tuple[str, bytes]:
    n = int.from_bytes(body[:2], "big")
    return body[2 : 2 + n].decode(), body[2 + n :]


@dataclass
class _TopicState:
    handler: GossipHandler
    mesh: Set[str] = field(default_factory=set)


class MeshFabric:
    """Endpoint-compatible gossip mesh + reqresp mux over pluggable links.

    Implements the surface consumed by ReqRespNode / Eth2Gossip /
    Network (handle / request / subscribe / unsubscribe / publish /
    deliver / close) plus the link-layer callbacks (add_link / on_frame
    / drop_link) and mesh maintenance (heartbeat).
    """

    def __init__(self, peer_id: str, request_timeout: float = REQUEST_TIMEOUT_S):
        self.peer_id = peer_id
        self.request_timeout = request_timeout
        self.conns: Dict[str, object] = {}  # peer_id -> Link
        self.request_handlers: Dict[str, RequestHandler] = {}
        self._topics: Dict[str, _TopicState] = {}
        self._pending: Dict[int, asyncio.Future] = {}
        self._req_counter = 0
        self._tasks: Set[asyncio.Task] = set()
        self._hb_task: Optional[asyncio.Task] = None
        # recent message cache for IWANT serving + IHAVE digests
        self._mcache: "OrderedDict[bytes, Tuple[str, bytes]]" = OrderedDict()
        self._mcache_max = 512
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()
        self._seen_max = 1 << 15
        self.frames_dropped = 0  # write-side frames lost to injected faults

    # -- link lifecycle ------------------------------------------------

    async def add_link(self, link) -> str:
        """Register a live link and announce our subscriptions on it.
        A reconnect supersedes (and closes) the previous link."""
        link.topics = getattr(link, "topics", set())
        link.pending_reqs = getattr(link, "pending_reqs", set())
        old = self.conns.get(link.peer_id)
        self.conns[link.peer_id] = link
        if old is not None:
            # registered FIRST so drop_link sees the replacement and
            # leaves mesh membership alone, but still fails the old
            # link's in-flight requests immediately (binding-uniform —
            # the TCP recv loop used to provide this as a side effect)
            self.drop_link(old)
        for topic in self._topics:
            await self._send_frame(link, bytes([_SUB]) + _with_topic(topic))
        return link.peer_id

    def drop_link(self, link) -> None:
        if self.conns.get(link.peer_id) is link:
            # only the ACTIVE link's death evicts peer state — a link
            # superseded by a reconnect must not wipe the (still valid)
            # mesh membership of its replacement
            del self.conns[link.peer_id]
            for st in self._topics.values():
                st.mesh.discard(link.peer_id)
        # fail this link's in-flight requests now instead of letting
        # callers wait out the request timeout
        for rid in list(getattr(link, "pending_reqs", ())):
            fut = self._pending.get(rid)
            if fut is not None and not fut.done():
                fut.set_exception(ConnectionError("peer disconnected"))
        if getattr(link, "pending_reqs", None):
            link.pending_reqs.clear()
        link.close()

    def disconnect_peer(self, peer_id: str) -> None:
        """Sever the live link to a peer (ban enforcement: score
        bookkeeping alone leaves the connection — and its mesh slots —
        alive)."""
        link = self.conns.get(peer_id)
        if link is not None:
            self.drop_link(link)

    def start_heartbeat(self) -> None:
        if self._hb_task is None:
            self._hb_task = asyncio.ensure_future(self._heartbeat_loop())

    def close(self) -> None:
        if self._hb_task:
            self._hb_task.cancel()
            self._hb_task = None
        for link in list(self.conns.values()):
            link.close()
        self.conns.clear()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("transport closed"))
        self._pending.clear()
        for t in self._tasks:
            t.cancel()

    # -- frame send path (the net.transport.write seam) ----------------

    async def _send_frame(self, link, plain: bytes) -> None:
        """One outbound frame through the write checkpoint.  An injected
        Delay stalls just this frame; Drop (or any FaultError) discards
        it — the deterministic model of a lossy link.  Real link errors
        drop the link itself."""
        try:
            faults.fire(
                "net.transport.write",
                src=self.peer_id,
                dst=link.peer_id,
                ftype=plain[0],
            )
        except faults.Delay as d:
            await asyncio.sleep(d.seconds)
        except faults.FaultError:
            self.frames_dropped += 1
            return
        try:
            await link.send(plain)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            _log.debug(
                f"send to {link.peer_id} failed: {type(e).__name__}: {e}"
            )
            self.drop_link(link)

    def _bg_send(self, link, plain: bytes) -> None:
        self._bg(self._send_frame(link, plain))

    # -- reqresp (Endpoint surface) ------------------------------------

    def handle(self, protocol_id: str, handler: RequestHandler) -> None:
        self.request_handlers[protocol_id] = handler

    async def request(self, to_peer: str, protocol_id: str, data: bytes) -> bytes:
        link = self.conns.get(to_peer)
        if link is None:
            raise ConnectionError(f"not connected to {to_peer}")
        self._req_counter += 1
        req_id = self._req_counter
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        pb = protocol_id.encode()
        link.pending_reqs.add(req_id)
        try:
            await self._send_frame(
                link,
                bytes([_REQ])
                + req_id.to_bytes(8, "big")
                + len(pb).to_bytes(2, "big")
                + pb
                + data,
            )
            return await asyncio.wait_for(fut, self.request_timeout)
        finally:
            link.pending_reqs.discard(req_id)
            self._pending.pop(req_id, None)

    # -- gossip (Endpoint surface) -------------------------------------

    def subscribe(self, topic: str, handler: GossipHandler) -> None:
        self._topics[topic] = _TopicState(handler=handler)
        self._broadcast_control(_SUB, topic)

    def unsubscribe(self, topic: str) -> None:
        if topic in self._topics:
            del self._topics[topic]
            self._broadcast_control(_UNSUB, topic)

    def _broadcast_control(self, ftype: int, topic: str) -> None:
        for link in list(self.conns.values()):
            self._bg_send(link, bytes([ftype]) + _with_topic(topic))

    async def publish(self, topic: str, message: bytes) -> int:
        """Send to mesh peers (or all subscribed peers while the mesh is
        still forming); returns receiver count."""
        msg_id = compute_message_id(topic, message)
        self._remember(topic, msg_id, message)
        targets = self._forward_targets(topic, exclude=None)
        frame = bytes([_GOSSIP]) + _with_topic(topic, message)
        for pid in targets:
            link = self.conns.get(pid)
            if link:
                self._bg_send(link, frame)
        return len(targets)

    def deliver(self, from_peer: str, topic: str, message: bytes) -> None:
        st = self._topics.get(topic)
        if st is None:
            return
        self._bg(st.handler(from_peer, topic, message))

    def mesh_sizes(self) -> Dict[str, int]:
        """Per-topic mesh degree (observability: the swarm-visible
        mesh-size gauge reads this)."""
        return {topic: len(st.mesh) for topic, st in self._topics.items()}

    # -- internals -----------------------------------------------------

    def _bg(self, coro: Awaitable) -> None:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _remember(self, topic: str, msg_id: bytes, message: bytes) -> None:
        self._seen[msg_id] = None
        while len(self._seen) > self._seen_max:
            self._seen.popitem(last=False)
        self._mcache[msg_id] = (topic, message)
        while len(self._mcache) > self._mcache_max:
            self._mcache.popitem(last=False)

    def _forward_targets(self, topic: str, exclude: Optional[str]) -> List[str]:
        st = self._topics.get(topic)
        mesh = set(st.mesh) if st else set()
        if not mesh:
            mesh = {
                p
                for p, link in self.conns.items()
                if topic in getattr(link, "topics", ())
            }
        mesh.discard(exclude)
        return [p for p in mesh if p in self.conns]

    async def on_frame(self, link, plain: bytes) -> None:
        """Link-layer callback: one inbound plaintext frame, through the
        net.transport.read checkpoint (Drop/FaultError = the frame was
        lost in flight)."""
        try:
            faults.fire(
                "net.transport.read",
                src=link.peer_id,
                dst=self.peer_id,
                ftype=plain[0],
            )
        except faults.Delay as d:
            await asyncio.sleep(d.seconds)
        except faults.FaultError:
            return
        ftype, body = plain[0], plain[1:]
        if ftype == _REQ:
            req_id = int.from_bytes(body[:8], "big")
            plen = int.from_bytes(body[8:10], "big")
            proto = body[10 : 10 + plen].decode()
            data = body[10 + plen :]
            self._bg(self._serve_request(link, req_id, proto, data))
        elif ftype in (_RESP_OK, _RESP_ERR):
            req_id = int.from_bytes(body[:8], "big")
            fut = self._pending.get(req_id)
            if fut and not fut.done():
                if ftype == _RESP_OK:
                    fut.set_result(body[8:])
                else:
                    fut.set_exception(
                        ConnectionError(body[8:].decode(errors="replace"))
                    )
        elif ftype == _GOSSIP:
            topic, message = _read_topic(body)
            msg_id = compute_message_id(topic, message)
            if msg_id in self._seen:
                return
            self._remember(topic, msg_id, message)
            self.deliver(link.peer_id, topic, message)
            # forward within the mesh (multi-hop propagation)
            frame = bytes([_GOSSIP]) + _with_topic(topic, message)
            for pid in self._forward_targets(topic, exclude=link.peer_id):
                c = self.conns.get(pid)
                if c:
                    self._bg_send(c, frame)
        elif ftype == _SUB:
            topic, _ = _read_topic(body)
            link.topics.add(topic)
        elif ftype == _UNSUB:
            topic, _ = _read_topic(body)
            link.topics.discard(topic)
            st = self._topics.get(topic)
            if st:
                st.mesh.discard(link.peer_id)
        elif ftype == _GRAFT:
            topic, _ = _read_topic(body)
            st = self._topics.get(topic)
            if st is not None and len(st.mesh) < MESH_D_HIGH:
                st.mesh.add(link.peer_id)
            else:  # not subscribed or mesh full: refuse
                self._bg_send(link, bytes([_PRUNE]) + _with_topic(topic))
        elif ftype == _PRUNE:
            topic, _ = _read_topic(body)
            st = self._topics.get(topic)
            if st:
                st.mesh.discard(link.peer_id)
        elif ftype == _IHAVE:
            topic, rest = _read_topic(body)
            if topic not in self._topics:
                return
            want = []
            for i in range(0, len(rest), _MSG_ID_LEN):
                mid = rest[i : i + _MSG_ID_LEN]
                if len(mid) == _MSG_ID_LEN and mid not in self._seen:
                    want.append(mid)
            if want:
                self._bg_send(
                    link, bytes([_IWANT]) + _with_topic(topic, b"".join(want))
                )
        elif ftype == _IWANT:
            topic, rest = _read_topic(body)
            for i in range(0, len(rest), _MSG_ID_LEN):
                mid = rest[i : i + _MSG_ID_LEN]
                entry = self._mcache.get(mid)
                if entry is not None:
                    t, message = entry
                    self._bg_send(
                        link, bytes([_GOSSIP]) + _with_topic(t, message)
                    )

    async def _serve_request(
        self, link, req_id: int, proto: str, data: bytes
    ) -> None:
        handler = self.request_handlers.get(proto)
        rid = req_id.to_bytes(8, "big")
        if handler is None:
            await self._send_frame(
                link, bytes([_RESP_ERR]) + rid + f"unsupported {proto}".encode()
            )
            return
        try:
            resp = await handler(link.peer_id, proto, data)
            await self._send_frame(link, bytes([_RESP_OK]) + rid + resp)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if not link.closed:
                await self._send_frame(
                    link, bytes([_RESP_ERR]) + rid + str(e)[:256].encode()
                )

    # -- mesh maintenance ----------------------------------------------

    async def _heartbeat_loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(HEARTBEAT_S)
                self._heartbeat_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                _log.warn(f"heartbeat failed: {type(e).__name__}: {e}")
                continue

    def _heartbeat_once(self) -> None:
        for topic, st in self._topics.items():
            st.mesh = {p for p in st.mesh if p in self.conns}
            subscribers = [
                p
                for p, link in self.conns.items()
                if topic in getattr(link, "topics", ())
            ]
            if len(st.mesh) < MESH_D_LOW:
                candidates = [p for p in subscribers if p not in st.mesh]
                random.shuffle(candidates)
                for pid in candidates[: MESH_D - len(st.mesh)]:
                    st.mesh.add(pid)
                    link = self.conns.get(pid)
                    if link:
                        self._bg_send(link, bytes([_GRAFT]) + _with_topic(topic))
            elif len(st.mesh) > MESH_D_HIGH:
                excess = random.sample(
                    sorted(st.mesh), len(st.mesh) - MESH_D
                )
                for pid in excess:
                    st.mesh.discard(pid)
                    link = self.conns.get(pid)
                    if link:
                        self._bg_send(link, bytes([_PRUNE]) + _with_topic(topic))
            # IHAVE digests of the recent cache to a sample of
            # subscribers.  Unlike canonical gossipsub this includes
            # mesh members: a peer GRAFTed after a publish would
            # otherwise never hear of it (mesh forwards only NEW
            # messages), and the cost is one id list — IWANT only pulls
            # unseen ids.
            ids = [
                mid for mid, (t, _) in self._mcache.items() if t == topic
            ][-32:]
            if ids:
                sample = list(subscribers)
                random.shuffle(sample)
                payload = bytes([_IHAVE]) + _with_topic(topic, b"".join(ids))
                for pid in sample[: IHAVE_PEERS + len(st.mesh)]:
                    link = self.conns.get(pid)
                    if link:
                        self._bg_send(link, payload)
