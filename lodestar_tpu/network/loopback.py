"""Loopback link layer: shared-memory frame pipes between MeshFabric
instances in one process (ISSUE 15 / ROADMAP 6).

This is the swarm harness's fabric — the REAL gossip mesh, scoring,
reqresp mux and rate limiter run unmodified (they live in MeshFabric and
its consumers); only the bottom byte-moving layer is replaced with
paired in-memory queues.  Unlike ``transport.InProcessHub`` (a one-hop
policy double that broadcasts to every subscriber), a loopback swarm
exercises multi-hop mesh propagation, GRAFT/PRUNE churn and IHAVE/IWANT
recovery exactly as the TCP stack does.

Per-direction delivery is FIFO: each link owns an unbounded deque
drained by one pump task, so frame order on a link matches send order
(the TCP guarantee) while cross-link interleaving is the event loop's —
the same nondeterminism surface production has.  Fault scripting happens
in MeshFabric's ``net.transport.read``/``write`` checkpoints (shared
with the TCP binding); ``net.transport.connect`` fires here per
``connect()`` so dial storms and unreachable-peer scripts work on the
loopback too.
"""
from __future__ import annotations

import asyncio
from collections import deque
from typing import Deque, Dict, Optional, Set, Tuple

from .fabric import MeshFabric
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger

_log = get_logger("loopback")


class LoopbackLink:
    """One direction-agnostic attachment: ``send()`` enqueues toward the
    remote fabric; a pump task dequeues and feeds the remote's
    ``on_frame`` with the REMOTE side's link object (mirroring how each
    end of a TCP connection owns its own _Conn)."""

    def __init__(self, local: MeshFabric, remote: MeshFabric):
        self.local = local
        self.remote = remote
        self.peer_id = remote.peer_id
        self.topics: Set[str] = set()
        self.pending_reqs: Set[int] = set()
        self.closed = False
        self.twin: Optional["LoopbackLink"] = None  # remote's link back to us
        self._queue: Deque[bytes] = deque()
        self._wakeup = asyncio.Event()
        self._pump_task: Optional[asyncio.Task] = None

    async def send(self, plain: bytes) -> None:
        if self.closed:
            raise ConnectionError(f"link to {self.peer_id} closed")
        self._queue.append(plain)
        self._wakeup.set()

    def start(self) -> None:
        if self._pump_task is None:
            self._pump_task = asyncio.ensure_future(self._pump())

    async def _pump(self) -> None:
        try:
            while True:
                while not self._queue:
                    self._wakeup.clear()
                    await self._wakeup.wait()
                plain = self._queue.popleft()
                try:
                    await self.remote.on_frame(self.twin, plain)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    # a malformed frame breaks the connection, exactly
                    # like the TCP recv loop's teardown
                    _log.debug(
                        f"loopback frame to {self.peer_id} failed: "
                        f"{type(e).__name__}: {e}; dropping link"
                    )
                    self.remote.drop_link(self.twin)
                    self.local.drop_link(self)
                    return
        except asyncio.CancelledError:
            raise

    def close(self) -> None:
        self.closed = True
        if self._pump_task is not None:
            self._pump_task.cancel()
            self._pump_task = None


class LoopbackNet:
    """Connection registry for a swarm of MeshFabrics in one process."""

    def __init__(self):
        self.fabrics: Dict[str, MeshFabric] = {}
        self._links: Dict[Tuple[str, str], LoopbackLink] = {}

    def register(self, fabric: MeshFabric) -> MeshFabric:
        self.fabrics[fabric.peer_id] = fabric
        return fabric

    async def connect(self, a: MeshFabric, b: MeshFabric) -> None:
        """Wire a<->b with a paired link per direction (idempotent:
        reconnect supersedes, as a TCP redial would)."""
        faults.fire("net.transport.connect", src=a.peer_id, dst=b.peer_id)
        ab = LoopbackLink(a, b)
        ba = LoopbackLink(b, a)
        ab.twin, ba.twin = ba, ab
        self._links[(a.peer_id, b.peer_id)] = ab
        self._links[(b.peer_id, a.peer_id)] = ba
        ab.start()
        ba.start()
        await a.add_link(ab)
        await b.add_link(ba)

    def disconnect(self, a_id: str, b_id: str) -> None:
        """Hard-drop both directions (a crashed peer / RST, not a polite
        goodbye): pending requests fail immediately on both ends."""
        for src, dst in ((a_id, b_id), (b_id, a_id)):
            link = self._links.pop((src, dst), None)
            if link is not None:
                fab = self.fabrics.get(src)
                if fab is not None:
                    fab.drop_link(link)
                else:
                    link.close()

    def close(self) -> None:
        for link in list(self._links.values()):
            link.close()
        self._links.clear()
        for fab in list(self.fabrics.values()):
            fab.close()
        self.fabrics.clear()
