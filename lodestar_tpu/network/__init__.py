from .transport import Endpoint, InProcessHub  # noqa: F401
from .network import Network  # noqa: F401
from .gossip import Eth2Gossip, GossipType  # noqa: F401
from .peers import PeerAction, PeerManager, PeerRpcScoreStore  # noqa: F401
