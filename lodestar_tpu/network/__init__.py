from .transport import Endpoint, InProcessHub  # noqa: F401
from .fabric import MeshFabric  # noqa: F401
from .loopback import LoopbackNet  # noqa: F401
from .network import Network  # noqa: F401
from .gossip import Eth2Gossip, GossipType  # noqa: F401
from .peers import (  # noqa: F401
    PeerAction,
    PeerBannedError,
    PeerManager,
    PeerRpcScoreStore,
)
