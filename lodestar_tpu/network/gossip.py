"""Eth2 gossip layer (reference: beacon-node/src/network/gossip/:
Eth2Gossipsub, topic.ts:53-66 topic schema, encoding.ts snappy raw,
validation/queue.ts per-topic queues).

Topics: /eth2/{fork_digest_hex}/{name}/ssz_snappy, raw-snappy payloads,
spec message-ids (MESSAGE_DOMAIN_VALID_SNAPPY scheme).  Each subscription
runs its validator inside a bounded JobItemQueue with the reference's
sizes (attestation 24,576 LIFO conc 64; block 1,024 FIFO conc 64...).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from enum import Enum
from typing import Awaitable, Callable, Dict, Optional

from lodestar_tpu.testing import faults
from lodestar_tpu.utils.queue import JobItemQueue, QueueType
from lodestar_tpu.utils.snappy import compress as snappy_compress
from lodestar_tpu.utils.snappy import decompress as snappy_decompress
from .transport import Endpoint

MESSAGE_DOMAIN_INVALID_SNAPPY = b"\x00\x00\x00\x00"
MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"


class GossipType(str, Enum):
    beacon_block = "beacon_block"
    beacon_aggregate_and_proof = "beacon_aggregate_and_proof"
    beacon_attestation = "beacon_attestation"  # per-subnet: beacon_attestation_{n}
    voluntary_exit = "voluntary_exit"
    proposer_slashing = "proposer_slashing"
    attester_slashing = "attester_slashing"
    sync_committee_contribution_and_proof = "sync_committee_contribution_and_proof"
    sync_committee = "sync_committee"
    light_client_finality_update = "light_client_finality_update"
    light_client_optimistic_update = "light_client_optimistic_update"
    # eip4844: block travels with its blobs sidecar (topic.ts:53-66
    # beacon_block_and_blobs_sidecar)
    beacon_block_and_blobs_sidecar = "beacon_block_and_blobs_sidecar"
    bls_to_execution_change = "bls_to_execution_change"


# per-topic queue policy (gossip/validation/queue.ts:13-28)
QUEUE_OPTS: Dict[GossipType, dict] = {
    GossipType.beacon_block: dict(max_length=1024, queue_type=QueueType.FIFO, max_concurrency=64),
    GossipType.beacon_aggregate_and_proof: dict(max_length=4096, queue_type=QueueType.LIFO, max_concurrency=64),
    GossipType.beacon_attestation: dict(max_length=24576, queue_type=QueueType.LIFO, max_concurrency=64),
    GossipType.voluntary_exit: dict(max_length=4096, queue_type=QueueType.FIFO, max_concurrency=4),
    GossipType.proposer_slashing: dict(max_length=4096, queue_type=QueueType.FIFO, max_concurrency=4),
    GossipType.attester_slashing: dict(max_length=4096, queue_type=QueueType.FIFO, max_concurrency=4),
    GossipType.sync_committee_contribution_and_proof: dict(max_length=4096, queue_type=QueueType.LIFO, max_concurrency=64),
    GossipType.sync_committee: dict(max_length=4096, queue_type=QueueType.LIFO, max_concurrency=64),
    GossipType.light_client_finality_update: dict(max_length=1024, queue_type=QueueType.FIFO, max_concurrency=4),
    GossipType.light_client_optimistic_update: dict(max_length=1024, queue_type=QueueType.FIFO, max_concurrency=4),
    GossipType.beacon_block_and_blobs_sidecar: dict(max_length=1024, queue_type=QueueType.FIFO, max_concurrency=64),
    GossipType.bls_to_execution_change: dict(max_length=4096, queue_type=QueueType.FIFO, max_concurrency=4),
}


def topic_string(fork_digest: bytes, name: str) -> str:
    return f"/eth2/{fork_digest.hex()}/{name}/ssz_snappy"


def compute_message_id(topic: str, raw_message: bytes) -> bytes:
    """Spec altair message-id for snappy-compressed messages."""
    try:
        decompressed = snappy_decompress(raw_message)
        domain = MESSAGE_DOMAIN_VALID_SNAPPY
        payload = decompressed
    # spec-mandated fallback: an undecompressable message gets the
    # INVALID_SNAPPY message-id domain (p2p spec, altair message-id) —
    # expected hostile input, not a fault
    except Exception:  # lodelint: disable=silent-except
        domain = MESSAGE_DOMAIN_INVALID_SNAPPY
        payload = raw_message
    topic_bytes = topic.encode()
    return hashlib.sha256(
        domain + len(topic_bytes).to_bytes(8, "little") + topic_bytes + payload
    ).digest()[:20]


def fast_message_id(raw_message: bytes) -> bytes:
    """Cheap pre-validation dedup id (the reference's xxhash-based
    fastMsgIdFn, test/perf/network/gossip/fastMsgIdFn.test.ts): an
    xxhash64 of the raw compressed payload, hex-encoded."""
    from lodestar_tpu import native

    if native.available():
        return native.xxh64(raw_message).to_bytes(8, "big")
    return hashlib.sha256(raw_message).digest()[:8]


class _BoundedSeen:
    """Insertion-ordered seen-cache with FIFO eviction (the gossipsub
    seenCache role; unbounded growth would leak on a long-lived node)."""

    def __init__(self, max_size: int = 1 << 16):
        from collections import OrderedDict

        self._d = OrderedDict()
        self.max_size = max_size

    def __contains__(self, key) -> bool:
        return key in self._d

    def add(self, key) -> None:
        if key in self._d:
            return
        self._d[key] = None
        while len(self._d) > self.max_size:
            self._d.popitem(last=False)


@dataclass
class GossipStats:
    published: int = 0
    received: int = 0
    duplicates: int = 0
    invalid: int = 0


class Eth2Gossip:
    """Typed publish/subscribe with validation queues and seen-message-id
    dedup (the Eth2Gossipsub role over the in-process fabric)."""

    def __init__(self, endpoint: Endpoint, fork_digest: bytes):
        from .gossip_scoring import GossipPeerScore

        self.endpoint = endpoint
        self.fork_digest = fork_digest
        self._queues: Dict[str, JobItemQueue] = {}
        self._seen_ids = _BoundedSeen()
        self._seen_fast_ids = _BoundedSeen()
        self.stats = GossipStats()
        # gossipsub v1.1 peer scoring (scoringParameters.ts)
        self.peer_score = GossipPeerScore()

    def _topic(self, gossip_type: GossipType, subnet: Optional[int] = None) -> str:
        name = gossip_type.value + (f"_{subnet}" if subnet is not None else "")
        return topic_string(self.fork_digest, name)

    async def publish(
        self, gossip_type: GossipType, ssz_type, obj, subnet: Optional[int] = None
    ) -> int:
        topic = self._topic(gossip_type, subnet)
        # chaos seam: a publish-side fault (armed per topic) surfaces to
        # the caller — the node-local model of "could not publish"
        faults.fire("net.gossip.publish", topic=topic)
        raw = snappy_compress(ssz_type.serialize(obj))
        self._seen_ids.add(compute_message_id(topic, raw))
        self._seen_fast_ids.add((topic, fast_message_id(raw)))
        self.stats.published += 1
        return await self.endpoint.publish(topic, raw)

    def subscribe(
        self,
        gossip_type: GossipType,
        ssz_type,
        validate_and_handle: Callable[[str, object], Awaitable[None]],
        subnet: Optional[int] = None,
    ) -> None:
        """validate_and_handle(from_peer, decoded) runs inside the topic's
        bounded queue; raising = invalid message (counted)."""
        topic = self._topic(gossip_type, subnet)
        opts = QUEUE_OPTS[gossip_type]

        async def process(job):
            from_peer, obj = job
            await validate_and_handle(from_peer, obj)

        queue = JobItemQueue(process, name=topic, **opts)
        self._queues[topic] = queue

        async def on_message(from_peer: str, topic_: str, raw: bytes) -> None:
            # chaos seam: Drop loses the delivery, Garble corrupts the
            # payload in flight — the corrupted bytes then take the
            # normal hostile-input path (deserialize failure → invalid
            # count → peer scoring), which is exactly what the seam
            # exists to prove
            try:
                faults.fire("net.gossip.deliver", peer=from_peer, topic=topic_)
            except faults.Garble as g:
                raw = g.mutate(raw)
            except faults.FaultError:
                return
            # cheap xxhash first-pass dedup (fastMsgIdFn role) before the
            # sha256 canonical id — most duplicates never get hashed fully
            fast_id = (topic_, fast_message_id(raw))
            if fast_id in self._seen_fast_ids:
                self.stats.duplicates += 1
                return
            # graylisted peers' fresh messages are ignored (gossipsub
            # graylistThreshold); checked after dedup so duplicates — the
            # common case — never pay the score lookup
            if self.peer_score.should_graylist(from_peer):
                return
            self._seen_fast_ids.add(fast_id)
            msg_id = compute_message_id(topic_, raw)
            if msg_id in self._seen_ids:
                self.stats.duplicates += 1
                return
            self._seen_ids.add(msg_id)
            self.stats.received += 1
            try:
                obj = ssz_type.deserialize(snappy_decompress(raw))
            except Exception:
                self.stats.invalid += 1
                self.peer_score.on_invalid_message(from_peer, topic_)
                return
            self.peer_score.on_first_delivery(from_peer, topic_)
            fut = queue.push((from_peer, obj))

            def _done(f):
                from lodestar_tpu.utils.queue import QueueFullError

                if f.cancelled():
                    return  # shutdown/abort: not the sender's fault
                e = f.exception()
                if e is None:
                    return
                self.stats.invalid += 1
                if isinstance(e, QueueFullError):
                    # local backpressure, NOT peer misbehaviour — scoring
                    # it would graylist honest peers exactly when this
                    # node is overloaded
                    return
                self.peer_score.on_invalid_message(from_peer, topic_)

            fut.add_done_callback(_done)

        self.endpoint.subscribe(topic, on_message)

    def unsubscribe(self, gossip_type: GossipType, subnet: Optional[int] = None) -> None:
        topic = self._topic(gossip_type, subnet)
        self.endpoint.unsubscribe(topic)
        q = self._queues.pop(topic, None)
        if q:
            q.abort()
