"""Network: gossip + reqresp + peers composed over a transport endpoint
(reference: beacon-node/src/network/network.ts:40 Network).

Wires the chain into the network: gossip handlers feed validation then the
chain/pools (gossip/handlers/index.ts:79); reqresp serves status, ping,
metadata, goodbye and block download from the db
(network/reqresp/handlers/).
"""
from __future__ import annotations

import asyncio
from typing import List, Optional

from lodestar_tpu.config import compute_fork_digest
from lodestar_tpu.params import ACTIVE_PRESET as _p
from lodestar_tpu.types import ssz
from .gossip import Eth2Gossip, GossipType
from .peers import PeerAction, PeerManager
from .reqresp import encoding as rr_enc
from .reqresp.encoding import ReqRespError, RespStatus
from .reqresp.protocols import (
    BEACON_BLOCK_AND_BLOBS_SIDECAR_BY_ROOT,
    BEACON_BLOCKS_BY_RANGE,
    BEACON_BLOCKS_BY_ROOT,
    BLOBS_SIDECARS_BY_RANGE,
    GOODBYE,
    METADATA,
    PING,
    STATUS,
)
from .reqresp.reqresp import ReqRespNode
from lodestar_tpu.types import signed_block_wire_codec
from .transport import Endpoint, InProcessHub
from lodestar_tpu.utils import get_logger

_log = get_logger("network")


class Network:
    def __init__(
        self,
        hub: Optional[InProcessHub],
        chain,
        db,
        peer_id: Optional[str] = None,
        endpoint=None,
        rate_quota=None,  # None -> reqresp.DEFAULT_RATE_QUOTA
    ):
        """`endpoint` overrides the in-process hub attachment with any
        Endpoint-surface transport — production passes a
        wire.WireTransport (TCP + noise + gossip mesh), the swarm
        harness a fabric.MeshFabric over loopback links; tests pass the
        hub double."""
        self.chain = chain
        self.db = db
        signed_block_wire_codec.configure(chain.cfg)
        self.endpoint = endpoint if endpoint is not None else Endpoint(hub, peer_id)
        self.peer_id = self.endpoint.peer_id
        self.metrics = getattr(chain, "metrics", None)
        fork_digest = compute_fork_digest(
            chain.cfg.GENESIS_FORK_VERSION, chain.genesis_validators_root
        )
        self.gossip = Eth2Gossip(self.endpoint, fork_digest)
        self.reqresp = ReqRespNode(
            self.endpoint,
            rate_quota=rate_quota,
            metrics=self.metrics,
            on_rate_limited=self._on_rate_limited,
        )
        self.peer_manager = PeerManager()
        # a ban must sever the live transport link, not just the
        # bookkeeping — otherwise the banned peer keeps its mesh slots
        # and goes on exchanging frames until IT hangs up
        self.peer_manager.on_ban = self._sever_peer_link
        self._unknown_block_lock = asyncio.Lock()
        self.metadata = ssz.phase0.Metadata(seq_number=0, attnets=[False] * 64)
        # subnet services (network/subnets/ in the reference) are always
        # present; duty expiry + random-subnet rotation ride the chain
        # clock's slot ticks (attnetsService slot handler)
        from .subnets import AttnetsService, SyncnetsService

        self.attnets_service = AttnetsService(self, chain.clock)
        self.syncnets_service = SyncnetsService(self)

        async def _subnets_on_slot(slot: int) -> None:
            self.attnets_service.on_slot(slot)

        chain.clock.on_slot(_subnets_on_slot)
        self._register_reqresp_handlers()

    def _sever_peer_link(self, peer_id: str) -> None:
        disconnect = getattr(self.endpoint, "disconnect_peer", None)
        if disconnect is not None:  # mesh transports; the hub double has
            disconnect(peer_id)     # no persistent links to sever

    def _on_rate_limited(self, peer: str, method: str) -> None:
        """A shed reqresp flood is protocol misbehaviour: penalize the
        flooder on both score registers so a sustained flood walks it
        into disconnect/graylist (and eventually the ban lifecycle)."""
        self.peer_manager.scores.apply_action(peer, PeerAction.HighToleranceError)
        self.gossip.peer_score.on_behaviour_penalty(peer)

    # ------------------------------------------------------------------
    # reqresp server handlers (network/reqresp/handlers/)
    # ------------------------------------------------------------------

    def _register_reqresp_handlers(self) -> None:
        async def on_status(from_peer, req):
            self.peer_manager.on_connect(from_peer).status = req
            return [self.local_status()]

        async def on_ping(from_peer, req):
            return [self.metadata.seq_number]

        async def on_metadata(from_peer, req):
            return [self.metadata]

        async def on_goodbye(from_peer, req):
            self.peer_manager.on_disconnect(from_peer)
            return [0]

        async def on_blocks_by_range(from_peer, req):
            if req.count > 1024 or req.step < 1:
                raise ReqRespError(RespStatus.INVALID_REQUEST, "bad range")
            out = []
            head_root = self.chain.head_root
            # walk fork choice canonical chain + finalized archive
            for slot in range(req.start_slot, req.start_slot + req.count * req.step, req.step):
                blk = self._block_at_slot(slot)
                if blk is not None:
                    out.append(blk)
            return out

        async def on_blocks_by_root(from_peer, req):
            out = []
            for root in req:
                blk = self.db.block.get(bytes(root))
                if blk is not None:
                    out.append(blk)
            return out

        async def on_blobs_sidecars_by_range(from_peer, req):
            if req.count > 128:
                raise ReqRespError(RespStatus.INVALID_REQUEST, "bad range")
            out = []
            for slot in range(req.start_slot, req.start_slot + req.count):
                blk = self._block_at_slot(slot)
                if blk is None:
                    continue
                root = type(blk.message).hash_tree_root(blk.message)
                sc = self.db.blobs_sidecar.get(root)
                if sc is not None:
                    out.append(sc)
            return out

        async def on_block_and_blobs_by_root(from_peer, req):
            out = []
            for root in req:
                blk = self.db.block.get(bytes(root))
                sc = self.db.blobs_sidecar.get(bytes(root))
                if blk is not None and sc is not None:
                    out.append(
                        ssz.eip4844.SignedBeaconBlockAndBlobsSidecar(
                            beacon_block=blk, blobs_sidecar=sc
                        )
                    )
            return out

        self.reqresp.register_handler(STATUS, on_status)
        self.reqresp.register_handler(PING, on_ping)
        self.reqresp.register_handler(METADATA, on_metadata)
        self.reqresp.register_handler(GOODBYE, on_goodbye)
        self.reqresp.register_handler(BEACON_BLOCKS_BY_RANGE, on_blocks_by_range)
        self.reqresp.register_handler(BEACON_BLOCKS_BY_ROOT, on_blocks_by_root)
        self.reqresp.register_handler(
            BLOBS_SIDECARS_BY_RANGE, on_blobs_sidecars_by_range
        )
        self.reqresp.register_handler(
            BEACON_BLOCK_AND_BLOBS_SIDECAR_BY_ROOT, on_block_and_blobs_by_root
        )

    async def _resolve_unknown_ancestry(self, from_peer: str, signed_block) -> None:
        """Gossip block with an unknown parent: fetch the missing
        ancestors by root and import the chain forward (unknownBlock.ts
        role, now wired into the gossip pipeline).  Serialized — two
        out-of-order blocks from one heal share one ancestor walk."""
        from lodestar_tpu.sync.unknown_block import UnknownBlockSync

        async with self._unknown_block_lock:
            parent = "0x" + bytes(signed_block.message.parent_root).hex()
            try:
                if self.chain.fork_choice.has_block(parent):
                    # an earlier walk already imported the ancestry
                    await self.chain.process_block(signed_block)
                else:
                    await UnknownBlockSync(self, self.chain).resolve(signed_block)
            except Exception as e:
                _log.debug(
                    f"unknown-ancestry resolve via {from_peer} failed: "
                    f"{type(e).__name__}: {e}"
                )
                self.peer_manager.scores.apply_action(
                    from_peer, PeerAction.HighToleranceError
                )

    def _block_at_slot(self, slot: int):
        # canonical root via fork choice ancestors of head
        node = self.chain.fork_choice.proto_array.get_ancestor_at_or_before_slot(
            "0x" + self.chain.head_root.hex(), slot
        )
        if node is not None and node.slot == slot:
            return self.db.block.get(bytes.fromhex(node.block_root[2:]))
        blk = self.db.block_archive.get(slot)
        return blk

    def local_status(self) -> "ssz.phase0.Status":
        store = self.chain.fork_choice.store
        head = self.chain.fork_choice.get_head()
        return ssz.phase0.Status(
            fork_digest=self.gossip.fork_digest,
            finalized_root=bytes.fromhex(store.finalized.root[2:]),
            finalized_epoch=store.finalized.epoch,
            head_root=bytes.fromhex(head.block_root[2:]),
            head_slot=head.slot,
        )

    # ------------------------------------------------------------------
    # client helpers
    # ------------------------------------------------------------------

    async def connect(self, peer: str) -> "ssz.phase0.Status":
        """Status handshake (peerManager onConnect flow)."""
        status = (await self.reqresp.request(peer, STATUS, self.local_status()))[0]
        self.peer_manager.on_connect(peer).status = status
        return status

    async def blocks_by_range(self, peer: str, start_slot: int, count: int) -> List:
        from .reqresp.protocols import BeaconBlocksByRangeRequest

        try:
            return await self.reqresp.request(
                peer,
                BEACON_BLOCKS_BY_RANGE,
                BeaconBlocksByRangeRequest(start_slot=start_slot, count=count, step=1),
            )
        except (ReqRespError, asyncio.TimeoutError):
            self.peer_manager.scores.apply_action(peer, PeerAction.LowToleranceError)
            raise

    async def blocks_by_root(self, peer: str, roots: List[bytes]) -> List:
        return await self.reqresp.request(peer, BEACON_BLOCKS_BY_ROOT, list(roots))

    # ------------------------------------------------------------------
    # gossip wiring (gossip/handlers/index.ts)
    # ------------------------------------------------------------------

    def subscribe_core_topics(self) -> None:
        from lodestar_tpu.chain.validation import (
            GossipErrorCode,
            GossipValidationError,
            validate_gossip_aggregate_and_proof,
            validate_gossip_attestation,
            validate_gossip_block,
        )

        async def on_block(from_peer, signed_block):
            try:
                await validate_gossip_block(self.chain, signed_block)
            except GossipValidationError as e:
                if e.code is GossipErrorCode.UNKNOWN_BEACON_BLOCK_ROOT:
                    # unknown parent is a US problem (partition heal,
                    # out-of-order delivery), not the forwarder's:
                    # resolve the ancestry by root instead of punishing
                    await self._resolve_unknown_ancestry(from_peer, signed_block)
                    return
                self.peer_manager.scores.apply_action(
                    from_peer, PeerAction.LowToleranceError
                )
                raise
            await self.chain.process_block(signed_block)

        async def on_aggregate(from_peer, signed_agg):
            try:
                indices = await validate_gossip_aggregate_and_proof(
                    self.chain, signed_agg
                )
            except GossipValidationError:
                self.peer_manager.scores.apply_action(
                    from_peer, PeerAction.LowToleranceError
                )
                raise
            agg = signed_agg.message.aggregate
            self.chain.aggregated_attestation_pool.add(agg)
            self.chain.fork_choice.on_attestation(
                indices,
                "0x" + bytes(agg.data.beacon_block_root).hex(),
                agg.data.target.epoch,
            )

        self.gossip.subscribe(
            GossipType.beacon_block, signed_block_wire_codec, on_block
        )
        self.gossip.subscribe(
            GossipType.beacon_aggregate_and_proof,
            ssz.phase0.SignedAggregateAndProof,
            on_aggregate,
        )

    def subscribe_attestation_subnet(self, subnet: int) -> None:
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_gossip_attestation,
        )

        async def on_attestation(from_peer, attestation):
            try:
                indices = await validate_gossip_attestation(
                    self.chain, attestation, subnet
                )
            except GossipValidationError:
                self.peer_manager.scores.apply_action(
                    from_peer, PeerAction.HighToleranceError
                )
                raise
            self.chain.attestation_pool.add(attestation)
            self.chain.fork_choice.on_attestation(
                indices,
                "0x" + bytes(attestation.data.beacon_block_root).hex(),
                attestation.data.target.epoch,
            )

        self.gossip.subscribe(
            GossipType.beacon_attestation,
            ssz.phase0.Attestation,
            on_attestation,
            subnet=subnet,
        )
        self.metadata.attnets[subnet] = True
        self.metadata.seq_number += 1

    def unsubscribe_attestation_subnet(self, subnet: int) -> None:
        self.gossip.unsubscribe(GossipType.beacon_attestation, subnet=subnet)
        self.metadata.attnets[subnet] = False
        self.metadata.seq_number += 1

    def unsubscribe_sync_committee_subnet(self, subnet: int) -> None:
        self.gossip.unsubscribe(GossipType.sync_committee, subnet=subnet)


    def subscribe_sync_committee_subnet(self, subnet: int) -> None:
        """sync_committee_{subnet} topic: validate + feed the message pool
        (syncnetsService.ts role)."""
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_sync_committee_message,
        )

        async def on_sync_message(from_peer, message):
            try:
                positions = await validate_sync_committee_message(
                    self.chain, message, subnet
                )
            except GossipValidationError:
                self.peer_manager.scores.apply_action(
                    from_peer, PeerAction.HighToleranceError
                )
                raise
            for pos in positions:
                self.chain.sync_committee_message_pool.add(subnet, pos, message)

        self.gossip.subscribe(
            GossipType.sync_committee,
            ssz.altair.SyncCommitteeMessage,
            on_sync_message,
            subnet=subnet,
        )

    def subscribe_sync_contributions(self) -> None:
        from lodestar_tpu.chain.validation import (
            GossipValidationError,
            validate_sync_committee_contribution,
        )

        async def on_contribution(from_peer, signed):
            try:
                await validate_sync_committee_contribution(self.chain, signed)
            except GossipValidationError:
                self.peer_manager.scores.apply_action(
                    from_peer, PeerAction.LowToleranceError
                )
                raise
            self.chain.sync_contribution_pool.add(signed.message.contribution)

        self.gossip.subscribe(
            GossipType.sync_committee_contribution_and_proof,
            ssz.altair.SignedContributionAndProof,
            on_contribution,
        )

    async def publish_sync_committee_message(self, message, subnet: int) -> int:
        return await self.gossip.publish(
            GossipType.sync_committee,
            ssz.altair.SyncCommitteeMessage,
            message,
            subnet,
        )

    async def publish_sync_contribution(self, signed) -> int:
        return await self.gossip.publish(
            GossipType.sync_committee_contribution_and_proof,
            ssz.altair.SignedContributionAndProof,
            signed,
        )

    async def publish_block(self, signed_block) -> int:
        return await self.gossip.publish(
            GossipType.beacon_block, signed_block_wire_codec, signed_block
        )

    async def publish_attestation(self, attestation, subnet: int) -> int:
        return await self.gossip.publish(
            GossipType.beacon_attestation, ssz.phase0.Attestation, attestation, subnet
        )

    async def publish_aggregate(self, signed_agg) -> int:
        return await self.gossip.publish(
            GossipType.beacon_aggregate_and_proof,
            ssz.phase0.SignedAggregateAndProof,
            signed_agg,
        )

    # ------------------------------------------------------------------
    # discovery-driven peer top-up (peers/discover.ts + peerManager
    # heartbeat: when below the target peer count, query discovery and
    # dial what it found)
    # ------------------------------------------------------------------

    def attach_discovery(self, discovery, resolve_peer) -> None:
        """`discovery` is a DiscoveryService; `resolve_peer(enr) ->
        Optional[peer_id]` maps a discovered record onto a dialable
        transport address (in-process: the sim's registry; production:
        the ENR's ip/tcp_port)."""
        self._discovery = discovery
        self._resolve_peer = resolve_peer

    async def heartbeat(self, target_peers: int = 8) -> int:
        """One peer-maintenance round (peerManager.ts heartbeat):
        quarantine/disconnect bad peers, prune unbounded per-peer state
        (rate-limiter TATs, long-disconnected score entries), publish
        peer observability, then top up from discovery.  Returns the
        connected-peer count."""
        for pid in list(self.peer_manager.connected_peers()):
            if self.gossip.peer_score.should_graylist(pid):
                # gossip-quarantined (e.g. served invalid blocks): this
                # is ban-grade misbehaviour, not a soft disconnect — a
                # reconnect before unban is refused outright
                self.peer_manager.ban(pid)
            elif self.peer_manager.scores.should_disconnect(pid):
                self.peer_manager.on_disconnect(pid)
                # rpc scores are retained so the peer is still suspect
                # on reconnect until its score decays; maintain() prunes
                # the entry once it has been disconnected long enough
        # escalate score-banned peers, expire bans, prune stale entries
        self.peer_manager.maintain()
        # the GCRA limiter's per-(peer, method) TAT map grows with peer
        # churn; prune entries whose window has long passed (a pruned
        # key re-admits at full burst, which is the correct cold start)
        self.reqresp.rate_limiter.prune()
        self.gossip.peer_score.decay()
        self._publish_peer_metrics()
        discovery = getattr(self, "_discovery", None)
        if discovery is not None:
            connected = self.peer_manager.connected_peers()
            if len(connected) < target_peers:
                for enr in await discovery.discover_peers(
                    target_peers - len(connected)
                ):
                    pid = self._resolve_peer(enr)
                    if asyncio.iscoroutine(pid):  # async resolver: dials TCP
                        try:
                            pid = await pid
                        except Exception as e:
                            _log.debug(
                                f"peer resolve failed: "
                                f"{type(e).__name__}: {e}"
                            )
                            continue
                    if pid is None or pid in self.peer_manager.connected_peers():
                        continue
                    try:
                        await self.connect(pid)
                    except Exception as e:
                        _log.debug(
                            f"dial {pid} failed: {type(e).__name__}: {e}"
                        )
                        continue
        return len(self.peer_manager.connected_peers())

    def _publish_peer_metrics(self) -> None:
        """Heartbeat observability (ISSUE 15 / ROADMAP 8c): peer-score
        distribution, per-topic mesh degree (mesh transports only), and
        the ban counter."""
        if self.metrics is None:
            return
        lm = self.metrics.lodestar
        for pid in self.peer_manager.connected_peers():
            lm.peer_score.observe(self.peer_manager.scores.score(pid))
        mesh_sizes = getattr(self.endpoint, "mesh_sizes", None)
        if mesh_sizes is not None:
            for topic, size in mesh_sizes().items():
                lm.gossip_mesh_peers.labels(topic=topic).set(size)
        self.metrics.beacon.peers.set(len(self.peer_manager.connected_peers()))

    def close(self) -> None:
        self.endpoint.close()
