"""Peer manager + scoring (reference:
beacon-node/src/network/peers/{peerManager,score}.ts, simplified to the
semantics that matter: per-peer score with decay, ban threshold,
status/metadata tracking, disconnect of banned peers).

ISSUE 15 hardening: bans are a real lifecycle, not just a score
predicate — ``ban()`` disconnects the peer, evicts its entries from
BOTH stores (the pre-existing leak: a banned peer stayed in
``PeerManager.peers`` and ``PeerRpcScoreStore._peers`` forever) and
time-boxes the ban (`BAN_DURATION_S`); ``maintain()`` runs at the
network heartbeat to escalate score-banned peers, prune
long-disconnected entries, and expire old bans.  ``wait_for_peer()``
lets a Stalled range-sync chain re-arm when connectivity returns
instead of spinning.
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional


class PeerAction(float, Enum):
    """Score deltas (score.ts PeerAction)."""

    Fatal = -(2**10)
    LowToleranceError = -10.0
    MidToleranceError = -5.0
    HighToleranceError = -1.0


MIN_SCORE = -100.0
DEFAULT_BAN_THRESHOLD = -50.0
DISCONNECT_THRESHOLD = -20.0
SCORE_HALFLIFE_S = 600.0
# lifecycle knobs (peerManager.ts: banned peers are released after a
# window; disconnected peers' bookkeeping is pruned after a retention)
BAN_DURATION_S = 600.0
DISCONNECT_RETENTION_S = 300.0


class PeerBannedError(ConnectionError):
    """Raised when a banned peer tries to (re)connect before unban."""


@dataclass
class PeerInfo:
    peer_id: str
    score: float = 0.0
    last_update: float = field(default_factory=time.monotonic)
    status: Optional[object] = None      # ssz Status
    metadata: Optional[object] = None    # ssz Metadata
    ping_seq: int = 0
    connected: bool = True
    disconnected_at: Optional[float] = None


class PeerRpcScoreStore:
    def __init__(self, now=time.monotonic):
        self._peers: Dict[str, PeerInfo] = {}
        self._now = now

    def peer(self, peer_id: str) -> PeerInfo:
        if peer_id not in self._peers:
            self._peers[peer_id] = PeerInfo(peer_id, last_update=self._now())
        return self._peers[peer_id]

    def apply_action(self, peer_id: str, action: PeerAction) -> float:
        p = self.peer(peer_id)
        self._decay(p)
        p.score = max(MIN_SCORE, p.score + float(action.value))
        return p.score

    def score(self, peer_id: str) -> float:
        p = self.peer(peer_id)
        self._decay(p)
        return p.score

    def is_banned(self, peer_id: str) -> bool:
        return self.score(peer_id) < DEFAULT_BAN_THRESHOLD

    def should_disconnect(self, peer_id: str) -> bool:
        return self.score(peer_id) < DISCONNECT_THRESHOLD

    def evict(self, peer_id: str) -> None:
        """Drop a peer's score entry (ban eviction / disconnect prune).
        A re-appearing peer starts from a fresh zero score — the ban
        window itself is what keeps a banned peer out meanwhile."""
        self._peers.pop(peer_id, None)

    def prune_disconnected(self, cutoff: float) -> List[str]:
        """Evict entries of peers disconnected at or before ``cutoff``;
        returns the evicted ids (the store owns its representation —
        callers must not reach into ``_peers``)."""
        evicted = [
            pid
            for pid, info in self._peers.items()
            if (
                not info.connected
                and info.disconnected_at is not None
                and info.disconnected_at <= cutoff
            )
        ]
        for pid in evicted:
            del self._peers[pid]
        return evicted

    def _decay(self, p: PeerInfo) -> None:
        now = self._now()
        dt = now - p.last_update
        if dt > 0:
            p.score *= 0.5 ** (dt / SCORE_HALFLIFE_S)
            p.last_update = now


class PeerManager:
    """Tracks connected peers; periodic ping/status + maintenance are
    driven by the Network's heartbeat (peerManager.ts)."""

    def __init__(self, scores: Optional[PeerRpcScoreStore] = None, now=time.monotonic):
        self._now = now
        self.scores = scores or PeerRpcScoreStore(now=now)
        self.peers: Dict[str, PeerInfo] = {}
        self.banned_until: Dict[str, float] = {}
        self.bans_total = 0
        self._peer_event: Optional[asyncio.Event] = None
        # on_ban(peer_id): the owner severs the transport link — score
        # bookkeeping alone cannot disconnect a live connection (Network
        # wires this to the endpoint)
        self.on_ban: Optional[callable] = None

    # -- connection lifecycle ------------------------------------------

    def on_connect(self, peer_id: str) -> PeerInfo:
        if self.is_banned(peer_id):
            raise PeerBannedError(f"{peer_id} is banned")
        info = self.scores.peer(peer_id)
        info.connected = True
        info.disconnected_at = None
        self.peers[peer_id] = info
        if self._peer_event is not None:
            self._peer_event.set()
        return info

    def on_disconnect(self, peer_id: str) -> None:
        info = self.peers.pop(peer_id, None)
        if info:
            info.connected = False
            info.disconnected_at = self._now()

    # -- ban lifecycle --------------------------------------------------

    def ban(self, peer_id: str, duration_s: float = BAN_DURATION_S) -> None:
        """Banned ⇒ disconnected + pruned from both stores, with a
        time-boxed unban.  Idempotent; re-banning extends the window."""
        self.on_disconnect(peer_id)
        self.scores.evict(peer_id)
        self.banned_until[peer_id] = self._now() + duration_s
        self.bans_total += 1
        if self.on_ban is not None:
            self.on_ban(peer_id)

    def is_banned(self, peer_id: str) -> bool:
        until = self.banned_until.get(peer_id)
        if until is not None:
            if self._now() < until:
                return True
            del self.banned_until[peer_id]  # time-boxed unban
        return self.scores.is_banned(peer_id)

    # -- heartbeat maintenance -----------------------------------------

    def maintain(self, retention_s: float = DISCONNECT_RETENTION_S) -> None:
        """One maintenance round: escalate score-banned peers into the
        ban lifecycle, expire old bans, and prune score-store entries of
        peers disconnected longer than the retention (the unbounded-
        growth leak: nothing ever removed them)."""
        for pid in list(self.peers):
            if self.scores.is_banned(pid):
                self.ban(pid)
        now = self._now()
        for pid in [p for p, t in self.banned_until.items() if t <= now]:
            del self.banned_until[pid]
        for pid in self.scores.prune_disconnected(now - retention_s):
            self.peers.pop(pid, None)

    # -- sync re-arm signal --------------------------------------------

    async def wait_for_peer(self, timeout: Optional[float] = None) -> bool:
        """Block until a peer (re)connects; returns False on timeout.
        Used by range sync to re-arm a Stalled chain when peers return
        instead of spinning.  A connect that happened since the LAST
        wait is not lost: the event is cleared after a wake, never on
        entry (no missed-wakeup race)."""
        if self._peer_event is None:
            self._peer_event = asyncio.Event()
        try:
            await asyncio.wait_for(self._peer_event.wait(), timeout)
            self._peer_event.clear()
            return True
        except asyncio.TimeoutError:
            return False

    # -- views ----------------------------------------------------------

    def connected_peers(self) -> List[str]:
        return [
            p
            for p, i in self.peers.items()
            if i.connected and not self.is_banned(p)
        ]

    def best_peers(self, min_head_slot: int = 0) -> List[str]:
        """Peers whose reported head is usable for syncing, best score
        first (ties broken by peer id, descending — deterministic)."""
        out = []
        for pid in self.connected_peers():
            info = self.peers[pid]
            head_slot = getattr(info.status, "head_slot", 0) if info.status else 0
            if head_slot >= min_head_slot:
                out.append((self.scores.score(pid), pid))
        out.sort(reverse=True)
        return [pid for _, pid in out]
