"""Peer manager + scoring (reference:
beacon-node/src/network/peers/{peerManager,score}.ts, simplified to the
semantics that matter: per-peer score with decay, ban threshold,
status/metadata tracking, disconnect of banned peers).
"""
from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional


class PeerAction(float, Enum):
    """Score deltas (score.ts PeerAction)."""

    Fatal = -(2**10)
    LowToleranceError = -10.0
    MidToleranceError = -5.0
    HighToleranceError = -1.0


MIN_SCORE = -100.0
DEFAULT_BAN_THRESHOLD = -50.0
DISCONNECT_THRESHOLD = -20.0
SCORE_HALFLIFE_S = 600.0


@dataclass
class PeerInfo:
    peer_id: str
    score: float = 0.0
    last_update: float = field(default_factory=time.monotonic)
    status: Optional[object] = None      # ssz Status
    metadata: Optional[object] = None    # ssz Metadata
    ping_seq: int = 0
    connected: bool = True


class PeerRpcScoreStore:
    def __init__(self, now=time.monotonic):
        self._peers: Dict[str, PeerInfo] = {}
        self._now = now

    def peer(self, peer_id: str) -> PeerInfo:
        if peer_id not in self._peers:
            self._peers[peer_id] = PeerInfo(peer_id, last_update=self._now())
        return self._peers[peer_id]

    def apply_action(self, peer_id: str, action: PeerAction) -> float:
        p = self.peer(peer_id)
        self._decay(p)
        p.score = max(MIN_SCORE, p.score + float(action.value))
        return p.score

    def score(self, peer_id: str) -> float:
        p = self.peer(peer_id)
        self._decay(p)
        return p.score

    def is_banned(self, peer_id: str) -> bool:
        return self.score(peer_id) < DEFAULT_BAN_THRESHOLD

    def should_disconnect(self, peer_id: str) -> bool:
        return self.score(peer_id) < DISCONNECT_THRESHOLD

    def _decay(self, p: PeerInfo) -> None:
        now = self._now()
        dt = now - p.last_update
        if dt > 0:
            p.score *= 0.5 ** (dt / SCORE_HALFLIFE_S)
            p.last_update = now


class PeerManager:
    """Tracks connected peers; periodic ping/status handled by the
    Network's heartbeat (peerManager.ts)."""

    def __init__(self, scores: Optional[PeerRpcScoreStore] = None):
        self.scores = scores or PeerRpcScoreStore()
        self.peers: Dict[str, PeerInfo] = {}

    def on_connect(self, peer_id: str) -> PeerInfo:
        info = self.scores.peer(peer_id)
        info.connected = True
        self.peers[peer_id] = info
        return info

    def on_disconnect(self, peer_id: str) -> None:
        info = self.peers.pop(peer_id, None)
        if info:
            info.connected = False

    def connected_peers(self) -> List[str]:
        return [p for p, i in self.peers.items() if i.connected and not self.scores.is_banned(p)]

    def best_peers(self, min_head_slot: int = 0) -> List[str]:
        """Peers whose reported head is usable for syncing, best score
        first."""
        out = []
        for pid in self.connected_peers():
            info = self.peers[pid]
            head_slot = getattr(info.status, "head_slot", 0) if info.status else 0
            if head_slot >= min_head_slot:
                out.append((self.scores.score(pid), pid))
        out.sort(reverse=True)
        return [pid for _, pid in out]
