from .encoding import ReqRespError, RespStatus  # noqa: F401
from .protocols import (  # noqa: F401
    ALL_PROTOCOLS,
    BEACON_BLOCKS_BY_RANGE,
    BEACON_BLOCKS_BY_ROOT,
    GOODBYE,
    METADATA,
    PING,
    STATUS,
    BeaconBlocksByRangeRequest,
    Protocol,
)
from .rate_limiter import RateLimiterGCRA  # noqa: F401
from .reqresp import ReqRespNode  # noqa: F401
