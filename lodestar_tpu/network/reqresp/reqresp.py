"""ReqResp node: typed request/response over a transport endpoint
(reference: packages/reqresp/src/ReqResp.ts +
beacon-node/src/network/reqresp/ReqRespBeaconNode.ts).

ISSUE 15 hardening: client requests pass the ``net.reqresp.request``
checkpoint and count per-method request/timeout metrics; a timed-out or
failed request can retry on OTHER peers with a bounded attempt budget
(``request_any``); the server side passes ``net.reqresp.respond`` (a
``faults.Delay`` models a stalling responder) and sheds floods through
the GCRA limiter, reporting the flooder via ``on_rate_limited`` so the
network layer can penalize it.
"""
from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Dict, List, Optional, Sequence

from lodestar_tpu.network.transport import Endpoint
from lodestar_tpu.testing import faults
from .encoding import (
    RespStatus,
    ReqRespError,
    decode_request,
    decode_response_chunks,
    encode_error_chunk,
    encode_request,
    encode_response_chunks,
)
from .protocols import ALL_PROTOCOLS, BY_ID, Protocol
from .rate_limiter import RateLimiterGCRA

REQUEST_TIMEOUT_S = 10.0
MAX_REQUEST_ATTEMPTS = 3  # request_any's cross-peer retry budget
# GCRA server-side quota: 50 requests / 10 s per (peer, method) — THE
# default; wrappers (Network, swarm) pass None to inherit it
DEFAULT_RATE_QUOTA = (50, 10_000)


class ReqRespNode:
    """Registers protocol handlers on an Endpoint and offers typed
    client-side requests with rate limiting, timeouts, and bounded
    retry-on-another-peer."""

    def __init__(
        self,
        endpoint: Endpoint,
        rate_quota=None,
        metrics=None,
        request_timeout: float = REQUEST_TIMEOUT_S,
        on_rate_limited: Optional[Callable[[str, str], None]] = None,
    ):
        self.endpoint = endpoint
        self._handlers: Dict[str, Callable] = {}
        self.rate_limiter = RateLimiterGCRA(*(rate_quota or DEFAULT_RATE_QUOTA))
        self.request_timeout = request_timeout
        self._metrics = metrics
        # on_rate_limited(peer, method): the flood was shed — score it
        self.on_rate_limited = on_rate_limited

    def _count(self, counter: str, method: str) -> None:
        if self._metrics is None:
            return
        fam = getattr(self._metrics.lodestar, counter)
        fam.labels(method=method).inc()

    # server side ------------------------------------------------------

    def register_handler(
        self,
        protocol: Protocol,
        handler: Callable[[str, object], Awaitable[List[object]]],
    ) -> None:
        """handler(from_peer, request_value) -> list of response values."""

        async def raw_handler(from_peer: str, protocol_id: str, data: bytes) -> bytes:
            if not self.rate_limiter.allows((from_peer, protocol.method)):
                self._count("reqresp_rate_limited_total", protocol.method)
                if self.on_rate_limited is not None:
                    self.on_rate_limited(from_peer, protocol.method)
                return encode_error_chunk(RespStatus.INVALID_REQUEST, "rate limited")
            try:
                faults.fire(
                    "net.reqresp.respond",
                    peer=from_peer,
                    method=protocol.method,
                    server=getattr(self.endpoint, "peer_id", None),
                )
            except faults.Delay as d:  # stalling responder
                await asyncio.sleep(d.seconds)
            except faults.FaultError as e:
                return encode_error_chunk(RespStatus.SERVER_ERROR, str(e))
            try:
                req = decode_request(protocol.request_type, data)
            except Exception as e:
                return encode_error_chunk(RespStatus.INVALID_REQUEST, str(e))
            try:
                values = await handler(from_peer, req)
            except ReqRespError as e:
                return encode_error_chunk(e.status, str(e))
            except Exception as e:
                return encode_error_chunk(RespStatus.SERVER_ERROR, str(e))
            return encode_response_chunks(protocol.response_type, values)

        self.endpoint.handle(protocol.protocol_id, raw_handler)

    # client side ------------------------------------------------------

    async def request(
        self, peer: str, protocol: Protocol, request_value=None,
        timeout: Optional[float] = None,
    ) -> List[object]:
        try:
            faults.fire("net.reqresp.request", peer=peer, method=protocol.method)
        except faults.Delay as d:  # slow client-side path; failures raise
            await asyncio.sleep(d.seconds)
        self._count("reqresp_requests_total", protocol.method)
        data = encode_request(protocol.request_type, request_value)
        try:
            raw = await asyncio.wait_for(
                self.endpoint.request(peer, protocol.protocol_id, data),
                self.request_timeout if timeout is None else timeout,
            )
        except asyncio.TimeoutError:
            self._count("reqresp_request_timeouts_total", protocol.method)
            raise
        values, _ = decode_response_chunks(protocol.response_type, raw)
        if protocol.max_response_chunks is not None and len(values) > protocol.max_response_chunks:
            raise ReqRespError(RespStatus.INVALID_REQUEST, "too many chunks")
        return values

    async def request_any(
        self,
        peers: Sequence[str],
        protocol: Protocol,
        request_value=None,
        timeout: Optional[float] = None,
        attempts: int = MAX_REQUEST_ATTEMPTS,
    ) -> List[object]:
        """Try ``peers`` in order until one answers, spending at most
        ``attempts`` requests — the bounded retry-on-another-peer shape
        a timed-out/failed peer must not stall (reference: ReqResp
        callers iterate shuffled peer sets with attempt ceilings)."""
        if not peers:
            raise ConnectionError("no peers to request from")
        last_exc: Optional[Exception] = None
        for i, peer in enumerate(peers[:attempts]):
            if i > 0:
                self._count("reqresp_request_retries_total", protocol.method)
            try:
                return await self.request(peer, protocol, request_value, timeout)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                last_exc = e
        raise last_exc  # every attempted peer failed
