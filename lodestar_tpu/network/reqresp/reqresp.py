"""ReqResp node: typed request/response over a transport endpoint
(reference: packages/reqresp/src/ReqResp.ts +
beacon-node/src/network/reqresp/ReqRespBeaconNode.ts).
"""
from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Dict, List, Optional

from lodestar_tpu.network.transport import Endpoint
from .encoding import (
    RespStatus,
    ReqRespError,
    decode_request,
    decode_response_chunks,
    encode_error_chunk,
    encode_request,
    encode_response_chunks,
)
from .protocols import ALL_PROTOCOLS, BY_ID, Protocol
from .rate_limiter import RateLimiterGCRA

REQUEST_TIMEOUT_S = 10.0


class ReqRespNode:
    """Registers protocol handlers on an Endpoint and offers typed
    client-side requests with rate limiting and timeouts."""

    def __init__(self, endpoint: Endpoint, rate_quota=(50, 10_000)):
        self.endpoint = endpoint
        self._handlers: Dict[str, Callable] = {}
        self.rate_limiter = RateLimiterGCRA(*rate_quota)

    # server side ------------------------------------------------------

    def register_handler(
        self,
        protocol: Protocol,
        handler: Callable[[str, object], Awaitable[List[object]]],
    ) -> None:
        """handler(from_peer, request_value) -> list of response values."""

        async def raw_handler(from_peer: str, protocol_id: str, data: bytes) -> bytes:
            if not self.rate_limiter.allows((from_peer, protocol.method)):
                return encode_error_chunk(RespStatus.INVALID_REQUEST, "rate limited")
            try:
                req = decode_request(protocol.request_type, data)
            except Exception as e:
                return encode_error_chunk(RespStatus.INVALID_REQUEST, str(e))
            try:
                values = await handler(from_peer, req)
            except ReqRespError as e:
                return encode_error_chunk(e.status, str(e))
            except Exception as e:
                return encode_error_chunk(RespStatus.SERVER_ERROR, str(e))
            return encode_response_chunks(protocol.response_type, values)

        self.endpoint.handle(protocol.protocol_id, raw_handler)

    # client side ------------------------------------------------------

    async def request(
        self, peer: str, protocol: Protocol, request_value=None,
        timeout: float = REQUEST_TIMEOUT_S,
    ) -> List[object]:
        data = encode_request(protocol.request_type, request_value)
        raw = await asyncio.wait_for(
            self.endpoint.request(peer, protocol.protocol_id, data), timeout
        )
        values, _ = decode_response_chunks(protocol.response_type, raw)
        if protocol.max_response_chunks is not None and len(values) > protocol.max_response_chunks:
            raise ReqRespError(RespStatus.INVALID_REQUEST, "too many chunks")
        return values
