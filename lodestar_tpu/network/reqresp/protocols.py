"""ReqResp protocol registry (reference: packages/reqresp/src/protocols/ +
beacon-node/src/network/reqresp/types.ts:22-32).

Protocol IDs follow the wire spec:
/eth2/beacon_chain/req/{method}/{version}/ssz_snappy

NOTE: no `from __future__ import annotations` here — SSZ Container field
annotations must be live type objects, not strings.
"""
from dataclasses import dataclass
from typing import Optional

from lodestar_tpu.ssz.core import Container, uint64
from lodestar_tpu.types import ssz


class BeaconBlocksByRangeRequest(Container):
    start_slot: uint64
    count: uint64
    step: uint64


from lodestar_tpu.ssz.core import Bytes32, List as SszList  # noqa: E402

BeaconBlocksByRootRequest = SszList[Bytes32, 1024]  # MAX_REQUEST_BLOCKS


@dataclass(frozen=True)
class Protocol:
    method: str
    version: int
    request_type: Optional[object]   # SSZ type or None (metadata)
    response_type: Optional[object]  # SSZ type or None (goodbye has resp? yes uint64)
    # max chunks a response may contain (None = single chunk)
    max_response_chunks: Optional[int] = 1

    @property
    def protocol_id(self) -> str:
        return f"/eth2/beacon_chain/req/{self.method}/{self.version}/ssz_snappy"


STATUS = Protocol("status", 1, ssz.phase0.Status, ssz.phase0.Status)
GOODBYE = Protocol("goodbye", 1, uint64, uint64)
PING = Protocol("ping", 1, uint64, uint64)
METADATA = Protocol("metadata", 2, None, ssz.phase0.Metadata)
# fork-aware block codec: resolves phase0/altair from the slot inside the
# serialized block (configured by Network from the chain config)
from lodestar_tpu.types import signed_block_wire_codec

BEACON_BLOCKS_BY_RANGE = Protocol(
    "beacon_blocks_by_range", 1, BeaconBlocksByRangeRequest,
    signed_block_wire_codec, max_response_chunks=1024,
)
BEACON_BLOCKS_BY_ROOT = Protocol(
    "beacon_blocks_by_root", 1, BeaconBlocksByRootRequest,
    signed_block_wire_codec, max_response_chunks=1024,
)

ALL_PROTOCOLS = [
    STATUS, GOODBYE, PING, METADATA, BEACON_BLOCKS_BY_RANGE, BEACON_BLOCKS_BY_ROOT
]
BY_ID = {p.protocol_id: p for p in ALL_PROTOCOLS}
