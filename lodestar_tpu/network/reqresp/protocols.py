"""ReqResp protocol registry (reference: packages/reqresp/src/protocols/ +
beacon-node/src/network/reqresp/types.ts:22-32).

Protocol IDs follow the wire spec:
/eth2/beacon_chain/req/{method}/{version}/ssz_snappy

NOTE: no `from __future__ import annotations` here — SSZ Container field
annotations must be live type objects, not strings.
"""
from dataclasses import dataclass
from typing import Optional

from lodestar_tpu.ssz.core import Container, uint64
from lodestar_tpu.types import ssz


class BeaconBlocksByRangeRequest(Container):
    start_slot: uint64
    count: uint64
    step: uint64


from lodestar_tpu.ssz.core import Bytes32, List as SszList  # noqa: E402

BeaconBlocksByRootRequest = SszList[Bytes32, 1024]  # MAX_REQUEST_BLOCKS


@dataclass(frozen=True)
class Protocol:
    method: str
    version: int
    request_type: Optional[object]   # SSZ type or None (metadata)
    response_type: Optional[object]  # SSZ type or None (goodbye has resp? yes uint64)
    # max chunks a response may contain (None = single chunk)
    max_response_chunks: Optional[int] = 1

    @property
    def protocol_id(self) -> str:
        return f"/eth2/beacon_chain/req/{self.method}/{self.version}/ssz_snappy"


STATUS = Protocol("status", 1, ssz.phase0.Status, ssz.phase0.Status)
GOODBYE = Protocol("goodbye", 1, uint64, uint64)
PING = Protocol("ping", 1, uint64, uint64)
METADATA = Protocol("metadata", 2, None, ssz.phase0.Metadata)
# fork-aware block codec: resolves phase0/altair from the slot inside the
# serialized block (configured by Network from the chain config)
from lodestar_tpu.types import signed_block_wire_codec

BEACON_BLOCKS_BY_RANGE = Protocol(
    "beacon_blocks_by_range", 1, BeaconBlocksByRangeRequest,
    signed_block_wire_codec, max_response_chunks=1024,
)
BEACON_BLOCKS_BY_ROOT = Protocol(
    "beacon_blocks_by_root", 1, BeaconBlocksByRootRequest,
    signed_block_wire_codec, max_response_chunks=1024,
)


# eip4844 blobs (reference network/reqresp/types.ts BlobsSidecarsByRange /
# BeaconBlockAndBlobsSidecarByRoot)
class BlobsSidecarsByRangeRequest(Container):
    start_slot: uint64
    count: uint64


BLOBS_SIDECARS_BY_RANGE = Protocol(
    "blobs_sidecars_by_range", 1, BlobsSidecarsByRangeRequest,
    ssz.eip4844.BlobsSidecar, max_response_chunks=128,
)
BEACON_BLOCK_AND_BLOBS_SIDECAR_BY_ROOT = Protocol(
    "beacon_block_and_blobs_sidecar_by_root", 1, BeaconBlocksByRootRequest,
    ssz.eip4844.SignedBeaconBlockAndBlobsSidecar, max_response_chunks=1024,
)


# light client (reference reqresp/protocols/LightClient*.ts)
class LightClientUpdatesByRangeRequest(Container):
    start_period: uint64
    count: uint64


LIGHT_CLIENT_BOOTSTRAP = Protocol(
    "light_client_bootstrap", 1, Bytes32, ssz.altair.LightClientBootstrap
)
LIGHT_CLIENT_UPDATES_BY_RANGE = Protocol(
    "light_client_updates_by_range", 1, LightClientUpdatesByRangeRequest,
    ssz.altair.LightClientUpdate, max_response_chunks=128,
)
LIGHT_CLIENT_FINALITY_UPDATE = Protocol(
    "light_client_finality_update", 1, None, ssz.altair.LightClientFinalityUpdate
)
LIGHT_CLIENT_OPTIMISTIC_UPDATE = Protocol(
    "light_client_optimistic_update", 1, None, ssz.altair.LightClientOptimisticUpdate
)

ALL_PROTOCOLS = [
    STATUS, GOODBYE, PING, METADATA, BEACON_BLOCKS_BY_RANGE,
    BEACON_BLOCKS_BY_ROOT, BLOBS_SIDECARS_BY_RANGE,
    BEACON_BLOCK_AND_BLOBS_SIDECAR_BY_ROOT, LIGHT_CLIENT_BOOTSTRAP,
    LIGHT_CLIENT_UPDATES_BY_RANGE, LIGHT_CLIENT_FINALITY_UPDATE,
    LIGHT_CLIENT_OPTIMISTIC_UPDATE,
]
BY_ID = {p.protocol_id: p for p in ALL_PROTOCOLS}
