"""ssz_snappy reqresp encoding (reference:
packages/reqresp/src/encodingStrategies/sszSnappy/{encode,decode}.ts:27):
unsigned protobuf varint of the SSZ byte length, then the payload as a
snappy FRAMED stream.  Response streams carry one result byte per chunk
(0 = success, 1 = InvalidRequest, 2 = ServerError, 3 = ResourceUnavailable)
before the encoded payload; error chunks carry an ssz_snappy ErrorMessage.
"""
from __future__ import annotations

from enum import IntEnum
from typing import Iterator, List, Optional, Tuple

from lodestar_tpu.utils.snappy import (
    _read_uvarint,
    _write_uvarint,
    frame_compress,
    frame_decompress,
)

MAX_PAYLOAD = 10 * 1024 * 1024


class RespStatus(IntEnum):
    SUCCESS = 0
    INVALID_REQUEST = 1
    SERVER_ERROR = 2
    RESOURCE_UNAVAILABLE = 3


class ReqRespError(Exception):
    def __init__(self, status: RespStatus, message: str = ""):
        super().__init__(f"{status.name}: {message}")
        self.status = status


def encode_payload(ssz_type, value) -> bytes:
    data = ssz_type.serialize(value)
    return _write_uvarint(len(data)) + frame_compress(data)


def decode_payload(ssz_type, data: bytes) -> Tuple[object, int]:
    """Decode one varint+framed payload; returns (value, bytes_consumed)."""
    length, pos = _read_uvarint(data, 0)
    if length > MAX_PAYLOAD:
        raise ValueError(f"payload too large: {length}")
    raw = frame_decompress_prefix(data[pos:], length)
    consumed = pos + raw[1]
    return ssz_type.deserialize(raw[0]), consumed


def frame_decompress_prefix(data: bytes, want: int) -> Tuple[bytes, int]:
    """Decompress frames until `want` bytes produced; returns
    (payload, compressed_bytes_consumed).  Needed because response streams
    concatenate chunks back-to-back."""
    import struct

    from lodestar_tpu.utils.snappy import STREAM_IDENTIFIER, _masked_crc, decompress

    pos = 0
    out = bytearray()
    seen_id = False
    while len(out) < want:
        if pos + 4 > len(data):
            raise ValueError("truncated frame header")
        kind = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 4], "little")
        body = data[pos + 4 : pos + 4 + length]
        if len(body) != length:
            raise ValueError("truncated frame body")
        pos += 4 + length
        if kind == 0xFF:
            seen_id = True
            continue
        if not seen_id:
            raise ValueError("missing stream identifier")
        if kind == 0x00:
            crc = struct.unpack("<I", body[:4])[0]
            chunk = decompress(body[4:])
        elif kind == 0x01:
            crc = struct.unpack("<I", body[:4])[0]
            chunk = body[4:]
        elif 0x80 <= kind <= 0xFD:
            continue
        else:
            raise ValueError(f"unknown frame kind {kind:#x}")
        if _masked_crc(chunk) != crc:
            raise ValueError("frame crc mismatch")
        out += chunk
    if len(out) != want:
        raise ValueError("frame overshoot")
    return bytes(out), pos


# ---------------------------------------------------------------------------
# request / response streams
# ---------------------------------------------------------------------------


def encode_request(ssz_type, value) -> bytes:
    if ssz_type is None:
        return b""
    return encode_payload(ssz_type, value)


def decode_request(ssz_type, data: bytes):
    if ssz_type is None:
        return None
    value, _ = decode_payload(ssz_type, data)
    return value


def encode_response_chunks(ssz_type, values, context_bytes: bytes = b"") -> bytes:
    """Success chunks: <result=0><context><varint><frames> per value."""
    out = bytearray()
    for v in values:
        out += bytes([RespStatus.SUCCESS]) + context_bytes + encode_payload(ssz_type, v)
    return bytes(out)


def encode_error_chunk(status: RespStatus, message: str) -> bytes:
    from lodestar_tpu.ssz.core import ByteListT

    err_t = ByteListT(256)
    return bytes([status]) + encode_payload(err_t, message.encode()[:256])


def decode_response_chunks(ssz_type, data: bytes, context_bytes_len: int = 0):
    """Yield decoded values; raise ReqRespError on an error chunk."""
    pos = 0
    out = []
    contexts = []
    while pos < len(data):
        status = data[pos]
        pos += 1
        if status != RespStatus.SUCCESS:
            from lodestar_tpu.ssz.core import ByteListT

            try:
                msg, _ = decode_payload(ByteListT(256), data[pos:])
                text = bytes(msg).decode(errors="replace")
            except Exception as e:
                # the ReqRespError below is the surfaced fault; note
                # that the peer's error text itself was undecodable
                text = f"<undecodable error payload: {type(e).__name__}>"
            raise ReqRespError(RespStatus(status), text)
        ctx = data[pos : pos + context_bytes_len]
        pos += context_bytes_len
        value, consumed = decode_payload(ssz_type, data[pos:])
        pos += consumed
        out.append(value)
        contexts.append(ctx)
    return out, contexts
