"""GCRA rate limiter (reference:
packages/reqresp/src/rate_limiter/rateLimiterGRCA.ts).

Generic Cell Rate Algorithm: a theoretical-arrival-time per key; a request
of weight w is allowed iff TAT <= now + burst_window.
"""
from __future__ import annotations

import time
from typing import Dict, Hashable


class RateLimiterGCRA:
    def __init__(self, quota: int, quota_time_ms: int, now=time.monotonic):
        """Allow `quota` units per `quota_time_ms` window with full-burst
        tolerance (matches rateLimiterGRCA.ts::fromQuota)."""
        self._emission_ms = quota_time_ms / max(1, quota)
        self._burst_ms = quota_time_ms
        self._tat: Dict[Hashable, float] = {}
        self._now = now

    def allows(self, key: Hashable, weight: int = 1) -> bool:
        now_ms = self._now() * 1e3
        tat = self._tat.get(key, now_ms)
        new_tat = max(tat, now_ms) + weight * self._emission_ms
        if new_tat - now_ms > self._burst_ms:
            return False
        self._tat[key] = new_tat
        return True

    def __len__(self) -> int:
        """Tracked keys (per-peer TAT state) — bounded only because the
        network heartbeat calls prune()."""
        return len(self._tat)

    def prune(self, older_than_ms: float = 60_000) -> None:
        now_ms = self._now() * 1e3
        for k in [k for k, t in self._tat.items() if t < now_ms - older_than_ms]:
            del self._tat[k]
