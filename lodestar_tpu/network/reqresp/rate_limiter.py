"""GCRA rate limiter (reference:
packages/reqresp/src/rate_limiter/rateLimiterGRCA.ts).

Generic Cell Rate Algorithm: a theoretical-arrival-time per key; a request
of weight w is allowed iff TAT <= now + burst_window.
"""
from __future__ import annotations

import time
from typing import Dict, Hashable, Optional


class RateLimiterGCRA:
    def __init__(
        self,
        quota: int,
        quota_time_ms: int,
        now=time.monotonic,
        shares: Optional[Dict[Hashable, float]] = None,
    ):
        """Allow `quota` units per `quota_time_ms` window with full-burst
        tolerance (matches rateLimiterGRCA.ts::fromQuota).

        ``shares`` scales a key's quota: a key with share s advances its
        TAT by ``weight/s`` emission intervals per admitted unit, so it
        sustains ``s * quota`` units per window (and its largest
        admissible single request scales to ``s * quota`` units too).
        Unlisted keys have share 1.0."""
        self._emission_ms = quota_time_ms / max(1, quota)
        self._burst_ms = quota_time_ms
        self._tat: Dict[Hashable, float] = {}
        self._now = now
        self._shares: Dict[Hashable, float] = dict(shares or {})

    def set_share(self, key: Hashable, share: float) -> None:
        """(Re)weight a key; share must be positive."""
        if share <= 0:
            raise ValueError(f"share must be positive, got {share}")
        self._shares[key] = share

    def allows(self, key: Hashable, weight: float = 1) -> bool:
        now_ms = self._now() * 1e3
        tat = self._tat.get(key, now_ms)
        share = self._shares.get(key, 1.0)
        new_tat = max(tat, now_ms) + (weight / share) * self._emission_ms
        if new_tat - now_ms > self._burst_ms:
            # shed WITHOUT mutating TAT: a rejected burst must not
            # poison the key's own future quota
            return False
        self._tat[key] = new_tat
        return True

    def __len__(self) -> int:
        """Tracked keys (per-peer TAT state) — bounded only because the
        network heartbeat calls prune()."""
        return len(self._tat)

    def prune(self, older_than_ms: float = 60_000) -> None:
        now_ms = self._now() * 1e3
        for k in [k for k, t in self._tat.items() if t < now_ms - older_than_ms]:
            del self._tat[k]
