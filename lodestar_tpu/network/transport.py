"""In-process network transport — the loopback fabric multi-node sim tests
run on (reference: beacon-node/test/utils/network.ts wires N in-process
nodes over loopback libp2p; SURVEY §4 "Sim / multi-node").

The Hub routes reqresp calls and gossip publishes between registered
endpoints with optional per-link latency, mimicking the libp2p seams
(streams + pubsub) the production stack would provide; the consuming code
(ReqRespNode, Eth2Gossip, Network) is transport-agnostic.
"""
from __future__ import annotations

import asyncio
import secrets
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional, Set, Tuple

RequestHandler = Callable[[str, str, bytes], Awaitable[bytes]]
# (from_peer, topic, raw_message) -> None
GossipHandler = Callable[[str, str, bytes], Awaitable[None]]


def random_peer_id() -> str:
    return "16U" + secrets.token_hex(16)


class InProcessHub:
    def __init__(self, latency_s: float = 0.0):
        self.endpoints: Dict[str, "Endpoint"] = {}
        self.latency_s = latency_s

    def register(self, endpoint: "Endpoint") -> None:
        self.endpoints[endpoint.peer_id] = endpoint

    def unregister(self, peer_id: str) -> None:
        self.endpoints.pop(peer_id, None)

    async def request(
        self, from_peer: str, to_peer: str, protocol_id: str, data: bytes
    ) -> bytes:
        ep = self.endpoints.get(to_peer)
        if ep is None:
            raise ConnectionError(f"unknown peer {to_peer}")
        if self.latency_s:
            await asyncio.sleep(self.latency_s)
        handler = ep.request_handlers.get(protocol_id)
        if handler is None:
            raise ConnectionError(f"{to_peer} does not speak {protocol_id}")
        return await handler(from_peer, protocol_id, data)

    async def publish(self, from_peer: str, topic: str, message: bytes) -> int:
        """Deliver to every subscribed endpoint except the sender; returns
        receiver count (gossipsub mesh broadcast collapsed to one hop)."""
        count = 0
        for ep in list(self.endpoints.values()):
            if ep.peer_id == from_peer:
                continue
            handler = ep.subscriptions.get(topic)
            if handler is None:
                continue
            count += 1
            if self.latency_s:
                await asyncio.sleep(self.latency_s)
            ep.deliver(from_peer, topic, message)
        return count

    def peers_of(self, peer_id: str) -> List[str]:
        return [p for p in self.endpoints if p != peer_id]


class Endpoint:
    """One node's attachment to the hub."""

    def __init__(self, hub: InProcessHub, peer_id: Optional[str] = None):
        self.hub = hub
        self.peer_id = peer_id or random_peer_id()
        self.request_handlers: Dict[str, RequestHandler] = {}
        self.subscriptions: Dict[str, GossipHandler] = {}
        self._tasks: Set[asyncio.Task] = set()
        hub.register(self)

    # reqresp ----------------------------------------------------------

    def handle(self, protocol_id: str, handler: RequestHandler) -> None:
        self.request_handlers[protocol_id] = handler

    async def request(self, to_peer: str, protocol_id: str, data: bytes) -> bytes:
        return await self.hub.request(self.peer_id, to_peer, protocol_id, data)

    # gossip -----------------------------------------------------------

    def subscribe(self, topic: str, handler: GossipHandler) -> None:
        self.subscriptions[topic] = handler

    def unsubscribe(self, topic: str) -> None:
        self.subscriptions.pop(topic, None)

    async def publish(self, topic: str, message: bytes) -> int:
        return await self.hub.publish(self.peer_id, topic, message)

    def deliver(self, from_peer: str, topic: str, message: bytes) -> None:
        handler = self.subscriptions.get(topic)
        if handler is None:
            return
        task = asyncio.ensure_future(handler(from_peer, topic, message))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def close(self) -> None:
        self.hub.unregister(self.peer_id)
        for t in self._tasks:
            t.cancel()
