"""Peer discovery — the rebuild's discv5 layer (reference:
beacon-node/src/network/peers/discover.ts:79-119 wrapping
@chainsafe/discv5: signed node records, a Kademlia XOR routing table,
PING/PONG/FINDNODE/NODES over UDP datagrams, and subnet-targeted queries
feeding the peer manager).

Idiomatic deviations from wire-discv5 (documented, deliberate):

- **Identity scheme**: discv5 `v4` signs records with secp256k1; this
  framework ships no secp256k1 but does ship a complete from-scratch
  BLS12-381 stack, so node records are BLS-signed (`bls` identity
  scheme): ``node_id = sha256(pubkey)``, signature over the record
  content's hash_tree_root.  Record verification batches through the
  same `IBlsVerifier` path as every other signature in the node.
- **Wire format**: records and messages are SSZ containers (the
  codebase's single serialization engine) rather than RLP, framed with a
  1-byte message-type tag.  Session encryption (discv5's handshake/AES-GCM
  layer) is out of scope for the in-process/sim transports; the
  `DatagramEndpoint` seam is where it would bolt on.

The Kademlia mechanics (log2-distance buckets, iterative lookups over
FINDNODE with multiple distances, liveness via PING/PONG with ENR seq
freshness) follow the discv5 spec shape so the service behaves like the
reference's: it continuously tops up the peer manager and answers
subnet queries from ENR `attnets`/`syncnets` bitfields
(discover.ts subnetRequests / `shouldDialPeer`).
"""
# NOTE: no `from __future__ import annotations` — container field
# annotations must stay real SszType objects (ssz/core.py ContainerMeta).

import asyncio
import hashlib
import secrets
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional, Set, Tuple

from lodestar_tpu import ssz as s
from lodestar_tpu.utils import Logger

# ---------------------------------------------------------------------------
# node records (ENR role)
# ---------------------------------------------------------------------------


class ENRContent(s.Container):
    seq: s.uint64
    pubkey: s.Bytes48           # BLS identity key (compressed G1)
    ip: s.Bytes4
    udp_port: s.uint16
    tcp_port: s.uint16
    fork_digest: s.Bytes4       # the "eth2" ENR field's discriminant part
    attnets: s.Bitvector[64]
    syncnets: s.Bitvector[4]


class ENR(s.Container):
    content: ENRContent
    signature: s.Bytes96


def node_id_of(enr: "ENR") -> bytes:
    return hashlib.sha256(bytes(enr.content.pubkey)).digest()


def log2_distance(a: bytes, b: bytes) -> int:
    """discv5 log2 XOR distance: 0 for identical ids, else 256 - clz."""
    x = int.from_bytes(a, "big") ^ int.from_bytes(b, "big")
    return x.bit_length()


@dataclass
class LocalIdentity:
    """The node's own key + mutable record (seq bumps on change)."""

    secret_key: object  # crypto.bls.api.SecretKey
    ip: bytes = b"\x7f\x00\x00\x01"
    udp_port: int = 9000
    tcp_port: int = 9000
    fork_digest: bytes = b"\x00" * 4
    attnets: Optional[List[bool]] = None
    syncnets: Optional[List[bool]] = None
    seq: int = 1

    def to_enr(self) -> ENR:
        content = ENRContent(
            seq=self.seq,
            pubkey=self.secret_key.to_public_key().to_bytes(),
            ip=self.ip,
            udp_port=self.udp_port,
            tcp_port=self.tcp_port,
            fork_digest=self.fork_digest,
            attnets=self.attnets or [False] * 64,
            syncnets=self.syncnets or [False] * 4,
        )
        msg = ENRContent.hash_tree_root(content)
        sig = self.secret_key.sign(msg)
        return ENR(content=content, signature=sig.to_bytes())

    def bump(self, **changes) -> None:
        for k, v in changes.items():
            setattr(self, k, v)
        self.seq += 1


def verify_enr(enr: ENR) -> bool:
    """BLS identity-scheme check (discv5 verifies the v4 secp256k1 sig)."""
    from lodestar_tpu.crypto.bls import api

    try:
        pk = api.PublicKey.from_bytes(bytes(enr.content.pubkey))
        sig = api.Signature.from_bytes(bytes(enr.signature))
    # malformed pubkey/signature bytes are an invalid-ENR verdict
    # (False), not a fault to surface
    except Exception:  # lodelint: disable=silent-except
        return False
    return api.verify(pk, ENRContent.hash_tree_root(enr.content), sig)


# ---------------------------------------------------------------------------
# routing table (Kademlia k-buckets by log2 distance)
# ---------------------------------------------------------------------------

BUCKET_SIZE = 16


class KBuckets:
    def __init__(self, local_id: bytes):
        self.local_id = local_id
        self.buckets: Dict[int, List[Tuple[bytes, ENR]]] = {}

    def update(self, enr: ENR) -> None:
        nid = node_id_of(enr)
        if nid == self.local_id:
            return
        d = log2_distance(self.local_id, nid)
        bucket = self.buckets.setdefault(d, [])
        for i, (bid, existing) in enumerate(bucket):
            if bid == nid:
                if int(enr.content.seq) >= int(existing.content.seq):
                    # refresh: move to tail (most recently seen)
                    bucket.pop(i)
                    bucket.append((nid, enr))
                return
        if len(bucket) < BUCKET_SIZE:
            bucket.append((nid, enr))
        # full bucket: drop (liveness-check eviction is the caller's job
        # via remove() when a PING times out)

    def remove(self, nid: bytes) -> None:
        d = log2_distance(self.local_id, nid)
        bucket = self.buckets.get(d, [])
        self.buckets[d] = [(b, e) for (b, e) in bucket if b != nid]

    def at_distance(self, d: int, limit: int = BUCKET_SIZE) -> List[ENR]:
        if d == 0:
            return []
        return [e for _, e in self.buckets.get(d, [])[:limit]]

    def closest(self, target: bytes, limit: int = BUCKET_SIZE) -> List[ENR]:
        all_nodes = [(nid, e) for b in self.buckets.values() for nid, e in b]
        all_nodes.sort(
            key=lambda t: int.from_bytes(t[0], "big")
            ^ int.from_bytes(target, "big")
        )
        return [e for _, e in all_nodes[:limit]]

    def all(self) -> List[ENR]:
        return [e for b in self.buckets.values() for _, e in b]

    def __len__(self) -> int:
        return sum(len(b) for b in self.buckets.values())


# ---------------------------------------------------------------------------
# wire messages (SSZ + 1-byte tag)
# ---------------------------------------------------------------------------


class PingMsg(s.Container):
    request_id: s.uint64
    enr_seq: s.uint64


class PongMsg(s.Container):
    request_id: s.uint64
    enr_seq: s.uint64


class FindNodeMsg(s.Container):
    request_id: s.uint64
    distances: s.List[s.uint16, 8]


class NodesMsg(s.Container):
    request_id: s.uint64
    enrs: s.List[ENR, 16]


_TAGS = {1: PingMsg, 2: PongMsg, 3: FindNodeMsg, 4: NodesMsg}
_TAG_OF = {v: k for k, v in _TAGS.items()}


def encode_message(msg) -> bytes:
    t = type(msg)
    return bytes([_TAG_OF[t]]) + t.serialize(msg)


def decode_message(data: bytes):
    if not data or data[0] not in _TAGS:
        raise ValueError("bad discovery datagram")
    t = _TAGS[data[0]]
    return t.deserialize(data[1:])


# ---------------------------------------------------------------------------
# datagram transport seam
# ---------------------------------------------------------------------------

# async (from_addr, data) -> None
DatagramReceiver = Callable[[str, bytes], Awaitable[None]]


class InProcessDatagramHub:
    """Loopback UDP fabric for tests/sim (same role the InProcessHub plays
    for streams; addresses are opaque strings)."""

    def __init__(self, loss_rate: float = 0.0):
        self.endpoints: Dict[str, DatagramReceiver] = {}
        self.loss_rate = loss_rate
        self._rng = secrets.SystemRandom()

    def register(self, addr: str, receiver: DatagramReceiver) -> None:
        self.endpoints[addr] = receiver

    def unregister(self, addr: str) -> None:
        self.endpoints.pop(addr, None)

    async def send(self, from_addr: str, to_addr: str, data: bytes) -> None:
        rx = self.endpoints.get(to_addr)
        if rx is None:
            return  # UDP: silently dropped
        if self.loss_rate and self._rng.random() < self.loss_rate:
            return
        await rx(from_addr, data)


class UdpEndpoint:
    """Real asyncio UDP endpoint (production transport).  Addresses are
    "ip:port" strings."""

    def __init__(self):
        self._transport = None
        self._receiver: Optional[DatagramReceiver] = None
        # strong refs: the loop holds tasks weakly, so an unreferenced
        # datagram-handler task could be GC'd mid-flight
        self._tasks: Set[asyncio.Task] = set()

    async def open(self, host: str, port: int, receiver: DatagramReceiver):
        self._receiver = receiver
        loop = asyncio.get_running_loop()

        outer = self

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                if outer._receiver is not None:
                    task = asyncio.ensure_future(
                        outer._receiver(f"{addr[0]}:{addr[1]}", data)
                    )
                    outer._tasks.add(task)
                    task.add_done_callback(outer._tasks.discard)

        self._transport, _ = await loop.create_datagram_endpoint(
            _Proto, local_addr=(host, port)
        )

    async def send(self, _from: str, to_addr: str, data: bytes) -> None:
        host, port = to_addr.rsplit(":", 1)
        if self._transport is not None:
            self._transport.sendto(data, (host, int(port)))

    def close(self) -> None:
        if self._transport is not None:
            self._transport.close()
        # in-flight datagram handlers must not outlive the endpoint: a
        # handler resumed after close() would touch a dead transport
        for task in self._tasks:
            task.cancel()
        self._tasks.clear()


# ---------------------------------------------------------------------------
# the discovery service
# ---------------------------------------------------------------------------

REQUEST_TIMEOUT_S = 2.0
LOOKUP_PARALLELISM = 3          # discv5 alpha


def enr_addr(enr: ENR) -> str:
    ip = bytes(enr.content.ip)
    return f"{ip[0]}.{ip[1]}.{ip[2]}.{ip[3]}:{int(enr.content.udp_port)}"


class DiscoveryService:
    """discv5-shaped service: answers the protocol, keeps the table fresh,
    and surfaces peers to the caller (PeerDiscovery role in discover.ts).

    `send` is any (from_addr, to_addr, data) coroutine — the in-process
    hub in tests, a UdpEndpoint in production.
    """

    def __init__(
        self,
        identity: LocalIdentity,
        send,
        *,
        addr: Optional[str] = None,
        verify_records: bool = False,
        logger: Optional[Logger] = None,
        now=time.monotonic,
    ):
        self.identity = identity
        self.enr = identity.to_enr()
        self.node_id = node_id_of(self.enr)
        self.table = KBuckets(self.node_id)
        self._send = send
        self.addr = addr or enr_addr(self.enr)
        self.verify_records = verify_records
        self.log = logger.child("discv5") if logger else Logger("discv5")
        self._now = now
        self._req_id = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self._addr_of: Dict[bytes, str] = {}
        self._running = False
        self._task: Optional[asyncio.Task] = None
        # discovered-node callbacks (peer manager top-up)
        self.on_discovered: List[Callable[[ENR], None]] = []

    # -- record ingestion ------------------------------------------------

    def _ingest(self, enr: ENR) -> None:
        if self.verify_records and not verify_enr(enr):
            return
        nid = node_id_of(enr)
        if nid == self.node_id:
            return
        self._addr_of[nid] = enr_addr(enr)
        before = len(self.table)
        self.table.update(enr)
        if len(self.table) > before:
            for cb in self.on_discovered:
                cb(enr)

    def add_bootnode(self, enr: ENR) -> None:
        """Seed the table (bootEnrs in the reference's discv5 opts)."""
        self._ingest(enr)

    # -- inbound ---------------------------------------------------------

    async def on_datagram(self, from_addr: str, data: bytes) -> None:
        try:
            msg = decode_message(data)
        except ValueError:
            return
        if isinstance(msg, PingMsg):
            await self._reply(
                from_addr,
                PongMsg(request_id=msg.request_id, enr_seq=self.identity.seq),
            )
        elif isinstance(msg, FindNodeMsg):
            found: List[ENR] = [self.enr] if 0 in list(msg.distances) else []
            for d in msg.distances:
                found.extend(self.table.at_distance(int(d)))
            if len(found) < 4:
                # sparse buckets at the requested distances: backfill with
                # other known records so small meshes still converge
                # (deviation from strict discv5, which answers only the
                # asked distances — fine here since responses are capped
                # and records are self-certifying).
                seen = {node_id_of(e) for e in found}
                for e in self.table.all():
                    if node_id_of(e) not in seen:
                        found.append(e)
                        seen.add(node_id_of(e))
                    if len(found) >= 8:
                        break
            await self._reply(
                from_addr,
                NodesMsg(request_id=msg.request_id, enrs=found[:16]),
            )
        elif isinstance(msg, (PongMsg, NodesMsg)):
            fut = self._pending.pop(int(msg.request_id), None)
            if fut is not None and not fut.done():
                fut.set_result(msg)
            if isinstance(msg, NodesMsg):
                for enr in msg.enrs:
                    self._ingest(enr)

    async def _reply(self, to_addr: str, msg) -> None:
        await self._send(self.addr, to_addr, encode_message(msg))

    # -- outbound --------------------------------------------------------

    async def _request(self, to_addr: str, msg) -> Optional[object]:
        rid = int(msg.request_id)
        fut = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        await self._send(self.addr, to_addr, encode_message(msg))
        try:
            return await asyncio.wait_for(fut, REQUEST_TIMEOUT_S)
        except asyncio.TimeoutError:
            self._pending.pop(rid, None)
            return None

    def _next_id(self) -> int:
        self._req_id += 1
        return self._req_id

    async def ping(self, enr: ENR) -> bool:
        """Liveness probe; evicts dead nodes (bucket maintenance)."""
        msg = PingMsg(request_id=self._next_id(), enr_seq=self.identity.seq)
        pong = await self._request(enr_addr(enr), msg)
        if pong is None:
            self.table.remove(node_id_of(enr))
            return False
        return True

    async def find_node(self, enr: ENR, distances: List[int]) -> List[ENR]:
        msg = FindNodeMsg(
            request_id=self._next_id(), distances=distances[:8]
        )
        nodes = await self._request(enr_addr(enr), msg)
        if nodes is None:
            return []
        return list(nodes.enrs)

    async def lookup(self, target: Optional[bytes] = None) -> List[ENR]:
        """Iterative Kademlia lookup toward `target` (random by default) —
        the table-refresh walk discv5 runs continuously."""
        target = target or secrets.token_bytes(32)
        queried: Set[bytes] = set()
        for _round in range(4):  # bounded iterative deepening
            candidates = [
                e
                for e in self.table.closest(target, LOOKUP_PARALLELISM * 2)
                if node_id_of(e) not in queried
            ][:LOOKUP_PARALLELISM]
            if not candidates:
                break
            results = await asyncio.gather(
                *(
                    self.find_node(
                        e,
                        sorted(
                            {
                                log2_distance(node_id_of(e), target),
                                max(1, log2_distance(node_id_of(e), target) - 1),
                                min(256, log2_distance(node_id_of(e), target) + 1),
                            }
                        ),
                    )
                    for e in candidates
                ),
                # a peer erroring mid-lookup must not detach the sibling
                # queries; a failed query just contributes no nodes
                return_exceptions=True,
            )
            queried.update(node_id_of(e) for e in candidates)
            if not any(r for r in results if not isinstance(r, BaseException)):
                break
        return self.table.closest(target)

    # -- queries the node actually makes (discover.ts API) ---------------

    def subnet_peers(
        self, subnet: int, kind: str = "attnets", limit: int = 16
    ) -> List[ENR]:
        """ENRs advertising membership of an att/sync subnet
        (discover.ts subnetRequests filtering on the attnets bitfield)."""
        out = []
        for enr in self.table.all():
            bits = getattr(enr.content, kind)
            if subnet < len(bits) and bool(bits[subnet]):
                out.append(enr)
                if len(out) >= limit:
                    break
        return out

    async def discover_peers(self, count: int = 16) -> List[ENR]:
        """One discovery round: lookup + return up to `count` records."""
        await self.lookup()
        return self.table.closest(secrets.token_bytes(32), count)

    # -- background refresh loop ----------------------------------------

    async def start(self, interval_s: float = 30.0) -> None:
        self._running = True

        async def _loop():
            while self._running:
                try:
                    await self.lookup()
                except Exception as e:
                    self.log.warn(
                        f"discovery lookup failed: {type(e).__name__}: {e}"
                    )
                await asyncio.sleep(interval_s)

        self._task = asyncio.create_task(_loop())

    async def stop(self) -> None:
        self._running = False
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass  # our own cancel — the expected outcome
            except Exception as e:
                self.log.debug(
                    f"discovery task ended with {type(e).__name__}: {e}"
                )
            self._task = None
