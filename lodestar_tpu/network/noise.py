"""Noise-XX-style AEAD session handshake for the wire transport.

Role parity: the reference secures every libp2p connection with the
Noise protocol (@chainsafe/libp2p-noise + as-chacha20poly1305, SURVEY
§2.3); this module fills that role for the rebuild's TCP transport with
the same primitive suite (X25519 DH, SHA-256 HKDF chaining, ChaCha20-
Poly1305 AEAD) and the XX pattern's shape:

    -> e
    <- e, ee, s, es
    -> s, se

Both sides authenticate via static X25519 keys; the peer id is derived
from the remote static key, so a peer cannot claim another's identity
without its key.  DOCUMENTED DEVIATION (like discovery.py's): this is a
self-consistent implementation of the pattern, not wire-compatible with
libp2p-noise's framing (no libp2p handshake payload signatures); both
ends of every connection run this stack.

Transport framing after the handshake: 4-byte big-endian ciphertext
length || ChaCha20Poly1305(plaintext), nonce = 4 zero bytes || 8-byte
little-endian per-direction counter.  A tampered or replayed frame fails
authentication and tears down the connection.
"""
from __future__ import annotations

import hashlib
import hmac as _hmac
from dataclasses import dataclass

from cryptography.hazmat.primitives import serialization
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey,
    X25519PublicKey,
)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

_PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256/lodestar-tpu"


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _hkdf2(chaining_key: bytes, input_key_material: bytes) -> tuple:
    """Noise HKDF with two outputs (RFC 5869 with SHA-256)."""
    temp = _hmac.new(chaining_key, input_key_material, hashlib.sha256).digest()
    out1 = _hmac.new(temp, b"\x01", hashlib.sha256).digest()
    out2 = _hmac.new(temp, out1 + b"\x02", hashlib.sha256).digest()
    return out1, out2


def _pub_bytes(pub: X25519PublicKey) -> bytes:
    return pub.public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )


class HandshakeError(ConnectionError):
    pass


class _SymmetricState:
    def __init__(self):
        self.h = _sha256(_PROTOCOL_NAME)
        self.ck = self.h
        self.k: bytes | None = None
        self.n = 0

    def mix_hash(self, data: bytes) -> None:
        self.h = _sha256(self.h + data)

    def mix_key(self, ikm: bytes) -> None:
        self.ck, self.k = _hkdf2(self.ck, ikm)
        self.n = 0

    def _nonce(self) -> bytes:
        n = self.n
        self.n += 1
        return b"\x00" * 4 + n.to_bytes(8, "little")

    def encrypt_and_hash(self, plaintext: bytes) -> bytes:
        if self.k is None:
            self.mix_hash(plaintext)
            return plaintext
        ct = ChaCha20Poly1305(self.k).encrypt(self._nonce(), plaintext, self.h)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ciphertext: bytes) -> bytes:
        if self.k is None:
            self.mix_hash(ciphertext)
            return ciphertext
        try:
            pt = ChaCha20Poly1305(self.k).decrypt(
                self._nonce(), ciphertext, self.h
            )
        except Exception as e:
            raise HandshakeError(f"handshake decrypt failed: {e}") from e
        self.mix_hash(ciphertext)
        return pt

    def split(self) -> tuple:
        k1, k2 = _hkdf2(self.ck, b"")
        return k1, k2


@dataclass
class NoiseSession:
    """Post-handshake transport state for one direction pair."""

    send_key: bytes
    recv_key: bytes
    remote_static: bytes  # raw 32-byte remote static public key
    _send_n: int = 0
    _recv_n: int = 0

    def encrypt(self, plaintext: bytes) -> bytes:
        nonce = b"\x00" * 4 + self._send_n.to_bytes(8, "little")
        self._send_n += 1
        return ChaCha20Poly1305(self.send_key).encrypt(nonce, plaintext, b"")

    def decrypt(self, ciphertext: bytes) -> bytes:
        nonce = b"\x00" * 4 + self._recv_n.to_bytes(8, "little")
        self._recv_n += 1
        try:
            return ChaCha20Poly1305(self.recv_key).decrypt(nonce, ciphertext, b"")
        except Exception as e:
            raise HandshakeError(f"frame decrypt failed: {e}") from e


async def _read_msg(reader) -> bytes:
    hdr = await reader.readexactly(2)
    return await reader.readexactly(int.from_bytes(hdr, "big"))


def _write_msg(writer, data: bytes) -> None:
    writer.write(len(data).to_bytes(2, "big") + data)


async def initiator_handshake(reader, writer, static_priv: X25519PrivateKey) -> NoiseSession:
    """Run the XX pattern as initiator; returns the transport session."""
    st = _SymmetricState()
    e = X25519PrivateKey.generate()
    e_pub = _pub_bytes(e.public_key())
    s_pub = _pub_bytes(static_priv.public_key())

    # -> e
    st.mix_hash(e_pub)
    _write_msg(writer, e_pub)
    await writer.drain()

    # <- e, ee, s, es
    msg = await _read_msg(reader)
    if len(msg) < 32 + 48:
        raise HandshakeError("short handshake response")
    re_pub = msg[:32]
    st.mix_hash(re_pub)
    st.mix_key(e.exchange(X25519PublicKey.from_public_bytes(re_pub)))  # ee
    rs_ct = msg[32 : 32 + 48]
    rs_pub = st.decrypt_and_hash(rs_ct)  # s
    st.mix_key(e.exchange(X25519PublicKey.from_public_bytes(rs_pub)))  # es
    _ = st.decrypt_and_hash(msg[32 + 48 :])  # (empty payload)

    # -> s, se
    s_ct = st.encrypt_and_hash(s_pub)
    st.mix_key(static_priv.exchange(X25519PublicKey.from_public_bytes(re_pub)))  # se
    payload_ct = st.encrypt_and_hash(b"")
    _write_msg(writer, s_ct + payload_ct)
    await writer.drain()

    k1, k2 = st.split()
    return NoiseSession(send_key=k1, recv_key=k2, remote_static=rs_pub)


async def responder_handshake(reader, writer, static_priv: X25519PrivateKey) -> NoiseSession:
    """Run the XX pattern as responder; returns the transport session."""
    st = _SymmetricState()
    e = X25519PrivateKey.generate()
    e_pub = _pub_bytes(e.public_key())
    s_pub = _pub_bytes(static_priv.public_key())

    # -> e
    msg = await _read_msg(reader)
    if len(msg) != 32:
        raise HandshakeError("bad handshake initiation")
    re_pub = msg
    st.mix_hash(re_pub)

    # <- e, ee, s, es
    st.mix_hash(e_pub)
    st.mix_key(e.exchange(X25519PublicKey.from_public_bytes(re_pub)))  # ee
    s_ct = st.encrypt_and_hash(s_pub)
    st.mix_key(static_priv.exchange(X25519PublicKey.from_public_bytes(re_pub)))  # es
    payload_ct = st.encrypt_and_hash(b"")
    _write_msg(writer, e_pub + s_ct + payload_ct)
    await writer.drain()

    # -> s, se
    msg = await _read_msg(reader)
    if len(msg) < 48:
        raise HandshakeError("short handshake finish")
    rs_pub = st.decrypt_and_hash(msg[:48])  # s
    st.mix_key(e.exchange(X25519PublicKey.from_public_bytes(rs_pub)))  # se
    _ = st.decrypt_and_hash(msg[48:])

    k1, k2 = st.split()
    return NoiseSession(send_key=k2, recv_key=k1, remote_static=rs_pub)


def peer_id_from_static(pub_raw: bytes) -> str:
    """Derive the transport peer id from a raw static public key."""
    return "16U" + hashlib.sha256(b"lodestar-tpu-peer-id" + pub_raw).hexdigest()[:32]
