"""TCP wire transport: encrypted, multiplexed streams + gossip mesh.

Fills the role of the reference's libp2p stack (TCP transport + noise +
mplex + gossipsub v1.1: beacon-node/src/network/gossip/gossipsub.ts:77,
libp2p in package.json:100,113) behind the SAME `Endpoint` surface the
in-process hub provides (transport.py).

ISSUE 15 refactor: the gossip mesh, reqresp mux and frame schema moved
to ``fabric.MeshFabric`` — the pluggable transport seam shared with the
loopback swarm fabric (loopback.py).  This module is the OS-socket
binding: listen/dial, the noise handshake, AEAD frame
encryption/decryption, and the per-connection recv loop.  The TCP frame
layer is:

    frame := 4B BE ciphertext length || AEAD(plain)

with ``plain`` as documented in fabric.py.

Sessions are noise-XX by default (noise.py).  ``insecure=True`` swaps in
a cleartext session with a trivial peer-id-exchange handshake — for
transport-conformance tests on hosts without the ``cryptography``
package ONLY (both ends must opt in; an insecure node cannot complete a
noise handshake).  Production entry points never pass it.
"""
from __future__ import annotations

import asyncio
import hashlib
from typing import Optional, Set

from . import fabric as _fabric
from .fabric import (  # noqa: F401  (re-exported: frame schema + knobs)
    HEARTBEAT_S,
    IHAVE_PEERS,
    MESH_D,
    MESH_D_HIGH,
    MESH_D_LOW,
    MeshFabric,
    REQUEST_TIMEOUT_S,
    _GOSSIP,
    _GRAFT,
    _IHAVE,
    _IWANT,
    _PRUNE,
    _REQ,
    _RESP_ERR,
    _RESP_OK,
    _SUB,
    _UNSUB,
    _with_topic,
    _read_topic,
)
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger

_log = get_logger("wire")

MAX_FRAME = 1 << 22  # 4 MiB wire cap (> max ssz_snappy block)

_PLAIN_MAGIC = b"LTPU-PLAIN/1:"  # insecure handshake hello (32B key follows)


def _plain_peer_id(pub_raw: bytes) -> str:
    """Same derivation as noise.peer_id_from_static, duplicated so the
    insecure mode imports nothing from the cryptography-backed module."""
    return "16U" + hashlib.sha256(b"lodestar-tpu-peer-id" + pub_raw).hexdigest()[:32]


class _PlainSession:
    """Cleartext stand-in for noise.NoiseSession (insecure mode only)."""

    def __init__(self, remote_static: bytes):
        self.remote_static = remote_static

    def encrypt(self, plaintext: bytes) -> bytes:
        return plaintext

    def decrypt(self, ciphertext: bytes) -> bytes:
        return ciphertext


async def _plain_initiator(reader, writer, static_pub: bytes) -> _PlainSession:
    writer.write(_PLAIN_MAGIC + static_pub)
    await writer.drain()
    hello = await reader.readexactly(len(_PLAIN_MAGIC) + 32)
    if not hello.startswith(_PLAIN_MAGIC):
        raise ConnectionError("peer is not in insecure plaintext mode")
    return _PlainSession(hello[len(_PLAIN_MAGIC) :])


async def _plain_responder(reader, writer, static_pub: bytes) -> _PlainSession:
    hello = await reader.readexactly(len(_PLAIN_MAGIC) + 32)
    if not hello.startswith(_PLAIN_MAGIC):
        raise ConnectionError("peer is not in insecure plaintext mode")
    writer.write(_PLAIN_MAGIC + static_pub)
    await writer.drain()
    return _PlainSession(hello[len(_PLAIN_MAGIC) :])


class _Conn:
    """One (optionally encrypted) TCP connection to a peer — the TCP
    binding's Link (fabric.MeshFabric link contract)."""

    def __init__(self, transport: "WireTransport", reader, writer, session, peer_id):
        self.transport = transport
        self.reader = reader
        self.writer = writer
        self.session = session
        self.peer_id = peer_id
        self.topics: Set[str] = set()      # remote's subscriptions
        self.pending_reqs: Set[int] = set()  # req ids in flight on this conn
        self._send_lock = asyncio.Lock()
        self._recv_task: Optional[asyncio.Task] = None
        self.closed = False

    async def send(self, plain: bytes) -> None:
        ct = self.session.encrypt(plain)
        async with self._send_lock:
            self.writer.write(len(ct).to_bytes(4, "big") + ct)
            await self.writer.drain()

    async def _recv_loop(self) -> None:
        try:
            while True:
                hdr = await self.reader.readexactly(4)
                n = int.from_bytes(hdr, "big")
                if n > MAX_FRAME:
                    raise ConnectionError("oversized frame")
                plain = self.session.decrypt(await self.reader.readexactly(n))
                if not plain:
                    raise ConnectionError("empty frame")
                await self.transport.on_frame(self, plain)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # includes malformed-but-authenticated frames (bad topic
            # bytes, truncated bodies): the peer is broken either way —
            # tear the connection down rather than leak task exceptions
            _log.debug(
                f"recv loop ended: {type(e).__name__}: {e}; dropping conn"
            )
        finally:
            self.transport.drop_link(self)

    def close(self) -> None:
        self.closed = True
        if self._recv_task:
            self._recv_task.cancel()
        try:
            self.writer.close()
        except Exception as e:
            _log.debug(f"writer close failed: {type(e).__name__}: {e}")


class WireTransport(MeshFabric):
    """Endpoint-compatible transport over real TCP sockets.

    MeshFabric supplies the Endpoint surface (handle / request /
    subscribe / unsubscribe / publish / deliver / close) and the mesh
    heartbeat; this class adds listen() / dial() and the per-connection
    noise (or insecure-plaintext) sessions.
    """

    def __init__(self, static_priv=None, *, insecure: bool = False):
        self.insecure = insecure
        if insecure:
            import secrets

            self.static_priv = None
            self.static_pub = (
                static_priv if isinstance(static_priv, bytes) else secrets.token_bytes(32)
            )
            peer_id = _plain_peer_id(self.static_pub)
        else:
            from cryptography.hazmat.primitives import serialization as _ser
            from cryptography.hazmat.primitives.asymmetric.x25519 import (
                X25519PrivateKey,
            )

            from . import noise

            self.static_priv = static_priv or X25519PrivateKey.generate()
            self.static_pub = self.static_priv.public_key().public_bytes(
                _ser.Encoding.Raw, _ser.PublicFormat.Raw
            )
            peer_id = noise.peer_id_from_static(self.static_pub)
        super().__init__(peer_id)
        self._server: Optional[asyncio.AbstractServer] = None
        self.listen_port: Optional[int] = None

    # -- lifecycle -----------------------------------------------------

    async def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._on_accept, host, port)
        self.listen_port = self._server.sockets[0].getsockname()[1]
        self.start_heartbeat()
        return self.listen_port

    async def dial(self, host: str, port: int) -> str:
        """Connect + handshake; returns the remote peer id."""
        faults.fire("net.transport.connect", src=self.peer_id, dst=f"{host}:{port}")
        reader, writer = await asyncio.open_connection(host, port)
        if self.insecure:
            session = await _plain_initiator(reader, writer, self.static_pub)
            peer_id = _plain_peer_id(session.remote_static)
        else:
            from . import noise

            session = await noise.initiator_handshake(
                reader, writer, self.static_priv
            )
            peer_id = noise.peer_id_from_static(session.remote_static)
        return await self._start_conn(reader, writer, session, peer_id)

    async def _on_accept(self, reader, writer) -> None:
        try:
            faults.fire("net.transport.connect", src="inbound", dst=self.peer_id)
            if self.insecure:
                session = await asyncio.wait_for(
                    _plain_responder(reader, writer, self.static_pub), 5.0
                )
                peer_id = _plain_peer_id(session.remote_static)
            else:
                from . import noise

                session = await asyncio.wait_for(
                    noise.responder_handshake(reader, writer, self.static_priv), 5.0
                )
                peer_id = noise.peer_id_from_static(session.remote_static)
        except Exception as e:
            _log.debug(
                f"inbound handshake failed: {type(e).__name__}: {e}"
            )
            writer.close()
            return
        await self._start_conn(reader, writer, session, peer_id)

    async def _start_conn(self, reader, writer, session, peer_id) -> str:
        conn = _Conn(self, reader, writer, session, peer_id)
        await self.add_link(conn)
        conn._recv_task = asyncio.ensure_future(conn._recv_loop())
        return conn.peer_id

    def close(self) -> None:
        if self._server:
            self._server.close()
            self._server = None
        super().close()
