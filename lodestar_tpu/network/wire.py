"""TCP wire transport: encrypted, multiplexed streams + gossip mesh.

Fills the role of the reference's libp2p stack (TCP transport + noise +
mplex + gossipsub v1.1: beacon-node/src/network/gossip/gossipsub.ts:77,
libp2p in package.json:100,113) behind the SAME `Endpoint` surface the
in-process hub provides (transport.py) — ReqRespNode, Eth2Gossip and
Network are transport-agnostic, so two OS processes can now discover
(UDP discv5-like service), dial (this module), range-sync and gossip.

Wire format (after the noise handshake, noise.py):
    frame   := 4B BE ciphertext length || AEAD(plain)
    plain   := 1B type || body
    REQ     := 8B req id || 2B proto len || proto || data
    RESP_OK / RESP_ERR := 8B req id || data / utf8 error
    GOSSIP  := 2B topic len || topic || raw message
    SUB/UNSUB/GRAFT/PRUNE := 2B topic len || topic
    IHAVE   := 2B topic len || topic || N * 20B message ids
    IWANT   := 2B topic len || topic || N * 20B message ids

Gossip propagation is a degree-limited mesh per topic (gossipsub v1.1
shape): publishes and first-deliveries forward to mesh peers only;
heartbeat GRAFTs up to D from known subscribers / PRUNEs beyond D_HIGH,
and emits IHAVE digests of the recent cache to a sample of non-mesh
subscribers, who fetch missing messages with IWANT.  Dedup uses the
spec message-id (gossip.compute_message_id).
"""
from __future__ import annotations

import asyncio
import random
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional, Set, Tuple

from cryptography.hazmat.primitives.asymmetric.x25519 import X25519PrivateKey

from . import noise
from .gossip import compute_message_id
from .transport import GossipHandler, RequestHandler
from lodestar_tpu.utils import get_logger

_log = get_logger("wire")

# frame types
_REQ = 0x01
_RESP_OK = 0x02
_RESP_ERR = 0x03
_GOSSIP = 0x10
_SUB = 0x15
_UNSUB = 0x16
_GRAFT = 0x11
_PRUNE = 0x12
_IHAVE = 0x13
_IWANT = 0x14

# gossipsub-shaped mesh degrees (gossipsub v1.1 defaults)
MESH_D = 6
MESH_D_LOW = 4
MESH_D_HIGH = 10
IHAVE_PEERS = 3
HEARTBEAT_S = 1.0
MAX_FRAME = 1 << 22  # 4 MiB wire cap (> max ssz_snappy block)
REQUEST_TIMEOUT_S = 10.0

_MSG_ID_LEN = 20


def _with_topic(topic: str, rest: bytes = b"") -> bytes:
    tb = topic.encode()
    return len(tb).to_bytes(2, "big") + tb + rest


def _read_topic(body: bytes) -> Tuple[str, bytes]:
    n = int.from_bytes(body[:2], "big")
    return body[2 : 2 + n].decode(), body[2 + n :]


class _Conn:
    """One encrypted TCP connection to a peer."""

    def __init__(self, transport: "WireTransport", reader, writer, session):
        self.transport = transport
        self.reader = reader
        self.writer = writer
        self.session = session
        self.peer_id = noise.peer_id_from_static(session.remote_static)
        self.topics: Set[str] = set()      # remote's subscriptions
        self.pending_reqs: Set[int] = set()  # req ids in flight on this conn
        self._send_lock = asyncio.Lock()
        self._recv_task: Optional[asyncio.Task] = None
        self.closed = False

    async def send(self, plain: bytes) -> None:
        ct = self.session.encrypt(plain)
        async with self._send_lock:
            self.writer.write(len(ct).to_bytes(4, "big") + ct)
            await self.writer.drain()

    async def _recv_loop(self) -> None:
        try:
            while True:
                hdr = await self.reader.readexactly(4)
                n = int.from_bytes(hdr, "big")
                if n > MAX_FRAME:
                    raise ConnectionError("oversized frame")
                plain = self.session.decrypt(await self.reader.readexactly(n))
                if not plain:
                    raise ConnectionError("empty frame")
                await self.transport._on_frame(self, plain)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # includes malformed-but-authenticated frames (bad topic
            # bytes, truncated bodies): the peer is broken either way —
            # tear the connection down rather than leak task exceptions
            _log.debug(
                f"recv loop ended: {type(e).__name__}: {e}; dropping conn"
            )
        finally:
            self.transport._drop_conn(self)

    def close(self) -> None:
        self.closed = True
        if self._recv_task:
            self._recv_task.cancel()
        try:
            self.writer.close()
        except Exception as e:
            _log.debug(f"writer close failed: {type(e).__name__}: {e}")


@dataclass
class _TopicState:
    handler: GossipHandler
    mesh: Set[str] = field(default_factory=set)


class WireTransport:
    """Endpoint-compatible transport over real TCP + noise sessions.

    Implements the surface consumed by ReqRespNode / Eth2Gossip /
    Network (handle / request / subscribe / unsubscribe / publish /
    deliver / close) plus listen() / dial() / heartbeat_forever().
    """

    def __init__(self, static_priv: Optional[X25519PrivateKey] = None):
        self.static_priv = static_priv or X25519PrivateKey.generate()
        pub = self.static_priv.public_key()
        from cryptography.hazmat.primitives import serialization as _ser

        self.static_pub = pub.public_bytes(
            _ser.Encoding.Raw, _ser.PublicFormat.Raw
        )
        self.peer_id = noise.peer_id_from_static(self.static_pub)
        self.conns: Dict[str, _Conn] = {}
        self.request_handlers: Dict[str, RequestHandler] = {}
        self._topics: Dict[str, _TopicState] = {}
        self._pending: Dict[int, asyncio.Future] = {}
        self._req_counter = 0
        self._server: Optional[asyncio.AbstractServer] = None
        self._tasks: Set[asyncio.Task] = set()
        self._hb_task: Optional[asyncio.Task] = None
        # recent message cache for IWANT serving + IHAVE digests
        self._mcache: "OrderedDict[bytes, Tuple[str, bytes]]" = OrderedDict()
        self._mcache_max = 512
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()
        self._seen_max = 1 << 15
        self.listen_port: Optional[int] = None

    # -- lifecycle -----------------------------------------------------

    async def listen(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._server = await asyncio.start_server(self._on_accept, host, port)
        self.listen_port = self._server.sockets[0].getsockname()[1]
        self._hb_task = asyncio.ensure_future(self._heartbeat_loop())
        return self.listen_port

    async def dial(self, host: str, port: int) -> str:
        """Connect + handshake; returns the remote peer id."""
        reader, writer = await asyncio.open_connection(host, port)
        session = await noise.initiator_handshake(reader, writer, self.static_priv)
        return await self._start_conn(reader, writer, session)

    async def _on_accept(self, reader, writer) -> None:
        try:
            session = await asyncio.wait_for(
                noise.responder_handshake(reader, writer, self.static_priv), 5.0
            )
        except Exception as e:
            _log.debug(
                f"inbound handshake failed: {type(e).__name__}: {e}"
            )
            writer.close()
            return
        await self._start_conn(reader, writer, session)

    async def _start_conn(self, reader, writer, session) -> str:
        conn = _Conn(self, reader, writer, session)
        old = self.conns.get(conn.peer_id)
        if old is not None:
            old.close()
        self.conns[conn.peer_id] = conn
        conn._recv_task = asyncio.ensure_future(conn._recv_loop())
        # announce current subscriptions
        for topic in self._topics:
            await conn.send(bytes([_SUB]) + _with_topic(topic))
        return conn.peer_id

    def _drop_conn(self, conn: _Conn) -> None:
        if self.conns.get(conn.peer_id) is conn:
            # only the ACTIVE conn's death evicts peer state — a conn
            # superseded by a reconnect must not wipe the (still valid)
            # mesh membership of its replacement
            del self.conns[conn.peer_id]
            for st in self._topics.values():
                st.mesh.discard(conn.peer_id)
        # fail this conn's in-flight requests now instead of letting
        # callers wait out the 10 s request timeout
        for rid in list(conn.pending_reqs):
            fut = self._pending.get(rid)
            if fut is not None and not fut.done():
                fut.set_exception(ConnectionError("peer disconnected"))
        conn.pending_reqs.clear()
        conn.close()

    def close(self) -> None:
        if self._hb_task:
            self._hb_task.cancel()
        if self._server:
            self._server.close()
        for conn in list(self.conns.values()):
            conn.close()
        self.conns.clear()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("transport closed"))
        self._pending.clear()
        for t in self._tasks:
            t.cancel()

    # -- reqresp (Endpoint surface) ------------------------------------

    def handle(self, protocol_id: str, handler: RequestHandler) -> None:
        self.request_handlers[protocol_id] = handler

    async def request(self, to_peer: str, protocol_id: str, data: bytes) -> bytes:
        conn = self.conns.get(to_peer)
        if conn is None:
            raise ConnectionError(f"not connected to {to_peer}")
        self._req_counter += 1
        req_id = self._req_counter
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        pb = protocol_id.encode()
        conn.pending_reqs.add(req_id)
        try:
            await conn.send(
                bytes([_REQ])
                + req_id.to_bytes(8, "big")
                + len(pb).to_bytes(2, "big")
                + pb
                + data
            )
            return await asyncio.wait_for(fut, REQUEST_TIMEOUT_S)
        finally:
            conn.pending_reqs.discard(req_id)
            self._pending.pop(req_id, None)

    # -- gossip (Endpoint surface) -------------------------------------

    def subscribe(self, topic: str, handler: GossipHandler) -> None:
        self._topics[topic] = _TopicState(handler=handler)
        self._broadcast_control(_SUB, topic)

    def unsubscribe(self, topic: str) -> None:
        if topic in self._topics:
            del self._topics[topic]
            self._broadcast_control(_UNSUB, topic)

    def _broadcast_control(self, ftype: int, topic: str) -> None:
        for conn in list(self.conns.values()):
            self._bg(conn.send(bytes([ftype]) + _with_topic(topic)))

    async def publish(self, topic: str, message: bytes) -> int:
        """Send to mesh peers (or all subscribed peers while the mesh is
        still forming); returns receiver count."""
        msg_id = compute_message_id(topic, message)
        self._remember(topic, msg_id, message)
        targets = self._forward_targets(topic, exclude=None)
        frame = bytes([_GOSSIP]) + _with_topic(topic, message)
        for pid in targets:
            conn = self.conns.get(pid)
            if conn:
                self._bg(conn.send(frame))
        return len(targets)

    def deliver(self, from_peer: str, topic: str, message: bytes) -> None:
        st = self._topics.get(topic)
        if st is None:
            return
        self._bg(st.handler(from_peer, topic, message))

    # -- internals -----------------------------------------------------

    def _bg(self, coro: Awaitable) -> None:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _remember(self, topic: str, msg_id: bytes, message: bytes) -> None:
        self._seen[msg_id] = None
        while len(self._seen) > self._seen_max:
            self._seen.popitem(last=False)
        self._mcache[msg_id] = (topic, message)
        while len(self._mcache) > self._mcache_max:
            self._mcache.popitem(last=False)

    def _forward_targets(self, topic: str, exclude: Optional[str]) -> List[str]:
        st = self._topics.get(topic)
        mesh = set(st.mesh) if st else set()
        if not mesh:
            mesh = {p for p, c in self.conns.items() if topic in c.topics}
        mesh.discard(exclude)
        return [p for p in mesh if p in self.conns]

    async def _on_frame(self, conn: _Conn, plain: bytes) -> None:
        ftype, body = plain[0], plain[1:]
        if ftype == _REQ:
            req_id = int.from_bytes(body[:8], "big")
            plen = int.from_bytes(body[8:10], "big")
            proto = body[10 : 10 + plen].decode()
            data = body[10 + plen :]
            self._bg(self._serve_request(conn, req_id, proto, data))
        elif ftype in (_RESP_OK, _RESP_ERR):
            req_id = int.from_bytes(body[:8], "big")
            fut = self._pending.get(req_id)
            if fut and not fut.done():
                if ftype == _RESP_OK:
                    fut.set_result(body[8:])
                else:
                    fut.set_exception(
                        ConnectionError(body[8:].decode(errors="replace"))
                    )
        elif ftype == _GOSSIP:
            topic, message = _read_topic(body)
            msg_id = compute_message_id(topic, message)
            if msg_id in self._seen:
                return
            self._remember(topic, msg_id, message)
            self.deliver(conn.peer_id, topic, message)
            # forward within the mesh (multi-hop propagation)
            frame = bytes([_GOSSIP]) + _with_topic(topic, message)
            for pid in self._forward_targets(topic, exclude=conn.peer_id):
                c = self.conns.get(pid)
                if c:
                    self._bg(c.send(frame))
        elif ftype == _SUB:
            topic, _ = _read_topic(body)
            conn.topics.add(topic)
        elif ftype == _UNSUB:
            topic, _ = _read_topic(body)
            conn.topics.discard(topic)
            st = self._topics.get(topic)
            if st:
                st.mesh.discard(conn.peer_id)
        elif ftype == _GRAFT:
            topic, _ = _read_topic(body)
            st = self._topics.get(topic)
            if st is not None and len(st.mesh) < MESH_D_HIGH:
                st.mesh.add(conn.peer_id)
            else:  # not subscribed or mesh full: refuse
                self._bg(conn.send(bytes([_PRUNE]) + _with_topic(topic)))
        elif ftype == _PRUNE:
            topic, _ = _read_topic(body)
            st = self._topics.get(topic)
            if st:
                st.mesh.discard(conn.peer_id)
        elif ftype == _IHAVE:
            topic, rest = _read_topic(body)
            if topic not in self._topics:
                return
            want = []
            for i in range(0, len(rest), _MSG_ID_LEN):
                mid = rest[i : i + _MSG_ID_LEN]
                if len(mid) == _MSG_ID_LEN and mid not in self._seen:
                    want.append(mid)
            if want:
                self._bg(
                    conn.send(bytes([_IWANT]) + _with_topic(topic, b"".join(want)))
                )
        elif ftype == _IWANT:
            topic, rest = _read_topic(body)
            for i in range(0, len(rest), _MSG_ID_LEN):
                mid = rest[i : i + _MSG_ID_LEN]
                entry = self._mcache.get(mid)
                if entry is not None:
                    t, message = entry
                    self._bg(
                        conn.send(bytes([_GOSSIP]) + _with_topic(t, message))
                    )

    async def _serve_request(
        self, conn: _Conn, req_id: int, proto: str, data: bytes
    ) -> None:
        handler = self.request_handlers.get(proto)
        rid = req_id.to_bytes(8, "big")
        if handler is None:
            await conn.send(
                bytes([_RESP_ERR]) + rid + f"unsupported {proto}".encode()
            )
            return
        try:
            resp = await handler(conn.peer_id, proto, data)
            await conn.send(bytes([_RESP_OK]) + rid + resp)
        except Exception as e:
            if not conn.closed:
                await conn.send(
                    bytes([_RESP_ERR]) + rid + str(e)[:256].encode()
                )

    # -- mesh maintenance ----------------------------------------------

    async def _heartbeat_loop(self) -> None:
        while True:
            try:
                await asyncio.sleep(HEARTBEAT_S)
                self._heartbeat_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                _log.warn(f"heartbeat failed: {type(e).__name__}: {e}")
                continue

    def _heartbeat_once(self) -> None:
        for topic, st in self._topics.items():
            st.mesh = {p for p in st.mesh if p in self.conns}
            subscribers = [
                p for p, c in self.conns.items() if topic in c.topics
            ]
            if len(st.mesh) < MESH_D_LOW:
                candidates = [p for p in subscribers if p not in st.mesh]
                random.shuffle(candidates)
                for pid in candidates[: MESH_D - len(st.mesh)]:
                    st.mesh.add(pid)
                    conn = self.conns.get(pid)
                    if conn:
                        self._bg(conn.send(bytes([_GRAFT]) + _with_topic(topic)))
            elif len(st.mesh) > MESH_D_HIGH:
                excess = random.sample(
                    sorted(st.mesh), len(st.mesh) - MESH_D
                )
                for pid in excess:
                    st.mesh.discard(pid)
                    conn = self.conns.get(pid)
                    if conn:
                        self._bg(conn.send(bytes([_PRUNE]) + _with_topic(topic)))
            # IHAVE digests of the recent cache to a sample of
            # subscribers.  Unlike canonical gossipsub this includes
            # mesh members: a peer GRAFTed after a publish would
            # otherwise never hear of it (mesh forwards only NEW
            # messages), and the cost is one id list — IWANT only pulls
            # unseen ids.
            ids = [
                mid for mid, (t, _) in self._mcache.items() if t == topic
            ][-32:]
            if ids:
                sample = list(subscribers)
                random.shuffle(sample)
                payload = bytes([_IHAVE]) + _with_topic(topic, b"".join(ids))
                for pid in sample[: IHAVE_PEERS + len(st.mesh)]:
                    conn = self.conns.get(pid)
                    if conn:
                        self._bg(conn.send(payload))
