"""Gossipsub peer scoring (reference:
beacon-node/src/network/gossip/scoringParameters.ts, which parameterizes
gossipsub v1.1's score function).

The score model follows the gossipsub v1.1 spec shape, reduced to the
terms the reference actually tunes for eth2:

  per-topic:   P2 first-message deliveries (capped, decaying, positive)
               P4 invalid messages         (squared, decaying, negative)
  per-peer:    P7 behaviour penalty        (squared, decaying, negative)
  topic score = weight * (w2*P2 + w4*P4^2), clipped below at topic floor

Topic weights mirror the reference's split: blocks are worth more than
aggregates, aggregates more than per-subnet attestations (the
beacon_attestation_subnet weight there is divided across 64 subnets).

Scores decay toward zero on a fixed interval (`decay()` — the reference
runs decayInterval=12s).  `score()` feeds the same accept/graylist
thresholds gossipsub uses; the Network's heartbeat disconnects peers
below `gossip_threshold`.
"""
from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict

# thresholds (scoringParameters.ts gossipsubThresholds)
GOSSIP_THRESHOLD = -4000.0
PUBLISH_THRESHOLD = -8000.0
GRAYLIST_THRESHOLD = -16000.0

# decay per decay-interval tick
FIRST_DELIVERY_DECAY = 0.99
INVALID_DECAY = 0.97
BEHAVIOUR_DECAY = 0.986

FIRST_DELIVERY_CAP = 40.0
BEHAVIOUR_PENALTY_THRESHOLD = 6.0


@dataclass
class TopicParams:
    weight: float
    first_delivery_weight: float = 1.0
    invalid_weight: float = -99.0  # squared counter, strongly negative


# topic-kind -> params (weights shaped like the reference's)
DEFAULT_TOPIC_PARAMS: Dict[str, TopicParams] = {
    "beacon_block": TopicParams(weight=0.5),
    "beacon_aggregate_and_proof": TopicParams(weight=0.5),
    "beacon_attestation": TopicParams(weight=1.0 / 64),  # per subnet
    "sync_committee_contribution_and_proof": TopicParams(weight=0.2),
    "sync_committee": TopicParams(weight=0.2 / 4),
    "voluntary_exit": TopicParams(weight=0.05),
    "proposer_slashing": TopicParams(weight=0.05),
    "attester_slashing": TopicParams(weight=0.05),
    "bls_to_execution_change": TopicParams(weight=0.05),
}


def _topic_kind(topic: str) -> str:
    """`/eth2/<digest>/beacon_attestation_7/ssz_snappy` -> `beacon_attestation`."""
    parts = topic.split("/")
    name = parts[3] if len(parts) > 3 else topic
    base = name.rsplit("_", 1)
    if len(base) == 2 and base[1].isdigit():
        return base[0]
    return name


@dataclass
class _PeerTopicStats:
    first_deliveries: float = 0.0
    invalid: float = 0.0


@dataclass
class _PeerStats:
    topics: Dict[str, _PeerTopicStats] = field(default_factory=dict)
    behaviour_penalty: float = 0.0


class GossipPeerScore:
    """Per-peer gossip score register (PeerScore role inside gossipsub)."""

    def __init__(self, params: Dict[str, TopicParams] = None):
        self.params = params or DEFAULT_TOPIC_PARAMS
        self._peers: Dict[str, _PeerStats] = {}

    def _peer(self, peer_id: str) -> _PeerStats:
        if peer_id not in self._peers:
            self._peers[peer_id] = _PeerStats()
        return self._peers[peer_id]

    def _topic(self, peer_id: str, topic: str) -> _PeerTopicStats:
        p = self._peer(peer_id)
        if topic not in p.topics:
            p.topics[topic] = _PeerTopicStats()
        return p.topics[topic]

    # -- event hooks (called by the gossip router) ------------------------

    def on_first_delivery(self, peer_id: str, topic: str) -> None:
        t = self._topic(peer_id, topic)
        t.first_deliveries = min(FIRST_DELIVERY_CAP, t.first_deliveries + 1.0)

    def on_invalid_message(self, peer_id: str, topic: str) -> None:
        self._topic(peer_id, topic).invalid += 1.0

    def on_behaviour_penalty(self, peer_id: str) -> None:
        """Protocol misbehaviour outside topic scoring (e.g. flooding)."""
        self._peer(peer_id).behaviour_penalty += 1.0

    # -- scoring ----------------------------------------------------------

    def score(self, peer_id: str) -> float:
        p = self._peers.get(peer_id)
        if p is None:
            return 0.0
        total = 0.0
        for topic, st in p.topics.items():
            params = self.params.get(_topic_kind(topic))
            if params is None:
                continue
            topic_score = (
                params.first_delivery_weight * st.first_deliveries
                + params.invalid_weight * st.invalid * st.invalid
            )
            total += params.weight * topic_score
        excess = p.behaviour_penalty - BEHAVIOUR_PENALTY_THRESHOLD
        if excess > 0:
            total += -10.0 * excess * excess
        return total

    def should_graylist(self, peer_id: str) -> bool:
        return self.score(peer_id) < GRAYLIST_THRESHOLD

    def below_gossip_threshold(self, peer_id: str) -> bool:
        return self.score(peer_id) < GOSSIP_THRESHOLD

    def forget(self, peer_id: str) -> None:
        """Drop a disconnected peer's stats (the reference prunes scores
        after a retain window; heartbeat calls this on disconnect)."""
        self._peers.pop(peer_id, None)

    # -- decay loop -------------------------------------------------------

    def decay(self) -> None:
        """One decay tick (reference decayInterval = 12 s).  Peers whose
        counters have all decayed to zero are pruned — without this the
        registry grows with lifetime peer churn."""
        for pid in list(self._peers):
            p = self._peers[pid]
            empty = p.behaviour_penalty == 0.0
            for topic in list(p.topics):
                st = p.topics[topic]
                st.first_deliveries *= FIRST_DELIVERY_DECAY
                st.invalid *= INVALID_DECAY
                if st.invalid < 0.01:
                    st.invalid = 0.0
                if st.first_deliveries < 0.01:
                    st.first_deliveries = 0.0
                if st.invalid == 0.0 and st.first_deliveries == 0.0:
                    del p.topics[topic]
                else:
                    empty = False
            p.behaviour_penalty *= BEHAVIOUR_DECAY
            if p.behaviour_penalty < 0.01:
                p.behaviour_penalty = 0.0
            elif p.behaviour_penalty:
                empty = False
            if empty and not p.topics:
                del self._peers[pid]
