"""Backfill sync (reference: beacon-node/src/sync/backfill/backfill.ts +
verify.ts:43).

After a weak-subjectivity (checkpoint) start the node has no history
below its anchor.  BackfillSync walks BACKWARD from the anchor block:
batches of older blocks are fetched by range, hash-chain linked
(child.parent_root == root(parent)), and only PROPOSER signatures are
verified — batched through the pluggable BLS verifier — before the
blocks land in the by-slot block archive.  Full state-transition replay
is never needed for finalized history.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import ACTIVE_PRESET as _p, DOMAIN_BEACON_PROPOSER
from lodestar_tpu.state_transition.util.domain import (
    compute_domain,
    compute_signing_root,
)
from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot
from lodestar_tpu.types import ssz
from lodestar_tpu.utils import get_logger

_log = get_logger("backfill")


class BackfillError(ValueError):
    pass


@dataclass
class BackfillResult:
    archived: int
    oldest_slot: Optional[int]
    complete: bool  # reached slot 0 / genesis


class BackfillSync:
    def __init__(self, chain, network, batch_slots: Optional[int] = None):
        self.chain = chain
        self.network = network
        self.batch_slots = batch_slots or _p.SLOTS_PER_EPOCH
        # the backward frontier: we need the block whose ROOT equals this
        anchor = chain.db.block.get(chain.anchor_root)
        self.expected_root: bytes = (
            bytes(anchor.message.parent_root) if anchor else b"\x00" * 32
        )
        self.next_slot_hint: int = anchor.message.slot - 1 if anchor else 0

    # ------------------------------------------------------------------

    def _proposer_pubkey(self, proposer_index: int) -> bls.PublicKey:
        st = self.chain.get_head_state().state
        return bls.PublicKey.from_bytes(bytes(st.validators[proposer_index].pubkey))

    def _proposer_signature_set(self, signed_block) -> bls.SignatureSet:
        """Proposer sig over the block root with the proposer domain of the
        block's epoch (backfill/verify.ts verifyBlockProposerSignature)."""
        st = self.chain.get_head_state().state
        block = signed_block.message
        from lodestar_tpu.config import ForkConfig

        epoch = compute_epoch_at_slot(block.slot)
        fork_version = ForkConfig(self.chain.cfg).fork_version_at_epoch(epoch)
        domain = compute_domain(
            DOMAIN_BEACON_PROPOSER,
            fork_version,
            self.chain.genesis_validators_root,
        )
        root = compute_signing_root(type(block), block, domain)
        return bls.SignatureSet(
            self._proposer_pubkey(block.proposer_index),
            root,
            bls.Signature.from_bytes(bytes(signed_block.signature)),
        )

    async def _verify_batch(self, blocks: List) -> None:
        """Hash-chain linkage backward + batched proposer signatures."""
        expected = self.expected_root
        for signed in reversed(blocks):  # newest -> oldest
            msg = signed.message
            root = type(msg).hash_tree_root(msg)
            if root != expected:
                raise BackfillError(
                    f"chain break at slot {msg.slot}: {root.hex()[:16]} != "
                    f"{expected.hex()[:16]}"
                )
            expected = bytes(msg.parent_root)
        try:
            sets = [
                self._proposer_signature_set(b)
                for b in blocks
                if b.message.slot > 0  # genesis placeholder has no signature
            ]
        except ValueError as e:  # malformed pubkey/signature encoding
            raise BackfillError(f"malformed proposer signature: {e}")
        if sets:
            from lodestar_tpu.chain.bls import VerifyOptions

            ok = await self.chain.bls.verify_signature_sets(
                sets, VerifyOptions(batchable=True)
            )
            if not ok:
                raise BackfillError("proposer signature batch invalid")

    # ------------------------------------------------------------------

    async def run(self, to_slot: int = 0) -> BackfillResult:
        """Fill the archive backward until `to_slot` (or peers run dry)."""
        archived = 0
        oldest: Optional[int] = None
        while self.next_slot_hint >= to_slot and self.expected_root != b"\x00" * 32:
            start = max(to_slot, self.next_slot_hint - self.batch_slots + 1)
            count = self.next_slot_hint - start + 1
            blocks = await self._download(start, count)
            if not blocks:
                return BackfillResult(archived, oldest, complete=False)
            await self._verify_batch(blocks)
            for signed in blocks:
                slot = signed.message.slot
                self.chain.db.block_archive.put(slot, signed)
                self.chain.db.block_archive_root_index.put(
                    type(signed.message).hash_tree_root(signed.message), slot
                )
                oldest = slot if oldest is None else min(oldest, slot)
                archived += 1
            first = blocks[0].message
            # single-owner: run() is the one backfill task; the cursor
            # pair below has no concurrent writer
            self.expected_root = bytes(first.parent_root)  # lodelint: disable=await-in-critical
            self.next_slot_hint = first.slot - 1  # lodelint: disable=await-in-critical
            if first.slot == 0:
                break
        self.chain.db.backfilled_ranges.put(
            oldest if oldest is not None else 0, self.next_slot_hint + 1
        )
        return BackfillResult(archived, oldest, complete=True)

    async def _download(self, start: int, count: int) -> Optional[List]:
        for pid in self.network.peer_manager.connected_peers():
            try:
                blocks = await self.network.blocks_by_range(pid, start, count)
                if blocks:
                    return blocks
            except Exception as e:
                _log.debug(
                    f"blocks_by_range from {pid} failed: "
                    f"{type(e).__name__}: {e}; trying next peer"
                )
                continue
        return None
