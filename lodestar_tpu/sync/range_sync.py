"""Range sync: SyncChain with a per-batch state machine and pipelined
download/processing.

Reference behaviors (beacon-node/src/sync/range/chain.ts:80 SyncChain,
range/batch.ts Batch, sync/constants.ts:8-11 retry ceilings):

- the chain's slot span is cut into EPOCHS_PER_BATCH-epoch batches, each
  a small state machine (AwaitingDownload -> Downloading ->
  AwaitingProcessing -> Processing -> done / back to AwaitingDownload on
  failure) with its own download/processing attempt counters;
- up to BATCH_BUFFER_SIZE batches download CONCURRENTLY from distinct
  peers while earlier batches process — one slow peer no longer stalls
  the pipeline, it just serves a late batch;
- batches process strictly in slot order;
- a failed download retries on another peer; an invalid batch penalizes
  the peer that SERVED it (not the whole segment) and is re-downloaded
  from a different peer before the chain gives up.
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Set

from lodestar_tpu.params import ACTIVE_PRESET as _p
from lodestar_tpu.network.peers import PeerAction
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import get_logger

_log = get_logger("range-sync")

EPOCHS_PER_BATCH = 1  # sync/constants.ts:41
MAX_BATCH_DOWNLOAD_ATTEMPTS = 5  # sync/constants.ts:8
MAX_BATCH_PROCESSING_ATTEMPTS = 3  # sync/constants.ts:11
BATCH_BUFFER_SIZE = 5  # concurrent in-flight batches (chain.ts batchBuffer)
# a peer that served this many INVALID batches is byzantine, not
# unlucky: Fatal score + lifecycle ban (ISSUE 15 — "routes around
# byzantine peers instead of stalling")
INVALID_BATCH_BAN_STRIKES = 2
# Stalled chains re-arm when a peer (re)connects; cap how long one
# re-arm wait blocks before surfacing the Stalled result to the caller
REARM_WAIT_S = 30.0


class SyncState(str, Enum):
    Stalled = "Stalled"
    SyncingFinalized = "SyncingFinalized"
    SyncingHead = "SyncingHead"
    Synced = "Synced"


class BatchStatus(str, Enum):
    AwaitingDownload = "AwaitingDownload"
    Downloading = "Downloading"
    AwaitingProcessing = "AwaitingProcessing"
    Processing = "Processing"
    Done = "Done"
    Failed = "Failed"


@dataclass
class Batch:
    """One EPOCHS_PER_BATCH span and its retry bookkeeping (batch.ts)."""

    start_slot: int
    count: int
    status: BatchStatus = BatchStatus.AwaitingDownload
    blocks: List = field(default_factory=list)
    serving_peer: Optional[str] = None
    failed_peers: Set[str] = field(default_factory=set)
    download_attempts: int = 0
    processing_attempts: int = 0


@dataclass
class SyncResult:
    imported: int
    head_slot: int
    state: SyncState


class RangeSync:
    """SyncChain driver: concurrent batch downloads across peers, strictly
    ordered processing through the chain's block pipeline."""

    def __init__(self, network, chain, batch_buffer: int = BATCH_BUFFER_SIZE):
        self.network = network
        self.chain = chain
        self.batch_buffer = batch_buffer
        self.imported = 0
        self._metrics = getattr(chain, "metrics", None)
        # peer -> count of invalid (processing-failed) batches it served
        self._invalid_served: Dict[str, int] = {}

    def _count_batch(self, status: str) -> None:
        if self._metrics:
            self._metrics.lodestar.sync_batches_total.labels(status=status).inc()

    def _target_slot(self) -> int:
        best = 0
        for pid in self.network.peer_manager.connected_peers():
            info = self.network.peer_manager.peers[pid]
            if info.status is not None:
                best = max(best, info.status.head_slot)
        return best

    def _pick_peer(self, batch: Batch, busy: Dict[str, int]) -> Optional[str]:
        """Best peer that can serve the batch, avoiding peers that already
        failed it; prefers idle peers, then spreads overflow batches onto
        the LEAST-loaded peers (always re-picking the single best peer
        would funnel the whole window through it)."""
        peers = self.network.peer_manager.best_peers(
            min_head_slot=batch.start_slot
        )
        for pid in peers:
            if pid not in batch.failed_peers and not busy.get(pid):
                return pid
        best: Optional[str] = None
        for pid in peers:  # all idle peers failed it: allow busy ones
            if pid not in batch.failed_peers and (
                best is None or busy.get(pid, 0) < busy.get(best, 0)
            ):
                best = pid
        return best

    async def _download(self, batch: Batch, pid: str) -> None:
        batch.status = BatchStatus.Downloading
        batch.serving_peer = pid
        batch.download_attempts += 1
        try:
            faults.fire(
                "sync.range.batch_download",
                peer=pid,
                start_slot=batch.start_slot,
            )
            blocks = await self.network.blocks_by_range(
                pid, batch.start_slot, batch.count
            )
        except Exception as e:
            # the failure is HANDLED below (peer scored, batch retried)
            # — this just keeps the cause visible
            _log.debug(
                f"batch download from {pid} failed: "
                f"{type(e).__name__}: {e}"
            )
            blocks = None
        if blocks is None:
            batch.failed_peers.add(pid)
            self.network.peer_manager.scores.apply_action(
                pid, PeerAction.LowToleranceError
            )
            retryable = batch.download_attempts < MAX_BATCH_DOWNLOAD_ATTEMPTS
            self._count_batch("retried" if retryable else "failed")
            batch.status = (
                BatchStatus.AwaitingDownload if retryable else BatchStatus.Failed
            )
            return
        batch.blocks = blocks
        batch.status = BatchStatus.AwaitingProcessing
        self._count_batch("downloaded")

    async def _process(self, batch: Batch) -> bool:
        """Import the batch's blocks in order; on an invalid block penalize
        the serving peer and send the batch back for re-download from a
        different peer (batch.ts processing failure path)."""
        batch.status = BatchStatus.Processing
        try:
            for block in batch.blocks:
                await self.chain.process_block(block)
                self.imported += 1
        except ValueError:
            batch.processing_attempts += 1
            if batch.serving_peer is not None:
                batch.failed_peers.add(batch.serving_peer)
                self._penalize_invalid_batch(batch.serving_peer)
            batch.blocks = []
            retryable = batch.processing_attempts < MAX_BATCH_PROCESSING_ATTEMPTS
            self._count_batch("retried" if retryable else "failed")
            batch.status = (
                BatchStatus.AwaitingDownload if retryable else BatchStatus.Failed
            )
            return False
        batch.status = BatchStatus.Done
        self._count_batch("processed")
        return True

    def _penalize_invalid_batch(self, pid: str) -> None:
        """First invalid batch: tolerance-scored (an honest peer can race
        a prune).  Repeat offender: Fatal + lifecycle ban — the chain
        routes around it and it cannot redial until the ban expires."""
        strikes = self._invalid_served.get(pid, 0) + 1
        self._invalid_served[pid] = strikes
        pm = self.network.peer_manager
        if strikes >= INVALID_BATCH_BAN_STRIKES:
            pm.scores.apply_action(pid, PeerAction.Fatal)
            pm.ban(pid)
            _log.warn(f"banned {pid}: served {strikes} invalid batches")
        else:
            pm.scores.apply_action(pid, PeerAction.MidToleranceError)

    async def sync_until_synced(
        self,
        max_rounds: int = 10,
        rearm_wait_s: float = REARM_WAIT_S,
    ) -> SyncResult:
        """Drive sync() to completion across Stalled episodes: a Stalled
        round surfaces, then RE-ARMS when a peer (re)connects — no
        spinning against an empty peer set, no sleep loops.  Returns the
        first Synced result, or the last Stalled one when no peer
        arrives within ``rearm_wait_s`` (or after ``max_rounds``)."""
        pm = self.network.peer_manager
        result = await self.sync()
        for _ in range(max_rounds):
            if result.state is not SyncState.Stalled:
                return result
            # a peer that connected while sync() was finishing must not
            # be missed (and a Stalled verdict with usable peers — e.g.
            # after banning byzantine servers — retries on fresh batch
            # state immediately, bounded by max_rounds)
            if not pm.connected_peers():
                if not await pm.wait_for_peer(rearm_wait_s):
                    return result
            result = await self.sync()
        return result

    async def sync(self) -> SyncResult:
        batch_slots = EPOCHS_PER_BATCH * _p.SLOTS_PER_EPOCH
        batches: Dict[int, Batch] = {}  # start_slot -> Batch
        tasks: Dict[int, asyncio.Task] = {}
        next_start = self.chain.fork_choice.get_head().slot + 1

        try:
            while True:
                head_slot = self.chain.fork_choice.get_head().slot
                target = self._target_slot()
                if self._metrics:
                    self._metrics.lodestar.sync_target_slot.set(target)
                    self._metrics.lodestar.sync_peers.set(
                        len(
                            self.network.peer_manager.best_peers(
                                min_head_slot=head_slot + 1
                            )
                        )
                    )
                if head_slot >= target and not batches:
                    # an empty peer set cannot certify "synced" — there
                    # is no network head to compare against; surface
                    # Stalled so sync_until_synced re-arms on reconnect
                    if not self.network.peer_manager.connected_peers():
                        return SyncResult(
                            self.imported, head_slot, SyncState.Stalled
                        )
                    return SyncResult(self.imported, head_slot, SyncState.Synced)

                # extend the batch window up to the buffer size
                while len(batches) < self.batch_buffer and next_start <= target:
                    count = min(batch_slots, target - next_start + 1)
                    batches[next_start] = Batch(start_slot=next_start, count=count)
                    next_start += count

                if not batches:
                    # window drained: Synced only if the head actually
                    # reached the peers' target — peers serving EMPTY
                    # batches must not fake a successful sync
                    head_slot = self.chain.fork_choice.get_head().slot
                    return SyncResult(
                        self.imported,
                        head_slot,
                        SyncState.Synced if head_slot >= target else SyncState.Stalled,
                    )

                # any batch out of retries kills the chain (chain.ts
                # ChainErrorType.MAX_DOWNLOAD/PROCESSING_ATTEMPTS)
                if any(b.status is BatchStatus.Failed for b in batches.values()):
                    return SyncResult(self.imported, head_slot, SyncState.Stalled)

                # launch downloads for idle batches on distinct peers
                busy: Dict[str, int] = {}
                for b in batches.values():
                    if b.status is BatchStatus.Downloading and b.serving_peer:
                        busy[b.serving_peer] = busy.get(b.serving_peer, 0) + 1
                launched = False
                for start in sorted(batches):
                    b = batches[start]
                    if b.status is not BatchStatus.AwaitingDownload:
                        continue
                    pid = self._pick_peer(b, busy)
                    if pid is None:
                        continue
                    busy[pid] = busy.get(pid, 0) + 1
                    tasks[start] = asyncio.create_task(self._download(b, pid))
                    launched = True

                # process the LOWEST batch if ready (strict order) — while
                # it imports, the download tasks keep running concurrently
                lowest = min(batches)
                lb = batches[lowest]
                if lb.status is BatchStatus.AwaitingProcessing:
                    ok = await self._process(lb)
                    if ok:
                        tasks.pop(lowest, None)
                        del batches[lowest]
                    continue

                # nothing processable: wait for a download to finish
                pending = [t for t in tasks.values() if not t.done()]
                if pending:
                    await asyncio.wait(
                        pending, return_when=asyncio.FIRST_COMPLETED
                    )
                elif not launched:
                    # no peers can serve the remaining batches
                    return SyncResult(
                        self.imported,
                        self.chain.fork_choice.get_head().slot,
                        SyncState.Stalled,
                    )
        finally:
            for t in tasks.values():
                if not t.done():
                    t.cancel()
