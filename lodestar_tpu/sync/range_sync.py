"""Range sync (reference: beacon-node/src/sync/range/range.ts RangeSync +
sync/sync.ts BeaconSync orchestration, batches of EPOCHS_PER_BATCH=1 epoch,
retry limits from sync/constants.ts:8-11).
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass
from enum import Enum
from typing import List, Optional

from lodestar_tpu.params import ACTIVE_PRESET as _p
from lodestar_tpu.network.peers import PeerAction

EPOCHS_PER_BATCH = 1  # sync/constants.ts:41
MAX_BATCH_DOWNLOAD_ATTEMPTS = 5  # sync/constants.ts
MAX_BATCH_PROCESSING_ATTEMPTS = 3


class SyncState(str, Enum):
    Stalled = "Stalled"
    SyncingFinalized = "SyncingFinalized"
    SyncingHead = "SyncingHead"
    Synced = "Synced"


@dataclass
class SyncResult:
    imported: int
    head_slot: int
    state: SyncState


class RangeSync:
    """Pull batches from best peers and drive them through the chain's
    block pipeline until caught up with the peers' head."""

    def __init__(self, network, chain):
        self.network = network
        self.chain = chain

    def _target_slot(self) -> int:
        best = 0
        for pid in self.network.peer_manager.connected_peers():
            info = self.network.peer_manager.peers[pid]
            if info.status is not None:
                best = max(best, info.status.head_slot)
        return best

    async def sync(self) -> SyncResult:
        imported = 0
        batch_slots = EPOCHS_PER_BATCH * _p.SLOTS_PER_EPOCH
        while True:
            head_slot = self.chain.fork_choice.get_head().slot
            target = self._target_slot()
            if head_slot >= target:
                return SyncResult(imported, head_slot, SyncState.Synced)
            start = head_slot + 1
            count = min(batch_slots, target - head_slot)
            blocks = await self._download_batch(start, count)
            if not blocks:
                return SyncResult(imported, head_slot, SyncState.Stalled)
            for block in blocks:
                try:
                    await self.chain.process_block(block)
                    imported += 1
                except ValueError:
                    # invalid segment: penalize the serving peers and stop
                    for pid in self.network.peer_manager.best_peers(start):
                        self.network.peer_manager.scores.apply_action(
                            pid, PeerAction.MidToleranceError
                        )
                    return SyncResult(imported, head_slot, SyncState.Stalled)

    async def _download_batch(self, start: int, count: int) -> Optional[List]:
        peers = self.network.peer_manager.best_peers(min_head_slot=start)
        attempts = 0
        for pid in peers * MAX_BATCH_DOWNLOAD_ATTEMPTS:
            if attempts >= MAX_BATCH_DOWNLOAD_ATTEMPTS:
                break
            attempts += 1
            try:
                return await self.network.blocks_by_range(pid, start, count)
            except Exception:
                continue
        return None
