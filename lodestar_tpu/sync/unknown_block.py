"""Unknown-block sync (reference: beacon-node/src/sync/unknownBlock.ts):
fetch a gossip block's missing ancestors by root and import the chain
forward."""
from __future__ import annotations

from typing import List

from lodestar_tpu.utils import get_logger

_log = get_logger("unknown-block")

from lodestar_tpu.types import ssz

MAX_ANCESTOR_DEPTH = 32


class UnknownBlockSync:
    def __init__(self, network, chain):
        self.network = network
        self.chain = chain

    async def resolve(self, signed_block) -> List[bytes]:
        """Walk parents by root until a known ancestor, then import the
        chain oldest-first (incl. the original block).  Returns imported
        roots in order."""
        pending = [signed_block]
        parent = bytes(signed_block.message.parent_root)
        depth = 0
        while not self.chain.fork_choice.has_block("0x" + parent.hex()):
            depth += 1
            if depth > MAX_ANCESTOR_DEPTH:
                raise ValueError("ancestor chain too deep")
            fetched = None
            for pid in self.network.peer_manager.connected_peers():
                try:
                    got = await self.network.blocks_by_root(pid, [parent])
                    if got:
                        fetched = got[0]
                        break
                except Exception as e:
                    _log.debug(
                        f"blocks_by_root from {pid} failed: "
                        f"{type(e).__name__}: {e}; trying next peer"
                    )
                    continue
            if fetched is None:
                raise ValueError(f"cannot resolve ancestor {parent.hex()}")
            pending.append(fetched)
            parent = bytes(fetched.message.parent_root)
        roots = []
        for block in reversed(pending):
            roots.append(await self.chain.process_block(block))
        return roots
