"""Doppelganger protection (reference:
packages/validator/src/services/doppelgangerService.ts:37).

Before a validator client starts signing it watches the network for
liveness of its own indices: any attestation or proposal by one of our
validators during the observation window means ANOTHER instance is
running with the same keys — signing would self-slash, so the service
flags the key and the client must refuse duties for it.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Set

DEFAULT_REMAINING_EPOCHS = 2  # doppelgangerService.ts DEFAULT_REMAINING_DETECTION_EPOCHS


class DoppelgangerStatus(str, Enum):
    Unverified = "Unverified"      # still inside the observation window
    VerifiedSafe = "VerifiedSafe"  # window passed with no liveness hits
    DoppelgangerDetected = "DoppelgangerDetected"


@dataclass
class _Registration:
    remaining_epochs: int
    status: DoppelgangerStatus = DoppelgangerStatus.Unverified


class DoppelgangerService:
    def __init__(self, api, remaining_epochs: int = DEFAULT_REMAINING_EPOCHS):
        self.api = api
        self._default_epochs = remaining_epochs
        self._by_index: Dict[int, _Registration] = {}

    def register(self, index: int) -> None:
        if index not in self._by_index:
            self._by_index[index] = _Registration(self._default_epochs)

    def status(self, index: int) -> DoppelgangerStatus:
        reg = self._by_index.get(index)
        return reg.status if reg else DoppelgangerStatus.VerifiedSafe

    def is_safe(self, index: int) -> bool:
        return self.status(index) == DoppelgangerStatus.VerifiedSafe

    def detected(self) -> List[int]:
        return [
            i
            for i, r in self._by_index.items()
            if r.status == DoppelgangerStatus.DoppelgangerDetected
        ]

    async def check_epoch(self, epoch: int) -> None:
        """Run once per epoch during the observation window: query the
        node's liveness view of the PREVIOUS epoch for unverified keys."""
        pending = [
            i
            for i, r in self._by_index.items()
            if r.status == DoppelgangerStatus.Unverified
        ]
        if not pending:
            return
        results = await self.api.get_liveness(max(0, epoch - 1), pending)
        live = {int(r["index"]) for r in results if r["is_live"]}
        for i in pending:
            reg = self._by_index[i]
            if i in live:
                reg.status = DoppelgangerStatus.DoppelgangerDetected
            else:
                reg.remaining_epochs -= 1
                if reg.remaining_epochs <= 0:
                    reg.status = DoppelgangerStatus.VerifiedSafe
