"""ValidatorStore: keys + all signing duties with slashing protection
(reference: packages/validator/src/services/validatorStore.ts).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_CONTRIBUTION_AND_PROOF,
    DOMAIN_RANDAO,
    DOMAIN_SELECTION_PROOF,
    DOMAIN_SYNC_COMMITTEE,
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
    DOMAIN_VOLUNTARY_EXIT,
)
from lodestar_tpu.state_transition.util.domain import (
    compute_domain,
    compute_signing_root,
)
from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot
from lodestar_tpu.types import ssz
from .slashing_protection import (
    SignedAttestationRecord,
    SignedBlockRecord,
    SlashingProtection,
)


class ValidatorStore:
    def __init__(
        self,
        secret_keys: List[bls.SecretKey],
        fork_config,
        genesis_validators_root: bytes,
        slashing_protection: Optional[SlashingProtection] = None,
    ):
        self._by_pubkey: Dict[bytes, bls.SecretKey] = {
            sk.to_public_key().to_bytes(): sk for sk in secret_keys
        }
        self.fork_config = fork_config
        self.genesis_validators_root = genesis_validators_root
        self.slashing_protection = slashing_protection or SlashingProtection()

    @property
    def pubkeys(self) -> List[bytes]:
        return list(self._by_pubkey)

    def has(self, pubkey: bytes) -> bool:
        return pubkey in self._by_pubkey

    def add(self, sk: bls.SecretKey) -> bytes:
        pk = sk.to_public_key().to_bytes()
        self._by_pubkey[pk] = sk
        return pk

    def remove(self, pubkey: bytes) -> bool:
        return self._by_pubkey.pop(pubkey, None) is not None

    def _sk(self, pubkey: bytes) -> bls.SecretKey:
        if pubkey not in self._by_pubkey:
            raise KeyError(f"unknown validator {pubkey.hex()[:16]}")
        return self._by_pubkey[pubkey]

    def _domain(self, domain_type: bytes, epoch: int) -> bytes:
        version = self.fork_config.fork_version_at_epoch(epoch)
        return compute_domain(domain_type, version, self.genesis_validators_root)

    # signing duties ---------------------------------------------------

    def sign_block(self, pubkey: bytes, block) -> "ssz.phase0.SignedBeaconBlock":
        from lodestar_tpu.types import fork_of_block, types_for

        epoch = compute_epoch_at_slot(block.slot)
        domain = self._domain(DOMAIN_BEACON_PROPOSER, epoch)
        block_t = type(block)
        root = compute_signing_root(block_t, block, domain)
        self.slashing_protection.check_and_insert_block_proposal(
            pubkey, SignedBlockRecord(slot=block.slot, signing_root=root)
        )
        sig = self._sk(pubkey).sign(root)
        # fork-aware signed wrapper: a phase0 wrapper would re-serialize an
        # altair+ message with the phase0 body layout (dropping fields).
        # Blinded blocks (builder flow) get the blinded wrapper — both
        # share the same signing root by SSZ design.
        fork = fork_of_block(block)
        if hasattr(block.body, "execution_payload_header"):
            from lodestar_tpu.types import blinded_types_for

            signed_t = blinded_types_for(fork)[1]
        else:
            signed_t = types_for(fork)[2]
        return signed_t(message=block, signature=sig.to_bytes())

    def sign_attestation(
        self, pubkey: bytes, data: "ssz.phase0.AttestationData", committee_size: int,
        position: int,
    ) -> "ssz.phase0.Attestation":
        domain = self._domain(DOMAIN_BEACON_ATTESTER, data.target.epoch)
        root = compute_signing_root(ssz.phase0.AttestationData, data, domain)
        self.slashing_protection.check_and_insert_attestation(
            pubkey,
            SignedAttestationRecord(
                source_epoch=data.source.epoch,
                target_epoch=data.target.epoch,
                signing_root=root,
            ),
        )
        bits = [False] * committee_size
        bits[position] = True
        sig = self._sk(pubkey).sign(root)
        return ssz.phase0.Attestation(
            aggregation_bits=bits, data=data, signature=sig.to_bytes()
        )

    def sign_randao(self, pubkey: bytes, slot: int) -> bytes:
        epoch = compute_epoch_at_slot(slot)
        domain = self._domain(DOMAIN_RANDAO, epoch)
        root = compute_signing_root(ssz.phase0.Epoch, epoch, domain)
        return self._sk(pubkey).sign(root).to_bytes()

    def sign_selection_proof(self, pubkey: bytes, slot: int) -> bytes:
        epoch = compute_epoch_at_slot(slot)
        domain = self._domain(DOMAIN_SELECTION_PROOF, epoch)
        root = compute_signing_root(ssz.phase0.Slot, slot, domain)
        return self._sk(pubkey).sign(root).to_bytes()

    def sign_aggregate_and_proof(
        self, pubkey: bytes, agg_and_proof: "ssz.phase0.AggregateAndProof"
    ) -> "ssz.phase0.SignedAggregateAndProof":
        epoch = compute_epoch_at_slot(agg_and_proof.aggregate.data.slot)
        domain = self._domain(DOMAIN_AGGREGATE_AND_PROOF, epoch)
        root = compute_signing_root(
            ssz.phase0.AggregateAndProof, agg_and_proof, domain
        )
        sig = self._sk(pubkey).sign(root)
        return ssz.phase0.SignedAggregateAndProof(
            message=agg_and_proof, signature=sig.to_bytes()
        )

    def sign_sync_committee_message(
        self, pubkey: bytes, slot: int, beacon_block_root: bytes, validator_index: int
    ) -> "ssz.altair.SyncCommitteeMessage":
        """signSyncCommitteeSignature (validatorStore.ts): BLS over the head
        block root with DOMAIN_SYNC_COMMITTEE."""
        epoch = compute_epoch_at_slot(slot)
        domain = self._domain(DOMAIN_SYNC_COMMITTEE, epoch)
        root = compute_signing_root(ssz.phase0.Root, beacon_block_root, domain)
        sig = self._sk(pubkey).sign(root)
        return ssz.altair.SyncCommitteeMessage(
            slot=slot,
            beacon_block_root=beacon_block_root,
            validator_index=validator_index,
            signature=sig.to_bytes(),
        )

    def sign_sync_selection_proof(
        self, pubkey: bytes, slot: int, subcommittee_index: int
    ) -> bytes:
        """signSyncCommitteeSelectionProof: over SyncAggregatorSelectionData;
        is_sync_committee_aggregator(hash) decides aggregation duty."""
        epoch = compute_epoch_at_slot(slot)
        domain = self._domain(DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch)
        data = ssz.altair.SyncAggregatorSelectionData(
            slot=slot, subcommittee_index=subcommittee_index
        )
        root = compute_signing_root(ssz.altair.SyncAggregatorSelectionData, data, domain)
        return self._sk(pubkey).sign(root).to_bytes()

    def sign_contribution_and_proof(
        self,
        pubkey: bytes,
        contribution: "ssz.altair.SyncCommitteeContribution",
        aggregator_index: int,
        selection_proof: bytes,
    ) -> "ssz.altair.SignedContributionAndProof":
        cp = ssz.altair.ContributionAndProof(
            aggregator_index=aggregator_index,
            contribution=contribution,
            selection_proof=selection_proof,
        )
        epoch = compute_epoch_at_slot(contribution.slot)
        domain = self._domain(DOMAIN_CONTRIBUTION_AND_PROOF, epoch)
        root = compute_signing_root(ssz.altair.ContributionAndProof, cp, domain)
        sig = self._sk(pubkey).sign(root)
        return ssz.altair.SignedContributionAndProof(
            message=cp, signature=sig.to_bytes()
        )

    def sign_voluntary_exit(
        self, pubkey: bytes, validator_index: int, epoch: int
    ) -> "ssz.phase0.SignedVoluntaryExit":
        exit_ = ssz.phase0.VoluntaryExit(epoch=epoch, validator_index=validator_index)
        domain = self._domain(DOMAIN_VOLUNTARY_EXIT, epoch)
        root = compute_signing_root(ssz.phase0.VoluntaryExit, exit_, domain)
        sig = self._sk(pubkey).sign(root)
        return ssz.phase0.SignedVoluntaryExit(message=exit_, signature=sig.to_bytes())
