"""Validator-client sync-committee duty pipeline.

Reference: packages/validator/src/services/syncCommitteeDuties.ts:68
(duty fetch + subnet subscriptions per period) and syncCommittee.ts:22
(per-slot message production, then aggregator contribution publication a
third of a slot later).  Condensed to the same duty math on the rebuild's
API client:

  every slot, for each duty validator:
    1. sign the head block root with DOMAIN_SYNC_COMMITTEE and submit to
       the beacon pool route (node validates + gossips + pools it);
    2. for each subcommittee the validator sits in, sign the
       SyncAggregatorSelectionData; if is_sync_committee_aggregator
       (hash(sig) % MODULUS == 0, util/aggregator.py), fetch the pooled
       contribution and publish a SignedContributionAndProof.

Duties are refetched per epoch (cheap on the rebuild's in-process API)
rather than cached per period; subnet subscriptions go out with the
first fetch of each epoch like prepareSyncCommitteeSubnets.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_SIZE
from lodestar_tpu.state_transition.util.aggregator import (
    is_sync_committee_aggregator,
)
from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot
from lodestar_tpu.utils import get_logger

_log = get_logger("sync-committee-vc")


@dataclass
class SyncDuty:
    pubkey: bytes
    validator_index: int
    positions: List[int]  # indices within the full sync committee

    @property
    def subcommittees(self) -> List[int]:
        return sorted({p // SYNC_COMMITTEE_SUBNET_SIZE for p in self.positions})


@dataclass
class SyncCommitteeService:
    """Per-slot sync-committee duties for a key store's validators.

    `index_provider` returns the VC's pubkey->index map (the Validator
    client's indices service already maintains it — refetching the whole
    registry per epoch would cost a full-registry round trip at mainnet
    scale).  `tracker` (optional ChainHeaderTracker) supplies the SSE-
    pushed head root; duty production falls back to polling when the
    tracker hasn't caught up to the duty slot."""

    api: "ApiClient"
    store: "ValidatorStore"
    index_provider: "Callable[[], Dict[bytes, int]]" = None
    tracker: "ChainHeaderTracker" = None
    _duty_cache: Dict[int, List[SyncDuty]] = field(default_factory=dict)
    _subscribed_epochs: set = field(default_factory=set)

    async def _head_root(self, slot: int) -> bytes:
        t = self.tracker
        if (
            t is not None
            and t.head_root is not None
            and t.head_slot is not None
            and t.head_slot >= slot
        ):
            return t.head_root
        return await self.api.get_block_root("head")

    async def duties(self, epoch: int) -> List[SyncDuty]:
        if epoch in self._duty_cache:
            return self._duty_cache[epoch]
        if self.index_provider is not None:
            index_of = dict(self.index_provider())
        else:
            raw = await self.api.get_validators("head")
            index_of = {
                bytes.fromhex(v["validator"]["pubkey"][2:]): int(v["index"])
                for v in raw
            }
        pubkeys = {pk: True for pk in self.store.pubkeys}
        indices = [index_of[pk] for pk in pubkeys if pk in index_of]
        duties = []
        try:
            items = await self.api.get_sync_duties(epoch, indices)
        except Exception as e:
            # pre-altair node or route unavailable: no duties this epoch
            _log.debug(
                f"sync duties unavailable for epoch {epoch}: "
                f"{type(e).__name__}: {e}"
            )
            items = []
        for item in items:
            duties.append(
                SyncDuty(
                    pubkey=bytes.fromhex(item["pubkey"][2:]),
                    validator_index=int(item["validator_index"]),
                    positions=[
                        int(p) for p in item["validator_sync_committee_indices"]
                    ],
                )
            )
        self._duty_cache[epoch] = duties
        for old in [e for e in self._duty_cache if e < epoch - 1]:
            del self._duty_cache[old]
        if duties and epoch not in self._subscribed_epochs:
            self._subscribed_epochs.add(epoch)
            try:
                await self.api.prepare_sync_committee_subnets(
                    [
                        {
                            "validator_index": d.validator_index,
                            "sync_committee_indices": d.positions,
                            "until_epoch": epoch + 1,
                        }
                        for d in duties
                    ]
                )
            except Exception as e:
                # transient: retried with the next epoch's fetch
                _log.debug(
                    f"sync-subnet prepare failed: {type(e).__name__}: {e}"
                )
        return duties

    async def produce_messages(self, slot: int) -> int:
        """Sign + submit one SyncCommitteeMessage per duty validator over
        the current head root (syncCommittee.ts produceAndPublishSyncCommittees)."""
        duties = await self.duties(compute_epoch_at_slot(slot))
        if not duties:
            return 0
        head_root = await self._head_root(slot)
        messages = [
            self.store.sign_sync_committee_message(
                d.pubkey, slot, head_root, d.validator_index
            )
            for d in duties
        ]
        await self.api.submit_pool_sync_committee_messages(messages)
        return len(messages)

    async def aggregate_if_due(self, slot: int) -> int:
        """Selection proofs per (duty, subcommittee); aggregators fetch the
        pooled contribution and publish SignedContributionAndProof
        (syncCommittee.ts produceAndPublishAggregates)."""
        duties = await self.duties(compute_epoch_at_slot(slot))
        if not duties:
            return 0
        head_root = await self._head_root(slot)
        published = 0
        signed_batch = []
        for d in duties:
            for sub in d.subcommittees:
                proof = self.store.sign_sync_selection_proof(d.pubkey, slot, sub)
                if not is_sync_committee_aggregator(proof):
                    continue
                try:
                    contribution = await self.api.produce_sync_committee_contribution(
                        slot, sub, head_root
                    )
                except Exception as e:
                    # no messages pooled for this subcommittee (404-ish)
                    _log.debug(
                        f"no contribution for subnet {sub}: "
                        f"{type(e).__name__}: {e}"
                    )
                    continue
                signed_batch.append(
                    self.store.sign_contribution_and_proof(
                        d.pubkey, contribution, d.validator_index, proof
                    )
                )
        if signed_batch:
            await self.api.submit_contribution_and_proofs(signed_batch)
            published = len(signed_batch)
        return published
