"""Keymanager API server (reference: packages/api/src/keymanager/routes.ts
+ validator keymanager server in cmds/validator).

Standard eth keymanager surface over the validator's key store:
GET /eth/v1/keystores, POST /eth/v1/keystores (EIP-2335 import with
slashing-protection data), DELETE /eth/v1/keystores (export slashing
protection for the removed keys).
"""
from __future__ import annotations

import asyncio
import json
from typing import List, Optional

from aiohttp import web

from lodestar_tpu.crypto.bls import api as bls
from .keystore import KeystoreError, decrypt_keystore
from .slashing_protection import SlashingProtection


class KeymanagerApiServer:
    def __init__(
        self,
        store,
        slashing_protection: SlashingProtection,
        genesis_validators_root: bytes,
        host: str = "127.0.0.1",
        port: int = 5062,
    ):
        self.store = store
        self.slashing_protection = slashing_protection
        self.genesis_validators_root = genesis_validators_root
        self.host = host
        self.port = port
        self._runner = None
        self.app = web.Application()
        r = self.app.router
        r.add_get("/eth/v1/keystores", self.list_keystores)
        r.add_post("/eth/v1/keystores", self.import_keystores)
        r.add_delete("/eth/v1/keystores", self.delete_keystores)

    # ------------------------------------------------------------------

    async def list_keystores(self, request):
        return web.json_response(
            {
                "data": [
                    {"validating_pubkey": "0x" + pk.hex(), "readonly": False}
                    for pk in self.store.pubkeys
                ]
            }
        )

    async def import_keystores(self, request):
        body = await request.json()
        keystores = body.get("keystores", [])
        passwords = body.get("passwords", [])
        interchange = body.get("slashing_protection")
        if interchange:
            data = (
                json.loads(interchange)
                if isinstance(interchange, str)
                else interchange
            )
            # bulk sqlite writes (one row per recorded block/attestation):
            # off the event loop, other requests keep flowing
            await asyncio.get_running_loop().run_in_executor(
                None,
                self.slashing_protection.import_interchange,
                data,
                self.genesis_validators_root,
            )
        statuses = []
        for ks, pw in zip(keystores, passwords):
            try:
                ks_obj = json.loads(ks) if isinstance(ks, str) else ks
                secret = decrypt_keystore(ks_obj, pw)
                sk = bls.SecretKey.from_bytes(secret)
                pk = sk.to_public_key().to_bytes()
                if self.store.has(pk):
                    statuses.append({"status": "duplicate"})
                else:
                    self.store.add(sk)
                    statuses.append({"status": "imported"})
            except (KeystoreError, ValueError) as e:
                statuses.append({"status": "error", "message": str(e)})
        return web.json_response({"data": statuses})

    async def delete_keystores(self, request):
        body = await request.json()
        pubkeys = [bytes.fromhex(p.replace("0x", "")) for p in body.get("pubkeys", [])]
        statuses = []
        for pk in pubkeys:
            if self.store.has(pk):
                self.store.remove(pk)
                statuses.append({"status": "deleted"})
            else:
                statuses.append({"status": "not_found"})
        # bulk sqlite range scans: off the event loop
        interchange = await asyncio.get_running_loop().run_in_executor(
            None,
            self.slashing_protection.export_interchange,
            self.genesis_validators_root,
            pubkeys,
        )
        return web.json_response(
            {"data": statuses, "slashing_protection": json.dumps(interchange)}
        )

    # ------------------------------------------------------------------

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
