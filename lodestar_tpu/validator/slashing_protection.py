"""Slashing protection database (reference:
packages/validator/src/slashingProtection/ — attestation by-target records,
lower bounds, and min-max surround checks; EIP-3076 interchange format).

Storage: the shared KV controller under the reference's slashing-protection
buckets (db/schema.ts 20-24).
"""
from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from lodestar_tpu.db.controller import KvController, MemoryController
from lodestar_tpu.db.schema import Bucket, encode_key


class SlashingProtectionError(Exception):
    pass


@dataclass(frozen=True)
class SignedBlockRecord:
    slot: int
    signing_root: bytes


@dataclass(frozen=True)
class SignedAttestationRecord:
    source_epoch: int
    target_epoch: int
    signing_root: bytes


def _k(bucket: Bucket, pubkey: bytes, suffix: bytes = b"") -> bytes:
    return encode_key(bucket, pubkey + suffix)


# lodelint: disable-file=transitive-blocking
# Reviewed exception (lodelint interprocedural gate): every public method
# below takes self._lock, a *threading* lock that lodelint's effect
# analysis reaches from the async validator duty loop (sign_* ->
# check_and_insert_*).  The lock must be a threading.Lock because the
# keymanager runs bulk interchange import/export in an executor thread
# (off the event loop) while signing checks run on the loop — a check
# against a half-imported validator entry can emit a slashable vote.
# EIP-3076 invariants are per-validator, so import/export take the lock
# once per pubkey entry rather than across the whole file: a loop-side
# signer contends for at most one entry's KV ops (sub-ms, no I/O beyond
# sqlite WAL), and a signer for the pubkey mid-import stalling is
# exactly the required behavior.


class SlashingProtection:
    def __init__(self, db: Optional[KvController] = None):
        self.db = db or MemoryController()
        # serializes every logical operation across the event loop and
        # keymanager executor threads; import/export hold it per pubkey
        # entry so signing never observes a half-imported validator
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    # blocks
    # ------------------------------------------------------------------

    def check_and_insert_block_proposal(self, pubkey: bytes, record: SignedBlockRecord) -> None:
        """Deny re-signing at or below a previously signed slot (different
        root); idempotent for exact repeats."""
        with self._lock:
            self._check_and_insert_block_proposal(pubkey, record)

    def _check_and_insert_block_proposal(self, pubkey: bytes, record: SignedBlockRecord) -> None:
        key = _k(Bucket.phase0_slashingProtectionBlockBySlot, pubkey,
                 record.slot.to_bytes(8, "big"))
        existing = self.db.get(key)
        if existing is not None:
            if existing == record.signing_root:
                return  # same proposal, benign repeat
            raise SlashingProtectionError(
                f"double block proposal at slot {record.slot}"
            )
        # any earlier-signed slot >= this one means this is a re-org sign
        lo = _k(Bucket.phase0_slashingProtectionBlockBySlot, pubkey,
                record.slot.to_bytes(8, "big"))
        hi = _k(Bucket.phase0_slashingProtectionBlockBySlot, pubkey, b"\xff" * 8)
        for k in self.db.keys_range(lo, hi, limit=1):
            if k != key:
                raise SlashingProtectionError(
                    f"block slot {record.slot} not above last signed slot"
                )
        self.db.put(key, record.signing_root)

    # ------------------------------------------------------------------
    # attestations
    # ------------------------------------------------------------------

    def _att_records(self, pubkey: bytes) -> List[SignedAttestationRecord]:
        lo = _k(Bucket.phase0_slashingProtectionAttestationByTarget, pubkey)
        hi = _k(Bucket.phase0_slashingProtectionAttestationByTarget, pubkey, b"\xff" * 8)
        out = []
        for k, v in self.db.entries_range(lo, hi):
            target = int.from_bytes(k[-8:], "big")
            source = int.from_bytes(v[:8], "big")
            out.append(SignedAttestationRecord(source, target, v[8:]))
        return out

    def check_and_insert_attestation(
        self, pubkey: bytes, record: SignedAttestationRecord
    ) -> None:
        """EIP-3076 rules: no double vote (same target, different root), no
        surround in either direction, respect imported lower bounds."""
        with self._lock:
            self._check_and_insert_attestation(pubkey, record)

    def _check_and_insert_attestation(
        self, pubkey: bytes, record: SignedAttestationRecord
    ) -> None:
        if record.source_epoch > record.target_epoch:
            raise SlashingProtectionError("source > target")
        lb = self.db.get(
            _k(Bucket.phase0_slashingProtectionAttestationLowerBound, pubkey)
        )
        if lb is not None:
            lb_source = int.from_bytes(lb[:8], "big")
            lb_target = int.from_bytes(lb[8:16], "big")
            if record.source_epoch < lb_source:
                raise SlashingProtectionError("source below lower bound")
            if record.target_epoch <= lb_target:
                raise SlashingProtectionError("target at/below lower bound")
        key = _k(
            Bucket.phase0_slashingProtectionAttestationByTarget, pubkey,
            record.target_epoch.to_bytes(8, "big"),
        )
        existing = self.db.get(key)
        if existing is not None:
            if existing[8:] == record.signing_root:
                return
            raise SlashingProtectionError(
                f"double vote at target {record.target_epoch}"
            )
        for old in self._att_records(pubkey):
            if record.source_epoch < old.source_epoch and old.target_epoch < record.target_epoch:
                raise SlashingProtectionError("attestation surrounds a previous one")
            if old.source_epoch < record.source_epoch and record.target_epoch < old.target_epoch:
                raise SlashingProtectionError("attestation is surrounded")
        self.db.put(
            key, record.source_epoch.to_bytes(8, "big") + record.signing_root
        )

    # ------------------------------------------------------------------
    # EIP-3076 interchange
    # ------------------------------------------------------------------

    def export_interchange(self, genesis_validators_root: bytes, pubkeys: List[bytes]) -> dict:
        # lock per pubkey (not across the export): each entry is a
        # consistent snapshot of one validator, which is the granularity
        # EIP-3076 invariants live at — and a concurrent signer only
        # waits out one entry's reads
        data = [self._export_entry(pk) for pk in pubkeys]
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root": "0x" + genesis_validators_root.hex(),
            },
            "data": data,
        }

    def _export_entry(self, pk: bytes) -> dict:
        with self._lock:
            blocks = []
            lo = _k(Bucket.phase0_slashingProtectionBlockBySlot, pk)
            hi = _k(Bucket.phase0_slashingProtectionBlockBySlot, pk, b"\xff" * 8)
            for k, v in self.db.entries_range(lo, hi):
                blocks.append(
                    {"slot": str(int.from_bytes(k[-8:], "big")),
                     "signing_root": "0x" + v.hex()}
                )
            atts = [
                {
                    "source_epoch": str(r.source_epoch),
                    "target_epoch": str(r.target_epoch),
                    "signing_root": "0x" + r.signing_root.hex(),
                }
                for r in self._att_records(pk)
            ]
            return {"pubkey": "0x" + pk.hex(), "signed_blocks": blocks,
                    "signed_attestations": atts}

    def import_interchange(self, obj: dict, genesis_validators_root: bytes) -> None:
        meta = obj["metadata"]
        gvr = bytes.fromhex(meta["genesis_validators_root"][2:])
        if gvr != genesis_validators_root:
            raise SlashingProtectionError("genesis_validators_root mismatch")
        # lock per pubkey entry (not across the file): EIP-3076 slashing
        # invariants are per-validator, so a signer can only race the
        # entry for its own pubkey — and for that pubkey, waiting out the
        # entry's writes is the protection working as intended
        for entry in obj["data"]:
            with self._lock:
                self._import_entry(entry)

    def _import_entry(self, entry: dict) -> None:
        pk = bytes.fromhex(entry["pubkey"][2:])
        max_slot = -1
        max_source = -1
        max_target = -1
        for b in entry.get("signed_blocks", []):
            slot = int(b["slot"])
            root = bytes.fromhex(b.get("signing_root", "0x" + "00" * 32)[2:])
            self.db.put(
                _k(Bucket.phase0_slashingProtectionBlockBySlot, pk,
                   slot.to_bytes(8, "big")),
                root,
            )
            max_slot = max(max_slot, slot)
        for a in entry.get("signed_attestations", []):
            src, tgt = int(a["source_epoch"]), int(a["target_epoch"])
            root = bytes.fromhex(a.get("signing_root", "0x" + "00" * 32)[2:])
            self.db.put(
                _k(Bucket.phase0_slashingProtectionAttestationByTarget, pk,
                   tgt.to_bytes(8, "big")),
                src.to_bytes(8, "big") + root,
            )
            max_source = max(max_source, src)
            max_target = max(max_target, tgt)
        if max_source >= 0:
            # EIP-3076: merge with existing data — never LOWER a stored
            # bound (importing an old interchange after a newer one must
            # not weaken protection).
            lb_key = _k(Bucket.phase0_slashingProtectionAttestationLowerBound, pk)
            existing = self.db.get(lb_key)
            if existing is not None:
                max_source = max(max_source, int.from_bytes(existing[:8], "big"))
                max_target = max(max_target, int.from_bytes(existing[8:16], "big"))
            self.db.put(
                lb_key,
                max(0, max_source).to_bytes(8, "big")
                + max(0, max_target).to_bytes(8, "big"),
            )
