"""VC-side chain head tracker: follows the node's `head` SSE events.

Reference: packages/validator/src/services/chainHeaderTracker.ts — the VC
keeps the latest head (slot, root) pushed by the beacon node's event
stream instead of polling, and duty services read it synchronously.

The subscription runs in a reconnect loop with exponential backoff: an
SSE disconnect (node restart, proxy idle-timeout) must not silently end
head tracking for the VC's lifetime (ADVICE r5 — the old one-shot
subscription fell back to polling forever after the first hiccup).
Cancellation propagates; stop() is the only way the loop ends.
"""
from __future__ import annotations

import asyncio
import json
from typing import Optional

from lodestar_tpu.utils import Logger

RECONNECT_BACKOFF_MIN_S = 1.0
RECONNECT_BACKOFF_MAX_S = 30.0


class ChainHeaderTracker:
    """Background task consuming /eth/v1/events?topics=head."""

    def __init__(self, base_url: str, logger: Optional[Logger] = None):
        self.base_url = base_url.rstrip("/")
        self.head_slot: Optional[int] = None
        self.head_root: Optional[bytes] = None
        self._task: Optional[asyncio.Task] = None
        self._session = None
        self._backoff = RECONNECT_BACKOFF_MIN_S
        self._log = (logger or Logger("vc")).child("headTracker")

    async def start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession()
        self._task = asyncio.create_task(self._run())

    async def _subscribe_once(self) -> None:
        async with self._session.get(
            self.base_url + "/eth/v1/events",
            params={"topics": "head"},
            timeout=None,
        ) as resp:
            event = None
            async for raw in resp.content:
                line = raw.decode().strip()
                if line.startswith("event:"):
                    event = line.split(":", 1)[1].strip()
                elif line.startswith("data:") and event == "head":
                    data = json.loads(line.split(":", 1)[1])
                    self.head_slot = int(data["slot"])
                    self.head_root = bytes.fromhex(data["block"][2:])
                    # a live stream earns a fresh backoff for the next drop
                    self._backoff = RECONNECT_BACKOFF_MIN_S

    async def _run(self) -> None:
        while True:
            try:
                await self._subscribe_once()
                self._log.debug("head event stream ended; reconnecting")
            except asyncio.CancelledError:
                raise  # stop() requested — consumers fall back to polling
            except Exception as e:
                self._log.warn(
                    f"head event stream failed: {e!r}; "
                    f"retrying in {self._backoff:.1f}s"
                )
            # bump the backoff before yielding: no read->await->write on
            # shared state (await-in-critical), same observable schedule
            backoff = self._backoff
            self._backoff = min(backoff * 2.0, RECONNECT_BACKOFF_MAX_S)
            await asyncio.sleep(backoff)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._session is not None:
            await self._session.close()
