"""VC-side chain head tracker: follows the node's `head` SSE events.

Reference: packages/validator/src/services/chainHeaderTracker.ts — the VC
keeps the latest head (slot, root) pushed by the beacon node's event
stream instead of polling, and duty services read it synchronously.
"""
from __future__ import annotations

import asyncio
import json
from typing import Optional


class ChainHeaderTracker:
    """Background task consuming /eth/v1/events?topics=head."""

    def __init__(self, base_url: str):
        self.base_url = base_url.rstrip("/")
        self.head_slot: Optional[int] = None
        self.head_root: Optional[bytes] = None
        self._task: Optional[asyncio.Task] = None
        self._session = None

    async def start(self) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession()
        self._task = asyncio.create_task(self._run())

    async def _run(self) -> None:
        try:
            async with self._session.get(
                self.base_url + "/eth/v1/events",
                params={"topics": "head"},
                timeout=None,
            ) as resp:
                event = None
                async for raw in resp.content:
                    line = raw.decode().strip()
                    if line.startswith("event:"):
                        event = line.split(":", 1)[1].strip()
                    elif line.startswith("data:") and event == "head":
                        data = json.loads(line.split(":", 1)[1])
                        self.head_slot = int(data["slot"])
                        self.head_root = bytes.fromhex(data["block"][2:])
        except (asyncio.CancelledError, Exception):
            pass  # tracker is best-effort; consumers fall back to polling

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._session is not None:
            await self._session.close()
