"""Validator client (reference: packages/validator/src/validator.ts +
services/{attestationDuties,attestation,block}.ts): duties via the Beacon
API, signing via ValidatorStore (slashing-protected), submission back over
the API — a separate process from the node in production, same seam here.
"""
from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Dict, List, Optional

from lodestar_tpu.api.client import ApiClient
from lodestar_tpu.utils import get_logger

_log = get_logger("validator")
from lodestar_tpu.params import ACTIVE_PRESET as _p
from lodestar_tpu.ssz.json import to_json
from lodestar_tpu.state_transition.util.aggregator import (
    is_aggregator_from_committee_length,
)
from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot
from lodestar_tpu.types import ssz
from .validator_store import ValidatorStore


@dataclass
class AttesterDuty:
    pubkey: bytes
    validator_index: int
    committee_index: int
    committee_length: int
    committees_at_slot: int
    validator_committee_index: int
    slot: int


class Validator:
    """Drives proposal + attestation duties for its keys against a beacon
    node API; `run_slot` performs everything a production VC would do in a
    slot (proposals at slot start, attestations at 1/3 slot, aggregation at
    2/3 slot — here sequential)."""

    def __init__(
        self,
        api: ApiClient,
        store: ValidatorStore,
        use_builder: bool = False,
        fee_recipient: bytes = b"\x00" * 20,
        header_tracker=None,
    ):
        from .sync_committee import SyncCommitteeService

        self.api = api
        self.store = store
        # builder flow (validator --builder; reference's
        # produceBlindedBlock path, validator.ts:168): propose via
        # blinded blocks, node unblinds through its builder client
        self.use_builder = use_builder
        # prepareBeaconProposer (services/prepareBeaconProposer.ts):
        # announced once per epoch for every managed key
        self.fee_recipient = fee_recipient
        self._prepared_epochs: set = set()
        self._index_by_pubkey: Dict[bytes, int] = {}
        self.produced_blocks = 0
        self.produced_attestations = 0
        self.produced_aggregates = 0
        self.produced_sync_messages = 0
        self.produced_sync_contributions = 0
        self._announced_duty_epochs: set = set()
        self._selection_proofs: Dict[tuple, bytes] = {}
        # optional SSE head tracker (chainHeaderTracker.ts); start() it to
        # let duty services use the event-pushed head instead of polling
        self.header_tracker = header_tracker
        self.sync_committee = SyncCommitteeService(
            api=api,
            store=store,
            index_provider=lambda: self._index_by_pubkey,
            tracker=header_tracker,
        )

    async def initialize(self) -> None:
        """Map pubkeys to validator indices (validator.ts
        initializeFromBeaconNode / indices service)."""
        validators = await self.api.get_validators()
        mine = set(self.store.pubkeys)
        for item in validators:
            pk = bytes.fromhex(item["validator"]["pubkey"][2:])
            if pk in mine:
                self._index_by_pubkey[pk] = int(item["index"])

    @property
    def indices(self) -> List[int]:
        return sorted(self._index_by_pubkey.values())

    # ------------------------------------------------------------------

    async def propose_if_due(self, slot: int) -> Optional[bytes]:
        epoch = compute_epoch_at_slot(slot)
        duties = await self.api.get_proposer_duties(epoch)
        for duty in duties:
            if int(duty["slot"]) != slot:
                continue
            pk = bytes.fromhex(duty["pubkey"][2:])
            if not self.store.has(pk):
                continue
            randao = self.store.sign_randao(pk, slot)
            if self.use_builder:
                block = await self.api.produce_blinded_block(
                    slot, randao, graffiti="lodestar-tpu-vc"
                )
                signed = self.store.sign_block(pk, block)
                await self.api.publish_blinded_block(signed)
            else:
                block = await self.api.produce_block(
                    slot, randao, graffiti="lodestar-tpu-vc"
                )
                signed = self.store.sign_block(pk, block)
                await self.api.publish_block(signed)
            self.produced_blocks += 1
            return type(block).hash_tree_root(block)
        return None

    async def attest(self, slot: int) -> List["ssz.phase0.Attestation"]:
        epoch = compute_epoch_at_slot(slot)
        duties = await self._attester_duties(epoch)
        out = []
        for duty in duties:
            if duty.slot != slot:
                continue
            data = await self.api.produce_attestation_data(slot, duty.committee_index)
            att = self.store.sign_attestation(
                duty.pubkey, data, duty.committee_length, duty.validator_committee_index
            )
            out.append((duty, att))
        if out:
            await self.api.submit_pool_attestations([a for _, a in out])
            self.produced_attestations += len(out)
        return [a for _, a in out]

    async def aggregate_if_due(self, slot: int) -> int:
        """Aggregation duties (attestation.ts runAttestationTasks part 2 +
        aggregator selection)."""
        epoch = compute_epoch_at_slot(slot)
        duties = await self._attester_duties(epoch)
        submitted = 0
        for duty in duties:
            if duty.slot != slot:
                continue
            proof = self._selection_proof(duty.pubkey, slot)
            if not is_aggregator_from_committee_length(duty.committee_length, proof):
                continue
            data = await self.api.produce_attestation_data(slot, duty.committee_index)
            data_root = ssz.phase0.AttestationData.hash_tree_root(data)
            try:
                aggregate = await self.api.get_aggregate(slot, data_root)
            except Exception as e:
                # no matching aggregate pooled: a missed aggregation
                # duty — keep it visible
                _log.debug(
                    f"get_aggregate failed at slot {slot}: "
                    f"{type(e).__name__}: {e}"
                )
                continue
            aap = ssz.phase0.AggregateAndProof(
                aggregator_index=duty.validator_index,
                aggregate=aggregate,
                selection_proof=proof,
            )
            signed = self.store.sign_aggregate_and_proof(duty.pubkey, aap)
            try:
                await self.api.submit_aggregate_and_proofs([signed])
                submitted += 1
            except Exception as e:
                _log.warn(
                    f"aggregate submit failed at slot {slot}: "
                    f"{type(e).__name__}: {e}"
                )
                continue
        self.produced_aggregates += submitted
        return submitted

    async def _attester_duties(self, epoch: int) -> List[AttesterDuty]:
        raw = await self.api.get_attester_duties(epoch, self.indices)
        duties = [
            AttesterDuty(
                pubkey=bytes.fromhex(d["pubkey"][2:]),
                validator_index=int(d["validator_index"]),
                committee_index=int(d["committee_index"]),
                committee_length=int(d["committee_length"]),
                committees_at_slot=int(d["committees_at_slot"]),
                validator_committee_index=int(d["validator_committee_index"]),
                slot=int(d["slot"]),
            )
            for d in raw
        ]
        # announce duty subnets to the node so its attnets service meshes
        # them ahead of time (attestationDuties.ts prepareBeaconCommittee-
        # Subnet call); aggregator flag from the local selection proof
        if duties and epoch not in self._announced_duty_epochs:
            subs = [
                {
                    "validator_index": d.validator_index,
                    "committee_index": d.committee_index,
                    "committees_at_slot": d.committees_at_slot,
                    "slot": d.slot,
                    "is_aggregator": is_aggregator_from_committee_length(
                        d.committee_length,
                        self._selection_proof(d.pubkey, d.slot),
                    ),
                }
                for d in duties
            ]
            try:
                await self.api.prepare_beacon_committee_subnet(subs)
            except Exception as e:
                # transient / route-missing: retried next duty fetch
                _log.debug(
                    f"subnet announce failed: {type(e).__name__}: {e}"
                )
            else:
                self._announced_duty_epochs.add(epoch)
        return duties

    def _selection_proof(self, pubkey: bytes, slot: int) -> bytes:
        """Memoized aggregator selection proof: the announce path and
        aggregate_if_due need the same (pubkey, slot) signature."""
        key = (pubkey, slot)
        proof = self._selection_proofs.get(key)
        if proof is None:
            proof = self.store.sign_selection_proof(pubkey, slot)
            if len(self._selection_proofs) > 4096:
                self._selection_proofs.clear()
            self._selection_proofs[key] = proof
        return proof

    async def prepare_proposers_if_due(self, slot: int) -> None:
        """Once per epoch: register fee recipients for all managed keys
        (prepareBeaconProposer.ts pattern — re-sent each epoch so a
        restarted node re-learns them)."""
        epoch = compute_epoch_at_slot(slot)
        if epoch in self._prepared_epochs or not self._index_by_pubkey:
            return
        self._prepared_epochs.add(epoch)
        try:
            await self.api.prepare_beacon_proposer(
                [
                    {"validator_index": vi, "fee_recipient": self.fee_recipient}
                    for vi in self.indices
                ]
            )
        except Exception as e:
            # transient: retry next slot (fee recipients un-registered
            # until it lands — warn, this affects proposals)
            _log.warn(
                f"prepare_beacon_proposer failed: {type(e).__name__}: {e}"
            )
            self._prepared_epochs.discard(epoch)

    async def run_slot(self, slot: int) -> None:
        await self.prepare_proposers_if_due(slot)
        await self.propose_if_due(slot)
        await self.attest(slot)
        await self.aggregate_if_due(slot)
        # sync-committee duties (altair+; duties() resolves to [] when the
        # node has no committees for our keys, making these no-ops)
        messages = await self.sync_committee.produce_messages(slot)
        self.produced_sync_messages += messages
        contributions = await self.sync_committee.aggregate_if_due(slot)
        self.produced_sync_contributions += contributions
