"""EIP-2335 BLS keystores (reference: the cli's keystore handling,
packages/cli/src/cmds/validator/ via @chainsafe/bls-keystore).

Supports scrypt and pbkdf2 KDFs with AES-128-CTR, per the spec's test
vector parameters.
"""
from __future__ import annotations

import hashlib
import json
import os
import secrets
import uuid
from typing import Optional

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes


class KeystoreError(Exception):
    pass


def _derive_key(kdf: dict, password: bytes) -> bytes:
    params = kdf["params"]
    if kdf["function"] == "scrypt":
        return hashlib.scrypt(
            password,
            salt=bytes.fromhex(params["salt"]),
            n=params["n"], r=params["r"], p=params["p"],
            dklen=params["dklen"], maxmem=2**31 - 1,
        )
    if kdf["function"] == "pbkdf2":
        return hashlib.pbkdf2_hmac(
            params["prf"].replace("hmac-", ""),
            password,
            bytes.fromhex(params["salt"]),
            params["c"],
            dklen=params["dklen"],
        )
    raise KeystoreError(f"unsupported kdf {kdf['function']}")


def _aes128ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    cipher = Cipher(algorithms.AES(key), modes.CTR(iv))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


def _password_bytes(password: str) -> bytes:
    # EIP-2335: NFKD normalize, strip C0/C1 control codes
    import unicodedata

    norm = unicodedata.normalize("NFKD", password)
    return "".join(
        c for c in norm if not (ord(c) < 0x20 or 0x7F <= ord(c) <= 0x9F)
    ).encode()


def decrypt_keystore(keystore: dict, password: str) -> bytes:
    crypto = keystore["crypto"]
    dk = _derive_key(crypto["kdf"], _password_bytes(password))
    cipher_message = bytes.fromhex(crypto["cipher"]["message"])
    checksum = hashlib.sha256(dk[16:32] + cipher_message).hexdigest()
    if checksum != crypto["checksum"]["message"]:
        raise KeystoreError("invalid password (checksum mismatch)")
    if crypto["cipher"]["function"] != "aes-128-ctr":
        raise KeystoreError("unsupported cipher")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    return _aes128ctr(dk[:16], iv, cipher_message)


def create_keystore(
    secret: bytes,
    password: str,
    pubkey: Optional[bytes] = None,
    path: str = "",
    kdf: str = "scrypt",
) -> dict:
    salt = secrets.token_bytes(32)
    iv = secrets.token_bytes(16)
    if kdf == "scrypt":
        kdf_module = {
            "function": "scrypt",
            "params": {"dklen": 32, "n": 16384, "r": 8, "p": 1, "salt": salt.hex()},
            "message": "",
        }
    else:
        kdf_module = {
            "function": "pbkdf2",
            "params": {"dklen": 32, "c": 262144, "prf": "hmac-sha256", "salt": salt.hex()},
            "message": "",
        }
    dk = _derive_key(kdf_module, _password_bytes(password))
    cipher_message = _aes128ctr(dk[:16], iv, secret)
    checksum = hashlib.sha256(dk[16:32] + cipher_message).hexdigest()
    return {
        "version": 4,
        "uuid": str(uuid.uuid4()),
        "path": path,
        "pubkey": pubkey.hex() if pubkey else "",
        "crypto": {
            "kdf": kdf_module,
            "checksum": {"function": "sha256", "params": {}, "message": checksum},
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": cipher_message.hex(),
            },
        },
    }
