"""First-seen dedup caches (reference:
packages/beacon-node/src/chain/seenCache/: seenAttesters, seenAggregators,
seenBlockProposers, seenCommitteeContribution...).  Epoch-keyed maps pruned
on finalization, exactly the gossip-dedup semantics the spec requires.
"""
from __future__ import annotations

from typing import Dict, Set, Tuple


class SeenEpochCache:
    """validator-per-epoch first-seen cache (seenAttesters.ts)."""

    def __init__(self):
        self._by_epoch: Dict[int, Set[int]] = {}

    def is_known(self, epoch: int, index: int) -> bool:
        return index in self._by_epoch.get(epoch, ())

    def add(self, epoch: int, index: int) -> None:
        self._by_epoch.setdefault(epoch, set()).add(index)

    def prune(self, finalized_epoch: int) -> None:
        for e in [e for e in self._by_epoch if e <= finalized_epoch]:
            del self._by_epoch[e]


SeenAttesters = SeenEpochCache
SeenAggregators = SeenEpochCache


class SeenBlockProposers:
    """proposer-per-slot cache (seenBlockProposers.ts)."""

    def __init__(self):
        self._by_slot: Dict[int, Set[int]] = {}

    def is_known(self, slot: int, proposer: int) -> bool:
        return proposer in self._by_slot.get(slot, ())

    def add(self, slot: int, proposer: int) -> None:
        self._by_slot.setdefault(slot, set()).add(proposer)

    def is_known_proposer_in_epoch(self, epoch: int, proposer: int) -> bool:
        from lodestar_tpu.params import ACTIVE_PRESET as _p

        start = epoch * _p.SLOTS_PER_EPOCH
        return any(
            proposer in self._by_slot.get(s, ())
            for s in range(start, start + _p.SLOTS_PER_EPOCH)
        )

    def prune(self, finalized_slot: int) -> None:
        for s in [s for s in self._by_slot if s <= finalized_slot]:
            del self._by_slot[s]


class SeenAggregatedAttestations:
    """(target epoch, aggregate data root+bits superset) dedup
    (seenAggregateAndProof.ts simplified to root-key)."""

    def __init__(self):
        self._by_epoch: Dict[int, Dict[bytes, Tuple[bool, ...]]] = {}

    def is_known_superset(self, epoch: int, data_root: bytes, bits) -> bool:
        existing = self._by_epoch.get(epoch, {}).get(data_root)
        if existing is None or len(existing) != len(bits):
            return False
        return all(e or not b for e, b in zip(existing, bits))

    def add(self, epoch: int, data_root: bytes, bits) -> None:
        per = self._by_epoch.setdefault(epoch, {})
        existing = per.get(data_root)
        if existing is None or len(existing) != len(bits):
            per[data_root] = tuple(bits)
        else:
            per[data_root] = tuple(a or b for a, b in zip(existing, bits))

    def prune(self, finalized_epoch: int) -> None:
        for e in [e for e in self._by_epoch if e <= finalized_epoch]:
            del self._by_epoch[e]


class SeenSyncCommitteeMessages:
    """First-seen dedup for sync committee messages / contributions, keyed
    (slot, subnet, validator) (seenCommittee.ts / seenCommitteeContribution.ts)."""

    def __init__(self):
        self._seen: set = set()

    def is_known(self, slot: int, subnet: int, validator_index: int) -> bool:
        return (slot, subnet, validator_index) in self._seen

    def add(self, slot: int, subnet: int, validator_index: int) -> None:
        self._seen.add((slot, subnet, validator_index))

    def prune(self, before_slot: int) -> None:
        self._seen = {k for k in self._seen if k[0] >= before_slot}


class SeenBlsToExecutionChanges:
    """First-seen dedup per validator index (the p2p IGNORE rule for
    bls_to_execution_change; a validator changes credentials at most once,
    so no pruning is needed)."""

    def __init__(self):
        self._seen = set()

    def is_known(self, validator_index: int) -> bool:
        return validator_index in self._seen

    def add(self, validator_index: int) -> None:
        self._seen.add(validator_index)
