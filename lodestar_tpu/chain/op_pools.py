"""Operation pools (reference: packages/beacon-node/src/chain/opPools/).

AttestationPool aggregates unaggregated gossip attestations per slot+data
(attestationPool.ts:184 naive aggregation via signature addition);
AggregatedAttestationPool packs aggregates into blocks
(aggregatedAttestationPool.ts:321); OpPool persists slashings/exits.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import ACTIVE_PRESET as _p
from lodestar_tpu.types import ssz

SLOTS_RETAINED = 3  # attestationPool.ts SLOTS_RETAINED
MAX_RETAINED_DATAS_PER_SLOT = 16


@dataclass
class _AggregateFast:
    data: "ssz.phase0.AttestationData"
    aggregation_bits: List[bool]
    signature: "bls.Signature"

    def to_attestation(self) -> "ssz.phase0.Attestation":
        return ssz.phase0.Attestation(
            aggregation_bits=list(self.aggregation_bits),
            data=self.data,
            signature=self.signature.to_bytes(),
        )


class AttestationPool:
    """Unaggregated attestations, naively aggregated on insert."""

    def __init__(self):
        # slot -> data_root -> aggregate
        self._by_slot: Dict[int, Dict[bytes, _AggregateFast]] = {}
        self.lowest_permissible_slot = 0

    def add(self, attestation: "ssz.phase0.Attestation") -> str:
        slot = attestation.data.slot
        if slot < self.lowest_permissible_slot:
            return "old_slot"
        data_root = ssz.phase0.AttestationData.hash_tree_root(attestation.data)
        per_slot = self._by_slot.setdefault(slot, {})
        agg = per_slot.get(data_root)
        if agg is None:
            if len(per_slot) >= MAX_RETAINED_DATAS_PER_SLOT:
                return "reached_limit"
            per_slot[data_root] = _AggregateFast(
                data=attestation.data,
                aggregation_bits=list(attestation.aggregation_bits),
                signature=bls.Signature.from_bytes(bytes(attestation.signature)),
            )
            return "new_data"
        bits = attestation.aggregation_bits
        if len(bits) != len(agg.aggregation_bits):
            return "bits_mismatch"
        if any(a and b for a, b in zip(bits, agg.aggregation_bits)):
            return "already_known"
        agg.aggregation_bits = [
            a or b for a, b in zip(agg.aggregation_bits, bits)
        ]
        agg.signature = bls.aggregate_signatures(
            [agg.signature, bls.Signature.from_bytes(bytes(attestation.signature))]
        )
        return "aggregated"

    def get_aggregate(self, slot: int, data_root: bytes) -> Optional["ssz.phase0.Attestation"]:
        agg = self._by_slot.get(slot, {}).get(data_root)
        return agg.to_attestation() if agg else None

    def prune(self, clock_slot: int) -> None:
        self.lowest_permissible_slot = max(0, clock_slot - SLOTS_RETAINED)
        for slot in [s for s in self._by_slot if s < self.lowest_permissible_slot]:
            del self._by_slot[slot]


class AggregatedAttestationPool:
    """Aggregates awaiting block inclusion; getAttestationsForBlock packs
    the highest-value ones (most new attesting bits first)."""

    def __init__(self):
        self._by_data_root: Dict[bytes, List["ssz.phase0.Attestation"]] = {}
        self.lowest_permissible_slot = 0

    def add(self, attestation: "ssz.phase0.Attestation") -> str:
        if attestation.data.slot < self.lowest_permissible_slot:
            return "old_slot"
        root = ssz.phase0.AttestationData.hash_tree_root(attestation.data)
        lst = self._by_data_root.setdefault(root, [])
        new_bits = list(attestation.aggregation_bits)
        for existing in lst:
            ex_bits = list(existing.aggregation_bits)
            if all(not b or e for b, e in zip(new_bits, ex_bits)):
                return "already_known"  # subset of an existing aggregate
        lst.append(attestation)
        return "added"

    def get_attestations_for_block(self, state_slot: int) -> List["ssz.phase0.Attestation"]:
        candidates: List[Tuple[int, "ssz.phase0.Attestation"]] = []
        for lst in self._by_data_root.values():
            for att in lst:
                if (
                    att.data.slot + _p.MIN_ATTESTATION_INCLUSION_DELAY
                    <= state_slot
                    <= att.data.slot + _p.SLOTS_PER_EPOCH
                ):
                    candidates.append((sum(att.aggregation_bits), att))
        candidates.sort(key=lambda t: -t[0])
        return [att for _, att in candidates[: _p.MAX_ATTESTATIONS]]

    def prune(self, clock_slot: int) -> None:
        self.lowest_permissible_slot = max(0, clock_slot - _p.SLOTS_PER_EPOCH)
        for root in list(self._by_data_root):
            self._by_data_root[root] = [
                a
                for a in self._by_data_root[root]
                if a.data.slot >= self.lowest_permissible_slot
            ]
            if not self._by_data_root[root]:
                del self._by_data_root[root]


class OpPool:
    """Slashings, exits awaiting inclusion (opPool.ts), persisted via the
    db repositories on shutdown by the chain."""

    def __init__(self):
        self.attester_slashings: Dict[bytes, "ssz.phase0.AttesterSlashing"] = {}
        self.proposer_slashings: Dict[int, "ssz.phase0.ProposerSlashing"] = {}
        self.voluntary_exits: Dict[int, "ssz.phase0.SignedVoluntaryExit"] = {}
        # capella (opPool.ts blsToExecutionChanges)
        self.bls_to_execution_changes: Dict[int, object] = {}

    def add_bls_to_execution_change(self, c) -> None:
        self.bls_to_execution_changes[c.message.validator_index] = c

    def get_bls_to_execution_changes(self, state) -> list:
        from lodestar_tpu.params import BLS_WITHDRAWAL_PREFIX

        out = []
        for c in self.bls_to_execution_changes.values():
            idx = c.message.validator_index
            if idx < len(state.validators) and bytes(
                state.validators[idx].withdrawal_credentials
            )[:1] == bytes([BLS_WITHDRAWAL_PREFIX]):
                out.append(c)
        return out[: _p.MAX_BLS_TO_EXECUTION_CHANGES]

    def add_attester_slashing(self, s) -> None:
        root = ssz.phase0.AttesterSlashing.hash_tree_root(s)
        self.attester_slashings[root] = s

    def add_proposer_slashing(self, s) -> None:
        self.proposer_slashings[s.signed_header_1.message.proposer_index] = s

    def add_voluntary_exit(self, e) -> None:
        self.voluntary_exits[e.message.validator_index] = e

    def get_slashings_and_exits(self, state) -> Tuple[list, list, list]:
        from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot

        epoch = compute_epoch_at_slot(state.slot)
        proposer = [
            s
            for s in self.proposer_slashings.values()
            if not state.validators[s.signed_header_1.message.proposer_index].slashed
        ][: _p.MAX_PROPOSER_SLASHINGS]
        attester = list(self.attester_slashings.values())[: _p.MAX_ATTESTER_SLASHINGS]
        exits = [
            e
            for e in self.voluntary_exits.values()
            if state.validators[e.message.validator_index].exit_epoch
            == 2**64 - 1
            and epoch >= e.message.epoch
        ][: _p.MAX_VOLUNTARY_EXITS]
        return proposer, attester, exits


# ---------------------------------------------------------------------------
# sync committee pools (altair)
# ---------------------------------------------------------------------------


class SyncCommitteeMessagePool:
    """Aggregates per-slot/root/subcommittee sync messages into
    contributions (syncCommitteeMessagePool.ts:126).

    Aggregators pull contributions from here; participants' signatures are
    naively aggregated exactly like the attestation pool."""

    def __init__(self):
        # (slot, root, subcommittee) -> (bits, aggregated signature)
        self._by_key: Dict[Tuple[int, bytes, int], Tuple[List[bool], "bls.Signature"]] = {}

    def add(self, subcommittee_index: int, index_in_subcommittee: int, message) -> None:
        from lodestar_tpu.types.altair import SYNC_SUBCOMMITTEE_SIZE

        key = (message.slot, bytes(message.beacon_block_root), subcommittee_index)
        sig = bls.Signature.from_bytes(bytes(message.signature))
        entry = self._by_key.get(key)
        if entry is None:
            bits = [False] * SYNC_SUBCOMMITTEE_SIZE
            bits[index_in_subcommittee] = True
            self._by_key[key] = (bits, sig)
            return
        bits, agg = entry
        if bits[index_in_subcommittee]:
            return  # duplicate participant
        bits[index_in_subcommittee] = True
        self._by_key[key] = (bits, bls.aggregate_signatures([agg, sig]))

    def get_contribution(
        self, slot: int, beacon_block_root: bytes, subcommittee_index: int
    ) -> Optional["ssz.altair.SyncCommitteeContribution"]:
        entry = self._by_key.get((slot, bytes(beacon_block_root), subcommittee_index))
        if entry is None:
            return None
        bits, agg = entry
        return ssz.altair.SyncCommitteeContribution(
            slot=slot,
            beacon_block_root=bytes(beacon_block_root),
            subcommittee_index=subcommittee_index,
            aggregation_bits=list(bits),
            signature=agg.to_bytes(),
        )

    def prune(self, clock_slot: int) -> None:
        for k in [k for k in self._by_key if k[0] + SLOTS_RETAINED < clock_slot]:
            del self._by_key[k]


class SyncContributionAndProofPool:
    """Best contribution per (slot, root, subcommittee) for block packing
    (syncContributionAndProofPool.ts:169-185): the block's SyncAggregate is
    assembled by OR-ing the best contributions of the previous slot."""

    def __init__(self):
        self._best: Dict[Tuple[int, bytes, int], "ssz.altair.SyncCommitteeContribution"] = {}

    def add(self, contribution: "ssz.altair.SyncCommitteeContribution") -> None:
        key = (
            contribution.slot,
            bytes(contribution.beacon_block_root),
            contribution.subcommittee_index,
        )
        best = self._best.get(key)
        if best is None or sum(contribution.aggregation_bits) > sum(
            best.aggregation_bits
        ):
            self._best[key] = contribution

    def get_sync_aggregate(
        self, slot: int, beacon_block_root: bytes
    ) -> "ssz.altair.SyncAggregate":
        """SyncAggregate for a block at `slot` signing over root at slot-1."""
        from lodestar_tpu.types.altair import SYNC_SUBCOMMITTEE_SIZE
        from lodestar_tpu.params import SYNC_COMMITTEE_SUBNET_COUNT

        prev_slot = max(1, slot) - 1
        bits = [False] * _p.SYNC_COMMITTEE_SIZE
        sigs: List["bls.Signature"] = []
        for sub in range(SYNC_COMMITTEE_SUBNET_COUNT):
            c = self._best.get((prev_slot, bytes(beacon_block_root), sub))
            if c is None:
                continue
            for i, b in enumerate(c.aggregation_bits):
                if b:
                    bits[sub * SYNC_SUBCOMMITTEE_SIZE + i] = True
            sigs.append(bls.Signature.from_bytes(bytes(c.signature)))
        if sigs:
            sig = bls.aggregate_signatures(sigs).to_bytes()
        else:
            sig = b"\xc0" + b"\x00" * 95  # G2 infinity: empty aggregate
        return ssz.altair.SyncAggregate(
            sync_committee_bits=bits, sync_committee_signature=sig
        )

    def prune(self, clock_slot: int) -> None:
        for k in [k for k in self._best if k[0] + SLOTS_RETAINED < clock_slot]:
            del self._best[k]
