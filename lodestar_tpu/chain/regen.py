"""State caches + regeneration (reference:
packages/beacon-node/src/chain/stateCache/ and chain/regen/queued.ts).

StateContextCache: LRU of post-states by block root.  CheckpointStateCache:
epoch-boundary states by checkpoint.  StateRegenerator replays blocks from
the db when a requested state is not cached (regen.getPreState /
getBlockSlotState semantics), behind a bounded FIFO queue upstream.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple

from lodestar_tpu.state_transition import CachedBeaconState, process_slots, state_transition

MAX_STATES = 96  # stateContextCache default
MAX_CHECKPOINT_STATES = 8


class StateContextCache:
    def __init__(self, max_states: int = MAX_STATES):
        self._map: "OrderedDict[bytes, CachedBeaconState]" = OrderedDict()
        self.max_states = max_states
        # Pinned roots are exempt from LRU eviction: the anchor/finalized
        # state must stay resident or _replay_to has no terminal ancestor
        # (the anchor block's parent is not in the DB) and deep-branch
        # regen would fail permanently.
        self._pinned: set = set()

    def get(self, block_root: bytes) -> Optional[CachedBeaconState]:
        st = self._map.get(block_root)
        if st is not None:
            self._map.move_to_end(block_root)
        return st

    def add(self, block_root: bytes, state: CachedBeaconState) -> None:
        self._map[block_root] = state
        self._map.move_to_end(block_root)
        self._evict()

    def pin(self, block_root: bytes) -> None:
        self._pinned.add(block_root)

    def unpin(self, block_root: bytes) -> None:
        self._pinned.discard(block_root)

    def _evict(self) -> None:
        if len(self._map) <= self.max_states:
            return
        for root in list(self._map):
            if len(self._map) <= self.max_states:
                break
            if root in self._pinned:
                continue
            del self._map[root]

    def prune(self, keep_roots) -> None:
        keep = set(keep_roots) | self._pinned
        for root in [r for r in self._map if r not in keep]:
            del self._map[root]

    def __len__(self):
        return len(self._map)


class CheckpointStateCache:
    def __init__(self, max_states: int = MAX_CHECKPOINT_STATES):
        self._map: "OrderedDict[Tuple[int, bytes], CachedBeaconState]" = OrderedDict()
        self.max_states = max_states

    def get(self, epoch: int, root: bytes) -> Optional[CachedBeaconState]:
        st = self._map.get((epoch, root))
        if st is not None:
            self._map.move_to_end((epoch, root))
        return st

    def add(self, epoch: int, root: bytes, state: CachedBeaconState) -> None:
        self._map[(epoch, root)] = state
        self._map.move_to_end((epoch, root))
        while len(self._map) > self.max_states:
            self._map.popitem(last=False)


class StateRegenerator:
    """Replay-based state regeneration.  get_block_fn(root) must return the
    stored SignedBeaconBlock for a known root (db.block)."""

    def __init__(
        self,
        state_cache: StateContextCache,
        get_block_fn: Callable,
        on_miss: Optional[Callable[[], None]] = None,
    ):
        self.state_cache = state_cache
        self.get_block = get_block_fn
        self.on_miss = on_miss  # metrics hook: regen cache-miss counter
        # small memo of dialed-forward pre-states: gossip validation and
        # the import pipeline request the SAME (parent, slot) back-to-back
        # and the epoch-boundary dial is expensive (full epoch processing)
        self._dialed: "OrderedDict[Tuple[bytes, int], CachedBeaconState]" = OrderedDict()

    def get_pre_state(self, parent_root: bytes, slot: int) -> CachedBeaconState:
        """State to process a block with `parent_root` at `slot` on top of
        (regen.getPreState).  Callers must treat the result as read-only
        (state_transition clones before mutating)."""
        memo = self._dialed.get((parent_root, slot))
        if memo is not None:
            return memo
        state = self.state_cache.get(parent_root)
        if state is None:
            state = self._replay_to(parent_root)
        if state.state.slot < slot:
            state = state.clone()
            process_slots(state, slot)
            self._dialed[(parent_root, slot)] = state
            while len(self._dialed) > 4:
                self._dialed.popitem(last=False)
        return state

    def _replay_to(self, block_root: bytes) -> CachedBeaconState:
        """Walk back to a cached ancestor, then re-apply blocks forward
        (the regen miss path — hot on deep reorgs, chain/regen/regen.ts)."""
        if self.on_miss is not None:
            self.on_miss()
        chain = []
        root = block_root
        state = None
        while True:
            state = self.state_cache.get(root)
            if state is not None:
                break
            block = self.get_block(root)
            if block is None:
                raise ValueError(f"cannot regen: unknown block {root.hex()}")
            chain.append(block)
            root = bytes(block.message.parent_root)
        for block in reversed(chain):
            state = state_transition(
                state, block,
                verify_state_root=True, verify_proposer=False, verify_signatures=False,
            )
            msg = block.message
            self.state_cache.add(type(msg).hash_tree_root(msg), state)
        return state
