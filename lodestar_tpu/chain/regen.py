"""State caches + regeneration (reference:
packages/beacon-node/src/chain/stateCache/ and chain/regen/queued.ts).

StateContextCache: LRU of post-states by block root.  CheckpointStateCache:
epoch-boundary states by checkpoint.  StateRegenerator replays blocks from
the db when a requested state is not cached (regen.getPreState /
getBlockSlotState semantics), behind a bounded FIFO queue upstream.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, Optional, Tuple

from lodestar_tpu.state_transition import CachedBeaconState, process_slots, state_transition

MAX_STATES = 96  # stateContextCache default
MAX_CHECKPOINT_STATES = 8


class StateContextCache:
    def __init__(self, max_states: int = MAX_STATES):
        self._map: "OrderedDict[bytes, CachedBeaconState]" = OrderedDict()
        self.max_states = max_states

    def get(self, block_root: bytes) -> Optional[CachedBeaconState]:
        st = self._map.get(block_root)
        if st is not None:
            self._map.move_to_end(block_root)
        return st

    def add(self, block_root: bytes, state: CachedBeaconState) -> None:
        self._map[block_root] = state
        self._map.move_to_end(block_root)
        while len(self._map) > self.max_states:
            self._map.popitem(last=False)

    def prune(self, keep_roots) -> None:
        keep = set(keep_roots)
        for root in [r for r in self._map if r not in keep]:
            del self._map[root]

    def __len__(self):
        return len(self._map)


class CheckpointStateCache:
    def __init__(self, max_states: int = MAX_CHECKPOINT_STATES):
        self._map: "OrderedDict[Tuple[int, bytes], CachedBeaconState]" = OrderedDict()
        self.max_states = max_states

    def get(self, epoch: int, root: bytes) -> Optional[CachedBeaconState]:
        st = self._map.get((epoch, root))
        if st is not None:
            self._map.move_to_end((epoch, root))
        return st

    def add(self, epoch: int, root: bytes, state: CachedBeaconState) -> None:
        self._map[(epoch, root)] = state
        self._map.move_to_end((epoch, root))
        while len(self._map) > self.max_states:
            self._map.popitem(last=False)


class StateRegenerator:
    """Replay-based state regeneration.  get_block_fn(root) must return the
    stored SignedBeaconBlock for a known root (db.block)."""

    def __init__(self, state_cache: StateContextCache, get_block_fn: Callable):
        self.state_cache = state_cache
        self.get_block = get_block_fn

    def get_pre_state(self, parent_root: bytes, slot: int) -> CachedBeaconState:
        """State to process a block with `parent_root` at `slot` on top of
        (regen.getPreState)."""
        state = self.state_cache.get(parent_root)
        if state is None:
            state = self._replay_to(parent_root)
        if state.state.slot < slot:
            state = state.clone()
            process_slots(state, slot)
        return state

    def _replay_to(self, block_root: bytes) -> CachedBeaconState:
        """Walk back to a cached ancestor, then re-apply blocks forward
        (the regen miss path — hot on deep reorgs, chain/regen/regen.ts)."""
        chain = []
        root = block_root
        state = None
        while True:
            state = self.state_cache.get(root)
            if state is not None:
                break
            block = self.get_block(root)
            if block is None:
                raise ValueError(f"cannot regen: unknown block {root.hex()}")
            chain.append(block)
            root = bytes(block.message.parent_root)
        for block in reversed(chain):
            state = state_transition(
                state, block,
                verify_state_root=True, verify_proposer=False, verify_signatures=False,
            )
            from lodestar_tpu.types import ssz

            self.state_cache.add(
                ssz.phase0.BeaconBlock.hash_tree_root(block.message), state
            )
        return state
