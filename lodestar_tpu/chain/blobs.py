"""Blobs sidecar production/retrieval (reference:
packages/beacon-node/src/chain/produceBlock + db blobsSidecar flow for the
eip4844 "block and blobs sidecar" era).
"""
from __future__ import annotations

from typing import Sequence

from lodestar_tpu.crypto import kzg
from lodestar_tpu.types import ssz


def build_blobs_sidecar(block_root: bytes, slot: int, blobs: Sequence[bytes]):
    """Sidecar carrying `blobs` with one aggregated KZG proof (the proposer
    side of validate_blobs_sidecar)."""
    return ssz.eip4844.BlobsSidecar(
        beacon_block_root=bytes(block_root),
        beacon_block_slot=slot,
        blobs=[bytes(b) for b in blobs],
        kzg_aggregated_proof=kzg.compute_aggregate_kzg_proof(
            [bytes(b) for b in blobs]
        ),
    )


def empty_blobs_sidecar(block_root: bytes, slot: int):
    """Every eip4844 block ships a sidecar even with zero blobs (spec
    get_blobs_sidecar)."""
    return build_blobs_sidecar(block_root, slot, [])
