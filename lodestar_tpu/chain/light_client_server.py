"""LightClientServer (reference:
packages/beacon-node/src/chain/lightClient/index.ts:159 + proofs.ts).

Consumes imported blocks: whenever a block's sync aggregate attests its
parent with enough participation, the server materializes
LightClientUpdate data from the attested state — header, next sync
committee + branch, finalized header + finality branch — keeps the BEST
update per sync-committee period (most participation, finalized preferred),
and serves bootstrap/finality/optimistic artifacts to the REST routes and
reqresp handlers.
"""
from __future__ import annotations

from typing import Dict, Optional

from lodestar_tpu.params import ACTIVE_PRESET as _p
from lodestar_tpu.ssz.proof import container_field_proof
from lodestar_tpu.types import ssz
from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot


def sync_period_at_slot(slot: int) -> int:
    return (
        compute_epoch_at_slot(slot) // _p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    )


def block_to_header(block) -> "ssz.phase0.BeaconBlockHeader":
    return ssz.phase0.BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=bytes(block.parent_root),
        state_root=bytes(block.state_root),
        body_root=type(block.body).hash_tree_root(block.body),
    )


class LightClientServer:
    def __init__(self, chain):
        self.chain = chain
        self.best_update_by_period: Dict[int, "ssz.altair.LightClientUpdate"] = {}
        self.latest_finality_update: Optional["ssz.altair.LightClientFinalityUpdate"] = None
        self.latest_optimistic_update: Optional["ssz.altair.LightClientOptimisticUpdate"] = None
        from .chain import ChainEvent

        chain.on(ChainEvent.block, self._on_block)

    # ------------------------------------------------------------------

    def get_bootstrap(self, block_root: bytes) -> Optional["ssz.altair.LightClientBootstrap"]:
        """Bootstrap for a (finalized) block root: its header + the state's
        current sync committee with branch (spec create_light_client_bootstrap)."""
        signed = self.chain.db.block.get(block_root)
        state = self.chain.state_cache.get(block_root)
        if signed is None or state is None:
            return None
        st = state.state
        if not hasattr(st, "current_sync_committee"):
            return None
        _, branch, _, _ = container_field_proof(
            type(st), st, ["current_sync_committee"]
        )
        return ssz.altair.LightClientBootstrap(
            header=block_to_header(signed.message),
            current_sync_committee=st.current_sync_committee,
            current_sync_committee_branch=branch,
        )

    def get_update(self, period: int) -> Optional["ssz.altair.LightClientUpdate"]:
        return self.best_update_by_period.get(period)

    # ------------------------------------------------------------------

    def _on_block(self, signed_block, root: bytes) -> None:
        block = signed_block.message
        agg = getattr(block.body, "sync_aggregate", None)
        if agg is None:
            return
        participation = sum(1 for b in agg.sync_committee_bits if b)
        if participation < _p.MIN_SYNC_COMMITTEE_PARTICIPANTS:
            return
        attested_root = bytes(block.parent_root)
        attested_state = self.chain.state_cache.get(attested_root)
        attested_signed = self.chain.db.block.get(attested_root)
        if attested_state is None or attested_signed is None:
            return
        st = attested_state.state
        if not hasattr(st, "next_sync_committee"):
            return
        attested_header = block_to_header(attested_signed.message)

        _, nsc_branch, _, _ = container_field_proof(
            type(st), st, ["next_sync_committee"]
        )
        fin_epoch = st.finalized_checkpoint.epoch
        fin_root = bytes(st.finalized_checkpoint.root)
        finalized_header = ssz.phase0.BeaconBlockHeader.default()
        finality_branch = [b"\x00" * 32] * 6
        fin_signed = self.chain.db.block.get(fin_root) if fin_root != b"\x00" * 32 else None
        has_finality = fin_signed is not None
        if has_finality:
            finalized_header = block_to_header(fin_signed.message)
            _, finality_branch, _, _ = container_field_proof(
                type(st), st, ["finalized_checkpoint", "root"]
            )

        update = ssz.altair.LightClientUpdate(
            attested_header=attested_header,
            next_sync_committee=st.next_sync_committee,
            next_sync_committee_branch=nsc_branch,
            finalized_header=finalized_header,
            finality_branch=finality_branch,
            sync_aggregate=agg,
            signature_slot=block.slot,
        )

        period = sync_period_at_slot(attested_header.slot)
        best = self.best_update_by_period.get(period)
        if best is None or self._is_better(update, best):
            self.best_update_by_period[period] = update

        self.latest_optimistic_update = ssz.altair.LightClientOptimisticUpdate(
            attested_header=attested_header,
            sync_aggregate=agg,
            signature_slot=block.slot,
        )
        if has_finality:
            self.latest_finality_update = ssz.altair.LightClientFinalityUpdate(
                attested_header=attested_header,
                finalized_header=finalized_header,
                finality_branch=finality_branch,
                sync_aggregate=agg,
                signature_slot=block.slot,
            )

    @staticmethod
    def _is_better(a, b) -> bool:
        """isBetterUpdate (spec): finality first, then participation."""
        a_fin = a.finalized_header.slot != 0
        b_fin = b.finalized_header.slot != 0
        if a_fin != b_fin:
            return a_fin
        pa = sum(1 for x in a.sync_aggregate.sync_committee_bits if x)
        pb = sum(1 for x in b.sync_aggregate.sync_committee_bits if x)
        if pa != pb:
            return pa > pb
        return a.attested_header.slot > b.attested_header.slot
