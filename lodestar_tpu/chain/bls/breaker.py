"""Device circuit breaker + process-wide degradation record.

The degradation ladder (chain/bls/device_pool.py) keeps individual
verification packs alive through transient device faults; the breaker
handles the *persistent* fault — a wedged XLA runtime, a dead TPU
tunnel — where every device dispatch costs a multi-second failure
before the fallback runs.  After ``failure_threshold`` CONSECUTIVE
device failures the breaker opens and the pool routes packs straight to
the host verifier; after an exponential backoff it half-opens and
admits exactly ONE canary job to the device.  A canary success closes
the breaker (and resets the backoff); a canary failure re-opens it with
the backoff doubled, up to ``max_backoff_s``.

Verification verdicts are NOT failures: an invalid signature returns
``False`` through the normal per-set split and never touches the
breaker — only dispatch *exceptions* (XLA runtime errors, compile
crashes) count.

``note_tier``/``process_degradation`` record the worst degradation tier
any verifier in this process ever engaged.  bench.py stamps the record
into every stage's JSON so a driver round that silently ran on the host
fallback cannot bank a number that looks like device throughput.
"""
from __future__ import annotations

import threading
import time
from typing import Callable

# breaker states (exported as the lodestar_tpu_bls_pool_breaker_state gauge)
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

FAILURE_THRESHOLD = 3
BASE_BACKOFF_S = 5.0
MAX_BACKOFF_S = 300.0

# degradation tiers, best to worst; ordering is the ladder itself
TIER_DEVICE = "device"
TIER_DEVICE_RETRY = "device_retry"
TIER_PER_SET = "per_set"
TIER_HOST = "host"
_TIER_ORDER = (TIER_DEVICE, TIER_DEVICE_RETRY, TIER_PER_SET, TIER_HOST)


class DeviceCircuitBreaker:
    """Consecutive-failure breaker with exponential half-open backoff.

    Thread-safe: the pool records successes/failures from executor
    threads while the event loop asks for dispatch decisions.
    """

    def __init__(
        self,
        failure_threshold: int = FAILURE_THRESHOLD,
        base_backoff_s: float = BASE_BACKOFF_S,
        max_backoff_s: float = MAX_BACKOFF_S,
        clock: Callable[[], float] = time.monotonic,
    ):
        assert failure_threshold >= 1
        self.failure_threshold = failure_threshold
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._backoff_s = base_backoff_s
        self._open_until = 0.0
        self._canary_in_flight = False
        self._probe_gen = 0  # identity of the current/last canary
        self.trips = 0  # closed/half-open -> open transitions

    @property
    def state(self) -> str:
        # Reviewed exception: guards a one-field read — microseconds,
        # never held across I/O.
        with self._lock:  # lodelint: disable=transitive-blocking
            return self._state

    def allow_device(self) -> str:
        """Dispatch decision for the next job: ``"device"`` (breaker
        closed), ``"canary"`` (half-open probe — caller MUST report the
        outcome via record_success/record_failure), or ``"host"``
        (open, or a canary is already in flight)."""
        now = self._clock()
        # Reviewed exception: pure in-memory state machine — microseconds,
        # never held across I/O, called once per verification job.
        with self._lock:  # lodelint: disable=transitive-blocking
            if self._state == CLOSED:
                return "device"
            if self._state == OPEN and now >= self._open_until:
                self._state = HALF_OPEN
                self._canary_in_flight = False
            if self._state == HALF_OPEN and not self._canary_in_flight:
                self._canary_in_flight = True
                self._probe_gen += 1
                return "canary"
            return "host"

    def record_success(self, probe: bool = False) -> None:
        """Record a device dispatch success.  ``probe=True`` marks the
        outcome of a job that was admitted as the half-open canary —
        ONLY the canary's own outcome may close the breaker.  A
        straggler job that took its "device" decision before the trip
        and succeeds late must not close (or double-admit canaries);
        its success merely clears the closed-state failure streak."""
        # Reviewed exception: counter reset — microseconds, no I/O.
        with self._lock:  # lodelint: disable=transitive-blocking
            self._consecutive_failures = 0
            if probe and self._state == HALF_OPEN:
                # canary came back healthy: full service, backoff reset
                self._state = CLOSED
                self._backoff_s = self.base_backoff_s
                self._canary_in_flight = False

    @property
    def probe_token(self) -> int:
        """Identity of the most recently admitted canary; a caller
        that got "canary" from allow_device() reads this immediately
        (no other canary can be admitted until this one resolves) and
        passes it back to cancel_probe."""
        # Reviewed exception: one-field read — microseconds, no I/O.
        with self._lock:  # lodelint: disable=transitive-blocking
            return self._probe_gen

    def cancel_probe(self, token: int = None) -> None:
        """Release a canary whose job died before any outcome was
        recorded (pool close() mid-probe, an encode fault): the breaker
        stays half-open and the NEXT allow_device() may admit a fresh
        canary — without this the probe slot would be leaked forever
        and every future job would route to the host.  ``token``
        identity-scopes the release: a STALE ex-canary raising late
        (e.g. its post-resolution host verify fails during close())
        must not free a NEWER canary's in-flight slot and admit two
        concurrent probes."""
        # Reviewed exception: one flag write — microseconds, no I/O.
        with self._lock:  # lodelint: disable=transitive-blocking
            if self._state == HALF_OPEN and self._canary_in_flight and (
                token is None or token == self._probe_gen
            ):
                self._canary_in_flight = False

    def record_failure(self, probe: bool = False) -> bool:
        """Record one device dispatch exception; returns True when this
        failure TRIPPED the breaker (closed/half-open -> open).
        ``probe=True`` marks the canary's own outcome: only IT may
        re-open a half-open breaker — a straggler pre-trip job failing
        late (it can hold the device lock through a multi-second
        failure ladder, easily past the backoff) must not re-open,
        double the backoff, or free the canary slot for a second
        concurrent probe."""
        now = self._clock()
        # Reviewed exception: counter + state flip — microseconds, no I/O.
        with self._lock:  # lodelint: disable=transitive-blocking
            self._consecutive_failures += 1
            if probe:
                if self._state == HALF_OPEN:
                    # canary failed: back to open, backoff doubled
                    self._state = OPEN
                    self._canary_in_flight = False
                    self._backoff_s = min(
                        self._backoff_s * 2, self.max_backoff_s
                    )
                    self._open_until = now + self._backoff_s
                    self.trips += 1
                    return True
                return False
            if (
                self._state == CLOSED
                and self._consecutive_failures >= self.failure_threshold
            ):
                self._state = OPEN
                self._open_until = now + self._backoff_s
                self.trips += 1
                return True
            return False


# ---------------------------------------------------------------------------
# process-wide degradation record (read by bench.py)
# ---------------------------------------------------------------------------

_proc_lock = threading.Lock()
_PROCESS = {"worst_tier": TIER_DEVICE, "breaker_state": CLOSED, "breaker_trips": 0}


def note_tier(tier: str) -> None:
    """Record that a verification ran at ``tier``; keeps the worst."""
    # Reviewed exception: one dict compare-and-set — microseconds, no I/O.
    with _proc_lock:  # lodelint: disable=transitive-blocking
        if _TIER_ORDER.index(tier) > _TIER_ORDER.index(_PROCESS["worst_tier"]):
            _PROCESS["worst_tier"] = tier


def note_breaker(state: str, trips: int) -> None:
    # Reviewed exception: two dict writes — microseconds, no I/O.
    with _proc_lock:  # lodelint: disable=transitive-blocking
        _PROCESS["breaker_state"] = state
        _PROCESS["breaker_trips"] = max(_PROCESS["breaker_trips"], trips)


def process_degradation() -> dict:
    """Worst tier + breaker state this process ever saw (bench JSON)."""
    with _proc_lock:
        return dict(_PROCESS)


def reset_process_record() -> None:
    with _proc_lock:
        _PROCESS.update(
            worst_tier=TIER_DEVICE, breaker_state=CLOSED, breaker_trips=0
        )
